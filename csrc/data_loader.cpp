// Native (C++) prefetching token-batch loader.
//
// The runtime-native twin of utils/data.py's TokenFileDataset: random crops
// of seq_length+1 tokens from a flat binary token file (GPT-2-style packed
// corpus), assembled into int32 [B, S] token/target pairs by background
// threads and handed to Python through a bounded queue. The file is mmap'd
// read-only so the host working set stays at O(touched pages); crop
// assembly (gather + widen to int32 + next-token shift) runs off the Python
// thread entirely, so the train loop's host time is one memcpy per batch.
//
// Exposed via ctypes (see utils/data_native.py); no Python.h dependency.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr int DTYPE_U16 = 0;
constexpr int DTYPE_I32 = 1;

// splitmix64: tiny, high-quality, and trivially seedable per thread.
struct SplitMix64 {
  uint64_t s;
  uint64_t next() {
    uint64_t z = (s += 0x9e3779b97f4a7c15ULL);
    z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
    z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
    return z ^ (z >> 31);
  }
};

struct Loader {
  const void* map = nullptr;
  size_t map_bytes = 0;
  int fd = -1;
  int64_t n_tokens = 0;
  int64_t seq = 0;
  int64_t batch = 0;
  int dtype = DTYPE_U16;
  int depth = 4;

  std::vector<std::thread> threads;
  std::deque<std::vector<int32_t>> queue;  // each: [tokens | targets], 2*B*S
  std::mutex mu;
  std::condition_variable cv_space, cv_item, cv_readers;
  std::atomic<bool> stop{false};
  int readers = 0;  // in-flight dtpp_dl_next calls (guarded by mu)

  int32_t tok_at(int64_t i) const {
    return dtype == DTYPE_U16
               ? static_cast<int32_t>(static_cast<const uint16_t*>(map)[i])
               : static_cast<const int32_t*>(map)[i];
  }

  void worker(uint64_t seed) {
    SplitMix64 rng{seed};
    const uint64_t n_starts =
        static_cast<uint64_t>(n_tokens - seq);  // crop is seq+1 long
    while (!stop.load(std::memory_order_relaxed)) {
      std::vector<int32_t> buf(2 * batch * seq);
      int32_t* toks = buf.data();
      int32_t* tgts = toks + batch * seq;
      for (int64_t r = 0; r < batch; ++r) {
        const int64_t start = static_cast<int64_t>(rng.next() % n_starts);
        for (int64_t j = 0; j < seq; ++j) {
          toks[r * seq + j] = tok_at(start + j);
          tgts[r * seq + j] = tok_at(start + j + 1);
        }
      }
      std::unique_lock<std::mutex> lk(mu);
      cv_space.wait(lk, [&] {
        return stop.load() || static_cast<int>(queue.size()) < depth;
      });
      if (stop.load()) return;
      queue.push_back(std::move(buf));
      cv_item.notify_one();
    }
  }

  ~Loader() {
    {
      // The store+notify must happen under the mutex: a worker that has
      // evaluated its wait predicate but not yet blocked would otherwise
      // miss the wakeup and sleep forever, deadlocking join() below.
      std::lock_guard<std::mutex> lk(mu);
      stop.store(true);
    }
    cv_space.notify_all();
    cv_item.notify_all();
    for (auto& t : threads)
      if (t.joinable()) t.join();
    if (map != nullptr) munmap(const_cast<void*>(map), map_bytes);
    if (fd >= 0) close(fd);
  }
};

void set_err(char* err, int errlen, const std::string& msg) {
  if (err != nullptr && errlen > 0) {
    std::snprintf(err, static_cast<size_t>(errlen), "%s", msg.c_str());
  }
}

}  // namespace

extern "C" {

void* dtpp_dl_open(const char* path, int64_t seq, int64_t batch, int dtype,
                   uint64_t seed, int n_threads, int depth, char* err,
                   int errlen) {
  if (seq <= 0 || batch <= 0 || n_threads <= 0 || depth <= 0) {
    set_err(err, errlen, "seq, batch, n_threads, depth must be positive");
    return nullptr;
  }
  if (dtype != DTYPE_U16 && dtype != DTYPE_I32) {
    set_err(err, errlen, "dtype code must be 0 (uint16) or 1 (int32)");
    return nullptr;
  }
  auto ld = std::make_unique<Loader>();
  ld->fd = open(path, O_RDONLY);
  if (ld->fd < 0) {
    set_err(err, errlen, std::string("cannot open ") + path);
    return nullptr;
  }
  struct stat st;
  if (fstat(ld->fd, &st) != 0) {
    set_err(err, errlen, std::string("cannot stat ") + path);
    return nullptr;
  }
  ld->map_bytes = static_cast<size_t>(st.st_size);
  const size_t tok_bytes = dtype == DTYPE_U16 ? 2 : 4;
  ld->n_tokens = static_cast<int64_t>(ld->map_bytes / tok_bytes);
  if (ld->n_tokens < seq + 1) {
    set_err(err, errlen,
            "file holds " + std::to_string(ld->n_tokens) +
                " tokens, need at least " + std::to_string(seq + 1));
    return nullptr;
  }
  ld->map = mmap(nullptr, ld->map_bytes, PROT_READ, MAP_SHARED, ld->fd, 0);
  if (ld->map == MAP_FAILED) {
    ld->map = nullptr;
    set_err(err, errlen, std::string("mmap failed for ") + path);
    return nullptr;
  }
  madvise(const_cast<void*>(ld->map), ld->map_bytes, MADV_RANDOM);
  ld->seq = seq;
  ld->batch = batch;
  ld->dtype = dtype;
  ld->depth = depth;
  for (int t = 0; t < n_threads; ++t) {
    // distinct, deterministic stream per thread
    ld->threads.emplace_back(&Loader::worker, ld.get(),
                             seed + 0x517cc1b727220a95ULL * (t + 1));
  }
  return ld.release();
}

// Blocks until a batch is ready; copies into caller buffers of B*S int32 each.
// Safe against a concurrent dtpp_dl_close: close() waits for in-flight
// readers (the `readers` count) before freeing the Loader.
int dtpp_dl_next(void* handle, int32_t* toks_out, int32_t* tgts_out) {
  auto* ld = static_cast<Loader*>(handle);
  std::vector<int32_t> buf;
  size_t n = 0;
  {
    std::unique_lock<std::mutex> lk(ld->mu);
    ++ld->readers;
    ld->cv_item.wait(lk, [&] { return ld->stop.load() || !ld->queue.empty(); });
    const bool closing = ld->queue.empty();
    if (!closing) {
      buf = std::move(ld->queue.front());
      ld->queue.pop_front();
      ld->cv_space.notify_one();
      n = static_cast<size_t>(ld->batch * ld->seq);
    }
    if (--ld->readers == 0) ld->cv_readers.notify_all();
    if (closing) return 1;
    // `ld` must not be touched after unlock: close() may free it as soon as
    // readers hits zero. Everything needed below is in locals.
  }
  std::memcpy(toks_out, buf.data(), n * sizeof(int32_t));
  std::memcpy(tgts_out, buf.data() + n, n * sizeof(int32_t));
  return 0;
}

// Unblock every in-flight and future dtpp_dl_next (they return 1) without
// freeing the Loader. Callers that may race next() against close() should
// stop first, drain their readers, then close.
void dtpp_dl_stop(void* handle) {
  auto* ld = static_cast<Loader*>(handle);
  std::lock_guard<std::mutex> lk(ld->mu);
  ld->stop.store(true);
  ld->cv_item.notify_all();
  ld->cv_space.notify_all();
}

void dtpp_dl_close(void* handle) {
  auto* ld = static_cast<Loader*>(handle);
  {
    std::unique_lock<std::mutex> lk(ld->mu);
    ld->stop.store(true);
    ld->cv_item.notify_all();
    ld->cv_space.notify_all();
    ld->cv_readers.wait(lk, [&] { return ld->readers == 0; });
  }
  delete ld;  // ~Loader joins the (already stopping) worker threads
}

}  // extern "C"
