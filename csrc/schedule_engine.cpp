// Native schedule-compilation engine.
//
// C++ twin of parallel/schedules.py: per-device action-order generation for
// GPipe / 1F1B / Interleaved-1F1B / ZB-H1, ASAP tick scheduling with one-hop
// ppermute latency, greedy buffer-slot allocation from activation lifetimes,
// and emission of the executor tick table [T, D, 13] (column layout
// documented in schedules.py). Semantics must match the Python implementation
// exactly — tests assert bit-identical tables — so the Python path remains
// the executable specification and this library is the fast production path
// (large D*V*M schedule compilation is O(actions * ticks) host work).
//
// This fills the native-runtime slot that the reference occupies with
// vendored C++ (c10d/gloo transport + ATen, SURVEY.md §2.3): here the
// transport/compute layers are XLA's native code, and the first-party native
// layer is this schedule engine plus the Pallas kernels.
//
// Build: make -C csrc   (produces libschedule_engine.so; loaded via ctypes)

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <map>
#include <queue>
#include <string>
#include <vector>

namespace {

enum Op { OP_F = 0, OP_B = 1, OP_W = 2 };

struct Action {
  int stage;
  int op;  // Op
  int mb;
  bool operator<(const Action& o) const {
    if (stage != o.stage) return stage < o.stage;
    if (op != o.op) return op < o.op;
    return mb < o.mb;
  }
};

using Order = std::vector<Action>;

int fail(char* err, int errlen, const std::string& msg) {
  std::strncpy(err, msg.c_str(), errlen - 1);
  err[errlen - 1] = '\0';
  return 1;
}

std::vector<Order> gpipe_order(int D, int M) {
  std::vector<Order> orders(D);
  for (int d = 0; d < D; ++d) {
    for (int m = 0; m < M; ++m) orders[d].push_back({d, OP_F, m});
    for (int m = 0; m < M; ++m) orders[d].push_back({d, OP_B, m});
  }
  return orders;
}

std::vector<Order> one_f_one_b_order(int D, int M) {
  std::vector<Order> orders(D);
  for (int d = 0; d < D; ++d) {
    int warmup = std::min(M, D - 1 - d);
    int nf = 0, nb = 0;
    for (; nf < warmup; ++nf) orders[d].push_back({d, OP_F, nf});
    while (nf < M) {
      orders[d].push_back({d, OP_F, nf++});
      orders[d].push_back({d, OP_B, nb++});
    }
    for (; nb < M; ++nb) orders[d].push_back({d, OP_B, nb});
  }
  return orders;
}

std::vector<Order> interleaved_order(int D, int V, int M) {
  if (V == 1) return one_f_one_b_order(D, M);
  int num_rounds = std::max(1, M / D);
  int mbpr = M / num_rounds;  // microbatches per round
  int total = M * V;
  std::vector<Order> orders(D);
  auto fwd_vm = [&](int i, int* v, int* m) {
    *v = (i / mbpr) % V;
    *m = (i / (mbpr * V)) * mbpr + (i % mbpr);
  };
  auto bwd_vm = [&](int j, int* v, int* m) {
    *v = V - 1 - ((j / mbpr) % V);
    *m = (j / (mbpr * V)) * mbpr + (j % mbpr);
  };
  for (int d = 0; d < D; ++d) {
    int warmup = std::min(total, (V - 1) * mbpr + 2 * (D - 1 - d));
    int nf = 0, nb = 0, v, m;
    for (; nf < warmup; ++nf) {
      fwd_vm(nf, &v, &m);
      orders[d].push_back({v * D + d, OP_F, m});
    }
    while (nf < total) {
      fwd_vm(nf++, &v, &m);
      orders[d].push_back({v * D + d, OP_F, m});
      bwd_vm(nb++, &v, &m);
      orders[d].push_back({v * D + d, OP_B, m});
    }
    while (nb < total) {
      bwd_vm(nb++, &v, &m);
      orders[d].push_back({v * D + d, OP_B, m});
    }
  }
  return orders;
}

// BFS breadth-first pipeline (arXiv:2211.05953): GPipe generalized to V
// virtual stages with wrap placement — all forwards in (v, m) lexicographic
// order, then all backwards with v reversed. Mirrors schedules.bfs_order.
std::vector<Order> bfs_order(int D, int V, int M) {
  std::vector<Order> orders(D);
  for (int d = 0; d < D; ++d) {
    for (int v = 0; v < V; ++v)
      for (int m = 0; m < M; ++m) orders[d].push_back({v * D + d, OP_F, m});
    for (int v = V - 1; v >= 0; --v)
      for (int m = 0; m < M; ++m) orders[d].push_back({v * D + d, OP_B, m});
  }
  return orders;
}

// ZB-H1 (arXiv:2401.10241): dgrad/wgrad split backward; stage 0 has no B
// (nothing upstream to send a cotangent to) — its W does the full
// parameter+embedding backward. Orders come from the same greedy priority
// simulation as schedules._zb_greedy_order (B > F > W so wgrad sinks into
// bubble ticks; in-flight forward cap 2D - d, the memory price of hitting
// the paper's 3M + D - 1 makespan with the stage-0 dgrad elided). Must stay
// bit-identical to the Python generator.
std::vector<Order> zb_h1_order(int D, int M) {
  const int S = D;
  // done[s][op][m] = completion tick, or -1
  std::vector<std::vector<std::vector<int>>> done(
      S, std::vector<std::vector<int>>(3, std::vector<int>(M, -1)));
  // per (stage, op) next-microbatch pointer: within an op, readiness is
  // monotone in m, so the minimum remaining ready m is always the pointer
  std::vector<std::vector<int>> next_m(S, std::vector<int>(3, 0));
  std::vector<int> n_f(D, 0), n_w(D, 0);
  std::vector<Order> orders(D);
  int remaining = 3 * S * M - M;  // no B on stage 0
  int t = 0;
  const int limit = 8 * remaining + 64;

  auto ready = [&](int s, int op, int m, int now) {
    if (op == OP_F) {
      if (s == 0) return true;
      int d = done[s - 1][OP_F][m];
      return d >= 0 && d + 1 <= now;
    }
    if (done[s][OP_F][m] < 0) return false;
    if (op == OP_W) {
      if (s == 0) {
        int d = done[1][OP_B][m];
        return d >= 0 && d + 1 <= now;
      }
      if (s == S - 1) return true;
      return done[s][OP_B][m] >= 0;
    }
    // dgrad B
    if (s == S - 1) return true;
    int d = done[s + 1][OP_B][m];
    return d >= 0 && d + 1 <= now;
  };

  while (remaining > 0) {
    if (t > limit) return {};  // deadlock: caller reports failure
    for (int d = 0; d < D; ++d) {
      const int s = d;  // V = 1: stage == device
      // priority: B, then F (under the in-flight cap), then W
      const int order_ops[3] = {OP_B, OP_F, OP_W};
      for (int op : order_ops) {
        if (op == OP_B && s == 0) continue;
        int m = next_m[s][op];
        if (m >= M) continue;
        if (op == OP_F && n_f[d] - n_w[d] >= 2 * D - d) continue;
        if (!ready(s, op, m, t)) continue;
        done[s][op][m] = t;
        next_m[s][op] = m + 1;
        orders[d].push_back({s, op, m});
        if (op == OP_F) ++n_f[d];
        if (op == OP_W) ++n_w[d];
        --remaining;
        break;
      }
    }
    ++t;
  }
  return orders;
}

// Greedy interval slot allocation, identical to schedules._allocate_slots:
// events sorted by (store, release); min-heap of freed slots so the
// lowest-numbered free slot is always reused first.
struct SlotAlloc {
  std::map<std::pair<int, int>, int> assign;  // (stage, mb) -> slot
  int n_slots = 0;
};

SlotAlloc allocate(std::vector<std::tuple<int, int, std::pair<int, int>>> events) {
  std::sort(events.begin(), events.end(),
            [](const auto& a, const auto& b) {
              if (std::get<0>(a) != std::get<0>(b))
                return std::get<0>(a) < std::get<0>(b);
              return std::get<1>(a) < std::get<1>(b);
            });
  std::priority_queue<int, std::vector<int>, std::greater<int>> free_slots;
  std::priority_queue<std::pair<int, int>, std::vector<std::pair<int, int>>,
                      std::greater<std::pair<int, int>>> in_use;  // (release, slot)
  SlotAlloc out;
  for (const auto& [store, release, key] : events) {
    while (!in_use.empty() && in_use.top().first < store) {
      free_slots.push(in_use.top().second);
      in_use.pop();
    }
    int slot;
    if (!free_slots.empty()) {
      slot = free_slots.top();
      free_slots.pop();
    } else {
      slot = out.n_slots++;
    }
    out.assign[key] = slot;
    in_use.push({release, slot});
  }
  return out;
}

// Tick-table column layout (schedules.py). Columns 13-16 are the vshape
// (ZB-V) reverse/local transfer routes; the wrap-placement schedules this
// engine compiles never use them, so they stay -1 — keeping wrap tables
// bit-identical to the Python compiler's.
enum Cols {
  COL_STORE_F_SLOT = 0,
  COL_FWD_V = 1, COL_FWD_M = 2, COL_FWD_SLOT = 3,
  COL_STORE_B_SLOT = 4,
  COL_BWD_V = 5, COL_BWD_M = 6,
  COL_BWD_ASLOT = 7, COL_BWD_GSLOT = 8,
  COL_W_V = 9, COL_W_M = 10,
  COL_W_ASLOT = 11, COL_W_GSLOT = 12,
  COL_FWD_LOCAL_SLOT = 13, COL_STORE_F_NEG_SLOT = 14,
  COL_BWD_LOCAL_SLOT = 15, COL_STORE_B_POS_SLOT = 16,
  N_COLS = 17,
};

}  // namespace

extern "C" {

// Compiles a schedule. Returns 0 on success. table_out must hold
// table_capacity int32s; on success *t_out ticks were written as
// [T, D, N_COLS]. Matches compile_schedule() in schedules.py bit-for-bit.
int dtpp_compile_schedule(const char* name, int D, int V, int M,
                          int32_t* table_out, int64_t table_capacity,
                          int* t_out, int* n_act_out, int* n_grad_out,
                          char* err, int errlen) {
  std::string sname(name);
  std::vector<Order> orders;
  if (sname == "GPipe") {
    if (V != 1) return fail(err, errlen, "GPipe supports a single stage per device");
    orders = gpipe_order(D, M);
  } else if (sname == "1F1B" || (sname == "Interleaved1F1B" && V == 1)) {
    if (M < D) return fail(err, errlen, "1F1B requires n_microbatches >= n_devices");
    orders = one_f_one_b_order(D, M);
  } else if (sname == "Interleaved1F1B") {
    int num_rounds = std::max(1, M / D);
    if (M % num_rounds != 0)
      return fail(err, errlen, "Interleaved1F1B requires n_microbatches % num_rounds == 0");
    orders = interleaved_order(D, V, M);
  } else if (sname == "BFS") {
    orders = bfs_order(D, V, M);
  } else if (sname == "ZBH1") {
    if (V != 1) return fail(err, errlen, "ZBH1 supports a single stage per device");
    if (D < 2) return fail(err, errlen, "ZBH1 requires n_devices >= 2");
    if (M < D) return fail(err, errlen, "ZBH1 requires n_microbatches >= n_devices");
    orders = zb_h1_order(D, M);
    if (orders.empty())
      return fail(err, errlen, "ZBH1 synthesis deadlocked");
  } else {
    return fail(err, errlen, "unknown schedule: " + sname);
  }

  const int S = D * V;
  // --- ASAP tick scheduling (schedule_ticks) ---
  std::map<Action, int> done;
  std::vector<size_t> ptr(D, 0);
  int n_actions = 0;
  for (const auto& o : orders) n_actions += o.size();
  const int limit = 4 * n_actions + 4 * S + 16;
  int t = 0;
  auto pending = [&]() {
    for (int d = 0; d < D; ++d)
      if (ptr[d] < orders[d].size()) return true;
    return false;
  };
  while (pending()) {
    if (t > limit) return fail(err, errlen, "schedule deadlocked");
    for (int d = 0; d < D; ++d) {
      if (ptr[d] >= orders[d].size()) continue;
      const Action& a = orders[d][ptr[d]];
      bool ready;
      if (a.op == OP_F) {
        if (a.stage == 0) {
          ready = true;
        } else {
          auto it = done.find({a.stage - 1, OP_F, a.mb});
          ready = it != done.end() && it->second + 1 <= t;
        }
      } else if (a.op == OP_W) {
        ready = done.count({a.stage, OP_F, a.mb}) > 0;
        if (ready) {
          if (a.stage == 0) {
            auto it = done.find({1, OP_B, a.mb});
            ready = it != done.end() && it->second + 1 <= t;
          } else if (a.stage != S - 1) {
            ready = done.count({a.stage, OP_B, a.mb}) > 0;
          }
        }
      } else {  // OP_B
        ready = done.count({a.stage, OP_F, a.mb}) > 0;
        if (ready && a.stage != S - 1) {
          auto it = done.find({a.stage + 1, OP_B, a.mb});
          ready = it != done.end() && it->second + 1 <= t;
        }
      }
      if (ready) {
        done[a] = t;
        ++ptr[d];
      }
    }
    ++t;
  }
  int T = t + 1;  // +1 for trailing arrivals (trimmed below)

  // --- slot allocation from lifetimes ---
  std::vector<std::vector<std::tuple<int, int, std::pair<int, int>>>>
      act_events(D), grad_events(D);
  for (const auto& [a, ta] : done) {
    if (a.op != OP_F) continue;
    int d = a.stage % D;
    int store = a.stage == 0 ? ta : done.at({a.stage - 1, OP_F, a.mb}) + 1;
    int release = -1;
    auto itb = done.find({a.stage, OP_B, a.mb});
    if (itb != done.end()) release = std::max(release, itb->second);
    auto itw = done.find({a.stage, OP_W, a.mb});
    if (itw != done.end()) release = std::max(release, itw->second);
    act_events[d].push_back({store, release, {a.stage, a.mb}});
  }
  for (int s = 0; s < S - 1; ++s) {
    int d = s % D;
    for (int m = 0; m < M; ++m) {
      int store = done.at({s + 1, OP_B, m}) + 1;
      int release = -1;
      auto itb = done.find({s, OP_B, m});
      if (itb != done.end()) release = std::max(release, itb->second);
      auto itw = done.find({s, OP_W, m});
      if (itw != done.end()) release = std::max(release, itw->second);
      grad_events[d].push_back({store, release, {s, m}});
    }
  }
  std::vector<SlotAlloc> act_alloc(D), grad_alloc(D);
  int n_act = 0, n_grad = 0;
  for (int d = 0; d < D; ++d) {
    act_alloc[d] = allocate(act_events[d]);
    grad_alloc[d] = allocate(grad_events[d]);
    n_act = std::max(n_act, act_alloc[d].n_slots);
    n_grad = std::max(n_grad, grad_alloc[d].n_slots);
  }
  n_grad = std::max(n_grad, 1);

  // --- table emission ---
  if (static_cast<int64_t>(T) * D * N_COLS > table_capacity)
    return fail(err, errlen, "table capacity too small");
  std::vector<int32_t> table(static_cast<size_t>(T) * D * N_COLS, -1);
  auto cell = [&](int tt, int d, int c) -> int32_t& {
    return table[(static_cast<size_t>(tt) * D + d) * N_COLS + c];
  };
  for (const auto& [a, ta] : done) {
    int d = a.stage % D;
    int v = a.stage / D;
    if (a.op == OP_F) {
      cell(ta, d, COL_FWD_V) = v;
      cell(ta, d, COL_FWD_M) = a.mb;
      cell(ta, d, COL_FWD_SLOT) = act_alloc[d].assign.at({a.stage, a.mb});
      if (a.stage < S - 1) {
        int nd = (a.stage + 1) % D;
        cell(ta + 1, nd, COL_STORE_F_SLOT) =
            act_alloc[nd].assign.at({a.stage + 1, a.mb});
      }
    } else if (a.op == OP_B) {
      cell(ta, d, COL_BWD_V) = v;
      cell(ta, d, COL_BWD_M) = a.mb;
      cell(ta, d, COL_BWD_ASLOT) = act_alloc[d].assign.at({a.stage, a.mb});
      if (a.stage < S - 1)
        cell(ta, d, COL_BWD_GSLOT) = grad_alloc[d].assign.at({a.stage, a.mb});
      if (a.stage > 0) {
        int pd = (a.stage - 1) % D;
        cell(ta + 1, pd, COL_STORE_B_SLOT) =
            grad_alloc[pd].assign.at({a.stage - 1, a.mb});
      }
    } else {  // OP_W
      cell(ta, d, COL_W_V) = v;
      cell(ta, d, COL_W_M) = a.mb;
      cell(ta, d, COL_W_ASLOT) = act_alloc[d].assign.at({a.stage, a.mb});
      if (a.stage < S - 1)
        cell(ta, d, COL_W_GSLOT) = grad_alloc[d].assign.at({a.stage, a.mb});
    }
  }
  // trim trailing all-empty ticks
  auto tick_empty = [&](int tt) {
    for (int d = 0; d < D; ++d)
      for (int c = 0; c < N_COLS; ++c)
        if (cell(tt, d, c) != -1) return false;
    return true;
  };
  while (T > 1 && tick_empty(T - 1)) --T;

  std::memcpy(table_out, table.data(),
              static_cast<size_t>(T) * D * N_COLS * sizeof(int32_t));
  *t_out = T;
  *n_act_out = n_act;
  *n_grad_out = n_grad;
  return 0;
}

}  // extern "C"
