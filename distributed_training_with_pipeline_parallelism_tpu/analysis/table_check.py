"""Static table verifier: slot-lifetime dataflow analysis over tick tables.

:func:`verify_table` (``parallel.schedules``) is the compile-time gate —
it raises on the *first* violation of the store/compute/permute contract.
This module is the analysis-grade twin: :func:`check_table` interprets the
same contract over a ``[T, D, N_COLS]`` table but keeps going, returning a
structured :class:`TableReport` whose :class:`Hazard` entries carry an
exact (device, tick, column) location for every RAW/WAR/WAW violation,
every unpaired ppermute send/recv, and every route inconsistency against
:func:`fwd_route` / :func:`bwd_route` — which is what mutation testing and
CI gating need (a single opaque raise names one symptom; the report names
the corrupted cell).

On top of the hazard scan the report carries the *static* facts a clean
table proves:

- per-device slot high-water marks (``act_slots_used`` / ``act_live_peak``
  and the grad twins) — a static activation-memory bound per schedule;
- per-ring-channel comm volume: ``cells`` (table store entries) and
  ``hop_ticks`` (ticks with >= 1 store on the channel). ``hop_ticks`` is
  exactly the number of ``ppermute`` hops the unrolled executor emits per
  channel, because its dead-hop elision drops a channel's ppermute at
  tick ``t`` iff *no* device banks from it at tick ``t+1``
  (``pipeline.transfers``); the jaxpr auditor pins traced counts to this.
- ``compress_schedule`` -> ``replay_phases`` bit-exact roundtrip and
  ``table_unit_activity`` unit counts against the action set
  ``validate_order`` demands for (D, V, M, split_backward).
- the two-buffer ring discipline: ``overlap_bank_stages``'s deferred
  bank points re-verified independently (no unit ordered before a bank
  reads or writes the banked slot; same-slot channels keep lockstep
  write order), with per-channel exposed vs overlappable hop counts —
  the static proof behind ``comm_overlap="ring"``'s bit parity.

Everything here is numpy over the table plus the compiled metadata — no
jax import, so the checks run at table-build time (``DTPP_VERIFY_TABLES``)
for the cost of a small python interpretation.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..parallel.schedules import (BANK_BEFORE_B, BANK_BEFORE_F, BANK_BEFORE_W,
                                  COL_BWD_ASLOT, COL_BWD_GSLOT,
                                  COL_BWD_LOCAL_SLOT, COL_BWD_M, COL_BWD_V,
                                  COL_FWD_LOCAL_SLOT, COL_FWD_M, COL_FWD_SLOT,
                                  COL_FWD_V, COL_STORE_B_POS_SLOT,
                                  COL_STORE_B_SLOT, COL_STORE_F_NEG_SLOT,
                                  COL_STORE_F_SLOT, COL_W_ASLOT, COL_W_GSLOT,
                                  COL_W_M, COL_W_V, N_COLS, OVERLAP_CHANNELS,
                                  CompiledSchedule, ScheduleError, bwd_route,
                                  compress_schedule, fwd_route,
                                  overlap_bank_stages, phase_spans,
                                  placement_stage_of, replay_phases,
                                  table_unit_activity)

# Column-index -> name, for exact hazard locations ("which cell is wrong").
COLUMN_NAMES: Dict[int, str] = {
    COL_STORE_F_SLOT: "COL_STORE_F_SLOT",
    COL_FWD_V: "COL_FWD_V",
    COL_FWD_M: "COL_FWD_M",
    COL_FWD_SLOT: "COL_FWD_SLOT",
    COL_STORE_B_SLOT: "COL_STORE_B_SLOT",
    COL_BWD_V: "COL_BWD_V",
    COL_BWD_M: "COL_BWD_M",
    COL_BWD_ASLOT: "COL_BWD_ASLOT",
    COL_BWD_GSLOT: "COL_BWD_GSLOT",
    COL_W_V: "COL_W_V",
    COL_W_M: "COL_W_M",
    COL_W_ASLOT: "COL_W_ASLOT",
    COL_W_GSLOT: "COL_W_GSLOT",
    COL_FWD_LOCAL_SLOT: "COL_FWD_LOCAL_SLOT",
    COL_STORE_F_NEG_SLOT: "COL_STORE_F_NEG_SLOT",
    COL_BWD_LOCAL_SLOT: "COL_BWD_LOCAL_SLOT",
    COL_STORE_B_POS_SLOT: "COL_STORE_B_POS_SLOT",
}

# The four ring channels: (report key, bank column, sender ring offset).
# A value banked from channel (key, col) at tick t was sent at t-1 by the
# device ``(d - offset) % D`` — the executor's ppermute permutation.
RING_CHANNELS: Tuple[Tuple[str, int, int], ...] = (
    ("fwd_ring_pos", COL_STORE_F_SLOT, +1),
    ("bwd_ring_neg", COL_STORE_B_SLOT, -1),
    ("fwd_ring_neg", COL_STORE_F_NEG_SLOT, -1),
    ("bwd_ring_pos", COL_STORE_B_POS_SLOT, +1),
)


@dataclasses.dataclass(frozen=True)
class Hazard:
    """One verified violation, located to the exact table cell.

    ``kind`` is a stable machine-readable tag; ``device``/``tick`` are -1
    for table-global findings (unit-count or compression mismatches).
    """

    kind: str
    device: int
    tick: int
    column: str
    detail: str

    def location(self) -> str:
        return f"(device {self.device}, tick {self.tick}, {self.column})"

    def __str__(self) -> str:
        return f"{self.location()} {self.kind}: {self.detail}"


@dataclasses.dataclass
class TableReport:
    """Structured result of one static table verification."""

    name: str
    kind: str  # "train" | "forward" | "serving"
    n_devices: int
    n_virtual: int
    n_microbatches: int
    placement: str
    split_backward: bool
    makespan: int
    hazards: List[Hazard]
    # static memory bound: per-device max slot index in use + 1, and the
    # peak number of simultaneously-live values (<= slots used)
    act_slots_used: List[int]
    grad_slots_used: List[int]
    act_live_peak: List[int]
    grad_live_peak: List[int]
    n_act_slots: int
    n_grad_slots: int
    # channel key -> {"cells": stores in the table, "hop_ticks": ticks with
    # >= 1 store — the unrolled executor's emitted-ppermute count}
    comm: Dict[str, Dict[str, int]]
    unit_counts: Dict[str, int]
    compression: Dict[str, int]
    # channel key -> {"exposed_hop_ticks", "overlappable_hop_ticks"}: the
    # verified two-buffer discipline (train tables only; {} otherwise).
    # exposed + overlappable == the channel's hop_ticks.
    overlap: Dict[str, Dict[str, int]] = dataclasses.field(
        default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.hazards

    @property
    def predicted_ppermutes(self) -> int:
        """Total ppermute hops the unrolled tick executor emits for this
        table: per live channel, one hop per tick that banks from it
        (``pipeline.transfers`` elides the rest). Reverse channels only
        exist when the table routes through them."""
        keys = ["fwd_ring_pos", "bwd_ring_neg"]
        if self.uses_reverse_routes:
            keys += ["fwd_ring_neg", "bwd_ring_pos"]
        return sum(self.comm[k]["hop_ticks"] for k in keys if k in self.comm)

    @property
    def uses_reverse_routes(self) -> bool:
        return any(self.comm.get(k, {}).get("cells", 0) > 0
                   for k in ("fwd_ring_neg", "bwd_ring_pos",
                             "fwd_local", "bwd_local"))

    def summary(self) -> Dict[str, object]:
        """JSON-able digest (embedded in check reports and RunReport's
        ``static_analysis`` section)."""
        return {
            "name": self.name,
            "kind": self.kind,
            "n_devices": self.n_devices,
            "n_virtual": self.n_virtual,
            "n_microbatches": self.n_microbatches,
            "placement": self.placement,
            "split_backward": self.split_backward,
            "makespan": self.makespan,
            "ok": self.ok,
            "n_hazards": len(self.hazards),
            "hazards": [str(h) for h in self.hazards],
            "act_slots_used": list(self.act_slots_used),
            "grad_slots_used": list(self.grad_slots_used),
            "act_live_peak": list(self.act_live_peak),
            "grad_live_peak": list(self.grad_live_peak),
            "n_act_slots": self.n_act_slots,
            "n_grad_slots": self.n_grad_slots,
            "comm": {k: dict(v) for k, v in self.comm.items()},
            "predicted_ppermutes": self.predicted_ppermutes,
            "unit_counts": dict(self.unit_counts),
            "compression": dict(self.compression),
            "overlap": {k: dict(v) for k, v in self.overlap.items()},
            "overlappable_hop_ticks": sum(
                v["overlappable_hop_ticks"] for v in self.overlap.values()),
        }


def _comm_volume(table: np.ndarray) -> Dict[str, Dict[str, int]]:
    """Per-channel stores (``cells``) and live hop ticks (``hop_ticks``).

    A store at tick t is fed by the ppermute at the end of tick t-1, so
    hop ticks are counted over ``t >= 1`` (a tick-0 store reads the zero
    initial registers and is flagged as a hazard elsewhere).
    """
    out: Dict[str, Dict[str, int]] = {}
    for key, col, _ in RING_CHANNELS:
        stores = table[:, :, col] >= 0
        out[key] = {
            "cells": int(stores.sum()),
            "hop_ticks": int(stores[1:].any(axis=1).sum()),
        }
    for key, col in (("fwd_local", COL_FWD_LOCAL_SLOT),
                     ("bwd_local", COL_BWD_LOCAL_SLOT)):
        out[key] = {"cells": int((table[:, :, col] >= 0).sum()),
                    "hop_ticks": 0}
    return out


# Per-unit slot touches the deferred-bank discipline must not reorder
# against: (unit label, bank stage the unit runs after, activity column,
# ((slot column, buffer kind), ...)). A bank deferred past the unit's
# stage while the unit reads OR writes the banked slot breaks lockstep
# equivalence (reads would see the new arrival early; writes must land
# after the bank so the unit's write stays last).
_OVERLAP_UNIT_TOUCHES: Tuple[Tuple[str, int, int, Tuple[Tuple[int, str], ...]],
                             ...] = (
    ("F", BANK_BEFORE_F, COL_FWD_M,
     ((COL_FWD_SLOT, "act"), (COL_FWD_LOCAL_SLOT, "act"))),
    ("B", BANK_BEFORE_B, COL_BWD_M,
     ((COL_BWD_ASLOT, "act"), (COL_BWD_GSLOT, "grad"),
      (COL_BWD_LOCAL_SLOT, "grad"))),
    ("W", BANK_BEFORE_W, COL_W_M,
     ((COL_W_ASLOT, "act"), (COL_W_GSLOT, "grad"))),
)

# OVERLAP_CHANNELS shares RING_CHANNELS' column order; map columns to the
# report's channel keys so overlap stats join the comm dict keyspace.
_OVERLAP_KEYS: Tuple[str, ...] = tuple(key for key, _, _ in RING_CHANNELS)


def _overlap_discipline(table: np.ndarray,
                        hazards: List[Hazard]) -> Dict[str, Dict[str, int]]:
    """Verify the two-buffer (deferred-bank) ring discipline and count
    exposed vs overlappable hops per channel.

    ``schedules.overlap_bank_stages`` is the executor's single source of
    truth for where each arrival is committed; this check re-derives the
    constraint set independently (unit by unit, device by device) and
    flags any tick where a claimed bank stage is deferred past a unit that
    reads or writes the banked slot (``overlap-stage``), or where two
    same-buffer channels landing in one slot are assigned different
    stages, losing the lockstep write order (``overlap-order``). A clean
    report therefore *proves* the staged executor bit-equivalent to the
    lockstep one on this table.

    Returns per-channel ``{"exposed_hop_ticks", "overlappable_hop_ticks"}``
    over ticks ``t >= 1`` (same attribution as ``hop_ticks``): a hop whose
    arrival banks at stage 0 fences the next tick's first unit (exposed);
    any later stage lets the hop overlap the units before its bank point.
    """
    st = overlap_bank_stages(table)
    T = table.shape[0]
    out: Dict[str, Dict[str, int]] = {}
    for ci, (bank_col, kind) in enumerate(OVERLAP_CHANNELS):
        slots = table[:, :, bank_col]  # [T, D]; -1 = no bank
        banked = slots >= 0
        live = banked[1:].any(axis=1)  # per tick t >= 1
        deferred = st[1:, ci] > BANK_BEFORE_F
        out[_OVERLAP_KEYS[ci]] = {
            "exposed_hop_ticks": int((live & ~deferred).sum()),
            "overlappable_hop_ticks": int((live & deferred).sum()),
        }
        # soundness: no unit ordered before the bank touches the slot
        for label, unit_stage, m_col, slot_cols in _OVERLAP_UNIT_TOUCHES:
            if not (st[:, ci] > unit_stage).any():
                continue
            on = table[:, :, m_col] >= 0
            for slot_col, k in slot_cols:
                if k != kind:
                    continue
                touch = (table[:, :, slot_col] >= 0
                         if slot_col in (COL_FWD_LOCAL_SLOT,
                                         COL_BWD_LOCAL_SLOT)
                         else on)
                bad = (banked & touch & (table[:, :, slot_col] == slots)
                       & (st[:, ci] > unit_stage)[:, None])
                for t, d in np.argwhere(bad):
                    hazards.append(Hazard(
                        "overlap-stage", int(d), int(t),
                        COLUMN_NAMES[bank_col],
                        f"bank of slot {int(slots[t, d])} deferred to stage "
                        f"{int(st[t, ci])} but the {label} unit "
                        f"({COLUMN_NAMES[slot_col]}) touches it at stage "
                        f"{unit_stage}"))
    # same-buffer channels landing in the same slot must bank in lockstep
    # order, which the executor only preserves inside one stage
    for i, j in ((0, 2), (1, 3)):
        si = table[:, :, OVERLAP_CHANNELS[i][0]]
        sj = table[:, :, OVERLAP_CHANNELS[j][0]]
        clash = (si >= 0) & (sj >= 0) & (si == sj)
        for t in np.nonzero(clash.any(axis=1))[0]:
            if st[t, i] != st[t, j]:
                d = int(np.nonzero(clash[t])[0][0])
                hazards.append(Hazard(
                    "overlap-order", d, int(t),
                    COLUMN_NAMES[OVERLAP_CHANNELS[j][0]],
                    f"channels {_OVERLAP_KEYS[i]}/{_OVERLAP_KEYS[j]} bank "
                    f"slot {int(si[t, d])} at different stages "
                    f"({int(st[t, i])} vs {int(st[t, j])})"))
    return out


class _SlotFile:
    """One device's slot-addressed buffer under symbolic interpretation,
    with value liveness (outstanding expected reads) for WAR detection."""

    def __init__(self, label: str, n_slots: int):
        self.label = label
        self.n_slots = n_slots
        self.value: Dict[int, Tuple] = {}       # slot -> symbolic value
        self.reads_left: Dict[int, List[int]] = {}  # slot -> pending read ticks
        self.max_slot = -1
        self.live = 0
        self.live_peak = 0
        # optional write-event recorder (set by _TrainInterp): entries
        # (t, d, label, slot, column, prev_value, pending) let
        # recheck_after_swap re-derive prefix WAR hazards under a *new*
        # read schedule without reinterpreting the prefix
        self.log: Optional[List[Tuple]] = None

    def write(self, slot: int, val: Tuple, t: int, d: int, column: int,
              expected_reads: List[int], hazards: List[Hazard],
              written_this_tick: Dict[int, int]) -> None:
        self.max_slot = max(self.max_slot, slot)
        if slot in written_this_tick:
            hazards.append(Hazard(
                "double-store", d, t, COLUMN_NAMES[column],
                f"{self.label} slot {slot} written twice in one tick "
                f"(first via {COLUMN_NAMES[written_this_tick[slot]]})"))
        written_this_tick[slot] = column
        pending = [r for r in self.reads_left.get(slot, []) if r >= t]
        if self.log is not None:
            self.log.append((t, d, self.label, slot, column,
                             self.value.get(slot), tuple(pending)))
        if pending:
            hazards.append(Hazard(
                "overwrite-live", d, t, COLUMN_NAMES[column],
                f"{self.label} slot {slot} overwritten while "
                f"{self.value.get(slot)} still has reads at ticks "
                f"{pending}"))
        else:
            if self.reads_left.get(slot):
                self.live -= 1  # previous value retired cleanly
        self.value[slot] = val
        self.reads_left[slot] = list(expected_reads)
        if self.reads_left[slot]:
            self.live += 1
            self.live_peak = max(self.live_peak, self.live)

    def read(self, slot: int, expect: Tuple, t: int, d: int, column: int,
             what: str, hazards: List[Hazard]) -> None:
        self.max_slot = max(self.max_slot, slot)
        got = self.value.get(slot)
        if got != expect:
            hazards.append(Hazard(
                "read-wrong-value", d, t, COLUMN_NAMES[column],
                f"{what} expected {expect} in {self.label} slot {slot}, "
                f"found {got}"))
        pend = self.reads_left.get(slot)
        if pend and t in pend:
            pend.remove(t)
            if not pend:
                self.live -= 1


def _expected_reads(table: np.ndarray, placement: str, D: int
                    ) -> Tuple[Dict, Dict]:
    """Read schedule per device and value, derived from the table itself:
    ``act_reads[d][(s, m)]`` / ``grad_reads[d][(s, m)]`` -> sorted ticks at
    which the table claims to read that value. Drives WAR liveness (a
    corrupted read column simply shifts the claimed schedule — the
    symbolic value check still catches the mismatch)."""
    T = table.shape[0]
    act_reads: Dict[int, Dict[Tuple[int, int], List[int]]] = \
        {d: {} for d in range(D)}
    grad_reads: Dict[int, Dict[Tuple[int, int], List[int]]] = \
        {d: {} for d in range(D)}
    for t in range(T):
        for d in range(D):
            row = table[t, d]
            if row[COL_FWD_M] >= 0:
                s = placement_stage_of(placement, d, int(row[COL_FWD_V]), D)
                act_reads[d].setdefault((s, int(row[COL_FWD_M])),
                                        []).append(t)
            for vcol, mcol in ((COL_BWD_V, COL_BWD_M), (COL_W_V, COL_W_M)):
                if row[mcol] >= 0:
                    s = placement_stage_of(placement, d, int(row[vcol]), D)
                    m = int(row[mcol])
                    act_reads[d].setdefault((s, m), []).append(t)
                    grad_reads[d].setdefault((s, m), []).append(t)
    return act_reads, grad_reads


class _TrainInterp:
    """The symbolic interpreter behind :func:`check_table`, restructured as
    a resumable object so the schedule-search loop can snapshot per-tick
    state and revalidate only the suffix after a local move
    (:func:`recheck_after_swap`). ``run_tick`` is the exact per-tick
    contract: arrival stores, then F/B/W units, then send/recv pairing and
    register rotation."""

    def __init__(self, cs: CompiledSchedule):
        self.cs = cs
        self.table = np.asarray(cs.table)
        self.T, self.D = self.table.shape[0], cs.n_devices
        self.S, self.M = cs.n_stages, cs.n_microbatches
        self.pl = cs.placement
        self.hazards: List[Hazard] = []
        self.act_reads, self.grad_reads = _expected_reads(
            self.table, self.pl, self.D)
        self.act = [_SlotFile("act_buf", cs.n_act_slots)
                    for _ in range(self.D)]
        self.grad = [_SlotFile("grad_buf", cs.n_grad_slots)
                     for _ in range(self.D)]
        # channel registers: value delivered by last tick's ppermute
        self.regs: Dict[str, List[Optional[Tuple]]] = {
            key: [None] * self.D for key, _, _ in RING_CHANNELS}
        self.fwd_done: Dict[Tuple[int, int], int] = {}
        self.bwd_done: Dict[Tuple[int, int], int] = {}
        self.w_done: Dict[Tuple[int, int], int] = {}
        self.b_slots: Dict[Tuple[int, int, int], Tuple[int, int]] = {}
        self.f_slots: Dict[Tuple[int, int], int] = {}

    def _check_bounds(self, slot, n_slots, t, d, col, label):
        if slot >= n_slots:
            self.hazards.append(Hazard(
                "slot-out-of-bounds", d, t, COLUMN_NAMES[col],
                f"{label} slot {slot} >= declared n_slots {n_slots}"))

    def run_tick(self, t: int) -> None:
        table, D, S, pl = self.table, self.D, self.S, self.pl
        cs, hazards = self.cs, self.hazards
        act, grad = self.act, self.grad
        act_reads, grad_reads = self.act_reads, self.grad_reads
        check_bounds = self._check_bounds
        fwd_done, bwd_done, w_done = self.fwd_done, self.bwd_done, self.w_done
        b_slots, f_slots = self.b_slots, self.f_slots
        T = self.T

        sends: Dict[str, List[Optional[Tuple]]] = {
            key: [None] * D for key, _, _ in RING_CHANNELS}
        for d in range(D):
            row = table[t, d]
            written_act: Dict[int, int] = {}
            written_grad: Dict[int, int] = {}

            # 1. bank ring arrivals (reads channel registers filled at t-1)
            for key, col, _ in RING_CHANNELS:
                slot = int(row[col])
                if slot < 0:
                    continue
                buf = act[d] if col in (COL_STORE_F_SLOT,
                                        COL_STORE_F_NEG_SLOT) else grad[d]
                reads = act_reads if buf is act[d] else grad_reads
                check_bounds(slot, buf.n_slots, t, d, col, buf.label)
                val = self.regs[key][d]
                if val is None:
                    hazards.append(Hazard(
                        "store-empty-register", d, t, COLUMN_NAMES[col],
                        f"{key} store into {buf.label} slot {slot} but no "
                        f"value arrived on the channel (dropped or "
                        f"misrouted send at tick {t - 1})"))
                    continue
                buf.write(slot, val, t, d, col,
                          reads[d].get((val[1], val[2]), []), hazards,
                          written_act if buf is act[d] else written_grad)

            # 2. forward unit
            if row[COL_FWD_M] >= 0:
                s = placement_stage_of(pl, d, int(row[COL_FWD_V]), D)
                m = int(row[COL_FWD_M])
                slot = int(row[COL_FWD_SLOT])
                check_bounds(slot, cs.n_act_slots, t, d, COL_FWD_SLOT,
                             "act_buf")
                if s == 0:
                    # embed computed in place: the write IS this tick's F
                    act[d].write(slot, ("act", 0, m), t, d, COL_FWD_SLOT,
                                 act_reads[d].get((0, m), []), hazards,
                                 written_act)
                act[d].read(slot, ("act", s, m), t, d, COL_FWD_SLOT,
                            f"F(stage={s}, mb={m})", hazards)
                if (s, m) in fwd_done:
                    hazards.append(Hazard(
                        "duplicate-unit", d, t, COLUMN_NAMES[COL_FWD_M],
                        f"F(stage={s}, mb={m}) already ran at tick "
                        f"{fwd_done[(s, m)]}"))
                fwd_done[(s, m)] = t
                f_slots[(s, m)] = slot
                # route the output
                if s < S - 1:
                    route = fwd_route(pl, s, D)
                    if route == "local":
                        lslot = int(row[COL_FWD_LOCAL_SLOT])
                        if lslot < 0:
                            hazards.append(Hazard(
                                "route-mismatch", d, t,
                                "COL_FWD_LOCAL_SLOT",
                                f"F(stage={s}) routes 'local' but "
                                f"COL_FWD_LOCAL_SLOT is unset"))
                        else:
                            check_bounds(lslot, cs.n_act_slots, t, d,
                                         COL_FWD_LOCAL_SLOT, "act_buf")
                            act[d].write(
                                lslot, ("act", s + 1, m), t, d,
                                COL_FWD_LOCAL_SLOT,
                                act_reads[d].get((s + 1, m), []), hazards,
                                written_act)
                    else:
                        key = ("fwd_ring_pos" if route == "+1"
                               else "fwd_ring_neg")
                        sends[key][d] = ("act", s + 1, m)
                        if row[COL_FWD_LOCAL_SLOT] >= 0:
                            hazards.append(Hazard(
                                "route-mismatch", d, t,
                                "COL_FWD_LOCAL_SLOT",
                                f"F(stage={s}) routes '{route}' ring but "
                                f"COL_FWD_LOCAL_SLOT is set"))
                elif row[COL_FWD_LOCAL_SLOT] >= 0:
                    hazards.append(Hazard(
                        "route-mismatch", d, t, "COL_FWD_LOCAL_SLOT",
                        f"last stage F(stage={s}) must not route a local "
                        f"hop"))
            elif row[COL_FWD_LOCAL_SLOT] >= 0:
                hazards.append(Hazard(
                    "route-mismatch", d, t, "COL_FWD_LOCAL_SLOT",
                    "local fwd hop without an active forward unit"))

            # 3. backward (full or dgrad) unit
            if row[COL_BWD_M] >= 0:
                s = placement_stage_of(pl, d, int(row[COL_BWD_V]), D)
                m = int(row[COL_BWD_M])
                aslot = int(row[COL_BWD_ASLOT])
                check_bounds(aslot, cs.n_act_slots, t, d, COL_BWD_ASLOT,
                             "act_buf")
                act[d].read(aslot, ("act", s, m), t, d, COL_BWD_ASLOT,
                            f"B(stage={s}, mb={m}) saved input", hazards)
                gslot = int(row[COL_BWD_GSLOT])
                if s < S - 1:
                    check_bounds(gslot, cs.n_grad_slots, t, d,
                                 COL_BWD_GSLOT, "grad_buf")
                    grad[d].read(gslot, ("gout", s, m), t, d, COL_BWD_GSLOT,
                                 f"B(stage={s}, mb={m}) incoming cotangent",
                                 hazards)
                if (s, m) in bwd_done:
                    hazards.append(Hazard(
                        "duplicate-unit", d, t, COLUMN_NAMES[COL_BWD_M],
                        f"B(stage={s}, mb={m}) already ran at tick "
                        f"{bwd_done[(s, m)]}"))
                bwd_done[(s, m)] = t
                b_slots[(d, s, m)] = (aslot, gslot)
                if s > 0:
                    route = bwd_route(pl, s, D)
                    if route == "local":
                        lslot = int(row[COL_BWD_LOCAL_SLOT])
                        if lslot < 0:
                            hazards.append(Hazard(
                                "route-mismatch", d, t,
                                "COL_BWD_LOCAL_SLOT",
                                f"B(stage={s}) routes 'local' but "
                                f"COL_BWD_LOCAL_SLOT is unset"))
                        else:
                            check_bounds(lslot, cs.n_grad_slots, t, d,
                                         COL_BWD_LOCAL_SLOT, "grad_buf")
                            grad[d].write(
                                lslot, ("gout", s - 1, m), t, d,
                                COL_BWD_LOCAL_SLOT,
                                grad_reads[d].get((s - 1, m), []), hazards,
                                written_grad)
                    else:
                        key = ("bwd_ring_neg" if route == "-1"
                               else "bwd_ring_pos")
                        sends[key][d] = ("gout", s - 1, m)
                        if row[COL_BWD_LOCAL_SLOT] >= 0:
                            hazards.append(Hazard(
                                "route-mismatch", d, t,
                                "COL_BWD_LOCAL_SLOT",
                                f"B(stage={s}) routes '{route}' ring but "
                                f"COL_BWD_LOCAL_SLOT is set"))
                elif row[COL_BWD_LOCAL_SLOT] >= 0:
                    hazards.append(Hazard(
                        "route-mismatch", d, t, "COL_BWD_LOCAL_SLOT",
                        "stage 0 backward must not route a cotangent"))
            elif row[COL_BWD_LOCAL_SLOT] >= 0:
                hazards.append(Hazard(
                    "route-mismatch", d, t, "COL_BWD_LOCAL_SLOT",
                    "local bwd hop without an active backward unit"))

            # 4. weight-grad unit (split schedules)
            if row[COL_W_M] >= 0:
                s = placement_stage_of(pl, d, int(row[COL_W_V]), D)
                m = int(row[COL_W_M])
                aslot = int(row[COL_W_ASLOT])
                gslot = int(row[COL_W_GSLOT])
                check_bounds(aslot, cs.n_act_slots, t, d, COL_W_ASLOT,
                             "act_buf")
                act[d].read(aslot, ("act", s, m), t, d, COL_W_ASLOT,
                            f"W(stage={s}, mb={m}) saved input", hazards)
                if s < S - 1:
                    check_bounds(gslot, cs.n_grad_slots, t, d, COL_W_GSLOT,
                                 "grad_buf")
                    grad[d].read(gslot, ("gout", s, m), t, d, COL_W_GSLOT,
                                 f"W(stage={s}, mb={m}) incoming cotangent",
                                 hazards)
                if (s, m) in w_done:
                    hazards.append(Hazard(
                        "duplicate-unit", d, t, COLUMN_NAMES[COL_W_M],
                        f"W(stage={s}, mb={m}) already ran at tick "
                        f"{w_done[(s, m)]}"))
                w_done[(s, m)] = t
                # W must alias the B unit's saved slots, never a recycled
                # copy (split-backward contract; stage 0 has no B — its
                # saved input is F(0, m)'s own slot)
                if (d, s, m) in b_slots:
                    ba, bg = b_slots[(d, s, m)]
                    if aslot != ba:
                        hazards.append(Hazard(
                            "w-slot-alias", d, t, "COL_W_ASLOT",
                            f"W(stage={s}, mb={m}) saved-input slot "
                            f"{aslot} != B's slot {ba}"))
                    if s < S - 1 and gslot != bg:
                        hazards.append(Hazard(
                            "w-slot-alias", d, t, "COL_W_GSLOT",
                            f"W(stage={s}, mb={m}) cotangent slot {gslot} "
                            f"!= B's slot {bg}"))
                elif s == 0 and (0, m) in f_slots \
                        and aslot != f_slots[(0, m)]:
                    hazards.append(Hazard(
                        "w-slot-alias", d, t, "COL_W_ASLOT",
                        f"W(stage=0, mb={m}) saved-input slot {aslot} != "
                        f"F(0, {m})'s slot {f_slots[(0, m)]}"))

        # 5. send/recv pairing per ring direction, then rotate registers.
        # A send with no matching next-tick store silently drops data; a
        # store with no matching previous-tick send banks garbage — both
        # are located at the store cell. (A tick-0 store can pair with
        # nothing: the channel registers start empty.)
        for key, col, offset in RING_CHANNELS:
            if t == 0:
                for d in range(D):
                    if table[0, d, col] >= 0:
                        hazards.append(Hazard(
                            "recv-unpaired", d, 0, COLUMN_NAMES[col],
                            f"{key} store at tick 0 precedes any send"))
            for d in range(D):
                val = sends[key][d]
                dst = (d + offset) % D
                if val is not None:
                    if t + 1 >= T or table[t + 1, dst, col] < 0:
                        hazards.append(Hazard(
                            "send-unpaired", dst, t + 1,
                            COLUMN_NAMES[col],
                            f"{key} send of {val} from device {d} at tick "
                            f"{t} has no receiving store"))
                src = (d - offset) % D
                if (t + 1 < T and table[t + 1, d, col] >= 0
                        and sends[key][src] is None):
                    hazards.append(Hazard(
                        "recv-unpaired", d, t + 1, COLUMN_NAMES[col],
                        f"{key} store at tick {t + 1} has no matching "
                        f"send from device {src} at tick {t}"))
            # rotate: after the ppermute, device d holds what (d - offset)
            # sent — the channel register is indexed by receiver
            self.regs[key] = [sends[key][(d - offset) % D]
                              for d in range(D)]

    # -- snapshot/restore for the incremental recheck fast path ----------

    @staticmethod
    def _snap_files(files: List[_SlotFile]):
        return [(dict(f.value), {k: list(v) for k, v in f.reads_left.items()},
                 f.max_slot, f.live, f.live_peak) for f in files]

    def snapshot(self):
        """Copy of all interpreter state *before* the next run_tick call."""
        return (self._snap_files(self.act), self._snap_files(self.grad),
                {k: list(v) for k, v in self.regs.items()},
                dict(self.fwd_done), dict(self.bwd_done), dict(self.w_done),
                dict(self.b_slots), dict(self.f_slots))

    def restore(self, snap) -> None:
        acts, grads, regs, fd, bd, wd, bs, fs = snap
        for files, saved in ((self.act, acts), (self.grad, grads)):
            for f, (val, rl, ms, lv, lp) in zip(files, saved):
                f.value = dict(val)
                f.reads_left = {k: list(v) for k, v in rl.items()}
                f.max_slot, f.live, f.live_peak = ms, lv, lp
        self.regs = {k: list(v) for k, v in regs.items()}
        self.fwd_done = dict(fd)
        self.bwd_done = dict(bd)
        self.w_done = dict(wd)
        self.b_slots = dict(bs)
        self.f_slots = dict(fs)

    def repatch_reads(self, start: int) -> None:
        """Point restored slot liveness at THIS table's read schedule.

        After restoring a snapshot taken on a different (prefix-identical)
        table, each live value's pending reads must come from the *new*
        table's derived read schedule: for a clean prefix every expected
        read before ``start`` was consumed, so the remainder is exactly the
        new schedule filtered to ``>= start``."""
        for files, reads in ((self.act, self.act_reads),
                             (self.grad, self.grad_reads)):
            for d, f in enumerate(files):
                live = 0
                for slot, val in f.value.items():
                    pend = [r for r in reads[d].get((val[1], val[2]), [])
                            if r >= start]
                    f.reads_left[slot] = pend
                    if pend:
                        live += 1
                f.live = live
                f.live_peak = max(f.live_peak, live)

    def finish(self, *, compression: bool = True) -> TableReport:
        """Global (whole-table) checks + report assembly: unit counts vs
        the action set validate_order demands, and (optionally) the
        phase-compression roundtrip."""
        cs, table, hazards = self.cs, self.table, self.hazards
        T, S, M = self.T, self.S, self.M
        activity = table_unit_activity(table).sum(axis=(0, 1))
        n_f, n_b, n_w = int(activity[0]), int(activity[1]), int(activity[2])
        want_f = S * M
        want_b = (S - 1) * M if cs.split_backward else S * M
        want_w = S * M if cs.split_backward else 0
        for label, got, want, col in (("F", n_f, want_f, COL_FWD_M),
                                      ("B", n_b, want_b, COL_BWD_M),
                                      ("W", n_w, want_w, COL_W_M)):
            if got != want:
                hazards.append(Hazard(
                    "unit-count", -1, -1, COLUMN_NAMES[col],
                    f"{label} unit count {got} != expected {want} "
                    f"(S={S}, M={M}, split_backward={cs.split_backward})"))
        unit_counts = {"F": n_f, "B": n_b, "W": n_w, "idle": int(activity[3])}

        # phase-compression roundtrip (compress self-checks; assert anyway)
        comp: Dict[str, int] = {}
        if compression:
            try:
                phases = compress_schedule(table)
                if not np.array_equal(replay_phases(phases), table):
                    raise ScheduleError("replay does not reconstruct the table")
                spans = phase_spans(phases)
                if sum(n for _, n in spans) != T:
                    raise ScheduleError("phase spans do not tile the table")
                comp = {"n_phases": len(phases), "n_rows": T}
            except ScheduleError as e:
                hazards.append(Hazard("compression-roundtrip", -1, -1,
                                      "table", str(e)))

        return TableReport(
            name=cs.name, kind="train", n_devices=self.D,
            n_virtual=cs.n_virtual, n_microbatches=M, placement=self.pl,
            split_backward=cs.split_backward, makespan=T, hazards=hazards,
            act_slots_used=[a.max_slot + 1 for a in self.act],
            grad_slots_used=[g.max_slot + 1 for g in self.grad],
            act_live_peak=[a.live_peak for a in self.act],
            grad_live_peak=[g.live_peak for g in self.grad],
            n_act_slots=cs.n_act_slots, n_grad_slots=cs.n_grad_slots,
            comm=_comm_volume(table), unit_counts=unit_counts,
            compression=comp,
            overlap=_overlap_discipline(table, hazards))


def check_table(cs: CompiledSchedule) -> TableReport:
    """Statically verify a compiled training schedule's tick table.

    Interprets the executor contract cell by cell (arrival stores, then
    F/B/W units, then routed sends), accumulating every violation as a
    located :class:`Hazard` instead of raising — see the module docstring
    for the full check list."""
    interp = _TrainInterp(cs)
    for t in range(interp.T):
        interp.run_tick(t)
    return interp.finish()


# ---------------------------------------------------------------------------
# Search-loop fast path: digest memoization + incremental suffix recheck
# ---------------------------------------------------------------------------

_REPORT_MEMO: "OrderedDict[Tuple, TableReport]" = OrderedDict()
_REPORT_MEMO_MAX = 256


def _memo_key(cs: CompiledSchedule) -> Tuple:
    from ..parallel.schedules import table_digest
    return (table_digest(cs.table), cs.n_devices, cs.n_virtual,
            cs.n_microbatches, cs.placement, bool(cs.split_backward),
            cs.n_act_slots, cs.n_grad_slots)


def check_table_cached(cs: CompiledSchedule) -> TableReport:
    """:func:`check_table` memoized by table content digest + compile
    metadata (LRU, bounded). The returned report is shared across hits —
    treat it as immutable."""
    key = _memo_key(cs)
    hit = _REPORT_MEMO.get(key)
    if hit is not None:
        _REPORT_MEMO.move_to_end(key)
        return hit
    report = check_table(cs)
    _REPORT_MEMO[key] = report
    while len(_REPORT_MEMO) > _REPORT_MEMO_MAX:
        _REPORT_MEMO.popitem(last=False)
    return report


@dataclasses.dataclass
class TableCheckBaseline:
    """Full check of one table plus per-tick interpreter snapshots, the
    anchor :func:`recheck_after_swap` resumes from."""

    cs: CompiledSchedule
    table: np.ndarray
    report: TableReport
    snapshots: List[Tuple]  # snapshots[t] = state before run_tick(t)
    write_log: List[Tuple]  # every buffer write: (t, d, label, slot, ...)


def check_table_baseline(cs: CompiledSchedule) -> TableCheckBaseline:
    """Run the full :func:`check_table` pass, keeping a state snapshot
    before every tick so nearby candidate tables can be rechecked from the
    first tick that differs instead of from tick 0."""
    interp = _TrainInterp(cs)
    log: List[Tuple] = []
    for f in interp.act + interp.grad:
        f.log = log
    snaps: List[Tuple] = []
    for t in range(interp.T):
        snaps.append(interp.snapshot())
        interp.run_tick(t)
    report = interp.finish()
    return TableCheckBaseline(cs=cs, table=interp.table.copy(),
                              report=report, snapshots=snaps,
                              write_log=log)


# Hazard kinds at tick ``start`` that the resumed interpretation cannot
# re-derive (they were emitted by run_tick(start - 1)'s pairing stage
# against the unchanged row at ``start - 1``) — reused from the baseline.
_PAIRING_KINDS = ("send-unpaired", "recv-unpaired")


def recheck_after_swap(cs_new: CompiledSchedule,
                       baseline: TableCheckBaseline) -> TableReport:
    """Incrementally recheck ``cs_new`` against a clean baseline.

    Finds the first tick where the new table differs from the baseline's,
    restores the interpreter snapshot one tick earlier (pairing checks look
    one row ahead), repoints slot liveness at the new table's read
    schedule, and interprets only the suffix. Falls back to the full
    :func:`check_table` when the baseline has hazards or the compile
    metadata differs. Equivalent to the full check for hazard locations,
    slot high-water marks, unit counts, and comm volume (tested over a
    random-mutation corpus); the phase-compression roundtrip is skipped
    (``compression == {}``) and prefix live peaks are inherited from the
    baseline.
    """
    base_cs = baseline.cs
    if (cs_new.n_devices != base_cs.n_devices
            or cs_new.n_virtual != base_cs.n_virtual
            or cs_new.n_microbatches != base_cs.n_microbatches
            or cs_new.placement != base_cs.placement
            or bool(cs_new.split_backward) != bool(base_cs.split_backward)
            or cs_new.n_act_slots < base_cs.n_act_slots
            or cs_new.n_grad_slots < base_cs.n_grad_slots
            or not baseline.report.ok):
        return check_table(cs_new)
    new = np.asarray(cs_new.table)
    old = baseline.table
    k = min(new.shape[0], old.shape[0])
    diff = np.nonzero((new[:k] != old[:k]).any(axis=(1, 2)))[0]
    if diff.size == 0:
        if (new.shape[0] == old.shape[0]
                and cs_new.n_act_slots == base_cs.n_act_slots
                and cs_new.n_grad_slots == base_cs.n_grad_slots):
            return baseline.report  # identical table
        t0 = k
    else:
        t0 = int(diff[0])
    start = max(0, t0 - 1)
    interp = _TrainInterp(cs_new)
    interp.restore(baseline.snapshots[start])
    interp.repatch_reads(start)
    for t in range(start, interp.T):
        interp.run_tick(t)
    report = interp.finish(compression=False)
    if start > 0:
        # Prefix hazards carry over verbatim (rows < start are identical;
        # pairing hazards AT start were emitted by run_tick(start - 1))...
        prefix = [h for h in baseline.report.hazards
                  if 0 <= h.tick < start
                  or (h.tick == start and h.kind in _PAIRING_KINDS)]
        # ...except WAR liveness: the read schedule is derived from the
        # whole table, so a changed suffix can retroactively make a prefix
        # overwrite hit a still-live value. Claims below t0 are identical
        # (identical rows), so for the clean baseline a prefix write over
        # resident value P becomes overwrite-live iff P's *new* claim list
        # has reads >= t0.
        for (u, d, label, slot, column, prev_val, _pending) in \
                baseline.write_log:
            if u >= start or prev_val is None:
                continue
            reads = (interp.act_reads if label == "act_buf"
                     else interp.grad_reads)
            tail = [r for r in reads[d].get((prev_val[1], prev_val[2]), [])
                    if r >= t0]
            if tail:
                prefix.append(Hazard(
                    "overwrite-live", d, u, COLUMN_NAMES[column],
                    f"{label} slot {slot} overwritten while {prev_val} "
                    f"still has reads at ticks {tail}"))
        report.hazards[:0] = prefix
    return report


def check_forward_table(table: np.ndarray, n_devices: int, n_virtual: int,
                        n_microbatches: int, n_slots: int) -> TableReport:
    """Verify the 4-column forward-only table (``pipeline._fwd_tick_table``:
    columns (store_slot, fv, fm, src_slot), wrap placement, +1 ring only)."""
    table = np.asarray(table)
    T, D = table.shape[0], n_devices
    S, M = n_devices * n_virtual, n_microbatches
    hazards: List[Hazard] = []
    COLS = {0: "STORE_SLOT", 1: "FWD_V", 2: "FWD_M", 3: "SRC_SLOT"}

    # read schedule: value ("act", s, m) read at F(s, m)'s tick
    reads: Dict[int, Dict[Tuple[int, int], List[int]]] = \
        {d: {} for d in range(D)}
    for t in range(T):
        for d in range(D):
            if table[t, d, 2] >= 0 and table[t, d, 3] >= 0:
                s = int(table[t, d, 1]) * D + d
                reads[d].setdefault((s, int(table[t, d, 2])), []).append(t)

    bufs = [_SlotFile("act_buf", n_slots) for _ in range(D)]
    reg: List[Optional[Tuple]] = [None] * D
    fwd_done: Dict[Tuple[int, int], int] = {}
    for t in range(T):
        send: List[Optional[Tuple]] = [None] * D
        for d in range(D):
            store, fv, fm, src = (int(x) for x in table[t, d])
            written: Dict[int, int] = {}
            if store >= 0:
                if store >= n_slots:
                    hazards.append(Hazard(
                        "slot-out-of-bounds", d, t, COLS[0],
                        f"store slot {store} >= n_slots {n_slots}"))
                val = reg[d]
                if val is None:
                    hazards.append(Hazard(
                        "store-empty-register", d, t, COLS[0],
                        f"store into slot {store} with no arrival "
                        f"(dropped send at tick {t - 1})"))
                else:
                    bufs[d].write(store, val, t, d, COL_STORE_F_SLOT,
                                  reads[d].get((val[1], val[2]), []),
                                  hazards, written)
            if fm >= 0:
                s = fv * D + d
                if s > 0:
                    if src < 0:
                        hazards.append(Hazard(
                            "read-wrong-value", d, t, COLS[3],
                            f"F(stage={s}, mb={fm}) has no input slot"))
                    else:
                        bufs[d].read(src, ("act", s, fm), t, d,
                                     COL_FWD_SLOT,
                                     f"F(stage={s}, mb={fm})", hazards)
                if (s, fm) in fwd_done:
                    hazards.append(Hazard(
                        "duplicate-unit", d, t, COLS[2],
                        f"F(stage={s}, mb={fm}) already ran at tick "
                        f"{fwd_done[(s, fm)]}"))
                fwd_done[(s, fm)] = t
                if s + 1 < S:
                    send[d] = ("act", s + 1, fm)
        for d in range(D):
            if t == 0 and table[0, d, 0] >= 0:
                hazards.append(Hazard(
                    "recv-unpaired", d, 0, COLS[0],
                    "fwd store at tick 0 precedes any send"))
            dst = (d + 1) % D
            if send[d] is not None and (
                    t + 1 >= T or table[t + 1, dst, 0] < 0):
                hazards.append(Hazard(
                    "send-unpaired", dst, t + 1, COLS[0],
                    f"fwd send of {send[d]} from device {d} at tick {t} "
                    f"has no receiving store"))
            src_dev = (d - 1) % D
            if (t + 1 < T and table[t + 1, d, 0] >= 0
                    and send[src_dev] is None):
                hazards.append(Hazard(
                    "recv-unpaired", d, t + 1, COLS[0],
                    f"fwd store at tick {t + 1} has no matching send "
                    f"from device {src_dev} at tick {t}"))
        reg = [send[(d - 1) % D] for d in range(D)]

    want = {(s, m) for s in range(S) for m in range(M)}
    if set(fwd_done) != want:
        missing = sorted(want - set(fwd_done))[:4]
        hazards.append(Hazard(
            "unit-count", -1, -1, COLS[2],
            f"{len(fwd_done)} forward units != expected {len(want)} "
            f"(missing {missing})"))

    stores = table[:, :, 0] >= 0
    comm = {"fwd_ring_pos": {"cells": int(stores.sum()),
                             "hop_ticks": int(stores[1:].any(axis=1).sum())},
            "bwd_ring_neg": {"cells": 0, "hop_ticks": 0}}
    return TableReport(
        name="forward", kind="forward", n_devices=D, n_virtual=n_virtual,
        n_microbatches=M, placement="wrap", split_backward=False,
        makespan=T, hazards=hazards,
        act_slots_used=[b.max_slot + 1 for b in bufs],
        grad_slots_used=[0] * D,
        act_live_peak=[b.live_peak for b in bufs],
        grad_live_peak=[0] * D,
        n_act_slots=n_slots, n_grad_slots=0,
        comm=comm,
        unit_counts={"F": len(fwd_done), "B": 0, "W": 0,
                     "idle": int(T * D - len(fwd_done))},
        compression={})


def page_table_hazards(pages, *, refcount, n_pages: int, page_size: int,
                       write_lo: int, write_hi: int, cow_dst: int = -1,
                       slot: int = -1) -> List[Hazard]:
    """Discipline hazards for one slot's planned page-table row
    (ISSUE 19 satellite: the paged serving engine's admission-time
    check, also exercised synthetically by the CLI grid).

    ``pages`` is the allocated prefix of the row (table order: entry
    ``i`` backs positions ``[i*ps, (i+1)*ps)``); ``refcount`` the pool's
    per-page counts; ``[write_lo, write_hi)`` the position span the slot
    will write over its lifetime (cached-prefix end through the final
    chunk's junk tail). Rules:

    - every entry in-bounds and (non-null entries) refcount-live;
    - no duplicate non-null entries (aliased writes would corrupt);
    - the row covers the write span (rows past the last allocated page
      would scatter into the null page and read back garbage);
    - no write lands in a shared (refcount > 1) page — the divergence
      page must have been remapped to a private COW destination
      (``cow_dst``) before admission.
    """
    ps = page_size
    hazards: List[Hazard] = []
    seen: Dict[int, int] = {}
    for i, pg in enumerate(int(p) for p in pages):
        if pg < 0 or pg >= n_pages:
            hazards.append(Hazard(
                "page-oob", slot, i, "page_tbl",
                f"slot {slot} entry {i} -> page {pg} outside "
                f"[0, {n_pages})"))
            continue
        if pg == 0:
            continue  # null page: legal filler, never read as valid
        if refcount[pg] < 1:
            hazards.append(Hazard(
                "page-dead", slot, i, "page_tbl",
                f"slot {slot} entry {i} -> page {pg} is on the free list "
                f"(refcount {int(refcount[pg])})"))
        if pg in seen:
            hazards.append(Hazard(
                "page-dup", slot, i, "page_tbl",
                f"slot {slot} entries {seen[pg]} and {i} alias page {pg}"))
        seen[pg] = i
    if len(pages) * ps < write_hi:
        hazards.append(Hazard(
            "page-underalloc", slot, -1, "page_tbl",
            f"slot {slot}: {len(pages)} pages cover {len(pages) * ps} "
            f"rows < write frontier {write_hi}"))
    for i in range(write_lo // ps, min(-(-write_hi // ps), len(pages))):
        pg = int(pages[i])
        if 0 < pg < n_pages and refcount[pg] > 1 and pg != cow_dst:
            hazards.append(Hazard(
                "page-shared-write", slot, i, "page_tbl",
                f"slot {slot} writes positions in page {pg} "
                f"(refcount {int(refcount[pg])} > 1) without COW"))
    return hazards


def check_page_table(page_tbl, *, refcount, n_pages: int, page_size: int,
                     spans, cow_dst=None, n_devices: int = 1) -> TableReport:
    """Discipline report over a full ``[M, P_max]`` page table.

    ``spans`` is a per-slot list of ``(write_lo, write_hi)`` position
    spans (``(0, 0)`` for an idle slot — its row is skipped); ``cow_dst``
    an optional per-slot COW destination list. Returns a
    :class:`TableReport` (kind ``"serving"``) the CLI renders next to
    the ring checks."""
    hazards: List[Hazard] = []
    M = len(page_tbl)
    for slot in range(M):
        lo, hi = spans[slot]
        if hi <= 0:
            continue
        row = [int(p) for p in page_tbl[slot]]
        while row and row[-1] == 0:
            row.pop()  # trailing null filler is not an allocation
        hazards.extend(page_table_hazards(
            row, refcount=refcount, n_pages=n_pages, page_size=page_size,
            write_lo=lo, write_hi=hi,
            cow_dst=(cow_dst[slot] if cow_dst is not None else -1),
            slot=slot))
    return TableReport(
        name="serving_paging", kind="serving", n_devices=n_devices,
        n_virtual=1, n_microbatches=M, placement="wrap",
        split_backward=False, makespan=M, hazards=hazards,
        act_slots_used=[M] * n_devices, grad_slots_used=[0] * n_devices,
        act_live_peak=[M] * n_devices, grad_live_peak=[0] * n_devices,
        n_act_slots=M, n_grad_slots=0,
        comm={"fwd_ring_pos": {"cells": M * n_devices,
                               "hop_ticks": M}},
        unit_counts={"F": M * n_devices, "B": 0, "W": 0, "idle": 0},
        compression={})


def speculative_hazards(*, gamma: int, prefill_chunk: int,
                        slots=()) -> List[Hazard]:
    """Discipline hazards for the widened speculative metadata columns
    (ISSUE 20 satellite). Static rules first:

    - ``1 <= gamma`` and ``gamma + 1 <= prefill_chunk`` — the verify
      forward reuses the chunked-prefill channel width ``C``, and the
      rollback-by-overwrite discipline needs the next ``C``-wide write to
      cover every speculative overshoot row (``spec-gamma-oob``);

    then per-slot rules over ``slots``, an optional iterable of dicts
    with ``pos`` (committed token frontier), ``n_accepted`` (the value
    banked from the tok channel), ``committed`` (the paged allocator's
    committed-frontier ledger entry) and ``mapped_rows`` (rows covered by
    the slot's page row; ``None`` for contiguous slots):

    - ``n_accepted`` outside ``[1, gamma + 1]`` would advance the slot
      past the verify chunk or stall it forever (``spec-accept-oob``);
    - a committed frontier ahead of ``pos`` means draft overshoot leaked
      into the radix trie / COW pool (``spec-commit-overrun``);
    - a verify chunk whose junk tail extends past the mapped page span
      would scatter draft writes into unmapped rows
      (``spec-draft-overrun``).
    """
    hazards: List[Hazard] = []
    if gamma < 1:
        hazards.append(Hazard(
            "spec-gamma-oob", -1, -1, "gamma",
            f"gamma={gamma} < 1: speculative program proposes no tokens"))
    if gamma + 1 > prefill_chunk:
        hazards.append(Hazard(
            "spec-gamma-oob", -1, -1, "gamma",
            f"gamma+1={gamma + 1} > prefill_chunk={prefill_chunk}: the "
            f"verify chunk does not fit the channel width, so rejected "
            f"rows would never be overwritten by the next write"))
    for s, row in enumerate(slots or ()):
        slot = int(row.get("slot", s))
        n_acc = row.get("n_accepted")
        if n_acc is not None and not (1 <= int(n_acc) <= gamma + 1):
            hazards.append(Hazard(
                "spec-accept-oob", slot, -1, "tok_chan",
                f"slot {slot} banked n_accepted={int(n_acc)} outside "
                f"[1, {gamma + 1}]"))
        pos = row.get("pos")
        committed = row.get("committed")
        if pos is not None and committed is not None \
                and int(committed) > int(pos):
            hazards.append(Hazard(
                "spec-commit-overrun", slot, -1, "page_tbl",
                f"slot {slot} committed frontier {int(committed)} > "
                f"accepted position {int(pos)}: speculative overshoot "
                f"leaked into committed pages"))
        mapped = row.get("mapped_rows")
        if pos is not None and mapped is not None \
                and int(pos) + prefill_chunk > int(mapped):
            hazards.append(Hazard(
                "spec-draft-overrun", slot, -1, "page_tbl",
                f"slot {slot} verify chunk [{int(pos)}, "
                f"{int(pos) + prefill_chunk}) extends past mapped rows "
                f"{int(mapped)}"))
    return hazards


def check_serving_ring(n_devices: int, n_slots: int,
                       paging=None, speculative=None) -> TableReport:
    """Verify the serving executor's implicit round-robin slot schedule.

    ``serving.engine`` has no tick table: at tick ``u`` device ``d`` serves
    slot ``(u - d) % M`` and the scheduler banks last-stage output into
    slot ``(u - D) % M``. The static invariants that make the +1 metadata
    ring correct are checked over one full period:

    - ``M >= D`` (a slot's state must clear the pipe before it returns);
    - pipeline alignment: device ``d`` at tick ``u`` serves what device
      ``d-1`` served at ``u-1`` (state arrives via one +1 ppermute hop);
    - bank alignment: the banked slot at ``u`` is the slot device ``D-1``
      served at ``u-1``;
    - per device, each period serves every slot exactly once (permutation).

    ``paging`` (optional) additionally runs the page-table discipline
    check: a dict with ``page_tbl``, ``refcount``, ``n_pages``,
    ``page_size``, ``spans`` and optional ``cow_dst`` as accepted by
    :func:`check_page_table`; its hazards are merged into this report.

    ``speculative`` (optional) runs the widened-metadata discipline
    check for draft-verify programs: a dict with ``gamma``,
    ``prefill_chunk`` and optional ``slots`` as accepted by
    :func:`speculative_hazards`; its hazards are merged too.
    """
    D, M = n_devices, n_slots
    hazards: List[Hazard] = []
    if speculative is not None:
        hazards.extend(speculative_hazards(
            gamma=speculative["gamma"],
            prefill_chunk=speculative["prefill_chunk"],
            slots=speculative.get("slots", ())))
    if paging is not None:
        hazards.extend(check_page_table(
            paging["page_tbl"], refcount=paging["refcount"],
            n_pages=paging["n_pages"], page_size=paging["page_size"],
            spans=paging["spans"], cow_dst=paging.get("cow_dst"),
            n_devices=D).hazards)
    if M < D:
        hazards.append(Hazard(
            "ring-underfull", -1, -1, "n_slots",
            f"n_slots={M} < pipe degree {D}: a slot would be re-admitted "
            f"while its previous request is still in flight"))
    else:
        for u in range(M):
            for d in range(1, D):
                if (u - d) % M != ((u - 1) - (d - 1)) % M:
                    hazards.append(Hazard(
                        "ring-misaligned", d, u, "serve_slot",
                        f"device {d} at tick {u} does not serve device "
                        f"{d - 1}'s tick-{u - 1} slot"))
            if (u - D) % M != ((u - 1) - (D - 1)) % M:
                hazards.append(Hazard(
                    "ring-misaligned", D - 1, u, "bank_slot",
                    f"banked slot at tick {u} is not the last stage's "
                    f"tick-{u - 1} output"))
        for d in range(D):
            served = {(u - d) % M for u in range(M)}
            if served != set(range(M)):
                hazards.append(Hazard(
                    "ring-incomplete", d, -1, "serve_slot",
                    f"device {d} serves {sorted(served)} per period, not "
                    f"all {M} slots"))
    return TableReport(
        name="serving", kind="serving", n_devices=D, n_virtual=1,
        n_microbatches=M, placement="wrap", split_backward=False,
        makespan=M, hazards=hazards,
        act_slots_used=[M] * D, grad_slots_used=[0] * D,
        act_live_peak=[M] * D, grad_live_peak=[0] * D,
        n_act_slots=M, n_grad_slots=0,
        comm={"fwd_ring_pos": {"cells": M * D, "hop_ticks": M}},
        unit_counts={"F": M * D, "B": 0, "W": 0, "idle": 0},
        compression={})


def static_analysis_section(reports: List[TableReport],
                            verifier_version: int) -> Dict[str, object]:
    """Assemble the ``RunReport`` manifest's ``static_analysis`` block
    (see ``utils.telemetry.validate_report``) from verified tables."""
    def label(r: TableReport) -> str:
        return (f"{r.name}[D={r.n_devices},V={r.n_virtual},"
                f"M={r.n_microbatches},{r.placement}]")

    return {
        "verifier_version": verifier_version,
        "schedules": [label(r) for r in reports],
        "hazards": sum(len(r.hazards) for r in reports),
        "slot_high_water": {
            label(r): {"act": max(r.act_slots_used, default=0),
                       "grad": max(r.grad_slots_used, default=0)}
            for r in reports},
    }
