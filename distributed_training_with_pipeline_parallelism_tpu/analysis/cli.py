"""CLI driver for the static-analysis passes (also ``scripts/check.py``).

``--tables`` verifies every registered schedule over a config grid plus
the forward-only table and the serving ring; ``--lint`` runs the repo
lint; ``--jaxpr`` traces small train/serving step functions on a
simulated mesh and audits them (needs a jax backend — the script wrapper
sets up 8 fake CPU devices before any jax import); ``--memory`` prices
per-device HBM over the same grid and pins the analytic-bytes identity
(docs/observability.md "Memory observatory"); ``--overlap`` prices the
grid in the cost model's ``comm_overlap`` mode and pins the overlap
sandwich + two-buffer hop census (docs/performance.md "Comm/compute
overlap"); ``--all`` is every pass.
Exit code 0 iff every requested pass is clean. ``--json PATH`` writes
the full structured report (the CI artifact).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple

from . import VERIFIER_VERSION

GridEntry = Tuple[str, int, int, int]  # (schedule, D, V, M)


def default_grid() -> List[GridEntry]:
    """One grid entry per registered schedule x device count x virtual
    depth, with microbatch counts satisfying each schedule's constraints
    (1F1B/ZBH1: M >= D; ZBV: M >= 2D; Interleaved: divisibility)."""
    from ..parallel.schedules import schedule_names
    grid: List[GridEntry] = []
    for name in schedule_names():
        if name == "ZBV":
            v_options: Tuple[int, ...] = (2,)
        elif name in ("Interleaved1F1B", "BFS"):
            v_options = (1, 2)
        else:
            v_options = (1,)
        for D in (2, 4):
            for V in v_options:
                for M in sorted({D, 2 * D, 8}):
                    if name == "ZBV" and M < 2 * D:
                        continue
                    if name in ("1F1B", "ZBH1", "Interleaved1F1B") \
                            and M < D:
                        continue
                    if name == "Interleaved1F1B" and V > 1:
                        rounds = max(1, M // D)
                        if M % rounds != 0:
                            continue
                    grid.append((name, D, V, M))
    return grid


def run_table_checks(grid: Optional[List[GridEntry]] = None
                     ) -> Dict[str, Any]:
    from ..parallel.pipeline import _fwd_tick_table
    from ..parallel.schedules import ScheduleError, compile_schedule
    from .table_check import (check_forward_table, check_serving_ring,
                              check_table)
    reports: List[Dict[str, Any]] = []
    n_hazards = 0
    for name, D, V, M in (grid if grid is not None else default_grid()):
        try:
            cs = compile_schedule(name, D, V, M)
        except ScheduleError as e:
            reports.append({"name": name, "n_devices": D, "n_virtual": V,
                            "n_microbatches": M, "ok": False,
                            "n_hazards": 1,
                            "hazards": [f"compile failed: {e}"]})
            n_hazards += 1
            continue
        reports.append(check_table(cs).summary())
        n_hazards += reports[-1]["n_hazards"]
    for D, V, M in ((2, 1, 4), (4, 1, 8), (2, 2, 4)):
        table, n_slots = _fwd_tick_table(D, V, M)
        reports.append(check_forward_table(table, D, V, M,
                                           n_slots).summary())
        n_hazards += reports[-1]["n_hazards"]
    for D, M in ((2, 2), (4, 4), (4, 6)):
        reports.append(check_serving_ring(D, M).summary())
        n_hazards += reports[-1]["n_hazards"]
    # ISSUE 19: page-table discipline over a synthetic paged ring — a
    # 4-slot pool where slots 0/1 share a refcount-2 prefix page
    # (read-only: their write spans start past it) and slots 2/3 hold
    # private rows. Trailing zeros are null-page filler. The grid must
    # come back hazard-free; the negative cases live in the unit tests.
    paging = {
        "page_size": 4, "n_pages": 16,
        "page_tbl": [[1, 2, 3, 0], [1, 4, 5, 0],
                     [6, 7, 8, 0], [9, 10, 11, 0]],
        "refcount": [1, 2, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 0, 0, 0, 0],
        "spans": [(4, 12), (4, 12), (0, 12), (0, 12)],
        "cow_dst": [-1, -1, -1, -1],
    }
    reports.append(check_serving_ring(2, 4, paging=paging).summary())
    n_hazards += reports[-1]["n_hazards"]
    # ISSUE 20: speculative widened-metadata discipline over a synthetic
    # draft-verify ring — gamma=2 inside a prefill_chunk=3 channel, two
    # slots mid-verify with in-range accepted lengths, committed
    # frontiers at/behind the accepted position, and page rows covering
    # the verify chunk's junk tail. Hazard-free by construction; the
    # negative cases (accept OOB, commit overrun, draft overrun) live in
    # the unit tests.
    speculative = {
        "gamma": 2, "prefill_chunk": 3,
        "slots": [
            {"slot": 0, "n_accepted": 3, "pos": 9, "committed": 8,
             "mapped_rows": 16},
            {"slot": 1, "n_accepted": 1, "pos": 5, "committed": 5,
             "mapped_rows": 12},
        ],
    }
    reports.append(check_serving_ring(2, 4,
                                      speculative=speculative).summary())
    n_hazards += reports[-1]["n_hazards"]
    return {"n_checked": len(reports), "n_hazards": n_hazards,
            "ok": n_hazards == 0, "reports": reports}


def run_memory_checks(grid: Optional[List[GridEntry]] = None
                      ) -> Dict[str, Any]:
    """The ``--memory`` pass: over the same schedule grid the table
    verifier walks, build :func:`.memory_model.memory_model_section` and
    assert the integer identity — per-device analytic activation/grad
    bytes equal the verifier's slot live peaks times one slot's slab
    bytes, exactly. Host-side only (``jax.eval_shape``): no backend, no
    compiles."""
    from ..parallel.schedules import ScheduleError, compile_schedule
    from ..utils.config import ModelConfig
    from .memory_model import memory_model_section
    from .table_check import check_table

    cfg = ModelConfig(dim=32, n_layers=4, n_heads=4, vocab_size=64,
                      ffn_dim=64, max_seq_len=16)
    batch, seq = 8, 16
    reports: List[Dict[str, Any]] = []
    n_bad = 0
    for name, D, V, M in (grid if grid is not None else default_grid()):
        row: Dict[str, Any] = {"name": name, "n_devices": D, "n_virtual": V,
                               "n_microbatches": M}
        try:
            cs = compile_schedule(name, D, V, M)
        except ScheduleError as e:
            row.update(ok=False, error=f"compile failed: {e}")
            reports.append(row)
            n_bad += 1
            continue
        tr = check_table(cs)
        sec = memory_model_section(cs, cfg, batch_size=batch,
                                   seq_length=seq, table_report=tr)
        slot_b = sec["analytic"]["act_slot_bytes"]
        exact = all(
            pd["act_bytes"] == tr.act_live_peak[pd["device"]] * slot_b
            and pd["grad_bytes"] == tr.grad_live_peak[pd["device"]] * slot_b
            for pd in sec["analytic"]["per_device"])
        row.update(ok=bool(exact),
                   act_slot_bytes=slot_b,
                   backward_policy=sec["backward_policy"],
                   peak_bytes=sec["analytic"]["peak_bytes"],
                   per_device=sec["analytic"]["per_device"])
        if not exact:
            row["error"] = "analytic bytes != live_peak x slot_bytes"
            n_bad += 1
        reports.append(row)
    # the remaining rows of the table pass's 44-entry grid: forward-only
    # tables and the serving ring carry live peaks too — price them with
    # the same identity (one [mb, seq, dim] / [1, C, dim] slab per slot)
    from ..parallel.pipeline import _fwd_tick_table
    from .memory_model import activation_slot_bytes
    from .table_check import check_forward_table, check_serving_ring
    for D, V, M in ((2, 1, 4), (4, 1, 8), (2, 2, 4)):
        table, n_slots = _fwd_tick_table(D, V, M)
        tr = check_forward_table(table, D, V, M, n_slots)
        slot_b = activation_slot_bytes(cfg, batch, seq, M)
        per_device = [{"device": d, "act_live_peak": int(p),
                       "grad_live_peak": 0,
                       "act_bytes": int(p) * slot_b, "grad_bytes": 0}
                      for d, p in enumerate(tr.act_live_peak)]
        reports.append({"name": "forward", "n_devices": D,
                        "n_virtual": V, "n_microbatches": M, "ok": True,
                        "act_slot_bytes": slot_b,
                        "backward_policy": "none",
                        "peak_bytes": float(max(pd["act_bytes"]
                                                for pd in per_device)),
                        "per_device": per_device})
    from .cost_model import dtype_bytes
    for D, M in ((2, 2), (4, 4), (4, 6)):
        tr = check_serving_ring(D, M)
        slot_b = cfg.dim * dtype_bytes(cfg.dtype)  # one decode token/slot
        per_device = [{"device": d, "act_live_peak": int(p),
                       "grad_live_peak": 0,
                       "act_bytes": int(p) * slot_b, "grad_bytes": 0}
                      for d, p in enumerate(tr.act_live_peak)]
        reports.append({"name": "serving_ring", "n_devices": D,
                        "n_virtual": 1, "n_microbatches": M, "ok": True,
                        "act_slot_bytes": slot_b,
                        "backward_policy": "none",
                        "peak_bytes": float(max(pd["act_bytes"]
                                                for pd in per_device)),
                        "per_device": per_device})
    return {"n_checked": len(reports), "n_bad": n_bad, "ok": n_bad == 0,
            "batch_size": batch, "seq_length": seq, "reports": reports}


def run_overlap_checks(grid: Optional[List[GridEntry]] = None
                       ) -> Dict[str, Any]:
    """The ``--overlap`` pass: over the full schedule grid, price every
    table in the cost model's ``comm_overlap`` mode and pin the overlap
    contract (pure numpy — no jax backend):

    - ``step_s_comm_overlap <= step_s`` for every entry (hiding hops can
      never slow the predicted step down);
    - ``step_s_overlapped <= step_s_comm_overlap`` summed over the grid's
      real tables (the optimistic launch-tick bound stays below the
      bank-tick priced mode — the two attributions can differ tick by
      tick, so this is pinned per entry here where it holds for every
      registered schedule);
    - the verifier's exposed + overlappable hop census equals
      ``predicted_ppermutes`` (every hop is classified exactly once);
    - the overlap discipline itself is hazard-free (``check_table``'s
      two-buffer extension).
    """
    from ..parallel.schedules import ScheduleError, compile_schedule
    from .cost_model import comm_overlap_step_time, predicted_step_time
    from .table_check import check_table

    unit_s, hop_s = (1.0, 2.0, 1.0), 0.25
    reports: List[Dict[str, Any]] = []
    n_bad = 0
    for name, D, V, M in (grid if grid is not None else default_grid()):
        row: Dict[str, Any] = {"name": name, "n_devices": D, "n_virtual": V,
                               "n_microbatches": M}
        try:
            cs = compile_schedule(name, D, V, M)
        except ScheduleError as e:
            row.update(ok=False, error=f"compile failed: {e}")
            reports.append(row)
            n_bad += 1
            continue
        tr = check_table(cs)
        base = predicted_step_time(cs.table, unit_s, hop_s,
                                   tr.predicted_ppermutes)
        ov = comm_overlap_step_time(cs.table, unit_s, hop_s)
        census = sum(v["exposed_hop_ticks"] + v["overlappable_hop_ticks"]
                     for k, v in tr.overlap.items()
                     if k in tr.comm and tr.comm[k]["hop_ticks"] > 0)
        problems: List[str] = []
        if ov["step_s_comm_overlap"] > base["step_s"] + 1e-9:
            problems.append(
                f"comm_overlap {ov['step_s_comm_overlap']:.3f} > lockstep "
                f"step_s {base['step_s']:.3f}")
        if base["step_s_overlapped"] > ov["step_s_comm_overlap"] + 1e-9:
            problems.append(
                f"optimistic bound {base['step_s_overlapped']:.3f} > "
                f"comm_overlap {ov['step_s_comm_overlap']:.3f}")
        if census != tr.predicted_ppermutes:
            problems.append(f"overlap census {census} != predicted "
                            f"ppermutes {tr.predicted_ppermutes}")
        stage_hazards = [str(h) for h in tr.hazards
                         if h.kind.startswith("overlap-")]
        if stage_hazards:
            problems.extend(stage_hazards)
        row.update(ok=not problems,
                   step_s=base["step_s"],
                   step_s_overlapped=base["step_s_overlapped"],
                   step_s_comm_overlap=ov["step_s_comm_overlap"],
                   exposed_hops=ov["exposed_hops"],
                   overlappable_hops=ov["overlappable_hops"],
                   problems=problems)
        if problems:
            n_bad += 1
        reports.append(row)
    return {"n_checked": len(reports), "n_bad": n_bad, "ok": n_bad == 0,
            "unit_s": list(unit_s), "hop_s": hop_s, "reports": reports}


def run_lint() -> Dict[str, Any]:
    from .repo_lint import findings_summary, lint_repo
    findings = lint_repo()
    out = findings_summary(findings)
    out["ok"] = not findings
    return out


def run_jaxpr_audits() -> Dict[str, Any]:
    """Trace small step functions (nothing executes) and audit them: zero
    callbacks with telemetry off, collective axes declared on the mesh,
    and — for the unrolled tick executor — traced ppermute hops equal to
    the table verifier's predicted comm volume."""
    import jax
    import jax.numpy as jnp

    from ..models import transformer as tfm
    from ..parallel.mesh import make_mesh
    from ..parallel.pipeline import _compile, make_pipeline_step
    from ..utils.config import ModelConfig, ScheduleConfig
    from .jaxpr_audit import audit_fn
    from .table_check import check_table

    # 8 layers: divisible by 4 stages (V=1) and 8 stages (V=2 interleave)
    cfg = ModelConfig(dim=16, n_layers=8, n_heads=2, vocab_size=32,
                      ffn_dim=32, max_seq_len=8)
    mesh = make_mesh(n_pipe=4)
    params = tfm.transformer_init(jax.random.key(0), cfg)
    tokens = jnp.zeros((4, 8), jnp.int32)
    targets = jnp.zeros((4, 8), jnp.int32)
    out: Dict[str, Any] = {"cases": [], "ok": True}
    for name, V, M in (("GPipe", 1, 4), ("1F1B", 1, 4),
                       ("Interleaved1F1B", 2, 4)):
        sched = ScheduleConfig(name=name, n_microbatches=M, n_virtual=V)
        predicted = check_table(_compile(name, 4, V, M)).predicted_ppermutes
        # lockstep AND double-buffered executors: deferred banking moves
        # the store commit point, never the hop — both trace the table's
        # predicted comm volume exactly
        for comm_overlap in ("none", "ring"):
            step = make_pipeline_step(cfg, mesh, sched, unroll_ticks=True,
                                      comm_overlap=comm_overlap)
            audit = audit_fn(step, params, tokens, targets,
                             mesh_axes=tuple(mesh.axis_names),
                             expect_no_callbacks=True,
                             expected_ppermutes=predicted)
            case = {"case": f"train/{name}[D=4,V={V},M={M},"
                            f"overlap={comm_overlap}]",
                    "predicted_ppermutes": predicted, **audit.summary()}
            out["cases"].append(case)
            out["ok"] = out["ok"] and audit.ok
    # collective-matmul census: the ring TP forward traces exactly
    # (T-1) ppermutes per ring gather/scatter (no bare all_gather)
    import dataclasses as _dc

    import numpy as np
    from jax.sharding import Mesh as _Mesh, PartitionSpec as _P

    from ..models.transformer import layer_init, mlp_block
    from .jaxpr_audit import collective_matmul_ppermutes
    try:
        from jax.shard_map import shard_map as _shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map as _shard_map
    T = 4
    tp_mesh = _Mesh(np.array(jax.devices()[:T]), ("model",))
    tp_cfg = _dc.replace(cfg, arch="gpt2", tp_overlap="ring")
    lp = layer_init(jax.random.key(1), tp_cfg)
    mlp_specs = {"lin1": {"w": _P(None, "model"), "b": _P("model")},
                 "lin2": {"w": _P("model", None), "b": _P(None)}}
    specs = {k: mlp_specs.get(k, jax.tree.map(lambda _: _P(), lp[k]))
             for k in lp}
    ring_fwd = _shard_map(
        lambda p, x: mlp_block(tp_cfg, p, x, tp_axis="model", tp_size=T),
        mesh=tp_mesh, in_specs=(specs, _P()), out_specs=_P(),
        check_rep=False)
    # gpt2 ring MLP: all_gather_matmul + matmul_reduce_scatter +
    # seq_all_gather = 3 ring collectives
    expected_tp = collective_matmul_ppermutes(T, n_gathers=2, n_scatters=1)
    audit = audit_fn(ring_fwd, lp,
                     jnp.zeros((2, 8, tp_cfg.dim), jnp.float32),
                     mesh_axes=("model",), expect_no_callbacks=True,
                     expected_ppermutes=expected_tp)
    out["cases"].append({"case": f"tp_ring_mlp[T={T},gpt2]",
                         "predicted_ppermutes": expected_tp,
                         **audit.summary()})
    out["ok"] = out["ok"] and audit.ok
    # serving block: telemetry-free by construction; audit callbacks + axes
    from ..serving.engine import make_serving_step_fn
    serve_cfg = ModelConfig(dim=16, n_layers=8, n_heads=2, vocab_size=32,
                            ffn_dim=32, max_seq_len=16, arch="gpt2")
    serve_params = tfm.transformer_init(jax.random.key(0), serve_cfg)
    program = make_serving_step_fn(serve_cfg, mesh, n_slots=4, max_len=8,
                                   prompt_max=4, out_max=4)
    stacked, embed, head = program.prepare(serve_params)
    state = program.init_state()
    audit = audit_fn(program.step, stacked, embed, head, state,
                     mesh_axes=tuple(mesh.axis_names),
                     expect_no_callbacks=True)
    out["cases"].append({"case": "serving[D=4,n_slots=4]",
                         **audit.summary()})
    out["ok"] = out["ok"] and audit.ok
    return out


def run_search(out_path: Optional[str] = None, *, seed: int = 0,
               iterations: int = 300) -> Dict[str, Any]:
    """The ``--search`` pass: run the certifying schedule compiler on two
    small shapes (pure numpy — no jax backend needed), assert every
    winner is certified and beats or ties 1F1B's table-exact bubble
    fraction, and optionally save the first winner's artifact JSON."""
    from ..parallel.schedules import save_schedule_artifact
    from .schedule_search import SearchSpec, search_schedule

    # Case 1: split-backward greedy seeds — must strictly beat 1F1B's
    # bubble at D=4 (the acceptance bar). Case 2: full-backward search
    # from the builtin seeds — 1F1B is in the pool, so the winner ties
    # it at worst (split cannot beat 1F1B's idle fraction at D=2: the
    # elided stage-0 dgrad leaves the first device structurally idle).
    specs = [
        SearchSpec(n_devices=4, n_microbatches=8, seed=seed,
                   iterations=iterations),
        SearchSpec(n_devices=2, n_microbatches=4, split_backward=False,
                   seed=seed, iterations=iterations),
    ]
    out: Dict[str, Any] = {"cases": [], "ok": True}
    for i, spec in enumerate(specs):
        res = search_schedule(spec)
        beats = res.beats_1f1b
        case = {
            "case": f"search[D={spec.n_devices},V={spec.n_virtual},"
                    f"M={spec.n_microbatches},seed={spec.seed}]",
            "certified": res.report.ok,
            "bubble_table_exact": res.predicted["bubble_table_exact"],
            "bubble_1f1b": res.baselines.get("1F1B", {}).get(
                "bubble_table_exact"),
            "beats_or_ties_1f1b": beats,
            "makespan": res.predicted["makespan"],
            "winning_seed": res.stats["winning_seed"],
            "evaluated": res.stats["evaluated"],
        }
        case_ok = bool(res.report.ok) and beats is not False
        out["cases"].append(case)
        out["ok"] = out["ok"] and case_ok
        if i == 0 and out_path:
            save_schedule_artifact(res.artifact, out_path)
            case["artifact"] = out_path
    return out


def run_calibration_checks() -> Dict[str, Any]:
    """The ``--calibration`` pass: host-side structural checks over the
    calibration observatory (pure numpy — no backend, no measured
    probes; the measured leg is ``scripts/probe.py``):

    - the probe grid is seeded-deterministic and spans the contract
      (>= 8 configs, >= 3 schedule families, all three backward
      policies, both comm_overlap modes);
    - the least-squares correction fit recovers known synthetic
      efficiencies to float64 accuracy;
    - the correction artifact byte-roundtrips and rejects tampering;
    - a corrected ``cost_model_section`` preserves the overlap sandwich
      (overlapped <= comm_overlap <= serial) — positive de-rating can
      reorder nothing;
    - malformed ledger rows are rejected with located errors.
    """
    from ..parallel.schedules import compile_schedule
    from ..utils.config import ModelConfig
    from . import calibration as cal
    from .cost_model import cost_model_section

    cases: List[Dict[str, Any]] = []

    def case(name: str, ok: bool, **extra: Any) -> None:
        cases.append({"case": name, "ok": bool(ok), **extra})

    g0, g1 = cal.probe_grid(seed=0), cal.probe_grid(seed=0)
    case("grid_deterministic", g0 == g1)
    families = {cal.schedule_family(s.schedule) for s in g0}
    policies = {cal._policy_of(s.schedule, s.remat_backward, s.n_devices)
                for s in g0}
    overlaps = {s.comm_overlap for s in g0}
    case("grid_coverage",
         len(g0) >= 8 and len(families) >= 3
         and policies == {"stored", "remat", "split"}
         and overlaps == {"none", "ring"},
         n_configs=len(g0), families=sorted(families),
         policies=sorted(policies), overlaps=sorted(overlaps))

    # synthetic fit: measured = compute/e_f + comm/e_b must be recovered
    e_f, e_b = 0.02, 0.5
    rows = []
    for i, (c, k) in enumerate(((1e-3, 1e-4), (2e-3, 5e-4), (3e-3, 2e-4),
                                (5e-3, 8e-4))):
        rows.append({
            "schema_version": cal.CALIBRATION_SCHEMA_VERSION,
            "kind": cal.LEDGER_KIND, "source": "synthetic", "t": 0.0,
            "name": f"syn{i}", "backend": "cpu", "hardware": "syn_hw",
            "cpu_proxy": True, "schedule": "GPipe",
            "schedule_family": "GPipe", "backward_policy": "remat",
            "comm_overlap": "none", "n_devices": 2, "n_virtual": 1,
            "n_microbatches": 4, "batch_size": 8, "seq_length": 16,
            "predicted": {"compute_s": c, "comm_s": k,
                          "step_s": c + k},
            "measured": {"step_s": c / e_f + k / e_b},
            "rel_err": None, "corrected": None,
        })
    fit = cal.fit_correction(rows, "syn_hw")
    case("fit_recovers_synthetic",
         fit is not None
         and abs(fit.flops_efficiency - e_f) < 1e-9
         and abs(fit.bandwidth_efficiency - e_b) < 1e-9,
         fitted=None if fit is None else fit.summary())

    art = cal.correction_artifact({"syn_hw": fit})
    loaded = cal.load_correction_artifact(art)
    rebuilt = cal.correction_artifact_bytes(cal.correction_artifact(loaded))
    roundtrip_ok = rebuilt == cal.correction_artifact_bytes(art)
    tampered = dict(art)
    tampered["corrections"] = dict(art["corrections"],
                                   syn_hw=dict(art["corrections"]["syn_hw"],
                                               flops_efficiency=1.0))
    try:
        cal.load_correction_artifact(tampered)
        tamper_ok = False
    except cal.CalibrationError:
        tamper_ok = True
    case("artifact_roundtrip_and_tamper", roundtrip_ok and tamper_ok)

    # corrected sandwich over a real table: de-rating by positive scalars
    # must preserve overlapped <= comm_overlap <= serial
    cfg = ModelConfig(dim=16, n_layers=4, n_heads=2, vocab_size=64,
                      ffn_dim=32, max_seq_len=16)
    sandwich_ok, checked = True, []
    for name, D, V, M in (("GPipe", 2, 1, 4), ("1F1B", 4, 1, 8),
                          ("ZBH1", 4, 1, 8)):
        cs = compile_schedule(name, D, V, M)
        sec = cost_model_section(cs, cfg, batch_size=8, seq_length=16,
                                 correction=fit)
        corr = sec["predicted"]["corrected"]
        ok = (corr["step_s_overlapped"]
              <= corr["step_s_comm_overlap"] + 1e-12
              <= corr["step_s"] + 1e-12)
        sandwich_ok = sandwich_ok and ok
        checked.append({"schedule": name, "ok": ok,
                        "corrected_step_s": corr["step_s"]})
    case("corrected_sandwich", sandwich_ok, entries=checked)

    bad_rejected = 0
    for bad in ({}, {"schema_version": 99}, dict(rows[0], kind="wrong"),
                dict(rows[0], predicted={"no_step": 1.0})):
        try:
            cal.validate_ledger_row(bad)
        except cal.CalibrationError:
            bad_rejected += 1
    case("malformed_rows_rejected", bad_rejected == 4,
         n_rejected=bad_rejected)

    return {"cases": cases, "n_checked": len(cases),
            "n_bad": sum(1 for c in cases if not c["ok"]),
            "ok": all(c["ok"] for c in cases)}


def run_checks(tables: bool = True, lint: bool = True,
               jaxpr: bool = False, search: bool = False,
               search_out: Optional[str] = None,
               memory: bool = False, overlap: bool = False,
               calibration: bool = False) -> Dict[str, Any]:
    report: Dict[str, Any] = {"verifier_version": VERIFIER_VERSION}
    ok = True
    if tables:
        report["tables"] = run_table_checks()
        ok = ok and report["tables"]["ok"]
    if memory:
        report["memory"] = run_memory_checks()
        ok = ok and report["memory"]["ok"]
    if overlap:
        report["overlap"] = run_overlap_checks()
        ok = ok and report["overlap"]["ok"]
    if calibration:
        report["calibration"] = run_calibration_checks()
        ok = ok and report["calibration"]["ok"]
    if lint:
        report["lint"] = run_lint()
        ok = ok and report["lint"]["ok"]
    if jaxpr:
        report["jaxpr"] = run_jaxpr_audits()
        ok = ok and report["jaxpr"]["ok"]
    if search:
        report["search"] = run_search(search_out)
        ok = ok and report["search"]["ok"]
    report["ok"] = ok
    return report


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m distributed_training_with_pipeline_parallelism_tpu"
             ".analysis",
        description="Static analysis: table verifier, repo lint, jaxpr "
                    "audit (docs/static_analysis.md)")
    ap.add_argument("--tables", action="store_true",
                    help="verify every registered schedule's tick table "
                         "over the config grid")
    ap.add_argument("--lint", action="store_true", help="run the repo lint")
    ap.add_argument("--jaxpr", action="store_true",
                    help="trace + audit step functions (needs a jax "
                         "backend with >= 4 pipe devices)")
    ap.add_argument("--search", action="store_true",
                    help="run the certifying schedule compiler on small "
                         "shapes and assert the winners are certified and "
                         "beat/tie 1F1B's table-exact bubble")
    ap.add_argument("--search-out", metavar="PATH",
                    help="with --search: save the first winner's schedule "
                         "artifact JSON to PATH")
    ap.add_argument("--memory", action="store_true",
                    help="price per-device HBM over the schedule grid and "
                         "pin analytic bytes == slot live peaks x slot "
                         "bytes (host-side, no backend)")
    ap.add_argument("--overlap", action="store_true",
                    help="price the schedule grid in comm_overlap mode and "
                         "pin step_s_overlapped <= step_s_comm_overlap <= "
                         "step_s plus the two-buffer hop census (host-side, "
                         "no backend)")
    ap.add_argument("--calibration", action="store_true",
                    help="structural checks over the calibration "
                         "observatory: probe-grid determinism/coverage, "
                         "synthetic least-squares recovery, correction-"
                         "artifact roundtrip + tamper rejection, corrected "
                         "sandwich, malformed-ledger-row rejection "
                         "(host-side, no backend)")
    ap.add_argument("--all", action="store_true", help="all three passes")
    ap.add_argument("--json", metavar="PATH",
                    help="write the structured report to PATH")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the per-pass console summary")
    args = ap.parse_args(argv)

    tables = args.tables or args.all
    lint = args.lint or args.all
    jaxpr = args.jaxpr or args.all
    search = args.search or args.all
    memory = args.memory or args.all
    overlap = args.overlap or args.all
    calibration = args.calibration or args.all
    if not (tables or lint or jaxpr or search or memory or overlap
            or calibration):
        tables = lint = True  # cheap default: no jax import needed

    report = run_checks(tables=tables, lint=lint, jaxpr=jaxpr,
                        search=search, search_out=args.search_out,
                        memory=memory, overlap=overlap,
                        calibration=calibration)

    if not args.quiet:
        if "tables" in report:
            t = report["tables"]
            print(f"tables: {t['n_checked']} checked, "
                  f"{t['n_hazards']} hazards")
            for r in t["reports"]:
                for h in r.get("hazards", []):
                    print(f"  {r.get('name')}: {h}")
        if "memory" in report:
            m = report["memory"]
            print(f"memory: {m['n_checked']} priced, {m['n_bad']} identity "
                  f"violations (batch={m['batch_size']}, "
                  f"seq={m['seq_length']})")
            for r in m["reports"]:
                if "error" in r:
                    print(f"  {r['name']}[D={r['n_devices']},"
                          f"V={r['n_virtual']},M={r['n_microbatches']}]: "
                          f"{r['error']}")
                    continue
                cells = " ".join(
                    f"d{pd['device']}:{pd['act_live_peak']}x"
                    f"{r['act_slot_bytes']}B+{pd['grad_live_peak']}g"
                    for pd in r["per_device"])
                print(f"  {r['name']}[D={r['n_devices']},"
                      f"V={r['n_virtual']},M={r['n_microbatches']}] "
                      f"{r['backward_policy']}: "
                      f"peak {r['peak_bytes'] / 1e6:.3f} MB  {cells}")
        if "overlap" in report:
            ov = report["overlap"]
            print(f"overlap: {ov['n_checked']} priced, {ov['n_bad']} "
                  f"contract violations")
            for r in ov["reports"]:
                for p in r.get("problems", []) or (
                        [r["error"]] if "error" in r else []):
                    print(f"  {r['name']}[D={r['n_devices']},"
                          f"V={r['n_virtual']},M={r['n_microbatches']}]: "
                          f"{p}")
        if "calibration" in report:
            ca = report["calibration"]
            print(f"calibration: {ca['n_checked']} checks, "
                  f"{ca['n_bad']} failures")
            for c in ca["cases"]:
                if not c["ok"]:
                    print(f"  {c['case']}: FAIL "
                          f"{ {k: v for k, v in c.items() if k not in ('case', 'ok')} }")
        if "lint" in report:
            li = report["lint"]
            print(f"lint: {li['n_findings']} findings")
            for f in li["findings"]:
                print(f"  {f}")
        if "jaxpr" in report:
            for case in report["jaxpr"]["cases"]:
                status = "ok" if not case["problems"] else "FAIL"
                print(f"jaxpr: {case['case']}: {status} "
                      f"(ppermutes={case['ppermute_count']}, "
                      f"callbacks={case['n_callbacks']})")
                for p in case["problems"]:
                    print(f"  {p}")
        if "search" in report:
            for case in report["search"]["cases"]:
                status = ("ok" if case["certified"]
                          and case["beats_or_ties_1f1b"] is not False
                          else "FAIL")
                print(f"search: {case['case']}: {status} "
                      f"(bubble={case['bubble_table_exact']:.4f} vs "
                      f"1F1B={case['bubble_1f1b']}, "
                      f"seed={case['winning_seed']})")
        print(f"check: {'OK' if report['ok'] else 'FAILED'}")

    if args.json:
        with open(args.json, "w") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
            fh.write("\n")

    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
