"""Static analysis passes over the pipeline framework (docs/static_analysis.md).

Three passes plus a CLI (``python -m
distributed_training_with_pipeline_parallelism_tpu.analysis``):

- :mod:`.table_check` — symbolic interpreter over compiled tick tables:
  RAW/WAR/WAW slot hazards with exact (device, tick, column) locations,
  ppermute send/recv pairing per ring direction, route consistency,
  compression roundtrips, unit counts, slot high-water marks (a static
  activation-memory bound), and per-channel comm volume (the unrolled
  executor's predicted ppermute count).
- :mod:`.jaxpr_audit` — walks traced step functions: zero host callbacks
  with telemetry off, collective counts/axes vs the mesh and the table
  verifier's prediction, dtype drift.
- :mod:`.repo_lint` — ast rules: no host calls in tick/scan bodies,
  lazy-export discipline in ``__init__.py``, no bare ``jax.jit`` without
  a named scope in ``parallel/``, no raw host-clock step timing outside
  the sanctioned timing surfaces (``raw-step-timing``).
- :mod:`.cost_model` — analytical roofline accounting over compiled tick
  tables (FLOPs per F/B/W unit, bytes per ring hop, predicted step time
  under a :class:`~.cost_model.HardwareSpec`, table-exact/closed-form
  bubble fractions, MFU/HFU from measured step time) — the predicted
  side of the predicted↔measured loop ``utils.telemetry`` closes
  (docs/observability.md "Cost model & MFU").
- :mod:`.memory_model` — the bytes-domain twin of the cost model:
  per-device HBM priced three ways (analytic slot-peaks x slot-bytes +
  params/optimizer/KV, AOT-compiled ``memory_analysis()``, live
  ``memory_stats()`` watermarks) and reconciled; source of the
  sweep/bench OOM preflight and the byte-denominated search budgets
  (docs/observability.md "Memory observatory").
- :mod:`.calibration` — the measured-probe leg that closes the loop on
  both models: a deterministic micro-probe harness
  (``scripts/probe.py``), the predicted-vs-measured ledger
  (``results/calibration.jsonl``, per-axis signed relative error grouped
  by backend/schedule family/backward policy), and least-squares
  per-hardware correction factors the cost model applies when available
  (docs/observability.md "Calibration observatory").
- :mod:`.schedule_search` — the certifying schedule compiler: seeded,
  deterministic search over per-device action orders whose objective is
  the cost model's predicted step time and whose hard constraints are
  the static proofs above (every emitted artifact is certified
  hazard-free and budget-bounded; docs/static_analysis.md "Schedule
  compiler").

The builders call the table passes at table-build time behind the
``DTPP_VERIFY_TABLES`` env flag (on in tests, off by default in
production runs — the checks are pure numpy but nonzero).
"""

import os
from typing import Optional

VERIFIER_VERSION = 1


def verify_tables_enabled() -> bool:
    """True when ``DTPP_VERIFY_TABLES`` asks for build-time verification."""
    return os.environ.get("DTPP_VERIFY_TABLES", "").lower() not in (
        "", "0", "false", "off", "no")


def maybe_verify_schedule(cs) -> None:
    """Build-time hook (``parallel.pipeline._compile``): verify a compiled
    schedule's table when ``DTPP_VERIFY_TABLES`` is set; raise
    ``ScheduleError`` naming every hazard location otherwise stay silent."""
    if not verify_tables_enabled():
        return
    from ..parallel.schedules import ScheduleError
    from .table_check import check_table
    report = check_table(cs)
    if not report.ok:
        raise ScheduleError(
            f"static table verification failed for {cs.name} "
            f"(D={cs.n_devices}, V={cs.n_virtual}, M={cs.n_microbatches}, "
            f"{cs.placement}): "
            + "; ".join(str(h) for h in report.hazards[:8]))


def maybe_verify_forward_table(table, n_devices: int, n_virtual: int,
                               n_microbatches: int, n_slots: int) -> None:
    """Build-time hook for the forward-only executors
    (``pipeline._fwd_tick_table``)."""
    if not verify_tables_enabled():
        return
    from ..parallel.schedules import ScheduleError
    from .table_check import check_forward_table
    report = check_forward_table(table, n_devices, n_virtual,
                                 n_microbatches, n_slots)
    if not report.ok:
        raise ScheduleError(
            f"static forward-table verification failed "
            f"(D={n_devices}, V={n_virtual}, M={n_microbatches}): "
            + "; ".join(str(h) for h in report.hazards[:8]))


def maybe_verify_serving(n_devices: int, n_slots: int,
                         gamma: Optional[int] = None,
                         prefill_chunk: Optional[int] = None) -> None:
    """Build-time hook for the serving executor's round-robin ring
    (``serving.engine.make_serving_step_fn``). Speculative programs pass
    ``gamma``/``prefill_chunk`` so the widened-metadata checks (verify
    chunk fits the channel, acceptance bounds well-formed) run at build
    time too."""
    if not verify_tables_enabled():
        return
    from .table_check import check_serving_ring
    spec = (dict(gamma=gamma, prefill_chunk=prefill_chunk)
            if gamma is not None else None)
    report = check_serving_ring(n_devices, n_slots, speculative=spec)
    if not report.ok:
        raise ValueError(
            f"serving ring verification failed (D={n_devices}, "
            f"n_slots={n_slots}): "
            + "; ".join(str(h) for h in report.hazards[:8]))


def maybe_verify_page_table(pages, *, refcount, n_pages: int,
                            page_size: int, write_lo: int, write_hi: int,
                            cow_dst: int = -1, slot: int = -1) -> None:
    """Admission-time hook for the paged serving engine
    (``serving.engine.ServingEngine._admit``): verify one slot's planned
    page-table row against the pool's refcounts when
    ``DTPP_VERIFY_TABLES`` is set (in-bounds, refcount-live, no aliased
    or shared-page writes without COW)."""
    if not verify_tables_enabled():
        return
    from .table_check import page_table_hazards
    hazards = page_table_hazards(
        pages, refcount=refcount, n_pages=n_pages, page_size=page_size,
        write_lo=write_lo, write_hi=write_hi, cow_dst=cow_dst, slot=slot)
    if hazards:
        raise ValueError(
            f"page-table discipline verification failed (slot={slot}): "
            + "; ".join(str(h) for h in hazards[:8]))


_LAZY = {
    "Hazard": ("table_check", "Hazard"),
    "TableReport": ("table_check", "TableReport"),
    "check_table": ("table_check", "check_table"),
    "check_table_cached": ("table_check", "check_table_cached"),
    "check_table_baseline": ("table_check", "check_table_baseline"),
    "recheck_after_swap": ("table_check", "recheck_after_swap"),
    "TableCheckBaseline": ("table_check", "TableCheckBaseline"),
    "check_forward_table": ("table_check", "check_forward_table"),
    "check_serving_ring": ("table_check", "check_serving_ring"),
    "check_page_table": ("table_check", "check_page_table"),
    "page_table_hazards": ("table_check", "page_table_hazards"),
    "speculative_hazards": ("table_check", "speculative_hazards"),
    "static_analysis_section": ("table_check", "static_analysis_section"),
    "JaxprAudit": ("jaxpr_audit", "JaxprAudit"),
    "audit_jaxpr": ("jaxpr_audit", "audit_jaxpr"),
    "audit_fn": ("jaxpr_audit", "audit_fn"),
    "LintFinding": ("repo_lint", "LintFinding"),
    "lint_repo": ("repo_lint", "lint_repo"),
    "lint_source": ("repo_lint", "lint_source"),
    "main": ("cli", "main"),
    "run_checks": ("cli", "run_checks"),
    "default_grid": ("cli", "default_grid"),
    "HardwareSpec": ("cost_model", "HardwareSpec"),
    "hardware_spec_for": ("cost_model", "hardware_spec_for"),
    "detect_hardware": ("cost_model", "detect_hardware"),
    "cost_model_section": ("cost_model", "cost_model_section"),
    "serving_cost_model_section": ("cost_model",
                                   "serving_cost_model_section"),
    "expected_tokens_per_verify": ("cost_model",
                                   "expected_tokens_per_verify"),
    "train_flops_per_token": ("cost_model", "train_flops_per_token"),
    "fwd_flops_per_token": ("cost_model", "fwd_flops_per_token"),
    "resolve_backward_policy": ("cost_model", "resolve_backward_policy"),
    "backward_weights": ("cost_model", "backward_weights"),
    "predicted_step_time": ("cost_model", "predicted_step_time"),
    "memory_model_section": ("memory_model", "memory_model_section"),
    "serving_memory_section": ("memory_model", "serving_memory_section"),
    "activation_slot_bytes": ("memory_model", "activation_slot_bytes"),
    "params_bytes": ("memory_model", "params_bytes"),
    "compiled_memory_section": ("memory_model", "compiled_memory_section"),
    "reconcile_memory": ("memory_model", "reconcile_memory"),
    "oom_preflight": ("memory_model", "oom_preflight"),
    "size_page_pool": ("memory_model", "size_page_pool"),
    "kv_page_bytes": ("memory_model", "kv_page_bytes"),
    "kv_slot_bytes": ("memory_model", "kv_slot_bytes"),
    "contiguous_slots_for_budget": ("memory_model",
                                    "contiguous_slots_for_budget"),
    "comm_overlap_step_time": ("cost_model", "comm_overlap_step_time"),
    "predicted_tick_seconds": ("cost_model", "predicted_tick_seconds"),
    "memory_probe_axes": ("memory_model", "memory_probe_axes"),
    "CalibrationError": ("calibration", "CalibrationError"),
    "ProbeSpec": ("calibration", "ProbeSpec"),
    "probe_grid": ("calibration", "probe_grid"),
    "run_probe": ("calibration", "run_probe"),
    "reprice_row": ("calibration", "reprice_row"),
    "schedule_family": ("calibration", "schedule_family"),
    "load_ledger": ("calibration", "load_ledger"),
    "append_ledger_rows": ("calibration", "append_ledger_rows"),
    "group_errors": ("calibration", "group_errors"),
    "CorrectionFactors": ("calibration", "CorrectionFactors"),
    "fit_corrections": ("calibration", "fit_corrections"),
    "correction_artifact": ("calibration", "correction_artifact"),
    "load_correction_artifact": ("calibration", "load_correction_artifact"),
    "maybe_load_default_corrections": ("calibration",
                                       "maybe_load_default_corrections"),
    "calibration_section": ("calibration", "calibration_section"),
    "calibration_section_from_cost_model":
        ("calibration", "calibration_section_from_cost_model"),
    "SearchSpec": ("schedule_search", "SearchSpec"),
    "SearchResult": ("schedule_search", "SearchResult"),
    "search_schedule": ("schedule_search", "search_schedule"),
    "seed_orders": ("schedule_search", "seed_orders"),
    "run_search": ("cli", "run_search"),
}


def __getattr__(name):
    if name in _LAZY:
        import importlib
        mod, attr = _LAZY[name]
        value = getattr(importlib.import_module(f".{mod}", __name__), attr)
        globals()[name] = value
        return value
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_LAZY))


__all__ = ["VERIFIER_VERSION", "verify_tables_enabled",
           "maybe_verify_schedule", "maybe_verify_forward_table",
           "maybe_verify_serving", "maybe_verify_page_table",
           *sorted(_LAZY)]
