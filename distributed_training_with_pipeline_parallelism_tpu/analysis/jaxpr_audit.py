"""Jaxpr auditor: host-callback, collective, and dtype-drift checks.

The table verifier (:mod:`.table_check`) proves what the *schedule* says
should happen; this pass checks what the *traced program* actually
contains. It walks a closed jaxpr recursively (through pjit/shard_map
calls, scan bodies with their trip counts, cond branches, custom-vjp
wrappers) and accumulates:

- ``n_callbacks``: host callbacks (``io_callback`` / ``pure_callback`` /
  debug prints). The telemetry contract (docs/observability.md) is that
  an uninstrumented step fn contains ZERO of these — telemetry off is
  free at trace time.
- ``collectives``: weighted counts per collective primitive. Scan bodies
  multiply by the scan ``length``; cond contributes the elementwise MAX
  over its branches (the executor's worst-case tick); a while loop makes
  the counts lower bounds (``unbounded`` is set). For an unrolled tick
  executor the traced ``ppermute`` count must equal
  ``TableReport.predicted_ppermutes`` — the dead-hop elision contract.
- ``psum_axes`` / ``unknown_axes``: every axis name a collective reduces
  over, and those not present in the declared mesh axes.
- dtype drift: ``f64_values`` (any float64 output — unintended x64
  promotion) and ``bf16_upcasts`` (bf16 -> f32 ``convert_element_type``;
  legitimate sites — loss accumulators, RoPE tables — are bounded by the
  caller's allowlist budget, not matched by name).

Only :func:`audit_fn` imports jax (lazily): the module itself stays
importable in jax-free tooling contexts.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

_CALLBACK_MARKERS = ("callback", "outside_call", "debug_print")
_COLLECTIVE_PREFIXES = ("ppermute", "pbroadcast", "psum", "pmax", "pmin",
                        "all_gather", "all_to_all", "reduce_scatter",
                        "psum_scatter")


@dataclasses.dataclass
class JaxprAudit:
    """Aggregated facts about one traced step function."""

    n_callbacks: int = 0
    collectives: Dict[str, int] = dataclasses.field(default_factory=dict)
    psum_axes: Tuple[str, ...] = ()
    unknown_axes: Tuple[str, ...] = ()
    f64_values: int = 0
    bf16_upcasts: int = 0
    unbounded: bool = False  # a while loop made counts lower bounds
    problems: List[str] = dataclasses.field(default_factory=list)

    @property
    def ppermute_count(self) -> int:
        return sum(n for name, n in self.collectives.items()
                   if name.startswith("ppermute"))

    @property
    def ok(self) -> bool:
        return not self.problems

    def summary(self) -> Dict[str, Any]:
        return {
            "n_callbacks": self.n_callbacks,
            "collectives": dict(self.collectives),
            "ppermute_count": self.ppermute_count,
            "psum_axes": list(self.psum_axes),
            "unknown_axes": list(self.unknown_axes),
            "f64_values": self.f64_values,
            "bf16_upcasts": self.bf16_upcasts,
            "unbounded": self.unbounded,
            "problems": list(self.problems),
        }


class _Acc:
    def __init__(self):
        self.callbacks = 0
        self.collectives: Dict[str, int] = {}
        self.axes: Dict[str, bool] = {}  # axis name -> seen on a psum-like
        self.f64 = 0
        self.upcasts = 0
        self.unbounded = False

    def merge_max(self, others: Sequence["_Acc"]) -> None:
        """Elementwise max across cond branches, added into self."""
        if not others:
            return
        self.callbacks += max(o.callbacks for o in others)
        for name in {n for o in others for n in o.collectives}:
            self.collectives[name] = self.collectives.get(name, 0) + max(
                o.collectives.get(name, 0) for o in others)
        for o in others:
            self.axes.update(o.axes)
            self.unbounded |= o.unbounded
        self.f64 += max(o.f64 for o in others)
        self.upcasts += max(o.upcasts for o in others)


def _inner_jaxpr(obj: Any) -> Optional[Any]:
    """Duck-typed unwrap: ClosedJaxpr -> Jaxpr, Jaxpr -> itself."""
    if hasattr(obj, "eqns"):
        return obj
    if hasattr(obj, "jaxpr") and hasattr(getattr(obj, "jaxpr"), "eqns"):
        return obj.jaxpr
    return None


def _axis_names(params: Dict[str, Any]) -> List[str]:
    names: List[str] = []
    for key in ("axis_name", "axes", "axis_index_groups_axis"):
        v = params.get(key)
        if v is None:
            continue
        for item in (v if isinstance(v, (tuple, list)) else (v,)):
            if isinstance(item, str):
                names.append(item)
    return names


def _walk(jaxpr: Any, mult: int, acc: _Acc) -> None:
    import numpy as np

    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if any(m in name for m in _CALLBACK_MARKERS):
            acc.callbacks += mult
        if name.startswith(_COLLECTIVE_PREFIXES):
            acc.collectives[name] = acc.collectives.get(name, 0) + mult
            for ax in _axis_names(eqn.params):
                acc.axes[ax] = True
        for var in eqn.outvars:
            aval = getattr(var, "aval", None)
            dt = getattr(aval, "dtype", None)
            if dt is not None and dt == np.dtype("float64"):
                acc.f64 += mult
        if name == "convert_element_type":
            src = getattr(getattr(eqn.invars[0], "aval", None), "dtype",
                          None)
            dst = eqn.params.get("new_dtype")
            if (src is not None and dst is not None
                    and str(src) == "bfloat16" and str(dst) == "float32"):
                acc.upcasts += mult
        # recurse into sub-jaxprs with the right multiplier
        if name == "scan":
            length = int(eqn.params.get("length", 1))
            sub = _inner_jaxpr(eqn.params.get("jaxpr"))
            if sub is not None:
                _walk(sub, mult * length, acc)
            continue
        if name == "while":
            acc.unbounded = True
            for key in ("body_jaxpr", "cond_jaxpr"):
                sub = _inner_jaxpr(eqn.params.get(key))
                if sub is not None:
                    _walk(sub, mult, acc)
            continue
        if name == "cond":
            branch_accs = []
            for br in eqn.params.get("branches", ()):
                sub = _inner_jaxpr(br)
                if sub is not None:
                    b = _Acc()
                    _walk(sub, mult, b)
                    branch_accs.append(b)
            acc.merge_max(branch_accs)
            continue
        for v in eqn.params.values():
            for item in (v if isinstance(v, (tuple, list)) else (v,)):
                sub = _inner_jaxpr(item)
                if sub is not None:
                    _walk(sub, mult, acc)


def audit_jaxpr(closed_jaxpr: Any, mesh_axes: Sequence[str] = (),
                expect_no_callbacks: bool = False,
                expected_ppermutes: Optional[int] = None,
                upcast_budget: Optional[int] = None) -> JaxprAudit:
    """Audit a (closed) jaxpr. Facts are always collected; ``problems`` is
    populated only for the contracts the caller opted into (plus unknown
    collective axes whenever ``mesh_axes`` is given)."""
    acc = _Acc()
    jaxpr = _inner_jaxpr(closed_jaxpr)
    if jaxpr is None:
        raise TypeError(f"not a jaxpr: {type(closed_jaxpr)!r}")
    _walk(jaxpr, 1, acc)

    audit = JaxprAudit(
        n_callbacks=acc.callbacks,
        collectives=dict(sorted(acc.collectives.items())),
        psum_axes=tuple(sorted(acc.axes)),
        f64_values=acc.f64,
        bf16_upcasts=acc.upcasts,
        unbounded=acc.unbounded,
    )
    if mesh_axes:
        unknown = tuple(a for a in audit.psum_axes if a not in mesh_axes)
        audit.unknown_axes = unknown
        if unknown:
            audit.problems.append(
                f"collectives reduce over undeclared axes {unknown} "
                f"(mesh declares {tuple(mesh_axes)})")
    if expect_no_callbacks and audit.n_callbacks:
        audit.problems.append(
            f"{audit.n_callbacks} host callback(s) traced with telemetry "
            f"off (must be zero)")
    if expected_ppermutes is not None \
            and audit.ppermute_count != expected_ppermutes:
        audit.problems.append(
            f"traced ppermute count {audit.ppermute_count} != table-"
            f"predicted comm volume {expected_ppermutes}")
    if audit.f64_values:
        audit.problems.append(
            f"{audit.f64_values} float64 value(s) traced (unintended x64 "
            f"promotion)")
    if upcast_budget is not None and audit.bf16_upcasts > upcast_budget:
        audit.problems.append(
            f"{audit.bf16_upcasts} bf16->f32 upcasts exceed the allowlist "
            f"budget {upcast_budget}")
    return audit


def audit_fn(fn: Any, *args: Any, mesh_axes: Sequence[str] = (),
             **kwargs: Any) -> JaxprAudit:
    """Trace ``fn(*args)`` with ``jax.make_jaxpr`` (abstract — nothing
    executes) and audit the result. Keyword arguments are forwarded to
    :func:`audit_jaxpr`."""
    import jax

    closed = jax.make_jaxpr(fn)(*args)
    return audit_jaxpr(closed, mesh_axes=mesh_axes, **kwargs)


def collective_matmul_ppermutes(axis_size: int, n_gathers: int,
                                n_scatters: int = 0) -> int:
    """Chunked-permute census for the ring collective-matmul forms
    (:mod:`..ops.collectives`): every ring gather (``all_gather_matmul``,
    ``seq_all_gather``) and ring scatter (``matmul_reduce_scatter``)
    traces exactly ``axis_size - 1`` ppermutes. Add this to a program's
    ``expected_ppermutes`` when auditing a ``tp_overlap="ring"`` forward
    — the double-buffered pipeline executors themselves keep the table's
    ``predicted_ppermutes`` unchanged (deferred banking moves the store
    commit, never the hop)."""
    return (int(axis_size) - 1) * (int(n_gathers) + int(n_scatters))
