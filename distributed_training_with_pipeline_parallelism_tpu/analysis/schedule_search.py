"""Certifying schedule compiler: cost-model-guided search over verified
tick tables (docs/static_analysis.md "Schedule compiler").

The verifier stops being only a gate here and becomes a compiler pass:
:func:`search_schedule` explores per-device action orders, compiles each
candidate with :func:`~..parallel.schedules.compile_order`, *rejects any
candidate the static checks do not certify*, and scores the survivors
with the exact cost model :func:`.cost_model.predicted_step_time` prices
reports with. The emitted artifact therefore carries a proof, not a
hope: its table was certified hazard-free by :func:`.table_check
.check_table`, its slot high-water marks fit the caller's activation
budget, and the loader re-certifies cell-by-cell on every load.

Search layout (deterministic for a fixed seed — no wall clock, no global
RNG):

1. **Seeds** — greedy list-scheduling orders from the
   ``_zb_greedy_order`` family (the ZB-H1/ZB-V synthesis, parameterized
   by the in-flight live cap) for split-backward specs; the built-in
   schedule orders (1F1B/GPipe/Interleaved/BFS) otherwise. Seeds that
   deadlock or fail certification are skipped, not fatal.
2. **Refinement** — seeded simulated annealing over local moves (adjacent
   swaps and short-window reinsertions within one device's order). Every
   candidate is compiled and statically rechecked; hazardous or
   over-budget candidates are rejected regardless of predicted cost.
   The incremental :func:`.table_check.recheck_after_swap` fast path
   makes the inner loop cheap: only the suffix from the first changed
   tick is reinterpreted against the last accepted baseline.
3. **Certification** — the winner is recompiled with full verification
   (``verify_table`` + :func:`.table_check.check_table`) and emitted as a
   versioned JSON artifact via
   :func:`~..parallel.schedules.schedule_artifact`, embedding the clean
   ``TableReport`` summary, the predicted cost, and the 1F1B baseline it
   is measured against.
"""

from __future__ import annotations

import dataclasses
import math
import random
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..parallel.schedules import (
    Action,
    B,
    CompiledSchedule,
    F,
    ScheduleError,
    W,
    _zb_greedy_order,
    build_order,
    compile_order,
    compile_schedule,
    placement_device_of,
    schedule_artifact,
)
from .cost_model import (backward_weights, comm_overlap_step_time,
                         predicted_step_time)
from .table_check import (
    TableCheckBaseline,
    TableReport,
    check_table,
    check_table_baseline,
    recheck_after_swap,
)

__all__ = [
    "SearchSpec",
    "SearchResult",
    "search_schedule",
    "seed_orders",
    "one_f_one_b_baseline",
]


@dataclasses.dataclass(frozen=True)
class SearchSpec:
    """One search problem: a pipeline shape plus budgets and knobs.

    ``unit_s`` is the (F, B, W) per-unit cost vector for the objective;
    when ``None`` it defaults to :func:`.cost_model.backward_weights`
    under the spec's resolved backward policy (``split`` when
    ``split_backward``, else ``remat``; ``stored`` on one device) — i.e.
    abstract forward-unit equivalents, which is exactly what
    ``cost_model_section`` prices up to the hardware scale factor.
    ``act_slot_budget``/``grad_slot_budget`` bound the per-device slot
    high-water marks (``TableReport.act_slots_used`` /
    ``grad_slots_used``); candidates over budget are rejected as hard
    constraint violations, same as hazards.

    Budgets can also be stated in *bytes* of HBM:
    ``act_bytes_budget``/``grad_bytes_budget`` (e.g. a fraction of
    :attr:`.cost_model.HardwareSpec.hbm_bytes` left after parameters and
    optimizer state) are divided by ``act_slot_bytes``/``grad_slot_bytes``
    (one slot's slab size, :func:`.memory_model.activation_slot_bytes`)
    and floored into an equivalent slot cap; when both a slot and a byte
    budget are given the tighter one wins (:meth:`resolved_slot_budgets`).
    """

    n_devices: int
    n_microbatches: int
    n_virtual: int = 1
    placement: str = "wrap"
    split_backward: bool = True
    seed: int = 0
    iterations: int = 600
    unit_s: Optional[Tuple[float, float, float]] = None
    hop_s: float = 0.0
    act_slot_budget: Optional[int] = None
    grad_slot_budget: Optional[int] = None
    act_bytes_budget: Optional[float] = None
    grad_bytes_budget: Optional[float] = None
    act_slot_bytes: Optional[int] = None
    grad_slot_bytes: Optional[int] = None
    name: str = "Searched"
    # Cost objective: "step_s" ranks candidates by the lockstep serial
    # prediction; "comm_overlap" ranks by the double-buffered executor's
    # step_s_comm_overlap — i.e. the search ASSUMES ring-hop fusion and
    # optimizes for tables whose arrivals defer past the consuming tick's
    # early units. Both predictions are recorded in every artifact either
    # way, so the predicted end-to-end payoff per schedule is always
    # visible.
    objective: str = "step_s"

    def resolved_unit_s(self) -> Tuple[float, float, float]:
        if self.unit_s is not None:
            f, b, w = self.unit_s
            return (float(f), float(b), float(w))
        if self.split_backward:
            policy = "split"
        elif self.n_devices == 1:
            policy = "stored"
        else:
            policy = "remat"
        b, w = backward_weights(policy)
        return (1.0, float(b), float(w))

    def resolved_slot_budgets(self) -> Tuple[Optional[int], Optional[int]]:
        """Effective (act, grad) per-device slot caps: the tighter of the
        slot-count budget and ``floor(bytes_budget / slot_bytes)``."""
        def tighter(slots: Optional[int], bytes_budget: Optional[float],
                    slot_bytes: Optional[int]) -> Optional[int]:
            caps = [] if slots is None else [int(slots)]
            if bytes_budget is not None:
                caps.append(int(float(bytes_budget) // int(slot_bytes)))
            return min(caps) if caps else None
        return (tighter(self.act_slot_budget, self.act_bytes_budget,
                        self.act_slot_bytes),
                tighter(self.grad_slot_budget, self.grad_bytes_budget,
                        self.grad_slot_bytes))

    def validate(self) -> None:
        if self.n_devices < 1:
            raise ScheduleError(f"n_devices must be >= 1, got {self.n_devices}")
        if self.n_microbatches < 1:
            raise ScheduleError(
                f"n_microbatches must be >= 1, got {self.n_microbatches}")
        if self.n_virtual < 1:
            raise ScheduleError(f"n_virtual must be >= 1, got {self.n_virtual}")
        if self.placement not in ("wrap", "vshape"):
            raise ScheduleError(
                f"placement must be 'wrap' or 'vshape', got {self.placement!r}")
        if self.placement == "vshape" and self.n_virtual != 2:
            raise ScheduleError("vshape placement runs exactly 2 chunks per "
                                "device (set n_virtual=2)")
        if self.placement == "vshape" and not self.split_backward:
            raise ScheduleError("vshape search requires split_backward=True "
                                "(the ZB-V executor contract)")
        if self.iterations < 0:
            raise ScheduleError(f"iterations must be >= 0, got {self.iterations}")
        if self.objective not in ("step_s", "comm_overlap"):
            raise ScheduleError(f"objective must be 'step_s' or "
                                f"'comm_overlap', got {self.objective!r}")
        for kind in ("act", "grad"):
            bytes_budget = getattr(self, f"{kind}_bytes_budget")
            slot_bytes = getattr(self, f"{kind}_slot_bytes")
            if bytes_budget is not None:
                if slot_bytes is None or slot_bytes <= 0:
                    raise ScheduleError(
                        f"{kind}_bytes_budget needs {kind}_slot_bytes > 0 to "
                        f"convert bytes into slots (use analysis.memory_model"
                        f".activation_slot_bytes), got {slot_bytes!r}")
                if bytes_budget < slot_bytes:
                    raise ScheduleError(
                        f"{kind}_bytes_budget={bytes_budget} holds zero slots "
                        f"of {slot_bytes} bytes — no schedule can fit")


@dataclasses.dataclass
class SearchResult:
    """A certified winner: the compiled schedule, its clean report, the
    predicted cost it was selected on, baselines, and the versioned JSON
    artifact (``schedules.load_schedule_artifact`` re-certifies it)."""

    spec: SearchSpec
    cs: CompiledSchedule
    orders: List[List[Action]]
    report: TableReport
    predicted: Dict[str, float]
    baselines: Dict[str, Dict[str, float]]
    stats: Dict[str, object]
    artifact: Dict[str, object]

    @property
    def beats_1f1b(self) -> Optional[bool]:
        base = self.baselines.get("1F1B")
        if not base:
            return None
        return (self.predicted["bubble_table_exact"]
                <= base["bubble_table_exact"] + 1e-12)


# ---------------------------------------------------------------------------
# Seeds
# ---------------------------------------------------------------------------


def _greedy_seed_caps(D: int, M: int) -> List[Tuple[str, Callable[[int], int]]]:
    """Live-cap variants for the greedy synthesis: the ZB-H1 cap (2D - d),
    a flat deep bank, a tight memory-lean cap, and effectively-unbounded.
    Distinct caps land in different basins; the annealer refines each."""
    caps: List[Tuple[str, Callable[[int], int]]] = [
        ("zb-cap-2D-d", lambda d: 2 * D - d),
        ("zb-cap-2D+2", lambda d: 2 * D + 2),
        ("zb-cap-D+1", lambda d: D + 1),
        ("zb-cap-M", lambda d: max(M, 1)),
    ]
    return caps


def seed_orders(spec: SearchSpec) -> List[Tuple[str, List[List[Action]]]]:
    """Deterministic seed pool of (label, per-device orders) for a spec.

    Split-backward specs seed from the shared ``_zb_greedy_order``
    synthesis under several live caps (ZB-H1's ``2D - d`` among them, so
    the known-good zero-bubble orders are always in the pool); full-
    backward specs seed from the built-in schedule orders that fit the
    shape. Seeds whose synthesis deadlocks are skipped silently — the
    pool just shrinks.
    """
    D, V, M = spec.n_devices, spec.n_virtual, spec.n_microbatches
    S = D * V
    seeds: List[Tuple[str, List[List[Action]]]] = []
    if spec.split_backward:
        device_of = lambda s: placement_device_of(spec.placement, s, D)
        for label, cap in _greedy_seed_caps(D, M):
            try:
                seeds.append((label, _zb_greedy_order(
                    D, M, S, device_of, cap, f"search seed {label}")))
            except ScheduleError:
                continue
    else:
        names = (["1F1B", "GPipe"] if V == 1 else ["Interleaved1F1B", "BFS"])
        for name in names:
            try:
                seeds.append((f"builtin-{name}", build_order(name, D, V, M)))
            except ScheduleError:
                continue
    if not seeds:
        raise ScheduleError(
            f"schedule search: no feasible seed for D={D}, V={V}, M={M}, "
            f"placement={spec.placement!r}, split={spec.split_backward}")
    return seeds


# ---------------------------------------------------------------------------
# Local moves
# ---------------------------------------------------------------------------


def _device_order_ok(order: Sequence[Action]) -> bool:
    """Cheap necessary condition before paying for a compile: within one
    device, F(s, m) must precede B(s, m) must precede W(s, m) (same stage
    => same device, so the full validator would reject these anyway)."""
    pos: Dict[Tuple[int, str, int], int] = {}
    for i, a in enumerate(order):
        pos[(a.stage, a.op, a.microbatch)] = i
    for (s, op, m), i in pos.items():
        if op == B:
            j = pos.get((s, F, m))
            if j is not None and j > i:
                return False
        elif op == W:
            j = pos.get((s, B, m))
            if j is not None and j > i:
                return False
            j = pos.get((s, F, m))
            if j is not None and j > i:
                return False
    return True


def _mutate(orders: List[List[Action]], rng: random.Random,
            ) -> Optional[List[List[Action]]]:
    """One local move: adjacent swap or short-window reinsertion inside a
    single device's order. Returns new orders, or None when the move is a
    no-op / trivially invalid (caller just draws again)."""
    candidates = [d for d, o in enumerate(orders) if len(o) > 1]
    if not candidates:
        return None
    d = rng.choice(candidates)
    order = list(orders[d])
    n = len(order)
    if rng.random() < 0.6:
        i = rng.randrange(n - 1)
        order[i], order[i + 1] = order[i + 1], order[i]
    else:
        i = rng.randrange(n)
        a = order.pop(i)
        lo, hi = max(0, i - 4), min(len(order), i + 4)
        j = rng.randrange(lo, hi + 1)
        if j == i:
            return None
        order.insert(j, a)
    if not _device_order_ok(order):
        return None
    out = list(orders)
    out[d] = order
    return out


# ---------------------------------------------------------------------------
# Evaluation: compile -> certify -> budget -> price
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Candidate:
    orders: List[List[Action]]
    cs: CompiledSchedule
    report: TableReport
    predicted: Dict[str, float]
    cost: Tuple[float, int, float]


def _evaluate(spec: SearchSpec, orders: List[List[Action]],
              unit_s: Tuple[float, float, float],
              baseline: Optional[TableCheckBaseline],
              stats: Dict[str, int]) -> Optional[_Candidate]:
    try:
        cs = compile_order(spec.name, orders, spec.n_devices, spec.n_virtual,
                           spec.n_microbatches,
                           split_backward=spec.split_backward,
                           placement=spec.placement, verify=False)
    except ScheduleError:
        stats["rejected_compile"] += 1
        return None
    if baseline is not None:
        report = recheck_after_swap(cs, baseline)
    else:
        report = check_table(cs)
    if report.hazards:
        stats["rejected_hazards"] += 1
        return None
    act_cap, grad_cap = spec.resolved_slot_budgets()
    if (act_cap is not None
            and max(report.act_slots_used, default=0) > act_cap):
        stats["rejected_budget"] += 1
        return None
    if (grad_cap is not None
            and max(report.grad_slots_used, default=0) > grad_cap):
        stats["rejected_budget"] += 1
        return None
    predicted = dict(predicted_step_time(cs.table, unit_s, spec.hop_s,
                                         report.predicted_ppermutes))
    predicted.update(comm_overlap_step_time(cs.table, unit_s, spec.hop_s))
    objective_s = (predicted["step_s_comm_overlap"]
                   if spec.objective == "comm_overlap"
                   else predicted["step_s"])
    cost = (objective_s, int(cs.makespan),
            predicted["bubble_table_exact"])
    return _Candidate(orders=orders, cs=cs, report=report,
                      predicted=predicted, cost=cost)


def one_f_one_b_baseline(spec: SearchSpec) -> Optional[Dict[str, float]]:
    """1F1B priced under the *same* objective (same unit costs, same hop
    cost) — the baseline embedded in every artifact and asserted against
    by the search smoke. None when 1F1B does not fit the shape."""
    try:
        cs = compile_schedule("1F1B", spec.n_devices, 1, spec.n_microbatches)
    except ScheduleError:
        return None
    report = check_table(cs)
    predicted = predicted_step_time(cs.table, spec.resolved_unit_s(),
                                    spec.hop_s, report.predicted_ppermutes)
    predicted = dict(predicted)
    predicted.update(comm_overlap_step_time(cs.table, spec.resolved_unit_s(),
                                            spec.hop_s))
    predicted["makespan"] = int(cs.makespan)
    predicted["ok"] = bool(report.ok)
    return predicted


# ---------------------------------------------------------------------------
# The search
# ---------------------------------------------------------------------------


def search_schedule(spec: SearchSpec) -> SearchResult:
    """Run the certifying search and return a :class:`SearchResult`.

    Deterministic for a fixed ``spec`` (byte-identical artifacts across
    runs — seeded ``random.Random``, no timestamps, canonical JSON).
    Raises :class:`~..parallel.schedules.ScheduleError` when no seed is
    feasible or the winner unexpectedly fails final certification.
    """
    spec.validate()
    unit_s = spec.resolved_unit_s()
    rng = random.Random(spec.seed)
    stats: Dict[str, int] = {
        "evaluated": 0, "accepted": 0, "improved": 0,
        "rejected_compile": 0, "rejected_hazards": 0, "rejected_budget": 0,
    }

    # --- seed pool: certify each seed, keep the best as the incumbent.
    seeds = seed_orders(spec)
    best: Optional[_Candidate] = None
    best_seed_label = None
    seed_labels: List[str] = []
    for label, orders in seeds:
        seed_labels.append(label)
        stats["evaluated"] += 1
        cand = _evaluate(spec, orders, unit_s, None, stats)
        if cand is not None and (best is None or cand.cost < best.cost):
            best, best_seed_label = cand, label
    if best is None:
        raise ScheduleError(
            f"schedule search: no seed certified for D={spec.n_devices}, "
            f"V={spec.n_virtual}, M={spec.n_microbatches} "
            f"(budgets act={spec.act_slot_budget}, grad={spec.grad_slot_budget})")

    # --- seeded annealing over local moves. The baseline anchors the
    # incremental recheck; it is rebased whenever the incumbent improves
    # so the suffix being reinterpreted stays short.
    current = best
    baseline = check_table_baseline(current.cs)
    t0_cost = max(current.cost[0], 1e-12)
    for it in range(spec.iterations):
        mutated = _mutate(current.orders, rng)
        if mutated is None:
            continue
        stats["evaluated"] += 1
        cand = _evaluate(spec, mutated, unit_s, baseline, stats)
        if cand is None:
            continue
        # geometric cooling, relative acceptance: early worsening moves of
        # a few percent pass, late ones effectively never.
        temp = 0.02 * (0.995 ** it)
        delta = (cand.cost[0] - current.cost[0]) / t0_cost
        if cand.cost < current.cost or (
                temp > 1e-9 and rng.random() < math.exp(-delta / temp)):
            current = cand
            stats["accepted"] += 1
            if cand.cost < best.cost:
                best, best_seed_label = cand, best_seed_label
                stats["improved"] += 1
                baseline = check_table_baseline(cand.cs)

    # --- final certification: recompile the winner with the executor-
    # contract verifier on, then a full (uncached, non-incremental)
    # check_table. Both must pass for the artifact to exist at all.
    cs = compile_order(spec.name, best.orders, spec.n_devices, spec.n_virtual,
                       spec.n_microbatches, split_backward=spec.split_backward,
                       placement=spec.placement, verify=True)
    report = check_table(cs)
    if not report.ok:
        raise ScheduleError(
            "schedule search: winner failed final certification: "
            + "; ".join(str(h) for h in report.hazards[:4]))
    predicted = dict(predicted_step_time(cs.table, unit_s, spec.hop_s,
                                         report.predicted_ppermutes))
    predicted.update(comm_overlap_step_time(cs.table, unit_s, spec.hop_s))
    predicted["makespan"] = int(cs.makespan)

    baselines: Dict[str, Dict[str, float]] = {}
    base = one_f_one_b_baseline(spec)
    if base is not None:
        baselines["1F1B"] = base

    search_meta: Dict[str, object] = {
        "algorithm": "greedy-seeds+annealing",
        "seed": spec.seed,
        "iterations": spec.iterations,
        "seed_pool": seed_labels,
        "winning_seed": best_seed_label,
        "unit_s": list(unit_s),
        "hop_s": spec.hop_s,
        "act_slot_budget": spec.act_slot_budget,
        "grad_slot_budget": spec.grad_slot_budget,
        "act_bytes_budget": spec.act_bytes_budget,
        "grad_bytes_budget": spec.grad_bytes_budget,
        "act_slot_bytes": spec.act_slot_bytes,
        "grad_slot_bytes": spec.grad_slot_bytes,
        "effective_act_slot_budget": spec.resolved_slot_budgets()[0],
        "effective_grad_slot_budget": spec.resolved_slot_budgets()[1],
        "objective": ("comm_overlap_step_time.step_s_comm_overlap"
                      if spec.objective == "comm_overlap"
                      else "predicted_step_time.step_s"),
        **stats,
    }
    artifact = schedule_artifact(
        cs, orders=best.orders, seed=spec.seed,
        table_report=report.summary(), predicted=predicted,
        baselines=baselines, search=search_meta)
    return SearchResult(spec=spec, cs=cs, orders=best.orders, report=report,
                        predicted=predicted, baselines=baselines,
                        stats=search_meta, artifact=artifact)
