"""AST-based repo lint: project rules the test suite cannot see.

Each rule encodes a contract documented elsewhere in the repo
(docs/static_analysis.md explains how to add more):

``scan-body-host-call``
    No ``time.time()`` / ``time.perf_counter()``, ``.item()``, or
    ``np.asarray`` / ``numpy.asarray`` inside tick/scan bodies — a host
    sync or host-side constant inside a traced loop body either fails
    under jit or silently re-traces. A "tick/scan body" is any function
    passed to ``lax.scan`` / ``lax.fori_loop`` / ``lax.while_loop``
    (positionally or by name), any function named ``tick``, and every
    function nested inside one. ``jnp.asarray`` is fine (traced).

``init-lazy-exports``
    Package ``__init__.py`` files must not eagerly import submodules:
    re-exports go through the ``_LAZY`` + ``__getattr__`` pattern of the
    top-level ``__init__`` so ``import dtpp`` stays cheap. The only
    allowlisted eager import is ``utils.config`` (pure-python dataclasses
    the one-import surface needs at definition time).

``jit-named-scope``
    No bare ``jax.jit`` in ``parallel/`` modules without a
    ``jax.named_scope`` somewhere in the same file: profile legibility
    (docs/observability.md) requires every jitted entry point to carry
    named scopes so XProf timelines attribute time to pipeline phases.

``raw-tick-table``
    No constructing or mutating raw ``[T, D, 17]`` tick tables outside
    ``analysis/`` and the schedule compilers (``parallel/schedules.py``,
    ``parallel/native.py``): flagged are ``np``/``numpy``/``jnp``
    ``full``/``zeros``/``ones``/``empty`` calls whose shape mentions
    ``N_COLS``, subscript *stores* indexed by a ``COL_*`` column
    constant, and ``.at[...COL_*...].set/add`` updates. Reading table
    cells (``row[COL_FWD_V]``) stays legal everywhere — the executor
    does exactly that. Everything else must go through
    ``compile_schedule``/``compile_order`` or a certified schedule
    artifact, which is what makes the static certification meaningful
    (docs/static_analysis.md "Schedule compiler").

``tp-bare-collective``
    No bare ``jax.lax.all_gather`` / ``jax.lax.psum_scatter`` *calls* in
    ``parallel/tensor_parallel.py`` outside the collective-matmul
    wrappers (``tp_all_gather_matmul`` / ``tp_matmul_reduce_scatter``).
    The wrappers are the single dispatch point for the ``tp_overlap``
    knob (docs/performance.md "Comm/compute overlap") — a bare call
    elsewhere silently bypasses the ring overlap path. Reads/mentions
    of the names stay legal; only call sites are flagged.

``dynamics-sync-read``
    No host fetch (``jax.device_get``, ``jax.block_until_ready``, or a
    ``float(...)`` coercion) of a training-dynamics statistic —
    identifiers or dict keys like ``sq_mb``, ``grad_norm_per_stage``,
    ``nonfinite_per_stage``, ``last_bad_stage``, ``dyn_latest`` —
    outside the modules that own the log-sync boundary
    (``utils/train.py``, ``utils/dynamics.py``) and the off-the-clock
    sweep probe (``utils/sweep.py``). The dynamics contract
    (docs/observability.md §7) is that per-stage stats live in
    device-resident buffers and are read **only** when the loss is
    synced anyway; a fetch anywhere else adds a device round-trip per
    step and silently serializes the pipeline.

``raw-step-timing``
    No direct host-clock *calls* (``time.time()``,
    ``time.perf_counter()``, ``time.perf_counter_ns()``,
    ``time.monotonic()``) outside the sanctioned timing surfaces:
    ``utils/telemetry.py`` (stamp recorder + event log),
    ``utils/metrics.py`` (the timed benchmark loop),
    ``utils/profiling.py``, ``utils/train.py`` (log-window wall clock),
    ``utils/resilience.py`` (checkpoint stamps), ``serving/engine.py``
    (serving wall clock), and ``analysis/calibration.py`` (the probe
    harness). Anywhere else, a raw clock read is an ad-hoc step timing
    that bypasses the predicted-vs-measured calibration ledger
    (docs/observability.md §9) — route it through ``utils.metrics`` /
    telemetry so every measurement is reconcilable with the cost model.

The linter is stdlib-only (``ast``) — no jax import, safe for CI legs
that run before any backend exists.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Set, Tuple

# __init__.py relative imports that may stay eager (see rule docstring).
LAZY_IMPORT_ALLOWLIST = frozenset({"utils.config"})

# Calls banned inside tick/scan bodies: (dotted-name, message).
_BANNED_DOTTED = {
    "time.time": "host clock read inside a traced tick/scan body",
    "time.perf_counter": "host clock read inside a traced tick/scan body",
    "np.asarray": "host-side numpy materialization inside a traced "
                  "tick/scan body (use jnp.asarray)",
    "numpy.asarray": "host-side numpy materialization inside a traced "
                     "tick/scan body (use jnp.asarray)",
}

_SCAN_ENTRY_POINTS = {"scan", "fori_loop", "while_loop"}
# positional index of the body callable per entry point
_BODY_ARG_INDEX = {"scan": 0, "fori_loop": 2, "while_loop": 1}


@dataclasses.dataclass(frozen=True)
class LintFinding:
    path: str
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


def _dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _scan_body_names(tree: ast.AST) -> Tuple[Set[str], List[ast.Lambda]]:
    """Names of functions passed as scan/fori/while bodies, plus inline
    lambda bodies."""
    names: Set[str] = set()
    lambdas: List[ast.Lambda] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        callee = _dotted_name(node.func)
        if callee is None:
            continue
        leaf = callee.rsplit(".", 1)[-1]
        if leaf not in _SCAN_ENTRY_POINTS:
            continue
        idx = _BODY_ARG_INDEX[leaf]
        if idx < len(node.args):
            body = node.args[idx]
            if isinstance(body, ast.Name):
                names.add(body.id)
            elif isinstance(body, ast.Lambda):
                lambdas.append(body)
    return names, lambdas


def _check_banned_calls(scope: ast.AST, path: str,
                        findings: List[LintFinding]) -> None:
    for node in ast.walk(scope):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted_name(node.func)
        if dotted in _BANNED_DOTTED:
            findings.append(LintFinding(
                path, node.lineno, "scan-body-host-call",
                f"{dotted}(): {_BANNED_DOTTED[dotted]}"))
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr == "item" and not node.args):
            findings.append(LintFinding(
                path, node.lineno, "scan-body-host-call",
                ".item(): host sync inside a traced tick/scan body"))


def _lint_scan_bodies(tree: ast.AST, path: str,
                      findings: List[LintFinding]) -> None:
    body_names, body_lambdas = _scan_body_names(tree)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
                node.name in body_names or node.name == "tick"):
            _check_banned_calls(node, path, findings)
    for lam in body_lambdas:
        _check_banned_calls(lam, path, findings)


def _lint_init_exports(tree: ast.Module, path: str,
                       findings: List[LintFinding]) -> None:
    for node in tree.body:
        if isinstance(node, ast.ImportFrom) and node.level >= 1:
            module = node.module or ""
            if module in LAZY_IMPORT_ALLOWLIST:
                continue
            findings.append(LintFinding(
                path, node.lineno, "init-lazy-exports",
                f"eager relative import of {'.' * node.level}{module} in "
                f"__init__.py — route re-exports through the _LAZY/"
                f"__getattr__ pattern"))


def _lint_jit_named_scope(tree: ast.AST, path: str,
                          findings: List[LintFinding]) -> None:
    jit_sites: List[int] = []
    has_named_scope = False
    for node in ast.walk(tree):
        dotted = None
        if isinstance(node, ast.Call):
            dotted = _dotted_name(node.func)
        elif isinstance(node, ast.Attribute):
            dotted = _dotted_name(node)
        if dotted == "jax.jit":
            jit_sites.append(node.lineno)
        elif dotted == "jax.named_scope":
            has_named_scope = True
    if not has_named_scope:
        # de-dup Call/Attribute double counting of the same site
        for line in sorted(set(jit_sites)):
            findings.append(LintFinding(
                path, line, "jit-named-scope",
                "jax.jit in parallel/ without any jax.named_scope in the "
                "module — jitted entry points must carry named scopes "
                "for profile attribution"))


# raw-tick-table: files allowed to build/mutate tables directly (the
# compilers and the analysis passes themselves).
_RAW_TABLE_ALLOWLIST = ("parallel/schedules.py", "parallel/native.py")
_TABLE_CTORS = frozenset({"full", "zeros", "ones", "empty"})
_TABLE_NAMESPACES = frozenset({"np", "numpy", "jnp"})


def _mentions_name(node: ast.AST, match) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and match(sub.id):
            return True
        if isinstance(sub, ast.Attribute) and match(sub.attr):
            return True
    return False


def _lint_raw_tables(tree: ast.AST, path: str,
                     findings: List[LintFinding]) -> None:
    is_ncols = lambda s: s in ("N_COLS", "N_COLS_CLASSIC")
    is_col = lambda s: s.startswith("COL_")
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            dotted = _dotted_name(node.func)
            if dotted is not None and "." in dotted:
                ns, leaf = dotted.rsplit(".", 1)
                if (leaf in _TABLE_CTORS
                        and ns.rsplit(".", 1)[-1] in _TABLE_NAMESPACES
                        and any(_mentions_name(a, is_ncols) for a in
                                list(node.args)
                                + [kw.value for kw in node.keywords])):
                    findings.append(LintFinding(
                        path, node.lineno, "raw-tick-table",
                        f"{dotted}(...N_COLS...): raw tick-table "
                        f"construction outside analysis//parallel/"
                        f"schedules.py — go through compile_schedule/"
                        f"compile_order or a certified artifact"))
            # jnp functional update: table.at[..., COL_X].set(v)
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("set", "add", "multiply",
                                           "max", "min")
                    and isinstance(node.func.value, ast.Subscript)
                    and isinstance(node.func.value.value, ast.Attribute)
                    and node.func.value.value.attr == "at"
                    and _mentions_name(node.func.value.slice, is_col)):
                findings.append(LintFinding(
                    path, node.lineno, "raw-tick-table",
                    ".at[...COL_*...] update of a tick-table column "
                    "outside analysis//parallel/schedules.py — compiled "
                    "tables are immutable; go through compile_order or a "
                    "certified artifact"))
            continue
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for tgt in targets:
            for sub in ast.walk(tgt):
                if (isinstance(sub, ast.Subscript)
                        and _mentions_name(sub.slice, is_col)):
                    findings.append(LintFinding(
                        path, sub.lineno, "raw-tick-table",
                        "subscript store indexed by a COL_* column "
                        "outside analysis//parallel/schedules.py — "
                        "compiled tables are immutable; go through "
                        "compile_order or a certified artifact"))


# tp-bare-collective: the only functions in parallel/tensor_parallel.py
# allowed to call the bare lax collectives they wrap.
_TP_WRAPPER_FNS = frozenset({"tp_all_gather_matmul",
                             "tp_matmul_reduce_scatter"})
_TP_BARE_COLLECTIVES = frozenset({"all_gather", "psum_scatter"})


def _lint_tp_bare_collectives(tree: ast.AST, path: str,
                              findings: List[LintFinding]) -> None:
    def walk(node: ast.AST, inside_wrapper: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            inside_wrapper = inside_wrapper or node.name in _TP_WRAPPER_FNS
        if isinstance(node, ast.Call) and not inside_wrapper:
            dotted = _dotted_name(node.func)
            if dotted is not None:
                parts = dotted.split(".")
                if (parts[-1] in _TP_BARE_COLLECTIVES
                        and "lax" in parts[:-1]):
                    findings.append(LintFinding(
                        path, node.lineno, "tp-bare-collective",
                        f"{dotted}(): bare collective in parallel/"
                        f"tensor_parallel.py outside the collective-"
                        f"matmul wrappers — route through "
                        f"tp_all_gather_matmul/tp_matmul_reduce_scatter "
                        f"so the tp_overlap knob stays authoritative"))
        for child in ast.iter_child_nodes(node):
            walk(child, inside_wrapper)

    walk(tree, False)


# dynamics-sync-read: modules that own the log-sync boundary (train's
# fit loop, the dynamics host helpers) or read off the timed clock
# (sweep's post-loop probe).
_DYN_SYNC_ALLOWLIST = ("utils/train.py", "utils/dynamics.py",
                       "utils/sweep.py")
# identifiers / dict keys that name device-resident dynamics stats
_DYN_STAT_NAMES = frozenset({
    "sq_mb", "dyn_latest", "dyn_stats",
    "grad_norm_per_stage", "grad_max_per_stage", "nonfinite_per_stage",
    "grad_norm_per_layer", "param_rms_per_stage", "update_ratio_per_stage",
    "last_bad_stage",
})
_SYNC_CALLS = frozenset({"jax.device_get", "jax.block_until_ready"})


def _mentions_dyn_stat(node: ast.AST) -> Optional[str]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in _DYN_STAT_NAMES:
            return sub.id
        if isinstance(sub, ast.Attribute) and sub.attr in _DYN_STAT_NAMES:
            return sub.attr
        if (isinstance(sub, ast.Constant) and isinstance(sub.value, str)
                and sub.value in _DYN_STAT_NAMES):
            return sub.value
    return None


def _lint_dynamics_sync_reads(tree: ast.AST, path: str,
                              findings: List[LintFinding]) -> None:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted_name(node.func)
        is_float = isinstance(node.func, ast.Name) and node.func.id == "float"
        if dotted not in _SYNC_CALLS and not is_float:
            continue
        for arg in node.args:
            stat = _mentions_dyn_stat(arg)
            if stat is not None:
                what = dotted if dotted in _SYNC_CALLS else "float"
                findings.append(LintFinding(
                    path, node.lineno, "dynamics-sync-read",
                    f"{what}(...{stat}...): host fetch of a dynamics "
                    f"statistic outside the log-sync boundary "
                    f"(utils/train.py / utils/dynamics.py) — per-stage "
                    f"stats stay device-resident and are read only when "
                    f"the loss syncs (docs/observability.md §7)"))
                break


# raw-step-timing: modules allowed to read host clocks directly — the
# sanctioned timing surfaces plus the calibration probe harness (see
# the rule docstring). Everything else must time through them.
_RAW_TIMING_ALLOWLIST = ("utils/telemetry.py", "utils/metrics.py",
                         "utils/profiling.py", "utils/resilience.py",
                         "utils/train.py", "serving/engine.py",
                         "analysis/calibration.py")
_RAW_TIMING_CALLS = frozenset({"time.time", "time.perf_counter",
                               "time.perf_counter_ns", "time.monotonic"})


def _lint_raw_step_timing(tree: ast.AST, path: str,
                          findings: List[LintFinding]) -> None:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted_name(node.func)
        if dotted in _RAW_TIMING_CALLS:
            findings.append(LintFinding(
                path, node.lineno, "raw-step-timing",
                f"{dotted}(): raw host-clock read outside the sanctioned "
                f"timing surfaces (utils/metrics.py, utils/telemetry.py, "
                f"...) — ad-hoc step timing bypasses the calibration "
                f"ledger (docs/observability.md §9); route measurements "
                f"through utils.metrics / telemetry stamps"))


def lint_source(path: str, source: str,
                package_relpath: Optional[str] = None) -> List[LintFinding]:
    """Lint one python source. ``package_relpath`` is the path relative to
    the package root (drives per-directory rules); defaults to ``path``."""
    rel = package_relpath if package_relpath is not None else path
    findings: List[LintFinding] = []
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        findings.append(LintFinding(path, e.lineno or 0, "syntax",
                                    f"unparsable: {e.msg}"))
        return findings
    _lint_scan_bodies(tree, path, findings)
    if os.path.basename(rel) == "__init__.py":
        _lint_init_exports(tree, path, findings)
    parts = rel.replace(os.sep, "/").split("/")
    if "parallel" in parts[:-1]:
        _lint_jit_named_scope(tree, path, findings)
    rel_posix = rel.replace(os.sep, "/")
    if parts[0] != "analysis" and rel_posix not in _RAW_TABLE_ALLOWLIST:
        _lint_raw_tables(tree, path, findings)
    if parts[0] != "analysis" and rel_posix not in _DYN_SYNC_ALLOWLIST:
        _lint_dynamics_sync_reads(tree, path, findings)
    if rel_posix == "parallel/tensor_parallel.py":
        _lint_tp_bare_collectives(tree, path, findings)
    if rel_posix not in _RAW_TIMING_ALLOWLIST:
        _lint_raw_step_timing(tree, path, findings)
    return findings


def package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_repo(root: Optional[str] = None) -> List[LintFinding]:
    """Lint every ``.py`` file under the package (default: this package's
    own root). Returns findings sorted by (path, line)."""
    root = root or package_root()
    findings: List[LintFinding] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".git", "build")]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root)
            with open(path, "r", encoding="utf-8") as fh:
                src = fh.read()
            findings.extend(lint_source(path, src, package_relpath=rel))
    findings.sort(key=lambda f: (f.path, f.line))
    return findings


def findings_summary(findings: List[LintFinding]) -> Dict[str, object]:
    by_rule: Dict[str, int] = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    return {"n_findings": len(findings), "by_rule": by_rule,
            "findings": [str(f) for f in findings]}
