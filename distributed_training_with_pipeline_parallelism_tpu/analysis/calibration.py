"""Calibration observatory: measured micro-probes vs. the analytical models.

``analysis.cost_model`` and ``analysis.memory_model`` *predict*;
``utils.telemetry`` *measures*. Nothing in between tracked the error —
``results/history.jsonl`` accumulates points but nobody computes, groups
or guards the model residual, and the ROADMAP's auto-planner search
("validated by measured probes") needs exactly that layer. This module
closes the loop:

- **Probes**: :func:`run_probe` executes one short measured run (a few
  warm steps of a tiny model on the live mesh) for one
  :class:`ProbeSpec` — schedule family x microbatch count x backward
  policy x comm_overlap mode — and records the measured step time, the
  telemetry-derived comm seconds and the compiled peak HBM side-by-side
  with every prediction variant the models quote (lockstep serial,
  optimistically overlapped, double-buffered comm_overlap, table-exact
  bubble, analytic peak bytes). :func:`probe_grid` builds the seeded
  deterministic grid ``scripts/probe.py`` sweeps.
- **Ledger**: probe rows append to ``results/calibration.jsonl`` — one
  canonical (sorted-key) JSON line per probe, validated on write *and*
  on read (:func:`validate_ledger_row`; malformed lines are counted and
  surfaced, never silently dropped). Signed relative error is computed
  per axis and grouped by (backend, schedule family, backward policy)
  so "where can the model be trusted" is a one-dict read
  (:func:`group_errors`).
- **Corrections**: :func:`fit_corrections` least-squares fits
  per-:class:`~.cost_model.HardwareSpec` efficiency scalars — an
  effective-FLOPs factor and an effective-bandwidth factor — from the
  ledger (deterministic float64 normal equations over sorted rows), and
  persists them as a versioned, fingerprinted artifact exactly like the
  schedule artifacts of ``parallel.schedules``
  (:func:`correction_artifact` / :func:`load_correction_artifact`).
  ``cost_model_section(..., correction=...)`` applies them, so predicted
  step time carries both raw and corrected values and
  ``scripts/regress.py`` can guard the corrected error.

Everything except :func:`run_probe` is host-side stdlib+numpy — no jax
at import, so the ledger/fit/artifact layer works in any analysis
context (CI, notebooks, the regression sentinel).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
from typing import Any, Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "CALIBRATION_SCHEMA_VERSION", "LEDGER_KIND",
    "CORRECTION_ARTIFACT_VERSION", "CORRECTION_ARTIFACT_KIND",
    "DEFAULT_LEDGER_PATH", "DEFAULT_CORRECTIONS_PATH", "CORRECTIONS_ENV",
    "CalibrationError", "ProbeSpec", "probe_grid", "schedule_family",
    "signed_rel_err", "validate_ledger_row", "canonical_row_line",
    "deterministic_fields", "append_ledger_rows", "load_ledger",
    "group_errors", "CorrectionFactors", "fit_correction", "fit_corrections",
    "correction_artifact", "correction_artifact_bytes",
    "save_correction_artifact", "load_correction_artifact",
    "maybe_load_default_corrections", "row_from_cost_model",
    "backfill_row_from_history", "backfill_row_from_bench",
    "run_probe", "reprice_row", "calibration_section",
    "calibration_section_from_cost_model",
]

CALIBRATION_SCHEMA_VERSION = 1
LEDGER_KIND = "calibration_probe"
CORRECTION_ARTIFACT_VERSION = 1
CORRECTION_ARTIFACT_KIND = "calibration_correction"
DEFAULT_LEDGER_PATH = os.path.join("results", "calibration.jsonl")
DEFAULT_CORRECTIONS_PATH = os.path.join("results",
                                        "calibration_corrections.json")
CORRECTIONS_ENV = "DTPP_CALIBRATION_CORRECTIONS"

# Fitted efficiencies are clamped into a physically readable band: a
# scalar below the floor means the probe measured pure overhead (the
# fit is still recorded — the floor only stops a zero/negative divide),
# above 1.0 means the model *under*-prices work; 10x is a generous cap
# for model error before the fit itself should be distrusted.
EFFICIENCY_CLAMP = (1e-6, 10.0)


class CalibrationError(ValueError):
    """Located validation failure in a ledger row or correction artifact."""


# ---------------------------------------------------------------------------
# Probe specs and grids
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ProbeSpec:
    """One point of the probe grid.

    ``remat_backward`` is the executor's tri-state knob (None = auto →
    'remat' at D>1, False = force 'stored', True = force 'remat');
    split-backward schedules (ZBH1/ZBV) resolve to 'split' regardless.
    ``comm_overlap`` is the ring-hop discipline ("none"/"ring"); the
    double-buffered executor requires the unrolled tick loop, which
    :func:`run_probe` selects automatically."""

    schedule: str
    n_devices: int = 2
    n_virtual: int = 1
    n_microbatches: int = 4
    remat_backward: Optional[bool] = None
    comm_overlap: str = "none"

    @property
    def label(self) -> str:
        return (f"{self.schedule}[D={self.n_devices},V={self.n_virtual},"
                f"M={self.n_microbatches}]"
                f"/{_policy_of(self.schedule, self.remat_backward, self.n_devices)}"
                f"/{self.comm_overlap}")

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def _policy_of(schedule: str, remat_backward, n_devices: int) -> str:
    if schedule in ("ZBH1", "ZBV"):
        return "split"
    stored = remat_backward is False or (remat_backward is None
                                         and n_devices == 1)
    return "stored" if stored else "remat"


# The smoke grid: >= 8 configs spanning GPipe/1F1B/Interleaved x
# stored/remat/split x overlap on/off. A 2-device mesh keeps the probes
# micro (the unrolled tick loop's compile time scales with T; a probe
# measures model error, not scale). 'stored' at D>1 pairs only with
# comm_overlap="none": the double-buffered executor rejects the
# stored-residual program (docs/performance.md), and the probe harness
# honors the same constraint rather than papering over it.
_SMOKE_GRID: Tuple[ProbeSpec, ...] = (
    ProbeSpec("GPipe", n_microbatches=2, remat_backward=False),
    ProbeSpec("GPipe", n_microbatches=4, remat_backward=True),
    ProbeSpec("GPipe", n_microbatches=2, remat_backward=True,
              comm_overlap="ring"),
    ProbeSpec("1F1B", n_microbatches=2, remat_backward=False),
    ProbeSpec("1F1B", n_microbatches=2, remat_backward=True,
              comm_overlap="ring"),
    ProbeSpec("Interleaved1F1B", n_virtual=2, n_microbatches=4,
              remat_backward=True),
    ProbeSpec("Interleaved1F1B", n_virtual=2, n_microbatches=2,
              remat_backward=True, comm_overlap="ring"),
    ProbeSpec("ZBH1", n_microbatches=4),
    ProbeSpec("ZBH1", n_microbatches=2, comm_overlap="ring"),
)

_GRIDS: Dict[str, Tuple[ProbeSpec, ...]] = {"smoke": _SMOKE_GRID}


def probe_grid(name: str = "smoke", seed: int = 0) -> List[ProbeSpec]:
    """The named grid in a seeded deterministic order.

    The permutation decorrelates probe order from grid-definition order
    (so steady-state host effects — page cache, turbo — don't bias one
    schedule family), while same seed → same order → byte-identical
    ledger rows modulo measured fields (the determinism contract
    ``tests/test_calibration.py`` pins)."""
    try:
        grid = _GRIDS[name]
    except KeyError:
        raise CalibrationError(
            f"unknown probe grid {name!r}; available: {sorted(_GRIDS)}")
    perm = np.random.default_rng(seed).permutation(len(grid))
    return [grid[int(i)] for i in perm]


_FAMILIES = (
    (re.compile(r"^GPipe"), "GPipe"),
    (re.compile(r"^1F1B"), "1F1B"),
    (re.compile(r"^Interleaved"), "Interleaved"),
    (re.compile(r"^BFS"), "BFS"),
    (re.compile(r"^ZB"), "ZB"),
    (re.compile(r"^Searched"), "searched"),
)


def schedule_family(name: str) -> str:
    """Coarse family key for error grouping ("other" when unrecognized)."""
    for pat, fam in _FAMILIES:
        if pat.match(name or ""):
            return fam
    return "other"


# ---------------------------------------------------------------------------
# Ledger rows
# ---------------------------------------------------------------------------


def signed_rel_err(predicted, measured) -> Optional[float]:
    """(predicted - measured) / measured; None when either side is
    missing or the measurement is non-positive. Negative = the model
    under-predicts (optimistic), positive = over-predicts."""
    if predicted is None or measured is None:
        return None
    measured = float(measured)
    if measured <= 0.0 or not np.isfinite(measured):
        return None
    return (float(predicted) - measured) / measured


def _rel_err_block(predicted: Optional[Dict[str, Any]],
                   measured: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Per-axis signed error between matching keys of the two blocks."""
    if not predicted or not measured:
        return None
    out: Dict[str, Any] = {}
    for axis in ("step_s", "step_s_overlapped", "step_s_comm_overlap",
                 "comm_s", "peak_bytes"):
        m_axis = "step_s" if axis.startswith("step_s") else axis
        err = signed_rel_err(predicted.get(axis), measured.get(m_axis))
        if err is not None:
            out[axis] = err
    return out or None


# required key -> allowed types. "predicted"/"measured"/"rel_err"/
# "corrected" are dict-or-None; a missing *required* key or a wrong type
# is a hard CalibrationError so a truncated write can't masquerade as
# a probe.
_ROW_SCHEMA: Tuple[Tuple[str, tuple], ...] = (
    ("schema_version", (int,)),
    ("kind", (str,)),
    ("source", (str,)),
    ("t", (int, float)),
    ("name", (str,)),
    ("backend", (str,)),
    ("hardware", (str,)),
    ("cpu_proxy", (bool,)),
    ("schedule", (str,)),
    ("schedule_family", (str,)),
    ("backward_policy", (str,)),
    ("comm_overlap", (str,)),
    ("n_devices", (int,)),
    ("n_virtual", (int,)),
    ("n_microbatches", (int,)),
    ("batch_size", (int,)),
    ("seq_length", (int,)),
    ("predicted", (dict, type(None))),
    ("measured", (dict, type(None))),
    ("rel_err", (dict, type(None))),
    ("corrected", (dict, type(None))),
)

# Fields excluded from the determinism contract: everything measured
# (and everything derived from a measurement) plus the wall-clock stamp.
_MEASURED_FIELDS = ("t", "measured", "rel_err", "corrected")


def validate_ledger_row(row: Any, where: str = "row") -> Dict[str, Any]:
    """Schema-check one ledger row; returns it. Raises
    :class:`CalibrationError` naming the offending field."""
    if not isinstance(row, dict):
        raise CalibrationError(f"{where}: not a JSON object "
                               f"({type(row).__name__})")
    for key, types in _ROW_SCHEMA:
        if key not in row:
            raise CalibrationError(f"{where}: missing required field {key!r}")
        if not isinstance(row[key], types):
            raise CalibrationError(
                f"{where}: field {key!r} has type "
                f"{type(row[key]).__name__}, expected "
                f"{'/'.join(t.__name__ for t in types)}")
    if row["schema_version"] != CALIBRATION_SCHEMA_VERSION:
        raise CalibrationError(
            f"{where}: schema_version {row['schema_version']} != "
            f"{CALIBRATION_SCHEMA_VERSION}")
    if row["kind"] != LEDGER_KIND:
        raise CalibrationError(f"{where}: kind {row['kind']!r} != "
                               f"{LEDGER_KIND!r}")
    pred = row["predicted"]
    if pred is not None and "step_s" not in pred:
        raise CalibrationError(f"{where}: predicted block has no step_s")
    meas = row["measured"]
    if meas is not None and "step_s" not in meas:
        raise CalibrationError(f"{where}: measured block has no step_s")
    return row


def canonical_row_line(row: Dict[str, Any]) -> str:
    """The canonical (byte-deterministic) one-line encoding the ledger
    stores: sorted keys, minimal separators, no trailing spaces."""
    return json.dumps(row, sort_keys=True, separators=(",", ":"))


def deterministic_fields(row: Dict[str, Any]) -> Dict[str, Any]:
    """The row minus its measured-side fields — the part the determinism
    test requires to be byte-identical across same-seed probe runs."""
    return {k: v for k, v in row.items() if k not in _MEASURED_FIELDS}


def append_ledger_rows(path: str, rows: Iterable[Dict[str, Any]]) -> int:
    """Validate and append rows to the ledger; returns the count."""
    rows = [validate_ledger_row(r, f"append[{i}]")
            for i, r in enumerate(rows)]
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, "a", encoding="utf-8") as fh:
        for row in rows:
            fh.write(canonical_row_line(row) + "\n")
    return len(rows)


def load_ledger(path: str, strict: bool = False
                ) -> Tuple[List[Dict[str, Any]], List[str]]:
    """Read the ledger: (valid rows, malformed-line descriptions).

    Malformed lines — bad JSON or schema violations — are never silently
    dropped: each contributes a located description (``strict=True``
    raises on the first instead). A missing file is an empty ledger."""
    rows: List[Dict[str, Any]] = []
    bad: List[str] = []
    if not os.path.exists(path):
        return rows, bad
    with open(path, "r", encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, start=1):
            line = line.strip()
            if not line:
                continue
            where = f"{path}:{lineno}"
            try:
                rows.append(validate_ledger_row(json.loads(line), where))
            except (json.JSONDecodeError, CalibrationError) as e:
                if strict:
                    raise CalibrationError(f"{where}: {e}") from e
                bad.append(f"{where}: {e}")
    return rows, bad


def group_errors(rows: Sequence[Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    """Signed step-time error grouped by backend|family|policy.

    Rows without both a prediction and a measurement contribute to the
    group's ``n`` (the ledger's coverage is part of the answer) but not
    to its medians."""
    groups: Dict[str, List[Optional[float]]] = {}
    for row in rows:
        key = "|".join((row["backend"], row["schedule_family"],
                        row["backward_policy"]))
        err = (row.get("rel_err") or {}).get("step_s")
        groups.setdefault(key, []).append(err)
    out: Dict[str, Dict[str, Any]] = {}
    for key in sorted(groups):
        errs = [e for e in groups[key] if e is not None]
        out[key] = {
            "n": len(groups[key]),
            "n_with_err": len(errs),
            "median_rel_err": float(np.median(errs)) if errs else None,
            "median_abs_rel_err":
                float(np.median(np.abs(errs))) if errs else None,
        }
    return out


# ---------------------------------------------------------------------------
# Correction factors: deterministic least squares + signed artifact
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CorrectionFactors:
    """Per-hardware efficiency scalars the roofline divides by.

    ``measured_step ~= compute_s / flops_efficiency
    + comm_s / bandwidth_efficiency`` — so a factor of 1.0 means the
    roofline was exact, 0.01 means the hardware delivered 1% of the
    modeled rate on these probes. ``n_rows``/``residual_rms`` record the
    fit's evidence so a consumer can weigh it."""

    hardware: str
    flops_efficiency: float
    bandwidth_efficiency: float
    n_rows: int
    residual_rms: float

    def summary(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def _clamp_eff(x: float) -> float:
    lo, hi = EFFICIENCY_CLAMP
    return float(min(max(x, lo), hi))


def fit_correction(rows: Sequence[Dict[str, Any]], hardware: str
                   ) -> Optional[CorrectionFactors]:
    """Least-squares fit of (flops, bandwidth) efficiency for one
    hardware name over its ledger rows.

    Model: ``measured = a * compute_s + b * comm_s`` with
    ``a = 1/e_flops``, ``b = 1/e_bw`` — solved by explicit 2x2 normal
    equations in float64 over *sorted* canonical rows, so the result is
    bit-deterministic for a given ledger regardless of row order. When
    the comm column is degenerate (all ~0, or collinear with compute,
    or the solve lands non-positive) it falls back to a pure-FLOPs fit
    with ``e_bw = 1.0``. None when no row has both sides."""
    pts: List[Tuple[str, float, float, float]] = []
    for row in rows:
        if row.get("hardware") != hardware:
            continue
        pred, meas = row.get("predicted"), row.get("measured")
        if not pred or not meas:
            continue
        c = pred.get("compute_s")
        k = pred.get("comm_s")
        m = meas.get("step_s")
        if c is None or m is None or float(m) <= 0 or float(c) <= 0:
            continue
        pts.append((canonical_row_line(deterministic_fields(row)),
                    float(c), 0.0 if k is None else float(k), float(m)))
    if not pts:
        return None
    pts.sort()
    comp = np.array([p[1] for p in pts], dtype=np.float64)
    comm = np.array([p[2] for p in pts], dtype=np.float64)
    meas = np.array([p[3] for p in pts], dtype=np.float64)

    def _flops_only() -> Tuple[float, float]:
        return float((comp * meas).sum() / (comp * comp).sum()), 1.0

    scc = float((comp * comp).sum())
    skk = float((comm * comm).sum())
    sck = float((comp * comm).sum())
    det = scc * skk - sck * sck
    if skk <= 0.0 or det <= 1e-12 * scc * max(skk, 1e-300):
        a, b = _flops_only()
    else:
        rhs_c = float((comp * meas).sum())
        rhs_k = float((comm * meas).sum())
        a = (rhs_c * skk - rhs_k * sck) / det
        b = (rhs_k * scc - rhs_c * sck) / det
        if a <= 0.0 or b <= 0.0:
            a, b = _flops_only()
    resid = a * comp + b * comm - meas
    return CorrectionFactors(
        hardware=hardware,
        flops_efficiency=_clamp_eff(1.0 / a),
        bandwidth_efficiency=_clamp_eff(1.0 / b),
        n_rows=len(pts),
        residual_rms=float(np.sqrt(np.mean(resid * resid))),
    )


def fit_corrections(rows: Sequence[Dict[str, Any]]
                    ) -> Dict[str, CorrectionFactors]:
    """One :class:`CorrectionFactors` per hardware name in the rows."""
    out: Dict[str, CorrectionFactors] = {}
    for hw in sorted({r.get("hardware") for r in rows
                      if isinstance(r.get("hardware"), str)}):
        fit = fit_correction(rows, hw)
        if fit is not None:
            out[hw] = fit
    return out


_CORRECTION_FIELDS = ("hardware", "flops_efficiency", "bandwidth_efficiency",
                      "n_rows", "residual_rms")


def _corrections_fingerprint(art: Dict[str, Any]) -> str:
    payload = {k: art.get(k) for k in
               ("artifact_version", "kind", "schema_version", "corrections")}
    blob = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def correction_artifact(factors: Mapping[str, CorrectionFactors]
                        ) -> Dict[str, Any]:
    """Versioned, fingerprinted JSON artifact for a set of fitted
    corrections — the same interchange discipline as the schedule
    artifacts (``parallel.schedules``): the fingerprint signs the
    payload, the loader re-derives and rejects any tamper."""
    art: Dict[str, Any] = {
        "artifact_version": CORRECTION_ARTIFACT_VERSION,
        "kind": CORRECTION_ARTIFACT_KIND,
        "schema_version": CALIBRATION_SCHEMA_VERSION,
        "corrections": {hw: cf.summary() for hw, cf in sorted(factors.items())},
    }
    art["fingerprint"] = _corrections_fingerprint(art)
    return art


def correction_artifact_bytes(art: Dict[str, Any]) -> bytes:
    """Canonical (byte-deterministic) encoding of a correction artifact."""
    return (json.dumps(art, sort_keys=True) + "\n").encode()


def save_correction_artifact(art: Dict[str, Any], path: str) -> None:
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, "wb") as fh:
        fh.write(correction_artifact_bytes(art))


def load_correction_artifact(source) -> Dict[str, CorrectionFactors]:
    """Load + verify a correction artifact (path or dict) into
    per-hardware :class:`CorrectionFactors`. Every failure is a located
    :class:`CalibrationError`."""
    if isinstance(source, dict):
        art, label = source, "<dict>"
    else:
        label = str(source)
        try:
            with open(source, "r", encoding="utf-8") as fh:
                art = json.load(fh)
        except OSError as e:
            raise CalibrationError(f"correction artifact {label}: "
                                   f"unreadable: {e}")
        except json.JSONDecodeError as e:
            raise CalibrationError(f"correction artifact {label}: "
                                   f"invalid JSON: {e}")
    if not isinstance(art, dict):
        raise CalibrationError(f"correction artifact {label}: not an object")
    if art.get("kind") != CORRECTION_ARTIFACT_KIND:
        raise CalibrationError(f"correction artifact {label}: kind "
                               f"{art.get('kind')!r} != "
                               f"{CORRECTION_ARTIFACT_KIND!r}")
    if art.get("artifact_version") != CORRECTION_ARTIFACT_VERSION:
        raise CalibrationError(
            f"correction artifact {label}: artifact_version "
            f"{art.get('artifact_version')!r} != "
            f"{CORRECTION_ARTIFACT_VERSION}")
    if art.get("fingerprint") != _corrections_fingerprint(art):
        raise CalibrationError(f"correction artifact {label}: fingerprint "
                               "mismatch (payload was modified)")
    corr = art.get("corrections")
    if not isinstance(corr, dict):
        raise CalibrationError(f"correction artifact {label}: corrections "
                               "is not an object")
    out: Dict[str, CorrectionFactors] = {}
    for hw, blob in corr.items():
        if not isinstance(blob, dict):
            raise CalibrationError(f"correction artifact {label}: "
                                   f"corrections[{hw!r}] is not an object")
        for field in _CORRECTION_FIELDS:
            if field not in blob:
                raise CalibrationError(
                    f"correction artifact {label}: corrections[{hw!r}] "
                    f"missing {field!r}")
        lo, hi = EFFICIENCY_CLAMP
        for field in ("flops_efficiency", "bandwidth_efficiency"):
            v = blob[field]
            if not isinstance(v, (int, float)) or not (lo <= v <= hi):
                raise CalibrationError(
                    f"correction artifact {label}: corrections[{hw!r}]"
                    f".{field}={v!r} outside clamp {EFFICIENCY_CLAMP}")
        out[hw] = CorrectionFactors(
            hardware=str(blob["hardware"]),
            flops_efficiency=float(blob["flops_efficiency"]),
            bandwidth_efficiency=float(blob["bandwidth_efficiency"]),
            n_rows=int(blob["n_rows"]),
            residual_rms=float(blob["residual_rms"]))
    return out


def maybe_load_default_corrections() -> Optional[Dict[str, CorrectionFactors]]:
    """Corrections from ``$DTPP_CALIBRATION_CORRECTIONS`` or the default
    ``results/calibration_corrections.json`` — None when neither exists
    or the artifact fails verification. Never raises: a bad artifact
    must degrade to uncorrected predictions, not break a training run
    (the probe/regress legs are where a bad artifact is a hard error)."""
    path = os.environ.get(CORRECTIONS_ENV) or DEFAULT_CORRECTIONS_PATH
    if not os.path.exists(path):
        return None
    try:
        return load_correction_artifact(path)
    except CalibrationError:
        return None


# ---------------------------------------------------------------------------
# Row builders: probe, cost-model reports, backfill
# ---------------------------------------------------------------------------


def row_from_cost_model(cm: Dict[str, Any], *, source: str, name: str,
                        backend: str, t: float = 0.0,
                        seed: Optional[int] = None,
                        measured_comm_s: Optional[float] = None,
                        predicted_peak_bytes: Optional[float] = None,
                        measured_peak_bytes: Optional[float] = None
                        ) -> Dict[str, Any]:
    """Build one validated ledger row from a ``cost_model_section`` dict
    (which already pairs a predicted block with a measured one)."""
    hw = cm.get("hardware") or {}
    pred_src = cm.get("predicted") or {}
    meas_src = cm.get("measured")
    predicted: Optional[Dict[str, Any]] = None
    if pred_src:
        predicted = {k: pred_src.get(k) for k in
                     ("compute_s", "comm_s", "step_s", "step_s_overlapped",
                      "step_s_comm_overlap", "bubble_table_exact")}
        if predicted_peak_bytes is not None:
            predicted["peak_bytes"] = float(predicted_peak_bytes)
    measured: Optional[Dict[str, Any]] = None
    if meas_src and meas_src.get("step_s"):
        measured = {"step_s": float(meas_src["step_s"]),
                    "tokens_per_sec": meas_src.get("tokens_per_sec")}
        if measured_comm_s is not None:
            measured["comm_s"] = float(measured_comm_s)
        if measured_peak_bytes is not None:
            measured["peak_bytes"] = float(measured_peak_bytes)
    corrected = None
    corr_src = pred_src.get("corrected")
    if corr_src and measured:
        corrected = dict(corr_src)
        corrected["rel_err_step_s"] = signed_rel_err(
            corr_src.get("step_s"), measured["step_s"])
    row: Dict[str, Any] = {
        "schema_version": CALIBRATION_SCHEMA_VERSION,
        "kind": LEDGER_KIND,
        "source": source,
        "t": float(t),
        "name": name,
        "backend": backend,
        "hardware": str(hw.get("name", "unknown")),
        "cpu_proxy": bool(hw.get("cpu_proxy", False)),
        "schedule": str(cm.get("schedule", "unknown")),
        "schedule_family": schedule_family(str(cm.get("schedule", ""))),
        "backward_policy": str(cm.get("backward_policy", "unknown")),
        "comm_overlap": str(cm.get("comm_overlap", "none")),
        "n_devices": int(cm.get("n_devices", 0)),
        "n_virtual": int(cm.get("n_virtual", 1)),
        "n_microbatches": int(cm.get("n_microbatches", 0)),
        "batch_size": int(cm.get("batch_size", 0)),
        "seq_length": int(cm.get("seq_length", 0)),
        "predicted": predicted,
        "measured": measured,
        "rel_err": _rel_err_block(predicted, measured),
        "corrected": corrected,
    }
    if seed is not None:
        row["seed"] = int(seed)
    return validate_ledger_row(row, f"row_from_cost_model[{name}]")


def backfill_row_from_history(hrow: Dict[str, Any], *, path: str = "history"
                              ) -> Optional[Dict[str, Any]]:
    """One ``results/history.jsonl`` row → a ledger row, or None with a
    reason attached when the row carries nothing calibratable.

    History rows predate the ledger and carry only headline scalars;
    rows with a measured step but no prediction are kept with
    ``predicted: null`` (the ISSUE's never-drop-silently contract —
    the *caller* prints the reason for the ones that return None)."""
    meas_step = hrow.get("measured_step_s")
    pred_step = hrow.get("predicted_step_s")
    if meas_step is None and pred_step is None:
        return None
    schedule = str(hrow.get("schedule") or "unknown")
    backend = str(hrow.get("backend") or "unknown")
    predicted = None
    if pred_step is not None:
        predicted = {"step_s": float(pred_step), "compute_s": None,
                     "comm_s": None}
    measured = None
    if meas_step is not None:
        measured = {"step_s": float(meas_step),
                    "tokens_per_sec": hrow.get("tokens_per_sec")}
        if hrow.get("peak_temp_bytes") is not None:
            measured["peak_bytes"] = float(hrow["peak_temp_bytes"])
    row = {
        "schema_version": CALIBRATION_SCHEMA_VERSION,
        "kind": LEDGER_KIND,
        "source": f"backfill:{path}",
        "t": float(hrow.get("t") or 0.0),
        "name": str(hrow.get("name") or "history"),
        "backend": backend,
        "hardware": "cpu_proxy" if backend == "cpu" else "unknown",
        "cpu_proxy": backend == "cpu",
        "schedule": schedule,
        "schedule_family": schedule_family(schedule),
        "backward_policy": "unknown",
        "comm_overlap": "none",
        "n_devices": 0,
        "n_virtual": 1,
        "n_microbatches": 0,
        "batch_size": 0,
        "seq_length": 0,
        "predicted": predicted,
        "measured": measured,
        "rel_err": _rel_err_block(predicted, measured),
        "corrected": None,
    }
    return validate_ledger_row(row, f"backfill:{path}")


_BENCH_META = re.compile(
    r"\((?P<sched>[A-Za-z0-9_]+),.*?batch (?P<batch>\d+), "
    r"seq (?P<seq>\d+),.*?(?P<stages>\d+)-stage", re.S)


def backfill_row_from_bench(blob: Dict[str, Any], *, label: str
                            ) -> Optional[Dict[str, Any]]:
    """One ``BENCH_rNN.json`` wrapper → a ledger row, or None when the
    run failed / parsed nothing (caller reports the skip)."""
    parsed = blob.get("parsed")
    if not isinstance(parsed, dict) or parsed.get("value") in (None, 0):
        return None
    if parsed.get("unit") != "tokens/sec":
        return None
    meta = _BENCH_META.search(str(parsed.get("metric", "")))
    schedule = meta.group("sched") if meta else "unknown"
    batch = int(meta.group("batch")) if meta else 0
    seq = int(meta.group("seq")) if meta else 0
    stages = int(meta.group("stages")) if meta else 0
    tps = float(parsed["value"])
    measured = {"step_s": (batch * seq / tps) if batch and seq else None,
                "tokens_per_sec": tps}
    if measured["step_s"] is None:
        # tokens/sec alone can't be turned into a step time — keep the
        # throughput but there is no calibratable axis
        return None
    row = {
        "schema_version": CALIBRATION_SCHEMA_VERSION,
        "kind": LEDGER_KIND,
        "source": f"backfill:{label}",
        "t": 0.0,
        "name": label,
        "backend": "unknown",
        "hardware": "unknown",
        "cpu_proxy": False,
        "schedule": schedule,
        "schedule_family": schedule_family(schedule),
        "backward_policy": "unknown",
        "comm_overlap": "none",
        "n_devices": stages,
        "n_virtual": 1,
        "n_microbatches": 0,
        "batch_size": batch,
        "seq_length": seq,
        "predicted": None,       # bench wrappers predate the cost model rows
        "measured": measured,
        "rel_err": None,
        "corrected": None,
    }
    return validate_ledger_row(row, f"backfill:{label}")


# ---------------------------------------------------------------------------
# The measured micro-probe
# ---------------------------------------------------------------------------

# Tiny probe model: 4 layers divide both the 2-stage (V=1) and 4-stage
# (V=2) placements of the 2-device smoke mesh; batch 8 divides every
# grid microbatch count.
_PROBE_MODEL = dict(dim=16, n_layers=4, n_heads=2, vocab_size=64,
                    ffn_dim=32, max_seq_len=16)
_PROBE_BATCH = 8
_PROBE_SEQ = 16


def run_probe(spec: ProbeSpec, *, seed: int = 0, num_iterations: int = 2,
              warmup_iterations: int = 1, correction=None,
              t: float = 0.0,
              detail: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Execute one measured micro-probe on the live mesh → a validated
    ledger row.

    A few warm steps of a tiny model (warmup compiles + pages, then
    ``num_iterations`` timed steps via ``utils.metrics.
    run_train_iterations`` — the only sanctioned step clock), with a
    :class:`~..utils.telemetry.PipelineTelemetry` attached for the
    measured comm-seconds axis and XLA's AOT accounting for the
    measured peak-HBM axis. Deterministic modulo the measured fields:
    the spec, seeds, model and every predicted number are pure
    functions of (spec, seed). ``t`` stamps the row (pass
    ``time.time()`` from the driver; defaults to 0 so library callers
    stay deterministic). Passing a dict as ``detail`` stashes the run's
    live objects (``telemetry``, ``cost_model``, ``memory``,
    ``compiled_schedule``) for callers that need more than the row —
    ``scripts/probe.py`` uses it to write the annotated Perfetto trace
    from a real probe instead of a synthetic run."""
    import jax

    from ..models.transformer import transformer_init
    from ..parallel.mesh import make_mesh
    from ..parallel.pipeline import aot_memory_analysis, make_pipeline_step
    from ..parallel.schedules import compile_schedule
    from ..utils.config import ModelConfig, ScheduleConfig
    from ..utils.metrics import run_train_iterations
    from ..utils.telemetry import PipelineTelemetry, critical_path
    from .cost_model import cost_model_section, resolve_backward_policy
    from .memory_model import memory_model_section, memory_probe_axes

    cfg = ModelConfig(**_PROBE_MODEL)
    sched = ScheduleConfig(name=spec.schedule,
                           n_microbatches=spec.n_microbatches,
                           n_virtual=spec.n_virtual)
    cs = compile_schedule(spec.schedule, spec.n_devices, spec.n_virtual,
                          spec.n_microbatches)
    mesh = make_mesh(n_pipe=spec.n_devices)
    tel = PipelineTelemetry()
    # the double-buffered executor requires the unrolled tick loop; every
    # other probe takes the scan executor, whose once-compiled tick body
    # keeps a 9-point grid's compile bill in CI budget (the probe measures
    # steady-state step time, which executor formulation doesn't change —
    # and the choice is a pure function of the row's comm_overlap field)
    unroll = True if spec.comm_overlap == "ring" else False
    step = make_pipeline_step(cfg, mesh, sched,
                              remat_backward=spec.remat_backward,
                              unroll_ticks=unroll,
                              comm_overlap=spec.comm_overlap,
                              telemetry=tel)
    params = transformer_init(jax.random.key(seed), cfg)
    kx, ky = jax.random.split(jax.random.key(seed + 1))
    tokens = jax.random.randint(kx, (_PROBE_BATCH, _PROBE_SEQ), 0,
                                cfg.vocab_size)
    targets = jax.random.randint(ky, (_PROBE_BATCH, _PROBE_SEQ), 0,
                                 cfg.vocab_size)
    metrics = run_train_iterations(step, params, tokens, targets,
                                   num_iterations=num_iterations,
                                   warmup_iterations=warmup_iterations,
                                   telemetry=tel)
    measured_step_s = metrics["elapsed_time"] / num_iterations
    measured_comm_s = None
    if tel.events:
        cp = critical_path(tel)
        # telemetry covers the whole timed loop (reset after warmup)
        measured_comm_s = float(cp["comm_s"]) / num_iterations

    cm = cost_model_section(cs, cfg, batch_size=_PROBE_BATCH,
                            seq_length=_PROBE_SEQ,
                            remat_backward=spec.remat_backward,
                            measured_step_s=measured_step_s,
                            comm_overlap=spec.comm_overlap,
                            correction=correction)
    mem = memory_model_section(
        cs, cfg, batch_size=_PROBE_BATCH, seq_length=_PROBE_SEQ,
        remat_backward=spec.remat_backward,
        compiled=aot_memory_analysis(step, params, tokens, targets))
    peaks = memory_probe_axes(mem)

    backend = jax.devices()[0].platform
    policy = resolve_backward_policy(cs, spec.remat_backward, spec.n_devices)
    name = (f"probe_{spec.schedule}_D{spec.n_devices}V{spec.n_virtual}"
            f"M{spec.n_microbatches}_{policy}_{spec.comm_overlap}")
    if detail is not None:
        detail.update(telemetry=tel, cost_model=cm, memory=mem,
                      compiled_schedule=cs)
    return row_from_cost_model(
        cm, source="probe", name=name, backend=backend, t=t, seed=seed,
        measured_comm_s=measured_comm_s,
        predicted_peak_bytes=peaks["predicted_peak_bytes"],
        measured_peak_bytes=peaks["measured_peak_bytes"])


def reprice_row(row: Dict[str, Any], spec: ProbeSpec, correction
                ) -> Dict[str, Any]:
    """Re-price one probe row under fitted corrections WITHOUT
    re-measuring: recompile the schedule table (pure numpy) and re-run
    the cost model with the correction applied, keeping the row's
    measured fields verbatim. This is how ``scripts/probe.py`` reports
    corrected error from the same run that fitted the correction — the
    measurement is the expensive part; the pricing is host math."""
    from ..parallel.schedules import compile_schedule
    from ..utils.config import ModelConfig
    from .cost_model import cost_model_section

    cfg = ModelConfig(**_PROBE_MODEL)
    cs = compile_schedule(spec.schedule, spec.n_devices, spec.n_virtual,
                          spec.n_microbatches)
    meas = row.get("measured") or {}
    pred_old = row.get("predicted") or {}
    cm = cost_model_section(cs, cfg, batch_size=row["batch_size"],
                            seq_length=row["seq_length"],
                            remat_backward=spec.remat_backward,
                            measured_step_s=meas.get("step_s"),
                            comm_overlap=spec.comm_overlap,
                            correction=correction)
    return row_from_cost_model(
        cm, source=row["source"], name=row["name"], backend=row["backend"],
        t=row["t"], seed=row.get("seed"),
        measured_comm_s=meas.get("comm_s"),
        predicted_peak_bytes=pred_old.get("peak_bytes"),
        measured_peak_bytes=meas.get("peak_bytes"))


# ---------------------------------------------------------------------------
# RunReport section
# ---------------------------------------------------------------------------


def _compact_row(row: Dict[str, Any]) -> Dict[str, Any]:
    pred = row.get("predicted") or {}
    meas = row.get("measured") or {}
    corr = row.get("corrected") or {}
    return {
        "schedule": row["schedule"],
        "schedule_family": row["schedule_family"],
        "backward_policy": row["backward_policy"],
        "comm_overlap": row["comm_overlap"],
        "n_devices": row["n_devices"],
        "n_microbatches": row["n_microbatches"],
        "predicted_step_s": pred.get("step_s"),
        "predicted_step_s_corrected": corr.get("step_s"),
        "measured_step_s": meas.get("step_s"),
        "rel_err": (row.get("rel_err") or {}).get("step_s"),
        "rel_err_corrected": corr.get("rel_err_step_s"),
    }


def calibration_section(rows: Sequence[Dict[str, Any]], *,
                        correction: Optional[Mapping[str, Any]] = None,
                        ledger_path: Optional[str] = None) -> Dict[str, Any]:
    """The schema-validated ``calibration`` RunReport section: compact
    per-config rows plus the raw-vs-corrected error summary the regress
    sentinel guards."""
    compact = [_compact_row(validate_ledger_row(r, f"section[{i}]"))
               for i, r in enumerate(rows)]
    raw = [abs(c["rel_err"]) for c in compact if c["rel_err"] is not None]
    cor = [abs(c["rel_err_corrected"]) for c in compact
           if c["rel_err_corrected"] is not None]
    section: Dict[str, Any] = {
        "schema_version": CALIBRATION_SCHEMA_VERSION,
        "n_rows": len(compact),
        "rows": compact,
        "summary": {
            "n_with_predictions":
                sum(1 for c in compact if c["predicted_step_s"] is not None),
            "median_abs_rel_err_raw":
                float(np.median(raw)) if raw else None,
            "median_abs_rel_err_corrected":
                float(np.median(cor)) if cor else None,
            "groups": group_errors(rows),
        },
        "correction": None,
        "ledger_path": ledger_path,
    }
    if correction:
        section["correction"] = {
            hw: (cf.summary() if isinstance(cf, CorrectionFactors)
                 else dict(cf))
            for hw, cf in sorted(correction.items())}
    return section


def calibration_section_from_cost_model(cm: Dict[str, Any], *, backend: str,
                                        name: str = "run",
                                        correction: Optional[Mapping[str, Any]]
                                        = None) -> Optional[Dict[str, Any]]:
    """Single-run calibration section from a measured
    ``cost_model_section`` — how fit/sweep/bench report their own
    predicted-vs-measured point without running a probe grid. None when
    the section carries no measurement (nothing to calibrate)."""
    if not (cm.get("measured") or {}).get("step_s"):
        return None
    row = row_from_cost_model(cm, source="run", name=name, backend=backend)
    return calibration_section([row], correction=correction)
