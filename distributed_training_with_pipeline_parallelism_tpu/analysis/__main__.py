"""``python -m distributed_training_with_pipeline_parallelism_tpu.analysis``."""

import sys

from .cli import main

if __name__ == "__main__":
    sys.exit(main())
