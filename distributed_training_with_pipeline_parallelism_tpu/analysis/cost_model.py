"""Analytical cost model: roofline accounting over the compiled tick table.

The tick table already *is* the program (docs/schedules.md): every F/B/W
unit and every ring hop a step will execute appears as a cell. This
module prices those cells — FLOPs per unit from the model config, bytes
per hop from the microbatch activation shape — against a
:class:`HardwareSpec` roofline (peak dense FLOP/s + per-link ICI
bandwidth) and produces the *predicted* side of the predicted↔measured
loop that :mod:`..utils.telemetry` closes:

- per-unit FLOPs (F, and B/W under the backward policy the executor
  actually compiles: stored / remat / split — the same resolution
  ``utils.sweep`` records as ``backward_policy``),
- bytes moved per ring hop and total predicted ppermute hops (the
  dead-hop-elided count from :class:`.table_check.TableReport`),
- ideal step time under the roofline (serial and compute/comm-overlapped
  bounds),
- bubble fractions three ways: *table-exact* (idle cells over the
  ``[T, D]`` grid, identical by construction to the static verifier's
  ``unit_counts['idle'] / (T*D)``), *weighted* (per-tick lockstep
  simulation under the backward-policy weights, equal to
  ``schedules.simulated_bubble``), and *closed-form*
  (``schedules.analytic_bubble_fraction``),
- MFU/HFU once a measured step time is supplied (model FLOPs use the
  standard ``6N + attention`` accounting; hardware FLOPs charge the
  recompute the chosen backward policy actually executes).

Everything here is host-side numpy over a handful of ``[T, D, 17]``
tables — no jax execution (``jax.eval_shape`` only, for the parameter
count). The output of :func:`cost_model_section` is a plain dict that
rides the RunReport manifest (``attach_cost_model``; schema enforced by
``utils.telemetry.validate_report``) and feeds
``scripts/profile_breakdown.py`` and the ``scripts/regress.py``
perf-regression sentinel.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..parallel.schedules import (BANK_BEFORE_F, COL_STORE_B_POS_SLOT,
                                  COL_STORE_B_SLOT,
                                  COL_STORE_F_NEG_SLOT, COL_STORE_F_SLOT,
                                  CompiledSchedule, analytic_bubble_fraction,
                                  overlap_bank_stages, table_unit_activity)

__all__ = [
    "HardwareSpec", "CPU_PROXY", "TPU_PRESETS", "hardware_spec_for",
    "detect_hardware", "fwd_flops_per_token", "train_flops_per_token",
    "resolve_backward_policy", "backward_weights", "dtype_bytes",
    "predicted_step_time", "comm_overlap_step_time",
    "predicted_tick_seconds", "cost_model_section",
    "serving_cost_model_section",
]

# The ring columns a hop can bank into, with the offset the sender sits
# at: a store at (t, d) was ppermuted during tick t-1 by device
# (d - offset) % D. Mirrors table_check.RING_CHANNELS (kept literal here
# so the cost model never imports the verifier just for four constants).
_STORE_CHANNELS = (
    ("fwd_ring_pos", COL_STORE_F_SLOT, +1),
    ("bwd_ring_neg", COL_STORE_B_SLOT, -1),
    ("fwd_ring_neg", COL_STORE_F_NEG_SLOT, -1),
    ("bwd_ring_pos", COL_STORE_B_POS_SLOT, +1),
)

_DTYPE_BYTES = {"float32": 4, "bfloat16": 2, "float16": 2, "float64": 8}


@dataclasses.dataclass(frozen=True)
class HardwareSpec:
    """Roofline parameters for one chip of the pipeline mesh.

    ``peak_flops``: advertised dense bf16 peak per chip (the same numbers
    ``bench.chip_peak_flops`` divides MFU by — kept equal by test).
    ``ici_bytes_per_s``: usable unidirectional bandwidth of the one ICI
    link a ring hop crosses. ``hbm_bytes_per_s``: per-chip HBM bandwidth
    (the second roofline ceiling, reported for context). ``hbm_bytes``:
    per-chip HBM *capacity* — the denominator of
    ``analysis.memory_model``'s OOM preflight and the unit byte-valued
    ``schedule_search`` budgets are quoted in (0.0 = unknown, preflight
    disabled). ``cpu_proxy``: the numbers are order-of-magnitude
    placeholders for a simulated-CPU host — predictions keep their
    *structure* (relative schedule ranking, bubble fractions are
    hardware-free) but absolute seconds are not accelerator claims, and
    downstream consumers (regress.py) treat the run as warn-only."""

    name: str
    peak_flops: float
    ici_bytes_per_s: float
    hbm_bytes_per_s: float
    hbm_bytes: float = 0.0
    cpu_proxy: bool = False

    def summary(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


# Peaks match bench._PEAK_FLOPS (v5e is 197 TFLOP/s bf16 — not its INT8
# TOPS). ICI: one link of v4/v5e 3D/2D torus ~45-50 GB/s usable each
# way; v5p ~100 GB/s; v6e ~90 GB/s. HBM: v5e 819 GB/s (the number
# profile_breakdown.py's roofline uses), v4 1228, v5p 2765, v6e 1640.
# Capacity: v5e/v6e 16 GiB-class (16e9), v4 32, v5p 95.
TPU_PRESETS: Dict[str, HardwareSpec] = {
    "v5 lite": HardwareSpec("v5e", 197e12, 5.0e10, 8.19e11, 16e9),
    "v5e": HardwareSpec("v5e", 197e12, 5.0e10, 8.19e11, 16e9),
    "v5p": HardwareSpec("v5p", 459e12, 1.0e11, 2.765e12, 95e9),
    "v4": HardwareSpec("v4", 275e12, 5.0e10, 1.228e12, 32e9),
    "v6": HardwareSpec("v6e", 918e12, 9.0e10, 1.64e12, 32e9),
}

# One host CPU core-ish matmul throughput and loopback "interconnect":
# honest only about orders of magnitude, flagged cpu_proxy=True. The
# 16e9 "HBM" stands in for a host-RAM slice so the memory-model OOM
# preflight stays exercisable (and testable) on the simulated mesh.
CPU_PROXY = HardwareSpec("cpu_proxy", 5e10, 1e9, 5e10, 16e9,
                         cpu_proxy=True)


def hardware_spec_for(device_kind: str) -> HardwareSpec:
    """Map a ``device_kind``/platform string to a preset.

    Substring match over the TPU presets (same rule as
    ``bench.chip_peak_flops``); anything CPU-ish gets the labelled
    :data:`CPU_PROXY`; an unrecognized accelerator defaults to the v5e
    preset (the fleet default, matching bench's fallback)."""
    kind = (device_kind or "").lower()
    for key, spec in TPU_PRESETS.items():
        if key in kind:
            return spec
    if "cpu" in kind or kind == "":
        return CPU_PROXY
    return TPU_PRESETS["v5e"]


def detect_hardware() -> HardwareSpec:
    """Spec for the first visible device; :data:`CPU_PROXY` when the
    backend is CPU or unavailable."""
    try:
        import jax
        dev = jax.devices()[0]
        if dev.platform != "tpu":
            return CPU_PROXY
        return hardware_spec_for(getattr(dev, "device_kind", "tpu"))
    except Exception:
        return CPU_PROXY


def dtype_bytes(dtype: str) -> int:
    return _DTYPE_BYTES.get(dtype, 4)


def fwd_flops_per_token(cfg, seq: int) -> float:
    """Forward FLOPs per token: ``2N + 4*L*dim*seq`` attention term.

    ``N`` counts matmul-participating params only (lookup-only embedding
    tables excluded; a tied table IS the head matmul so it stays in) via
    ``jax.eval_shape`` — no arrays are materialized. Causal attention
    halves the live score matrix; ``ref_decoder`` runs two unmasked
    attentions per layer (self + cross), doubling it instead. This is the
    canonical accounting: ``bench.train_flops_per_token`` is 3x this."""
    import jax

    from ..models import transformer as tfm
    shapes = jax.eval_shape(
        lambda: tfm.transformer_init(jax.random.key(0), cfg))
    n_params = sum(x.size for x in jax.tree.leaves(shapes))
    if not cfg.tie_embeddings:
        n_params -= shapes["embed"]["tok"].size  # lookup only, zero matmuls
    if "pos" in shapes["embed"]:
        n_params -= shapes["embed"]["pos"].size  # additive lookup
    attn_fwd_per_tok = 2 * 2 * cfg.n_layers * cfg.dim * seq
    attn_fwd_per_tok *= 2 if cfg.arch == "ref_decoder" else 0.5
    return 2.0 * n_params + attn_fwd_per_tok


def train_flops_per_token(cfg, seq: int) -> float:
    """``6N + 12*L*dim*seq``-family model FLOPs per trained token (fwd +
    2x bwd — PaLM appendix B). The single source of truth bench delegates
    to."""
    return 3.0 * fwd_flops_per_token(cfg, seq)


def resolve_backward_policy(cs: CompiledSchedule, remat_backward=None,
                            n_devices: Optional[int] = None) -> str:
    """Which backward the executor compiles for this schedule.

    Mirrors ``make_pipeline_grad_fn``'s resolution (the rule
    ``utils.sweep`` inlined until this module became the shared home):
    split-backward schedules always rematerialize into separate
    B (recompute + dgrad) and W (recompute + wgrad) units; otherwise
    'stored' at D==1 by default or on explicit ``remat_backward=False``,
    else 'remat'."""
    if cs.split_backward:
        return "split"
    D = cs.n_devices if n_devices is None else n_devices
    stored = remat_backward is False or (remat_backward is None and D == 1)
    return "stored" if stored else "remat"


def backward_weights(policy: str):
    """Per-tick cost of (B, W) units in forward-unit equivalents.

    stored: B = dgrad + wgrad ~ 2F, no W unit. remat: +1F recompute.
    split: B = recompute + dgrad ~ 2F, W = recompute + wgrad ~ 2F."""
    return {"stored": (2.0, 1.0), "remat": (3.0, 1.0),
            "split": (2.0, 2.0)}[policy]


def _hops_per_tick(table: np.ndarray) -> np.ndarray:
    """Live ring hops launched at the end of each tick.

    A store at ``(t, d, channel)`` banks data ppermuted during tick
    ``t-1``, and one ppermute per channel serves every device that tick —
    so hops[t-1] = number of channels with >= 1 store at t. Summed over
    ticks this equals ``TableReport.predicted_ppermutes`` (channels with
    zero cells contribute zero hop ticks)."""
    T = table.shape[0]
    hops = np.zeros(T, dtype=np.int64)
    for t in range(1, T):
        n_live = sum(1 for _, col, _ in _STORE_CHANNELS
                     if (table[t, :, col] >= 0).any())
        hops[t - 1] = n_live
    return hops


def predicted_step_time(table: np.ndarray, unit_s: Tuple[float, float, float],
                        hop_s: float, hops_total: int) -> Dict[str, float]:
    """The exact time model ``cost_model_section`` prices ``predicted``
    with, factored out so the schedule search's objective is *identical*
    to the reported cost: lockstep per-tick max across devices (every
    device waits for the tick's straggler), ring hops serialized after
    compute (``step_s``) or overlapped with the launching tick
    (``step_s_overlapped``). ``unit_s`` is (F, B, W) seconds per unit —
    absolute (unit FLOPs / peak) or abstract forward-unit equivalents;
    the argmin over candidate tables is scale-invariant either way."""
    activity = table_unit_activity(table)          # [T, D, (F,B,W,idle)]
    vec = np.array([unit_s[0], unit_s[1], unit_s[2], 0.0], dtype=np.float64)
    per_dev_tick_s = activity.astype(np.float64) @ vec          # [T, D]
    compute_tick_s = per_dev_tick_s.max(axis=1)                 # [T]
    t_compute_s = float(compute_tick_s.sum())
    t_comm_s = float(hops_total) * hop_s
    hops_per_tick = _hops_per_tick(table)
    idle_cells = int(activity[:, :, 3].sum())
    T, D = int(table.shape[0]), int(table.shape[1])
    return {
        "compute_s": t_compute_s,
        "comm_s": t_comm_s,
        "step_s": t_compute_s + t_comm_s,
        "step_s_overlapped": float(
            np.maximum(compute_tick_s, hops_per_tick * hop_s).sum()),
        "bubble_table_exact": idle_cells / float(T * D),
    }


def comm_overlap_step_time(table: np.ndarray,
                           unit_s: Tuple[float, float, float],
                           hop_s: float,
                           bank_stages: Optional[np.ndarray] = None,
                           correction=None,
                           ) -> Dict[str, float]:
    """Predicted step time under the DOUBLE-BUFFERED executor
    (``comm_overlap="ring"``) — the first-class mode between the lockstep
    ``step_s`` (hops serialized after compute) and the fully optimistic
    ``step_s_overlapped`` lower bound.

    Attribution follows the executor's actual dataflow: a hop launched at
    the end of tick ``u-1`` lands in a recv register and is committed at
    tick ``u``'s bank stage (:func:`..parallel.schedules.
    overlap_bank_stages`, the same classifier the executor banks by). A
    stage-0 bank means the first unit of tick ``u`` consumes the arrival —
    the hop is EXPOSED, serialized exactly as in lockstep. A later stage
    means the hop overlaps tick ``u``'s earlier compute, so the tick costs
    ``max(compute_u, overlappable_comm_u)`` instead of the sum:

        time_u = exposed_hops_u * hop_s
                 + max(compute_u, overlappable_hops_u * hop_s)

    Per tick this is >= ``max(compute_u, all_hops_u * hop_s)`` and
    <= ``compute_u + all_hops_u * hop_s``, so summed it sits within the
    [overlapped, serial] envelope the existing bounds quote (the
    ``overlapped`` bound attributes hops to the LAUNCH tick, so the
    orderings can differ tick-by-tick, but hold summed on real schedule
    tables — ``scripts/check.py --overlap`` asserts the grid-wide
    ``<= step_s`` invariant and the search smoke pins the strict
    sandwich on searched artifacts).

    ``correction``: an ``analysis.calibration.CorrectionFactors`` (or
    any object with ``flops_efficiency``/``bandwidth_efficiency``) — the
    per-hardware efficiency scalars fitted from measured probes; when
    present the inputs are de-rated (``unit_s / e_flops``,
    ``hop_s / e_bw``) before pricing, which preserves the envelope
    ordering (both scalings are positive)."""
    if correction is not None:
        e_f = float(correction.flops_efficiency)
        e_b = float(correction.bandwidth_efficiency)
        unit_s = (unit_s[0] / e_f, unit_s[1] / e_f, unit_s[2] / e_f)
        hop_s = hop_s / e_b
    table = np.asarray(table)
    if bank_stages is None:
        bank_stages = overlap_bank_stages(table)
    activity = table_unit_activity(table)
    vec = np.array([unit_s[0], unit_s[1], unit_s[2], 0.0], dtype=np.float64)
    compute_tick_s = (activity.astype(np.float64) @ vec).max(axis=1)  # [T]
    T = table.shape[0]
    exposed = np.zeros(T, dtype=np.int64)
    deferred = np.zeros(T, dtype=np.int64)
    for u in range(1, T):
        for ci, (_, col, _) in enumerate(_STORE_CHANNELS):
            if (table[u, :, col] >= 0).any():
                if bank_stages[u, ci] == BANK_BEFORE_F:
                    exposed[u] += 1
                else:
                    deferred[u] += 1
    tick_s = exposed * hop_s + np.maximum(compute_tick_s, deferred * hop_s)
    return {
        "step_s_comm_overlap": float(tick_s.sum()),
        "exposed_hops": int(exposed.sum()),
        "overlappable_hops": int(deferred.sum()),
        "exposed_comm_s": float(exposed.sum() * hop_s),
        "hidden_comm_s": float(
            (np.minimum(deferred * hop_s, compute_tick_s)).sum()),
    }


def predicted_tick_seconds(table: np.ndarray,
                           unit_s: Tuple[float, float, float],
                           hop_s: float,
                           bank_stages: Optional[np.ndarray] = None,
                           correction=None) -> np.ndarray:
    """Per-tick predicted seconds ``[T]`` under the double-buffered
    attribution of :func:`comm_overlap_step_time` — the vector the
    Perfetto exporter lays beside each measured tick slice so
    predicted-vs-measured disagreement is visible per tick, not just as
    one summed scalar. Sums exactly to ``step_s_comm_overlap``."""
    if correction is not None:
        e_f = float(correction.flops_efficiency)
        e_b = float(correction.bandwidth_efficiency)
        unit_s = (unit_s[0] / e_f, unit_s[1] / e_f, unit_s[2] / e_f)
        hop_s = hop_s / e_b
    table = np.asarray(table)
    if bank_stages is None:
        bank_stages = overlap_bank_stages(table)
    activity = table_unit_activity(table)
    vec = np.array([unit_s[0], unit_s[1], unit_s[2], 0.0], dtype=np.float64)
    compute_tick_s = (activity.astype(np.float64) @ vec).max(axis=1)  # [T]
    T = table.shape[0]
    exposed = np.zeros(T, dtype=np.int64)
    deferred = np.zeros(T, dtype=np.int64)
    for u in range(1, T):
        for ci, (_, col, _) in enumerate(_STORE_CHANNELS):
            if (table[u, :, col] >= 0).any():
                if bank_stages[u, ci] == BANK_BEFORE_F:
                    exposed[u] += 1
                else:
                    deferred[u] += 1
    return exposed * hop_s + np.maximum(compute_tick_s, deferred * hop_s)


def _resolve_correction(correction, hw_name: str):
    """Accept a CorrectionFactors, a {hardware_name: CorrectionFactors}
    mapping (the :func:`..analysis.calibration.load_correction_artifact`
    shape), or None; return the factors for ``hw_name`` or None."""
    if correction is None:
        return None
    if hasattr(correction, "flops_efficiency"):
        return correction
    if hasattr(correction, "get"):
        return correction.get(hw_name)
    return None


def cost_model_section(cs: CompiledSchedule, cfg, *, batch_size: int,
                       seq_length: int,
                       hardware: Optional[HardwareSpec] = None,
                       remat_backward=None,
                       measured_step_s: Optional[float] = None,
                       telemetry=None,
                       table_report=None,
                       comm_overlap: str = "none",
                       correction=None) -> Dict[str, Any]:
    """Price one compiled schedule against a roofline; reconcile with a
    measured run when one is supplied.

    ``telemetry``: a stamped :class:`..utils.telemetry.PipelineTelemetry`
    — supplies ``measured_step_s`` (sum of timeline durations) when not
    given explicitly, and adds the critical-path attribution table
    (compute vs comm vs bubble seconds, straggler stage).
    ``table_report``: a precomputed :class:`.table_check.TableReport`;
    verified fresh via ``check_table`` when absent. ``comm_overlap``
    records the ring-hop discipline the run's executor compiled
    ("none"/"ring") — the ``step_s_comm_overlap`` prediction itself is
    always reported (it prices the table, not the run).
    ``correction``: calibration-fitted efficiency scalars (a
    ``CorrectionFactors`` or the per-hardware mapping
    ``analysis.calibration.load_correction_artifact`` returns) — when
    one matches this run's hardware, ``predicted`` additionally carries
    a ``corrected`` block (every step-time variant re-priced under the
    de-rated roofline) and the measured reconciliation reports both
    ``rel_err`` and ``rel_err_corrected``. Returns the plain dict that
    ``RunReport.attach_cost_model`` embeds."""
    table = cs.table
    T, D = int(table.shape[0]), int(table.shape[1])
    hw = hardware if hardware is not None else detect_hardware()
    policy = resolve_backward_policy(cs, remat_backward)
    w_b, w_w = backward_weights(policy)

    # --- FLOPs per unit: one F unit = one microbatch through one stage
    fwd_tok = fwd_flops_per_token(cfg, seq_length)
    tokens_per_step = float(batch_size) * float(seq_length)
    tokens_per_mb = tokens_per_step / cs.n_microbatches
    unit_f = fwd_tok * tokens_per_mb / cs.n_stages
    unit_b, unit_w = w_b * unit_f, w_w * unit_f
    model_per_step = 3.0 * fwd_tok * tokens_per_step

    activity = table_unit_activity(table)          # [T, D, (F,B,W,idle)]
    counts = activity.sum(axis=(0, 1))             # cells per unit kind
    # hardware FLOPs are table-exact: ZB variants elide stage-0 dgrad,
    # remat recomputes — both show up in the cell counts / weights
    hardware_per_step = (float(counts[0]) * unit_f
                         + float(counts[1]) * unit_b
                         + float(counts[2]) * unit_w)

    # --- comm: activation slab one microbatch moves per ring hop
    bytes_per_hop = (tokens_per_mb * cfg.dim * dtype_bytes(cfg.dtype))
    if table_report is None:
        from .table_check import check_table
        table_report = check_table(cs)
    hops_total = int(table_report.predicted_ppermutes)
    hop_s = bytes_per_hop / hw.ici_bytes_per_s

    # --- roofline: lockstep per-tick max across devices, hops serialized
    # or overlapped — the shared time model (predicted_step_time) the
    # schedule search optimizes, so search objective == reported cost
    unit_sec = (unit_f / hw.peak_flops, unit_b / hw.peak_flops,
                unit_w / hw.peak_flops)
    tm = predicted_step_time(table, unit_sec, hop_s, hops_total)
    ov = comm_overlap_step_time(table, unit_sec, hop_s)
    t_compute_s = tm["compute_s"]
    t_comm_s = tm["comm_s"]
    ideal_compute_s = hardware_per_step / (D * hw.peak_flops)
    step_s_overlapped = tm["step_s_overlapped"]

    # --- bubbles three ways (see module docstring)
    bubble_table_exact = tm["bubble_table_exact"]
    bubble_weighted = (1.0 - ideal_compute_s / t_compute_s
                       if t_compute_s > 0 else 0.0)
    bubble_closed_form = float(analytic_bubble_fraction(
        cs.name, D, cs.n_virtual, cs.n_microbatches, cs=cs))

    section: Dict[str, Any] = {
        "schedule": cs.name,
        "n_devices": D,
        "n_virtual": int(cs.n_virtual),
        "n_microbatches": int(cs.n_microbatches),
        "n_ticks": T,
        "batch_size": int(batch_size),
        "seq_length": int(seq_length),
        "backward_policy": policy,
        "comm_overlap": comm_overlap,
        "hardware": hw.summary(),
        "flops": {
            "fwd_per_token": fwd_tok,
            "train_per_token": 3.0 * fwd_tok,
            "unit": {"F": unit_f, "B": unit_b, "W": unit_w},
            "model_per_step": model_per_step,
            "hardware_per_step": hardware_per_step,
        },
        "comm": {
            "bytes_per_hop": float(bytes_per_hop),
            "hops": hops_total,
            "bytes_total": float(bytes_per_hop) * hops_total,
            "exposed_hops": ov["exposed_hops"],
            "overlappable_hops": ov["overlappable_hops"],
        },
        "predicted": {
            "compute_s": t_compute_s,
            "comm_s": t_comm_s,
            "step_s": t_compute_s + t_comm_s,
            "step_s_overlapped": step_s_overlapped,
            "step_s_comm_overlap": ov["step_s_comm_overlap"],
            "exposed_comm_s": ov["exposed_comm_s"],
            "hidden_comm_s": ov["hidden_comm_s"],
            "ideal_compute_s": ideal_compute_s,
            "bubble_table_exact": bubble_table_exact,
            "bubble_weighted": bubble_weighted,
            "bubble_closed_form": bubble_closed_form,
        },
    }

    corr = _resolve_correction(correction, hw.name)
    if corr is not None:
        # re-price every variant under the de-rated roofline; positive
        # scalings preserve the serial/comm_overlap/overlapped envelope
        e_f = float(corr.flops_efficiency)
        unit_sec_c = tuple(u / e_f for u in unit_sec)
        tm_c = predicted_step_time(
            table, unit_sec_c, hop_s / float(corr.bandwidth_efficiency),
            hops_total)
        ov_c = comm_overlap_step_time(table, unit_sec, hop_s,
                                      correction=corr)
        section["predicted"]["corrected"] = {
            "flops_efficiency": e_f,
            "bandwidth_efficiency": float(corr.bandwidth_efficiency),
            "compute_s": tm_c["compute_s"],
            "comm_s": tm_c["comm_s"],
            "step_s": tm_c["step_s"],
            "step_s_overlapped": tm_c["step_s_overlapped"],
            "step_s_comm_overlap": ov_c["step_s_comm_overlap"],
        }

    if telemetry is not None and getattr(telemetry, "events", None):
        if measured_step_s is None:
            measured_step_s = sum((rec.get("duration_s") or 0.0)
                                  for rec in telemetry.timeline())
        from ..utils.telemetry import critical_path
        cp = critical_path(telemetry)
        section["attribution"] = {
            k: cp[k] for k in ("compute_s", "comm_s", "bubble_s", "total_s",
                               "n_ticks", "straggler_device",
                               "straggler_stage", "straggler_s_per_device")}

    if measured_step_s is not None and measured_step_s > 0:
        chip_s = measured_step_s * D * hw.peak_flops
        measured: Dict[str, Any] = {
            "step_s": float(measured_step_s),
            "tokens_per_sec": tokens_per_step / measured_step_s,
            "mfu": model_per_step / chip_s,
            "hfu": hardware_per_step / chip_s,
            "predicted_over_measured":
                section["predicted"]["step_s"] / measured_step_s,
            # signed relative error, the calibration ledger's headline
            # axis: negative = the roofline is optimistic
            "rel_err": (section["predicted"]["step_s"] - measured_step_s)
                / measured_step_s,
        }
        corrected = section["predicted"].get("corrected")
        if corrected is not None:
            measured["rel_err_corrected"] = \
                (corrected["step_s"] - measured_step_s) / measured_step_s
        if telemetry is not None and getattr(telemetry, "events", None):
            sb = telemetry.stage_breakdown()
            if "bubble_measured_mean" in sb:
                measured["bubble_measured_mean"] = sb["bubble_measured_mean"]
        section["measured"] = measured

    return section


def expected_tokens_per_verify(alpha: float, gamma: int) -> float:
    """Expected emitted tokens per verify forward under greedy
    speculative decoding with per-position acceptance rate ``alpha``
    and draft length ``gamma`` (Leviathan et al., arXiv:2211.17192):

        E[tokens] = (1 - alpha^(gamma+1)) / (1 - alpha)

    i.e. the run-length of i.i.d. accepts plus the free token the
    verify forward always yields. Continuous at the endpoints:
    ``gamma + 1`` as ``alpha -> 1`` and ``1`` at ``alpha = 0``."""
    g = int(gamma)
    if g < 0:
        raise ValueError(f"gamma must be >= 0, got {gamma}")
    a = min(max(float(alpha), 0.0), 1.0)
    if a >= 1.0:
        return float(g + 1)
    return (1.0 - a ** (g + 1)) / (1.0 - a)


def serving_cost_model_section(cfg, n_pipe: int, n_slots: int,
                               summary: Dict[str, Any],
                               hardware: Optional[HardwareSpec] = None,
                               draft_cfg=None, correction=None,
                               ) -> Dict[str, Any]:
    """Cost-model section for a serving run (same manifest schema).

    A decode tick moves one token-slot through each stage and rolls the
    ring once; predicted per-tick time is the roofline on one token's
    stage slice plus one hop of a ``dim``-wide activation row. Measured
    MFU uses forward FLOPs only (decoding trains nothing). ``summary``:
    a ``serving_summary`` dict (ticks, wall_s, tokens_out...).

    When ``summary`` carries the speculative gauges
    (``speculative``/``gamma``/``acceptance_rate``) a ``speculative``
    subsection prices the draft-verify tick: target verify FLOPs over
    ``gamma+1`` rows, draft FLOPs (``draft_cfg``, replicated so not
    divided by the pipe degree) for ``gamma`` proposals, expected
    tokens/tick from the measured acceptance rate, and the predicted
    saturation-knee shift — de-rated through ``correction``
    (calibration-fitted efficiency scalars, same contract as
    :func:`cost_model_section`) when available."""
    hw = hardware if hardware is not None else detect_hardware()
    seq = cfg.max_seq_len
    fwd_tok = fwd_flops_per_token(cfg, seq)
    bytes_per_hop = float(cfg.dim * dtype_bytes(cfg.dtype))
    per_tick_compute_s = fwd_tok / n_pipe / hw.peak_flops
    hop_s = bytes_per_hop / hw.ici_bytes_per_s
    ticks = int(summary.get("ticks") or 0)
    wall_s = float(summary.get("wall_s") or 0.0)
    tokens_out = int(summary.get("tokens_out") or 0)
    section: Dict[str, Any] = {
        "schedule": "serving_ring",
        "n_devices": int(n_pipe),
        "n_virtual": 1,
        "n_microbatches": int(n_slots),
        "n_ticks": ticks,
        "batch_size": int(n_slots),
        "seq_length": int(seq),
        "backward_policy": "none",
        "hardware": hw.summary(),
        "flops": {
            "fwd_per_token": fwd_tok,
            "train_per_token": 0.0,
            "unit": {"F": fwd_tok / n_pipe, "B": 0.0, "W": 0.0},
            "model_per_step": fwd_tok,        # per decoded token
            "hardware_per_step": fwd_tok,
        },
        "comm": {
            "bytes_per_hop": bytes_per_hop,
            # the ring rolls every tick regardless of slot occupancy
            "hops": ticks,
            "bytes_total": bytes_per_hop * ticks,
        },
        "predicted": {
            "compute_s": per_tick_compute_s,
            "comm_s": hop_s,
            "step_s": per_tick_compute_s + hop_s,   # per tick
            "step_s_overlapped": max(per_tick_compute_s, hop_s),
            # the serving ring is still lockstep (arrival consumed at the
            # tick top), so its comm_overlap prediction equals serial
            "step_s_comm_overlap": per_tick_compute_s + hop_s,
            "ideal_compute_s": per_tick_compute_s,
            "bubble_table_exact": 0.0,
            "bubble_weighted": 0.0,
            "bubble_closed_form": 0.0,
        },
    }
    if ticks > 0 and wall_s > 0:
        chip_s = wall_s * n_pipe * hw.peak_flops
        section["measured"] = {
            "step_s": wall_s / ticks,                # per tick
            "tokens_per_sec": tokens_out / wall_s,
            "mfu": tokens_out * fwd_tok / chip_s,
            "hfu": tokens_out * fwd_tok / chip_s,
            "predicted_over_measured":
                section["predicted"]["step_s"] / (wall_s / ticks),
        }

    if summary.get("speculative"):
        gamma = int(summary.get("gamma") or 0)
        alpha = summary.get("acceptance_rate")
        exp_tok = expected_tokens_per_verify(
            alpha if alpha is not None else 0.0, gamma)
        draft_tok = (fwd_flops_per_token(draft_cfg, seq)
                     if draft_cfg is not None else 0.0)
        # verify widens the target forward to gamma+1 rows; the draft is
        # replicated (stage 0 runs it for every slot), so its FLOPs are
        # NOT divided by the pipe degree
        verify_s = (gamma + 1) * fwd_tok / n_pipe / hw.peak_flops
        draft_s = gamma * draft_tok / hw.peak_flops
        base_tick_s = per_tick_compute_s + hop_s
        spec_tick_s = verify_s + draft_s + hop_s
        # tokens/s scale = (tokens per tick gain) / (tick cost gain);
        # offered-load capacity is tokens/s-limited at saturation, so
        # the knee is predicted to shift by the same factor
        knee_scale = (exp_tok / (spec_tick_s / base_tick_s)
                      if base_tick_s > 0 else None)
        spec: Dict[str, Any] = {
            "gamma": gamma,
            "acceptance_rate": alpha,
            "expected_tokens_per_tick": exp_tok,
            "draft_flops_per_token": draft_tok,
            "flops_per_tick": {
                "verify": (gamma + 1) * fwd_tok,
                "draft": gamma * draft_tok,
            },
            "predicted": {
                "tick_s": spec_tick_s,
                "s_per_token": spec_tick_s / exp_tok,
                "baseline_s_per_token": base_tick_s,
                "tokens_per_sec_scale": knee_scale,
                "knee_scale": knee_scale,
            },
        }
        corr = _resolve_correction(correction, hw.name)
        if corr is not None:
            e_f = float(corr.flops_efficiency)
            e_b = float(corr.bandwidth_efficiency)
            c_base = per_tick_compute_s / e_f + hop_s / e_b
            c_tick = (verify_s + draft_s) / e_f + hop_s / e_b
            spec["predicted"]["corrected"] = {
                "tick_s": c_tick,
                "s_per_token": c_tick / exp_tok,
                "knee_scale": (exp_tok / (c_tick / c_base)
                               if c_base > 0 else None),
            }
        section["speculative"] = spec
    return section
