"""Analytical HBM model: the bytes-domain twin of :mod:`.cost_model`.

The tick table prices *time* through :func:`.cost_model.cost_model_section`;
this module prices *memory*, three ways, and reconciles them:

1. **analytic** — per-device bytes built from the static verifier's exact
   slot high-water marks (:class:`.table_check.TableReport`'s
   ``act_live_peak`` / ``grad_live_peak``): the tick executors bank one
   stage-boundary activation slab per slot (``[mb, seq, dim]`` in the
   compute dtype — the same slab the cost model prices a ring hop with),
   so per-device activation bytes are *exactly*
   ``live_peak x slot_bytes`` — an integer identity the test-suite and
   ``analysis.cli --memory`` pin over the whole schedule grid. On top
   ride parameters (pipe-sharded layers + replicated embed/head, shapes
   from ``jax.eval_shape`` so dtype mixes are honest), optimizer state,
   the grads output, and — under the 'stored' backward policy
   (:func:`.cost_model.resolve_backward_policy`) — a first-order
   estimate of the per-layer residuals autodiff keeps live per in-flight
   microbatch (remat/split rematerialize and keep none).
2. **compiled** — XLA's own accounting from an AOT
   ``lower().compile().memory_analysis()`` of the jitted step
   (:func:`..parallel.pipeline.aot_memory_analysis` /
   the serving-block analog): argument / output / temp / alias bytes.
   :func:`reconcile_memory` pins analytic parameter+input bytes against
   the compiled argument bytes (documented tolerance: 10% — layout
   padding and donation are XLA's business, wholesale drift is ours).
3. **live** — ``device.memory_stats()`` watermarks sampled at step
   boundaries by :class:`..utils.telemetry.PipelineTelemetry` (a no-op
   on backends that return ``None``, e.g. CPU), summarized per device
   and drawn as a Perfetto counter track.

All three land in the schema-validated ``memory`` RunReport section
(``attach_memory``) that fit/sweep/bench/serving auto-attach, and the
analytic peak against :attr:`.cost_model.HardwareSpec.hbm_bytes` is the
OOM preflight sweep/bench consult *before* compiling a config
(:func:`oom_preflight`).

Host-side only: ``jax.eval_shape`` for shapes/dtypes, numpy for sums —
no arrays are materialized and no backend is required.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ..parallel.schedules import CompiledSchedule
from .cost_model import (HardwareSpec, detect_hardware, dtype_bytes,
                         resolve_backward_policy)

__all__ = [
    "activation_slot_bytes", "params_bytes", "stored_residual_bytes",
    "memory_model_section", "serving_memory_section",
    "compiled_memory_section", "reconcile_memory", "oom_preflight",
    "memory_probe_axes",
]


def memory_probe_axes(section: Dict[str, Any]
                      ) -> Dict[str, Optional[float]]:
    """The (predicted, measured) peak-bytes pair a calibration ledger row
    records, extracted from a ``memory_model_section`` dict: analytic
    per-device peak on the predicted side, XLA's compiled ``temp_bytes``
    on the measured side (None when the AOT analysis was unavailable or
    degraded to an error row)."""
    analytic = section.get("analytic") or {}
    compiled = section.get("compiled") or {}
    predicted = analytic.get("peak_bytes")
    measured = (compiled.get("temp_bytes")
                if "error" not in compiled else None)
    return {
        "predicted_peak_bytes":
            None if predicted is None else float(predicted),
        "measured_peak_bytes":
            None if measured is None else float(measured),
    }


def _tree_bytes(shapes) -> int:
    """Total bytes of an ``eval_shape`` pytree, per-leaf dtype-aware."""
    import jax
    return sum(int(x.size) * dtype_bytes(str(x.dtype))
               for x in jax.tree.leaves(shapes))


def activation_slot_bytes(cfg, batch_size: int, seq_length: int,
                          n_microbatches: int) -> int:
    """Bytes one activation/grad slot holds: the stage-boundary slab.

    The tick executors' slot buffers are literally ``[n_slots, mb, seq,
    dim]`` arrays in the compute dtype — one microbatch's boundary
    activation (or its cotangent, same shape) per slot. Shaped via
    ``jax.eval_shape`` on the stage partition so the dtype accounting
    cannot drift from the model config. Equal to the cost model's
    ``bytes_per_hop`` (a ring hop moves exactly one slot's contents)."""
    import jax
    import jax.numpy as jnp
    mb = batch_size // n_microbatches
    slab = jax.eval_shape(
        lambda: jnp.zeros((mb, seq_length, cfg.dim), dtype=cfg.dtype))
    return int(slab.size) * dtype_bytes(str(slab.dtype))


def params_bytes(cfg, n_devices: int) -> Dict[str, float]:
    """Per-device parameter bytes under the pipeline sharding.

    Layer stacks are sharded over the pipe axis (one ``L/D`` slice per
    device); embed and head are replicated onto every device (the
    ``fsdp_shard_params`` contract). Shapes and dtypes come from
    ``jax.eval_shape`` of ``transformer_init`` — storage dtype, tied
    embeddings and per-arch head layouts are all honest."""
    import jax

    from ..models import transformer as tfm
    shapes = jax.eval_shape(
        lambda: tfm.transformer_init(jax.random.key(0), cfg))
    layer_b = _tree_bytes(shapes["layers"])
    embed_b = _tree_bytes(shapes["embed"]) + _tree_bytes(shapes["head"])
    n_params = sum(int(x.size) for x in jax.tree.leaves(shapes))
    return {
        "total_bytes": float(layer_b + embed_b),
        "per_device_bytes": float(layer_b) / n_devices + embed_b,
        "layer_bytes": float(layer_b),
        "replicated_bytes": float(embed_b),
        "n_params": int(n_params),
    }


def stored_residual_bytes(cfg, n_stages: int, tokens_per_mb: float) -> float:
    """First-order per-microbatch residual bytes the 'stored' backward
    keeps live per stage: per layer, the residual-stream input/output
    pair plus the MLP hidden (``2*dim + ffn_dim`` values per token, in
    the compute dtype). Remat/split policies recompute these inside the
    backward and keep none. An estimate, not an identity — XLA's
    ``temp_bytes`` is the ground truth it is reconciled against."""
    layers_per_stage = cfg.n_layers / float(n_stages)
    per_layer = tokens_per_mb * (2 * cfg.dim + cfg.ffn_dim)
    return layers_per_stage * per_layer * dtype_bytes(cfg.dtype)


def compiled_memory_section(stats: Optional[Dict[str, Any]]
                            ) -> Optional[Dict[str, Any]]:
    """Normalize an :func:`..parallel.pipeline.aot_memory_analysis`
    result into the manifest's ``compiled`` subsection (pass-through for
    ``{"error": ...}`` degradation rows)."""
    if not stats:
        return None
    if "error" in stats:
        return {"error": str(stats["error"])}
    out = {k: float(stats[k]) for k in
           ("argument_bytes", "output_bytes", "temp_bytes", "alias_bytes",
            "generated_code_bytes") if k in stats}
    out["total_bytes"] = (out.get("argument_bytes", 0.0)
                          + out.get("output_bytes", 0.0)
                          + out.get("temp_bytes", 0.0)
                          - out.get("alias_bytes", 0.0))
    return out


def reconcile_memory(analytic: Dict[str, Any],
                     compiled: Optional[Dict[str, Any]],
                     tolerance: float = 0.10) -> Optional[Dict[str, Any]]:
    """Pin analytic vs compiled where both account the same thing.

    XLA's ``argument_bytes`` is the program's *per-shard* input
    footprint: each device's slice of the parameter tree (layers/D under
    the pipe sharding) plus the replicated token/target (or
    serving-state) operands — the analytic
    ``params_per_device_bytes + input_bytes``. On an unpadded layout the
    two agree to the integer (the CPU-mesh test pins this); layout
    padding gives XLA a few percent of slack on real chips, so ``ok``
    flags relative error within ``tolerance`` (documented at 10%).
    ``temp_bytes`` is reported alongside the analytic activation peak
    for reading, not pinned — XLA fuses/rematerializes inside a tick at
    will."""
    if not compiled or "error" in compiled:
        return None
    expected = float(analytic.get("params_per_device_bytes", 0.0)
                     + analytic.get("input_bytes", 0.0))
    got = float(compiled.get("argument_bytes", 0.0))
    rel = abs(got - expected) / expected if expected > 0 else 0.0
    return {
        "expected_argument_bytes": expected,
        "compiled_argument_bytes": got,
        "argument_rel_err": rel,
        "tolerance": float(tolerance),
        "ok": bool(rel <= tolerance),
        "compiled_temp_bytes": float(compiled.get("temp_bytes", 0.0)),
        "analytic_activation_peak_bytes": float(
            analytic.get("activation_peak_bytes", 0.0)),
    }


def _live_section(telemetry) -> Optional[Dict[str, Any]]:
    if telemetry is None:
        return None
    summary = getattr(telemetry, "memory_summary", None)
    if summary is None:
        return None
    return summary()


def memory_model_section(cs: CompiledSchedule, cfg, *, batch_size: int,
                         seq_length: int,
                         hardware: Optional[HardwareSpec] = None,
                         remat_backward=None,
                         optimizer_slots: int = 0,
                         table_report=None,
                         compiled: Optional[Dict[str, Any]] = None,
                         telemetry=None) -> Dict[str, Any]:
    """Price one compiled schedule's per-device HBM; reconcile with the
    compiled and live accountings when supplied.

    ``optimizer_slots``: fp32 moment buffers per parameter the training
    loop keeps (2 for the ``fit`` AdamW path; 0 for the bare
    loss-and-grads step sweep/bench time). ``table_report``: precomputed
    :class:`.table_check.TableReport` (verified fresh when absent) —
    the source of the exact slot live peaks. ``compiled``: an
    ``aot_memory_analysis`` dict. ``telemetry``: a stamped
    :class:`..utils.telemetry.PipelineTelemetry` with watermark samples.
    Returns the plain dict ``RunReport.attach_memory`` embeds."""
    D = int(cs.table.shape[1])
    hw = hardware if hardware is not None else detect_hardware()
    policy = resolve_backward_policy(cs, remat_backward)
    if table_report is None:
        from .table_check import check_table
        table_report = check_table(cs)

    slot_b = activation_slot_bytes(cfg, batch_size, seq_length,
                                   cs.n_microbatches)
    tokens_per_mb = (batch_size // cs.n_microbatches) * seq_length
    stored_mb_b = (stored_residual_bytes(cfg, cs.n_stages, tokens_per_mb)
                   if policy == "stored" else 0.0)
    pb = params_bytes(cfg, D)
    # sweep/bench/fit steps all return a grads pytree shaped like params;
    # optimizer moments are fp32 regardless of the storage dtype
    grads_dev_b = pb["per_device_bytes"]
    opt_dev_b = optimizer_slots * pb["n_params"] * 4.0 / D \
        if optimizer_slots else 0.0
    # int32 tokens + targets, replicated onto the mesh
    input_b = 2.0 * batch_size * seq_length * 4.0

    act_peaks = [int(p) for p in table_report.act_live_peak]
    grad_peaks = [int(p) for p in table_report.grad_live_peak]
    per_device = []
    for d in range(D):
        act_b = act_peaks[d] * slot_b          # the integer identity
        grad_b = grad_peaks[d] * slot_b
        stored_b = act_peaks[d] * stored_mb_b  # residuals per in-flight mb
        total = (act_b + grad_b + stored_b + pb["per_device_bytes"]
                 + grads_dev_b + opt_dev_b)
        per_device.append({
            "device": d,
            "act_live_peak": act_peaks[d],
            "grad_live_peak": grad_peaks[d],
            "act_bytes": int(act_b),
            "grad_bytes": int(grad_b),
            "stored_residual_bytes": float(stored_b),
            "params_bytes": pb["per_device_bytes"],
            "grads_bytes": grads_dev_b,
            "opt_state_bytes": opt_dev_b,
            "total_bytes": float(total),
        })
    peak = max(pd["total_bytes"] for pd in per_device)
    analytic: Dict[str, Any] = {
        "act_slot_bytes": int(slot_b),
        "grad_slot_bytes": int(slot_b),
        "stored_residual_bytes_per_mb": float(stored_mb_b),
        "params_total_bytes": pb["total_bytes"],
        "params_per_device_bytes": pb["per_device_bytes"],
        "n_params": pb["n_params"],
        "optimizer_slots": int(optimizer_slots),
        "input_bytes": input_b,
        "activation_peak_bytes": float(
            max(a["act_bytes"] + a["grad_bytes"] for a in per_device)),
        "per_device": per_device,
        "peak_bytes": float(peak),
    }
    if hw.hbm_bytes:
        analytic["hbm_frac"] = peak / hw.hbm_bytes

    section: Dict[str, Any] = {
        "schedule": cs.name,
        "n_devices": D,
        "n_virtual": int(cs.n_virtual),
        "n_microbatches": int(cs.n_microbatches),
        "batch_size": int(batch_size),
        "seq_length": int(seq_length),
        "dtype": str(cfg.dtype),
        "param_dtype": str(cfg.storage_dtype),
        "backward_policy": policy,
        "hardware": hw.summary(),
        "analytic": analytic,
    }
    comp = compiled_memory_section(compiled)
    if comp is not None:
        section["compiled"] = comp
        rec = reconcile_memory(analytic, comp)
        if rec is not None:
            section["reconciliation"] = rec
    live = _live_section(telemetry)
    if live is not None:
        section["live"] = live
    return section


def kv_page_bytes(cfg, *, n_devices: int, page_size: int) -> float:
    """Bytes one K+V page pair costs per device (the paged pool's unit
    price): ``2 x layers/D x page_size x n_kv x head_dim x dtype``."""
    lps = cfg.n_layers // n_devices
    n_kv = cfg.n_kv_heads or cfg.n_heads
    return (2.0 * lps * page_size * n_kv * cfg.head_dim
            * dtype_bytes(cfg.dtype))


def kv_slot_bytes(cfg, *, n_devices: int, mlen_alloc: int) -> float:
    """Bytes one contiguous slot's K+V cache costs per device — what
    every slot reserves up front in non-paged serving."""
    lps = cfg.n_layers // n_devices
    n_kv = cfg.n_kv_heads or cfg.n_heads
    return (2.0 * lps * mlen_alloc * n_kv * cfg.head_dim
            * dtype_bytes(cfg.dtype))


def size_page_pool(cfg, *, n_devices: int, page_size: int,
                   budget_bytes: float) -> int:
    """Largest ``n_pages`` (null page 0 included) whose per-device pool
    fits ``budget_bytes`` — the ROADMAP's "oom_preflight bounds
    page-pool sizing" knob. Returns 0 when not even two pages fit (a
    pool needs the null page plus one usable page)."""
    pg_b = kv_page_bytes(cfg, n_devices=n_devices, page_size=page_size)
    n = int(budget_bytes // pg_b)
    return n if n >= 2 else 0


def contiguous_slots_for_budget(cfg, *, n_devices: int, mlen_alloc: int,
                                budget_bytes: float) -> int:
    """How many worst-case contiguous slots the same budget buys — the
    paged-vs-contiguous comparison's matched-budget twin of
    :func:`size_page_pool`."""
    slot_b = kv_slot_bytes(cfg, n_devices=n_devices, mlen_alloc=mlen_alloc)
    return int(budget_bytes // slot_b)


def serving_memory_section(cfg, program, *,
                           hardware: Optional[HardwareSpec] = None,
                           compiled: Optional[Dict[str, Any]] = None,
                           prefix_stats: Optional[Dict[str, Any]] = None
                           ) -> Dict[str, Any]:
    """Memory section for a serving run (same manifest schema).

    Activation state is the ``[D, 1, C, dim]`` ring payload — one slab
    per device, priced as one ``act`` slot of ``C`` tokens. The dominant
    term is the KV cache: contiguous mode prices ``2 x layers/D x
    n_slots x mlen_alloc x n_kv_heads x head_dim`` per device; paged
    mode (``program.paged``) prices the pool ``n_pages x page_size``
    rows instead plus the int32 page table, sized from the same
    expressions ``ServingProgram.init_state`` allocates with.

    ``prefix_stats`` (paged runs; e.g. ``{"hit_rate": h,
    "mean_prompt_len": p, "mean_budget": b}`` from a workload or a
    measured run) adds the expected *demand* discount from prefix
    sharing: a fraction ``h`` of prompt rows is served from shared
    pages, so per-request page demand shrinks by ``h * p / (p + b)`` —
    the pool does not get smaller, it admits proportionally more
    requests."""
    hw = hardware if hardware is not None else detect_hardware()
    D = int(program.n_stages)
    M = int(program.n_slots)
    C = int(program.prefill_chunk)
    lps = cfg.n_layers // D
    n_kv = cfg.n_kv_heads or cfg.n_heads
    dt_b = dtype_bytes(cfg.dtype)
    paged = bool(getattr(program, "paged", False))
    paged_info: Optional[Dict[str, Any]] = None
    if paged:
        pg_b = kv_page_bytes(cfg, n_devices=D, page_size=program.page_size)
        kv_dev_b = program.n_pages * pg_b
        # int32 table + COW command pair, replicated on every device
        tbl_b = 4.0 * M * (program.max_pages_per_slot + 2)
        kv_dev_b += tbl_b
        paged_info = {
            "page_size": int(program.page_size),
            "n_pages": int(program.n_pages),
            "max_pages_per_slot": int(program.max_pages_per_slot),
            "page_bytes_per_device": float(pg_b),
            "pool_bytes_per_device": float(program.n_pages * pg_b),
            "page_table_bytes_per_device": float(tbl_b),
            # what the same bytes would have bought as contiguous slots
            "contiguous_slot_bytes": float(kv_slot_bytes(
                cfg, n_devices=D, mlen_alloc=program.mlen_alloc)),
        }
        if prefix_stats:
            h = float(prefix_stats.get("hit_rate", 0.0))
            p_len = float(prefix_stats.get("mean_prompt_len", 0.0))
            b_len = float(prefix_stats.get("mean_budget", 0.0))
            disc = (h * p_len / (p_len + b_len)
                    if (p_len + b_len) > 0 else 0.0)
            paged_info["expected_sharing_discount"] = round(disc, 6)
            paged_info["effective_capacity_factor"] = (
                round(1.0 / (1.0 - disc), 6) if disc < 1.0 else None)
    else:
        kv_dev_b = (2.0 * lps * M * program.mlen_alloc * n_kv
                    * cfg.head_dim * dt_b)
    slot_b = C * cfg.dim * dt_b
    pb = params_bytes(cfg, D)
    per_device = []
    for d in range(D):
        total = slot_b + kv_dev_b + pb["per_device_bytes"]
        per_device.append({
            "device": d, "act_live_peak": 1, "grad_live_peak": 0,
            "act_bytes": int(slot_b), "grad_bytes": 0,
            "kv_cache_bytes": float(kv_dev_b),
            "params_bytes": pb["per_device_bytes"],
            "opt_state_bytes": 0.0,
            "total_bytes": float(total),
        })
    peak = max(pd["total_bytes"] for pd in per_device)
    analytic: Dict[str, Any] = {
        "act_slot_bytes": int(slot_b),
        "grad_slot_bytes": 0,
        "kv_cache_bytes_per_device": float(kv_dev_b),
        "params_total_bytes": pb["total_bytes"],
        "params_per_device_bytes": pb["per_device_bytes"],
        "n_params": pb["n_params"],
        "optimizer_slots": 0,
        # the serving step takes the state pytree as an operand; the
        # per-device KV slice dominates it, so that is what the
        # (per-shard) argument accounting sees
        "input_bytes": float(kv_dev_b),
        "activation_peak_bytes": float(slot_b),
        "per_device": per_device,
        "peak_bytes": float(peak),
    }
    if hw.hbm_bytes:
        analytic["hbm_frac"] = peak / hw.hbm_bytes
    if paged_info is not None:
        analytic["paged"] = paged_info
    section: Dict[str, Any] = {
        "schedule": "serving_ring",
        "n_devices": D,
        "n_virtual": 1,
        "n_microbatches": M,
        "batch_size": M,
        "seq_length": int(program.max_len),
        "dtype": str(cfg.dtype),
        "param_dtype": str(cfg.storage_dtype),
        "backward_policy": "none",
        "hardware": hw.summary(),
        "analytic": analytic,
    }
    comp = compiled_memory_section(compiled)
    if comp is not None:
        section["compiled"] = comp
        # serving-state aliasing/donation makes the argument pin too
        # loose to assert; report the raw numbers without a verdict
        section["reconciliation"] = {
            "expected_argument_bytes": analytic["params_per_device_bytes"]
            + analytic["input_bytes"],
            "compiled_argument_bytes": comp.get("argument_bytes", 0.0),
        }
    return section


def oom_preflight(section: Dict[str, Any],
                  hardware: Optional[HardwareSpec] = None,
                  headroom: float = 1.0) -> Dict[str, Any]:
    """Price a memory section against the chip's HBM capacity.

    ``ok=False`` means the analytic per-device peak exceeds
    ``headroom x HardwareSpec.hbm_bytes`` — the sweep/bench preflight
    then emits a ``skip_reason="predicted_oom"`` row *before* compiling.
    Unknown capacity (``hbm_bytes == 0``) always passes."""
    hw = hardware if hardware is not None else detect_hardware()
    peak = float(section["analytic"]["peak_bytes"])
    cap = float(hw.hbm_bytes) * headroom
    return {
        "ok": bool(cap <= 0 or peak <= cap),
        "predicted_peak_bytes": peak,
        "hbm_bytes": float(hw.hbm_bytes),
        "headroom": float(headroom),
        "hbm_frac": peak / cap if cap > 0 else None,
    }
