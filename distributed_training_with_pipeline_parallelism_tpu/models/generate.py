"""Autoregressive decoding with a static-shape KV cache.

The reference has no inference path at all (its models are randomly
initialized, trained for throughput measurement, and discarded —
``LLMsDistributedTrainingHelper.py:191-194``); a complete framework needs one.
This module is TPU-first by construction:

- The KV cache is a **fixed-shape** ring of ``[n_layers, B, max_len, kv_heads,
  head_dim]`` buffers updated with ``lax.dynamic_update_slice`` — no growing
  arrays, so the whole decode loop jits once and runs as a single XLA program.
- Prefill and decode share one code path: ``_forward_with_cache`` processes S
  new positions starting at a traced offset (S = prompt length for prefill,
  S = 1 per decode step), attending each query against the full cache under a
  position mask. One implementation, no prefill/decode drift.
- The token loop is a ``lax.scan`` over decode steps (no Python loop, no
  per-step dispatch); sampling (greedy / temperature / top-k / top-p) happens
  on device.

Supports the ``gpt2`` and ``llama`` block families. ``ref_decoder`` is
rejected: the reference model is non-causal with no positional encoding
(SURVEY.md C2), so autoregressive decoding is semantically undefined for it.

Scope note: this module's decode loop runs single-device or GSPMD-TP
(tests/test_generate.py::test_generate_with_tp_sharded_params). Decoding
over a PIPELINE mesh lives in :mod:`..parallel.pipelined_decode`
(round 4): naively pipelining one-token steps would run at 1/D
utilization (each step's compute cannot fill even one stage), so that
executor round-robins M >= D independent batch streams through the
stages — steady-state-full like training microbatches, with the sampled
token riding the same +1 ring home (stage D-1 -> 0 IS the +1 hop).
Batch scoring over a pipe mesh is
``parallel.pipeline.make_pipeline_forward`` (fill-drain, V chunks
supported), and eval losses on any dense training mesh are
``make_pipeline_loss_fn``. For models too big for one chip at decode
time, TP (here) splits the bandwidth-bound weight reads; pipelined
decode splits the model depth-wise with the same stage slicing as
training.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops.attention import (apply_rope, gqa_expand, rope_frequencies,
                             scaled_dot_attention)
from ..ops.layers import (embedding_apply, layer_norm_apply, linear_apply,
                          rms_norm_apply)
from ..utils.config import ModelConfig
from .transformer import head_apply, mlp_block

Pytree = Dict


def init_cache(cfg: ModelConfig, batch_size: int, max_len: int,
               dtype=None) -> Pytree:
    """Allocate an all-zeros KV cache: leaves [n_layers, B, max_len, Hkv, hd]."""
    n_kv = cfg.n_kv_heads or cfg.n_heads
    shape = (cfg.n_layers, batch_size, max_len, n_kv, cfg.head_dim)
    dtype = dtype or jnp.dtype(cfg.dtype)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def _attend_cached(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                   offset: jax.Array, n_heads: int,
                   window: Optional[int] = None) -> jax.Array:
    """Attention of S new queries against the full cached sequence.

    q: [B, S, H, hd] at global positions offset..offset+S-1;
    k_cache/v_cache: [B, T, Hkv, hd]. A key at cache index j is visible to the
    query at global position i iff j <= i — which simultaneously enforces
    causality inside the new block and masks the unwritten cache tail.
    """
    from ..ops.attention import band_mask
    k_cache, v_cache = gqa_expand(k_cache, v_cache, n_heads)
    s, t = q.shape[1], k_cache.shape[1]
    mask = band_mask(s, t, window, q_offset=offset)[None, None]
    out = scaled_dot_attention(q, k_cache, v_cache, mask)
    return out.reshape(q.shape[0], s, -1)


def _layer_step(cfg: ModelConfig, lp: Pytree, h: jax.Array, k_cache: jax.Array,
                v_cache: jax.Array, offset: jax.Array,
                rope_slice: Optional[jax.Array],
                tp_axis: Optional[str] = None, tp_size: int = 1,
                prefill: bool = False
                ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One block over S new positions; writes their k/v into the cache at
    ``offset`` and returns (h_out, k_cache, v_cache).

    ``tp_axis`` (round 5, inside shard_map only) runs the block
    Megatron-sharded over that mesh axis: q/k/v column-parallel (local
    head shards — the KV cache holds ``Hkv/tp_size`` heads per model
    rank), o and the MLP down-projection row-parallel with one psum each.
    Decode is where TP shines — small batch, weight-read bound — and the
    weight reads split ``tp_size`` ways.

    ``prefill=True`` is a STATIC promise by the caller that ``offset`` is
    zero and the cache holds nothing before this call — the S new
    positions are the whole sequence, so their attention is plain causal
    self-attention over the new block. Under that promise the call is
    eligible for the Pallas flash kernel with the training path's exact
    fallback discipline (``cfg.flash_for``: 'auto' = causal TPU
    sequences >= 1024, dense elsewhere); sites with traced offsets —
    decode steps, the serving engine's chunked prefill — must keep the
    default and stay on the cached dense path."""
    b, s, _ = h.shape
    n_heads = cfg.n_heads // tp_size
    n_kv = (cfg.n_kv_heads or cfg.n_heads) // tp_size
    if cfg.arch == "gpt2":
        a = layer_norm_apply(lp["ln1"], h)
    else:
        a = rms_norm_apply(lp["rms1"], h, cfg.rms_eps)
    ap = lp["attn"]
    q = linear_apply(ap["q"], a).reshape(b, s, n_heads, cfg.head_dim)
    k = linear_apply(ap["k"], a).reshape(b, s, n_kv, cfg.head_dim)
    v = linear_apply(ap["v"], a).reshape(b, s, n_kv, cfg.head_dim)
    if rope_slice is not None:
        q = apply_rope(q, rope_slice)
        k = apply_rope(k, rope_slice)
    k_cache = jax.lax.dynamic_update_slice(k_cache, k.astype(k_cache.dtype),
                                           (0, offset, 0, 0))
    v_cache = jax.lax.dynamic_update_slice(v_cache, v.astype(v_cache.dtype),
                                           (0, offset, 0, 0))
    if prefill and cfg.flash_for(True, s):
        # the new block IS the whole visible sequence (offset==0 promise),
        # so attend q against the pre-cache k/v through the flash kernel —
        # the cached tail is all masked zeros either way
        from ..ops.pallas_attention import flash_attention
        kf, vf = gqa_expand(k, v, n_heads)
        att = flash_attention(q, kf, vf, causal=True,
                              window=cfg.sliding_window).reshape(b, s, -1)
    else:
        att = _attend_cached(q, k_cache, v_cache, offset, n_heads,
                             cfg.sliding_window)
    if tp_axis is None:
        attn = linear_apply(ap["o"], att)
    else:
        from ..ops.collectives import tp_output_projection
        attn = tp_output_projection(ap["o"], att, tp_axis)
    return (mlp_block(cfg, lp, h + attn, tp_axis=tp_axis, tp_size=tp_size),
            k_cache, v_cache)


def _embed_at(cfg: ModelConfig, embed: Pytree, tokens: jax.Array,
              offset: jax.Array) -> jax.Array:
    """Embed S new tokens at global positions offset..offset+S-1 (decode
    twin of the training-path embed — gpt2 needs pos[offset:offset+s],
    not embed_apply's [:s])."""
    from .transformer import embed_apply
    if cfg.arch == "gpt2":
        h = embedding_apply(embed["tok"], tokens)
        if cfg.embed_scale:  # MoE-LM Gemma convention: scale precedes pos
            h = h * (cfg.dim ** 0.5)
        pos = jax.lax.dynamic_slice_in_dim(embed["pos"], offset,
                                           tokens.shape[1])
        return h + pos
    # the training-path embed (incl. Gemma's sqrt(dim) scaling) — shared
    # so decode cannot drift from train/eval
    return embed_apply(cfg, embed, tokens)


def rope_slice_at(cfg: ModelConfig, max_len: int, offset: jax.Array,
                  s: int) -> Optional[jax.Array]:
    """RoPE angles for S new positions starting at ``offset`` (None for
    non-RoPE archs)."""
    if cfg.arch != "llama":
        return None
    angles = rope_frequencies(cfg.head_dim, max_len, cfg.rope_theta,
                              cfg.rope_scaling)
    return jax.lax.dynamic_slice_in_dim(angles, offset, s)


def layers_with_cache(cfg: ModelConfig, layers: Pytree, h: jax.Array,
                      k_cache: jax.Array, v_cache: jax.Array,
                      offset: jax.Array, rope_slice: Optional[jax.Array],
                      tp_axis: Optional[str] = None, tp_size: int = 1,
                      prefill: bool = False
                      ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Scan a stack of blocks over S new positions with per-layer KV
    caches [L, B, T, Hkv(/tp_size), hd]. Shared by the single-device
    decode and the pipelined decode's stage bodies (each stage passes its
    layer slice and cache shard; with ``tp_axis`` the layer leaves are
    Megatron model-axis shards). ``prefill`` flags statically-zero-offset
    fresh-cache calls as flash-eligible (see :func:`_layer_step`)."""
    def body(carry, xs):
        lp, kc, vc = xs
        h, kc, vc = _layer_step(cfg, lp, carry, kc, vc, offset, rope_slice,
                                tp_axis=tp_axis, tp_size=tp_size,
                                prefill=prefill)
        return h, (kc, vc)

    return jax.lax.scan(body, h, (layers, k_cache, v_cache))


def _forward_with_cache(cfg: ModelConfig, params: Pytree, cache: Pytree,
                        tokens: jax.Array, offset: jax.Array,
                        prefill: bool = False
                        ) -> Tuple[jax.Array, Pytree]:
    """Run S new tokens (global positions offset..offset+S-1) through the model.

    Returns (last-position logits [B, V], updated cache). Serves as both
    prefill (offset=0, S=prompt_len, pass ``prefill=True`` for the flash
    fast path) and decode step (S=1).
    """
    if cfg.arch not in ("gpt2", "llama"):
        raise ValueError(
            f"generation is undefined for arch {cfg.arch!r}: the reference "
            "block is non-causal with no positional encoding (SURVEY.md C2)")
    from .transformer import compute_cast
    params = compute_cast(cfg, params)  # decode in the compute dtype too
    b, s = tokens.shape
    h = _embed_at(cfg, params["embed"], tokens, offset)
    rope_slice = rope_slice_at(cfg, cache["k"].shape[2], offset, s)
    h, (k_new, v_new) = layers_with_cache(cfg, params["layers"], h,
                                          cache["k"], cache["v"], offset,
                                          rope_slice)
    logits = head_apply(cfg, params["head"], h[:, -1:],
                        embed=params["embed"])[:, 0]
    return logits, {"k": k_new, "v": v_new}


def token_logprob(cfg: ModelConfig, logits: jax.Array,
                  tok: jax.Array) -> jax.Array:
    """Log-probability [B] f32 of the chosen token ``tok`` [B] under
    ``logits`` [B, V] — the decode-path twin of the training loss core:
    ``cfg.use_fused_xent`` routes through the Pallas fused-NLL kernel
    (``ops.pallas_xent``, which never materializes the [B, V]
    log-softmax), the default through the XLA formulation. Identical
    values either way (the kernel is tested against the formulation)."""
    if cfg.use_fused_xent:
        from ..ops.pallas_xent import fused_softmax_xent
        return -fused_softmax_xent(logits, tok.astype(jnp.int32))
    logz = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.take_along_axis(logz, tok.astype(jnp.int32)[:, None],
                               axis=-1)[:, 0]


def sample_logits(key: Optional[jax.Array], logits: jax.Array,
                  temperature: float = 0.0, top_k: Optional[int] = None,
                  top_p: Optional[float] = None) -> jax.Array:
    """Draw next-token ids [B] from logits [B, V].

    temperature=0 is greedy argmax (no key needed); otherwise categorical
    sampling after temperature scaling, optional top-k truncation, and
    optional top-p (nucleus) truncation.
    """
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits.astype(jnp.float32) / temperature
    if top_k is not None:
        top_k = min(top_k, logits.shape[-1])
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        # smallest prefix with mass >= top_p: cut at the last logit whose
        # *preceding* (exclusive) cumulative mass is < top_p
        exclusive_cdf = jnp.cumsum(probs, axis=-1) - probs
        cutoff_idx = jnp.sum(exclusive_cdf < top_p, axis=-1) - 1
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None], axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1)


def generate(cfg: ModelConfig, params: Pytree, prompt: jax.Array,
             max_new_tokens: int, *, key: Optional[jax.Array] = None,
             temperature: float = 0.0, top_k: Optional[int] = None,
             top_p: Optional[float] = None,
             max_len: Optional[int] = None,
             eos_id: Optional[int] = None,
             return_lengths: bool = False,
             return_logprobs: bool = False) -> jax.Array:
    """Generate ``max_new_tokens`` continuations of ``prompt`` [B, P].

    Returns [B, P + max_new_tokens]. Pure and jittable (see
    :func:`make_generate_fn` for the pre-jitted closure); the decode loop is a
    single ``lax.scan``.

    With ``eos_id`` decoding is EOS-aware while keeping every shape
    static: once a row emits ``eos_id`` it is *frozen* — its KV-cache
    writes are masked (``jnp.where`` keeps the old cache bit-for-bit)
    and every subsequent emitted token is forced to ``eos_id``. With
    ``return_lengths=True`` (requires ``eos_id``) returns
    ``(tokens [B, P+N], lengths [B])`` where ``lengths`` counts emitted
    tokens per row including the EOS itself (N when no EOS appeared).
    These are exactly the freeze semantics of the pipelined decoder and
    the serving executor, so all three stay token-for-token comparable.

    With ``return_logprobs=True`` the result additionally carries the
    emitted tokens' log-probabilities [B, N] f32 (appended last), each
    computed from the same logits its token was sampled from through
    :func:`token_logprob` (``cfg.use_fused_xent`` routes the Pallas
    fused-NLL kernel). EOS-frozen rows report 0.0 for their forced
    tokens — forced, not sampled — matching the pipelined decoder.
    """
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if return_lengths and eos_id is None:
        raise ValueError("return_lengths=True requires an eos_id (without "
                         "one every row emits exactly max_new_tokens)")
    b, p = prompt.shape
    total = p + max_new_tokens
    max_len = max_len or total
    if total > max_len:
        raise ValueError(f"prompt ({p}) + max_new_tokens ({max_new_tokens}) "
                         f"exceeds max_len ({max_len})")
    if cfg.arch == "gpt2" and total > cfg.max_seq_len:
        # past the learned position table, dynamic_slice would clamp and
        # silently reuse the last position's embedding
        raise ValueError(f"prompt ({p}) + max_new_tokens ({max_new_tokens}) "
                         f"exceeds the gpt2 position table "
                         f"(max_seq_len={cfg.max_seq_len})")
    if temperature != 0.0 and key is None:
        raise ValueError("sampling (temperature != 0) requires a PRNG key")
    cache = init_cache(cfg, b, max_len)
    logits, cache = _forward_with_cache(cfg, params, cache, prompt,
                                        jnp.int32(0), prefill=True)
    keys = jax.random.split(key if key is not None else jax.random.key(0),
                            max_new_tokens)
    first = sample_logits(keys[0], logits, temperature, top_k, top_p)

    lps = None
    if not return_logprobs:
        if eos_id is None:
            def step(carry, step_key):
                cache, tok, pos = carry
                logits, cache = _forward_with_cache(cfg, params, cache,
                                                    tok[:, None], pos)
                nxt = sample_logits(step_key, logits, temperature, top_k,
                                    top_p)
                return (cache, nxt, pos + 1), tok

            (_, last, _), toks = jax.lax.scan(
                step, (cache, first, jnp.int32(p)), keys[1:])
        else:
            # a row is done once the token it is ABOUT to consume is EOS —
            # that token's KV never enters the cache and all later emissions
            # are forced to eos_id (same freeze rule as pipelined_decode)
            def step(carry, step_key):
                cache, tok, pos, done = carry
                logits, cache2 = _forward_with_cache(cfg, params, cache,
                                                     tok[:, None], pos)
                m = done[None, :, None, None, None]
                cache = jax.tree.map(lambda old, new: jnp.where(m, old, new),
                                     cache, cache2)
                nxt = sample_logits(step_key, logits, temperature, top_k,
                                    top_p)
                nxt = jnp.where(done, jnp.asarray(eos_id, nxt.dtype), nxt)
                return (cache, nxt, pos + 1, done | (nxt == eos_id)), tok

            done0 = first == eos_id
            (_, last, _, _), toks = jax.lax.scan(
                step, (cache, first, jnp.int32(p), done0), keys[1:])
    else:
        # same loops with the token's logprob riding the carry; kept as a
        # separate Python branch so the default jaxpr is untouched
        lp0 = token_logprob(cfg, logits, first)
        if eos_id is None:
            def step(carry, step_key):
                cache, tok, lp, pos = carry
                logits, cache = _forward_with_cache(cfg, params, cache,
                                                    tok[:, None], pos)
                nxt = sample_logits(step_key, logits, temperature, top_k,
                                    top_p)
                return (cache, nxt, token_logprob(cfg, logits, nxt),
                        pos + 1), (tok, lp)

            (_, last, last_lp, _), (toks, lp_toks) = jax.lax.scan(
                step, (cache, first, lp0, jnp.int32(p)), keys[1:])
        else:
            def step(carry, step_key):
                cache, tok, lp, pos, done = carry
                logits, cache2 = _forward_with_cache(cfg, params, cache,
                                                     tok[:, None], pos)
                m = done[None, :, None, None, None]
                cache = jax.tree.map(lambda old, new: jnp.where(m, old, new),
                                     cache, cache2)
                nxt = sample_logits(step_key, logits, temperature, top_k,
                                    top_p)
                # frozen rows emit FORCED eos, not a sample: logprob 0.0
                nlp = jnp.where(done, 0.0, token_logprob(cfg, logits, nxt))
                nxt = jnp.where(done, jnp.asarray(eos_id, nxt.dtype), nxt)
                return (cache, nxt, nlp, pos + 1,
                        done | (nxt == eos_id)), (tok, lp)

            done0 = first == eos_id
            (_, last, last_lp, _, _), (toks, lp_toks) = jax.lax.scan(
                step, (cache, first, lp0, jnp.int32(p), done0), keys[1:])
        lps = jnp.concatenate([jnp.moveaxis(lp_toks, 0, 1),
                               last_lp[:, None]], axis=1)

    new = jnp.concatenate([jnp.moveaxis(toks, 0, 1), last[:, None]], axis=1)
    out = jnp.concatenate([prompt, new.astype(prompt.dtype)], axis=1)
    res = (out,)
    if return_lengths:
        hit = new == eos_id
        lengths = jnp.where(hit.any(axis=1), jnp.argmax(hit, axis=1) + 1,
                            max_new_tokens).astype(jnp.int32)
        res = res + (lengths,)
    if return_logprobs:
        res = res + (lps,)
    return res if len(res) > 1 else out


def make_generate_fn(cfg: ModelConfig, max_new_tokens: int, *,
                     temperature: float = 0.0, top_k: Optional[int] = None,
                     top_p: Optional[float] = None,
                     max_len: Optional[int] = None,
                     eos_id: Optional[int] = None,
                     return_lengths: bool = False,
                     return_logprobs: bool = False):
    """Jitted (params, prompt, key) -> tokens closure over the static knobs."""
    fn = functools.partial(generate, cfg, max_new_tokens=max_new_tokens,
                           temperature=temperature, top_k=top_k, top_p=top_p,
                           max_len=max_len, eos_id=eos_id,
                           return_lengths=return_lengths,
                           return_logprobs=return_logprobs)
    return jax.jit(lambda params, prompt, key=None: fn(params, prompt, key=key))
