"""Mixture-of-Experts feed-forward layers and a MoE decoder LM.

The reference is dense-FFN only (SURVEY.md §2.4, EP row: "NO — dense FFN
only (`nn.TransformerDecoderLayer`)"), so this module is beyond-parity
capability. The design is TPU-first throughout:

- **Capacity-based routing** (GShard, arXiv:2006.16668; Switch,
  arXiv:2101.03961): every shape is static under jit. Each expert processes
  exactly ``capacity`` token slots; dispatch and combine are dense one-hot
  tensors so the whole layer is four einsums that tile onto the MXU —
  no gather/scatter, no dynamic shapes, no host control flow.
- **Top-k token-choice gating** with per-slot priority: slot-0 assignments
  of all tokens beat slot-1 assignments, positions within an expert queue
  come from a cumulative sum, and tokens past capacity are dropped (their
  combine weight is zero — the residual stream carries them unchanged).
- **Load-balancing auxiliary loss** (Switch §2.2): ``E * Σ_e f_e · p_e``
  where ``f_e`` is the fraction of tokens whose top-1 choice is expert e
  and ``p_e`` the mean router probability — minimized (=1) at uniform load.
- **Expert parallelism**: pass ``axis_name`` to run with experts sharded
  over a mesh axis; token slots travel to their experts and back via two
  ``jax.lax.all_to_all`` collectives (see
  :mod:`..parallel.expert_parallel`). With ``axis_name=None`` the same
  math runs unsharded — the correctness oracle the EP path is tested
  against.

The router always computes in float32 (bf16 softmax over experts is the
classic MoE instability).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops.attention import mha_apply, mha_init
from ..ops.layers import (select_xent, embedding_apply, embedding_init,
                          layer_norm_apply, layer_norm_init, linear_apply,
                          linear_init)
from ..utils.config import ModelConfig


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    """Routing hyperparameters for MoE FFN layers.

    ``capacity_factor`` scales each expert's token-slot budget
    ``C = ceil(top_k * T * capacity_factor / n_experts)``; set it to
    ``n_experts`` to guarantee zero drops (used by the EP-vs-dense
    equivalence tests). ``ffn_dim=None`` inherits the model's dense
    ``ffn_dim``.
    """

    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    ffn_dim: Optional[int] = None

    def __post_init__(self):
        if self.top_k < 1 or self.top_k > self.n_experts:
            raise ValueError(f"top_k={self.top_k} must be in [1, {self.n_experts}]")

    def capacity(self, n_tokens: int) -> int:
        return max(1, math.ceil(self.top_k * n_tokens * self.capacity_factor
                                / self.n_experts))


# ---------------------------------------------------------------------------
# Routing
# ---------------------------------------------------------------------------


def route(probs: jax.Array, top_k: int, capacity: int,
          ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Token-choice top-k routing with per-expert capacity.

    probs: [T, E] router probabilities (float32). Returns
    ``(dispatch, combine, aux)`` where dispatch/combine are [T, E, C]
    (dispatch is combine's nonzero indicator; combine carries renormalized
    gate weights) and ``aux`` is the Switch load-balancing scalar.
    """
    T, E = probs.shape
    gate, idx = jax.lax.top_k(probs, top_k)  # [T, k]
    gate = gate / jnp.sum(gate, axis=-1, keepdims=True)
    onehot = jax.nn.one_hot(idx, E, dtype=probs.dtype)  # [T, k, E]
    # Queue positions: priority is (slot, token) lexicographic — every
    # token's first choice outranks any token's second choice.
    flat = onehot.transpose(1, 0, 2).reshape(top_k * T, E)
    pos = jnp.cumsum(flat, axis=0) - flat
    pos = pos.reshape(top_k, T, E).transpose(1, 0, 2)  # [T, k, E]
    keep = onehot * (pos < capacity)
    pos_onehot = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                                dtype=probs.dtype)  # [T, k, E, C]
    combine = jnp.einsum("tk,tke,tkec->tec", gate, keep, pos_onehot)
    dispatch = (combine > 0).astype(probs.dtype)
    top1 = onehot[:, 0]  # [T, E]
    aux = E * jnp.sum(jnp.mean(top1, axis=0) * jnp.mean(probs, axis=0))
    return dispatch, combine, aux


# ---------------------------------------------------------------------------
# MoE FFN layer
# ---------------------------------------------------------------------------


def moe_ffn_init(key: jax.Array, dim: int, ffn_dim: int, n_experts: int) -> Dict:
    kr, k1, k2 = jax.random.split(key, 3)
    b1 = 1.0 / math.sqrt(dim)
    b2 = 1.0 / math.sqrt(ffn_dim)
    return {
        "router": {"w": jax.random.uniform(kr, (dim, n_experts),
                                           minval=-b1, maxval=b1)},
        "w1": jax.random.uniform(k1, (n_experts, dim, ffn_dim),
                                 minval=-b1, maxval=b1),
        "b1": jnp.zeros((n_experts, ffn_dim)),
        "w2": jax.random.uniform(k2, (n_experts, ffn_dim, dim),
                                 minval=-b2, maxval=b2),
        "b2": jnp.zeros((n_experts, dim)),
    }


def _expert_mlp(params: Dict, x: jax.Array,
                tp_axis: Optional[str] = None) -> jax.Array:
    """Per-expert gelu MLP on [E_local, N, d] slot blocks (batched einsums).

    With ``tp_axis`` the expert matrices are Megatron-split over that mesh
    axis — w1/b1 column-parallel on the ffn dim, w2 row-parallel with one
    psum completing the partial outputs and b2 (replicated) added once —
    exactly the dense ``lin1``/``lin2`` pattern, batched over experts."""
    if tp_axis is not None:
        from ..ops.collectives import tp_copy, tp_reduce
        x = tp_copy(x, tp_axis)
    h = jnp.einsum("end,edf->enf", x, params["w1"]) + params["b1"][:, None]
    out = jnp.einsum("enf,efd->end", jax.nn.gelu(h), params["w2"])
    if tp_axis is not None:
        out = tp_reduce(out, tp_axis)
    return out + params["b2"][:, None]


def moe_ffn_apply(params: Dict, x: jax.Array, moe: MoEConfig,
                  axis_name: Optional[str] = None,
                  tp_axis: Optional[str] = None,
                  ) -> Tuple[jax.Array, jax.Array]:
    """MoE FFN on [B, S, d] activations -> ([B, S, d], aux loss scalar).

    With ``axis_name`` set (inside shard_map), experts are sharded over that
    mesh axis (leading expert dim of w1/b1/w2/b2 is the local shard) and
    token slots route through two ``all_to_all`` hops:

        dispatch [E, C, d] -> a2a -> local experts on [G, D*C, d] -> a2a back

    Tokens (the batch) are sharded over the same axis, so routing state
    (dispatch/combine/capacity) is per-shard — standard local load balancing.
    """
    B, S, d = x.shape
    xt = x.reshape(B * S, d)
    E = moe.n_experts
    logits = xt.astype(jnp.float32) @ params["router"]["w"].astype(jnp.float32)
    C = moe.capacity(B * S)
    dispatch, combine, aux = route(jax.nn.softmax(logits, axis=-1),
                                   moe.top_k, C)
    dispatch = dispatch.astype(x.dtype)
    combine = combine.astype(x.dtype)

    slots = jnp.einsum("tec,td->ecd", dispatch, xt)  # [E, C, d]
    if axis_name is None:
        if params["w1"].shape[0] != E:
            raise ValueError(
                f"params hold {params['w1'].shape[0]} experts, config says {E} "
                f"(running an expert-sharded pytree without axis_name?)")
        out = _expert_mlp(params, slots, tp_axis)  # [E, C, d]
    else:
        D = jax.lax.psum(1, axis_name)
        G = params["w1"].shape[0]  # local experts
        if G * D != E:
            raise ValueError(f"{G} local experts x {D} shards != {E}")
        send = slots.reshape(D, G, C, d)
        recv = jax.lax.all_to_all(send, axis_name, 0, 0)  # [D_src, G, C, d]
        hid = recv.transpose(1, 0, 2, 3).reshape(G, D * C, d)
        hid = _expert_mlp(params, hid, tp_axis)
        back = hid.reshape(G, D, C, d).transpose(1, 0, 2, 3)
        out = jax.lax.all_to_all(back, axis_name, 0, 0).reshape(E, C, d)
    y = jnp.einsum("tec,ecd->td", combine, out)
    return y.reshape(B, S, d), aux


# ---------------------------------------------------------------------------
# MoE decoder LM (gpt2-style blocks with MoE FFN)
# ---------------------------------------------------------------------------


def moe_layer_init(key: jax.Array, cfg: ModelConfig, moe: MoEConfig) -> Dict:
    ka, km = jax.random.split(key)
    return {
        "ln1": layer_norm_init(cfg.dim),
        "attn": mha_init(ka, cfg.dim, cfg.n_heads),
        "ln2": layer_norm_init(cfg.dim),
        "moe": moe_ffn_init(km, cfg.dim, moe.ffn_dim or cfg.ffn_dim,
                            moe.n_experts),
    }


def moe_layer_apply(cfg: ModelConfig, moe: MoEConfig, params: Dict,
                    h: jax.Array, axis_name: Optional[str] = None,
                    tp_axis: Optional[str] = None,
                    tp_size: int = 1,
                    rng: Optional[jax.Array] = None,
                    sp_axis: Optional[str] = None,
                    sp_attn_impl: str = "ring",
                    sp_size: int = 1,
                    ) -> Tuple[jax.Array, jax.Array]:
    """One MoE decoder block. ``axis_name`` shards experts (EP);
    ``tp_axis``/``tp_size`` additionally Megatron-shards the attention
    heads and each expert's ffn dim over the model axis — EP moves whole
    experts across devices, TP splits every expert's matmuls, and the two
    compose (each expert shard group runs its ffn slice).

    ``sp_axis`` (round 5) runs the block with the SEQUENCE sharded over
    that mesh axis: attention goes through the ring/Ulysses transport
    (``sp_attn_impl``) exactly as dense seq-parallel stages do, while the
    MoE FFN — position-wise by construction — routes each shard's LOCAL
    tokens with local capacity, the same local-routing semantics the EP
    path already uses for its batch sharding (capacity is computed from
    the local token count, so routing statistics are per-shard). No new
    collective: the expert all_to_all stays on the expert axis.

    ``rng`` (train mode, round 4) enables dropout at the dense gpt2
    block's sites: attention probabilities (stream 0), the attention
    residual (1), and the MoE-FFN residual (2). The FFN mask lands on the
    COMBINED expert output — position-wise on [B, S, d] — not on
    per-expert slot blocks, so it is invariant to the EP/TP partitioning
    by construction (no per-expert-slot mask streams needed) and follows
    the same (key, shard, microbatch, layer, site) convention as the
    dense executor (tests/test_moe_pipeline.py asserts the partition
    invariance). With ``sp_axis`` the residual/FFN masks are the
    full-sequence masks' local slices (``sharded_dropout_apply`` over
    dim 1, the dense sp path's rule), so a seq-sharded run reproduces
    the unsharded masks exactly; attention-prob masks follow the
    transport's own convention (Ulysses: oracle-exact post-scatter head
    blocks; ring: blockwise global-coordinate masks)."""
    from ..ops.layers import sharded_dropout_apply
    p = cfg.dropout if rng is not None else 0.0

    def site(i: int) -> Optional[jax.Array]:
        return None if rng is None else jax.random.fold_in(rng, i)

    def drop(x, i):
        # plain dropout_apply when sp_axis is None (the helper's own
        # fallback), local mask slices when seq-sharded
        return sharded_dropout_apply(x, p, site(i), axis=sp_axis,
                                     n_shards=sp_size, shard_dim=1)

    a = layer_norm_apply(params["ln1"], h)
    if sp_axis is not None:
        from ..parallel.seq_parallel import ATTN_IMPLS
        attn = ATTN_IMPLS[sp_attn_impl](
            params["attn"], a, a, cfg.n_heads // tp_size, sp_axis,
            causal=True, tp_axis=tp_axis, dropout_rate=p,
            dropout_rng=site(0))
    else:
        attn = mha_apply(params["attn"], a, a, cfg.n_heads // tp_size,
                         causal=True, tp_axis=tp_axis, tp_size=tp_size,
                         dropout_rate=p, dropout_rng=site(0))
    h = h + drop(attn, 1)
    m = layer_norm_apply(params["ln2"], h)
    y, aux = moe_ffn_apply(params["moe"], m, moe, axis_name, tp_axis)
    return h + drop(y, 2), aux


def moe_lm_init(key: jax.Array, cfg: ModelConfig, moe: MoEConfig) -> Dict:
    ke, kp, kl, ko = jax.random.split(key, 4)
    embed = {
        "tok": embedding_init(ke, cfg.vocab_size, cfg.dim),
        "pos": 0.02 * jax.random.normal(kp, (cfg.max_seq_len, cfg.dim)),
    }
    layers = jax.vmap(lambda k: moe_layer_init(k, cfg, moe))(
        jax.random.split(kl, cfg.n_layers))
    # tied embeddings (round 4): like transformer_init, the head is only
    # the norm — the vocab matmul reuses embed["tok"] (head_apply)
    head = {"norm": layer_norm_init(cfg.dim)}
    if not cfg.tie_embeddings:
        head["out"] = linear_init(ko, cfg.dim, cfg.vocab_size, bias=False)
    params = {"embed": embed, "layers": layers, "head": head}
    dtype = jnp.dtype(cfg.dtype)
    if dtype != jnp.float32:
        params = jax.tree.map(lambda x: x.astype(dtype), params)
    return params


def moe_lm_logits_aux(cfg: ModelConfig, moe: MoEConfig, params: Dict,
                      tokens: jax.Array,
                      axis_name: Optional[str] = None):
    """MoE LM forward: -> (logits [B, S, V], summed per-layer aux loss).
    The shared core of :func:`moe_lm_loss` and test oracles. With
    ``cfg.tie_embeddings`` the vocab matmul reuses the embedding table
    (round 4 — the pipeline executor's MoE stages share the same
    ``_stage_ce`` tied-head path)."""
    h = embedding_apply(params["embed"]["tok"], tokens)
    if cfg.embed_scale:
        # Gemma convention (models.transformer.embed_apply): embedding
        # OUTPUTS scale by sqrt(dim) while the tied head keeps the
        # unscaled table; scale before the positional rows so those stay
        # unscaled too (matching seq_parallel.sp_embed_apply's order)
        h = h * (cfg.dim ** 0.5)
    h = h + params["embed"]["pos"][: tokens.shape[1]]
    h = h.astype(jnp.dtype(cfg.dtype))

    def step(carry, layer_params):
        h, aux = carry
        h, a = moe_layer_apply(cfg, moe, layer_params, h, axis_name)
        return (h, aux + a), None

    (h, aux), _ = jax.lax.scan(step, (h, jnp.zeros((), jnp.float32)),
                               params["layers"])
    from .transformer import head_apply
    logits = head_apply(cfg, params["head"], h,
                        embed=params["embed"] if cfg.tie_embeddings
                        else None)
    return logits, aux


def moe_lm_loss(cfg: ModelConfig, moe: MoEConfig, params: Dict,
                tokens: jax.Array, targets: jax.Array,
                axis_name: Optional[str] = None) -> jax.Array:
    """CE loss + mean per-layer aux loss. Differentiable; works unsharded
    (``axis_name=None``) or inside the EP shard_map (tokens batch-sharded,
    experts sharded — :func:`..parallel.expert_parallel.make_ep_loss_fn`).

    With ``cfg.pad_token_id`` the CE normalizes by the (axis-global) valid
    count; the routing aux loss stays token-uniform (pad positions are
    routed and occupy expert capacity, so load balance legitimately counts
    them)."""
    logits, aux = moe_lm_logits_aux(cfg, moe, params, tokens, axis_name)
    aux_term = moe.aux_loss_weight * aux / cfg.n_layers
    if cfg.pad_token_id is not None:
        from ..ops.layers import select_masked_xent_sum
        s, n = select_masked_xent_sum(cfg.use_fused_xent)(
            logits, targets, cfg.pad_token_id)
        if axis_name is not None:
            s = jax.lax.psum(s, axis_name)
            n = jax.lax.psum(n, axis_name)
            aux_term = (jax.lax.psum(aux_term, axis_name)
                        / jax.lax.psum(1, axis_name))
        return s / jnp.maximum(n, 1) + aux_term
    loss = select_xent(cfg.use_fused_xent)(logits, targets) + aux_term
    if axis_name is not None:
        loss = jax.lax.psum(loss, axis_name) / jax.lax.psum(1, axis_name)
    return loss
