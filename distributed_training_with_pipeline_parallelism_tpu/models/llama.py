"""Llama family configurations (BASELINE.json config ladder entries 4-5:
"Llama-2 7B, 1F1B across v5e-16", "Llama-3 8B, Interleaved-1F1B x DP on
v5p-64 2-D mesh").
"""

from __future__ import annotations

from ..utils.config import ModelConfig


def llama_config(name: str = "llama2-7b", **overrides) -> ModelConfig:
    sizes = {
        # dim, layers, heads, kv_heads, ffn, vocab, rope_theta
        "llama2-7b": dict(dim=4096, n_layers=32, n_heads=32, n_kv_heads=32,
                          ffn_dim=11008, vocab_size=32000, rope_theta=1e4),
        "llama2-13b": dict(dim=5120, n_layers=40, n_heads=40, n_kv_heads=40,
                           ffn_dim=13824, vocab_size=32000, rope_theta=1e4),
        "llama3-8b": dict(dim=4096, n_layers=32, n_heads=32, n_kv_heads=8,
                          ffn_dim=14336, vocab_size=128256, rope_theta=5e5),
        # 3.1: same shape, 128k context via llama3 rope frequency scaling
        "llama3.1-8b": dict(dim=4096, n_layers=32, n_heads=32, n_kv_heads=8,
                            ffn_dim=14336, vocab_size=128256, rope_theta=5e5,
                            max_seq_len=131072,
                            rope_scaling=(8.0, 1.0, 4.0, 8192)),
        # Mistral v0.1: llama blocks + 4096-token sliding-window attention
        "mistral-7b-v0.1": dict(dim=4096, n_layers=32, n_heads=32,
                                n_kv_heads=8, ffn_dim=14336, vocab_size=32000,
                                rope_theta=1e4, max_seq_len=32768,
                                sliding_window=4096),
        # Mistral v0.3: full attention, 1e6 theta, extended vocab
        "mistral-7b-v0.3": dict(dim=4096, n_layers=32, n_heads=32,
                                n_kv_heads=8, ffn_dim=14336, vocab_size=32768,
                                rope_theta=1e6, max_seq_len=32768),
        # 3.2 small models: llama3 blocks, TIED embeddings, rope scaling
        "llama3.2-1b": dict(dim=2048, n_layers=16, n_heads=32, n_kv_heads=8,
                            ffn_dim=8192, vocab_size=128256, rope_theta=5e5,
                            max_seq_len=131072, tie_embeddings=True,
                            rope_scaling=(32.0, 1.0, 4.0, 8192)),
        "llama3.2-3b": dict(dim=3072, n_layers=28, n_heads=24, n_kv_heads=8,
                            ffn_dim=8192, vocab_size=128256, rope_theta=5e5,
                            max_seq_len=131072, tie_embeddings=True,
                            rope_scaling=(32.0, 1.0, 4.0, 8192)),
        # Qwen2: llama blocks + q/k/v biases; 0.5B ties its embeddings
        "qwen2-0.5b": dict(dim=896, n_layers=24, n_heads=14, n_kv_heads=2,
                           ffn_dim=4864, vocab_size=151936, rope_theta=1e6,
                           max_seq_len=32768, attention_qkv_bias=True,
                           tie_embeddings=True, rms_eps=1e-6),
        "qwen2-7b": dict(dim=3584, n_layers=28, n_heads=28, n_kv_heads=4,
                         ffn_dim=18944, vocab_size=152064, rope_theta=1e6,
                         max_seq_len=32768, attention_qkv_bias=True,
                         rms_eps=1e-6),
        # Gemma 1: decoupled head_dim 256, GeGLU, scaled embeddings, tied,
        # (1+w) norms folded at HF conversion; 2b is multi-query (kv=1)
        "gemma-2b": dict(dim=2048, n_layers=18, n_heads=8, n_kv_heads=1,
                         head_dim_override=256, ffn_dim=16384,
                         vocab_size=256000, rope_theta=1e4, max_seq_len=8192,
                         mlp_act="gelu", embed_scale=True,
                         tie_embeddings=True, rms_eps=1e-6),
        "gemma-7b": dict(dim=3072, n_layers=28, n_heads=16, n_kv_heads=16,
                         head_dim_override=256, ffn_dim=24576,
                         vocab_size=256000, rope_theta=1e4, max_seq_len=8192,
                         mlp_act="gelu", embed_scale=True,
                         tie_embeddings=True, rms_eps=1e-6),
        # scaled-down variant with the same shape ratios for tests/benches
        "llama-debug": dict(dim=256, n_layers=8, n_heads=8, n_kv_heads=4,
                            ffn_dim=688, vocab_size=1024, rope_theta=1e4),
    }
    if name not in sizes:
        raise ValueError(f"unknown Llama size {name!r}; options: {sorted(sizes)}")
    kw = dict(max_seq_len=4096, arch="llama", rms_eps=1e-5)
    kw.update(sizes[name])
    kw.update(overrides)
    return ModelConfig(**kw)
