"""Decoder-only transformer LMs as pure-JAX parameter pytrees.

Three block families selected by ``ModelConfig.arch``:

- ``ref_decoder`` — reference parity: the reference model
  (``LLMsDistributedTrainingHelper.py:31-55``) is ``nn.Embedding`` → N ×
  ``nn.TransformerDecoderLayer(dim, n_heads, batch_first=True)`` → ``LayerNorm``
  → ``Linear(dim, vocab)``, called as ``layer(h, h)`` — i.e. each decoder layer
  runs self-attention *and* cross-attention where the memory is the layer's own
  input hidden state; post-LN; relu FFN of width 2048; **no** causal mask and
  **no** positional encoding (the reference never passes masks or positions).
- ``gpt2`` — pre-LN, learned position embeddings, causal self-attn, gelu MLP.
- ``llama`` — pre-RMSNorm, RoPE, grouped-query causal attention, SwiGLU MLP,
  no biases.

Parameters are organized for pipeline stage-slicing (SURVEY.md §7: the C3
``manual_model_split`` equivalent is a pytree partition, not module deletion):

    {"embed": {...}, "layers": <leaves stacked on axis 0 over n_layers>,
     "head": {"norm": ..., "out": ...}}
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ..ops.attention import mha_apply, mha_init, rope_frequencies
from ..ops.layers import (dropout_apply, embedding_apply, embedding_init,
                          layer_norm_apply, layer_norm_init, linear_apply,
                          linear_init, rms_norm_apply, rms_norm_init,
                          select_xent, sharded_dropout_apply)
from ..utils.config import ModelConfig

# ---------------------------------------------------------------------------
# Per-layer init / apply
# ---------------------------------------------------------------------------


def layer_init(key: jax.Array, cfg: ModelConfig) -> Dict:
    ks = jax.random.split(key, 8)
    if cfg.arch == "ref_decoder":
        return {
            "self_attn": mha_init(ks[0], cfg.dim, cfg.n_heads),
            "cross_attn": mha_init(ks[1], cfg.dim, cfg.n_heads),
            "ln1": layer_norm_init(cfg.dim),
            "ln2": layer_norm_init(cfg.dim),
            "ln3": layer_norm_init(cfg.dim),
            "lin1": linear_init(ks[2], cfg.dim, cfg.ffn_dim),
            "lin2": linear_init(ks[3], cfg.ffn_dim, cfg.dim),
        }
    if cfg.arch == "gpt2":
        return {
            "ln1": layer_norm_init(cfg.dim),
            "attn": mha_init(ks[0], cfg.dim, cfg.n_heads),
            "ln2": layer_norm_init(cfg.dim),
            "lin1": linear_init(ks[2], cfg.dim, cfg.ffn_dim),
            "lin2": linear_init(ks[3], cfg.ffn_dim, cfg.dim),
        }
    if cfg.arch == "llama":
        return {
            "rms1": rms_norm_init(cfg.dim),
            "attn": mha_init(ks[0], cfg.dim, cfg.n_heads, cfg.n_kv_heads,
                             bias=cfg.attention_qkv_bias, o_bias=False,
                             head_dim=cfg.head_dim),
            "rms2": rms_norm_init(cfg.dim),
            "w1": linear_init(ks[2], cfg.dim, cfg.ffn_dim, bias=False),
            "w2": linear_init(ks[3], cfg.ffn_dim, cfg.dim, bias=False),
            "w3": linear_init(ks[4], cfg.dim, cfg.ffn_dim, bias=False),
        }
    raise ValueError(f"unknown arch {cfg.arch!r}")


def layer_apply(cfg: ModelConfig, params: Dict, h: jax.Array,
                rope_angles: Optional[jax.Array] = None,
                tp_axis: Optional[str] = None, tp_size: int = 1,
                rng: Optional[jax.Array] = None) -> jax.Array:
    """One decoder block. With ``tp_axis`` set the block runs Megatron
    tensor-parallel inside a manual-SPMD region: weight leaves are local
    shards (attention heads and FFN hidden dim column-split ``tp_size``
    ways), norms replicated, and the two row-parallel projections complete
    with a psum (see :mod:`..ops.collectives`).

    ``rng`` (train mode) enables dropout at the torch sites: attention
    probabilities inside each MHA, each residual branch, and the FFN's inner
    activation (``nn.TransformerDecoderLayer``'s dropout/dropout1/2/3 for the
    ref arch; GPT-2's attn/resid dropout). Each site folds a distinct stream
    from ``rng``, so one per-layer key determines every mask."""
    fl = cfg.flash_for(cfg.causal, h.shape[1])
    heads = cfg.n_heads // tp_size
    p = cfg.dropout

    def site(i: int) -> Optional[jax.Array]:
        return None if rng is None else jax.random.fold_in(rng, i)

    if cfg.arch == "ref_decoder":
        mem = h  # the reference calls layer(h, h): memory is the layer's input
        sa = mha_apply(params["self_attn"], h, h, heads, flash=fl,
                       tp_axis=tp_axis, tp_size=tp_size, dropout_rate=p,
                       dropout_rng=site(0))
        x = layer_norm_apply(params["ln1"], h + dropout_apply(sa, p, site(1)))
        ca = mha_apply(params["cross_attn"], x, mem, heads, flash=fl,
                       tp_axis=tp_axis, tp_size=tp_size, dropout_rate=p,
                       dropout_rng=site(2))
        x = layer_norm_apply(params["ln2"], x + dropout_apply(ca, p, site(3)))
        # the FFN-inner activation is a column-parallel local shard under
        # TP: its mask is the global mask's local slice (oracle-exact)
        ff = _ffn_out(params["lin2"],
                      sharded_dropout_apply(
                          jax.checkpoint(jax.nn.relu)(
                              linear_apply(params["lin1"],
                                           _tp_in(x, tp_axis))),
                          p, site(4), axis=tp_axis, n_shards=tp_size,
                          shard_dim=-1),
                      tp_axis)
        return layer_norm_apply(params["ln3"], x + dropout_apply(ff, p, site(5)))
    if cfg.arch == "gpt2":
        a = layer_norm_apply(params["ln1"], h)
        attn = mha_apply(params["attn"], a, a, heads, causal=cfg.causal,
                         flash=fl, tp_axis=tp_axis, tp_size=tp_size,
                         dropout_rate=p, dropout_rng=site(0))
        h = h + dropout_apply(attn, p, site(1))
        return mlp_block(cfg, params, h, tp_axis=tp_axis, tp_size=tp_size,
                         rng=site(2), dropout=p)
    if cfg.arch == "llama":
        a = rms_norm_apply(params["rms1"], h, cfg.rms_eps)
        attn = mha_apply(params["attn"], a, a, heads, causal=cfg.causal,
                         rope_angles=rope_angles, flash=fl, tp_axis=tp_axis,
                         tp_size=tp_size, window=cfg.sliding_window,
                         dropout_rate=p, dropout_rng=site(0))
        h = h + dropout_apply(attn, p, site(1))
        return mlp_block(cfg, params, h, tp_axis=tp_axis, tp_size=tp_size,
                         rng=site(2), dropout=p)
    raise ValueError(f"unknown arch {cfg.arch!r}")


def _tp_in(x: jax.Array, tp_axis: Optional[str]) -> jax.Array:
    if tp_axis is None:
        return x
    from ..ops.collectives import tp_copy
    return tp_copy(x, tp_axis)


def _ffn_out(params: Dict, z: jax.Array, tp_axis: Optional[str]) -> jax.Array:
    if tp_axis is None:
        return linear_apply(params, z)
    from ..ops.collectives import row_parallel_linear
    return row_parallel_linear(params, z, tp_axis)


def mlp_block(cfg: ModelConfig, params: Dict, h: jax.Array,
              tp_axis: Optional[str] = None, tp_size: int = 1,
              rng: Optional[jax.Array] = None, dropout: float = 0.0) -> jax.Array:
    """Post-attention half of a gpt2/llama block (norm + MLP + residual).

    Shared between the training path (:func:`layer_apply`) and the KV-cache
    decode path (:mod:`.generate`, which never passes an rng) so the two
    cannot drift. ``rng`` applies residual-branch dropout to the MLP output.

    With ``cfg.tp_overlap`` resolving to ``"ring"`` (TP only, dropout-free,
    seq divisible by ``tp_size``), the block's TP boundary runs the
    collective-matmul forms instead of the replicated copy/psum pair: the
    sequence is sharded at the norm output, the all-gather overlaps the
    up-projection and the reduce-scatter the down-projection, and the
    residual re-replicates via one ring gather (see
    :mod:`..ops.collectives`)."""
    if (tp_axis is not None and tp_size > 1 and cfg.tp_overlap != "none"
            and (rng is None or dropout == 0.0)):
        from ..parallel.tensor_parallel import resolve_tp_overlap
        if resolve_tp_overlap(cfg.tp_overlap, tp_size, h.shape[1]) == "ring":
            return _mlp_block_ring(cfg, params, h, tp_axis, tp_size)
    # the activations are checkpointed: backward saves only the [.., ffn]
    # pre-activation and recomputes the (tanh-)gelu/silu chain — without
    # this autodiff banks ~6 ffn-sized intermediates per layer, the
    # dominant residual cost of stored-activation backwards
    if cfg.arch == "gpt2":
        m = _tp_in(layer_norm_apply(params["ln2"], h), tp_axis)
        ff = _ffn_out(params["lin2"],
                      jax.checkpoint(jax.nn.gelu)(
                          linear_apply(params["lin1"], m)),
                      tp_axis)
        return h + dropout_apply(ff, dropout, rng)
    m = _tp_in(rms_norm_apply(params["rms2"], h, cfg.rms_eps), tp_axis)
    act = jax.nn.silu if cfg.mlp_act == "silu" else jax.nn.gelu
    ff = _ffn_out(params["w2"],
                  jax.checkpoint(lambda a, b: act(a) * b)(
                      linear_apply(params["w1"], m),
                      linear_apply(params["w3"], m)),
                  tp_axis)
    return h + dropout_apply(ff, dropout, rng)


def _mlp_block_ring(cfg: ModelConfig, params: Dict, h: jax.Array,
                    tp_axis: str, tp_size: int) -> jax.Array:
    """Collective-matmul MLP: sequence-shard the norm output (free slice of
    a replicated value), overlap the gather with the up-projection and the
    scatter with the down-projection, re-replicate for the residual. The
    up-projection is bit-identical to the unfused path; the down-projection
    sums partials in ring order (numerical, not bitwise, parity)."""
    from ..ops.collectives import seq_all_gather, seq_scatter
    from ..parallel.tensor_parallel import (tp_all_gather_matmul,
                                            tp_matmul_reduce_scatter)
    if cfg.arch == "gpt2":
        m = seq_scatter(layer_norm_apply(params["ln2"], h), tp_axis, tp_size)
        z = tp_all_gather_matmul(m, params["lin1"]["w"], tp_axis, tp_size,
                                 mode="ring") + params["lin1"]["b"]
        ff = tp_matmul_reduce_scatter(jax.checkpoint(jax.nn.gelu)(z),
                                      params["lin2"]["w"], tp_axis, tp_size,
                                      mode="ring")
        ff = seq_all_gather(ff, tp_axis, tp_size) + params["lin2"]["b"]
        return h + ff
    m = seq_scatter(rms_norm_apply(params["rms2"], h, cfg.rms_eps),
                    tp_axis, tp_size)
    act = jax.nn.silu if cfg.mlp_act == "silu" else jax.nn.gelu
    z1 = tp_all_gather_matmul(m, params["w1"]["w"], tp_axis, tp_size,
                              mode="ring")
    z3 = tp_all_gather_matmul(m, params["w3"]["w"], tp_axis, tp_size,
                              mode="ring")
    ff = tp_matmul_reduce_scatter(
        jax.checkpoint(lambda a, b: act(a) * b)(z1, z3),
        params["w2"]["w"], tp_axis, tp_size, mode="ring")
    return h + seq_all_gather(ff, tp_axis, tp_size)


# ---------------------------------------------------------------------------
# Whole-model init / apply
# ---------------------------------------------------------------------------


def transformer_init(key: jax.Array, cfg: ModelConfig) -> Dict:
    ke, kp, kl, kn, ko = jax.random.split(key, 5)
    if cfg.arch == "ref_decoder":
        # torch nn.Embedding parity: N(0, 1) (the reference's init)
        tok = embedding_init(ke, cfg.vocab_size, cfg.dim)
    else:
        # GPT-2/Llama convention: N(0, 0.02) — essential under tied
        # embeddings, where N(0,1) rows make initial logits ~sqrt(dim) hot
        tok = 0.02 * jax.random.normal(ke, (cfg.vocab_size, cfg.dim))
    embed: Dict = {"tok": tok}
    if cfg.arch == "gpt2":
        embed["pos"] = 0.02 * jax.random.normal(kp, (cfg.max_seq_len, cfg.dim))
    layer_keys = jax.random.split(kl, cfg.n_layers)
    layers = jax.vmap(lambda k: layer_init(k, cfg))(layer_keys)
    norm = (rms_norm_init(cfg.dim) if cfg.arch == "llama"
            else layer_norm_init(cfg.dim))
    if cfg.tie_embeddings:
        head = {"norm": norm}  # logits come from embed.tok.T
    elif cfg.arch == "llama":
        head = {"norm": norm,
                "out": linear_init(ko, cfg.dim, cfg.vocab_size, bias=False)}
    else:
        head = {"norm": norm,
                "out": linear_init(ko, cfg.dim, cfg.vocab_size, bias=cfg.arch == "ref_decoder")}
    params = {"embed": embed, "layers": layers, "head": head}
    dtype = jnp.dtype(cfg.storage_dtype)  # master-weight dtype under mixing
    if dtype != jnp.float32:
        params = jax.tree.map(lambda x: x.astype(dtype), params)
    return params


def compute_cast(cfg: ModelConfig, tree: Dict) -> Dict:
    """Cast a parameter (sub)tree from storage to compute dtype. Identity
    when no mixed precision is configured. Sits INSIDE autodiff at every
    use site, so cotangents flow back in the storage dtype."""
    if not cfg.mixed_precision:
        return tree
    dtype = jnp.dtype(cfg.dtype)
    return jax.tree.map(lambda x: x.astype(dtype), tree)


def embed_apply(cfg: ModelConfig, embed: Dict, tokens: jax.Array,
                rng: Optional[jax.Array] = None) -> jax.Array:
    h = embedding_apply(embed["tok"], tokens)
    if cfg.embed_scale:
        # Gemma scales embedding OUTPUTS by sqrt(dim); the tied head keeps
        # the unscaled table, so this cannot fold into the weights
        h = h * (cfg.dim ** 0.5)
    if cfg.arch == "gpt2":
        h = h + embed["pos"][: tokens.shape[1]]
        h = dropout_apply(h, cfg.dropout, rng)  # GPT-2 embedding dropout
    return h


def _rope(cfg: ModelConfig, seq_len: int) -> Optional[jax.Array]:
    if cfg.arch != "llama":
        return None
    return rope_frequencies(cfg.head_dim, seq_len, cfg.rope_theta,
                            cfg.rope_scaling)


def body_apply(cfg: ModelConfig, layers: Dict, h: jax.Array,
               tp_axis: Optional[str] = None, tp_size: int = 1,
               rng: Optional[jax.Array] = None,
               layer_offset=0) -> jax.Array:
    """Run a stack of layers whose leaves are stacked on axis 0 (any count).

    ``rng`` (train mode) enables dropout; each layer folds
    ``layer_offset + i`` from it, where ``layer_offset`` is the stack's first
    *global* layer index — so masks depend only on (rng, global layer, site),
    making a pipeline-stage run reproduce exactly the masks of any other
    stage partitioning of the same model (asserted in tests/test_dropout.py).
    """
    rope = _rope(cfg, h.shape[1])
    n = jax.tree.leaves(layers)[0].shape[0]

    if cfg.unroll_layers:
        # straight-line layers: no scan boundary, so XLA fuses across
        # layers and autodiff residuals stay SSA values instead of
        # round-tripping HBM through stacked scan outputs (the same
        # finding as the executor's unrolled stored backward,
        # docs/performance.md). Compile time grows with depth; measured
        # +5-12% train-step throughput for gpt2-small on one v5e chip.
        def one(layer_params, x, i):
            rng_l = (None if rng is None
                     else jax.random.fold_in(rng, layer_offset + i))
            return layer_apply(cfg, layer_params, x, rope, tp_axis=tp_axis,
                               tp_size=tp_size, rng=rng_l)

        if cfg.remat_layers:
            one = jax.checkpoint(one, static_argnums=(2,))
        for i in range(n):
            h = one(jax.tree.map(lambda x: x[i], layers), h, i)
        return h

    def step(carry, xs):
        layer_params, i = xs
        rng_l = None if rng is None else jax.random.fold_in(rng, layer_offset + i)
        return layer_apply(cfg, layer_params, carry, rope,
                           tp_axis=tp_axis, tp_size=tp_size, rng=rng_l), None

    if cfg.remat_layers:
        # rematerialize each layer in backward: activation memory drops from
        # O(layers x intermediates) to O(layers) block inputs
        step = jax.checkpoint(step)
    out, _ = jax.lax.scan(step, h, (layers, jnp.arange(n)))
    return out


def head_norm_apply(cfg: ModelConfig, head: Dict, h: jax.Array) -> jax.Array:
    """The head's final norm (arch-dispatched) — shared with the executor's
    vocab-parallel loss branch so the two cannot drift."""
    if cfg.arch == "llama":
        return rms_norm_apply(head["norm"], h, cfg.rms_eps)
    return layer_norm_apply(head["norm"], h)


def head_apply(cfg: ModelConfig, head: Dict, h: jax.Array,
               embed: Optional[Dict] = None) -> jax.Array:
    hn = head_norm_apply(cfg, head, h)
    # flatten [B, S, d] -> [B*S, d] around the vocab matmul: a 2-D dot
    # gets the default output layout, which the fused-CE kernel (and any
    # flat consumer) reads without a relayout — the 3-D form cost a
    # measured 2.5 ms/step full-logits copy at GPT-2 vocab (docs/profiles/)
    lead = hn.shape[:-1]
    hn2 = hn.reshape(-1, hn.shape[-1]) if hn.ndim > 2 else hn
    if cfg.tie_embeddings:
        assert embed is not None, "tied head needs the embedding table"
        logits = hn2 @ embed["tok"].T
    else:
        logits = linear_apply(head["out"], hn2)
    return logits.reshape(*lead, logits.shape[-1]) if hn.ndim > 2 else logits


def transformer_apply(cfg: ModelConfig, params: Dict, tokens: jax.Array,
                      rng: Optional[jax.Array] = None) -> jax.Array:
    """Full-model forward: tokens [B, S] -> logits [B, S, V].

    ``rng`` (train mode) enables dropout: layer i folds stream i, the
    embedding folds stream ``n_layers`` — the same convention the pipeline
    executor uses per microbatch, so executor masks are checkable against
    this path."""
    rng_e = None if rng is None else jax.random.fold_in(rng, cfg.n_layers)
    params = compute_cast(cfg, params)  # bf16 compute over fp32 masters
    h = embed_apply(cfg, params["embed"], tokens, rng=rng_e)
    h = body_apply(cfg, params["layers"], h, rng=rng)
    return head_apply(cfg, params["head"], h, embed=params["embed"])


def transformer_loss(cfg: ModelConfig, params: Dict, tokens: jax.Array,
                     targets: jax.Array,
                     rng: Optional[jax.Array] = None) -> jax.Array:
    """Single-device reference loss — the ground truth the pipeline executors
    are verified against (a check the reference itself never performs,
    SURVEY.md §4). With ``cfg.pad_token_id`` set, pad targets are ignored
    and the mean divides by the valid count."""
    logits = transformer_apply(cfg, params, tokens, rng=rng)
    if cfg.pad_token_id is not None:
        from ..ops.layers import select_masked_xent_sum
        s, n = select_masked_xent_sum(cfg.use_fused_xent)(
            logits, targets, cfg.pad_token_id)
        return s / jnp.maximum(n, 1)
    return select_xent(cfg.use_fused_xent)(logits, targets)
