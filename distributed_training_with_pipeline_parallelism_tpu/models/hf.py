"""Import Hugging Face GPT-2 / Llama checkpoints into this framework.

The reference trains randomly initialized models and discards them
(``LLMsDistributedTrainingHelper.py:191-194``, SURVEY.md §5 checkpoint row:
"models are randomly initialized per experiment"); real-model runs need
parameter *loading*. This module converts ``transformers`` checkpoints
(``GPT2LMHeadModel``, ``LlamaForCausalLM``) — or their raw state dicts — into
this framework's stacked-layer pytrees, so pretrained weights flow straight
into the pipeline/TP/FSDP shardings.

Convention notes (why the conversion is exact, verified to ~1e-4 in
``tests/test_hf_import.py``):

- HF GPT-2 ``Conv1D`` stores weights as ``[in, out]`` — already this
  framework's linear layout; torch ``nn.Linear`` (Llama) stores ``[out, in]``
  and is transposed.
- HF GPT-2's ``gelu_new`` is the tanh approximation == ``jax.nn.gelu``'s
  default; LayerNorm eps 1e-5 matches :func:`..ops.layers.layer_norm_apply`.
- HF Llama RoPE is the half-split ("rotate_half") convention — identical to
  :func:`..ops.attention.apply_rope`; rms eps is carried through the config.
- ``tie_word_embeddings`` carries through as ``cfg.tie_embeddings``: a tied
  HF checkpoint (GPT-2's default, Llama-3.2-class) imports as a tied config
  with no separate head matrix; untied checkpoints materialize
  ``head.out.w``.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax.numpy as jnp
import numpy as np

from ..utils.config import ModelConfig

Pytree = Dict


def _np(t) -> np.ndarray:
    if isinstance(t, np.ndarray):
        return t
    return t.detach().cpu().numpy()  # torch tensor


def _state_dict(model_or_sd) -> Dict[str, np.ndarray]:
    sd = model_or_sd if isinstance(model_or_sd, dict) else model_or_sd.state_dict()
    return {k: _np(v) for k, v in sd.items()}


def _stack(layer_dicts):
    """[{leaf: arr}] per layer -> {leaf: arr stacked on axis 0} (the stacked
    layer layout of :func:`..models.transformer.transformer_init`)."""
    import jax
    return jax.tree.map(lambda *xs: jnp.stack(xs), *layer_dicts)


# ---------------------------------------------------------------------------
# GPT-2
# ---------------------------------------------------------------------------


def gpt2_config_from_hf(hf_config) -> ModelConfig:
    return ModelConfig(
        dim=hf_config.n_embd, n_layers=hf_config.n_layer,
        n_heads=hf_config.n_head, vocab_size=hf_config.vocab_size,
        ffn_dim=hf_config.n_inner or 4 * hf_config.n_embd,
        max_seq_len=hf_config.n_positions, arch="gpt2",
        tie_embeddings=bool(getattr(hf_config, "tie_word_embeddings", True)))


def gpt2_params_from_hf(model_or_sd, cfg: ModelConfig) -> Pytree:
    sd = _state_dict(model_or_sd)
    pre = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    d = cfg.dim

    def layer(i):
        p = f"{pre}h.{i}."
        ca_w, ca_b = sd[p + "attn.c_attn.weight"], sd[p + "attn.c_attn.bias"]
        return {
            "ln1": {"scale": sd[p + "ln_1.weight"], "bias": sd[p + "ln_1.bias"]},
            "attn": {
                "q": {"w": ca_w[:, :d], "b": ca_b[:d]},
                "k": {"w": ca_w[:, d:2 * d], "b": ca_b[d:2 * d]},
                "v": {"w": ca_w[:, 2 * d:], "b": ca_b[2 * d:]},
                "o": {"w": sd[p + "attn.c_proj.weight"],
                      "b": sd[p + "attn.c_proj.bias"]},
            },
            "ln2": {"scale": sd[p + "ln_2.weight"], "bias": sd[p + "ln_2.bias"]},
            "lin1": {"w": sd[p + "mlp.c_fc.weight"], "b": sd[p + "mlp.c_fc.bias"]},
            "lin2": {"w": sd[p + "mlp.c_proj.weight"], "b": sd[p + "mlp.c_proj.bias"]},
        }

    wte = sd[pre + "wte.weight"]
    head = {"norm": {"scale": sd[pre + "ln_f.weight"],
                     "bias": sd[pre + "ln_f.bias"]}}
    if not cfg.tie_embeddings:
        # untied config: materialize the head matrix explicitly
        head["out"] = {"w": sd.get("lm_head.weight", wte).T}
    params = {
        "embed": {"tok": wte, "pos": sd[pre + "wpe.weight"][:cfg.max_seq_len]},
        "layers": _stack([layer(i) for i in range(cfg.n_layers)]),
        "head": head,
    }
    return _to_dtype(params, cfg)


# ---------------------------------------------------------------------------
# Llama
# ---------------------------------------------------------------------------


def llama_config_from_hf(hf_config) -> ModelConfig:
    # Refuse configs whose semantics this conversion does not carry — a
    # silent pass-through here would produce plausible-looking wrong logits.
    scaling = getattr(hf_config, "rope_scaling", None)
    rope_scaling = None
    if scaling:
        rope_type = scaling.get("rope_type", scaling.get("type"))
        if rope_type == "llama3":
            rope_scaling = (float(scaling["factor"]),
                            float(scaling["low_freq_factor"]),
                            float(scaling["high_freq_factor"]),
                            int(scaling["original_max_position_embeddings"]))
        elif rope_type != "default":
            # 'default' is transformers' spelling of plain RoPE; anything
            # else (yarn, dynamic NTK, ...) is not carried by this converter
            raise NotImplementedError(
                f"rope_scaling={scaling!r} is not supported by this "
                f"converter; plain RoPE and rope_type='llama3' are")
    if getattr(hf_config, "mlp_bias", False):
        raise NotImplementedError(
            "mlp_bias=True checkpoints are not supported (gate/up/down "
            "projection biases would be dropped)")
    # decoupled head_dim (Gemma, Mistral-Nemo-class): carried natively via
    # head_dim_override
    hd = getattr(hf_config, "head_dim", None)
    override = (int(hd) if hd and
                hd != hf_config.hidden_size // hf_config.num_attention_heads
                else None)
    # Qwen2 always carries q/k/v biases (its config has no attention_bias
    # field) and no o bias. Llama's attention_bias=True puts a bias on
    # o_proj TOO — this framework's blocks have no o bias, so importing
    # would silently drop it; refuse instead.
    qkv_bias = hf_config.model_type == "qwen2"
    if getattr(hf_config, "attention_bias", False):
        raise NotImplementedError(
            "Llama attention_bias=True checkpoints are not supported (the "
            "o_proj bias would be dropped; Qwen2's qkv-only biases are)")
    window = getattr(hf_config, "sliding_window", None)
    if hf_config.model_type == "qwen2":
        if not getattr(hf_config, "use_sliding_window", False):
            window = None  # qwen2 configs carry the field but default it off
        elif getattr(hf_config, "max_window_layers", 0) > 0:
            # HF windows only layers >= max_window_layers; this framework's
            # sliding_window is uniform — a silent import would window
            # layers HF attends fully
            raise NotImplementedError(
                "qwen2 use_sliding_window with max_window_layers > 0 mixes "
                "full and windowed layers; only uniform windowing "
                "(max_window_layers=0) is supported")
    return ModelConfig(
        dim=hf_config.hidden_size, n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=hf_config.num_key_value_heads,
        vocab_size=hf_config.vocab_size, ffn_dim=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings, arch="llama",
        rope_theta=float(hf_config.rope_theta),
        rope_scaling=rope_scaling,
        sliding_window=window,
        attention_qkv_bias=qkv_bias,
        head_dim_override=override,
        rms_eps=float(hf_config.rms_norm_eps),
        tie_embeddings=bool(getattr(hf_config, "tie_word_embeddings", False)))


def llama_params_from_hf(model_or_sd, cfg: ModelConfig,
                         norm_offset: float = 0.0) -> Pytree:
    """``norm_offset`` is added to every RMSNorm scale IN FLOAT32, before
    any dtype cast — Gemma's (1 + w) parametrization folds in exactly."""
    sd = _state_dict(model_or_sd)
    pre = "model." if any(k.startswith("model.") for k in sd) else ""

    def norm(name):
        return {"scale": sd[name].astype(np.float32) + norm_offset}

    def lin_t(name, bias=False):  # torch nn.Linear [out, in] -> [in, out]
        p = {"w": sd[name + ".weight"].T}
        if bias:
            p["b"] = sd[name + ".bias"]
        return p

    qkv_bias = cfg.attention_qkv_bias

    def layer(i):
        p = f"{pre}layers.{i}."
        return {
            "rms1": norm(p + "input_layernorm.weight"),
            "attn": {"q": lin_t(p + "self_attn.q_proj", qkv_bias),
                     "k": lin_t(p + "self_attn.k_proj", qkv_bias),
                     "v": lin_t(p + "self_attn.v_proj", qkv_bias),
                     "o": lin_t(p + "self_attn.o_proj")},
            "rms2": norm(p + "post_attention_layernorm.weight"),
            "w1": lin_t(p + "mlp.gate_proj"),
            "w2": lin_t(p + "mlp.down_proj"),
            "w3": lin_t(p + "mlp.up_proj"),
        }

    embed = sd[pre + "embed_tokens.weight"]
    head = {"norm": norm(pre + "norm.weight")}
    if not cfg.tie_embeddings:
        head["out"] = {"w": sd["lm_head.weight"].T if "lm_head.weight" in sd
                       else embed.T}  # materialize a tied source untied
    params = {
        "embed": {"tok": embed},
        "layers": _stack([layer(i) for i in range(cfg.n_layers)]),
        "head": head,
    }
    return _to_dtype(params, cfg)


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------


def _to_dtype(params: Pytree, cfg: ModelConfig) -> Pytree:
    import jax
    # storage dtype: under mixed precision (param_dtype='float32') imported
    # weights are the fp32 masters, matching transformer_init
    dtype = jnp.dtype(cfg.storage_dtype)
    return jax.tree.map(lambda x: jnp.asarray(x, dtype), params)


def gemma_config_from_hf(hf_config) -> ModelConfig:
    import dataclasses

    act = getattr(hf_config, "hidden_activation", None) or getattr(
        hf_config, "hidden_act", None)
    # 'gelu' is accepted only for model_type='gemma', where the historical
    # Gemma-1 checkpoints said 'gelu' but were trained with the tanh
    # approximation (the documented HF reading). Anywhere else 'gelu' means
    # exact erf GELU, which jax.nn.gelu's default would silently change.
    gemma1_tanh_reading = (
        act == "gelu" and getattr(hf_config, "model_type", None) == "gemma")
    if act != "gelu_pytorch_tanh" and not gemma1_tanh_reading:
        raise NotImplementedError(
            f"gemma hidden activation {act!r} is not supported (tanh-approx "
            f"gelu == jax.nn.gelu's default is; exact-erf 'gelu' outside "
            f"model_type='gemma' would need approximate=False plumbing)")
    base = llama_config_from_hf(hf_config)
    return dataclasses.replace(
        base,
        head_dim_override=int(hf_config.head_dim),
        mlp_act="gelu", embed_scale=True,
        # Gemma ties unconditionally (PretrainedConfig default True carries
        # through llama_config_from_hf's getattr already, but be explicit)
        tie_embeddings=True)


def gemma_params_from_hf(model_or_sd, cfg: ModelConfig) -> Pytree:
    """Gemma stores RMSNorm weights in the ``(1 + w)`` parametrization; this
    framework's norm multiplies by the stored scale directly, so the +1 is
    folded in (exactly, in float32, before any dtype cast) and unfolded on
    export — zero runtime cost."""
    return llama_params_from_hf(model_or_sd, cfg, norm_offset=1.0)


_CONVERTERS = {
    "gpt2": (gpt2_config_from_hf, gpt2_params_from_hf),
    "llama": (llama_config_from_hf, llama_params_from_hf),
    # Mistral = llama blocks + sliding-window attention; identical state
    # dict layout, window carried via config.sliding_window
    "mistral": (llama_config_from_hf, llama_params_from_hf),
    # Qwen2 = llama blocks + q/k/v biases (attention_qkv_bias)
    "qwen2": (llama_config_from_hf, llama_params_from_hf),
    # Gemma = llama blocks + decoupled head_dim + GeGLU + scaled embeddings
    # + (1+w) norms folded at conversion
    "gemma": (gemma_config_from_hf, gemma_params_from_hf),
}


def from_hf(model, dtype: str = "float32") -> Tuple[ModelConfig, Pytree]:
    """Convert a ``transformers`` causal-LM model to (ModelConfig, params).

    Dispatches on the HF config's ``model_type``: "gpt2", "llama",
    "mistral" (llama converter + sliding window), or "qwen2" (llama
    converter + q/k/v biases).
    """
    import dataclasses

    mt = model.config.model_type
    if mt not in _CONVERTERS:
        raise ValueError(
            f"unsupported HF model_type {mt!r}; expected {sorted(_CONVERTERS)}")
    config_fn, params_fn = _CONVERTERS[mt]
    cfg = dataclasses.replace(config_fn(model.config), dtype=dtype)
    return cfg, params_fn(model, cfg)


# ---------------------------------------------------------------------------
# Export (the inverse direction): this framework -> transformers
# ---------------------------------------------------------------------------


def _f32(x) -> np.ndarray:
    return np.asarray(x, dtype=np.float32)


def gpt2_state_dict(cfg: ModelConfig, params: Pytree) -> Dict[str, np.ndarray]:
    """Inverse of :func:`gpt2_params_from_hf`: stacked-layer pytree ->
    ``GPT2LMHeadModel`` state-dict arrays (Conv1D [in, out] layout; q/k/v
    packed back into ``c_attn``)."""
    L = cfg.n_layers
    lv = lambda leaf, i: _f32(leaf[i])  # noqa: E731 - stacked leaf -> layer i
    sd: Dict[str, np.ndarray] = {
        "transformer.wte.weight": _f32(params["embed"]["tok"]),
        "transformer.wpe.weight": _f32(params["embed"]["pos"]),
        "transformer.ln_f.weight": _f32(params["head"]["norm"]["scale"]),
        "transformer.ln_f.bias": _f32(params["head"]["norm"]["bias"]),
    }
    if not cfg.tie_embeddings:
        sd["lm_head.weight"] = _f32(params["head"]["out"]["w"]).T
    ly = params["layers"]
    for i in range(L):
        p = f"transformer.h.{i}."
        a = ly["attn"]
        sd[p + "ln_1.weight"] = lv(ly["ln1"]["scale"], i)
        sd[p + "ln_1.bias"] = lv(ly["ln1"]["bias"], i)
        sd[p + "attn.c_attn.weight"] = np.concatenate(
            [lv(a["q"]["w"], i), lv(a["k"]["w"], i), lv(a["v"]["w"], i)], axis=1)
        sd[p + "attn.c_attn.bias"] = np.concatenate(
            [lv(a["q"]["b"], i), lv(a["k"]["b"], i), lv(a["v"]["b"], i)])
        sd[p + "attn.c_proj.weight"] = lv(a["o"]["w"], i)
        sd[p + "attn.c_proj.bias"] = lv(a["o"]["b"], i)
        sd[p + "ln_2.weight"] = lv(ly["ln2"]["scale"], i)
        sd[p + "ln_2.bias"] = lv(ly["ln2"]["bias"], i)
        sd[p + "mlp.c_fc.weight"] = lv(ly["lin1"]["w"], i)
        sd[p + "mlp.c_fc.bias"] = lv(ly["lin1"]["b"], i)
        sd[p + "mlp.c_proj.weight"] = lv(ly["lin2"]["w"], i)
        sd[p + "mlp.c_proj.bias"] = lv(ly["lin2"]["b"], i)
    return sd


def llama_state_dict(cfg: ModelConfig, params: Pytree,
                     norm_offset: float = 0.0) -> Dict[str, np.ndarray]:
    """Inverse of :func:`llama_params_from_hf` ([in, out] -> torch [out, in]);
    ``norm_offset`` is SUBTRACTED from RMSNorm scales (Gemma's (1+w))."""
    sd: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": _f32(params["embed"]["tok"]),
        "model.norm.weight": _f32(params["head"]["norm"]["scale"]) - norm_offset,
    }
    if not cfg.tie_embeddings:
        sd["lm_head.weight"] = _f32(params["head"]["out"]["w"]).T
    ly = params["layers"]
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}."
        a = ly["attn"]
        sd[p + "input_layernorm.weight"] = (_f32(ly["rms1"]["scale"][i])
                                           - norm_offset)
        sd[p + "self_attn.q_proj.weight"] = _f32(a["q"]["w"][i]).T
        sd[p + "self_attn.k_proj.weight"] = _f32(a["k"]["w"][i]).T
        sd[p + "self_attn.v_proj.weight"] = _f32(a["v"]["w"][i]).T
        sd[p + "self_attn.o_proj.weight"] = _f32(a["o"]["w"][i]).T
        if cfg.attention_qkv_bias:
            sd[p + "self_attn.q_proj.bias"] = _f32(a["q"]["b"][i])
            sd[p + "self_attn.k_proj.bias"] = _f32(a["k"]["b"][i])
            sd[p + "self_attn.v_proj.bias"] = _f32(a["v"]["b"][i])
        sd[p + "post_attention_layernorm.weight"] = (
            _f32(ly["rms2"]["scale"][i]) - norm_offset)
        sd[p + "mlp.gate_proj.weight"] = _f32(ly["w1"]["w"][i]).T
        sd[p + "mlp.down_proj.weight"] = _f32(ly["w2"]["w"][i]).T
        sd[p + "mlp.up_proj.weight"] = _f32(ly["w3"]["w"][i]).T
    return sd


def to_hf(cfg: ModelConfig, params: Pytree):
    """Convert (ModelConfig, params) to a ``transformers`` model —
    ``GPT2LMHeadModel`` or ``LlamaForCausalLM``/``MistralForCausalLM``
    (Mistral when ``cfg.sliding_window`` is set). The round trip
    ``from_hf(to_hf(cfg, params))`` is exact, and exported logits match this
    framework's (tests/test_hf_export.py). Save with
    ``to_hf(...).save_pretrained(path)``.

    ``tie_word_embeddings`` follows ``cfg.tie_embeddings``: untied configs
    (the reference-parity default — SURVEY.md C2: ``Linear(dim, vocab)`` is
    untied) export an explicit ``lm_head``; tied configs export no head
    matrix and let transformers tie it to ``wte``/``embed_tokens``.

    The reference has no export path at all (SURVEY.md §5 checkpoint row);
    this closes the loop with :func:`from_hf` so models pretrained or
    fine-tuned here flow back into the HF ecosystem.
    """
    import torch
    import transformers

    if cfg.arch == "gpt2":
        if cfg.embed_scale:
            raise NotImplementedError(
                "embed_scale on gpt2 blocks (the MoE LM convention) has no "
                "HF model_type — GPT2LMHeadModel never scales embeddings "
                "and exporting without the scale would silently change the "
                "logits")
        hf_cfg = transformers.GPT2Config(
            vocab_size=cfg.vocab_size, n_positions=cfg.max_seq_len,
            n_embd=cfg.dim, n_layer=cfg.n_layers, n_head=cfg.n_heads,
            n_inner=cfg.ffn_dim,
            tie_word_embeddings=cfg.tie_embeddings)
        model = transformers.GPT2LMHeadModel(hf_cfg)
        sd = gpt2_state_dict(cfg, params)
    elif cfg.arch == "llama":
        common = dict(
            vocab_size=cfg.vocab_size, hidden_size=cfg.dim,
            intermediate_size=cfg.ffn_dim, num_hidden_layers=cfg.n_layers,
            num_attention_heads=cfg.n_heads,
            num_key_value_heads=cfg.n_kv_heads or cfg.n_heads,
            max_position_embeddings=cfg.max_seq_len,
            head_dim=cfg.head_dim,
            rms_norm_eps=cfg.rms_eps, rope_theta=cfg.rope_theta,
            tie_word_embeddings=cfg.tie_embeddings)
        if cfg.embed_scale:
            # Gemma: GeGLU + scaled embeddings + (1+w) norms (unfolded
            # below); loading/tying falls through to the shared tail
            if (cfg.mlp_act != "gelu" or not cfg.tie_embeddings
                    or cfg.rope_scaling is not None
                    or cfg.sliding_window is not None
                    or cfg.attention_qkv_bias):
                raise NotImplementedError(
                    "embed_scale exports as Gemma, which requires "
                    "mlp_act='gelu', tied embeddings, plain RoPE, no "
                    "window, no qkv bias")
            hf_cfg = transformers.GemmaConfig(
                hidden_activation="gelu_pytorch_tanh", **common)
            model = transformers.GemmaForCausalLM(hf_cfg)
            sd = llama_state_dict(cfg, params, norm_offset=1.0)
        elif cfg.mlp_act != "silu":
            raise NotImplementedError(
                "mlp_act='gelu' without embed_scale has no HF model_type "
                "(Llama/Mistral/Qwen2 are SwiGLU); exporting as SwiGLU "
                "would silently change the MLP")
        elif cfg.attention_qkv_bias:
            # Qwen2: llama blocks + always-on q/k/v biases
            if cfg.rope_scaling is not None:
                raise NotImplementedError(
                    "attention_qkv_bias + rope_scaling: Qwen2Config carries "
                    "no llama3 rope_scaling field")
            hf_cfg = transformers.Qwen2Config(
                use_sliding_window=cfg.sliding_window is not None,
                sliding_window=cfg.sliding_window or cfg.max_seq_len,
                # 0: window EVERY exported layer — this framework's window
                # is uniform, and HF's default (28) would silently disable
                # the window on models up to 28 layers
                max_window_layers=0,
                **common)
            model = transformers.Qwen2ForCausalLM(hf_cfg)
        elif cfg.sliding_window is not None:
            if cfg.rope_scaling is not None:
                raise NotImplementedError(
                    "sliding_window + rope_scaling: MistralConfig carries no "
                    "llama3 rope_scaling field")
            hf_cfg = transformers.MistralConfig(
                sliding_window=cfg.sliding_window, **common)
            model = transformers.MistralForCausalLM(hf_cfg)
        else:
            if cfg.rope_scaling is not None:
                factor, low, high, orig = cfg.rope_scaling
                common["rope_scaling"] = {
                    "rope_type": "llama3", "factor": factor,
                    "low_freq_factor": low, "high_freq_factor": high,
                    "original_max_position_embeddings": orig}
            hf_cfg = transformers.LlamaConfig(
                attention_bias=False, mlp_bias=False, **common)
            model = transformers.LlamaForCausalLM(hf_cfg)
        if not cfg.embed_scale:  # the Gemma branch built (and re-folded) sd
            sd = llama_state_dict(cfg, params)
    else:
        raise ValueError(
            f"arch {cfg.arch!r} has no HF equivalent (the ref_decoder block "
            f"is the reference-parity architecture, not a public one)")

    with torch.no_grad():
        # copy: from_numpy on a non-writable jax-exported array warns, and
        # the state dict should own its memory anyway
        missing, unexpected = model.load_state_dict(
            {k: torch.from_numpy(np.array(v)) for k, v in sd.items()},
            strict=False)
    # rotary inv_freq buffers etc. may be "missing" (they are derived), and
    # a tied config intentionally ships no lm_head (transformers ties it to
    # the embedding); any other missing weight or unknown key is a
    # conversion bug
    real_missing = [k for k in missing
                    if "inv_freq" not in k
                    and not (cfg.tie_embeddings and k == "lm_head.weight")]
    if real_missing or unexpected:
        raise RuntimeError(f"export mismatch: missing={real_missing}, "
                           f"unexpected={unexpected}")
    if cfg.tie_embeddings:
        model.tie_weights()  # re-point lm_head at the loaded embedding
    return model.eval()
