"""GPT-2 family configurations (BASELINE.json config ladder entries 2-3).

The reference never instantiates real model families (its Transformer is a
synthetic benchmark model, SURVEY.md C1/C2); these configs extend the same
pipeline machinery to the GPT-2 sizes named as north-star targets
("4-stage 1F1B on GPT-2-small (124M)", "8-stage Interleaved-1F1B on
GPT-2-medium").
"""

from __future__ import annotations

from ..utils.config import ModelConfig


def gpt2_config(name: str = "small", **overrides) -> ModelConfig:
    sizes = {
        "small": dict(dim=768, n_layers=12, n_heads=12),     # 124M
        "medium": dict(dim=1024, n_layers=24, n_heads=16),   # 350M
        "large": dict(dim=1280, n_layers=36, n_heads=20),    # 774M
        "xl": dict(dim=1600, n_layers=48, n_heads=25),       # 1.5B
    }
    if name not in sizes:
        raise ValueError(f"unknown GPT-2 size {name!r}; options: {sorted(sizes)}")
    base = sizes[name]
    kw = dict(vocab_size=50257, ffn_dim=4 * base["dim"], max_seq_len=1024,
              arch="gpt2", **base)
    kw.update(overrides)
    return ModelConfig(**kw)
