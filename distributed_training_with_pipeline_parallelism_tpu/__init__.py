"""TPU-native pipeline-parallel training framework.

A brand-new JAX/XLA/pjit framework with the capabilities of
``aa5490/Distributed-Training-with-Pipeline-Parallelism``: decoder-only
transformer LM training under GPipe / 1F1B / Interleaved-1F1B pipeline
schedules, expressed as single-program SPMD over a device mesh with
``jax.lax.ppermute`` rings instead of torch.distributed P2P over gloo.

Import alias convention: ``import distributed_training_with_pipeline_parallelism_tpu as dtpp``.
"""

from .utils.config import (MeshConfig, ModelConfig, RunConfig, ScheduleConfig,
                           virtual_stages_for)

__all__ = [
    "ModelConfig",
    "MeshConfig",
    "ScheduleConfig",
    "RunConfig",
    "virtual_stages_for",
]

__version__ = "0.1.0"
