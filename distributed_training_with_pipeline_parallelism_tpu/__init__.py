"""TPU-native pipeline-parallel training framework.

A brand-new JAX/XLA/pjit framework with the capabilities of
``aa5490/Distributed-Training-with-Pipeline-Parallelism``: decoder-only
transformer LM training under GPipe / 1F1B / Interleaved-1F1B pipeline
schedules, expressed as single-program SPMD over a device mesh with
``jax.lax.ppermute`` rings instead of torch.distributed P2P over gloo.

Import alias convention: ``import distributed_training_with_pipeline_parallelism_tpu as dtpp``.
"""

from .utils.config import (MeshConfig, ModelConfig, RunConfig, ScheduleConfig,
                           virtual_stages_for)

# Lazy top-level re-exports of the main builders, so the one-import surface
# (``import ... as dtpp``) covers the whole workflow without eagerly pulling
# every subsystem at package import:
#   dtpp.make_mesh(...)              device meshes (data/pipe/model/seq/expert)
#   dtpp.make_pipeline_step(...)     jitted (params, x, y) -> (loss, grads)
#   dtpp.make_pipeline_loss_fn(...)  forward-only eval loss, any dense mesh
#   dtpp.make_pipeline_forward(...)  pipelined batch inference logits
#   dtpp.fsdp_shard_params(...)      pp x fsdp resting placement
#   dtpp.fit(...)                    training loop (optax + orbax)
#   dtpp.ServingEngine(...)          continuous-batching serving (docs/serving.md)
#   dtpp.CheckpointManager(...)      crash-safe checkpoints (docs/resilience.md)
#   dtpp.AnomalyGuard / FaultPlan    anomaly guard + fault injection
_LAZY = {
    "make_mesh": ("parallel.mesh", "make_mesh"),
    "init_multihost": ("parallel.mesh", "init_multihost"),
    "simulate_cpu_devices": ("parallel.mesh", "simulate_cpu_devices"),
    "make_pipeline_step": ("parallel.pipeline", "make_pipeline_step"),
    "make_pipeline_grad_fn": ("parallel.pipeline", "make_pipeline_grad_fn"),
    "make_pipeline_loss_fn": ("parallel.pipeline", "make_pipeline_loss_fn"),
    "make_pipeline_forward": ("parallel.pipeline", "make_pipeline_forward"),
    "make_pipeline_generate_fn": ("parallel.pipelined_decode",
                                  "make_pipeline_generate_fn"),
    "fsdp_shard_params": ("parallel.pipeline", "fsdp_shard_params"),
    "register_schedule": ("parallel.schedules", "register_schedule"),
    "compile_schedule": ("parallel.schedules", "compile_schedule"),
    "fit": ("utils.train", "fit"),
    "evaluate": ("utils.train", "evaluate"),
    "make_eval_fn": ("utils.train", "make_eval_fn"),
    "run_all_experiments": ("utils.sweep", "run_all_experiments"),
    "run_one_experiment": ("utils.sweep", "run_one_experiment"),
    "MoEConfig": ("models.moe", "MoEConfig"),
    "AnomalyGuard": ("utils.resilience", "AnomalyGuard"),
    "CheckpointManager": ("utils.resilience", "CheckpointManager"),
    "FaultPlan": ("utils.resilience", "FaultPlan"),
    "Request": ("serving", "Request"),
    "ServingEngine": ("serving", "ServingEngine"),
    "make_serving_step_fn": ("serving", "make_serving_step_fn"),
    "run_serve_bench": ("serving.bench", "run_serve_bench"),
    "run_paged_bench": ("serving.bench", "run_paged_bench"),
    # static analysis (docs/static_analysis.md)
    "check_table": ("analysis", "check_table"),
    "TableReport": ("analysis", "TableReport"),
    "audit_fn": ("analysis", "audit_fn"),
    "lint_repo": ("analysis", "lint_repo"),
    "run_checks": ("analysis", "run_checks"),
}


def __getattr__(name):
    if name in _LAZY:
        import importlib
        mod, attr = _LAZY[name]
        value = getattr(importlib.import_module(f".{mod}", __name__), attr)
        globals()[name] = value  # cache: next access skips __getattr__
        return value
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(__all__))  # completion sees lazy names


__all__ = [
    "ModelConfig",
    "MeshConfig",
    "ScheduleConfig",
    "RunConfig",
    "virtual_stages_for",
    *sorted(_LAZY),
]

__version__ = "0.2.0"
