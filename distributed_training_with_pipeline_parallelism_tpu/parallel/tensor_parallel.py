"""Tensor (intra-layer) parallelism via GSPMD sharding annotations.

SURVEY.md §2.4 marks TP as the natural extension beyond the reference's
scope; here it is the scaling-book recipe verbatim: pick a mesh, annotate
parameter shardings (attention heads and FFN hidden dim split over a
'model' axis — the Megatron column/row-parallel pattern), and let XLA's
GSPMD partitioner insert the all-reduces. No manual collectives at all —
contrast with the pipeline executor, which is manual SPMD because schedules
need explicit control.

Composes with data parallelism (add a 'data' axis and shard the batch).

The manual-SPMD side (the pipeline executor's in-``shard_map`` TP) adds
the **collective-matmul wrappers** here: :func:`tp_all_gather_matmul` and
:func:`tp_matmul_reduce_scatter` are the canonical fused TP-boundary
matmuls behind ``ModelConfig.tp_overlap`` — ``"ring"`` dispatches to the
overlapped ring forms in :mod:`..ops.collectives`, ``"none"`` is the
unfused gather-then-matmul reference. They are also the *only* legal call
sites of bare ``jax.lax.all_gather`` / ``jax.lax.psum_scatter`` in this
module (``scripts/repo_lint.py`` enforces it), so every TP boundary
collective stays routed through one overlap-dispatchable seam.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.transformer import transformer_loss
from ..utils.config import ModelConfig
from .mesh import DATA_AXIS, MODEL_AXIS

TP_AXIS = MODEL_AXIS  # one axis-name constant: pipeline TP shards onto it

Pytree = Any


# ---------------------------------------------------------------------------
# Collective-matmul wrappers (the tp_overlap seam)
# ---------------------------------------------------------------------------


def resolve_tp_overlap(mode: str, axis_size: int, seq_len: int) -> str:
    """Resolve a ``ModelConfig.tp_overlap`` knob to a concrete mode.

    ``"none"`` keeps the unfused Megatron path bitwise unchanged.
    ``"ring"`` demands the fused forms (raises when the sequence cannot be
    chunked ``axis_size`` ways). ``"auto"`` picks ``"ring"`` on TPU when
    the shapes divide — the ring decomposition only *wins* where the hop
    rides a real ICI link — and falls back to ``"none"`` on the CPU proxy
    (where ppermute is a copy and the unfused collectives are cheaper to
    compile).
    """
    if mode not in ("none", "ring", "auto"):
        raise ValueError(f"tp_overlap must be 'none', 'ring' or 'auto', "
                         f"got {mode!r}")
    divisible = axis_size > 1 and seq_len % axis_size == 0
    if mode == "ring":
        if not divisible:
            raise ValueError(
                f"tp_overlap='ring' needs seq_len ({seq_len}) divisible by "
                f"the model-axis size ({axis_size}) > 1")
        return "ring"
    if mode == "auto":
        return ("ring" if divisible and jax.default_backend() == "tpu"
                else "none")
    return "none"


def tp_all_gather_matmul(x_loc: jax.Array, w: jax.Array, axis_name: str,
                         axis_size: int, mode: str = "none") -> jax.Array:
    """TP up-projection over a sequence-sharded input:
    ``all_gather(x, seq) @ w`` -> full-seq column-sharded ``[B, S, F/T]``.

    ``mode="ring"`` overlaps the gather into the matmul
    (:func:`ops.collectives.all_gather_matmul`, bit-identical);
    ``"none"`` is the unfused reference and the wrappers' one legal bare
    ``jax.lax.all_gather`` site."""
    if mode == "ring":
        from ..ops.collectives import all_gather_matmul
        return all_gather_matmul(x_loc, w, axis_name, axis_size)
    return jax.lax.all_gather(x_loc, axis_name, axis=1, tiled=True) @ w


def tp_matmul_reduce_scatter(z: jax.Array, w: jax.Array, axis_name: str,
                             axis_size: int, mode: str = "none") -> jax.Array:
    """TP down-projection completing into a sequence-sharded output:
    ``reduce_scatter(z @ w, seq)`` -> this rank's chunk ``[B, S/T, d]``.

    ``mode="ring"`` overlaps the scatter into the matmul
    (:func:`ops.collectives.matmul_reduce_scatter`; ring summation order,
    so parity with the unfused form is numerical); ``"none"`` is the
    unfused reference and the wrappers' one legal bare
    ``jax.lax.psum_scatter`` site."""
    if mode == "ring":
        from ..ops.collectives import matmul_reduce_scatter
        return matmul_reduce_scatter(z, w, axis_name, axis_size)
    return jax.lax.psum_scatter(z @ w, axis_name, scatter_dimension=1,
                                tiled=True)


def make_tp_mesh(n_model: int, n_data: int = 1, devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    need = n_model * n_data
    if len(devices) < need:
        raise ValueError(f"need {need} devices, have {len(devices)}")
    grid = np.asarray(devices[:need]).reshape(n_data, n_model)
    return Mesh(grid, (DATA_AXIS, TP_AXIS))


def _layer_specs(cfg: ModelConfig) -> dict:
    """PartitionSpecs for one arch's stacked layer leaves (leading axis =
    layer). Column-parallel: QKV and FFN-in split on the output feature dim;
    row-parallel: attention-out and FFN-out split on the input feature dim,
    whose partial sums GSPMD all-reduces."""
    col = {"w": P(None, None, TP_AXIS), "b": P(None, TP_AXIS)}
    row = {"w": P(None, TP_AXIS, None), "b": P(None)}
    col_nb = {"w": P(None, None, TP_AXIS)}
    row_nb = {"w": P(None, TP_AXIS, None)}
    ln = {"scale": P(None), "bias": P(None)}
    rms = {"scale": P(None)}
    attn = {"q": col, "k": col, "v": col, "o": row}
    attn_nb = {"q": col_nb, "k": col_nb, "v": col_nb, "o": row_nb}
    # Qwen2-family llama blocks: q/k/v carry biases (column-split with their
    # matrices), o stays bias-free
    attn_qkvb = {"q": col, "k": col, "v": col, "o": row_nb}
    if cfg.arch == "ref_decoder":
        return {"self_attn": attn, "cross_attn": attn, "ln1": ln, "ln2": ln,
                "ln3": ln, "lin1": col, "lin2": row}
    if cfg.arch == "gpt2":
        return {"ln1": ln, "attn": attn, "ln2": ln, "lin1": col, "lin2": row}
    if cfg.arch == "llama":
        return {"rms1": rms,
                "attn": attn_qkvb if cfg.attention_qkv_bias else attn_nb,
                "rms2": rms, "w1": col_nb, "w2": row_nb, "w3": col_nb}
    raise ValueError(cfg.arch)


def pipeline_layer_specs(cfg: ModelConfig, pipe_axis: str) -> dict:
    """Specs for the pipeline executor's stacked layer layout
    ``[devices, virtual, layers_per_stage, ...]``: the single leading layer
    axis of :func:`_layer_specs` becomes (pipe, None, None), the Megatron
    column/row placement of the trailing weight dims carries over. This is
    what lets TP compose with PP on a 3-D ``data x pipe x model`` mesh."""
    return jax.tree.map(
        lambda s: P(pipe_axis, None, None, *s[1:]), _layer_specs(cfg),
        is_leaf=lambda x: isinstance(x, P))


def param_specs(cfg: ModelConfig) -> dict:
    """PartitionSpec pytree for the full model: embeddings replicated, layer
    matmuls Megatron-sharded, output head column-parallel over the vocab."""
    embed = {"tok": P(None, None)}
    if cfg.arch == "gpt2":
        embed["pos"] = P(None, None)
    head_out = ({"w": P(None, TP_AXIS), "b": P(TP_AXIS)}
                if cfg.arch == "ref_decoder" else {"w": P(None, TP_AXIS)})
    norm = {"scale": P(None)} if cfg.arch == "llama" else \
        {"scale": P(None), "bias": P(None)}
    return {"embed": embed, "layers": _layer_specs(cfg),
            "head": {"norm": norm, "out": head_out}}


def shard_params(params: Pytree, cfg: ModelConfig, mesh: Mesh) -> Pytree:
    """Place a host pytree onto the mesh with TP shardings."""
    specs = param_specs(cfg)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs,
        is_leaf=lambda x: isinstance(x, P))


def make_tp_grad_fn(cfg: ModelConfig, mesh: Mesh,
                    ) -> Callable[[Pytree, jax.Array, jax.Array],
                                  Tuple[jax.Array, Pytree]]:
    """Jitted TP (loss, grads): the model function is the plain single-device
    ``transformer_loss``; parallelism comes entirely from input shardings +
    GSPMD propagation. Batch is sharded over 'data' when that axis exists."""
    specs = param_specs(cfg)
    n_data = mesh.shape.get(DATA_AXIS, 1)
    data_spec = P(DATA_AXIS) if n_data > 1 else P()
    in_sh = (
        jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                     is_leaf=lambda x: isinstance(x, P)),
        NamedSharding(mesh, data_spec),
        NamedSharding(mesh, data_spec),
    )

    def vg(params, tokens, targets):
        with jax.named_scope("tp/value_and_grad"):
            return jax.value_and_grad(
                lambda p: transformer_loss(cfg, p, tokens, targets))(params)

    return jax.jit(vg, in_shardings=in_sh)
