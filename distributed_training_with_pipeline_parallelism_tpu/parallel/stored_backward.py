"""Residual taint analysis for the stored-activation pipeline backward.

The tick executor's stored-activation mode (``remat_backward=False`` on
:func:`.pipeline.make_pipeline_grad_fn`) banks the stage body's ``jax.vjp``
residuals in slot-addressed buffers at forward time and replays them at
backward time — the TPU-native analog of how the reference's torch autograd
stashes saved tensors per microbatch and never recomputes the forward
(``LLMsDistributedTrainingHelper.py:98-143`` via upstream
``stage.py:857/937``).

``jax.vjp``'s returned pullback is a pytree whose leaves are *all* values the
backward needs — which includes the stage *weights* (a matmul's input
cotangent needs W) and cheap derived values (bf16 casts, RoPE tables, causal
masks). Storing those per in-flight microbatch would replicate parameters
per slot. This module answers, mechanically, "which residual leaves actually
depend on the stage input x?":

- **x-dependent leaves** are the true activations (layer inputs, attention
  statistics, FFN intermediates, dropout bits) — these get slot buffers.
- **x-independent leaves** are pure functions of (params, chunk index,
  microbatch index) — the backward unit re-traces the same vjp with a dummy
  x and takes these leaves from the fresh trace; the dummy trace's
  x-dependent chain feeds nothing (the stored leaves replace it) and XLA's
  dead-code elimination removes it, so no forward matmul is recomputed.

**Slot-buffer lifetime under the phase-compressed executor** (``unroll_
ticks="phases"``): the residual slot buffers live in the tick carry, and
:func:`.pipeline._phase_compressed_ticks` threads ONE carry through every
per-phase ``lax.scan`` — a residual banked by a forward tick in one phase
(e.g. the warmup) survives phase boundaries untouched until the backward
tick that consumes it, possibly several scans later (1F1B's last warmup
residuals are read deep into the cooldown). Nothing about slot lifetime is
phase-local: slots are allocated against the WHOLE table
(``schedules._allocate_slots``), phases only re-group the iteration order
of the same rows, and the per-phase scans neither reset nor re-shape the
carry. The one interaction to keep in mind is memory, not correctness:
each scan boundary materializes the full carry — including every slot
buffer — in HBM, so the stored policy pays the buffer HBM round-trip once
per phase transition rather than once per tick (cheaper than the plain
scan, more than the fully unrolled form, where XLA may keep residuals in
registers across ticks).

The analysis is a conservative taint propagation over the jaxpr of the
residual extraction, descending into scan (with carry-feedback fixpoint),
cond (union over branches), and single-subjaxpr call primitives
(pjit/remat/custom_vjp); unknown higher-order primitives fall back to
"any tainted input taints every output", which can only over-store, never
under-store — correctness does not depend on the classification, only
memory does (tests/test_stored_backward.py pins both).
"""

from __future__ import annotations

from typing import Callable, List, Sequence, Tuple

import jax
import numpy as np

try:  # jax >= 0.5 moved the public jaxpr types
    from jax.extend.core import Var
except Exception:  # pragma: no cover - older jax
    from jax.core import Var  # type: ignore


def _eqn_out_taint(eqn, in_taint: List[bool]) -> List[bool]:
    """Taint of one equation's outputs given its inputs' taint."""
    prim = eqn.primitive.name
    if prim == "scan":
        body = eqn.params["jaxpr"].jaxpr
        n_consts = eqn.params["num_consts"]
        n_carry = eqn.params["num_carry"]
        # fixpoint over the carry feedback loop (monotone, so it terminates
        # in <= n_carry iterations)
        t_in = list(in_taint)
        while True:
            out_t = _jaxpr_out_taint(body, t_in)
            new_in = list(t_in)
            for i in range(n_carry):
                if out_t[i]:
                    new_in[n_consts + i] = True
            if new_in == t_in:
                break
            t_in = new_in
        return _jaxpr_out_taint(body, t_in)
    if prim == "cond":
        op_taint = in_taint[1:]  # invars = [branch index, *operands]
        outs: List[bool] | None = None
        for br in eqn.params["branches"]:
            o = _jaxpr_out_taint(br.jaxpr, op_taint)
            outs = o if outs is None else [a or b for a, b in zip(outs, o)]
        assert outs is not None
        return outs
    if prim == "while":
        # conservative: loop-carried mixing
        return [any(in_taint)] * len(eqn.outvars)
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        sub = eqn.params.get(key)
        if sub is None:
            continue
        body = sub.jaxpr if hasattr(sub, "jaxpr") else sub
        pad = len(body.invars) - len(in_taint)
        if pad < 0:  # unexpected arity: be conservative
            return [any(in_taint)] * len(eqn.outvars)
        # custom_vjp/jvp prepend rule operands; padding with False is safe
        # because those extra invars are not the traced x
        out_t = _jaxpr_out_taint(body, [False] * pad + list(in_taint))
        if len(out_t) >= len(eqn.outvars):
            return out_t[: len(eqn.outvars)]
        return [any(in_taint)] * len(eqn.outvars)
    return [any(in_taint)] * len(eqn.outvars)


def _jaxpr_out_taint(jaxpr, in_taint: Sequence[bool]) -> List[bool]:
    tainted = {v for v, t in zip(jaxpr.invars, in_taint) if t}
    for eqn in jaxpr.eqns:
        eqn_in = [isinstance(v, Var) and v in tainted for v in eqn.invars]
        if not any(eqn_in):
            continue
        for v, t in zip(eqn.outvars, _eqn_out_taint(eqn, eqn_in)):
            if t:
                tainted.add(v)
    return [isinstance(v, Var) and v in tainted for v in jaxpr.outvars]


def x_dependent_mask(fn: Callable, args: Tuple, x_argnums: Sequence[int],
                     ) -> List[bool]:
    """Per-output bool: does output i of ``fn(*args)`` depend on any of
    ``args[j] for j in x_argnums``?  ``fn`` must return a flat tuple of
    arrays (use it on the flattened-vjp-leaf extraction). Closure values of
    ``fn`` become jaxpr constants — untainted by construction, which is
    exactly right: they are live at backward time."""
    closed = jax.make_jaxpr(fn)(*args)
    jaxpr = closed.jaxpr
    flat_sizes = [len(jax.tree.leaves(a)) for a in args]
    starts = np.cumsum([0] + flat_sizes)
    in_taint = [False] * len(jaxpr.invars)
    for i in x_argnums:
        for k in range(int(starts[i]), int(starts[i + 1])):
            in_taint[k] = True
    return _jaxpr_out_taint(jaxpr, in_taint)


def check_residual_leaves(leaves, struct, where: str) -> None:
    """Trace-time invariant: the live vjp trace must produce the same
    residual list (count, shapes, dtypes, order) as the abstract trace the
    slot buffers were allocated from. A mismatch means the two traces of
    the stage body diverged — raise before silent corruption."""
    if len(leaves) != len(struct):
        raise RuntimeError(
            f"stored-activation backward: residual count diverged at "
            f"{where} ({len(leaves)} leaves vs {len(struct)} at "
            f"allocation); the stage body traced differently between "
            f"forward and allocation — please report this configuration")
    for i, (l, s) in enumerate(zip(leaves, struct)):
        if tuple(l.shape) != tuple(s.shape) or l.dtype != s.dtype:
            raise RuntimeError(
                f"stored-activation backward: residual {i} diverged at "
                f"{where}: {l.shape}/{l.dtype} vs allocated "
                f"{s.shape}/{s.dtype}")
