"""Ring attention: sequence-parallel exact attention over a mesh axis.

Long-context support the reference does not have (SURVEY.md §5 long-context
row: fixed seq 128, no CP/ring/Ulysses anywhere) but a TPU-native framework
treats as first-class: the sequence dimension is sharded over a 'seq' mesh
axis, each device holds one K/V chunk, and K/V chunks rotate around the ICI
ring with ``jax.lax.ppermute`` while every device accumulates its queries'
attention with a numerically-stable online softmax (the blockwise/flash
recurrence of Liu et al., arXiv:2310.01889; Dao et al., arXiv:2205.14135).

Memory per device is O(seq/D) activations; compute overlaps with the ring
transfer (XLA pipelines the next chunk's ppermute with the current block's
matmuls since they are independent in the dataflow graph). Gradients come
from plain ``jax.grad`` — ``ppermute``'s transpose is the reverse-ring
``ppermute``, so the backward pass is itself a ring pass.

All math below runs inside ``shard_map``; use :func:`ring_mha_apply` as a
drop-in for ``ops.attention.mha_apply`` when the sequence axis is sharded.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ..ops.attention import qkv_project, rope_frequencies
from ..ops.layers import linear_apply

NEG_INF = -1e30


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, axis_name: str,
                   causal: bool = False, dropout_rate: float = 0.0,
                   dropout_rng: Optional[jax.Array] = None,
                   window: Optional[int] = None) -> jax.Array:
    """Exact attention with sequence sharded over ``axis_name``.

    q, k, v: [batch, seq_local, heads, head_dim] (per-device shards; K/V head
    count may differ from Q's for GQA — repeat before calling). Returns the
    attention output for the local query chunk, identical (up to float
    associativity) to unsharded attention over the full sequence.

    ``dropout_rng`` (train mode) enables attention-probability dropout with
    dropout-after-softmax semantics (torch parity): the kept probabilities
    are rescaled by 1/keep while the softmax DENOMINATOR stays unmasked —
    blockwise, ``l`` accumulates the raw ``p`` and only the value-weighted
    accumulation uses the masked/rescaled copy, which is exactly
    ``dropout(softmax(s)) @ V`` after the final ``o / l``. Each block's
    mask is keyed on the (query-chunk, key-chunk) GLOBAL coordinates
    (``fold_in(rng, my)`` then ``fold_in(·, src)``), so it is invariant to
    which ring step processes the pair — the full [S, S] mask is a
    deterministic function of (rng, shard layout) that an unsharded oracle
    can reconstruct block by block (tests/test_ring_attention.py).

    ``window`` (requires ``causal``) applies the Mistral sliding-window
    band — query i attends keys in ``[i - window + 1, i]`` — via the same
    global-coordinate block mask the causal case uses. Every ring step
    still runs (the schedule is static under ``lax.scan``), so unlike the
    Pallas kernel's block skipping this saves memory, not FLOPs; its
    value is composition: windowed models whose sequences only fit
    sharded.
    """
    if window is not None and (not causal or window < 1):
        raise ValueError("window requires causal attention and window >= 1")
    D = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, s_q, h, dh = q.shape
    s_kv = k.shape[1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(dh, jnp.float32))
    perm = [(i, (i + 1) % D) for i in range(D)]
    use_dropout = dropout_rng is not None and dropout_rate > 0.0
    rng_q = (jax.random.fold_in(dropout_rng, my) if use_dropout else None)

    qf = q.astype(jnp.float32)

    def block_update(carry, kv_and_src):
        m, l, o, k_cur, v_cur, src = carry
        # scores for this block: [b, h, s_q, s_kv] in f32
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_cur.astype(jnp.float32)) * scale
        if causal:
            iq = jnp.arange(s_q)[:, None] + my * s_q
            ik = jnp.arange(s_kv)[None, :] + src * s_kv
            keep = iq >= ik
            if window is not None:
                keep = keep & (iq - ik < window)
            s = jnp.where(keep[None, None], s, NEG_INF)
        m_blk = jnp.max(s, axis=-1)  # [b, h, s_q]
        m_new = jnp.maximum(m, m_blk)
        # guard fully-masked rows (exp(NEG_INF - NEG_INF) would be 1)
        p = jnp.exp(s - m_new[..., None])
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        alpha = jnp.exp(m - m_new)
        alpha = jnp.where(m <= NEG_INF / 2, 0.0, alpha)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        if use_dropout:
            keep = jax.random.bernoulli(jax.random.fold_in(rng_q, src),
                                        1.0 - dropout_rate, s.shape)
            p_v = jnp.where(keep, p, 0.0) / (1.0 - dropout_rate)
        else:
            p_v = p
        o_new = (o * alpha[..., None]
                 + jnp.einsum("bhqk,bkhd->bhqd", p_v,
                              v_cur.astype(jnp.float32)))
        # rotate K/V to the next device; chunk provenance rotates with it
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        src_nxt = jax.lax.ppermute(src, axis_name, perm)
        return (m_new, l_new, o_new, k_nxt, v_nxt, src_nxt), None

    m0 = jnp.full((b, h, s_q), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_q), jnp.float32)
    o0 = jnp.zeros((b, h, s_q, dh), jnp.float32)
    carry = (m0, l0, o0, k, v, my)
    carry, _ = jax.lax.scan(block_update, carry, None, length=D)
    _, l, o, _, _, _ = carry
    l = jnp.maximum(l, 1e-30)  # fully-masked rows (never happens for causal q>=0)
    out = (o / l[..., None]).transpose(0, 2, 1, 3)  # [b, s_q, h, dh]
    return out.astype(q.dtype)


def ring_mha_apply(params: Dict, q_in: jax.Array, kv_in: jax.Array,
                   n_heads: int, axis_name: str, causal: bool = False,
                   rope_angles: Optional[jax.Array] = None,
                   tp_axis: Optional[str] = None,
                   dropout_rate: float = 0.0,
                   dropout_rng=None,
                   window: Optional[int] = None) -> jax.Array:
    """Sequence-parallel drop-in for ``ops.attention.mha_apply``: projections
    are local (they are position-wise), attention runs over the ring.

    ``rope_angles`` must already be sliced to this device's global positions
    (see :func:`local_rope_angles`). With ``tp_axis`` the projections are
    additionally Megatron head-sharded over that axis (``n_heads`` = local
    head count, weights = local shards), composing sequence and tensor
    parallelism: the ring rotates this model-shard's K/V heads over 'seq'
    within each model column.
    """
    from ..ops.collectives import tp_attention_inputs, tp_output_projection
    b, s, _ = q_in.shape
    q_in, kv_in = tp_attention_inputs(q_in, kv_in, tp_axis)
    q, k, v = qkv_project(params, q_in, kv_in, n_heads, rope_angles)
    if dropout_rng is not None and tp_axis is not None:
        # each model rank holds a DIFFERENT head shard, so its attention
        # dropout must draw a distinct stream — without this fold every TP
        # rank reuses one mask across head groups (head i and head i+h/T
        # correlate) and the realized mask depends on the TP degree
        dropout_rng = jax.random.fold_in(dropout_rng,
                                         jax.lax.axis_index(tp_axis))
    out = ring_attention(q, k, v, axis_name, causal=causal,
                         dropout_rate=dropout_rate, dropout_rng=dropout_rng,
                         window=window)
    return tp_output_projection(params["o"], out.reshape(b, s, -1), tp_axis)


def local_rope_angles(cfg, seq_local: int, axis_name: str) -> jax.Array:
    """RoPE angles for this device's global position range."""
    my = jax.lax.axis_index(axis_name)
    D = jax.lax.psum(1, axis_name)
    full = rope_frequencies(cfg.head_dim, seq_local * D, cfg.rope_theta,
                            cfg.rope_scaling)
    return jax.lax.dynamic_slice_in_dim(full, my * seq_local, seq_local, axis=0)
