"""Device-mesh construction for pipeline (+ data) parallelism.

TPU-native replacement for the reference's process-group lifecycle
(``dist.init_process_group('gloo')`` with env-var rendezvous,
``LLMsDistributedTrainingHelper.py:168-178`` — SURVEY.md §2.4): a
``jax.sharding.Mesh`` over the slice's devices. Axis order is
('data', 'pipe') so pipeline ppermute hops ride the fastest (innermost,
ICI-adjacent) axis; multi-host DCN is handled transparently by
``jax.distributed`` + XLA.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
PIPE_AXIS = "pipe"
MODEL_AXIS = "model"  # tensor_parallel.TP_AXIS aliases this
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"


def _make_1d_mesh(n: int, axis_name: str, devices=None) -> "Mesh":
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < n:
        raise ValueError(f"need {n} devices, have {len(devices)}")
    return Mesh(np.asarray(devices[:n]), (axis_name,))


def make_sp_mesh(n_seq: int, devices=None) -> "Mesh":
    """1-D sequence-parallel mesh for ring attention."""
    return _make_1d_mesh(n_seq, SEQ_AXIS, devices)


def make_ep_mesh(n_expert: int, devices=None) -> "Mesh":
    """1-D expert-parallel mesh for MoE all-to-all dispatch."""
    return _make_1d_mesh(n_expert, EXPERT_AXIS, devices)


def make_mesh(n_pipe: int, n_data: int = 1, n_model: int = 1, n_seq: int = 1,
              n_expert: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the pipeline mesh: ('data', 'pipe'), growing a 'model' axis
    (tensor parallelism inside stages), a 'seq' axis (ring-attention
    sequence parallelism inside stages), and/or an 'expert' axis (MoE
    expert parallelism inside stages) when those sizes exceed 1. Extra
    axes are innermost — the highest-traffic collectives ride the shortest
    ICI hops."""
    devices = list(devices if devices is not None else jax.devices())
    sizes = [("n_data", DATA_AXIS, n_data), ("n_pipe", PIPE_AXIS, n_pipe)]
    if n_model > 1:
        sizes.append(("n_model", MODEL_AXIS, n_model))
    if n_seq > 1:
        sizes.append(("n_seq", SEQ_AXIS, n_seq))
    if n_expert > 1:
        sizes.append(("n_expert", EXPERT_AXIS, n_expert))
    need = int(np.prod([n for _, _, n in sizes]))
    if len(devices) < need:
        detail = ", ".join(f"{name[2:]}={n}" for name, _, n in sizes)
        raise ValueError(
            f"need {need} devices for mesh ({detail}), have {len(devices)}; "
            f"for CPU simulation set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=N before "
            f"importing jax (the JAX analog of the reference's "
            f"gloo-on-localhost trick)")
    grid = np.asarray(devices[:need]).reshape([n for _, _, n in sizes])
    return Mesh(grid, tuple(axis for _, axis, _ in sizes))


def init_multihost(coordinator_address: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None) -> None:
    """Initialize JAX's multi-host runtime for pod slices.

    TPU-native replacement for the reference's env-var rendezvous +
    ``init_process_group`` (``LLMsDistributedTrainingHelper.py:168-175``): on
    Cloud TPU the arguments auto-detect from the metadata server; elsewhere
    pass coordinator ``host:port``, world size, and this process's rank.
    After this, ``jax.devices()`` spans the slice and meshes built by
    :func:`make_mesh` place inter-host edges on DCN transparently (XLA
    routes collectives ICI-first).
    """
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def simulate_cpu_devices(n: int = 8) -> None:
    """Force an n-device simulated CPU backend (the JAX analog of the
    reference's gloo-on-localhost fake cluster, SURVEY.md §4).

    Must run before the first backend initialization in the process. Two
    traps this helper handles centrally (callers should not hand-roll it):

    - Duplicate ``--xla_force_host_platform_device_count`` flags: the *last*
      occurrence wins, so the requested count is appended — a pre-existing
      count in ``XLA_FLAGS`` (e.g. from the shell) is overridden, not
      silently kept.
    - Platform plugins (e.g. the axon TPU tunnel) auto-select themselves even
      when ``JAX_PLATFORMS=cpu`` is in the environment; only
      ``jax.config.update("jax_platforms", "cpu")`` reliably wins.
    """
    import os

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n}")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax as _jax  # local import: this may be the process's first

    try:
        _jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass  # backend already initialized; caller gets whatever exists
