"""Sequence-parallel training path: full model over a 'seq' mesh axis.

Shards the *sequence* dimension of activations over a 'seq' mesh axis —
embeddings, LayerNorms and MLPs are position-wise (purely local), and the
attention core runs under one of two strategies, selected by ``attn_impl``:
``"ring"`` (K/V ppermute ring, :mod:`.ring_attention`) or ``"ulysses"``
(head-scatter/seq-gather all-to-all, :mod:`.ulysses`). Loss and grads are
exact either way: identical to the unsharded model up to float
associativity.

This is the long-context scaling story the reference lacks entirely
(SURVEY.md §5: fixed seq 128, no sequence parallelism of any kind). It
composes with data parallelism (add a 'data' axis) and is orthogonal to the
pipeline executor.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..models.transformer import ModelConfig
from ..ops.layers import (select_xent, embedding_apply,
                          layer_norm_apply, linear_apply, rms_norm_apply)
from .mesh import SEQ_AXIS
from .pipeline import _shard_map
from .ring_attention import local_rope_angles, ring_mha_apply
from .ulysses import ulysses_mha_apply

Pytree = Any

ATTN_IMPLS = {"ring": ring_mha_apply, "ulysses": ulysses_mha_apply}


def sp_layer_apply(cfg: ModelConfig, params, h: jax.Array, axis_name: str,
                   rope_angles, attn_impl: str = "ring",
                   tp_axis: Optional[str] = None, tp_size: int = 1,
                   rng: Optional[jax.Array] = None,
                   sp_size: int = 1) -> jax.Array:
    """Sequence-sharded twin of ``models.transformer.layer_apply``.

    With ``tp_axis`` the block is additionally Megatron tensor-parallel
    (ring or, since round 5, Ulysses attention): weight leaves are local
    model-axis shards, norms replicated — the 4-D
    ``data x pipe x model x seq`` composition. Under Ulysses the local
    head shard must further divide by the seq-axis size.

    ``rng`` (train mode) enables dropout at the same sites (and with the
    same per-site streams) as the dense ``layer_apply``: residual and
    FFN-inner masks are the full-sequence masks' local slices
    (``sharded_dropout_apply`` over dim 1 with ``sp_size`` shards), and
    attention-prob masks ride Ulysses' post-scatter head blocks — so an sp
    run reproduces the unsharded masks exactly. Ring attention draws its
    attention-prob masks blockwise, keyed on (q-chunk, k-chunk) global
    coordinates (ring-step invariant; see
    :func:`..parallel.ring_attention.ring_attention`) — valid dropout with
    correct after-softmax semantics, though the mask layout is a function
    of the shard count rather than the unsharded oracle's."""
    from ..models.transformer import _ffn_out, _tp_in
    from ..ops.layers import sharded_dropout_apply

    sp_mha = ATTN_IMPLS[attn_impl]
    heads = cfg.n_heads // tp_size
    p = cfg.dropout if rng is not None else 0.0

    def site(i: int) -> Optional[jax.Array]:
        return None if rng is None else jax.random.fold_in(rng, i)

    def drop(x, i):
        """Residual/FFN dropout on a [b, s_local, ...] seq shard."""
        return sharded_dropout_apply(x, p, site(i), axis=axis_name,
                                     n_shards=sp_size, shard_dim=1)

    if cfg.arch == "ref_decoder":
        mem = h
        sa = sp_mha(params["self_attn"], h, h, heads, axis_name,
                    tp_axis=tp_axis, dropout_rate=p, dropout_rng=site(0))
        x = layer_norm_apply(params["ln1"], h + drop(sa, 1))
        ca = sp_mha(params["cross_attn"], x, mem, heads, axis_name,
                    tp_axis=tp_axis, dropout_rate=p, dropout_rng=site(2))
        x = layer_norm_apply(params["ln2"], x + drop(ca, 3))
        ff = _ffn_out(params["lin2"],
                      drop(jax.checkpoint(jax.nn.relu)(
                          linear_apply(params["lin1"],
                                       _tp_in(x, tp_axis))), 4),
                      tp_axis)
        return layer_norm_apply(params["ln3"], x + drop(ff, 5))
    if cfg.arch == "gpt2":
        a = layer_norm_apply(params["ln1"], h)
        attn = sp_mha(params["attn"], a, a, heads, axis_name,
                      causal=True, tp_axis=tp_axis, dropout_rate=p,
                      dropout_rng=site(0))
        h = h + drop(attn, 1)
        m = _tp_in(layer_norm_apply(params["ln2"], h), tp_axis)
        ff = _ffn_out(params["lin2"],
                      jax.checkpoint(jax.nn.gelu)(
                          linear_apply(params["lin1"], m)),
                      tp_axis)
        return h + drop(ff, 2)
    if cfg.arch == "llama":
        a = rms_norm_apply(params["rms1"], h, cfg.rms_eps)
        attn = sp_mha(params["attn"], a, a, heads, axis_name,
                      causal=True, rope_angles=rope_angles, tp_axis=tp_axis,
                      dropout_rate=p, dropout_rng=site(0),
                      window=cfg.sliding_window)
        h = h + drop(attn, 1)
        m = _tp_in(rms_norm_apply(params["rms2"], h, cfg.rms_eps), tp_axis)
        act = jax.nn.silu if cfg.mlp_act == "silu" else jax.nn.gelu
        ff = _ffn_out(params["w2"],
                      jax.checkpoint(lambda a, b: act(a) * b)(
                          linear_apply(params["w1"], m),
                          linear_apply(params["w3"], m)),
                      tp_axis)
        return h + drop(ff, 2)
    raise ValueError(f"unknown arch {cfg.arch!r}")


def sp_embed_apply(cfg: ModelConfig, embed, tokens: jax.Array,
                   axis_name: str, rng: Optional[jax.Array] = None,
                   sp_size: int = 1) -> jax.Array:
    """Sequence-sharded embed: token lookup plus (gpt2) the learned position
    rows offset by this shard's global position. Shared by the standalone
    sp loss and the pipeline executor's seq-sharded stages. ``rng`` applies
    GPT-2's embedding dropout with the full-sequence mask's local slice."""
    from ..ops.layers import sharded_dropout_apply
    x = embedding_apply(embed["tok"], tokens)
    if cfg.embed_scale:
        # Gemma scales embedding OUTPUTS by sqrt(dim) — position-wise, so
        # it applies unchanged to a sequence shard
        x = x * (cfg.dim ** 0.5)
    if cfg.arch == "gpt2":
        my = jax.lax.axis_index(axis_name)
        s_local = tokens.shape[1]
        x = x + jax.lax.dynamic_slice_in_dim(
            embed["pos"], my * s_local, s_local, axis=0)
        x = sharded_dropout_apply(x, cfg.dropout, rng, axis=axis_name,
                                  n_shards=sp_size, shard_dim=1)
    return x


def sp_body_apply(cfg: ModelConfig, layers, h: jax.Array, axis_name: str,
                  attn_impl: str = "ring", tp_axis: Optional[str] = None,
                  tp_size: int = 1, rng: Optional[jax.Array] = None,
                  layer_offset=0, sp_size: int = 1) -> jax.Array:
    """Sequence-sharded twin of ``models.transformer.body_apply``: scan the
    stacked layers with ring/Ulysses attention over ``axis_name``. ``rng``/
    ``layer_offset`` follow the dense body's convention: layer i folds
    ``layer_offset + i`` so masks key off the *global* layer index."""
    rope = (local_rope_angles(cfg, h.shape[1], axis_name)
            if cfg.arch == "llama" else None)
    n = jax.tree.leaves(layers)[0].shape[0]

    def step(carry, xs):
        layer_params, i = xs
        rng_l = (None if rng is None
                 else jax.random.fold_in(rng, layer_offset + i))
        return sp_layer_apply(cfg, layer_params, carry, axis_name, rope,
                              attn_impl=attn_impl, tp_axis=tp_axis,
                              tp_size=tp_size, rng=rng_l,
                              sp_size=sp_size), None

    if cfg.remat_layers:
        step = jax.checkpoint(step)
    h, _ = jax.lax.scan(step, h, (layers, jnp.arange(n)))
    return h


def make_sp_loss_fn(cfg: ModelConfig, mesh: Mesh, attn_impl: str = "ring",
                    ) -> Callable[[Pytree, jax.Array, jax.Array], jax.Array]:
    """Sequence-parallel loss: ``(params, tokens, targets) -> scalar``.
    Differentiable — wrap in ``jax.value_and_grad`` (+jit) for training;
    shard_map's transpose rules turn the forward collectives into the
    matching backward collectives (reverse ring / inverse all-to-all).

    ``attn_impl``: ``"ring"`` (no cap on the parallel degree) or
    ``"ulysses"`` (requires ``n_heads % axis size == 0``)."""
    if attn_impl not in ATTN_IMPLS:
        raise ValueError(f"attn_impl must be one of {sorted(ATTN_IMPLS)}, "
                         f"got {attn_impl!r}")
    D = mesh.shape[SEQ_AXIS]

    def spmd_loss(params, tokens, targets):
        # tokens/targets arrive as [B, S/D] local chunks
        from ..models.transformer import head_apply
        from ..ops.layers import select_masked_xent_sum
        h = sp_embed_apply(cfg, params["embed"], tokens, SEQ_AXIS)
        h = h.astype(jnp.dtype(cfg.dtype))
        h = sp_body_apply(cfg, params["layers"], h, SEQ_AXIS,
                          attn_impl=attn_impl)
        # head (incl. the final norm and the tied-embedding vocab matmul
        # when cfg.tie_embeddings — the table rides in replicated, so its
        # head grad needs no extra collective beyond shard_map's psum)
        logits = head_apply(cfg, params["head"], h,
                            embed=params["embed"] if cfg.tie_embeddings
                            else None)
        if cfg.pad_token_id is not None:
            # ignore-index masking, globally normalized: per-shard masked
            # NLL sums and valid counts psum over 'seq' so the result is
            # total_nll / global_valid_count — NOT a mean of per-shard
            # means, which would overweight shards rich in pad tokens
            # (mirrors the pipeline executor's global_pad_scale)
            s, n = select_masked_xent_sum(cfg.use_fused_xent)(
                logits, targets, cfg.pad_token_id)
            s = jax.lax.psum(s, SEQ_AXIS)
            n = jax.lax.psum(n.astype(jnp.float32), SEQ_AXIS)
            return s / jnp.maximum(n, 1.0)
        local = select_xent(cfg.use_fused_xent)(logits, targets)  # mean over local tokens
        return jax.lax.psum(local, SEQ_AXIS) / D  # equal chunks -> global mean

    return _shard_map(
        spmd_loss, mesh,
        in_specs=(P(), P(None, SEQ_AXIS), P(None, SEQ_AXIS)),
        out_specs=P(),
    )
