"""Ulysses-style all-to-all sequence parallelism.

The second classic long-context strategy (alongside ring attention,
:mod:`.ring_attention`) — the reference has neither (SURVEY.md §5
long-context row). After DeepSpeed-Ulysses (arXiv:2309.14509): activations
stay sequence-sharded ``[B, S/D, dim]`` through all position-wise compute;
around the attention core two ``jax.lax.all_to_all`` collectives re-shard
from sequence-split to *head*-split — each device then holds the FULL
sequence for ``H/D`` of the heads, runs ordinary dense attention on it, and
the inverse all-to-all restores sequence sharding.

Trade-offs vs the ring (why a complete framework carries both):

- Ulysses moves ``O(S * dim / D)`` bytes per device in two fused
  all-to-alls (great on ICI tori, where all-to-all bisection is high) and
  keeps the attention core a single large MXU-friendly matmul; the ring
  issues ``D`` ppermute hops but never materializes full-sequence scores.
- Ulysses caps the parallel degree at the head count (``H % D == 0``); the
  ring has no such cap (useful for GQA models with few KV heads).

Gradients come from plain ``jax.grad``: ``all_to_all`` is its own transpose
(with split/concat axes swapped), so the backward pass is also two
all-to-alls — no custom VJP needed.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ..ops.attention import gqa_expand, qkv_project, scaled_dot_attention
from ..ops.layers import linear_apply


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str, causal: bool = False,
                      dropout_rate: float = 0.0,
                      dropout_rng=None,
                      window: Optional[int] = None) -> jax.Array:
    """Exact attention with the sequence sharded over ``axis_name``.

    q, k, v: [batch, seq_local, heads, head_dim] per-device shards. Q heads
    must divide by the axis size; K/V may carry fewer (GQA) heads — when
    those also divide by the axis size they are all-to-all'd *unexpanded*
    (saving n_heads/n_kv_heads of the K/V communication volume) and expanded
    after the gather, otherwise they are expanded up front. Returns the local
    query chunk's attention output, identical to unsharded attention up to
    float associativity.
    """
    if window is not None and (not causal or window < 1):
        raise ValueError("window requires causal attention and window >= 1")
    D = jax.lax.psum(1, axis_name)
    h, h_kv = q.shape[2], k.shape[2]
    if h % D != 0:
        raise ValueError(f"Ulysses needs n_heads % axis size == 0 ({h} % {D})")
    if h_kv % D != 0:  # too few KV heads to split: expand before the scatter
        k, v = gqa_expand(k, v, h)

    def scatter_heads(x):  # [b, s/D, h, dh] -> [b, s, h/D, dh]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    q, k, v = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    k, v = gqa_expand(k, v, q.shape[2])  # no-op if already expanded
    mask = None
    if causal:
        # post-scatter each device holds the FULL sequence for its head
        # block, so the (optionally windowed) band mask is the ordinary
        # dense one — no global-coordinate bookkeeping needed
        from ..ops.attention import band_mask
        s = q.shape[1]
        mask = band_mask(s, s, window)[None, None]
    # post-scatter the probs are [b, h/D, s, s] — a head-block shard of the
    # unsharded probs, so attention-prob dropout uses the same axis-aware
    # full-draw+slice masks as tensor parallelism (oracle-exact)
    out = scaled_dot_attention(q, k, v, mask, dropout_rate, dropout_rng,
                               head_shard=(axis_name, D)
                               if dropout_rng is not None else None)
    # [b, s, h/D, dh] -> [b, s/D, h, dh]
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)


def ulysses_mha_apply(params: Dict, q_in: jax.Array, kv_in: jax.Array,
                      n_heads: int, axis_name: str, causal: bool = False,
                      rope_angles: Optional[jax.Array] = None,
                      tp_axis: Optional[str] = None,
                      dropout_rate: float = 0.0,
                      dropout_rng=None,
                      window: Optional[int] = None) -> jax.Array:
    """Sequence-parallel drop-in for ``ops.attention.mha_apply`` (same
    signature as :func:`..ring_attention.ring_mha_apply`): projections are
    position-wise (local); the attention core re-shards via all-to-all.

    ``tp_axis`` (round 5) additionally Megatron-shards the projections
    over that mesh axis — ``n_heads`` is then the LOCAL head count
    (``H / tp_size``, weight leaves local model-axis shards) and the two
    shardings nest: each model column all-to-alls its own head shard over
    'seq', so post-scatter a device owns the full sequence for
    ``H / (tp_size * seq_size)`` heads (requires the local head count to
    divide by the seq-axis size), and the o-projection completes
    row-parallel with one psum. Attention-prob dropout under TP folds the
    model-axis rank into the rng (each model rank holds a DIFFERENT head
    shard — the ring path's rule), so the realized mask layout is a
    function of the TP degree rather than the unsharded oracle's.

    ``rope_angles`` must be pre-sliced to this device's global positions
    (``ring_attention.local_rope_angles``) — rotation happens *before* the
    head-scatter, while rows still sit at their global positions.
    """
    from ..ops.collectives import tp_attention_inputs, tp_output_projection
    b, s, _ = q_in.shape
    q_in, kv_in = tp_attention_inputs(q_in, kv_in, tp_axis)
    q, k, v = qkv_project(params, q_in, kv_in, n_heads, rope_angles,
                          expand_gqa=False)  # expansion happens post-gather
    if dropout_rng is not None and tp_axis is not None:
        dropout_rng = jax.random.fold_in(dropout_rng,
                                         jax.lax.axis_index(tp_axis))
    out = ulysses_attention(q, k, v, axis_name, causal=causal,
                            dropout_rate=dropout_rate,
                            dropout_rng=dropout_rng, window=window)
    return tp_output_projection(params["o"], out.reshape(b, s, -1), tp_axis)
