"""ctypes binding for the native (C++) schedule-compilation engine.

``compile_schedule_native`` produces the same ``CompiledSchedule`` as the
Python compiler in :mod:`.schedules` (tables are asserted bit-identical in
tests); the Python path is the executable specification, this is the fast
production path. The shared library is built on first use with the repo's
``csrc/Makefile`` (plain g++, no external deps); if no compiler is available
the caller should fall back to the Python compiler.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_CSRC = os.path.join(os.path.dirname(__file__), "..", "..", "csrc")


class NativeLib:
    """Lazy, cached loader for one csrc/ shared library.

    First use invokes make (mtime-incremental: a no-op when the .so is
    fresh, a rebuild when the source changed — a stale .so would silently
    misbehave). If no build toolchain is available but a prebuilt and
    source-fresh .so exists, it is loaded anyway. ``configure`` receives the
    CDLL to declare restype/argtypes. Load failure is cached; ``get()``
    then returns None so callers can fall back to their Python twin.
    """

    def __init__(self, so_name: str, src_name: str, configure):
        self._so = os.path.abspath(os.path.join(_CSRC, so_name))
        self._src = os.path.join(_CSRC, src_name)
        self._configure = configure
        self._lock = threading.Lock()
        self._lib: Optional[ctypes.CDLL] = None
        self._failed = False

    def get(self) -> Optional[ctypes.CDLL]:
        with self._lock:
            if self._lib is not None or self._failed:
                return self._lib
            try:
                try:
                    subprocess.run(["make", "-C", os.path.abspath(_CSRC)],
                                   check=True, capture_output=True)
                except (OSError, subprocess.CalledProcessError):
                    if not os.path.exists(self._so):
                        raise
                    if (os.path.exists(self._src)
                            and os.path.getmtime(self._so)
                            < os.path.getmtime(self._src)):
                        raise  # stale .so relative to source; don't trust it
                lib = ctypes.CDLL(self._so)
                self._configure(lib)
                self._lib = lib
            except Exception:
                self._failed = True
            return self._lib


def _configure_schedule_engine(lib: ctypes.CDLL) -> None:
    lib.dtpp_compile_schedule.restype = ctypes.c_int
    lib.dtpp_compile_schedule.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int), ctypes.c_char_p, ctypes.c_int,
    ]


_engine = NativeLib("libschedule_engine.so", "schedule_engine.cpp",
                    _configure_schedule_engine)


def _load() -> Optional[ctypes.CDLL]:
    return _engine.get()


def native_available() -> bool:
    return _load() is not None


def compile_schedule_native(name: str, n_devices: int, n_virtual: int,
                            n_microbatches: int):
    """Native twin of ``schedules.compile_schedule`` (without the Action tick
    map — the table is the executor contract). Raises ScheduleError with the
    engine's message on invalid configs, RuntimeError if the library is
    unavailable."""
    from .schedules import (N_COLS, CompiledSchedule, ScheduleError,
                            verify_table)

    lib = _load()
    if lib is None:
        raise RuntimeError("native schedule engine unavailable (no compiler?)")
    S = n_devices * n_virtual
    n_actions = 3 * S * n_microbatches  # F + B + W upper bound
    cap_ticks = 4 * n_actions + 4 * S + 18
    table = np.full((cap_ticks, n_devices, N_COLS), -1, dtype=np.int32)
    t_out = ctypes.c_int()
    n_act = ctypes.c_int()
    n_grad = ctypes.c_int()
    err = ctypes.create_string_buffer(256)
    rc = lib.dtpp_compile_schedule(
        name.encode(), n_devices, n_virtual, n_microbatches,
        table.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), table.size,
        ctypes.byref(t_out), ctypes.byref(n_act), ctypes.byref(n_grad),
        err, len(err))
    if rc != 0:
        raise ScheduleError(err.value.decode())
    from .schedules import is_split_backward
    cs = CompiledSchedule(
        name=name, n_devices=n_devices, n_virtual=n_virtual,
        n_microbatches=n_microbatches, table=table[: t_out.value].copy(),
        makespan=t_out.value, ticks={}, n_act_slots=n_act.value,
        n_grad_slots=n_grad.value, split_backward=is_split_backward(name))
    verify_table(cs)
    return cs
