"""Expert parallelism: MoE experts sharded over an 'expert' mesh axis.

Beyond-parity capability (SURVEY.md §2.4: the reference has no MoE/EP).
The sharding recipe is the GShard one (arXiv:2006.16668), expressed as
SPMD over a 1-D ``Mesh(('expert',))``:

- The token batch and the expert stacks (leading dim of w1/b1/w2/b2) are
  both sharded over 'expert'; the router and all attention/norm/embed
  parameters are replicated.
- Each device routes its local tokens, packs per-expert capacity slots,
  and exchanges slot blocks with every other device via
  ``jax.lax.all_to_all`` — the ICI-native equivalent of NCCL all-to-all
  dispatch in GPU MoE stacks — runs its resident experts, and reverses
  the exchange. Static slot shapes mean both collectives compile to fixed
  ICI transfers with no data-dependent sizes.
- Gradients need no extra code: ``all_to_all`` is its own transpose, so
  ``jax.grad`` of the shard_mapped loss produces the reverse exchanges.

Composes with data parallelism by treating 'expert' as the data axis for
the non-MoE parameters (they see a plain batch shard).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
from jax.sharding import Mesh, PartitionSpec as P
from jax.tree_util import DictKey

from ..models.moe import MoEConfig, moe_lm_loss
from ..utils.config import ModelConfig
from .mesh import EXPERT_AXIS
from .pipeline import _shard_map

Pytree = Any

_EXPERT_LEAVES = frozenset({"w1", "b1", "w2", "b2"})


def is_expert_leaf(path) -> bool:
    """True iff a pytree path addresses an expert-sharded stack (a leaf
    under a "moe" subtree whose name is one of the expert weight/bias
    stacks). The single source of truth for EP sharding decisions — used
    by :func:`ep_param_specs` and the pipeline executor's spec builder and
    gradient reduction."""
    keys = [k.key for k in path if isinstance(k, DictKey)]
    return bool(keys) and "moe" in keys and keys[-1] in _EXPERT_LEAVES


def ep_param_specs(params: Pytree) -> Pytree:
    """PartitionSpec tree for a MoE LM pytree: expert stacks are sharded on
    their expert dim (axis 1 — axis 0 is the layer stack), everything else
    replicated."""

    def spec(path, _leaf):
        return P(None, EXPERT_AXIS) if is_expert_leaf(path) else P()

    return jax.tree_util.tree_map_with_path(spec, params)


def make_ep_loss_fn(cfg: ModelConfig, moe: MoEConfig, mesh: Mesh,
                    ) -> Callable[[Pytree, jax.Array, jax.Array], jax.Array]:
    """Expert-parallel ``(params, tokens, targets) -> scalar loss``.

    ``params`` is the full (unsharded-layout) pytree from ``moe_lm_init``;
    shard_map's in_specs slice the expert stacks across the mesh. Tokens /
    targets are [B, S] with B divisible by the mesh size. Differentiable —
    wrap in ``jax.value_and_grad`` (+jit) for training. The CE term matches
    the unsharded :func:`..models.moe.moe_lm_loss` exactly when no tokens
    overflow expert capacity; the aux load-balancing term uses *per-shard*
    routing statistics (standard local load balancing), so full-loss
    equality additionally requires ``aux_loss_weight=0`` — see
    tests/test_moe.py."""
    if moe.n_experts % mesh.shape[EXPERT_AXIS] != 0:
        raise ValueError(
            f"n_experts={moe.n_experts} must divide over "
            f"{mesh.shape[EXPERT_AXIS]} expert shards")

    def spmd_loss(params, tokens, targets):
        return moe_lm_loss(cfg, moe, params, tokens, targets,
                           axis_name=EXPERT_AXIS)

    def loss_fn(params, tokens, targets):
        return _shard_map(
            spmd_loss, mesh,
            in_specs=(ep_param_specs(params), P(EXPERT_AXIS), P(EXPERT_AXIS)),
            out_specs=P(),
        )(params, tokens, targets)

    return loss_fn
