"""FSDP / ZeRO-3-style parameter sharding via GSPMD.

Beyond the reference's scope (SURVEY.md §2.4: "full per-stage weights on each
rank"), but first-class here: every parameter leaf is sharded over the 'data'
axis on its largest divisible dimension, the batch is sharded over the same
axis, and XLA's partitioner materializes the classic ZeRO dataflow — params
all-gathered just-in-time per layer, gradients reduce-scattered back to their
shards. No wrapper classes, no hooks: sharding annotations are the whole
implementation, so FSDP composes with the optimizer (optax states inherit the
param shardings) and with tensor parallelism (use a 3-D mesh).
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.transformer import transformer_loss
from ..utils.config import ModelConfig
from .mesh import DATA_AXIS

Pytree = Any


def make_fsdp_mesh(n_data: int, devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < n_data:
        raise ValueError(f"need {n_data} devices, have {len(devices)}")
    return Mesh(np.asarray(devices[:n_data]), (DATA_AXIS,))


def fsdp_specs(params: Pytree, n_shards: int) -> Pytree:
    """Shard each leaf over 'data' on its largest dimension divisible by the
    shard count (replicate scalars/indivisible leaves). Skips axis 0 of
    stacked layer leaves only if a later axis is as large (prefer sharding
    weight matrices over the layer-stack axis)."""

    def spec_for(x) -> P:
        if not hasattr(x, "ndim") or x.ndim == 0:
            return P()  # python scalars (optax counters) and 0-d arrays
        sizes = list(x.shape)
        order = sorted(range(x.ndim), key=lambda i: (sizes[i], i != 0),
                       reverse=True)
        for dim in order:
            if sizes[dim] % n_shards == 0 and sizes[dim] >= n_shards:
                spec = [None] * x.ndim
                spec[dim] = DATA_AXIS
                return P(*spec)
        return P()

    return jax.tree.map(spec_for, params)


def shard_params_fsdp(params: Pytree, mesh: Mesh) -> Pytree:
    """Place a pytree with :func:`fsdp_specs` shardings. Non-array leaves
    (e.g. optax step counters' python ints) pass through untouched, so this
    also serves ZeRO-1 optimizer-state placement (``utils.train``)."""
    n = mesh.shape[DATA_AXIS]

    def place(x, spec):
        if not hasattr(x, "ndim"):
            return x
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(place, params, fsdp_specs(params, n),
                        is_leaf=lambda x: isinstance(x, P))


def make_fsdp_grad_fn(cfg: ModelConfig, mesh: Mesh, params_template: Pytree,
                      ) -> Callable[[Pytree, jax.Array, jax.Array],
                                    Tuple[jax.Array, Pytree]]:
    """Jitted (loss, grads) with ZeRO-sharded params and data-sharded batch.
    Gradients come back sharded like the parameters (reduce-scatter)."""
    n = mesh.shape[DATA_AXIS]
    specs = fsdp_specs(params_template, n)
    in_sh = (
        jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                     is_leaf=lambda x: isinstance(x, P)),
        NamedSharding(mesh, P(DATA_AXIS)),
        NamedSharding(mesh, P(DATA_AXIS)),
    )

    def vg(params, tokens, targets):
        with jax.named_scope("fsdp/value_and_grad"):
            return jax.value_and_grad(
                lambda p: transformer_loss(cfg, p, tokens, targets))(params)

    # out_shardings pins grads to the param shards (reduce-scatter), which
    # XLA would otherwise be free to replicate
    return jax.jit(vg, in_shardings=in_sh,
                   out_shardings=(NamedSharding(mesh, P()), in_sh[0]))
