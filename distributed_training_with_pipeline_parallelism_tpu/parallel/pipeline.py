"""SPMD pipeline-parallel executor: tick tables -> one jitted program.

TPU-native replacement for the reference's entire L2+L1 stack (SURVEY.md §1):
where torch builds per-process ``PipelineStage`` objects exchanging
activations via batched gloo P2P (``stage.py:463-603``) under a Python
schedule loop (``schedules.py:740``), here the *whole pipeline* — all stages,
all microbatches, forward and backward — is a single ``shard_map``-ped
program over a ``Mesh(('data', 'pipe'))``:

- **Stage placement**: layer parameters are stacked ``[D, V, layers/stage, ...]``
  and sharded over the 'pipe' axis — the pytree-partition equivalent of the
  reference's ``manual_model_split`` module deletion
  (``LLMsDistributedTrainingHelper.py:60-94``), including the interleaved wrap
  placement ``stage = rank + world_size * v`` (``:208``).
- **Transport**: every tick ends with two ``jax.lax.ppermute`` ring shifts
  (+1 for activations, -1 for gradients) — the ICI-native replacement for
  ``dist.batch_isend_irecv`` over gloo/TCP (SURVEY.md U6). Shapes are static
  under jit, so the reference's runtime shape-metadata exchange
  (``stage.py:1720-1744``) has no equivalent here at all.
- **Schedule execution**: a ``lax.scan`` over the compiled tick table
  (:mod:`.schedules`). Each tick conditionally runs one forward or backward
  unit; devices idle in the bubble run the (cheap) false branches. This is
  the SPMD analog of upstream's lowered action-IR interpreter
  (``_PipelineScheduleRuntime._step_microbatches``, ``schedules.py:2407``).
- **Backward**: rematerializing — the forward unit saves only the stage
  *input* per in-flight microbatch in a slot-addressed rotating buffer sized
  from the schedule's actual activation lifetimes (so 1F1B keeps its
  O(in-flight) ~ O(D) activation-memory advantage over GPipe's O(M));
  the backward unit recomputes the stage forward under ``jax.value_and_grad``
  — one extra stage forward per backward, the standard TPU trade of MXU FLOPs
  for HBM (SURVEY.md §7 hard-part (b)).
- **Loss / grad semantics**: token-mean CE per microbatch on the last stage,
  accumulated across microbatches and scaled by 1/M — reproducing upstream's
  ``scale_grads`` behavior (``schedules.py:692-694``) and the reference's
  ``tokenwise_loss_fn`` (``LLMsDistributedTrainingHelper.py:197-201``), so a
  pipeline step's (loss, grads) match a single-device full-batch step.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..models.transformer import (body_apply, compute_cast, embed_apply,
                                  head_apply, head_norm_apply,
                                  transformer_loss)
from ..ops.layers import (global_pad_scale, linear_apply,
                          select_masked_xent_sum, select_xent)
from ..utils.config import ModelConfig, ScheduleConfig
from .mesh import (DATA_AXIS, EXPERT_AXIS, MODEL_AXIS, PIPE_AXIS,
                   SEQ_AXIS)
from .schedules import (BANK_BEFORE_B, BANK_BEFORE_F, BANK_BEFORE_W,
                        BANK_END, COL_BWD_ASLOT, COL_BWD_GSLOT,
                        COL_BWD_LOCAL_SLOT, COL_BWD_M, COL_BWD_V,
                        COL_FWD_LOCAL_SLOT, COL_FWD_M,
                        COL_FWD_SLOT, COL_FWD_V, COL_STORE_B_POS_SLOT,
                        COL_STORE_B_SLOT, COL_STORE_F_NEG_SLOT,
                        COL_STORE_F_SLOT, COL_W_ASLOT, COL_W_GSLOT, COL_W_M,
                        COL_W_V, CompiledSchedule, compile_schedule,
                        overlap_bank_stages)


def _shard_map(fn, mesh, in_specs, out_specs):
    try:  # jax >= 0.6 exposes shard_map at top level (check_vma kwarg)
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    except AttributeError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map as esm
        return esm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=False)

Pytree = Any


def _fsdp_shard_dims(cfg: ModelConfig, n_data: int, T: int = 1) -> Pytree:
    """Per-leaf 'data'-shard dim under pp x fsdp (ZeRO-3): for MATRICES
    (q/k/v/o/ffn weights — template leaves are layer-stacked ``[L, w0,
    ...]``, so a matrix has ndim >= 3) the first weight dim that (a) is not
    Megatron-sharded over 'model' when ``T > 1`` — the round-4 pp x fsdp x
    tp composition puts 'data' and 'model' on DIFFERENT dims of the same
    leaf — and (b) divides ``n_data``. ``-1`` = replicated over 'data'
    (norm scales, biases: they are O(dim), noise next to the matrices, and
    sharding them would add latency-bound collectives per tick for
    nothing). Dim indices are the layer template's ([L, w0, w1, ...]);
    the executor's stacked [D, V, lps, w0, ...] layout offsets them by +2,
    while the in-shard_map gathers/scatters (chunk-selected [lps, w0,
    ...]) use them as-is. The SINGLE source of the layout —
    ``make_pipeline_grad_fn``'s in/out specs and ``fsdp_shard_params``'s
    placement must agree or jit silently reshards every leaf every step."""
    from ..models.transformer import transformer_init
    template = jax.eval_shape(
        lambda: transformer_init(jax.random.key(0), cfg))["layers"]
    if T > 1:
        from .tensor_parallel import _layer_specs
        tp_specs = _layer_specs(cfg)
    else:
        tp_specs = jax.tree.map(lambda _: P(), template)

    def dim_for(leaf, spec):
        if leaf.ndim < 3:
            return -1
        entries = tuple(spec) + (None,) * (leaf.ndim - len(tuple(spec)))
        for dim in range(1, leaf.ndim):
            if entries[dim] is None and leaf.shape[dim] % n_data == 0:
                return dim
        return -1

    return jax.tree.map(dim_for, template, tp_specs,
                        is_leaf=lambda x: isinstance(x, P))


def _dense_layer_specs(cfg: ModelConfig, T: int, fsdp_dims) -> Pytree:
    """Stacked-layout ([D, V, lps, w0, ...]) PartitionSpecs for dense
    stages: the Megatron 'model' placement (``T > 1``) merged with the
    per-leaf fsdp 'data' dims (stacked offset +2). Each leaf carries at
    most one axis per dim — :func:`_fsdp_shard_dims` picked 'data' dims
    disjoint from the 'model' ones."""
    if T > 1:
        from .tensor_parallel import pipeline_layer_specs
        base = pipeline_layer_specs(cfg, PIPE_AXIS)
    else:
        base = jax.tree.map(lambda _: P(PIPE_AXIS), fsdp_dims)
    if fsdp_dims is None:
        return base
    return _merge_fsdp_into_stacked(base, fsdp_dims)


def _merge_fsdp_into_stacked(base: Pytree, fsdp_dims: Pytree) -> Pytree:
    """Overlay per-leaf fsdp 'data' dims (template space, offset +2 for
    the stacked [D, V, lps, ...] layout) onto stacked PartitionSpecs."""

    def merge(spec, dm):
        if dm < 0:
            return spec
        e = list(tuple(spec))
        e += [None] * (dm + 3 - len(e))
        assert e[dm + 2] is None, (spec, dm)
        e[dm + 2] = DATA_AXIS
        return P(*e)

    return jax.tree.map(merge, base, fsdp_dims,
                        is_leaf=lambda x: isinstance(x, P))


def _compile(name: str, D: int, V: int, M: int) -> CompiledSchedule:
    """Compile via the native C++ engine when available (bit-identical to the
    Python compiler — see tests/test_native_engine.py), else in Python.
    Custom registered schedules always compile in Python (their order
    functions are Python). With ``DTPP_VERIFY_TABLES`` set, the compiled
    table additionally passes the static hazard verifier
    (``analysis.table_check``) before it reaches the executor."""
    from ..analysis import maybe_verify_schedule
    from . import native
    from .schedules import is_custom, verify_artifact_pin
    if is_custom(name) or name == "ZBV":
        # custom orders are Python functions; ZBV's order is synthesized by
        # a Python greedy simulation the C++ engine does not mirror
        cs = compile_schedule(name, D, V, M)
        maybe_verify_schedule(cs)
        return cs
    cs = None
    if native.native_available():
        from .schedules import ScheduleError
        try:
            cs = native.compile_schedule_native(name, D, V, M)
        except ScheduleError:
            raise
        except Exception:
            pass  # fall through to the Python reference implementation
    if cs is None:
        cs = compile_schedule(name, D, V, M)
    # Artifact-backed names always take the is_custom path above (their
    # order fns are Python), but re-check the pin here too so a native
    # table can never shadow a certified artifact name.
    verify_artifact_pin(cs)
    maybe_verify_schedule(cs)
    return cs


# ---------------------------------------------------------------------------
# Stage slicing: full-model pytree <-> stacked per-device layout
# ---------------------------------------------------------------------------


def _stage_index_map(placement: str, D: int, V: int):
    """[D, V] array: global stage held by (device, chunk)."""
    import numpy as np

    from .schedules import placement_stage_of
    return np.array([[placement_stage_of(placement, d, v, D)
                      for v in range(V)] for d in range(D)])


def stack_stage_layers(layers: Pytree, n_devices: int, n_virtual: int,
                       placement: str = "wrap") -> Pytree:
    """[L, ...] leaves -> [D, V, L/S, ...]: device d, chunk v holds global
    stage ``placement_stage_of(d, v)`` — wrap (the reference's
    ``stage = rank + world_size * v``) or vshape (ZB-V)."""

    def reshape(x):
        L = x.shape[0]
        S = n_devices * n_virtual
        if L % S != 0:
            raise ValueError(f"n_layers={L} must divide evenly into {S} stages")
        lps = L // S
        if placement == "wrap":
            return (x.reshape(n_virtual, n_devices, lps, *x.shape[1:])
                    .swapaxes(0, 1))
        idx = _stage_index_map(placement, n_devices, n_virtual)
        return x.reshape(S, lps, *x.shape[1:])[idx]

    return jax.tree.map(reshape, layers)


def unstack_stage_layers(stacked: Pytree, placement: str = "wrap") -> Pytree:
    """Inverse of :func:`stack_stage_layers`: [D, V, lps, ...] -> [L, ...]."""

    def reshape(x):
        D, V, lps = x.shape[:3]
        if placement == "wrap":
            return x.swapaxes(0, 1).reshape(V * D * lps, *x.shape[3:])
        idx = _stage_index_map(placement, D, V).reshape(-1)  # [D*V] -> stage
        flat = x.reshape(D * V, lps, *x.shape[3:])
        import numpy as np
        inv = np.argsort(idx)  # stage -> (d, v) flat position
        return flat[inv].reshape(V * D * lps, *x.shape[3:])

    return jax.tree.map(reshape, stacked)


# ---------------------------------------------------------------------------
# The executor
# ---------------------------------------------------------------------------



def _masked_store(buf, reg, slot):
    """Bank ``reg`` into ``buf[slot]`` when slot >= 0, else no-op (shared by
    the training and forward-only executors)."""
    active = slot >= 0
    ss = jnp.maximum(slot, 0)
    new = jnp.where(active, reg, buf[ss])
    return buf.at[ss].set(new)


def _stage_ce(cfg, head_p, embed_p, y, tgt, *, tp_axis, T,
              tp_vocab_parallel, pad_scale, loss_norm):
    with jax.named_scope("pp/loss"):
        return _stage_ce_impl(cfg, head_p, embed_p, y, tgt, tp_axis=tp_axis,
                              T=T, tp_vocab_parallel=tp_vocab_parallel,
                              pad_scale=pad_scale, loss_norm=loss_norm)


def _stage_ce_impl(cfg, head_p, embed_p, y, tgt, *, tp_axis, T,
                   tp_vocab_parallel, pad_scale, loss_norm):
    """Last-stage cross entropy for one microbatch — plain, ignore-index
    masked, or Megatron vocab-parallel (incl. the tied-embedding vocab-row
    slice). The ONE implementation shared by the training executor's stage
    objective and the forward-only eval executor, so train and eval losses
    cannot drift. With pad masking the returned value is the masked SUM
    scaled by the caller's global ``pad_scale`` (which absorbs
    ``loss_norm``); otherwise the token mean divided by ``loss_norm``.

    Under vocab-parallel + tied embeddings each model shard uses its
    vocab-row slice of the (replicated) embedding as the local head
    columns; ``tp_copy`` on the table makes the backward psum the
    per-shard partial row-grads into the full table grad, while the
    stage-0 lookup grad stays unwrapped (it is computed replicated, so a
    psum would T-fold it)."""
    if tp_vocab_parallel:
        # Megatron parallel CE: head matmul column-split over 'model'; the
        # [mb, s, V] logits never materialize.
        from ..ops.collectives import (tp_copy, vocab_parallel_masked_xent_sum,
                                       vocab_parallel_xent)
        yn = tp_copy(head_norm_apply(cfg, head_p, y), tp_axis)
        if cfg.tie_embeddings:
            v_loc = cfg.vocab_size // T
            my = jax.lax.axis_index(tp_axis)
            tok = tp_copy(embed_p["tok"], tp_axis)
            w_loc = jax.lax.dynamic_slice_in_dim(tok, my * v_loc, v_loc, 0)
            logits_local = yn @ w_loc.T
        else:
            logits_local = linear_apply(head_p["out"], yn)
        if cfg.pad_token_id is not None:
            s, _ = vocab_parallel_masked_xent_sum(
                logits_local, tgt, tp_axis, cfg.pad_token_id)
            return s * pad_scale  # scale absorbs loss_norm
        return vocab_parallel_xent(logits_local, tgt, tp_axis) / loss_norm
    logits = head_apply(cfg, head_p, y, embed=embed_p)
    if cfg.pad_token_id is not None:
        s, _ = select_masked_xent_sum(cfg.use_fused_xent)(
            logits, tgt, cfg.pad_token_id)
        return s * pad_scale  # scale absorbs loss_norm
    return select_xent(cfg.use_fused_xent)(logits, tgt) / loss_norm


def _check_tp_divisibility(cfg: ModelConfig, T: int) -> None:
    """Megatron-TP shape contract, shared by every builder that accepts a
    'model' axis (train step, forward-only loss, batch inference) so the
    three cannot drift."""
    if T <= 1:
        return
    n_kv = cfg.n_kv_heads or cfg.n_heads
    if cfg.n_heads % T or n_kv % T or cfg.ffn_dim % T:
        raise ValueError(
            f"tensor parallelism needs n_heads ({cfg.n_heads}), "
            f"n_kv_heads ({n_kv}) and ffn_dim ({cfg.ffn_dim}) divisible "
            f"by the model-axis size {T}")


def _moe_layer_specs(cfg: ModelConfig, moe, T: int, n_ep: int) -> Pytree:
    """Per-leaf PartitionSpecs for the stacked MoE layer pytree.

    Stacked MoE layer layout [D, V, lps, ...]: expert stacks (leading
    expert dim = axis 3) sharded over 'expert'; with a model axis the
    attention heads and each expert's ffn dim are additionally
    Megatron-split (w1/b1 column, w2 row, router/norms/b2 replicated).
    Specs are derived per-leaf from the real layer tree (eval_shape: no
    arrays materialize) via the shared EP predicate. Shared by the
    training executor and the forward-only eval program so the two cannot
    disagree about where an expert leaf lives."""
    from ..models.moe import moe_layer_init
    from .expert_parallel import is_expert_leaf
    template = jax.eval_shape(
        lambda: moe_layer_init(jax.random.key(0), cfg, moe))

    def moe_leaf_spec(path, _):
        keys = [p.key for p in path if hasattr(p, "key")]
        ep = EXPERT_AXIS if (n_ep > 1 and is_expert_leaf(path)) else None
        if T > 1 and "moe" in keys:
            name = keys[-1]
            # stacked dims [pipe, V, lps] then [E(, dim/ffn), ...]
            moe_specs = {"w1": P(PIPE_AXIS, None, None, ep, None,
                                 MODEL_AXIS),
                         "b1": P(PIPE_AXIS, None, None, ep, MODEL_AXIS),
                         "w2": P(PIPE_AXIS, None, None, ep, MODEL_AXIS,
                                 None),
                         "b2": P(PIPE_AXIS, None, None, ep, None)}
            return moe_specs.get(name, P(PIPE_AXIS))  # router: replicated
        if T > 1 and "attn" in keys:
            proj, wb = keys[-2], keys[-1]
            if proj == "o":  # row-parallel; bias replicated, added once
                return (P(PIPE_AXIS, None, None, MODEL_AXIS, None)
                        if wb == "w" else P(PIPE_AXIS))
            return (P(PIPE_AXIS, None, None, None, MODEL_AXIS)
                    if wb == "w" else P(PIPE_AXIS, None, None, MODEL_AXIS))
        if ep is not None:
            return P(PIPE_AXIS, None, None, EXPERT_AXIS)
        return P(PIPE_AXIS)

    return jax.tree_util.tree_map_with_path(moe_leaf_spec, template)


def _moe_template_specs(cfg: ModelConfig, moe, T: int, n_ep: int) -> Pytree:
    """Full-model-layout ([L, w0, ...]) PartitionSpecs for MoE layer
    leaves: :func:`_moe_layer_specs`' stacked [D, V, lps, ...] placement
    with the three leading stack dims dropped (entry 0 — the layer-stack
    dim — left free for the caller to claim, e.g. 'pipe' in
    :func:`fsdp_shard_params`'s resting layout)."""
    stacked = _moe_layer_specs(cfg, moe, T, n_ep)

    def unstack(spec):
        return P(None, *tuple(spec)[3:])

    return jax.tree.map(unstack, stacked,
                        is_leaf=lambda x: isinstance(x, P))


def _moe_fsdp_shard_dims(cfg: ModelConfig, moe, n_data: int, T: int,
                         n_ep: int) -> Pytree:
    """MoE twin of :func:`_fsdp_shard_dims` (pp x fsdp x MoE, VERDICT r4
    item 3): per-leaf 'data'-shard dim chosen to avoid BOTH the Megatron
    'model' dim and the expert dim the EP axis owns — e.g. w1 [L, E, d, f]
    under ep x tp shards 'data' on d, the only free matrix dim. The
    router and per-expert biases (b1/b2) stay replicated, mirroring the
    dense rule's treatment of norms/biases (O(dim·E) leaves, noise next
    to the expert matrices, and sharding them would add latency-bound
    collectives per tick). Dim indices are the layer-STACKED template's
    ([L, w0, ...]) — same conventions as the dense helper, which is why
    the template comes from ``moe_lm_init``'s vmapped layer stack, not
    the per-layer ``moe_layer_init`` (per-layer leaves would shift every
    dim by one and misclassify [d, d] attention matrices as biases)."""
    from ..models.moe import moe_lm_init
    template = jax.eval_shape(
        lambda: moe_lm_init(jax.random.key(0), cfg, moe))["layers"]
    specs = _moe_template_specs(cfg, moe, T, n_ep)

    def dim_for(path, leaf, spec):
        keys = [p.key for p in path if hasattr(p, "key")]
        if leaf.ndim < 3 or "router" in keys or keys[-1] in ("b1", "b2"):
            return -1
        entries = tuple(spec) + (None,) * (leaf.ndim - len(tuple(spec)))
        for dim in range(1, leaf.ndim):
            if entries[dim] is None and leaf.shape[dim] % n_data == 0:
                return dim
        return -1

    return jax.tree_util.tree_map_with_path(
        dim_for, template, specs, is_leaf=lambda x: isinstance(x, P))


def _resolve_fsdp_dims(cfg: ModelConfig, moe, n_data: int, T: int,
                       n_ep: int, fsdp: bool):
    """The per-leaf fsdp 'data'-dim map shared by the training executor,
    the forward-only eval program, and ``fsdp_shard_params`` — one
    resolution site so train/eval/placement can never disagree about
    where a leaf's 'data' shard lives (the silent-reshard drift the
    helpers' docstrings warn about)."""
    if not fsdp:
        return None
    if moe is not None:
        return _moe_fsdp_shard_dims(cfg, moe, n_data, T, n_ep)
    return _fsdp_shard_dims(cfg, n_data, T)


def _check_moe_mesh(cfg: ModelConfig, moe, T: int, n_seq: int,
                    n_ep: int) -> None:
    """The MoE mesh-composition contract, shared by the training executor
    and the forward-only eval program (raise identically on both).

    A seq axis composes since round 5: attention rides the ring/Ulysses
    transport while the (position-wise) MoE FFN routes each shard's
    local tokens with local capacity — the EP path's local-routing
    semantics applied to the sequence dimension. Dropout composes too:
    the residual/FFN masks are the full-sequence masks' local slices
    (``sharded_dropout_apply``, the dense sp path's rule)."""
    if cfg.arch != "gpt2":
        raise ValueError("MoE pipeline blocks are gpt2-style; set "
                         "arch='gpt2'")
    if moe.n_experts % n_ep:
        raise ValueError(f"n_experts={moe.n_experts} must divide over "
                         f"{n_ep} expert shards")
    if T > 1 and (moe.ffn_dim or cfg.ffn_dim) % T:
        raise ValueError(
            f"MoE expert ffn_dim={moe.ffn_dim or cfg.ffn_dim} must be "
            f"divisible by the model-axis size {T}")


# Auto-unroll threshold for the tick executor: tables at or below this many
# tick rows compile as straight-line code (each row's units traced once
# more), above it the lax.scan form keeps compile time bounded. Set from
# round-5 v5e measurements (results/unroll_crossover.json, GPipe D=1 remat
# executor, per-microbatch shapes fixed): unrolled beats scanned at EVERY
# size measured — 1.19-1.20x through 32 rows, narrowing to ~1.05x at 48-64
# rows — so there is no throughput crossover to encode; the binding cost is
# compile time, which grows ~2.2 s/row (14 s at 8 rows -> 140 s at 64).
# 64 rows covers every ladder config (Interleaved D=4/V=2/M=8 = 38 rows,
# GPipe D=1 M=32 = 64) at <= ~2.5 min compile; beyond it the measured win
# trend (shrinking) no longer justifies unbounded compile growth. Callers
# iterating interactively can pass unroll_ticks=False for ~7 s compiles.
_UNROLL_TICKS_LIMIT = 64
# The FORWARD-only executor (make_pipeline_forward / eval) keeps the
# round-4 budget: its per-row economics (forward ticks, no backward) were
# not part of the round-5 measurement.
_UNROLL_FWD_TICKS_LIMIT = 32


def _concrete_know(col_vals):
    """Concrete (unrolled-tick) knowledge of a unit predicate across the
    pipe axis: True = every device takes the unit, False = none does,
    None = mixed, or no concrete row (the scan path)."""
    if col_vals is None:
        return None
    if (col_vals >= 0).all():
        return True
    if (col_vals < 0).all():
        return False
    return None


# Test instrumentation for the phase-compressed executor: when set, called
# once per PYTHON TRACE of a phase body (not per scanned tick) — the
# compile-counter tests assert the trace count tracks unique patterns, not
# table length (tests/test_pipeline.py::test_phase_executor_trace_count).
_PHASE_TRACE_HOOK = None

logger = logging.getLogger(__name__)


def _phase_compressed_ticks(tick, carry, table, phases, telemetry=None,
                            bank_stages=None):
    """Drive a tick program as per-phase ``lax.scan`` s with per-pattern
    specialized bodies — the ``unroll_ticks="phases"`` executor core,
    shared by the training and forward-only programs.

    ``phases`` is :func:`..schedules.compress_schedule`'s segmentation of
    the host-side table. Each phase is refined to its MINIMAL MASK PERIOD
    ``q``: the affine descriptor needs a period long enough for slot
    indices to advance affinely (a full slot-reuse cycle, which grows with
    M in 1F1B's steady state), but the executor feeds the real table rows
    as scanned inputs, so only the active/idle structure has to repeat —
    the steady state's F/B alternation is a 2-tick body regardless of M.
    Each distinct (mask pattern, successor mask) builds ONE body closure,
    memoized so repeated patterns (and every same-shaped length-1
    warmup/cooldown row) share a single trace: ``lax.scan`` caches body
    jaxprs per function object, so compile cost scales with unique
    patterns, not ticks. Inside a body every tick gets the exact
    per-position mask as its concrete row (cond elision via ``know``,
    store elision) and the next position's mask as ``next_concrete``
    (dead-ppermute elision); at a phase boundary the next mask is the
    union of the in-phase position 0 and the successor phase's first row —
    conservative is sound, because a ppermute whose arrival no device
    banks is dead (``_masked_store`` skips slot -1), so results stay
    bit-exact against the plain scan executor.

    ``telemetry`` (a :class:`..utils.telemetry.PipelineTelemetry`, opt-in)
    brackets each phase's scan with host-timestamp stamps whose probes are
    scalars drawn from the live carry — dataflow pins phase j's start
    stamp after phase j-1's work and its end stamp after its own, giving a
    measured per-phase timeline aligned with the ``phases`` descriptors.
    When None (default), no callback is emitted at all.

    ``bank_stages`` (opt-in, ``[T, 4]`` int from ``..schedules.
    overlap_bank_stages``) enables the double-buffered ring discipline:
    each body position banks its ring arrivals at the per-position stage
    folded over every tick the position covers (min across blocks —
    banking earlier than latest-safe is always lockstep-correct). The
    stage tuple joins the memo key, so two phases sharing a mask pattern
    but differing in bank stages compile separate bodies."""
    from ..utils import telemetry as _tm
    memo = {}
    n_cols = phases[0].base.shape[-1]
    end_mask = np.full(phases[0].base.shape[1:], -1, np.int32)  # [D, C]

    def pseudo(mask):
        """bool mask [D, C] -> a concrete row stand-in (0 active, -1 idle):
        exactly the information the elision checks read from real rows."""
        return np.where(mask, 0, -1).astype(np.int32)

    for j, ph in enumerate(phases):
        base_mask = ph.base >= 0  # [period, D, C]
        p, L = ph.period, ph.length
        q = next(qq for qq in range(1, p + 1)
                 if p % qq == 0
                 and (base_mask
                      == np.tile(base_mask[:qq], (p // qq, 1, 1))).all())
        masks_q = base_mask[:q]
        succ = (pseudo(phases[j + 1].base[0] >= 0) if j + 1 < len(phases)
                else end_mask)  # after the last tick nothing banks
        if L // q > 1:
            # at the block boundary the next row is position 0 of the next
            # block — except on the last block, where it is the successor
            # phase; the body is one program for all blocks, so take the
            # union (0 = active wins)
            succ = np.maximum(succ, pseudo(masks_q[0]))
        if bank_stages is None:
            st_q = None
        else:
            st_q = bank_stages[ph.start:ph.start + L].reshape(
                L // q, q, -1).min(axis=0)  # [q, 4]
        key = (q, masks_q.tobytes(), succ.tobytes(),
               None if st_q is None else st_q.tobytes())
        if key not in memo:
            rows_c = [pseudo(m) for m in masks_q]
            nxts = rows_c[1:] + [succ]
            stages_c = ([None] * q if st_q is None
                        else [tuple(int(v) for v in st_q[i])
                              for i in range(q)])

            def body(c, xs, _rows=rows_c, _nxts=nxts, _stages=stages_c):
                if _PHASE_TRACE_HOOK is not None:
                    _PHASE_TRACE_HOOK()
                with jax.named_scope("pp/tick_body"):
                    for i, (rc, nc) in enumerate(zip(_rows, _nxts)):
                        # kwarg only when staged: the forward-only tick
                        # (which shares this driver) stays lockstep
                        kw = ({} if _stages[i] is None
                              else {"bank_stages": _stages[i]})
                        c, _ = tick(c, xs[i], concrete=rc, next_concrete=nc,
                                    **kw)
                return c, None

            memo[key] = body
        xs = table[ph.start:ph.start + L].reshape(L // q, q, -1, n_cols)
        if telemetry is not None:
            telemetry.emit(_tm.PHASE_START, j, _tm.probe_of(carry))
        with jax.named_scope(f"pp/phase{j}"):
            carry, _ = jax.lax.scan(memo[key], carry, xs)
        if telemetry is not None:
            telemetry.emit(_tm.PHASE_END, j, _tm.probe_of(carry))
    return carry


def make_pipeline_grad_fn(cfg: ModelConfig, mesh: Mesh, sched: ScheduleConfig,
                          force_tick_executor: bool = False, moe=None,
                          sp_attn_impl: str = "ring",
                          tp_vocab_parallel: bool = False,
                          fsdp: bool = False,
                          remat_backward=None,
                          unroll_ticks=None,
                          telemetry=None,
                          dynamics=None,
                          comm_overlap: str = "none",
                          ) -> Callable[[Pytree, jax.Array, jax.Array],
                                        Tuple[jax.Array, Pytree]]:
    """Build an (unjitted) ``(params, tokens, targets) -> (loss, grads)``
    pipeline step — compose with an optimizer under one jit (see
    :mod:`..utils.train`) or jit directly via :func:`make_pipeline_step`.
    With ``cfg.dropout > 0`` the step takes a fourth argument — a per-step
    PRNG key — and runs train-mode dropout with masks that depend only on
    (key, data shard, microbatch, global layer, site), i.e. independent of
    the (D, V) stage partitioning (tests/test_dropout.py asserts this).

    ``params`` is the full-model pytree from ``transformer_init`` (or
    ``moe_lm_init`` when ``moe`` — a :class:`..models.moe.MoEConfig` — is
    given: stages then run MoE blocks, experts sharded over an 'expert'
    mesh axis when present, and the loss gains the routing aux term,
    microbatch-averaged). ``grads`` comes back in the same layout. ``tokens``/``targets`` are ``[B, S]`` with
    ``B`` divisible by (n_data * n_microbatches); the batch is split over the
    'data' mesh axis, then into microbatches along dim 0 (upstream
    ``DEFAULT_CHUNK_DIM=0``, ``microbatch.py:57``).

    ``remat_backward`` selects the backward's activation policy (measured
    policy table in docs/performance.md "Backward policy"):

    - ``None`` (default, auto): at D == 1 (incl. pure data/tensor/seq
      meshes and the benchmark's ``force_tick_executor`` runs), the
      UNROLLED stored program — microbatches as straight-line code,
      autodiff residuals managed and fused by XLA; measured the fastest
      single-chip formulation. At D > 1, the REMATERIALIZING backward:
      on TPU the backward's stage-forward recompute costs ~1.33x FLOPs on
      the MXU, which measures cheaper than pushing stored residuals
      through HBM scan boundaries at both the reference config and
      gpt2-small seq 1024.
    - ``True``: always rematerialize — the forward unit saves only the
      stage *input*; the backward recomputes the stage forward. Minimal
      activation memory (O(in-flight) stage inputs).
    - ``False``: stored-activation backward — nothing is recomputed,
      matching the reference's torch-autograd semantics (its backward
      stashes, never recomputes — ``LLMsDistributedTrainingHelper.py:
      98-143`` via upstream ``stage.py:857/937``). Phase-separated
      schedules (GPipe/BFS: per-device all-F-then-all-B) differentiate
      through the forward tick scan (:func:`_make_phase_stored_grad_fn`);
      other schedules bank the stage body's ``jax.vjp`` residuals in
      slot-addressed buffers (x-independent residuals — weights, casts,
      RoPE — are re-derived live instead of stored, see
      :mod:`.stored_backward`). Raises on configurations that cannot
      support it (split-backward schedules, whose W units re-derive
      parameter grads by design; ``fsdp=True``, where residuals would pin
      the just-in-time-gathered full weights).

    ``unroll_ticks`` selects the tick-executor formulation (docs/
    performance.md "Executor formulations"); it changes the loop form
    only, so it composes with every backward policy and mesh axis:

    - ``True`` (round 4, VERDICT r3 item 2 — the SPMD analog of
      upstream's per-rank lowered-IR execution, ``schedules.py:
      2279-2337``): emit the tick program as straight-line code instead
      of a ``lax.scan`` over table rows. Each tick's per-device COLUMN
      VALUES stay dynamic (``table[t][axis_index]`` scalar reads — one
      program for all devices), but the tick LOOP is a Python loop over
      the concrete table, so the scan boundary — which forces every
      cross-tick value through HBM and blocks forward/backward fusion —
      disappears, and per-tick structure specializes against the
      concrete rows: units that every device takes lose their
      ``lax.cond``, all-idle units and never-banked ring transfers are
      elided entirely (warmup ticks carry no backward ring hop, cooldown
      no forward one). Worth 1.05-1.2x throughput over the scan form on
      v5e, but compile time grows ~2.2 s per table row (14 s at 8 rows,
      ~140 s at 64 — results/unroll_crossover.json).
    - ``"phases"``: the phase-compressed executor. The table is
      segmented into periodic phases (:func:`..schedules.
      compress_schedule`), each unique active/idle pattern is traced
      ONCE as a specialized body (same concrete-``know`` cond elision
      and dead-ppermute elision as the unrolled form), and each phase
      runs as a ``lax.scan`` feeding the real table rows as scanned
      inputs. Compile cost scales with unique patterns, not ticks —
      steady-state 1F1B is one 2-tick body regardless of M — so large
      tables compile in a handful of traces instead of minutes, while
      per-tick dispatch overhead still disappears.
    - ``False``: one cond-dispatched ``lax.scan`` over the whole table —
      the bounded-compile escape hatch (~7 s regardless of table size;
      pays ``tick_executor_overhead`` per tick). Use when iterating
      interactively.
    - ``None`` (auto, default): ``True`` for tables of at most
      ``_UNROLL_TICKS_LIMIT`` (= 64) rows, ``"phases"`` above (a one-line
      ``logging.info`` records when that auto phase-compression fires).

    ``telemetry`` (a :class:`..utils.telemetry.PipelineTelemetry`, default
    None) opts in to a MEASURED tick/phase timeline: the executor plants
    host-timestamp callbacks at segment boundaries — per phase
    (``"phases"``), per tick (``True``), or per step (``False``) — and
    records the compiled table/phases on the collector so its analysis
    aligns the stamps with the simulated timeline (docs/observability.md).
    When None the built program contains NO callback (tests assert
    ``"io_callback" not in str(jaxpr)``) and is bit-identical to an
    uninstrumented build.

    ``dynamics`` (truthy, default None) additionally accumulates each
    microbatch's squared gradient norm in an ``[M]`` f32 carry — the
    backward/W units already materialize one gradient per (stage,
    microbatch), and stages partition the (untied) parameters, so a
    pipe-axis psum completes ``|g_m|^2`` with no extra backward work.
    The step then returns ``(loss, grads, sq_mb)``; ``sq_mb[m]`` feeds
    the gradient-noise-scale estimator (:mod:`..utils.dynamics`, data
    replicas averaged — each holds a different microbatch sample).
    Supported on dense untied-embedding pipe x data meshes with the tick
    executor only (raises otherwise: the degenerate 1-stage fast path
    and the phase-stored program never materialize per-microbatch
    grads, and tied embeddings / tensor / seq / expert sharding break
    the stages-partition-the-params norm decomposition). When falsy the
    traced program is byte-identical to a build without the argument
    (tests/test_dynamics.py pins the jaxpr).

    ``comm_overlap`` selects the ring-hop discipline (docs/performance.md
    "Comm/compute overlap"):

    - ``"none"`` (default): lockstep — every tick banks last tick's ring
      arrivals into the edge slots at the tick top, so each ppermute is a
      data dependency of ALL of the next tick's compute.
    - ``"ring"``: double-buffered edge slots. The recv register a
      ppermute lands in is held across the next tick's units and
      committed to its edge slot only at the channel's bank stage — the
      latest point the static classifier
      (:func:`..schedules.overlap_bank_stages`) proves conflict-free —
      so the hop overlaps every unit that doesn't read or write the
      banked slot (in 1F1B's steady state the grad arrival is consumed
      by B, which runs AFTER F: the backward ring hop overlaps the whole
      forward unit). Bit-identical to ``"none"`` by construction
      (tests/test_overlap.py). Requires the unrolled or phase-compressed
      executor — the cond-dispatched scan sees only traced rows, so
      ``unroll_ticks=False`` raises.
    - ``"auto"``: ``"ring"`` whenever the resolved executor supports it
      (unrolled / phases), ``"none"`` otherwise (scan, phase-stored,
      degenerate 1-stage).

    ``fsdp=True`` (pp x fsdp, ZeRO-3 within the pipeline): per-stage layer
    weights live sharded over the 'data' axis (per-leaf weight dim from
    :func:`_fsdp_shard_dims` — use :func:`fsdp_shard_params` to place
    them), each tick's active virtual chunk is all-gathered just in time
    inside the compute unit, and layer gradients are reduce-scattered per
    backward tick, so the grad accumulator carry is sharded too.
    Per-device layer-param residency drops from full-stage to 1/n_data of
    it (+ one transient gathered chunk); grads/optimizer state inherit the
    sharding through the returned pytree. Composes with Megatron TP
    (round 4): on a 3-D ``data x pipe x model`` mesh each matrix leaf is
    'model'-split on its Megatron dim and 'data'-split on a DIFFERENT
    dim, so residency is ~1/(D * T * n_data). Composes with MoE/expert
    stages too (round 5): expert matrices pick a 'data' dim disjoint
    from both the EP-owned expert dim and the Megatron dim
    (:func:`_moe_fsdp_shard_dims`) — expert models are precisely where
    parameter sharding pays. A seq axis composes too (round 5): the
    weight all-gathers ride 'data' while activations shard over 'seq' —
    orthogonal by construction.
    """
    D = mesh.shape[PIPE_AXIS]
    n_data = mesh.shape.get(DATA_AXIS, 1)
    T = mesh.shape.get(MODEL_AXIS, 1)
    n_seq = mesh.shape.get(SEQ_AXIS, 1)
    n_ep = mesh.shape.get(EXPERT_AXIS, 1)
    V = sched.n_virtual
    M = sched.n_microbatches
    cs: CompiledSchedule = _compile(sched.name, D, V, M)
    tp_axis = MODEL_AXIS if T > 1 else None
    sp_axis = SEQ_AXIS if n_seq > 1 else None
    if sp_attn_impl not in ("ring", "ulysses"):
        raise ValueError(f"sp_attn_impl must be 'ring' or 'ulysses', "
                         f"got {sp_attn_impl!r}")
    if tp_vocab_parallel:
        if T <= 1:
            raise ValueError("tp_vocab_parallel needs a 'model' mesh axis")
        if cfg.vocab_size % T:
            raise ValueError(f"vocab_size={cfg.vocab_size} must divide over "
                             f"the model-axis size {T}")
    # Only ring attention puts a ppermute (flat-pair collective) inside the
    # schedule units; Ulysses' all_to_all is grouped, so its units may keep
    # the efficient cond dispatch.
    uniform_units = sp_axis is not None and sp_attn_impl == "ring"
    _check_tp_divisibility(cfg, T)
    ep_axis = EXPERT_AXIS if n_ep > 1 else None
    if n_ep > 1 and moe is None:
        raise ValueError("mesh has an 'expert' axis but no MoEConfig given")
    if fsdp and n_data <= 1:
        raise ValueError("fsdp=True needs a 'data' mesh axis to shard "
                         "parameters over")
    # fsdp x seq composes (round 5): the weight all-gathers ride the
    # 'data' axis while activations shard over 'seq' — orthogonal by
    # construction, and the epilogue's per-leaf reductions already do the
    # right thing (psum_scatter over 'data' per tick, then the seq psum
    # completes every leaf's token share)
    fsdp_dims = _resolve_fsdp_dims(cfg, moe, n_data, T, n_ep, fsdp)
    use_dropout = cfg.dropout > 0.0
    # pad masking composes with every supported mesh, including MoE/expert
    # stages: the CE is globally valid-count normalized while the routing
    # aux loss stays token-uniform (routing happens for pad positions too —
    # they occupy expert capacity, so load balance legitimately counts them)
    if moe is not None:
        _check_moe_mesh(cfg, moe, T, n_seq, n_ep)
    if comm_overlap not in ("none", "ring", "auto"):
        raise ValueError(f"comm_overlap must be 'none', 'ring', or 'auto', "
                         f"got {comm_overlap!r}")
    dyn = bool(dynamics)
    if dyn:
        blockers = []
        if moe is not None:
            blockers.append("moe")
        if fsdp:
            blockers.append("fsdp")
        if T > 1:
            blockers.append("a 'model' mesh axis")
        if n_seq > 1:
            blockers.append("a 'seq' mesh axis")
        if n_ep > 1:
            blockers.append("an 'expert' mesh axis")
        if cfg.tie_embeddings:
            # the tied embedding takes grads from BOTH the first stage
            # (wgrad through stage_embed) and the last (the head's vocab
            # matmul), so per-stage squared norms no longer sum to
            # |g_m|^2 — the decomposition the accumulator relies on
            blockers.append("tie_embeddings")
        if blockers:
            raise ValueError(
                "dynamics per-microbatch accumulation needs stages to "
                "partition the parameters (dense untied pipe x data "
                "mesh); unsupported here: " + ", ".join(blockers))
    if (D == 1 and n_data == 1 and T == 1 and n_seq == 1 and V == 1
            and moe is None and not use_dropout and not force_tick_executor):
        if dyn:
            raise ValueError(
                "dynamics=True needs the tick executor's per-microbatch "
                "gradients; the degenerate 1-stage fast path computes one "
                "fused full-batch gradient — pass force_tick_executor="
                "True with remat_backward=True")
        # Degenerate 1-stage pipeline == a plain full-batch train step: the
        # microbatch-accumulated, 1/M-scaled loss/grads equal the full-batch
        # mean exactly (asserted in tests/test_pipeline.py), so skip the tick
        # machinery and its rematerializing backward entirely and let XLA
        # fuse the whole step. The schedule was still compiled above, so
        # invalid (name, D, V, M) combinations raise identically.
        def degenerate_step(params, tokens, targets):
            # same config contract as the tick executor's shard_map assert
            assert tokens.shape[0] % M == 0, (
                f"batch {tokens.shape[0]} not divisible by n_microbatches={M}")
            return jax.value_and_grad(
                lambda p: transformer_loss(cfg, p, tokens, targets))(params)

        return degenerate_step
    split = cs.split_backward  # ZB-H1 family: B is dgrad-only, W carries wgrad
    # Backward-policy resolution, from v5e measurements (docs/performance.md
    # "Backward policy"):
    #
    # - D == 1 (any non-split schedule — every schedule's grads are
    #   order-independent and the table is device-symmetric): the UNROLLED
    #   stored program — straight-line microbatch code, autodiff residuals
    #   fused by XLA. Measured fastest (no scan boundary).
    # - D > 1: REMATERIALIZING backward by default. Stored variants
    #   (scan-vjp for phase-separated GPipe/BFS, slot-buffer residual
    #   banking otherwise) are opt-in via remat_backward=False: on TPU the
    #   backward's stage-forward recompute rides the MXU at ~1.33x FLOPs
    #   while stored residuals ride HBM through scan boundaries — measured
    #   SLOWER than remat at both the reference config and gpt2-small
    #   seq 1024 on one chip. (The reference's torch-CPU runtime has the
    #   opposite economics, hence its stash-don't-recompute backward.)
    # - Split-backward schedules and fsdp always rematerialize (W's
    #   recompute fills bubbles by design; fsdp residuals would pin
    #   gathered full weights).
    phase_ok = (not split and cs.placement == "wrap" and moe is None
                and not fsdp
                and (D == 1 or sched.name in ("GPipe", "BFS")))
    if remat_backward is None:
        use_phase = phase_ok and D == 1
        use_stored = False
    elif remat_backward:
        use_phase = use_stored = False
    else:
        if split:
            raise ValueError(
                f"remat_backward=False is incompatible with split-backward "
                f"schedule {sched.name!r}: its W units re-derive parameter "
                f"grads from saved inputs by design (that recompute is what "
                f"fills the bubble ticks)")
        if fsdp:
            raise ValueError(
                "remat_backward=False is incompatible with fsdp=True: the "
                "stage body's residuals would pin each tick's just-in-time "
                "all-gathered full weights per in-flight microbatch, "
                "voiding the ZeRO-3 residency bound")
        use_phase = phase_ok
        use_stored = not phase_ok
    if use_phase:
        if dyn:
            raise ValueError(
                "dynamics=True needs per-microbatch gradients; the "
                "phase-stored program differentiates through its forward "
                "tick scan and never materializes them — pass "
                "remat_backward=True for the tick executor")
        if comm_overlap == "ring":
            raise ValueError(
                "comm_overlap='ring' is incompatible with the phase-stored "
                "backward (it differentiates through the forward tick scan "
                "and has no per-tick bank sites) — pass remat_backward="
                "True/None for the tick executor, or comm_overlap='auto' "
                "to fall back to lockstep here")
        fn = _make_phase_stored_grad_fn(cfg, mesh, sched, sp_attn_impl,
                                        tp_vocab_parallel)
        if telemetry is None:
            return fn
        # The phase-stored program differentiates THROUGH its forward tick
        # scan, so stamps cannot live inside it (io_callback has no
        # transpose rule); bracket the whole step instead — one measured
        # whole-table segment, the same shape as the scan executor's
        # record.
        from ..utils import telemetry as _tm
        telemetry.attach(cs.table, None, "phase_stored")

        def instrumented(params, tokens, targets, *rest):
            telemetry.emit(_tm.STEP_START, 0, _tm.probe_of(tokens))
            out = fn(params, tokens, targets, *rest)
            telemetry.emit(_tm.STEP_END, 0, _tm.probe_of(out))
            return out

        return instrumented
    if unroll_ticks is None:
        # auto: unroll small tables (straight-line specialization, ~2.2 s
        # compile per row); beyond the budget the PHASE-COMPRESSED form —
        # per-pattern specialized scan bodies — replaces the old
        # cond-dispatched whole-table scan as the default
        unroll_ticks = (True if cs.table.shape[0] <= _UNROLL_TICKS_LIMIT
                        else "phases")
        if unroll_ticks == "phases":
            logger.info(
                "pipeline: %d-row tick table exceeds _UNROLL_TICKS_LIMIT=%d; "
                "auto-selecting the phase-compressed executor "
                "(unroll_ticks='phases'; pass unroll_ticks=False for the "
                "bounded-compile scan form, or True to force full unrolling)",
                cs.table.shape[0], _UNROLL_TICKS_LIMIT)
    if unroll_ticks not in (True, False, "phases"):
        raise ValueError(f"unroll_ticks must be True, False, 'phases', or "
                         f"None (auto), got {unroll_ticks!r}")
    if comm_overlap == "auto":
        comm_overlap = "ring" if unroll_ticks in (True, "phases") else "none"
    elif comm_overlap == "ring" and unroll_ticks is False:
        raise ValueError(
            "comm_overlap='ring' needs static per-tick bank stages; the "
            "cond-dispatched scan executor (unroll_ticks=False) sees only "
            "traced rows — use unroll_ticks=True or 'phases' (or "
            "comm_overlap='auto' to fall back to lockstep)")
    bank_stages_tab = (overlap_bank_stages(cs.table)
                       if comm_overlap == "ring" else None)
    if unroll_ticks == "phases":
        from .schedules import compress_schedule
        phases = compress_schedule(cs.table)
    else:
        phases = None
    if telemetry is not None:
        telemetry.attach(cs.table, phases,
                         {True: "unrolled", False: "scan",
                          "phases": "phases"}[unroll_ticks])
    table = jnp.asarray(cs.table)  # [T, D, N_COLS]
    dtype = jnp.dtype(cfg.dtype)
    fwd_perm = [(i, (i + 1) % D) for i in range(D)]
    bwd_perm = [(i, (i - 1) % D) for i in range(D)]
    # vshape placement (ZB-V): some transfers ride the reverse rings or stay
    # on-device; the last stage lives at (device 0, chunk 1), not (D-1, V-1)
    placement = cs.placement
    reverse_routes = cs.uses_reverse_routes
    from .schedules import (placement_chunk_of, placement_device_of)
    last_dev = placement_device_of(placement, D * V - 1, D)
    last_chunk = placement_chunk_of(placement, D * V - 1, D)

    lps = cfg.n_layers // (D * V)  # layers per stage (stack_stage_layers checks)

    def spmd_fn(layers_stacked, embed, head, tokens, targets, rng_data=None):
        # Shapes inside shard_map: layers_stacked leaves [1, V, lps, ...];
        # embed/head replicated; tokens/targets [B_local, S]; rng_data (train
        # mode, dropout > 0) is the step key's raw data, replicated.
        d = jax.lax.axis_index(PIPE_AXIS)
        layers_local = jax.tree.map(lambda x: x[0], layers_stacked)
        is_first_dev = d == 0
        is_last_dev = d == last_dev  # wrap: D-1; vshape: 0 (the V returns)

        def stage_of(vv):
            """Traced global stage index of this device's chunk vv."""
            if placement == "wrap":
                return vv * D + d
            return jnp.where(vv == 0, d, 2 * D - 1 - d)

        if use_dropout:
            base_rng = jax.random.wrap_key_data(rng_data)
            if n_data > 1:  # decorrelate masks across data replicas
                base_rng = jax.random.fold_in(
                    base_rng, jax.lax.axis_index(DATA_AXIS))
            if n_ep > 1:
                # 'expert' doubles as a batch axis (batch_spec shards the
                # batch over data x expert): each expert shard holds
                # DIFFERENT tokens, so its masks must draw a distinct
                # stream too
                base_rng = jax.random.fold_in(
                    base_rng, jax.lax.axis_index(EXPERT_AXIS))
        else:
            base_rng = None

        def mb_rng(mm):
            """Per-microbatch dropout stream. Masks depend only on (step key,
            data shard, microbatch, global layer, site) — independent of the
            (D, V) stage partitioning, and identical between the forward unit
            and the rematerializing backward of the same microbatch."""
            return None if base_rng is None else jax.random.fold_in(base_rng, mm)

        b_local, seq = tokens.shape
        assert b_local % M == 0, (
            f"local batch {b_local} not divisible by n_microbatches={M}")
        mb = b_local // M
        tokens_mb = tokens.reshape(M, mb, seq)
        targets_mb = targets.reshape(M, mb, seq)
        mb_shape = (mb, seq, cfg.dim)
        # tied embeddings: the head argument of the stage objective bundles
        # the embedding so the last stage's VJP produces its grad
        head_bundle = (head, embed) if cfg.tie_embeddings else head

        def stage_body(layer_p, x, vv=0, mm=0):
            # XProf legibility: every stage-compute op lands under pp/...
            with jax.named_scope("pp/stage_body"):
                return _stage_body_impl(layer_p, x, vv, mm)

        def _stage_body_impl(layer_p, x, vv=0, mm=0):
            """-> (y, aux): aux is the stage's summed routing load-balance
            loss (MoE stages), else a constant 0 that XLA eliminates.
            ``(vv, mm)`` select the dropout stream (train mode): the stack's
            global layer offset is ``(vv*D + d) * lps``."""
            zero = jnp.zeros((), jnp.float32)
            layer_p = compute_cast(cfg, layer_p)  # bf16 compute, fp32 masters
            if moe is not None:
                from ..models.moe import moe_layer_apply
                rng_mb = mb_rng(mm)
                offset = stage_of(vv) * lps

                def mstep(carry, xs):
                    lp, i = xs
                    h, aux = carry
                    # per-layer dropout stream keyed on the GLOBAL layer
                    # index, matching the dense body's convention — masks
                    # are (D, V)-partition invariant
                    rng_l = (None if rng_mb is None
                             else jax.random.fold_in(rng_mb, offset + i))
                    h, a = moe_layer_apply(cfg, moe, lp, h, ep_axis,
                                           tp_axis=tp_axis, tp_size=T,
                                           rng=rng_l, sp_axis=sp_axis,
                                           sp_attn_impl=sp_attn_impl,
                                           sp_size=n_seq)
                    return (h, aux + a), None

                if cfg.remat_layers:
                    mstep = jax.checkpoint(mstep)
                (y, aux), _ = jax.lax.scan(mstep, (x, zero),
                                           (layer_p, jnp.arange(lps)))
                return y, aux
            if sp_axis is None:
                return (body_apply(cfg, layer_p, x, tp_axis=tp_axis,
                                   tp_size=T, rng=mb_rng(mm),
                                   layer_offset=stage_of(vv) * lps), zero)
            # sequence-sharded stage: ring/Ulysses attention across 'seq'
            # (ring optionally Megatron head-sharded over 'model' as well)
            from .seq_parallel import sp_body_apply
            return (sp_body_apply(cfg, layer_p, x, sp_axis,
                                  attn_impl=sp_attn_impl,
                                  tp_axis=tp_axis, tp_size=T,
                                  rng=mb_rng(mm),
                                  layer_offset=stage_of(vv) * lps,
                                  sp_size=n_seq), zero)

        def stage_embed(embed_p, toks, mm=0):
            with jax.named_scope("pp/embed"):
                embed_p = compute_cast(cfg, embed_p)
                rng_mb = mb_rng(mm)
                rng_e = (None if rng_mb is None
                         else jax.random.fold_in(rng_mb, cfg.n_layers))
                if sp_axis is None:
                    return embed_apply(cfg, embed_p, toks, rng=rng_e)
                from .seq_parallel import sp_embed_apply
                return sp_embed_apply(cfg, embed_p, toks, sp_axis, rng=rng_e,
                                      sp_size=n_seq)

        def select_v(tree, v):
            return jax.tree.map(
                lambda x: jax.lax.dynamic_index_in_dim(x, v, 0, keepdims=False),
                tree)

        def stage_params(vv):
            """This tick's active chunk parameters. Under fsdp the sharded
            leaves all-gather over 'data' just in time — only ONE chunk's
            full weights are ever resident, and only for the tick. The
            gather dim is per-leaf (``_fsdp_shard_dims``): with TP, 'data'
            rides a different dim than the leaf's 'model' shard."""
            p = select_v(layers_local, vv)
            if not fsdp:
                return p
            return jax.tree.map(
                lambda x, dm: jax.lax.all_gather(x, DATA_AXIS, axis=dm,
                                                 tiled=True) if dm >= 0
                else x,
                p, fsdp_dims)

        def scatter_chunk_grads(gp):
            """ZeRO-2 half of fsdp: reduce-scatter this tick's full chunk
            grads over 'data' so the accumulator carry stays sharded (the
            scatter also performs the cross-replica grad sum for these
            leaves — the epilogue skips its data-psum for them)."""
            if not fsdp:
                return gp
            return jax.tree.map(
                lambda g, dm: jax.lax.psum_scatter(
                    g, DATA_AXIS, scatter_dimension=dm, tiled=True)
                if dm >= 0 else g,
                gp, fsdp_dims)

        masked_store = _masked_store

        # Every device's objective is its local share; the shards' implicit
        # SPMD sum is the global mean, so no collective sits inside the
        # objective. The reported loss is psum'd once, outside the schedule.
        loss_norm = n_seq * n_ep
        aux_scale = (moe.aux_loss_weight / cfg.n_layers / loss_norm
                     if moe is not None else 0.0)

        if cfg.pad_token_id is not None:
            # the scale absorbs the WHOLE normalization (incl. the seq- and
            # expert-shard sums), so the pad branches below skip /loss_norm
            shard_axes = tuple(
                ax for ax, n in ((SEQ_AXIS, n_seq), (EXPERT_AXIS, n_ep))
                if n > 1)
            pad_scale = global_pad_scale(
                targets, cfg.pad_token_id, M,
                data_axis=DATA_AXIS if n_data > 1 else None,
                shard_axes=shard_axes or None)

        def stage_objective(p_v, head_arg, x_in, vv, mm, last_stage, g_in):
            """-> (objective, loss_report). The objective's gradients are the
            stage VJP: the real loss through the head on the last stage, else
            the contraction of the stage output with the incoming cotangent —
            plus this stage's share of the MoE routing aux loss. loss_report
            is what the tick accumulates into the reported loss. ``(vv, mm)``
            select the dropout stream, so the rematerialized forward here
            draws exactly the masks the forward unit drew. Under tied
            embeddings ``head_arg`` is ``(head, embed)`` so the embedding
            receives its head-matmul gradient through this VJP."""
            head_arg = compute_cast(cfg, head_arg)
            if cfg.tie_embeddings:
                head_p, embed_p = head_arg
            else:
                head_p, embed_p = head_arg, None
            y, aux = stage_body(p_v, x_in, vv, mm)

            def loss_branch():
                return _stage_ce(
                    cfg, head_p, embed_p, y, targets_mb[mm],
                    tp_axis=tp_axis, T=T,
                    tp_vocab_parallel=tp_vocab_parallel,
                    pad_scale=pad_scale if cfg.pad_token_id is not None
                    else None,
                    loss_norm=loss_norm)

            main = jax.lax.cond(
                last_stage, loss_branch,
                lambda: jnp.sum(y.astype(jnp.float32)
                                * g_in.astype(jnp.float32)))
            aux_term = aux * aux_scale
            report = jnp.where(last_stage, main, 0.0) + aux_term
            return main + aux_term, report

        if use_stored:
            # Stored-activation backward: classify the stage body's vjp
            # residuals once (abstract trace; vv/mm/x are arguments so the
            # jaxpr matches the live units', where they are tracers) and
            # allocate slot buffers for the x-dependent leaves only — the
            # x-independent ones (casts of weights, RoPE tables, masks) are
            # re-derived live at backward. See stored_backward module doc.
            from .stored_backward import (check_residual_leaves,
                                          x_dependent_mask)

            def body_vjp_leaves(p_v, x_in, vv, mm):
                _, vjp_fn = jax.vjp(
                    lambda p, xi: stage_body(p, xi, vv, mm), p_v, x_in)
                return tuple(jax.tree.leaves(vjp_fn))

            _mask_args = (select_v(layers_local, 0),
                          jnp.zeros(mb_shape, dtype),
                          jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32))
            res_mask = x_dependent_mask(body_vjp_leaves, _mask_args, (1,))
            res_struct = jax.eval_shape(body_vjp_leaves, *_mask_args)
            stored_struct = tuple(
                s for s, m0 in zip(res_struct, res_mask) if m0)
        else:
            res_mask = stored_struct = res_struct = ()

        def run_unit(pred, unit, noop, operand, know=None):
            """Execute one schedule unit. Default: a lax.cond (idle devices
            take the cheap branch; psum/all_to_all inside are grouped, so a
            group that skips together is fine). Ring-attention stages: run
            the unit unconditionally and where-mask its outputs against the
            noop's — ppermute (flat-pair collective-permute) requires full
            participation, so every seq peer must execute the unit's ring
            collectives every tick (see docs/parallelism.md). ``know``
            (unrolled ticks): the concrete device-uniform predicate — the
            cond/mask disappears. Elision is uniform across seq/model/data
            peers because the table row is shared along those axes."""
            if know is True:
                return unit(operand)
            if know is False:
                return noop(operand)
            if not uniform_units:
                return jax.lax.cond(pred, unit, noop, operand)
            return jax.tree.map(lambda n, o: jnp.where(pred, n, o),
                                unit(operand), noop(operand))

        def transfers(fwd_send, bwd_send, next_concrete=None):
            """End-of-tick ring hops. Classic wrap placement: activations
            ride +1, cotangents -1. With reverse routes (vshape), the same
            send values ALSO ride the opposite rings — each consumer banks
            only from the channel its table entry names, so the extra
            copies are dead unless routed. Unrolled ticks pass the NEXT
            tick's concrete row block: a channel no device banks next tick
            is dead, so its ppermute is elided (zeros flow instead) — the
            last tick and e.g. GPipe's whole warmup lose their grad-ring
            hops this way."""
            def hop(send, perm, bank_col, name):
                if next_concrete is not None and (
                        next_concrete[:, bank_col] < 0).all():
                    return jnp.zeros(mb_shape, dtype)
                with jax.named_scope(name):
                    return jax.lax.ppermute(send, PIPE_AXIS, perm)

            fr = hop(fwd_send, fwd_perm, COL_STORE_F_SLOT, "pp/ring_fwd")
            br = hop(bwd_send, bwd_perm, COL_STORE_B_SLOT, "pp/ring_bwd")
            if not reverse_routes:
                return (fr, br)
            return (fr, br,
                    hop(fwd_send, bwd_perm, COL_STORE_F_NEG_SLOT,
                        "pp/ring_fwd_rev"),
                    hop(bwd_send, fwd_perm, COL_STORE_B_POS_SLOT,
                        "pp/ring_bwd_rev"))

        def _sq_tree(t):
            """Sum of squared elements over a pytree, f32 (dynamics: one
            unit's share of its microbatch's squared grad norm)."""
            return sum((jnp.sum(jnp.square(l.astype(jnp.float32)))
                        for l in jax.tree.leaves(t)), jnp.float32(0.0))

        def tick(carry, row_all, concrete=None, next_concrete=None,
                 bank_stages=None):
            if dyn:
                (act_buf, grad_buf, res_bufs, recvs,
                 g_layers, g_embed, g_head, loss_acc, sq_mb) = carry
            else:
                (act_buf, grad_buf, res_bufs, recvs,
                 g_layers, g_embed, g_head, loss_acc) = carry
                sq_mb = None
            row = row_all[d]

            def ccol(col):
                return None if concrete is None else concrete[:, col]

            def store(buf, val, col):
                # unrolled: a row block that banks nowhere skips the
                # masked dynamic-update-slice entirely
                if concrete is not None and (concrete[:, col] < 0).all():
                    return buf
                return masked_store(buf, val, row[col])

            # 1. bank arrivals from last tick's ppermute channels — each at
            # its bank stage (comm_overlap='ring': the recv register IS the
            # second edge-slot buffer of the double-buffered discipline, so
            # deferring the edge-slot commit past units that don't touch the
            # slot removes the data dependency that fences the hop against
            # this tick's compute). ``bank_stages=None`` — the default and
            # the scan path — is the all-stage-0 lockstep program,
            # bit-identical to the pre-overlap executor.
            stages = (0, 0, 0, 0) if bank_stages is None else tuple(bank_stages)

            def bank_now(k, act_buf, grad_buf):
                if stages[0] == k:
                    act_buf = store(act_buf, recvs[0], COL_STORE_F_SLOT)
                if stages[1] == k:
                    grad_buf = store(grad_buf, recvs[1], COL_STORE_B_SLOT)
                if reverse_routes:
                    if stages[2] == k:
                        act_buf = store(act_buf, recvs[2],
                                        COL_STORE_F_NEG_SLOT)
                    if stages[3] == k:
                        grad_buf = store(grad_buf, recvs[3],
                                         COL_STORE_B_POS_SLOT)
                return act_buf, grad_buf

            act_buf, grad_buf = bank_now(BANK_BEFORE_F, act_buf, grad_buf)

            # 2. forward unit
            fv, fm, fslot = row[COL_FWD_V], row[COL_FWD_M], row[COL_FWD_SLOT]

            if use_stored:
                # Buffer discipline (measured on v5e): the slot-buffer
                # writes live INSIDE the cond — only the taken branch
                # touches them, so idle ticks cost nothing. (The
                # alternative — cond returns the leaves, masked_store
                # outside — materializes slot-sized zeros every idle tick
                # and re-writes every active tick: measured 1.4x slower.)
                def fwd_unit(op):
                    act_buf, res_bufs, loss_acc = op
                    vv, mm = jnp.maximum(fv, 0), jnp.maximum(fm, 0)
                    ss = jnp.maximum(fslot, 0)
                    first_stage = is_first_dev & (vv == 0)
                    x_emb = stage_embed(embed, tokens_mb[mm],
                                        mm).astype(dtype)
                    x = jnp.where(first_stage, x_emb, act_buf[ss])
                    (y, aux), vjp_fn = jax.vjp(
                        lambda p, xi: stage_body(p, xi, vv, mm),
                        stage_params(vv), x)
                    leaves, _ = jax.tree.flatten(vjp_fn)
                    check_residual_leaves(leaves, res_struct, "forward")
                    stored = (l for l, m0 in zip(leaves, res_mask) if m0)
                    res_bufs = tuple(
                        b.at[ss].set(l) for b, l in zip(res_bufs, stored))
                    # the slot banks the body OUTPUT (the backward's head
                    # input on the last stage); x is spent — same lifetime,
                    # same slot, no extra buffer
                    act_buf = act_buf.at[ss].set(y)
                    # the MoE routing aux share of the reported loss is
                    # known at forward time here (the CE share lands in the
                    # backward unit); the remat path accumulates both at
                    # backward — the totals are identical
                    return (act_buf, res_bufs,
                            loss_acc + aux * aux_scale), y

                def fwd_noop(op):
                    return op, jnp.zeros(mb_shape, dtype)

                with jax.named_scope("pp/fwd"):
                    (act_buf, res_bufs, loss_acc), fwd_send = run_unit(
                        fm >= 0, fwd_unit, fwd_noop,
                        (act_buf, res_bufs, loss_acc),
                        know=_concrete_know(ccol(COL_FWD_M)))
            else:
                def fwd_unit(act_buf):
                    vv, mm = jnp.maximum(fv, 0), jnp.maximum(fm, 0)
                    ss = jnp.maximum(fslot, 0)
                    first_stage = is_first_dev & (vv == 0)
                    x_emb = stage_embed(embed, tokens_mb[mm],
                                        mm).astype(dtype)
                    x = jnp.where(first_stage, x_emb, act_buf[ss])
                    act_buf = act_buf.at[ss].set(x)  # saved for remat bwd
                    y, _ = stage_body(stage_params(vv), x, vv, mm)
                    return act_buf, y

                def fwd_noop(act_buf):
                    return act_buf, jnp.zeros(mb_shape, dtype)

                with jax.named_scope("pp/fwd"):
                    act_buf, fwd_send = run_unit(
                        fm >= 0, fwd_unit, fwd_noop, act_buf,
                        know=_concrete_know(ccol(COL_FWD_M)))
            if reverse_routes:
                # same-device hop (vshape's V turning point): the output IS
                # the next chunk's input — bank it locally, no ring transit
                act_buf = store(act_buf, fwd_send, COL_FWD_LOCAL_SLOT)
            act_buf, grad_buf = bank_now(BANK_BEFORE_B, act_buf, grad_buf)

            # 3. backward unit (rematerializing)
            bv, bm = row[COL_BWD_V], row[COL_BWD_M]

            if split:
                # Split backward (ZB-H1): B computes only the input cotangent
                # (the half on the inter-stage critical path — upstream's
                # stage_backward_input, _backward.py:177); W later redoes the
                # stage VJP for parameter grads (stage_backward_weight,
                # _backward.py:281) in ticks that would otherwise be bubble.
                def dgrad_unit(loss_acc):
                    vv, mm = jnp.maximum(bv, 0), jnp.maximum(bm, 0)
                    last_stage = is_last_dev & (vv == last_chunk)
                    x = act_buf[jnp.maximum(row[COL_BWD_ASLOT], 0)]
                    g_in = grad_buf[jnp.maximum(row[COL_BWD_GSLOT], 0)]
                    params_v = stage_params(vv)
                    (_, report), gx = jax.value_and_grad(
                        lambda x_in: stage_objective(params_v, head_bundle, x_in, vv,
                                                     mm, last_stage, g_in),
                        has_aux=True)(x)
                    return loss_acc + report, gx

                def dgrad_noop(loss_acc):
                    return loss_acc, jnp.zeros(mb_shape, dtype)

                with jax.named_scope("pp/bwd_dgrad"):
                    loss_acc, bwd_send = run_unit(
                        bm >= 0, dgrad_unit, dgrad_noop, loss_acc,
                        know=_concrete_know(ccol(COL_BWD_M)))
                if reverse_routes:
                    grad_buf = store(grad_buf, bwd_send, COL_BWD_LOCAL_SLOT)
                act_buf, grad_buf = bank_now(BANK_BEFORE_W, act_buf,
                                             grad_buf)

                wv, wm = row[COL_W_V], row[COL_W_M]

                def wgrad_unit(operand):
                    if dyn:
                        g_layers, g_embed, g_head, sq_mb = operand
                    else:
                        g_layers, g_embed, g_head = operand
                    vv, mm = jnp.maximum(wv, 0), jnp.maximum(wm, 0)
                    last_stage = is_last_dev & (vv == last_chunk)
                    first_stage = is_first_dev & (vv == 0)
                    x_slot = act_buf[jnp.maximum(row[COL_W_ASLOT], 0)]
                    g_in = grad_buf[jnp.maximum(row[COL_W_GSLOT], 0)]
                    params_v = stage_params(vv)
                    (gp, gh, gx), _ = jax.grad(
                        lambda p_v, head_p, x_in: stage_objective(
                            p_v, head_p, x_in, vv, mm, last_stage, g_in),
                        argnums=(0, 1, 2), has_aux=True)(params_v, head_bundle, x_slot)
                    if cfg.tie_embeddings:
                        # fold the tied head's embed grad into the ONE
                        # g_embed accumulator (a bundle-shaped g_head carry
                        # would duplicate the [vocab, dim] buffer per device)
                        gh, gh_embed = gh
                        g_embed = jax.tree.map(jnp.add, g_embed, gh_embed)
                    gp = scatter_chunk_grads(gp)
                    g_layers = jax.tree.map(lambda a, g: a.at[vv].add(g),
                                            g_layers, gp)
                    g_head = jax.tree.map(jnp.add, g_head, gh)
                    # Embedding wgrad only on the first stage (its saved input
                    # IS the embed output, so gx is the embed cotangent).
                    if dyn:
                        # dynamics restructures the cond to return the
                        # grad-or-zeros tree so its norm is observable;
                        # the off path keeps the original trace untouched
                        eg = jax.lax.cond(
                            first_stage,
                            lambda: jax.grad(lambda e: jnp.vdot(
                                stage_embed(e, tokens_mb[mm],
                                            mm).astype(jnp.float32),
                                gx.astype(jnp.float32)))(embed),
                            lambda: jax.tree.map(jnp.zeros_like, embed))
                        g_embed = jax.tree.map(jnp.add, g_embed, eg)
                        sq_mb = sq_mb.at[mm].add(
                            _sq_tree(gp) + _sq_tree(gh) + _sq_tree(eg))
                        return (g_layers, g_embed, g_head, sq_mb)
                    g_embed = jax.lax.cond(
                        first_stage,
                        lambda: jax.tree.map(
                            jnp.add, g_embed,
                            jax.grad(lambda e: jnp.vdot(
                                stage_embed(e, tokens_mb[mm], mm).astype(jnp.float32),
                                gx.astype(jnp.float32)))(embed)),
                        lambda: g_embed)
                    return (g_layers, g_embed, g_head)

                with jax.named_scope("pp/wgrad"):
                    w_op = (g_layers, g_embed, g_head) + (
                        (sq_mb,) if dyn else ())
                    w_out = run_unit(
                        wm >= 0, wgrad_unit, lambda operand: operand, w_op,
                        know=_concrete_know(ccol(COL_W_M)))
                    if dyn:
                        g_layers, g_embed, g_head, sq_mb = w_out
                    else:
                        g_layers, g_embed, g_head = w_out

                act_buf, grad_buf = bank_now(BANK_END, act_buf, grad_buf)
                return (act_buf, grad_buf, res_bufs,
                        transfers(fwd_send, bwd_send, next_concrete),
                        g_layers, g_embed, g_head, loss_acc) + (
                            (sq_mb,) if dyn else ()), None

            def bwd_unit_stored(operand):
                """Stored-activation backward: head+CE grads from live
                weights and the banked body output y; body grads by
                replaying the banked vjp residuals (x-independent leaves
                re-derived live — the dummy-x forward chain is dead code
                XLA eliminates). No stage forward is recomputed."""
                if dyn:
                    g_layers, g_embed, g_head, loss_acc, sq_mb = operand
                else:
                    g_layers, g_embed, g_head, loss_acc = operand
                vv, mm = jnp.maximum(bv, 0), jnp.maximum(bm, 0)
                last_stage = is_last_dev & (vv == last_chunk)
                first_stage = is_first_dev & (vv == 0)
                aslot = jnp.maximum(row[COL_BWD_ASLOT], 0)
                y = act_buf[aslot]
                g_in = grad_buf[jnp.maximum(row[COL_BWD_GSLOT], 0)]
                params_v = stage_params(vv)

                def head_obj(head_arg, yy):
                    head_arg = compute_cast(cfg, head_arg)
                    if cfg.tie_embeddings:
                        head_p, embed_p = head_arg
                    else:
                        head_p, embed_p = head_arg, None
                    return _stage_ce(
                        cfg, head_p, embed_p, yy, targets_mb[mm],
                        tp_axis=tp_axis, T=T,
                        tp_vocab_parallel=tp_vocab_parallel,
                        pad_scale=pad_scale if cfg.pad_token_id is not None
                        else None,
                        loss_norm=loss_norm)

                def last_branch():
                    ce, (gh_d, ct_y) = jax.value_and_grad(
                        head_obj, argnums=(0, 1))(head_bundle, y)
                    return gh_d, ct_y, ce

                def other_branch():
                    return (jax.tree.map(jnp.zeros_like, head_bundle),
                            g_in, jnp.zeros((), jnp.float32))

                gh, ct_y, ce = jax.lax.cond(last_stage, last_branch,
                                            other_branch)
                # replay the banked residuals: re-trace the SAME vjp with a
                # dummy x, take x-independent leaves fresh, banked otherwise
                _, vjp2 = jax.vjp(
                    lambda p, xi: stage_body(p, xi, vv, mm), params_v,
                    jnp.zeros(mb_shape, dtype))
                fresh, treedef2 = jax.tree.flatten(vjp2)
                check_residual_leaves(fresh, res_struct, "backward")
                banked = iter(res_bufs)
                sel = [next(banked)[aslot] if m0 else f
                       for m0, f in zip(res_mask, fresh)]
                gp, gx = jax.tree.unflatten(treedef2, sel)(
                    (ct_y, jnp.asarray(aux_scale, jnp.float32)))

                if cfg.tie_embeddings:
                    gh, gh_embed = gh
                    g_embed = jax.tree.map(jnp.add, g_embed, gh_embed)
                g_layers = jax.tree.map(lambda a, g: a.at[vv].add(g),
                                        g_layers, gp)
                g_head = jax.tree.map(jnp.add, g_head, gh)
                if dyn:
                    eg = jax.lax.cond(
                        first_stage,
                        lambda: jax.grad(lambda e: jnp.vdot(
                            stage_embed(e, tokens_mb[mm],
                                        mm).astype(jnp.float32),
                            gx.astype(jnp.float32)))(embed),
                        lambda: jax.tree.map(jnp.zeros_like, embed))
                    g_embed = jax.tree.map(jnp.add, g_embed, eg)
                    sq_mb = sq_mb.at[mm].add(
                        _sq_tree(gp) + _sq_tree(gh) + _sq_tree(eg))
                    loss_acc = loss_acc + ce
                    return (g_layers, g_embed, g_head, loss_acc, sq_mb), gx
                g_embed = jax.lax.cond(
                    first_stage,
                    lambda: jax.tree.map(
                        jnp.add, g_embed,
                        jax.grad(lambda e: jnp.vdot(
                            stage_embed(e, tokens_mb[mm],
                                        mm).astype(jnp.float32),
                            gx.astype(jnp.float32)))(embed)),
                    lambda: g_embed)
                loss_acc = loss_acc + ce
                return (g_layers, g_embed, g_head, loss_acc), gx

            def bwd_unit_remat(operand):
                if dyn:
                    g_layers, g_embed, g_head, loss_acc, sq_mb = operand
                else:
                    g_layers, g_embed, g_head, loss_acc = operand
                vv, mm = jnp.maximum(bv, 0), jnp.maximum(bm, 0)
                last_stage = is_last_dev & (vv == last_chunk)
                first_stage = is_first_dev & (vv == 0)
                x = act_buf[jnp.maximum(row[COL_BWD_ASLOT], 0)]
                g_in = grad_buf[jnp.maximum(row[COL_BWD_GSLOT], 0)]
                params_v = stage_params(vv)
                (_, report), (gp, gh, gx) = jax.value_and_grad(
                    lambda p_v, head_p, x_in: stage_objective(
                        p_v, head_p, x_in, vv, mm, last_stage, g_in),
                    argnums=(0, 1, 2), has_aux=True)(params_v, head_bundle, x)

                if cfg.tie_embeddings:
                    # fold the tied head's embed grad into the ONE g_embed
                    # accumulator (see wgrad_unit note)
                    gh, gh_embed = gh
                    g_embed = jax.tree.map(jnp.add, g_embed, gh_embed)
                gp = scatter_chunk_grads(gp)
                g_layers = jax.tree.map(lambda a, g: a.at[vv].add(g),
                                        g_layers, gp)
                g_head = jax.tree.map(jnp.add, g_head, gh)
                if dyn:
                    eg = jax.lax.cond(
                        first_stage,
                        lambda: jax.grad(lambda e: jnp.vdot(
                            stage_embed(e, tokens_mb[mm],
                                        mm).astype(jnp.float32),
                            gx.astype(jnp.float32)))(embed),
                        lambda: jax.tree.map(jnp.zeros_like, embed))
                    g_embed = jax.tree.map(jnp.add, g_embed, eg)
                    sq_mb = sq_mb.at[mm].add(
                        _sq_tree(gp) + _sq_tree(gh) + _sq_tree(eg))
                    loss_acc = loss_acc + report
                    return (g_layers, g_embed, g_head, loss_acc, sq_mb), gx
                g_embed = jax.lax.cond(
                    first_stage,
                    lambda: jax.tree.map(
                        jnp.add, g_embed,
                        jax.grad(lambda e: jnp.vdot(
                            stage_embed(e, tokens_mb[mm], mm).astype(jnp.float32),
                            gx.astype(jnp.float32)))(embed)),
                    lambda: g_embed)
                loss_acc = loss_acc + report
                return (g_layers, g_embed, g_head, loss_acc), gx

            def bwd_noop(operand):
                return operand, jnp.zeros(mb_shape, dtype)

            with jax.named_scope("pp/bwd"):
                b_op = (g_layers, g_embed, g_head, loss_acc) + (
                    (sq_mb,) if dyn else ())
                b_out, bwd_send = run_unit(
                    bm >= 0,
                    bwd_unit_stored if use_stored else bwd_unit_remat,
                    bwd_noop, b_op,
                    know=_concrete_know(ccol(COL_BWD_M)))
                if dyn:
                    g_layers, g_embed, g_head, loss_acc, sq_mb = b_out
                else:
                    g_layers, g_embed, g_head, loss_acc = b_out
            if reverse_routes:
                grad_buf = store(grad_buf, bwd_send, COL_BWD_LOCAL_SLOT)
            # non-split: no W unit, so the BEFORE_W and END bank points
            # coincide here (both after B, before the hops)
            act_buf, grad_buf = bank_now(BANK_BEFORE_W, act_buf, grad_buf)
            act_buf, grad_buf = bank_now(BANK_END, act_buf, grad_buf)

            # 4. ring transfer: activations +1, gradients -1 (ICI hops);
            # vshape placements add the two reverse channels
            return (act_buf, grad_buf, res_bufs,
                    transfers(fwd_send, bwd_send, next_concrete),
                    g_layers, g_embed, g_head, loss_acc) + (
                        (sq_mb,) if dyn else ()), None

        n_chan = 4 if reverse_routes else 2
        carry0 = (
            jnp.zeros((cs.n_act_slots,) + mb_shape, dtype),
            jnp.zeros((cs.n_grad_slots,) + mb_shape, dtype),
            tuple(jnp.zeros((cs.n_act_slots,) + s.shape, s.dtype)
                  for s in stored_struct),
            tuple(jnp.zeros(mb_shape, dtype) for _ in range(n_chan)),
            jax.tree.map(jnp.zeros_like, layers_local),
            jax.tree.map(jnp.zeros_like, embed),
            jax.tree.map(jnp.zeros_like, head),
            jnp.zeros((), jnp.float32),
        ) + ((jnp.zeros((M,), jnp.float32),) if dyn else ())
        if unroll_ticks == "phases":
            # phase-compressed: one specialized scan body per unique row
            # pattern, each phase driven as a lax.scan over its real rows
            carry = _phase_compressed_ticks(tick, carry0, table, phases,
                                            telemetry=telemetry,
                                            bank_stages=bank_stages_tab)
        elif unroll_ticks:
            # straight-line tick program: the Python loop IS the schedule,
            # each tick specialized against its concrete table row block
            # (cond/ppermute/store elision — see the tick helpers above)
            carry = carry0
            n_rows = cs.table.shape[0]
            if telemetry is not None:
                from ..utils import telemetry as _tm
                telemetry.emit(_tm.STEP_START, 0, _tm.probe_of(carry))
            # after the final tick nothing banks: an all-dead pseudo-row
            # elides the last hops (None means "no knowledge" — scan path)
            end_row = np.full_like(cs.table[0], -1)
            for t in range(n_rows):
                nxt = cs.table[t + 1] if t + 1 < n_rows else end_row
                bs = (None if bank_stages_tab is None
                      else tuple(int(v) for v in bank_stages_tab[t]))
                with jax.named_scope(f"pp/tick{t:03d}"):
                    carry, _ = tick(carry, table[t], concrete=cs.table[t],
                                    next_concrete=nxt, bank_stages=bs)
                if telemetry is not None:
                    telemetry.emit(_tm.TICK, t, _tm.probe_of(carry))
        else:
            if telemetry is not None:
                from ..utils import telemetry as _tm
                telemetry.emit(_tm.STEP_START, 0, _tm.probe_of(carry0))
            carry, _ = jax.lax.scan(tick, carry0, table)
            if telemetry is not None:
                telemetry.emit(_tm.STEP_END, 0, _tm.probe_of(carry))
        if dyn:
            (_, _, _, _, g_layers, g_embed, g_head, loss_acc,
             sq_mb) = carry
        else:
            (_, _, _, _, g_layers, g_embed, g_head, loss_acc) = carry

        # Reductions: loss lives on the last stage only; embed/head grads on
        # one device each — psum replicates them across 'pipe'. Scale by 1/M
        # (upstream scale_grads semantics) and mean over data replicas.
        inv = 1.0 / M
        loss = jax.lax.psum(loss_acc, PIPE_AXIS) * inv
        if n_seq > 1:
            # each shard accumulated local_mean/n_seq -> sum = global mean
            loss = jax.lax.psum(loss, SEQ_AXIS)
        if n_ep > 1:
            loss = jax.lax.psum(loss, EXPERT_AXIS)
        g_layers = jax.tree.map(lambda x: x[None] * inv, g_layers)
        g_embed = jax.tree.map(lambda x: jax.lax.psum(x * inv, PIPE_AXIS), g_embed)
        g_head = jax.tree.map(lambda x: jax.lax.psum(x * inv, PIPE_AXIS), g_head)
        if n_data > 1:
            nd = 1.0 / n_data
            loss = jax.lax.psum(loss * nd, DATA_AXIS)
            if fsdp:
                # sharded layer leaves were already cross-replica summed by
                # the per-tick psum_scatter — only the scale remains; a
                # second psum here would n_data-fold them
                g_layers = jax.tree.map(
                    lambda x, dm: x * nd if dm >= 0
                    else jax.lax.psum(x * nd, DATA_AXIS),
                    g_layers, fsdp_dims)
                g_embed, g_head = jax.tree.map(
                    lambda x: jax.lax.psum(x * nd, DATA_AXIS),
                    (g_embed, g_head))
            else:
                g_layers, g_embed, g_head = jax.tree.map(
                    lambda x: jax.lax.psum(x * nd, DATA_AXIS),
                    (g_layers, g_embed, g_head))
        if n_seq > 1:
            # each seq shard holds its local-token share of d(global mean
            # loss)/d(params); the full grad is their unscaled sum (loss is
            # already the global mean and replicated across 'seq')
            g_layers, g_embed, g_head = jax.tree.map(
                lambda x: jax.lax.psum(x, SEQ_AXIS),
                (g_layers, g_embed, g_head))
        if n_ep > 1:
            # 'expert' doubles as a batch axis: replicated params sum their
            # per-shard local contributions; expert-sharded stacks (the
            # w1/b1/w2/b2 leaves under "moe") are already complete per shard
            # (every token reached its expert via the all_to_all), so they
            # stay local
            from .expert_parallel import is_expert_leaf

            def ep_reduce(path, g):
                return g if is_expert_leaf(path) else \
                    jax.lax.psum(g, EXPERT_AXIS)

            g_layers = jax.tree_util.tree_map_with_path(ep_reduce, g_layers)
            g_embed, g_head = jax.tree.map(
                lambda x: jax.lax.psum(x, EXPERT_AXIS), (g_embed, g_head))
        if dyn:
            # stages partition the (untied) params, so the pipe psum
            # completes each microbatch's |g_m|^2; data replicas hold
            # DIFFERENT microbatches — average their norms (each is one
            # sample of E|g_small|^2, the GNS small-batch moment)
            sq_mb = jax.lax.psum(sq_mb, PIPE_AXIS)
            if n_data > 1:
                sq_mb = jax.lax.psum(sq_mb * (1.0 / n_data), DATA_AXIS)
            return loss, g_layers, g_embed, g_head, sq_mb
        return loss, g_layers, g_embed, g_head

    if moe is not None:
        layer_spec = _moe_layer_specs(cfg, moe, T, n_ep)
        if fsdp_dims is not None:
            layer_spec = _merge_fsdp_into_stacked(layer_spec, fsdp_dims)
    elif T > 1 or fsdp:
        # Per-leaf placement for the stacked layer pytree: Megatron 'model'
        # placement (heads and FFN hidden column-split, o/down row-split)
        # merged with the per-leaf fsdp 'data' dims — pp x tp, pp x fsdp,
        # and pp x fsdp x tp all come from the one helper
        layer_spec = _dense_layer_specs(cfg, T, fsdp_dims)
    else:
        layer_spec = P(PIPE_AXIS)
    if n_seq > 1:
        # with an expert axis too (MoE x seq, round 5) the batch shards
        # over data x expert while the sequence shards over seq
        lead = (DATA_AXIS, EXPERT_AXIS) if n_ep > 1 else DATA_AXIS
        batch_spec = P(lead, SEQ_AXIS)
    elif n_ep > 1:
        batch_spec = P((DATA_AXIS, EXPERT_AXIS))  # batch over data x expert
    else:
        batch_spec = P(DATA_AXIS)
    if tp_vocab_parallel and not cfg.tie_embeddings:
        # vocab-sharded head: out.w [dim, V] column-split, bias (ref arch)
        # split with it; the norm stays replicated
        out_spec = ({"w": P(None, MODEL_AXIS), "b": P(MODEL_AXIS)}
                    if cfg.arch == "ref_decoder"
                    else {"w": P(None, MODEL_AXIS)})
        head_spec = {"norm": P(), "out": out_spec}
    else:
        # tied + vocab-parallel: the head is only the norm; the vocab split
        # is a row-slice of the replicated embedding inside the objective
        head_spec = P()
    in_specs = (layer_spec, P(), head_spec, batch_spec, batch_spec)
    if use_dropout:
        in_specs = in_specs + (P(),)  # step rng: replicated raw key data
    sharded = _shard_map(
        spmd_fn, mesh,
        in_specs=in_specs,
        out_specs=(P(), layer_spec, P(), head_spec) + (
            (P(),) if dyn else ()),
    )

    def unpack(loss, g_layers, g_embed, g_head, *extras):
        grads = {
            "embed": g_embed,
            "layers": unstack_stage_layers(g_layers, placement),
            "head": g_head,
        }
        if dyn:
            return loss, grads, extras[0]
        return loss, grads

    if use_dropout:
        # Train-mode step: the caller supplies a per-step PRNG key; passing
        # the key's raw data through shard_map sidesteps typed-key sharding.
        def step(params, tokens, targets, rng):
            stacked = stack_stage_layers(params["layers"], D, V, placement)
            return unpack(*sharded(
                stacked, params["embed"], params["head"], tokens, targets,
                jax.random.key_data(rng)))

        return step

    def step(params, tokens, targets):
        stacked = stack_stage_layers(params["layers"], D, V, placement)
        return unpack(*sharded(
            stacked, params["embed"], params["head"], tokens, targets))

    return step


def make_pipeline_step(cfg: ModelConfig, mesh: Mesh, sched: ScheduleConfig,
                       force_tick_executor: bool = False, moe=None,
                       sp_attn_impl: str = "ring",
                       tp_vocab_parallel: bool = False,
                       fsdp: bool = False,
                       remat_backward=None,
                       unroll_ticks=None,
                       telemetry=None,
                       dynamics=None,
                       comm_overlap: str = "none",
                       ) -> Callable[[Pytree, jax.Array, jax.Array],
                                     Tuple[jax.Array, Pytree]]:
    """Jitted ``(params, tokens, targets) -> (loss, grads)`` pipeline step.

    Matching the reference's measurement semantics (SURVEY.md §3.3 note): the
    step computes loss and gradients only — no optimizer update — so it can be
    timed exactly like ``schedule.step``. ``force_tick_executor`` disables
    the degenerate 1-device fast path (a single fused full-batch step that
    ignores microbatching), so the step really executes the compiled
    schedule's microbatch program; WHICH executor formulation runs it is
    chosen by ``remat_backward`` (see :func:`make_pipeline_grad_fn` — at
    D == 1 the default is the unrolled stored program; pass
    ``remat_backward=True`` for the rematerializing tick scan, as
    ``utils.profiling.measure_bubble`` does for its cost-matched
    comparator).

    ``unroll_ticks`` picks the tick-loop form (full detail and measured
    compile-time economics in :func:`make_pipeline_grad_fn`): ``True``
    unrolls the table into straight-line specialized ticks (1.05-1.2x
    throughput, ~2.2 s compile per row), ``"phases"`` scans per-pattern
    specialized bodies (the same specialization at a compile cost that
    scales with UNIQUE tick patterns — O(1) in M for steady-state 1F1B),
    ``False`` is the bounded-compile cond-dispatched scan (~7 s), and
    ``None`` (default) auto-selects ``True`` up to ``_UNROLL_TICKS_LIMIT``
    rows and ``"phases"`` beyond — a one-line ``logging.info`` announces
    when a large table triggers that auto phase-compression. If compile
    time still hurts (or you are bisecting an executor-formulation
    difference), the ESCAPE HATCHES are explicit ``unroll_ticks=False``
    (bounded-compile scan) or ``unroll_ticks="phases"`` — both run the
    identical tick program, bit-exact against the unrolled form.

    ``telemetry`` (opt-in ``utils.telemetry.PipelineTelemetry``) records a
    measured tick/phase timeline; None (default) compiles zero
    instrumentation (see :func:`make_pipeline_grad_fn`).

    ``dynamics`` (truthy) returns ``(loss, grads, sq_mb)`` instead — the
    per-microbatch squared grad norms feeding the gradient-noise-scale
    estimator (see :func:`make_pipeline_grad_fn`; falsy compiles a
    byte-identical program without the accumulator).

    ``comm_overlap`` (``"none"``/``"ring"``/``"auto"``) selects the
    double-buffered ring-hop discipline — bit-identical outputs, hops
    overlapped with the next tick's F/B compute (see
    :func:`make_pipeline_grad_fn`).
    """
    return jax.jit(make_pipeline_grad_fn(
        cfg, mesh, sched, force_tick_executor=force_tick_executor, moe=moe,
        sp_attn_impl=sp_attn_impl, tp_vocab_parallel=tp_vocab_parallel,
        fsdp=fsdp, remat_backward=remat_backward, unroll_ticks=unroll_ticks,
        telemetry=telemetry, dynamics=dynamics, comm_overlap=comm_overlap))


def aot_memory_analysis(step, *args) -> Dict[str, Any]:
    """XLA's memory accounting for a jitted step, ahead of time.

    ``lower(*args).compile()`` the step (the compile cache makes this
    free when the step already ran) and extract
    ``compiled.memory_analysis()``'s byte counters — the *compiled*
    accounting ``analysis.memory_model`` reconciles against its analytic
    slot model. Sizes are per addressable shard: a pipe-sharded
    parameter tree counts as layers/D plus the replicated operands per
    device (the reconciliation pin relies on this). Degrades to
    ``{"error": ...}`` on backends whose runtime exposes no memory
    analysis rather than failing the run."""
    try:
        compiled = step.lower(*args).compile()
        ma = compiled.memory_analysis()
        if ma is None:
            return {"error": "memory_analysis unavailable on this backend"}
        return {
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
            "alias_bytes": int(ma.alias_size_in_bytes),
            "generated_code_bytes": int(ma.generated_code_size_in_bytes),
        }
    except Exception as e:  # AOT paths vary by backend/jax version
        return {"error": str(e)}


def fsdp_shard_params(params: Pytree, cfg: ModelConfig, mesh: Mesh,
                      moe=None) -> Pytree:
    """Place a full-model pytree for pp x fsdp: layer leaves sharded over
    'pipe' on the layer dim (each pipe device keeps only its stages) AND
    over 'data' on the first weight dim for matrix leaves — the placement
    the executor's grads come back in, so params, grads, and optimizer
    state all rest at ~1/(D * n_data) of the model's layer weights per
    device. Embed/head stay replicated (O(vocab*dim), a few percent of a
    Llama-class model). With n_virtual > 1 the wrap placement's strided
    stage->device map makes the per-step stacking a (small, sharded)
    permute; with V=1 stacking is movement-free."""
    from jax.sharding import NamedSharding
    n_data = mesh.shape.get(DATA_AXIS, 1)
    if n_data <= 1:
        raise ValueError("fsdp_shard_params needs a 'data' mesh axis to "
                         "shard parameters over (make_mesh(n_data=...))")
    T = mesh.shape.get(MODEL_AXIS, 1)
    n_ep = mesh.shape.get(EXPERT_AXIS, 1)
    dims = _resolve_fsdp_dims(cfg, moe, n_data, T, n_ep, True)
    if moe is not None:
        # MoE resting layout (pp x fsdp x MoE): expert stacks over
        # 'expert', Megatron dims over 'model', fsdp 'data' on the
        # remaining free matrix dim — same per-leaf map the executor's
        # in/out specs use
        base = _moe_template_specs(cfg, moe, T, n_ep)
    elif T > 1:
        from .tensor_parallel import _layer_specs
        base = _layer_specs(cfg)
    else:
        base = jax.tree.map(lambda _: P(), dims)

    def put_layer(x, spec, dm):
        # full-model layer leaves are [L, w0, ...]: 'pipe' on the layer
        # dim, 'model' per the Megatron spec (T > 1), 'data' on the fsdp
        # dim — the same resting layout the executor's in/out specs name
        e = list(tuple(spec))
        e += [None] * (x.ndim - len(e))
        e[0] = PIPE_AXIS
        if dm >= 0:
            assert e[dm] is None, (spec, dm)
            e[dm] = DATA_AXIS
        return jax.device_put(x, NamedSharding(mesh, P(*e)))

    return {
        "embed": jax.tree.map(
            lambda x: jax.device_put(x, NamedSharding(mesh, P())),
            params["embed"]),
        "layers": jax.tree.map(put_layer, params["layers"], base, dims,
                               is_leaf=lambda x: isinstance(x, P)),
        "head": jax.tree.map(
            lambda x: jax.device_put(x, NamedSharding(mesh, P())),
            params["head"]),
    }


def _fwd_tick_table(D: int, V: int, M: int):
    """Forward-only tick table for the eval/inference executors: the
    F actions of the breadth-first (BFS) order — fill-drain generalized to
    V wrap-placed chunks — tick-scheduled and slot-allocated with the same
    machinery as the training tables. Returns (table [T, D, 4] int32 with
    columns (store_slot, fv, fm, src_slot), n_slots); store_slot banks the
    previous tick's +1-ring arrival, src_slot is where this tick's F reads
    its input (-1 = first stage: embed)."""
    import numpy as np

    from .schedules import (Action, F, _allocate_slots, bfs_order,
                            schedule_ticks)
    forders = [[a for a in order if a.op == F]
               for order in bfs_order(D, V, M)]
    ticks, T_compute = schedule_ticks(forders, D, V)
    # no +1: a store at t+1 always has a consumer at most at T_compute-1,
    # so the final compute tick is also the final row
    T = T_compute
    S = D * V
    # arrival of F(s, m)'s output at device (s+1) % D: store at tick+1,
    # consumed by F(s+1, m)'s tick
    events = {d: [] for d in range(D)}
    for a, t in ticks.items():
        if a.stage + 1 < S:
            nxt = Action(a.stage + 1, F, a.microbatch)
            events[(a.stage + 1) % D].append((t + 1, ticks[nxt], nxt))
    slot_of, n_slots = {}, 0
    for d in range(D):
        assign, n = _allocate_slots(events[d])
        slot_of.update(assign)
        n_slots = max(n_slots, n)
    table = np.full((T, D, 4), -1, dtype=np.int32)
    for a, t in ticks.items():
        d = a.stage % D
        table[t, d, 1] = a.stage // D
        table[t, d, 2] = a.microbatch
        if a.stage > 0:
            table[t, d, 3] = slot_of[a]
    for d in range(D):
        for arrive, _, key in events[d]:
            table[arrive, d, 0] = slot_of[key]
    n_slots = max(n_slots, 1)
    from ..analysis import maybe_verify_forward_table
    maybe_verify_forward_table(table, D, V, M, n_slots)
    return table, n_slots


def _build_forward_program(cfg: ModelConfig, mesh: Mesh,
                           sched: ScheduleConfig, sp_attn_impl: str,
                           tp_vocab_parallel: bool, fsdp: bool,
                           train_dropout: bool = False,
                           unroll=False, moe=None):
    """The forward-only tick program (BFS fill-drain over
    ``sched.n_virtual`` wrap-placed chunks; every schedule's forward order
    is fill-drain) shared by the eval loss (:func:`make_pipeline_loss_fn`)
    and the phase-separated stored backward (autodiff THROUGH this scan —
    see :func:`make_pipeline_grad_fn`). The last stage computes the
    token-mean CE per microbatch and accumulates it; [B, S, V] logits never
    materialize.

    ``unroll``: emit the ticks as a static Python loop instead of a
    ``lax.scan``. At D == 1 the table is device-symmetric, so every row is
    compile-time concrete and the program is pure straight-line code — no
    slot buffers, no conds, no self-loop ppermute; measured 148k vs 107k
    tok/s for the same 4-microbatch program on one v5e chip (scan
    boundaries force every residual through HBM, the dominant cost of
    microbatched training at small per-microbatch shapes,
    docs/performance.md). At D > 1 (round 4) slot buffers and per-device
    column reads stay dynamic, but the scan boundary still disappears and
    device-uniform ticks lose their conds and dead ring hops — autodiff
    residuals become per-tick SSA values instead of stacked scan outputs.

    Returns ``(spmd_fn, in_specs, D, V)`` where ``spmd_fn(layers_stacked,
    embed, head, tokens, targets[, rng_data])`` -> per-device partial loss
    (the PIPE/SEQ/DATA reductions are left to the caller so its gradient —
    taken inside shard_map — comes out as per-device partials, mirroring
    the tick executor's epilogue). With ``train_dropout`` the function
    takes the step key's raw data and draws the executor's exact mask
    streams (fold_in(step key, microbatch) then global-layer offsets), so
    a phase-separated stored-backward step equals the slot-buffer
    executor's bit-for-tolerance."""
    D = mesh.shape[PIPE_AXIS]
    n_data = mesh.shape.get(DATA_AXIS, 1)
    T = mesh.shape.get(MODEL_AXIS, 1)
    n_seq = mesh.shape.get(SEQ_AXIS, 1)
    n_ep = mesh.shape.get(EXPERT_AXIS, 1)
    ep_axis = EXPERT_AXIS if n_ep > 1 else None
    if n_ep > 1 and moe is None:
        raise ValueError("mesh has an 'expert' axis but no MoEConfig given")
    if moe is not None:
        # MoE eval convention (VERDICT r2 item 4): the reported eval loss
        # is the CE term ONLY. The routing load-balance aux is a training
        # regularizer, not a model-quality quantity — perplexity comes
        # from CE — so the forward program drops each stage's aux scalar
        # (docs/parallelism.md "MoE evaluation").
        _check_moe_mesh(cfg, moe, T, n_seq, n_ep)
        if train_dropout:
            raise NotImplementedError(
                "the phase-stored/forward program does not plumb dropout "
                "rng into MoE stage bodies (the tick executor does, via "
                "moe_layer_apply's per-layer rng); use the tick executor "
                "for MoE training with dropout")
    if fsdp and n_data <= 1:
        raise ValueError("fsdp eval needs a 'data' mesh axis (matching "
                         "the training-side pp x fsdp support)")
    fsdp_dims = _resolve_fsdp_dims(cfg, moe, n_data, T, n_ep, fsdp)
    V = sched.n_virtual
    M = sched.n_microbatches
    tp_axis = MODEL_AXIS if T > 1 else None
    sp_axis = SEQ_AXIS if n_seq > 1 else None
    if sp_attn_impl not in ("ring", "ulysses"):
        raise ValueError(f"sp_attn_impl must be 'ring' or 'ulysses', "
                         f"got {sp_attn_impl!r}")
    if tp_vocab_parallel:
        if T <= 1:
            raise ValueError("tp_vocab_parallel needs a 'model' mesh axis")
        if cfg.vocab_size % T:
            raise ValueError(f"vocab_size={cfg.vocab_size} must divide over "
                             f"the model-axis size {T}")
    _check_tp_divisibility(cfg, T)
    S = D * V
    if cfg.n_layers % S:
        raise ValueError(f"n_layers={cfg.n_layers} must divide over {S} stages")
    lps = cfg.n_layers // S
    uniform_units = sp_axis is not None and sp_attn_impl == "ring"
    table_np, n_slots = _fwd_tick_table(D, V, M)
    if unroll is None:
        # auto: D == 1 always unrolls (measured fastest); D > 1 up to the
        # forward executor's OWN row budget — round 5 raised the training
        # executor's _UNROLL_TICKS_LIMIT to 64 from measurements of the
        # train-step economics (results/unroll_crossover.json); forward
        # ticks are ~1/3 of a train tick's compute, so the unroll win per
        # compile-second is unmeasured here and the round-4 budget stays.
        # Beyond the budget the phase-compressed form replaces the plain
        # whole-table scan (same default flip as the training executor).
        unroll = (True if (D == 1
                           or table_np.shape[0] <= _UNROLL_FWD_TICKS_LIMIT)
                  else "phases")
    if unroll not in (True, False, "phases"):
        raise ValueError(f"unroll must be True, False, 'phases', or None "
                         f"(auto), got {unroll!r}")
    if unroll == "phases":
        from .schedules import compress_schedule
        fwd_phases = compress_schedule(table_np)
    else:
        fwd_phases = None
    table = jnp.asarray(table_np)
    dtype = jnp.dtype(cfg.dtype)
    fwd_perm = [(i, (i + 1) % D) for i in range(D)]
    loss_norm = n_seq * n_ep  # each shard contributes its local-mean share

    def spmd_fn(layers_stacked, embed, head, tokens, targets,
                rng_data=None):
        d = jax.lax.axis_index(PIPE_AXIS)
        layers_local = compute_cast(
            cfg, jax.tree.map(lambda x: x[0], layers_stacked))
        embed_c = compute_cast(cfg, embed)
        head_c = compute_cast(cfg, head)
        b_local, seq = tokens.shape
        assert b_local % M == 0, (
            f"local batch {b_local} not divisible by n_microbatches={M}")
        mb = b_local // M
        tokens_mb = tokens.reshape(M, mb, seq)
        targets_mb = targets.reshape(M, mb, seq)
        mb_shape = (mb, seq, cfg.dim)

        if train_dropout:
            base_rng = jax.random.wrap_key_data(rng_data)
            if n_data > 1:
                base_rng = jax.random.fold_in(
                    base_rng, jax.lax.axis_index(DATA_AXIS))
        else:
            base_rng = None

        def mb_rng(mm):
            return (None if base_rng is None
                    else jax.random.fold_in(base_rng, mm))

        def stage_body(vv, x, mm=0):
            layer_p = jax.tree.map(
                lambda t: jax.lax.dynamic_index_in_dim(t, vv, 0,
                                                       keepdims=False),
                layers_local)
            if fsdp:
                # JIT all-gather of just this chunk's weights (the same
                # per-tick residency bound as the training executor)
                layer_p = jax.tree.map(
                    lambda x_, dm: jax.lax.all_gather(
                        x_, DATA_AXIS, axis=dm, tiled=True) if dm >= 0
                    else x_,
                    layer_p, fsdp_dims)
            if moe is not None:
                from ..models.moe import moe_layer_apply

                def mstep(h, lp):
                    # aux dropped: eval reports CE only (module docstring)
                    h, _aux = moe_layer_apply(cfg, moe, lp, h, ep_axis,
                                              tp_axis=tp_axis, tp_size=T,
                                              sp_axis=sp_axis,
                                              sp_attn_impl=sp_attn_impl,
                                              sp_size=n_seq)
                    return h, None

                y, _ = jax.lax.scan(mstep, x, layer_p)
                return y
            offset = (vv * D + d) * lps  # wrap placement's global layer
            if sp_axis is None:
                return body_apply(cfg, layer_p, x, tp_axis=tp_axis,
                                  tp_size=T, rng=mb_rng(mm),
                                  layer_offset=offset)
            from .seq_parallel import sp_body_apply
            return sp_body_apply(cfg, layer_p, x, sp_axis,
                                 attn_impl=sp_attn_impl,
                                 tp_axis=tp_axis, tp_size=T,
                                 rng=mb_rng(mm), layer_offset=offset,
                                 sp_size=n_seq)

        def stage_embed(toks, mm=0):
            rng_mb = mb_rng(mm)
            rng_e = (None if rng_mb is None
                     else jax.random.fold_in(rng_mb, cfg.n_layers))
            if sp_axis is None:
                return embed_apply(cfg, embed_c, toks, rng=rng_e)
            from .seq_parallel import sp_embed_apply
            return sp_embed_apply(cfg, embed_c, toks, sp_axis, rng=rng_e,
                                  sp_size=n_seq)

        if cfg.pad_token_id is not None:
            shard_axes = tuple(
                ax for ax, n in ((SEQ_AXIS, n_seq), (EXPERT_AXIS, n_ep))
                if n > 1)
            pad_scale = global_pad_scale(
                targets, cfg.pad_token_id, M,
                data_axis=DATA_AXIS if n_data > 1 else None,
                shard_axes=shard_axes or None)

        def mb_loss(y, mm):
            return _stage_ce(
                cfg, head_c, embed_c, y, targets_mb[mm], tp_axis=tp_axis,
                T=T, tp_vocab_parallel=tp_vocab_parallel,
                pad_scale=pad_scale if cfg.pad_token_id is not None
                else None,
                loss_norm=loss_norm)

        if unroll is True and D == 1:
            # D == 1: every table row is concrete, so the tick loop lowers
            # to straight-line code — slots become Python variables, conds
            # become Python ifs, the self-loop ppermute disappears
            saved: dict = {}
            recv = None
            loss = jnp.zeros((), jnp.float32)
            for t in range(table_np.shape[0]):
                s0, fv_, fm_, src = (int(v) for v in table_np[t, 0])
                if s0 >= 0:
                    assert recv is not None, "forward table banks a value " \
                        "no prior tick sent"
                    saved[s0] = recv
                if fm_ < 0:
                    recv = None
                    continue
                if fv_ == 0:
                    x = stage_embed(tokens_mb[fm_], fm_).astype(dtype)
                else:
                    x = saved[src]
                y = stage_body(fv_, x, fm_)
                if fv_ == V - 1:
                    loss = loss + mb_loss(y, fm_)
                recv = y
            return loss / M

        masked_store = _masked_store

        def run_unit(pred, unit, noop, operand, know=None):
            if know is True:
                return unit(operand)
            if know is False:
                return noop(operand)
            if not uniform_units:
                return jax.lax.cond(pred, unit, noop, operand)
            return jax.tree.map(lambda n, o: jnp.where(pred, n, o),
                                unit(operand), noop(operand))

        def tick(carry, row_all, concrete=None, next_concrete=None):
            act_buf, recv, loss_acc = carry
            row = row_all[d]
            if concrete is None or (concrete[:, 0] >= 0).any():
                act_buf = masked_store(act_buf, recv, row[0])
            fv, fm, src = row[1], row[2], row[3]

            def fwd_unit(act_buf):
                vv, mm = jnp.maximum(fv, 0), jnp.maximum(fm, 0)
                first_stage = (d == 0) & (vv == 0)
                x_emb = stage_embed(tokens_mb[mm], mm).astype(dtype)
                x = jnp.where(first_stage, x_emb,
                              act_buf[jnp.maximum(src, 0)])
                y = stage_body(vv, x, mm)
                last_stage = (d == D - 1) & (vv == V - 1)
                l = jax.lax.cond(last_stage, lambda: mb_loss(y, mm),
                                 lambda: jnp.zeros((), jnp.float32))
                return y, l

            def fwd_noop(act_buf):
                return (jnp.zeros(mb_shape, dtype),
                        jnp.zeros((), jnp.float32))

            y, l = run_unit(fm >= 0, fwd_unit, fwd_noop, act_buf,
                            know=_concrete_know(
                                None if concrete is None else concrete[:, 2]))
            if next_concrete is not None and (next_concrete[:, 0] < 0).all():
                nxt_recv = jnp.zeros(mb_shape, dtype)  # hop elided: dead
            else:
                nxt_recv = jax.lax.ppermute(y, PIPE_AXIS, fwd_perm)
            return (act_buf, nxt_recv, loss_acc + l), None

        carry0 = (jnp.zeros((n_slots,) + mb_shape, dtype),
                  jnp.zeros(mb_shape, dtype),
                  jnp.zeros((), jnp.float32))
        if unroll == "phases":
            # phase-compressed ticks (same core as the training executor)
            carry = _phase_compressed_ticks(tick, carry0, table, fwd_phases)
        elif unroll:
            # D > 1 unrolled: the tick loop is a Python loop over concrete
            # rows — slot buffers and per-device column reads stay dynamic,
            # but the scan boundary disappears and device-uniform ticks
            # lose their conds/hops (mirrors the training executor's
            # unroll_ticks; VERDICT r3 item 2)
            carry = carry0
            n_rows = table_np.shape[0]
            end_row = np.full_like(table_np[0], -1)
            for t in range(n_rows):
                nxt = table_np[t + 1] if t + 1 < n_rows else end_row
                carry, _ = tick(carry, table[t], concrete=table_np[t],
                                next_concrete=nxt)
        else:
            carry, _ = jax.lax.scan(tick, carry0, table)
        (_, _, loss) = carry
        return loss / M  # per-device partial (non-last stages: 0)

    if moe is not None:
        layer_spec = _moe_layer_specs(cfg, moe, T, n_ep)
        if fsdp_dims is not None:
            layer_spec = _merge_fsdp_into_stacked(layer_spec, fsdp_dims)
    elif T > 1 or fsdp:
        layer_spec = _dense_layer_specs(cfg, T, fsdp_dims)
    else:
        layer_spec = P(PIPE_AXIS)
    if tp_vocab_parallel and not cfg.tie_embeddings:
        out_spec = ({"w": P(None, MODEL_AXIS), "b": P(MODEL_AXIS)}
                    if cfg.arch == "ref_decoder"
                    else {"w": P(None, MODEL_AXIS)})
        head_spec = {"norm": P(), "out": out_spec}
    else:
        head_spec = P()
    if n_seq > 1:
        # with an expert axis too (MoE x seq, round 5) the batch shards
        # over data x expert while the sequence shards over seq
        lead = (DATA_AXIS, EXPERT_AXIS) if n_ep > 1 else DATA_AXIS
        batch_spec = P(lead, SEQ_AXIS)
    elif n_ep > 1:
        batch_spec = P((DATA_AXIS, EXPERT_AXIS))  # batch over data x expert
    else:
        batch_spec = P(DATA_AXIS)
    in_specs = (layer_spec, P(), head_spec, batch_spec, batch_spec)
    return spmd_fn, in_specs, D, V


def make_pipeline_loss_fn(cfg: ModelConfig, mesh: Mesh, sched: ScheduleConfig,
                          sp_attn_impl: str = "ring",
                          tp_vocab_parallel: bool = False,
                          fsdp: bool = False, moe=None,
                          unroll_ticks=False,
                          ) -> Callable[[Pytree, jax.Array, jax.Array],
                                        jax.Array]:
    """Jitted forward-only eval loss: ``(params, tokens, targets) -> loss``.

    The evaluation twin of :func:`make_pipeline_grad_fn` — the forward
    tick program of :func:`_build_forward_program` (eval mode: no dropout)
    with the cross-device loss reductions applied. The mean over
    microbatches equals the single-device full-batch ``transformer_loss``
    exactly (asserted in tests/test_eval.py), at forward-only cost — no
    backward, no rematerialization.

    Covers the full training-mesh space (VERDICT r1 item 7 / r2 item 4):
    data x pipe x model x seq meshes, V >= 1, Megatron TP inside stages,
    ring/Ulysses sequence parallelism, the vocab-parallel CE
    (``tp_vocab_parallel`` — incl. tied embeddings), pp x fsdp resting
    layouts (``fsdp=True``: params arrive pipe x data sharded and each
    chunk is gathered just in time, preserving the ZeRO-3 residency bound
    during eval), and MoE stages (``moe=`` a MoEConfig, experts sharded
    over an 'expert' axis when present). **MoE aux convention**: the eval
    loss is the CE term only — the routing load-balance aux is a training
    regularizer, so the forward program drops it and the comparison
    target is the training loss minus its aux term (asserted in
    tests/test_eval.py::test_moe_pipeline_eval_loss).

    ``unroll_ticks`` picks the forward tick-loop form — ``True``
    (straight-line), ``"phases"`` (per-pattern specialized scan bodies),
    ``False`` (cond-dispatched scan, the default: eval compiles once and
    runs rarely, so bounded compile wins), or ``None`` (the training-side
    auto rule with the forward budget ``_UNROLL_FWD_TICKS_LIMIT``).
    """
    spmd_fn, in_specs, D, V = _build_forward_program(
        cfg, mesh, sched, sp_attn_impl, tp_vocab_parallel, fsdp, moe=moe,
        unroll=unroll_ticks)
    n_data = mesh.shape.get(DATA_AXIS, 1)
    n_seq = mesh.shape.get(SEQ_AXIS, 1)
    n_ep = mesh.shape.get(EXPERT_AXIS, 1)

    def reduced(layers_stacked, embed, head, tokens, targets):
        loss = jax.lax.psum(
            spmd_fn(layers_stacked, embed, head, tokens, targets),
            PIPE_AXIS)  # lives on the last stage
        if n_seq > 1:
            loss = jax.lax.psum(loss, SEQ_AXIS)
        if n_ep > 1:
            # 'expert' doubles as a batch axis; the objective already
            # divided by n_ep, so the psum completes the global mean
            loss = jax.lax.psum(loss, EXPERT_AXIS)
        if n_data > 1:
            loss = jax.lax.psum(loss / n_data, DATA_AXIS)
        return loss

    sharded = _shard_map(reduced, mesh, in_specs=in_specs, out_specs=P())

    @jax.jit
    def loss_fn(params, tokens, targets):
        stacked = stack_stage_layers(params["layers"], D, V)
        return sharded(stacked, params["embed"], params["head"],
                       tokens, targets)

    return loss_fn


def _make_phase_stored_grad_fn(cfg: ModelConfig, mesh: Mesh,
                               sched: ScheduleConfig, sp_attn_impl: str,
                               tp_vocab_parallel: bool):
    """Stored-activation backward for phase-separated schedules (GPipe,
    BFS — and ANY non-split schedule at D == 1): differentiate THROUGH
    the forward tick program.

    These schedules run, per device, every forward before any backward —
    so the backward tick order is exactly the time-reversal of the forward
    program, which is precisely what ``jax.value_and_grad`` produces: XLA
    banks each tick's residuals (ordinary fused SSA values in the unrolled
    program — D == 1's straight-line form or round 4's D > 1 Python tick
    loop — static scan outputs only beyond the unroll budget), the
    generated backward replays them in reverse, and the transposed
    ``ppermute`` IS the gradient ring (+1 forward ring transposes to the
    -1 grad ring). This matches the reference's torch-autograd semantics
    exactly (GPipe's backward stashes per-microbatch saved tensors and
    never recomputes — upstream ``schedules.py:872-992`` over
    ``stage.py:857/937``). Activation residency is O(M) microbatches —
    GPipe's own requirement; schedules whose point is O(D) residency
    (1F1B/Interleaved) interleave B among F and cannot use this path at
    D > 1 (their stored backward is the slot-banked tick executor, which
    round 4 also unrolls — ``unroll_ticks``). Single-chip measurements
    (v5e, docs/performance.md): the unrolled D == 1 form is the FASTEST
    executor formulation (~1.25x over the remat tick scan); the scanned
    D > 1 form measures SLOWER than remat (scan-boundary residual
    traffic), hence stored remains opt-in via ``remat_backward=False`` —
    now served by the unrolled form wherever the tick budget allows.
    """
    use_dropout = cfg.dropout > 0.0
    spmd_fn, in_specs, D, V = _build_forward_program(
        cfg, mesh, sched, sp_attn_impl, tp_vocab_parallel, False,
        train_dropout=use_dropout, unroll=None)
    n_data = mesh.shape.get(DATA_AXIS, 1)
    n_seq = mesh.shape.get(SEQ_AXIS, 1)

    def grad_prog(layers_stacked, embed, head, tokens, targets,
                  rng_data=None):
        def obj(ls, e, h):
            if use_dropout:
                return spmd_fn(ls, e, h, tokens, targets, rng_data)
            return spmd_fn(ls, e, h, tokens, targets)

        loss, (g_l, g_e, g_h) = jax.value_and_grad(
            obj, argnums=(0, 1, 2))(layers_stacked, embed, head)
        # same reduction epilogue as the tick executor: loss lives on the
        # last stage; replicated embed/head grads are per-device partials
        loss = jax.lax.psum(loss, PIPE_AXIS)
        g_e = jax.tree.map(lambda x: jax.lax.psum(x, PIPE_AXIS), g_e)
        g_h = jax.tree.map(lambda x: jax.lax.psum(x, PIPE_AXIS), g_h)
        if n_seq > 1:
            loss = jax.lax.psum(loss, SEQ_AXIS)
            g_l, g_e, g_h = jax.tree.map(
                lambda x: jax.lax.psum(x, SEQ_AXIS), (g_l, g_e, g_h))
        if n_data > 1:
            nd = 1.0 / n_data
            loss = jax.lax.psum(loss * nd, DATA_AXIS)
            g_l, g_e, g_h = jax.tree.map(
                lambda x: jax.lax.psum(x * nd, DATA_AXIS),
                (g_l, g_e, g_h))
        return loss, g_l, g_e, g_h

    grad_specs = in_specs + ((P(),) if use_dropout else ())
    sharded = _shard_map(
        grad_prog, mesh, in_specs=grad_specs,
        out_specs=(P(), in_specs[0], P(), in_specs[2]))

    def unpack(loss, g_l, g_e, g_h):
        return loss, {"embed": g_e,
                      "layers": unstack_stage_layers(g_l),
                      "head": g_h}

    if use_dropout:
        def step(params, tokens, targets, rng):
            stacked = stack_stage_layers(params["layers"], D, V)
            return unpack(*sharded(stacked, params["embed"],
                                   params["head"], tokens, targets,
                                   jax.random.key_data(rng)))
        return step

    def step(params, tokens, targets):
        stacked = stack_stage_layers(params["layers"], D, V)
        return unpack(*sharded(stacked, params["embed"], params["head"],
                               tokens, targets))

    return step


def make_pipeline_forward(cfg: ModelConfig, mesh: Mesh, sched: ScheduleConfig,
                          ) -> Callable[[Pytree, jax.Array], jax.Array]:
    """Jitted forward-only pipeline: ``(params, tokens) -> logits [B, S, V]``.

    The parity twin of upstream's ``PipelineScheduleSingle.step`` return
    value — per-microbatch last-stage outputs merged back into the
    full-batch logits (``merge_chunks``, ``schedules.py:794-798``). Runs a
    BFS fill-drain forward over ``sched.n_virtual`` wrap-placed chunks
    (every schedule's forward order is fill-drain; no backward), so it
    doubles as pipelined batch inference.

    Meshes: data x pipe x model (VERDICT r2 item 6) — with a 'model' axis
    the stage bodies run Megatron-TP (weight leaves are local shards, the
    row-parallel projections complete with a psum) while the head stays
    replicated, so every model rank materializes the same full [B, S, V]
    logits and a TP-pipeline-trained checkpoint scores/samples without
    any resharding (tests/test_tp_pipeline.py). Seq/expert axes remain
    scope cuts because the CONTRACT here is materialized full-batch
    logits — under those meshes use :func:`make_pipeline_loss_fn` (which
    never materializes logits) for eval.
    """
    D = mesh.shape[PIPE_AXIS]
    T = mesh.shape.get(MODEL_AXIS, 1)
    tp_axis = MODEL_AXIS if T > 1 else None
    for axis in (SEQ_AXIS, EXPERT_AXIS):
        if mesh.shape.get(axis, 1) > 1:
            raise NotImplementedError(
                f"make_pipeline_forward supports data x pipe x model meshes "
                f"(got a '{axis}' axis); for eval losses on SP/MoE meshes "
                f"use make_pipeline_loss_fn")
    _check_tp_divisibility(cfg, T)
    M = sched.n_microbatches
    V = sched.n_virtual
    if M < 1:
        raise ValueError(f"n_microbatches={M} must be >= 1")
    # No schedule compilation: every schedule's *forward* order is the same
    # fill-drain, so training-only constraints (e.g. 1F1B's M >= D) do not
    # apply to batch inference. ScheduleConfig already validates the name.
    if cfg.n_layers % (D * V):
        raise ValueError(f"n_layers={cfg.n_layers} must divide over "
                         f"{D * V} stages")
    dtype = jnp.dtype(cfg.dtype)
    fwd_perm = [(i, (i + 1) % D) for i in range(D)]
    table_np, n_slots = _fwd_tick_table(D, V, M)
    table = jnp.asarray(table_np)

    def spmd_fn(layers_stacked, embed, head, tokens):
        d = jax.lax.axis_index(PIPE_AXIS)
        layers_local = compute_cast(
            cfg, jax.tree.map(lambda x: x[0], layers_stacked))
        embed = compute_cast(cfg, embed)
        head = compute_cast(cfg, head)
        b_local, seq = tokens.shape
        assert b_local % M == 0, (
            f"local batch {b_local} not divisible by n_microbatches={M}")
        mb = b_local // M
        tokens_mb = tokens.reshape(M, mb, seq)
        mb_shape = (mb, seq, cfg.dim)

        masked_store = _masked_store

        def tick(carry, row_all):
            act_buf, recv, out = carry
            row = row_all[d]
            act_buf = masked_store(act_buf, recv, row[0])
            fv, fm, src = row[1], row[2], row[3]

            def fwd_unit(act_buf):
                vv, mm = jnp.maximum(fv, 0), jnp.maximum(fm, 0)
                first_stage = (d == 0) & (vv == 0)
                x_emb = embed_apply(cfg, embed, tokens_mb[mm]).astype(dtype)
                x = jnp.where(first_stage, x_emb,
                              act_buf[jnp.maximum(src, 0)])
                layer_p = jax.tree.map(
                    lambda t: jax.lax.dynamic_index_in_dim(
                        t, vv, 0, keepdims=False), layers_local)
                y = body_apply(cfg, layer_p, x, tp_axis=tp_axis, tp_size=T)
                last = (d == D - 1) & (vv == V - 1)
                logits_mb = jax.lax.cond(
                    last,
                    lambda: head_apply(cfg, head, y,
                                       embed=embed).astype(jnp.float32),
                    lambda: jnp.zeros((mb, seq, cfg.vocab_size),
                                      jnp.float32))
                return y, logits_mb, last

            def fwd_noop(act_buf):
                return (jnp.zeros(mb_shape, dtype),
                        jnp.zeros((mb, seq, cfg.vocab_size), jnp.float32),
                        jnp.asarray(False))

            y, logits_mb, last = jax.lax.cond(fm >= 0, fwd_unit, fwd_noop,
                                              act_buf)
            mm = jnp.maximum(fm, 0)
            out = out.at[mm].set(jnp.where(last, logits_mb, out[mm]))
            return (act_buf, jax.lax.ppermute(y, PIPE_AXIS, fwd_perm),
                    out), None

        out0 = jnp.zeros((M, mb, seq, cfg.vocab_size), jnp.float32)
        carry0 = (jnp.zeros((n_slots,) + mb_shape, dtype),
                  jnp.zeros(mb_shape, dtype), out0)
        (_, _, out), _ = jax.lax.scan(tick, carry0, table)
        # logits live on the last-stage device; replicate via psum of zeros
        out = jax.lax.psum(jnp.where(d == D - 1, out, 0.0), PIPE_AXIS)
        return out.reshape(b_local, seq, cfg.vocab_size)

    if T > 1:
        # Megatron per-leaf shards for the stacked layers; the head (and
        # tied embedding) stay replicated, so the full logits fall out of
        # every model rank identically — no gather, no resharding
        from .tensor_parallel import pipeline_layer_specs
        layer_spec = pipeline_layer_specs(cfg, PIPE_AXIS)
    else:
        layer_spec = P(PIPE_AXIS)
    sharded = _shard_map(
        spmd_fn, mesh,
        in_specs=(layer_spec, P(), P(), P(DATA_AXIS)),
        out_specs=P(DATA_AXIS),
    )

    @jax.jit
    def forward(params, tokens):
        stacked = stack_stage_layers(params["layers"], D, V)
        return sharded(stacked, params["embed"], params["head"], tokens)

    return forward
