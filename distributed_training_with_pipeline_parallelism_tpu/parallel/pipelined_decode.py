"""Autoregressive decoding over a pipeline mesh (round 4, VERDICT r3 item 8).

The reference has no inference path at all; this closes the last mesh gap
of this framework's own inference story — training meshes slice a model
depth-wise over 'pipe', and now decode runs on that same slicing (until
round 4 only ``make_pipeline_forward``'s batch-scoring path was
pipelined; the token-by-token decode loop was single-device/TP only).

Naively pipelining a one-token decode step runs at 1/D utilization by
construction: each step's compute is a sliver with a strict
stage-(d+1)-after-stage-d dependency. The executor here instead
round-robins ``M >= D`` INDEPENDENT batch streams through the stages —
the decode-time analog of training microbatches:

- tick u, device d works on stream ``(u - d) mod M``: in steady state
  every stage is busy every tick, on a [B/M, 1, dim] sliver of a
  different stream.
- the sampled token needs to travel stage D-1 -> stage 0 for its
  stream's next round; on a ring that hop IS the +1 permute, so one
  ``ppermute`` carries both payloads each tick — hidden states d -> d+1
  and tokens D-1 -> 0. No second collective, no host round-trip.
- stream g re-enters stage 0 at tick ``g + e*M`` (its round-e token
  arrived at ``g + (e-1)*M + D``), which is why ``M >= D`` is required
  for a stall-free schedule.
- each device holds the KV cache for ITS layer slice only
  ``[lps, B, max_len, Hkv, hd]`` — the model is depth-split at decode
  exactly as it is at training, so a model that only fits sharded can
  still generate. Warmup/drain ticks take a ``lax.cond`` noop branch,
  so inactive devices never touch their caches.

Prefill is the same round-robin over whole prompts (a fill-drain pass,
M + D - 1 ticks, Python-unrolled), writing each stage's prompt KV and
sampling every stream's first token on the last stage.

Sampling semantics, cache layout and the per-layer math are shared with
:mod:`..models.generate` (``layers_with_cache`` / ``sample_logits``), so
pipelined greedy decode emits exactly the single-device tokens
(tests/test_pipelined_decode.py).

The whole-prompt prefill pass runs with ``prefill=True`` — offset is
statically zero and every stage's cache is fresh, so the blocks route
attention through the Pallas flash kernel under the training path's
``cfg.flash_for`` fallback discipline (``ops.pallas_attention``); decode
ticks (s=1, traced offsets) and the serving engine's chunked prefill
stay on the cached dense path. ``return_logprobs`` likewise reuses the
training loss's kernel dispatch (``cfg.use_fused_xent`` ->
``ops.pallas_xent``) for the emitted tokens' log-probabilities.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..models.generate import (_embed_at, init_cache, layers_with_cache,
                               rope_slice_at, sample_logits)
from ..models.transformer import compute_cast, head_apply
from ..utils.config import ModelConfig
from .mesh import MODEL_AXIS, PIPE_AXIS
from .pipeline import (_check_tp_divisibility, _dense_layer_specs,
                       _shard_map, stack_stage_layers)


def _slot_cache_apply(cfg: ModelConfig, layers_d, h, kc, vc, g, n_rows: int,
                      offset, s: int, *, tp_axis: Optional[str] = None,
                      tp_size: int = 1, live_rows=None,
                      prefill: bool = False):
    """One stage's layer slice on ``h`` [n_rows, s, dim] for slot/stream
    ``g``: slice that slot's cache rows (``g*n_rows .. (g+1)*n_rows``),
    run the blocks, write the new k/v back.

    ``live_rows`` (optional [n_rows] bool) masks the cache write-back per
    batch row — frozen rows (EOS-finished streams, retired serving slots)
    keep their previous k/v bit-for-bit, so completed requests stop
    mutating state without changing any shape. Shared by the static
    round-robin decoder below and the continuous-batching serving
    executor (:mod:`..serving.engine`).

    ``prefill=True`` marks statically-zero-offset fresh-cache calls
    (the round-robin decoder's whole-prompt prefill) flash-eligible —
    the blocks then route attention through the Pallas kernel under the
    training path's ``cfg.flash_for`` fallback discipline. The serving
    engine's chunked prefill consumes TRACED offsets and must keep the
    default dense path (see :func:`..models.generate._layer_step`)."""
    kg = jax.lax.dynamic_slice_in_dim(kc, g * n_rows, n_rows, axis=1)
    vg = jax.lax.dynamic_slice_in_dim(vc, g * n_rows, n_rows, axis=1)
    rope = rope_slice_at(cfg, kc.shape[2], offset, s)
    h, (kg2, vg2) = layers_with_cache(cfg, layers_d, h, kg, vg, offset, rope,
                                      tp_axis=tp_axis, tp_size=tp_size,
                                      prefill=prefill)
    if live_rows is not None:
        m = live_rows[None, :, None, None, None]
        kg2 = jnp.where(m, kg2, kg)
        vg2 = jnp.where(m, vg2, vg)
    kc = jax.lax.dynamic_update_slice_in_dim(kc, kg2, g * n_rows, axis=1)
    vc = jax.lax.dynamic_update_slice_in_dim(vc, vg2, g * n_rows, axis=1)
    return h, kc, vc


def _head_token(cfg: ModelConfig, head_c, embed_c, y_last, key, *,
                temperature: float = 0.0, top_k: Optional[int] = None,
                top_p: Optional[float] = None, tp_axis: Optional[str] = None,
                tp_size: int = 1, vocab_parallel: bool = False,
                return_logprobs: bool = False):
    """Next-token ids [B] from the last-position hidden ``y_last``
    [B, 1, dim] — the last-stage head of both decode executors (the
    caller conds on its stage index so other stages skip the vocab
    matmul entirely).

    Greedy under TP goes vocab-parallel when ``vocab_parallel``: each
    model rank reads only its V/T column slice of the head weight (the
    O(dim*V) head read is often the largest weight in a decode tick —
    replicating it would cap the TP speedup well below T) and the argmax
    merges via a [T, B] all_gather of per-shard (max, argmax) pairs.
    First-max-wins on both levels reproduces the global argmax tie-break
    (lowest index) exactly. Sampling keeps the replicated head: top-k /
    top-p need globally truncated logits.

    ``return_logprobs`` (replicated head only — the caller disables the
    vocab-parallel fast path) additionally returns the sampled token's
    log-probability [B] f32 via :func:`..models.generate.token_logprob`
    (``cfg.use_fused_xent`` -> the Pallas fused-NLL kernel)."""
    if not vocab_parallel:
        logits = head_apply(cfg, head_c, y_last, embed=embed_c)[:, 0]
        tok = sample_logits(key, logits, temperature, top_k,
                            top_p).astype(jnp.int32)
        if return_logprobs:
            from ..models.generate import token_logprob
            return tok, token_logprob(cfg, logits, tok)
        return tok
    if return_logprobs:
        raise ValueError("return_logprobs needs the replicated head "
                         "(full logits); vocab_parallel must be off")
    from ..models.transformer import head_norm_apply
    t = jax.lax.axis_index(tp_axis)
    Vl = cfg.vocab_size // tp_size
    hn = head_norm_apply(cfg, head_c, y_last)[:, 0]  # [B, dim]
    if cfg.tie_embeddings:
        wsl = jax.lax.dynamic_slice_in_dim(
            embed_c["tok"], t * Vl, Vl, axis=0)  # [Vl, dim]
        logits_l = hn @ wsl.T
    else:
        wsl = jax.lax.dynamic_slice_in_dim(
            head_c["out"]["w"], t * Vl, Vl, axis=1)
        logits_l = hn @ wsl  # gpt2/llama heads carry no bias
    val = jnp.max(logits_l, axis=-1)
    idx = jnp.argmax(logits_l, axis=-1) + t * Vl
    vals = jax.lax.all_gather(val, tp_axis)  # [T, B]
    idxs = jax.lax.all_gather(idx, tp_axis)
    win = jnp.argmax(vals, axis=0)
    return jnp.take_along_axis(idxs, win[None], axis=0)[0].astype(jnp.int32)


def spec_accept_len(drafts, targets):
    """Longest-matching-prefix acceptance for greedy speculative decoding
    (serving.engine's verify step; Leviathan et al., arXiv:2211.17192).

    ``drafts`` [gamma]: the draft model's proposed tokens. ``targets``
    [>= gamma]: the target model's per-row argmaxes over the verify
    chunk, where row ``i`` conditions on the context *through draft
    ``i``* — so ``targets[i]`` is what greedy decoding would emit after
    accepting ``drafts[:i+1]``... but also, crucially, ``targets[i-1]``
    is what it emits after ``drafts[:i]``, which is why draft ``i`` is
    acceptable iff ``drafts[i] == targets[i-1]`` with ``targets[-1]``
    read as the free token row 0 yields. Returns ``n_accepted = 1 +
    run-length of the matching prefix`` in ``[1, gamma+1]`` — bit-exact
    greedy by construction: the first mismatch row's own argmax is the
    token greedy would have emitted, and it rides the tok channel as
    ``targets[n_accepted - 1]``. Traceable (jnp) and numpy-compatible,
    so the unit tests run it directly on host arrays."""
    drafts = jnp.asarray(drafts)
    g = drafts.shape[0]
    hit = jnp.cumprod(
        (drafts == jnp.asarray(targets)[:g]).astype(jnp.int32))
    return 1 + hit.sum()


def make_pipeline_generate_fn(cfg: ModelConfig, mesh: Mesh,
                              max_new_tokens: int, *,
                              n_streams: Optional[int] = None,
                              temperature: float = 0.0,
                              top_k: Optional[int] = None,
                              top_p: Optional[float] = None,
                              max_len: Optional[int] = None,
                              eos_id: Optional[int] = None,
                              return_lengths: bool = False,
                              return_logprobs: bool = False):
    """Build a jitted ``(params, prompt[, key]) -> tokens [B, P+N]``
    decoder over ``mesh``'s 'pipe' axis.

    ``return_logprobs=True`` appends the emitted tokens' log-probs
    [B, N] f32 to the result — computed on the last stage from the same
    logits each token was sampled from (``cfg.use_fused_xent`` routes
    the Pallas fused-NLL kernel, the training loss's dispatch), ridden
    home on the same ring hop as the token, banked next to it on stage
    0. EOS-frozen rows report 0.0 for forced tokens. Disables the
    vocab-parallel greedy head (logprobs need full logits). Matches the
    single-device ``generate(..., return_logprobs=True)`` row for row.

    ``eos_id`` makes decoding EOS-aware: once a request emits ``eos_id``
    its stream freezes — subsequent banked tokens are forced to
    ``eos_id`` and every stage masks that request's KV-cache writes (a
    live-row mask rides the same ring hop as the data, so jit shapes
    never change), and a stream whose requests have ALL finished skips
    its stage compute entirely instead of burning ticks to
    ``max_new_tokens``. With ``return_lengths=True`` (requires
    ``eos_id``) the decoder returns ``(tokens [B, P+N], lengths [B])``
    where ``lengths`` counts emitted tokens per request including the
    EOS itself.

    ``params`` is the full-model pytree (stage slicing happens inside,
    via the training executor's ``stack_stage_layers``); ``prompt`` is
    [B, P] with uniform length P and ``B`` divisible by ``n_streams``
    (default: the pipe degree D). Greedy when ``temperature == 0``;
    sampling knobs match :func:`..models.generate.sample_logits`.

    A 'model' mesh axis (round 5) composes Megatron TP inside each
    stage: layer weights are model-axis shards (the training executor's
    stacked specs), each model rank caches only its kv-head shard, and
    the o/down projections psum per layer — decode is weight-read bound
    at small batch, so TP splits exactly the bandwidth that limits it.
    The KV cache stays stage-sliced over 'pipe' as before. Seq/expert
    axes remain unsupported here.
    """
    if cfg.arch not in ("gpt2", "llama"):
        raise ValueError(
            f"generation is undefined for arch {cfg.arch!r} (see "
            "models.generate)")
    D = mesh.shape[PIPE_AXIS]
    T = mesh.shape.get(MODEL_AXIS, 1)
    for ax, n in mesh.shape.items():
        if ax not in (PIPE_AXIS, MODEL_AXIS) and n > 1:
            raise NotImplementedError(
                f"pipelined decode composes pipe x model meshes; axis "
                f"{ax!r} has size {n} (batch scoring via "
                "make_pipeline_forward supports the full mesh space)")
    _check_tp_divisibility(cfg, T)
    tp_axis = MODEL_AXIS if T > 1 else None
    if cfg.n_layers % D:
        raise ValueError(f"n_layers={cfg.n_layers} must divide over {D} "
                         "stages")
    M = n_streams or D
    if M < D:
        raise ValueError(f"n_streams={M} must be >= the pipe degree {D} "
                         "(fewer streams than stages stalls the ring)")
    N = max_new_tokens
    if N < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {N}")
    if return_lengths and eos_id is None:
        raise ValueError("return_lengths=True requires an eos_id (without "
                         "one every stream emits exactly max_new_tokens)")
    if temperature != 0.0:
        need_key = True
    else:
        need_key = False
    want_lp = return_logprobs

    def spmd(layers_stacked, embed, head, prompt, key_data):
        d = jax.lax.axis_index(PIPE_AXIS)
        layers_d = jax.tree.map(lambda x: x[0, 0], layers_stacked)  # [lps,..]
        layers_d = compute_cast(cfg, layers_d)
        embed_c = compute_cast(cfg, embed)
        head_c = compute_cast(cfg, head)
        B, Pp = prompt.shape
        Bg = B // M
        total = Pp + N
        mlen = max_len or total
        lps = cfg.n_layers // D
        # under TP each model rank caches only ITS kv-head shard
        n_kv = (cfg.n_kv_heads or cfg.n_heads) // T
        kc = jnp.zeros((lps, B, mlen, n_kv, cfg.head_dim),
                       jnp.dtype(cfg.dtype))
        vc = kc
        prompt_g = prompt.reshape(M, Bg, Pp)
        base_key = jax.random.wrap_key_data(key_data)

        perm = [(i, (i + 1) % D) for i in range(D)]

        def ring(tree):
            return jax.tree.map(
                lambda x: jax.lax.ppermute(x, PIPE_AXIS, perm), tree)

        def stage_apply(h, kc, vc, g, offset, s, live_rows=None,
                        prefill=False):
            """This device's layer slice on [Bg, s, dim] for stream g
            (shared :func:`_slot_cache_apply`; ``live_rows`` masks cache
            writes of EOS-frozen requests; ``prefill`` flags the
            statically-zero-offset whole-prompt pass flash-eligible)."""
            return _slot_cache_apply(cfg, layers_d, h, kc, vc, g, Bg,
                                     offset, s, tp_axis=tp_axis, tp_size=T,
                                     live_rows=live_rows, prefill=prefill)

        # ------------------------------------------------------------------
        # prefill: fill-drain over whole prompts, M + D ticks (the +1 tick
        # delivers the last stream's first token back to stage 0)
        # ------------------------------------------------------------------
        h_chan = jnp.zeros((Bg, Pp, cfg.dim), jnp.dtype(cfg.dtype))
        tok_chan = jnp.zeros((Bg,), jnp.int32)
        token_buf = jnp.zeros((M, Bg), jnp.int32)
        out_buf = jnp.zeros((N, M, Bg), jnp.int32)
        # token logprobs ride/bank exactly like the tokens themselves
        lp_chan = jnp.zeros((Bg,), jnp.float32) if want_lp else None
        lp_buf = jnp.zeros((N, M, Bg), jnp.float32) if want_lp else None
        # EOS bookkeeping lives on stage 0 only (it banks every token);
        # stages d > 0 learn liveness from the mask riding the ring. All
        # of it is gated at Python level so the eos_id=None jaxpr is
        # unchanged.
        use_eos = eos_id is not None
        done = jnp.zeros((M, Bg), bool) if use_eos else None

        vocab_parallel_head = (tp_axis is not None and not need_key
                               and cfg.vocab_size % T == 0 and not want_lp)

        def head_sample(y_last, g, e):
            """Last stage only: logits + sample via the shared
            :func:`_head_token` (vocab-parallel greedy under TP); other
            stages skip the vocab matmul entirely. With ``want_lp`` the
            pair (tok, logprob) comes back instead of the bare token."""
            def live():
                key = (jax.random.fold_in(jax.random.fold_in(base_key, e), g)
                       if need_key else None)
                return _head_token(cfg, head_c, embed_c, y_last, key,
                                   temperature=temperature, top_k=top_k,
                                   top_p=top_p, tp_axis=tp_axis, tp_size=T,
                                   vocab_parallel=vocab_parallel_head,
                                   return_logprobs=want_lp)

            if want_lp:
                return jax.lax.cond(
                    d == D - 1, live,
                    lambda: (jnp.zeros((Bg,), jnp.int32),
                             jnp.zeros((Bg,), jnp.float32)))
            return jax.lax.cond(d == D - 1, live,
                                lambda: jnp.zeros((Bg,), jnp.int32))

        for t in range(M + D):
            # bank last tick's token arrival (stage 0 only)
            wp = t - D  # prefill stream whose first token arrives now
            if 0 <= wp < M:
                is_d0 = d == 0
                token_buf = jnp.where(is_d0,
                                      token_buf.at[wp].set(tok_chan),
                                      token_buf)
                out_buf = jnp.where(is_d0, out_buf.at[0, wp].set(tok_chan),
                                    out_buf)
                if want_lp:  # the first token is always genuinely sampled
                    lp_buf = jnp.where(is_d0, lp_buf.at[0, wp].set(lp_chan),
                                       lp_buf)
                if use_eos:  # a prompt may yield EOS as its FIRST token
                    done = jnp.where(is_d0,
                                     done.at[wp].set(tok_chan == eos_id),
                                     done)
            w = t - d  # this device's active stream this tick
            active = (w >= 0) & (w < M)
            g = jnp.clip(w, 0, M - 1)

            def unit(op):
                kc, vc = op
                x = jnp.where(d == 0,
                              _embed_at(cfg, embed_c, prompt_g[g],
                                        jnp.int32(0)).astype(h_chan.dtype),
                              h_chan)
                y, kc, vc = stage_apply(x, kc, vc, g, jnp.int32(0), Pp,
                                        prefill=True)
                if want_lp:
                    tok, lp = head_sample(y[:, -1:], g, 0)
                    return (kc, vc), y, tok, lp
                tok = head_sample(y[:, -1:], g, 0)
                return (kc, vc), y, tok

            def noop(op):
                z = (op, jnp.zeros_like(h_chan), jnp.zeros((Bg,), jnp.int32))
                return z + (jnp.zeros((Bg,), jnp.float32),) if want_lp else z

            # one ring carries everything: h for d < D-1, token (and its
            # logprob) for d == D-1
            if want_lp:
                (kc, vc), y, tok, lp = jax.lax.cond(active, unit, noop,
                                                    (kc, vc))
                h_chan, tok_chan, lp_chan = ring((y, tok, lp))
            else:
                (kc, vc), y, tok = jax.lax.cond(active, unit, noop, (kc, vc))
                h_chan, tok_chan = ring((y, tok))

        # ------------------------------------------------------------------
        # decode: lax.scan over M*(N-1) + D round-robin ticks (the last
        # tick does no compute — it exists only to bank the final
        # stage-(D-1) -> 0 token arrival)
        # ------------------------------------------------------------------
        h1 = jnp.zeros((Bg, 1, cfg.dim), jnp.dtype(cfg.dtype))

        def tick(carry, u):
            # carry layout: 6 fixed slots, then (done, lives_chan) when
            # EOS-aware, then (lp_buf, lp_chan) when logprobs ride along
            h_chan, tok_chan, kc, vc, token_buf, out_buf = carry[:6]
            i = 6
            if use_eos:
                done, lives_chan = carry[i:i + 2]
                i += 2
            else:
                done = lives_chan = None
            if want_lp:
                lp_buf, lp_chan = carry[i:i + 2]
            else:
                lp_buf = lp_chan = None
            # bank the arrival from tick u-1 (which left the last stage at
            # entry index (u - D) // M, producing output token index +1)
            wa = u - D
            ga = jnp.clip(wa % M, 0, M - 1)
            ia = jnp.clip(wa // M + 1, 0, N - 1)
            bank = (wa >= 0) & (d == 0)
            # finished rows emit forced EOS from then on; the garbage the
            # skipped/frozen compute produced never reaches the output
            tok_eff = (jnp.where(done[ga], jnp.int32(eos_id), tok_chan)
                       if use_eos else tok_chan)
            token_buf = jnp.where(bank, token_buf.at[ga].set(tok_eff),
                                  token_buf)
            out_buf = jnp.where(bank, out_buf.at[ia, ga].set(tok_eff),
                                out_buf)
            if want_lp:
                # forced-EOS rows bank 0.0 (not sampled), same rule as the
                # single-device generate; `done` is still pre-update here
                lp_eff = (jnp.where(done[ga], 0.0, lp_chan) if use_eos
                          else lp_chan)
                lp_buf = jnp.where(bank, lp_buf.at[ia, ga].set(lp_eff),
                                   lp_buf)
            if use_eos:
                done = jnp.where(
                    bank, done.at[ga].set(done[ga] | (tok_eff == eos_id)),
                    done)

            w = u - d
            active = (w >= 0) & (w < M * (N - 1))
            g = jnp.clip(w % M, 0, M - 1)
            e = jnp.clip(w // M, 0, max(N - 2, 0))  # entry index
            pos = Pp + e  # the consumed token's global position

            if use_eos:
                # banking above ran first, so in the M == D case where a
                # stream's token arrives and is consumed in the same tick,
                # `done` already reflects it. Stage 0 reads its own table;
                # later stages reuse the mask that rode in with the data.
                lives = jnp.where(d == 0, ~done[g], lives_chan)
                # a stream whose rows ALL hit EOS skips its stage compute
                # entirely — that's the satellite's "stop burning ticks"
                active = active & jnp.any(lives)
            else:
                lives = None

            def unit(op):
                kc, vc = op
                x = jnp.where(d == 0,
                              _embed_at(cfg, embed_c, token_buf[g][:, None],
                                        pos).astype(h1.dtype),
                              h_chan)
                y, kc, vc = stage_apply(x, kc, vc, g, pos, 1, live_rows=lives)
                if want_lp:
                    tok, lp = head_sample(y, g, e + 1)
                    return (kc, vc), y, tok, lp
                tok = head_sample(y, g, e + 1)
                return (kc, vc), y, tok

            def noop(op):
                z = (op, jnp.zeros_like(h1), jnp.zeros((Bg,), jnp.int32))
                return z + (jnp.zeros((Bg,), jnp.float32),) if want_lp else z

            if want_lp:
                (kc, vc), y, tok, lp = jax.lax.cond(active, unit, noop,
                                                    (kc, vc))
            else:
                (kc, vc), y, tok = jax.lax.cond(active, unit, noop, (kc, vc))
                lp = None
            payload = [y, tok]
            if use_eos:
                payload.append(lives & active)
            if want_lp:
                payload.append(lp)
            ringed = ring(tuple(payload))
            h_chan, tok_chan = ringed[0], ringed[1]
            j = 2
            if use_eos:
                lives_chan = ringed[j]
                j += 1
            if want_lp:
                lp_chan = ringed[j]
            out = (h_chan, tok_chan, kc, vc, token_buf, out_buf)
            if use_eos:
                out = out + (done, lives_chan)
            if want_lp:
                out = out + (lp_buf, lp_chan)
            return out, None

        T_dec = M * (N - 1) + D
        if T_dec > 0 and N > 1:
            carry0 = (h1, tok_chan, kc, vc, token_buf, out_buf)
            if use_eos:
                carry0 = carry0 + (done, jnp.zeros((Bg,), bool))
            if want_lp:
                carry0 = carry0 + (lp_buf, lp_chan)
            carry, _ = jax.lax.scan(tick, carry0, jnp.arange(T_dec))
            token_buf, out_buf = carry[4], carry[5]
            if want_lp:
                lp_buf = carry[6 + (2 if use_eos else 0)]

        # outputs live on device 0; psum replicates across the pipe ring
        out = jax.lax.psum(jnp.where(d == 0, out_buf, 0), PIPE_AXIS)
        # [N, M, Bg] -> [B, N]
        toks = jnp.moveaxis(out, 0, -1).reshape(B, N)
        if want_lp:
            lpo = jax.lax.psum(jnp.where(d == 0, lp_buf, 0.0), PIPE_AXIS)
            lps = jnp.moveaxis(lpo, 0, -1).reshape(B, N)
        if not use_eos:
            return (toks, lps) if want_lp else toks
        hit = toks == eos_id
        lengths = jnp.where(hit.any(axis=1), jnp.argmax(hit, axis=1) + 1,
                            N).astype(jnp.int32)
        return (toks, lengths, lps) if want_lp else (toks, lengths)

    # layers: 'pipe' on the stage dim, plus Megatron 'model' dims when a
    # model axis is present (same stacked-layout specs as the training
    # executor, so a pp x tp-trained pytree decodes in-place)
    layer_spec = (_dense_layer_specs(cfg, T, None) if T > 1
                  else P(PIPE_AXIS))
    sharded = _shard_map(
        spmd, mesh,
        in_specs=(layer_spec, P(), P(), P(), P()),
        out_specs=P(),
    )

    @jax.jit
    def _gen(params, prompt, key_data):
        with jax.named_scope("decode/stack"):
            stacked = stack_stage_layers(params["layers"], D, 1)
        with jax.named_scope("decode/pipeline"):
            res = sharded(stacked, params["embed"], params["head"], prompt,
                          key_data)
        # spmd returns toks[, lengths when eos-aware][, logprobs]
        new = res[0] if (eos_id is not None or want_lp) else res
        toks = jnp.concatenate([prompt, new.astype(prompt.dtype)], axis=1)
        outs = (toks,)
        if return_lengths:
            outs = outs + (res[1],)
        if want_lp:
            outs = outs + (res[-1],)
        return outs if len(outs) > 1 else toks

    def gen(params, prompt, key=None):
        # precondition checks run OUTSIDE jit so violations surface as
        # plain ValueErrors at the call site, not mid-trace
        B, Pp = prompt.shape
        if B % M:
            raise ValueError(
                f"batch {B} is not divisible by n_streams={M}; each "
                "round-robin stream carries B/M requests, so pad the batch "
                "or pick n_streams dividing it")
        total = Pp + N
        mlen = max_len or total
        if total > mlen:
            raise ValueError(f"prompt ({Pp}) + max_new_tokens ({N}) "
                             f"exceeds max_len ({mlen})")
        if cfg.arch == "gpt2" and total > cfg.max_seq_len:
            raise ValueError(f"prompt ({Pp}) + max_new_tokens ({N}) "
                             f"exceeds the gpt2 position table "
                             f"(max_seq_len={cfg.max_seq_len})")
        if need_key and key is None:
            raise ValueError("sampling (temperature != 0) requires a PRNG "
                             "key")
        key = key if key is not None else jax.random.key(0)
        return _gen(params, prompt, jax.random.key_data(key))

    return gen
