"""Pipeline-schedule IR: per-device action lists, tick scheduling, validation.

The reference delegates scheduling to ``torch.distributed.pipelining``
(SURVEY.md U2-U4): ``ScheduleGPipe`` (fill-drain, ``schedules.py:872``),
``Schedule1F1B`` (warmup/steady/cooldown, ``schedules.py:995``), and
``ScheduleInterleaved1F1B`` (explicit per-rank action-list IR over virtual
stages, ``schedules.py:2891``, after Megatron-LM arXiv:2104.04473).

This module re-expresses all three as a host-side IR compiled for a
single-program SPMD executor:

1. **Action lists** — for each device, an ordered list of
   ``Action(stage, op, microbatch)`` (``op`` in {F, B}; ``stage`` is the
   *global* stage index; device(stage) = stage % n_devices, virtual index
   v = stage // n_devices — the reference's wrap placement
   ``stage_idx = rank + world_size * i``, ``LLMsDistributedTrainingHelper.py:208``).
2. **Tick scheduling** — an ASAP list scheduler assigns each action to a
   discrete tick: one compute action per device per tick, actions execute
   in list order per device, and a cross-device data dependency costs one
   tick of transfer latency (the ``ppermute`` hop).
3. **Tick tables** — dense int32 arrays the SPMD executor scans over; every
   entry is static, so the whole schedule compiles into one XLA program with
   no data-dependent control flow.

Under jit the ticks become real lockstep super-steps separated by
``ppermute`` collectives, so the tick abstraction here *is* the runtime
model, not just an analysis.
"""

from __future__ import annotations

import dataclasses
import hashlib
import heapq
import json
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

F = "F"
B = "B"  # full backward — or input-grad (dgrad) only under a split schedule
W = "W"  # weight-grad (wgrad) — split schedules (ZB-H1) only

SPLIT_BACKWARD_SCHEDULES = frozenset({"ZBH1", "ZBV"})

# User-registered schedules: name -> (order_fn, split_backward).
# ``order_fn(n_devices, n_virtual, n_microbatches) -> List[List[Action]]``.
_CUSTOM_SCHEDULES: Dict[str, Tuple[object, bool]] = {}


def register_schedule(name: str, order_fn, split_backward: bool = False,
                      overwrite: bool = False) -> None:
    """Register a custom pipeline schedule under ``name``.

    ``order_fn(n_devices, n_virtual, n_microbatches)`` returns per-device
    action lists using this module's :class:`Action` (wrap placement:
    device(stage) = stage % n_devices). The order is validated, deadlock-
    checked, tick-scheduled, slot-allocated, and symbolically verified by
    the same machinery as the built-ins, then runs on the unmodified SPMD
    executor — the whole point of keeping the schedule as data
    (upstream torch gates this behind ``_PipelineScheduleRuntime``'s CSV
    loader, ``schedules.py:2279``; here it is a first-class API, tested in
    tests/test_custom_schedule.py). With ``split_backward`` the order must
    emit dgrad ``B`` + wgrad ``W`` pairs per ZB-H1 conventions (no ``B``
    on stage 0).
    """
    if not overwrite and (name in BUILTIN_SCHEDULE_NAMES
                          or name in _CUSTOM_SCHEDULES):
        raise ScheduleError(f"schedule {name!r} already exists")
    if name in BUILTIN_SCHEDULE_NAMES:
        raise ScheduleError(f"cannot overwrite built-in schedule {name!r}")
    _CUSTOM_SCHEDULES[name] = (order_fn, split_backward)


def unregister_schedule(name: str) -> None:
    _CUSTOM_SCHEDULES.pop(name, None)
    _ARTIFACT_PINS.pop(name, None)


def is_split_backward(name: str) -> bool:
    if name in _CUSTOM_SCHEDULES:
        return _CUSTOM_SCHEDULES[name][1]
    return name in SPLIT_BACKWARD_SCHEDULES


def is_custom(name: str) -> bool:
    return name in _CUSTOM_SCHEDULES


def schedule_names() -> Tuple[str, ...]:
    return BUILTIN_SCHEDULE_NAMES + tuple(_CUSTOM_SCHEDULES)


BUILTIN_SCHEDULE_NAMES = ("GPipe", "1F1B", "Interleaved1F1B", "ZBH1", "BFS",
                          "ZBV")


def schedule_placement(name: str) -> str:
    return "vshape" if name == "ZBV" else "wrap"


@dataclasses.dataclass(frozen=True)
class Action:
    stage: int  # global stage index in [0, n_stages)
    op: str  # F, B, or W
    microbatch: int


class ScheduleError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Per-device action-order generators
# ---------------------------------------------------------------------------


def gpipe_order(n_devices: int, n_microbatches: int) -> List[List[Action]]:
    """Fill-drain: all forwards in microbatch order, then all backwards.

    Mirrors upstream ScheduleGPipe semantics (SURVEY.md U2): per stage, M
    forwards then M backwards, both in increasing microbatch order.
    """
    orders = []
    for d in range(n_devices):
        acts = [Action(d, F, m) for m in range(n_microbatches)]
        acts += [Action(d, B, m) for m in range(n_microbatches)]
        orders.append(acts)
    return orders


def one_f_one_b_order(n_devices: int, n_microbatches: int) -> List[List[Action]]:
    """1F1B: per-device warmup of (D-1-d) forwards, steady-state alternating
    F/B, cooldown backwards (SURVEY.md U3; upstream requires M >= D,
    ``schedules.py:1020-1024`` — enforced here too)."""
    D, M = n_devices, n_microbatches
    if M < D:
        raise ScheduleError(f"1F1B requires n_microbatches >= n_devices ({M} < {D})")
    orders = []
    for d in range(D):
        warmup = min(M, D - 1 - d)
        acts = [Action(d, F, m) for m in range(warmup)]
        nf, nb = warmup, 0
        while nf < M:  # steady state: one forward, one backward
            acts.append(Action(d, F, nf))
            nf += 1
            acts.append(Action(d, B, nb))
            nb += 1
        acts += [Action(d, B, m) for m in range(nb, M)]
        orders.append(acts)
    return orders


def interleaved_order(n_devices: int, n_virtual: int,
                      n_microbatches: int) -> List[List[Action]]:
    """Interleaved 1F1B over V virtual stages per device (Megatron-LM style,
    upstream ``ScheduleInterleaved1F1B``, SURVEY.md U4).

    Global stage v * D + d lives on device d (wrap placement). Forwards are
    issued in rounds of ``mb_per_round`` microbatches per virtual stage;
    warmup depth is ``(V-1) * mb_per_round + 2 * (D-1-d)``; steady state is
    one-forward-one-backward; backward virtual-stage order is reversed.
    Upstream requires ``n_mb % num_rounds == 0`` with
    ``num_rounds = max(1, n_mb // D)`` (``schedules.py:2935-2942``).

    With V == 1 this degenerates to the plain 1F1B layout — matching the
    reference's fallback when ``n_layers % (world_size*2) != 0``
    (``LLMsDistributedTrainingHelper.py:181-185``).
    """
    D, V, M = n_devices, n_virtual, n_microbatches
    if V == 1:
        return one_f_one_b_order(D, M)
    num_rounds = max(1, M // D)
    if M % num_rounds != 0:
        raise ScheduleError(
            f"Interleaved1F1B requires n_microbatches % num_rounds == 0 "
            f"(M={M}, num_rounds={num_rounds})")
    mbpr = M // num_rounds  # microbatches per round

    def fwd_vm(i: int) -> Tuple[int, int]:
        v = (i // mbpr) % V
        m = (i // (mbpr * V)) * mbpr + (i % mbpr)
        return v, m

    def bwd_vm(j: int) -> Tuple[int, int]:
        v = V - 1 - ((j // mbpr) % V)
        m = (j // (mbpr * V)) * mbpr + (j % mbpr)
        return v, m

    total = M * V
    orders = []
    for d in range(D):
        warmup = min(total, (V - 1) * mbpr + 2 * (D - 1 - d))
        acts = []
        nf = nb = 0
        for _ in range(warmup):
            v, m = fwd_vm(nf)
            acts.append(Action(v * D + d, F, m))
            nf += 1
        while nf < total:  # steady state
            v, m = fwd_vm(nf)
            acts.append(Action(v * D + d, F, m))
            nf += 1
            v, m = bwd_vm(nb)
            acts.append(Action(v * D + d, B, m))
            nb += 1
        while nb < total:  # cooldown
            v, m = bwd_vm(nb)
            acts.append(Action(v * D + d, B, m))
            nb += 1
        orders.append(acts)
    return orders


def bfs_order(n_devices: int, n_virtual: int,
              n_microbatches: int) -> List[List[Action]]:
    """BFS (breadth-first) pipeline: GPipe generalized to V virtual stages
    per device with wrap placement (Lamy-Poirier, arXiv:2211.05953).

    Per device: all forwards in (virtual, microbatch) lexicographic order —
    every microbatch sweeps virtual stage v before any touches v+1 — then
    all backwards with the virtual order reversed. With V == 1 this *is*
    GPipe's fill-drain. Versus Interleaved-1F1B it keeps GPipe's simple
    all-F-then-all-B structure (activation memory O(M*V), no steady-state
    interleaving) while shrinking the bubble the same way: per-device work
    grows to 2MV unit ticks against the same ~2(D-1) ramp.

    Beyond-parity: the reference's three schedules (SURVEY.md U2-U4) do not
    include BFS; it completes the depth-first (interleaved) vs breadth-first
    axis of the virtual-stage design space.
    """
    D, V, M = n_devices, n_virtual, n_microbatches
    orders = []
    for d in range(D):
        acts = [Action(v * D + d, F, m)
                for v in range(V) for m in range(M)]
        acts += [Action(v * D + d, B, m)
                 for v in reversed(range(V)) for m in range(M)]
        orders.append(acts)
    return orders


def _zb_greedy_order(D: int, M: int, S: int, device_of,
                     live_cap_of, label: str) -> List[List[Action]]:
    """Greedy priority synthesis shared by the zero-bubble schedules.

    At each tick every device picks its highest-priority READY action:
    dgrad ``B`` first (it unblocks a neighbor), then ``F``, then ``W`` —
    so weight-grad work sinks into exactly the ticks that would otherwise
    be bubbles (warmup for late devices, cooldown for early ones). This
    is what makes the compiled tables meet the papers' makespans instead
    of approximating them (asserted against the closed forms in
    :func:`analytic_bubble_fraction` by tests/test_zero_bubble.py).
    Stage 0 elides ``B`` (no upstream to send a cotangent to; its ``W``
    carries the full parameter+embedding backward), and ``live_cap_of``
    bounds each device's in-flight forwards (F count minus W count — W is
    the releasing read of the saved input) so the greedy cannot front-load
    toward GPipe-class memory.
    """
    remaining = {(s, F, m) for s in range(S) for m in range(M)}
    remaining |= {(s, W, m) for s in range(S) for m in range(M)}
    remaining |= {(s, B, m) for s in range(1, S) for m in range(M)}
    done: Dict[Tuple[int, str, int], int] = {}
    orders: List[List[Action]] = [[] for _ in range(D)]
    t = 0
    limit = 8 * len(remaining) + 64

    def ready(s, op, m, now):
        if op == F:
            if s == 0:
                return True
            d = done.get((s - 1, F, m))
            return d is not None and d + 1 <= now
        if (s, F, m) not in done:
            return False
        if op == W:
            if s == 0:
                d = done.get((1, B, m))
                return d is not None and d + 1 <= now
            if s == S - 1:
                return True
            return (s, B, m) in done
        # dgrad B
        if s == S - 1:
            return True
        d = done.get((s + 1, B, m))
        return d is not None and d + 1 <= now

    def priority(s, op, m):
        # smaller sorts first: B before F before W; within an op, deeper
        # stages first (the return leg drains eagerly under multi-chunk
        # placements); then older microbatches
        op_rank = {B: 0, F: 1, W: 2}[op]
        return (op_rank, -s, m)

    n_f = [0] * D
    n_w = [0] * D
    while remaining:
        if t > limit:
            raise ScheduleError(f"{label} synthesis deadlocked")
        for d in range(D):
            cands = sorted(
                ((s, op, m) for (s, op, m) in remaining
                 if device_of(s) == d and ready(s, op, m, t)
                 and not (op == F and n_f[d] - n_w[d] >= live_cap_of(d))),
                key=lambda a: priority(*a))
            if cands:
                s, op, m = cands[0]
                remaining.discard((s, op, m))
                done[(s, op, m)] = t
                orders[d].append(Action(s, op, m))
                if op == F:
                    n_f[d] += 1
                elif op == W:
                    n_w[d] += 1
        t += 1
    return orders


def zb_h1_order(n_devices: int, n_microbatches: int) -> List[List[Action]]:
    """ZB-H1 zero-bubble schedule (Qi et al., arXiv:2401.10241): the full
    backward is split into an input-grad half ``B`` (on the critical path —
    it unblocks the upstream stage) and a weight-grad half ``W`` (off the
    critical path — it fills what would otherwise be bubble ticks).

    Upstream torch.distributed.pipelining exposes exactly this split as
    ``stage_backward_input`` / ``stage_backward_weight``
    (``_backward.py:177,281`` — SURVEY.md U5); the reference's three
    schedules never exercise it, so this schedule is beyond-parity.

    Orders come from the shared greedy synthesis (V=1, stage == device).
    The in-flight cap is ``2D - d``: eliding stage 0's dgrad means the
    first W (the releasing read) cannot exist before the first cotangent
    makes the full ~2D-tick round trip, so hitting the paper's makespan
    requires stage 0 to front-run up to 2D forwards — a deliberate
    memory-for-makespan trade (the paper's uniform-work H1 peaks at ~D
    in-flight but runs M more actions; ours runs fewer actions and banks
    deeper on the first stage). Tighter caps (e.g. ``D - d + 1``) stall
    device 0's forwards during the ramp and sit 1..(D-3) ticks over the
    ``3M + D - 1`` optimum, which the compiled table now meets exactly
    (asserted against :func:`analytic_bubble_fraction`'s closed form).
    """
    D, M = n_devices, n_microbatches
    if D < 2:
        raise ScheduleError("ZBH1 requires n_devices >= 2 (loss lives on the "
                            "last stage's dgrad unit, which stage 0 elides)")
    if M < D:
        raise ScheduleError(f"ZBH1 requires n_microbatches >= n_devices ({M} < {D})")
    return _zb_greedy_order(D, M, D, lambda s: s,
                            lambda d: 2 * D - d, "ZBH1")


def zb_v_order(n_devices: int, n_microbatches: int) -> List[List[Action]]:
    """ZB-V (Qi et al., arXiv:2401.10241 §4): 2 chunks per device in the
    V-shaped placement — device d holds stages d and 2D-1-d, so the last
    forward stage and the first backward stage share device 0 and cotangents
    begin flowing with no cross-device turnaround. Combined with the
    dgrad/wgrad split, the warm pipeline has (near-)zero bubble at 1F1B's
    activation memory.

    The per-device order is synthesized by a greedy priority simulation
    rather than transcribed from the paper's figure: at each tick every
    device picks its highest-priority READY action (dgrad B first — it
    unblocks a neighbor — then F, then W to fill leftover ticks), with
    chunk-1 work preferred over chunk-0 so the V's return leg drains
    eagerly. The validator/tick-scheduler then re-checks the result like
    any other order. Stage 0 elides B per the ZB-H1 convention (no upstream
    to send a cotangent to; its W carries the full parameter backward).
    """
    D, M = n_devices, n_microbatches
    if D < 2:
        raise ScheduleError("ZBV requires n_devices >= 2")
    if M < 2 * D:
        raise ScheduleError(
            f"ZBV requires n_microbatches >= 2 * n_devices ({M} < {2 * D}); "
            f"fewer microbatches cannot fill the V's steady state")
    # Activation-memory cap ~2D+2 live stage inputs per device: without it
    # the greedy front-loads every forward and peak memory degrades to
    # GPipe's O(M·V); with it the slot allocator recovers 1F1B-class O(D)
    # buffers (asserted in tests). The cap never deadlocks: B/W chains are
    # always schedulable once their forwards ran.
    return _zb_greedy_order(D, M, 2 * D,
                            lambda s: placement_device_of("vshape", s, D),
                            lambda d: 2 * D + 2, "ZBV")


def build_order(name: str, n_devices: int, n_virtual: int,
                n_microbatches: int) -> List[List[Action]]:
    if name in _CUSTOM_SCHEDULES:
        return _CUSTOM_SCHEDULES[name][0](n_devices, n_virtual, n_microbatches)
    if name == "ZBV":
        if n_virtual != 2:
            raise ScheduleError("ZBV runs exactly 2 chunks per device "
                                "(set n_virtual=2)")
        return zb_v_order(n_devices, n_microbatches)
    if name == "ZBH1":
        if n_virtual != 1:
            raise ScheduleError("ZBH1 supports a single stage per device")
        return zb_h1_order(n_devices, n_microbatches)
    if name == "GPipe":
        if n_virtual != 1:
            raise ScheduleError("GPipe supports a single stage per device")
        return gpipe_order(n_devices, n_microbatches)
    if name == "1F1B":
        if n_virtual != 1:
            raise ScheduleError("1F1B supports a single stage per device")
        return one_f_one_b_order(n_devices, n_microbatches)
    if name == "Interleaved1F1B":
        return interleaved_order(n_devices, n_virtual, n_microbatches)
    if name == "BFS":
        return bfs_order(n_devices, n_virtual, n_microbatches)
    raise ScheduleError(f"unknown schedule {name!r}")


# ---------------------------------------------------------------------------
# Stage placements
# ---------------------------------------------------------------------------
#
# "wrap" (the reference's ``stage = rank + world_size * v``): device(s) = s % D.
# Inter-stage transfers always travel +1 (fwd) / -1 (bwd) on the device ring.
#
# "vshape" (ZB-V, Qi et al. arXiv:2401.10241): V=2 chunks per device laid out
# as a V — device(s) = s for s < D, else 2D-1-s. The s=D-1 -> D transfer stays
# on-device; chunk-1 forwards travel -1 on the ring (and their cotangents +1).


def placement_device_of(placement: str, stage: int, D: int) -> int:
    if placement == "wrap":
        return stage % D
    if placement == "vshape":
        return stage if stage < D else 2 * D - 1 - stage
    raise ScheduleError(f"unknown placement {placement!r}")


def placement_chunk_of(placement: str, stage: int, D: int) -> int:
    """The local chunk index v such that stage_of(device, v) == stage."""
    if placement == "wrap":
        return stage // D
    if placement == "vshape":
        return 0 if stage < D else 1
    raise ScheduleError(f"unknown placement {placement!r}")


def placement_stage_of(placement: str, d: int, v: int, D: int) -> int:
    if placement == "wrap":
        return v * D + d
    if placement == "vshape":
        return d if v == 0 else 2 * D - 1 - d
    raise ScheduleError(f"unknown placement {placement!r}")


# ---------------------------------------------------------------------------
# Tick scheduling (ASAP list scheduler)
# ---------------------------------------------------------------------------


def schedule_ticks(orders: List[List[Action]], n_devices: int, n_virtual: int,
                   placement: str = "wrap") -> Tuple[Dict[Action, int], int]:
    """Assign each action a tick. Returns (action -> tick, makespan).

    Rules: one action per device per tick; per-device actions run in list
    order; F(s, m) needs F(s-1, m) completed >= 1 tick earlier when the stages
    live on different devices (ppermute latency), B(s, m) needs F(s, m) (same
    device, activations saved locally) and B(s+1, m) >= 1 tick earlier.
    (Same-device inter-stage transfers — vshape's s=D-1 -> D hop — need only
    ``done + 1 <= now`` too, which one-action-per-tick already implies.)

    This is the deadlock-freedom analog of upstream's ``_validate_schedule``
    (``schedules.py:1619``) plus gloo's peer-sorted P2P batching
    (SURVEY.md §5 race-detection row): here deadlocks surface as a scheduling
    error at compile time rather than a hang at run time.
    """
    D = n_devices
    S = D * n_virtual
    n_actions = sum(len(o) for o in orders)
    done: Dict[Action, int] = {}
    ptr = [0] * D
    t = 0
    limit = 4 * n_actions + 4 * S + 16

    def device_of(stage: int) -> int:
        return placement_device_of(placement, stage, D)

    def ready(a: Action, now: int) -> bool:
        if a.op == F:
            if a.stage == 0:
                return True
            dep = Action(a.stage - 1, F, a.microbatch)
            # one tick of ppermute latency (for D == 1 the +1 is subsumed by
            # one-action-per-tick, so the same rule applies)
            return dep in done and done[dep] + 1 <= now
        if Action(a.stage, F, a.microbatch) not in done:
            return False
        if a.op == W:
            # wgrad: needs the incoming cotangent. Stage 0 (no B of its own)
            # waits for the ppermute arrival from B(1, m); other stages'
            # same-device B already proved the cotangent is banked.
            if a.stage == 0:
                dep = Action(1, B, a.microbatch)
                return dep in done and done[dep] + 1 <= now
            if a.stage == S - 1:
                return True  # CE recompute needs no incoming cotangent
            return Action(a.stage, B, a.microbatch) in done
        # backward (full or dgrad)
        if a.stage == S - 1:
            return True
        dep = Action(a.stage + 1, B, a.microbatch)
        return dep in done and done[dep] + 1 <= now

    while any(ptr[d] < len(orders[d]) for d in range(D)):
        if t > limit:
            raise ScheduleError("schedule deadlocked: no progress within tick limit")
        for d in range(D):
            if ptr[d] >= len(orders[d]):
                continue
            a = orders[d][ptr[d]]
            if device_of(a.stage) != d:
                raise ScheduleError(f"action {a} listed on device {d}")
            if ready(a, t):
                done[a] = t
                ptr[d] += 1
        t += 1
    return done, t


def validate_order(orders: List[List[Action]], n_devices: int, n_virtual: int,
                   n_microbatches: int, split_backward: bool = False,
                   placement: str = "wrap") -> None:
    """Structural validation: every (stage, microbatch) has exactly one F and
    one full B (or, under a split schedule, one W plus one dgrad B for every
    stage except 0), F precedes B/W per device, W follows its dgrad twin
    (whose saved slots it aliases), and the tick scheduler completes.
    Error messages carry a (device, index) location prefix — the device and
    per-device order position of the offending action."""
    S = n_devices * n_virtual
    seen: Dict[Action, int] = {}
    for d, order in enumerate(orders):
        pos = {}
        for i, a in enumerate(order):
            if a in seen:
                raise ScheduleError(
                    f"(device {d}, index {i}): duplicate action {a} "
                    f"(first listed on device {seen[a]})")
            seen[a] = d
            pos[a] = i
        for a in order:
            if a.op in (B, W):
                fa = Action(a.stage, F, a.microbatch)
                if fa not in pos or pos[fa] > pos[a]:
                    raise ScheduleError(
                        f"(device {d}, index {pos[a]}): backward before "
                        f"forward: {a}")
            if a.op == W and a.stage >= 1:
                # split-backward W reuses the dgrad B unit's saved slots
                # (COL_W_ASLOT/COL_W_GSLOT alias COL_BWD_ASLOT/GSLOT, see
                # analysis.table_check's w-slot-alias hazard) — so B(s, m)
                # must precede W(s, m) in the same device order or the
                # aliased slots would not exist yet. Stage 0 has no B; its
                # W reads F(0, m)'s own slot.
                ba = Action(a.stage, B, a.microbatch)
                if ba not in pos or pos[ba] > pos[a]:
                    raise ScheduleError(
                        f"(device {d}, index {pos[a]}): {a} precedes its "
                        f"dgrad twin {ba}, whose saved slots it aliases")
    want = {Action(s, F, m) for s in range(S) for m in range(n_microbatches)}
    if split_backward:
        want |= {Action(s, W, m) for s in range(S) for m in range(n_microbatches)}
        want |= {Action(s, B, m) for s in range(1, S) for m in range(n_microbatches)}
    else:
        want |= {Action(s, B, m) for s in range(S) for m in range(n_microbatches)}
    if set(seen) != want:
        raise ScheduleError(
            f"action set mismatch: {len(seen)} actions vs expected {len(want)} "
            f"(missing {list(want - set(seen))[:4]}, "
            f"extra {list(set(seen) - want)[:4]})")
    schedule_ticks(orders, n_devices, n_virtual,
                   placement=placement)  # raises on deadlock


# ---------------------------------------------------------------------------
# Tick tables for the SPMD executor
# ---------------------------------------------------------------------------

# Columns of the per-(tick, device) table. -1 means "no-op this tick".
# Buffers are slot-addressed: slots are allocated from actual activation
# lifetimes, so 1F1B keeps its O(in-flight) activation-memory advantage over
# GPipe's O(M) instead of always allocating M microbatch buffers.
COL_STORE_F_SLOT = 0  # store +1-channel fwd arrival -> act_buf[slot]
COL_FWD_V, COL_FWD_M, COL_FWD_SLOT = 1, 2, 3  # forward unit: (v, m), input slot
COL_STORE_B_SLOT = 4  # store -1-channel grad arrival -> grad_buf[slot]
COL_BWD_V, COL_BWD_M = 5, 6  # backward unit: (v, m)
COL_BWD_ASLOT, COL_BWD_GSLOT = 7, 8  # saved-input slot, incoming-grad slot
COL_W_V, COL_W_M = 9, 10  # weight-grad unit (split schedules): (v, m)
COL_W_ASLOT, COL_W_GSLOT = 11, 12  # its saved-input slot, incoming-grad slot
# vshape-placement routes (always -1 under wrap placement, so wrap tables
# are bit-identical to the 13-column era):
N_COLS_CLASSIC = 13  # the wrap-placement-only column count
COL_FWD_LOCAL_SLOT = 13  # fwd output -> OWN act_buf[slot] (same-device hop)
COL_STORE_F_NEG_SLOT = 14  # store -1-channel fwd arrival -> act_buf[slot]
COL_BWD_LOCAL_SLOT = 15  # bwd cotangent -> OWN grad_buf[slot]
COL_STORE_B_POS_SLOT = 16  # store +1-channel grad arrival -> grad_buf[slot]
N_COLS = 17


def fwd_route(placement: str, s: int, D: int) -> str:
    """Where F(s)'s output travels to reach stage s+1: '+1' ring, '-1' ring,
    or 'local' (same device)."""
    if placement == "wrap":
        return "+1"
    if s == D - 1:
        return "local"  # the V's turning point
    return "+1" if s < D - 1 else "-1"


def bwd_route(placement: str, s: int, D: int) -> str:
    """Where B(s)'s cotangent travels to reach stage s-1."""
    if placement == "wrap":
        return "-1"
    if s == D:
        return "local"
    return "+1" if s > D else "-1"


@dataclasses.dataclass(frozen=True)
class CompiledSchedule:
    name: str
    n_devices: int
    n_virtual: int
    n_microbatches: int
    table: np.ndarray  # [T, D, N_COLS] int32
    makespan: int
    ticks: Dict[Action, int]
    n_act_slots: int
    n_grad_slots: int
    # True when B actions are dgrad-only and W actions carry the parameter
    # gradients (ZB-H1 family; custom schedules declare it at registration).
    # Captured at compile time — a live registry lookup would let a later
    # unregister/overwrite silently change an already-compiled schedule's
    # semantics.
    split_backward: bool = False
    # "wrap" (stage = v*D + d) or "vshape" (ZB-V: device d holds stages d
    # and 2D-1-d; some transfers ride the -1 ring or stay on-device).
    placement: str = "wrap"

    @property
    def n_stages(self) -> int:
        return self.n_devices * self.n_virtual

    @property
    def uses_reverse_routes(self) -> bool:
        """True when the table uses the -1 fwd / +1 bwd channels or local
        hops — the executor then issues the two extra ppermutes."""
        return bool(np.any(self.table[:, :, N_COLS_CLASSIC:] >= 0))


def _allocate_slots(events: List[Tuple[int, int, object]]) -> Tuple[Dict[object, int], int]:
    """Greedy interval slot allocation.

    ``events`` is a list of (store_tick, release_tick, key): the slot is
    written at ``store_tick`` and may be reused for stores at
    ``release_tick + 1`` onwards (release_tick is the tick whose compute
    reads it last). Returns (key -> slot, n_slots).
    """
    by_store = sorted(events, key=lambda e: (e[0], e[1]))
    free: List[int] = []
    in_use: List[Tuple[int, int]] = []  # (release_tick, slot)
    n_slots = 0
    assign: Dict[object, int] = {}
    for store, release, key in by_store:
        while in_use and in_use[0][0] < store:
            _, slot = heapq.heappop(in_use)
            heapq.heappush(free, slot)
        if free:
            slot = heapq.heappop(free)
        else:
            slot = n_slots
            n_slots += 1
        assign[key] = slot
        heapq.heappush(in_use, (release, slot))
    return assign, n_slots


def compile_schedule(name: str, n_devices: int, n_virtual: int,
                     n_microbatches: int) -> CompiledSchedule:
    """Generate, validate, and lower a schedule to executor tick tables.

    The lowering is the SPMD analog of upstream's comm insertion
    (``_add_send_recv`` / ``_prepare_schedule_with_comms``,
    ``schedules.py:1406, 2279`` — SURVEY.md U5): instead of SEND/RECV actions,
    every tick ends with a fwd ``ppermute`` (+1 ring) and a bwd ``ppermute``
    (-1 ring), and the table records which arrivals carry real data and which
    buffer slot holds each live value. The compiled table is self-checked by
    :func:`verify_table` (a symbolic interpreter) before being returned.
    """
    D, V, M = n_devices, n_virtual, n_microbatches
    split = is_split_backward(name)
    placement = schedule_placement(name)
    orders = build_order(name, D, V, M)
    cs = compile_order(name, orders, D, V, M, split_backward=split,
                       placement=placement)
    verify_artifact_pin(cs)
    return cs


def compile_order(name: str, orders: List[List[Action]], n_devices: int,
                  n_virtual: int, n_microbatches: int, *,
                  split_backward: bool = False, placement: str = "wrap",
                  verify: bool = True) -> CompiledSchedule:
    """Lower explicit per-device action orders to a verified tick table.

    This is :func:`compile_schedule` minus the order *generation* step: the
    caller supplies the per-device :class:`Action` lists directly, which is
    what the schedule-search pass (``analysis.schedule_search``) and the
    artifact loader need — both own their orders and must compile thousands
    of candidate permutations without registering each one. ``verify=False``
    skips the :func:`verify_table` self-check (the search certifies
    candidates with the richer ``analysis.check_table`` instead); validation
    of the action set and deadlock-freedom always runs.
    """
    D, V, M = n_devices, n_virtual, n_microbatches
    split = split_backward
    validate_order(orders, D, V, M, split_backward=split,
                   placement=placement)
    ticks, T_compute = schedule_ticks(orders, D, V, placement=placement)
    S = D * V

    def device_of(s):
        return placement_device_of(placement, s, D)

    # +1: arrivals land one tick after the producing compute; the final
    # backward of stage 0 produces no arrival, but a last-tick forward of a
    # non-final stage (never happens in practice) would need T_compute + 1.
    T = T_compute + 1

    # Activation lifetimes per device: input of stage s for microbatch m is
    # written at the producer's tick + 1 (ring arrival) — at the producer's
    # tick itself for a same-device hop, or at the forward tick for global
    # stage 0 (the embed is computed in place) — and last read by B(s, m),
    # or by W(s, m) under a split schedule (W runs after B by list order, so
    # W is the releasing read). Grad lifetimes mirror this for B(s+1, m).
    act_events: List[List[Tuple[int, int, object]]] = [[] for _ in range(D)]
    grad_events: List[List[Tuple[int, int, object]]] = [[] for _ in range(D)]
    for a, t in ticks.items():
        if a.op != F:
            continue
        d = device_of(a.stage)
        if a.stage == 0:
            store = t
        else:
            pt = ticks[Action(a.stage - 1, F, a.microbatch)]
            local = fwd_route(placement, a.stage - 1, D) == "local"
            store = pt if local else pt + 1
        release = max(ticks[r] for r in (Action(a.stage, B, a.microbatch),
                                         Action(a.stage, W, a.microbatch))
                      if r in ticks)
        act_events[d].append((store, release, (a.stage, a.microbatch)))
    for s in range(S - 1):
        d = device_of(s)
        for m in range(M):
            pt = ticks[Action(s + 1, B, m)]
            local = bwd_route(placement, s + 1, D) == "local"
            store = pt if local else pt + 1
            release = max(ticks[r] for r in (Action(s, B, m), Action(s, W, m))
                          if r in ticks)
            grad_events[d].append((store, release, (s, m)))

    act_assign, n_act = [], 0
    grad_assign, n_grad = [], 0
    for d in range(D):
        assign, n = _allocate_slots(act_events[d])
        act_assign.append(assign)
        n_act = max(n_act, n)
        assign, n = _allocate_slots(grad_events[d])
        grad_assign.append(assign)
        n_grad = max(n_grad, n)
    n_grad = max(n_grad, 1)  # executor buffers cannot be zero-sized

    table = np.full((T, D, N_COLS), -1, dtype=np.int32)
    for a, t in ticks.items():
        d = device_of(a.stage)
        v = placement_chunk_of(placement, a.stage, D)
        if a.op == F:
            slot = act_assign[d][(a.stage, a.microbatch)]
            table[t, d, COL_FWD_V] = v
            table[t, d, COL_FWD_M] = a.microbatch
            table[t, d, COL_FWD_SLOT] = slot
            if a.stage < S - 1:
                nd = device_of(a.stage + 1)
                nslot = act_assign[nd][(a.stage + 1, a.microbatch)]
                route = fwd_route(placement, a.stage, D)
                if route == "local":
                    table[t, d, COL_FWD_LOCAL_SLOT] = nslot
                elif route == "+1":
                    table[t + 1, nd, COL_STORE_F_SLOT] = nslot
                else:  # "-1"
                    table[t + 1, nd, COL_STORE_F_NEG_SLOT] = nslot
        elif a.op == B:
            table[t, d, COL_BWD_V] = v
            table[t, d, COL_BWD_M] = a.microbatch
            table[t, d, COL_BWD_ASLOT] = act_assign[d][(a.stage, a.microbatch)]
            if a.stage < S - 1:
                table[t, d, COL_BWD_GSLOT] = grad_assign[d][(a.stage, a.microbatch)]
            if a.stage > 0:
                pd = device_of(a.stage - 1)
                pslot = grad_assign[pd][(a.stage - 1, a.microbatch)]
                route = bwd_route(placement, a.stage, D)
                if route == "local":
                    table[t, d, COL_BWD_LOCAL_SLOT] = pslot
                elif route == "-1":
                    table[t + 1, pd, COL_STORE_B_SLOT] = pslot
                else:  # "+1"
                    table[t + 1, pd, COL_STORE_B_POS_SLOT] = pslot
        else:  # W (wgrad)
            table[t, d, COL_W_V] = v
            table[t, d, COL_W_M] = a.microbatch
            table[t, d, COL_W_ASLOT] = act_assign[d][(a.stage, a.microbatch)]
            if a.stage < S - 1:
                table[t, d, COL_W_GSLOT] = grad_assign[d][(a.stage, a.microbatch)]
    # Trim trailing all-empty ticks (keeps the executor scan minimal).
    while T > 1 and np.all(table[T - 1] == -1):
        T -= 1
    cs = CompiledSchedule(name, D, V, M, table[:T], T, ticks, n_act, n_grad,
                          split_backward=split, placement=placement)
    if verify:
        verify_table(cs)
    return cs


def verify_table(cs: CompiledSchedule) -> None:
    """Symbolic interpreter over the compiled table: executes the exact
    store/compute/permute contract the SPMD executor uses — four transfer
    channels (+1/-1 for each direction) plus same-device hops — and checks
    that every forward reads the right stage input and every backward reads
    the right saved input and incoming cotangent. Raises ScheduleError on
    any stale read, overwrite of a live value, or missing data."""
    D, V, S = cs.n_devices, cs.n_virtual, cs.n_stages
    pl = cs.placement
    act = [dict() for _ in range(D)]   # slot -> ("act", stage, mb)
    grad = [dict() for _ in range(D)]  # slot -> ("gout", stage, mb)
    fwd_in = [None] * D  # value delivered by last tick's +1 fwd ppermute
    fwd_in_neg = [None] * D  # ... -1 fwd channel (vshape chunk-1 forwards)
    bwd_in = [None] * D  # -1 bwd channel
    bwd_in_pos = [None] * D  # +1 bwd channel (vshape chunk-1 cotangents)
    fwd_done = set()
    bwd_done = set()
    w_done = set()
    for t in range(cs.table.shape[0]):
        fwd_send = [None] * D  # routed to +1, -1, or local per fwd_route
        fwd_send_neg = [None] * D
        bwd_send = [None] * D
        bwd_send_pos = [None] * D
        for d in range(D):
            row = cs.table[t, d]
            if row[COL_STORE_F_SLOT] >= 0:
                if fwd_in[d] is None:
                    raise ScheduleError(f"(device {d}, tick {t}): fwd store of empty register")
                act[d][int(row[COL_STORE_F_SLOT])] = fwd_in[d]
            if row[COL_STORE_F_NEG_SLOT] >= 0:
                if fwd_in_neg[d] is None:
                    raise ScheduleError(
                        f"(device {d}, tick {t}): fwd-neg store of empty register")
                act[d][int(row[COL_STORE_F_NEG_SLOT])] = fwd_in_neg[d]
            if row[COL_STORE_B_SLOT] >= 0:
                if bwd_in[d] is None:
                    raise ScheduleError(f"(device {d}, tick {t}): bwd store of empty register")
                grad[d][int(row[COL_STORE_B_SLOT])] = bwd_in[d]
            if row[COL_STORE_B_POS_SLOT] >= 0:
                if bwd_in_pos[d] is None:
                    raise ScheduleError(
                        f"(device {d}, tick {t}): bwd-pos store of empty register")
                grad[d][int(row[COL_STORE_B_POS_SLOT])] = bwd_in_pos[d]
            if row[COL_FWD_M] >= 0:
                s = placement_stage_of(pl, d, int(row[COL_FWD_V]), D)
                m = int(row[COL_FWD_M])
                slot = int(row[COL_FWD_SLOT])
                if s == 0:
                    act[d][slot] = ("act", 0, m)  # embed computed in place
                got = act[d].get(slot)
                if got != ("act", s, m):
                    raise ScheduleError(
                        f"(device {d}, tick {t}): F(stage={s}, mb={m}) read slot {slot} "
                        f"holding {got}")
                if s < S - 1:
                    route = fwd_route(pl, s, D)
                    if route == "local":
                        if row[COL_FWD_LOCAL_SLOT] < 0:
                            raise ScheduleError(
                                f"(device {d}, tick {t}): F(stage={s}) local route "
                                f"without COL_FWD_LOCAL_SLOT")
                        act[d][int(row[COL_FWD_LOCAL_SLOT])] = ("act", s + 1, m)
                    elif route == "+1":
                        fwd_send[d] = ("act", s + 1, m)
                    else:
                        fwd_send_neg[d] = ("act", s + 1, m)
                fwd_done.add((s, m))
            if row[COL_BWD_M] >= 0:
                s = placement_stage_of(pl, d, int(row[COL_BWD_V]), D)
                m = int(row[COL_BWD_M])
                aslot = int(row[COL_BWD_ASLOT])
                got = act[d].get(aslot)
                if got != ("act", s, m):
                    raise ScheduleError(
                        f"(device {d}, tick {t}): B(stage={s}, mb={m}) saved-input slot "
                        f"{aslot} holds {got}")
                if s < S - 1:
                    gslot = int(row[COL_BWD_GSLOT])
                    gg = grad[d].get(gslot)
                    if gg != ("gout", s, m):
                        raise ScheduleError(
                            f"(device {d}, tick {t}): B(stage={s}, mb={m}) grad slot "
                            f"{gslot} holds {gg}")
                if s > 0:
                    route = bwd_route(pl, s, D)
                    if route == "local":
                        if row[COL_BWD_LOCAL_SLOT] < 0:
                            raise ScheduleError(
                                f"(device {d}, tick {t}): B(stage={s}) local route "
                                f"without COL_BWD_LOCAL_SLOT")
                        grad[d][int(row[COL_BWD_LOCAL_SLOT])] = ("gout", s - 1, m)
                    elif route == "-1":
                        bwd_send[d] = ("gout", s - 1, m)
                    else:
                        bwd_send_pos[d] = ("gout", s - 1, m)
                bwd_done.add((s, m))
            if row[COL_W_M] >= 0:
                s = placement_stage_of(pl, d, int(row[COL_W_V]), D)
                m = int(row[COL_W_M])
                aslot = int(row[COL_W_ASLOT])
                got = act[d].get(aslot)
                if got != ("act", s, m):
                    raise ScheduleError(
                        f"(device {d}, tick {t}): W(stage={s}, mb={m}) saved-input slot "
                        f"{aslot} holds {got}")
                if s < S - 1:
                    gslot = int(row[COL_W_GSLOT])
                    gg = grad[d].get(gslot)
                    if gg != ("gout", s, m):
                        raise ScheduleError(
                            f"(device {d}, tick {t}): W(stage={s}, mb={m}) grad slot "
                            f"{gslot} holds {gg}")
                w_done.add((s, m))
        fwd_in = [fwd_send[(d - 1) % D] for d in range(D)]
        fwd_in_neg = [fwd_send_neg[(d + 1) % D] for d in range(D)]
        bwd_in = [bwd_send[(d + 1) % D] for d in range(D)]
        bwd_in_pos = [bwd_send_pos[(d - 1) % D] for d in range(D)]
    want = {(s, m) for s in range(S) for m in range(cs.n_microbatches)}
    if cs.split_backward:
        want_b = {(s, m) for s in range(1, S) for m in range(cs.n_microbatches)}
        ok = fwd_done == want and bwd_done == want_b and w_done == want
    else:
        ok = fwd_done == want and bwd_done == want and not w_done
    if not ok:
        raise ScheduleError("table does not execute every (stage, microbatch)")


# ---------------------------------------------------------------------------
# Schedule artifacts: certified, versioned JSON interchange for searched
# (or otherwise externally produced) schedules. An artifact carries the
# per-device action orders, the compiled [T, D, 17] table, a config
# fingerprint over its metadata, and (when emitted by the search) the
# embedded TableReport summary plus predicted cost. Loading recompiles the
# orders and certifies the stored table cell-by-cell, so a tampered or
# stale artifact fails with an exact (device, tick, column) location.
# ---------------------------------------------------------------------------

SCHEDULE_ARTIFACT_VERSION = 1
SCHEDULE_ARTIFACT_KIND = "schedule_artifact"

# Artifact-backed registered schedules: name -> pin. compile_schedule and
# pipeline._compile re-check the pin (verify_artifact_pin) so a re-registered
# order function can never silently swap a certified table.
_ARTIFACT_PINS: Dict[str, Dict[str, str]] = {}


def table_digest(table: np.ndarray) -> str:
    """Content digest of a tick table (shape + little-endian int32 cells)."""
    arr = np.ascontiguousarray(np.asarray(table, dtype="<i4"))
    h = hashlib.sha256()
    h.update(repr(arr.shape).encode())
    h.update(arr.tobytes())
    return h.hexdigest()


_FINGERPRINT_FIELDS = (
    "artifact_version", "kind", "name", "n_devices", "n_virtual",
    "n_microbatches", "placement", "split_backward", "n_act_slots",
    "n_grad_slots", "makespan", "verifier_version", "table_digest")


def _artifact_fingerprint(art: Dict[str, object]) -> str:
    payload = {k: art.get(k) for k in _FINGERPRINT_FIELDS}
    blob = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def _orders_from_ticks(cs: CompiledSchedule) -> List[List[Action]]:
    """Recover per-device action orders from a Python-compiled schedule's
    tick assignment (one compute action per device per tick)."""
    if not cs.ticks:
        raise ScheduleError(
            f"schedule {cs.name!r} has no tick map (natively compiled?); "
            "cannot recover per-device orders for an artifact")
    orders: List[List[Action]] = [[] for _ in range(cs.n_devices)]
    key = lambda kv: (kv[1], kv[0].stage, kv[0].op, kv[0].microbatch)
    for a, _t in sorted(cs.ticks.items(), key=key):
        orders[placement_device_of(cs.placement, a.stage, cs.n_devices)].append(a)
    return orders


def schedule_artifact(cs: CompiledSchedule, *,
                      orders: Optional[List[List[Action]]] = None,
                      seed: Optional[int] = None,
                      table_report: Optional[Dict[str, object]] = None,
                      predicted: Optional[Dict[str, object]] = None,
                      baselines: Optional[Dict[str, object]] = None,
                      search: Optional[Dict[str, object]] = None) -> Dict[str, object]:
    """Build the versioned JSON-serializable artifact for ``cs``.

    ``table_report`` is a ``TableReport.summary()`` dict (the caller runs
    ``check_table`` — this module stays import-clean of ``analysis``);
    ``predicted`` is the cost-model dict; both are embedded verbatim.
    The ``config_fingerprint`` signs the metadata fields only — table cells
    are covered separately by ``table_digest`` plus the loader's
    recompile-and-diff, which reports the exact mutated cell.
    """
    if orders is None:
        orders = _orders_from_ticks(cs)
    art: Dict[str, object] = {
        "artifact_version": SCHEDULE_ARTIFACT_VERSION,
        "kind": SCHEDULE_ARTIFACT_KIND,
        "name": cs.name,
        "n_devices": int(cs.n_devices),
        "n_virtual": int(cs.n_virtual),
        "n_microbatches": int(cs.n_microbatches),
        "placement": cs.placement,
        "split_backward": bool(cs.split_backward),
        "n_act_slots": int(cs.n_act_slots),
        "n_grad_slots": int(cs.n_grad_slots),
        "makespan": int(cs.makespan),
        "orders": [[[int(a.stage), a.op, int(a.microbatch)] for a in order]
                   for order in orders],
        "table": np.asarray(cs.table, dtype=np.int32).tolist(),
        "table_digest": table_digest(cs.table),
    }
    from ..analysis import VERIFIER_VERSION  # lazy: analysis imports us
    art["verifier_version"] = VERIFIER_VERSION
    if seed is not None:
        art["seed"] = int(seed)
    if table_report is not None:
        art["table_report"] = table_report
    if predicted is not None:
        art["predicted"] = predicted
    if baselines is not None:
        art["baselines"] = baselines
    if search is not None:
        art["search"] = search
    art["config_fingerprint"] = _artifact_fingerprint(art)
    return art


def schedule_artifact_bytes(art: Dict[str, object]) -> bytes:
    """Canonical (byte-deterministic) JSON encoding of an artifact."""
    return (json.dumps(art, sort_keys=True) + "\n").encode()


def save_schedule_artifact(art: Dict[str, object], path) -> None:
    with open(path, "wb") as fh:
        fh.write(schedule_artifact_bytes(art))


def _art_err(label: str, field: str, msg: str) -> ScheduleError:
    return ScheduleError(f"schedule artifact {label}: field {field!r}: {msg}")


def _load_artifact_dict(source) -> Tuple[Dict[str, object], str]:
    if isinstance(source, dict):
        return source, "<dict>"
    label = str(source)
    try:
        with open(source, "r", encoding="utf-8") as fh:
            art = json.load(fh)
    except OSError as e:
        raise ScheduleError(f"schedule artifact {label}: unreadable: {e}")
    except json.JSONDecodeError as e:
        raise ScheduleError(f"schedule artifact {label}: invalid JSON: {e}")
    if not isinstance(art, dict):
        raise ScheduleError(
            f"schedule artifact {label}: top level must be a JSON object, "
            f"got {type(art).__name__}")
    return art, label


def _validated_int(art: Dict[str, object], label: str, key: str,
                   minimum: int) -> int:
    v = art.get(key)
    if not isinstance(v, int) or isinstance(v, bool) or v < minimum:
        raise _art_err(label, key, f"must be an int >= {minimum}, got {v!r}")
    return v


def _load_schedule_artifact_impl(source, verify: bool,
                                 ) -> Tuple[CompiledSchedule, Dict[str, object],
                                            List[List[Action]], str]:
    art, label = _load_artifact_dict(source)
    # --- schema: every mismatch is a located ScheduleError, never a numpy
    # broadcasting error (tested with truncated columns / float cells).
    ver = art.get("artifact_version")
    if ver != SCHEDULE_ARTIFACT_VERSION:
        raise _art_err(label, "artifact_version",
                       f"unsupported version {ver!r} "
                       f"(this build reads {SCHEDULE_ARTIFACT_VERSION})")
    if art.get("kind") != SCHEDULE_ARTIFACT_KIND:
        raise _art_err(label, "kind",
                       f"expected {SCHEDULE_ARTIFACT_KIND!r}, got "
                       f"{art.get('kind')!r}")
    name = art.get("name")
    if not isinstance(name, str) or not name:
        raise _art_err(label, "name", f"must be a non-empty string, got {name!r}")
    D = _validated_int(art, label, "n_devices", 1)
    V = _validated_int(art, label, "n_virtual", 1)
    M = _validated_int(art, label, "n_microbatches", 1)
    n_act = _validated_int(art, label, "n_act_slots", 1)
    n_grad = _validated_int(art, label, "n_grad_slots", 1)
    makespan = _validated_int(art, label, "makespan", 1)
    placement = art.get("placement")
    if placement not in ("wrap", "vshape"):
        raise _art_err(label, "placement",
                       f"must be 'wrap' or 'vshape', got {placement!r}")
    split = art.get("split_backward")
    if not isinstance(split, bool):
        raise _art_err(label, "split_backward", f"must be a bool, got {split!r}")
    if not isinstance(art.get("table_digest"), str):
        raise _art_err(label, "table_digest", "must be a hex string")
    # --- stale-fingerprint check over the metadata fields, before any
    # numpy work: an edited field (say n_microbatches) fails here.
    fp = art.get("config_fingerprint")
    want_fp = _artifact_fingerprint(art)
    if fp != want_fp:
        raise _art_err(
            label, "config_fingerprint",
            "stale fingerprint: metadata was edited after the artifact was "
            f"signed (stored {str(fp)[:12]!r}, recomputed {want_fp[:12]!r})")
    # --- table structure: shape / dtype / column count.
    raw = art.get("table")
    if not isinstance(raw, list) or not raw:
        raise _art_err(label, "table",
                       f"must be a non-empty [T][D][{N_COLS}] nested list")
    try:
        arr = np.asarray(raw)
    except Exception as e:  # ragged nesting
        raise _art_err(label, "table", f"not a rectangular array: {e}")
    if arr.dtype == object or arr.ndim != 3:
        raise _art_err(label, "table",
                       f"must be rank-3 [T, D, {N_COLS}], got shape "
                       f"{arr.shape} ({arr.dtype})")
    if not np.issubdtype(arr.dtype, np.integer):
        raise _art_err(label, "table",
                       f"dtype mismatch: cells must be integers, got {arr.dtype}")
    if arr.shape[2] != N_COLS:
        raise _art_err(label, "table",
                       f"column-count mismatch: {arr.shape[2]} columns != "
                       f"N_COLS {N_COLS}")
    if arr.shape[1] != D:
        raise _art_err(label, "table",
                       f"shape mismatch: {arr.shape[1]} device rows != "
                       f"n_devices {D}")
    if arr.shape[0] != makespan:
        raise _art_err(label, "table",
                       f"shape mismatch: {arr.shape[0]} ticks != makespan "
                       f"{makespan}")
    if (arr < -1).any():
        t, d, c = (int(x) for x in np.argwhere(arr < -1)[0])
        raise _art_err(label, "table",
                       f"cell (device {d}, tick {t}, col {c}) = "
                       f"{int(arr[t, d, c])} is below -1")
    table = arr.astype(np.int32)
    # --- orders.
    raw_orders = art.get("orders")
    if not isinstance(raw_orders, list) or len(raw_orders) != D:
        raise _art_err(label, "orders",
                       f"must be a list of {D} per-device action lists, got "
                       f"{type(raw_orders).__name__} of length "
                       f"{len(raw_orders) if isinstance(raw_orders, list) else '?'}")
    orders: List[List[Action]] = []
    for d, dev in enumerate(raw_orders):
        if not isinstance(dev, list):
            raise _art_err(label, f"orders[{d}]", "must be a list")
        out: List[Action] = []
        for i, item in enumerate(dev):
            if (not isinstance(item, (list, tuple)) or len(item) != 3
                    or not isinstance(item[0], int) or isinstance(item[0], bool)
                    or item[1] not in (F, B, W)
                    or not isinstance(item[2], int) or isinstance(item[2], bool)):
                raise _art_err(label, f"orders[{d}][{i}]",
                               f"must be [stage:int, op in 'FBW', mb:int], "
                               f"got {item!r}")
            out.append(Action(int(item[0]), str(item[1]), int(item[2])))
        orders.append(out)
    # --- recompile the orders (the authoritative source) and certify the
    # stored table against the result, cell by cell.
    try:
        cs = compile_order(name, orders, D, V, M, split_backward=split,
                           placement=placement)
    except ScheduleError as e:
        raise ScheduleError(
            f"schedule artifact {label}: orders do not compile: {e}")
    if cs.n_act_slots != n_act:
        raise _art_err(label, "n_act_slots",
                       f"{n_act} != recompiled {cs.n_act_slots}")
    if cs.n_grad_slots != n_grad:
        raise _art_err(label, "n_grad_slots",
                       f"{n_grad} != recompiled {cs.n_grad_slots}")
    if cs.table.shape != table.shape or not np.array_equal(cs.table, table):
        k = min(cs.table.shape[0], table.shape[0])
        diff = np.argwhere(cs.table[:k] != table[:k])
        if diff.size:
            t, d, c = (int(x) for x in diff[0])
            col = _column_label(c)
            raise ScheduleError(
                f"schedule artifact {label}: certification failed at "
                f"(device {d}, tick {t}, {col}): stored cell "
                f"{int(table[t, d, c])} != certified value "
                f"{int(cs.table[t, d, c])} (table tampered or stale)")
        raise _art_err(label, "table",
                       f"tick count {table.shape[0]} != recompiled "
                       f"{cs.table.shape[0]}")
    if art["table_digest"] != table_digest(table):
        raise _art_err(label, "table_digest",
                       "digest does not match the stored table")
    # --- full static certification (and embedded-report consistency).
    if verify:
        from ..analysis.table_check import check_table
        report = check_table(cs)
        if report.hazards:
            h = report.hazards[0]
            raise ScheduleError(
                f"schedule artifact {label}: certification failed: {h}")
        emb = art.get("table_report")
        if emb is not None:
            if not isinstance(emb, dict):
                raise _art_err(label, "table_report", "must be an object")
            if emb.get("ok") is False or emb.get("n_hazards", 0):
                raise _art_err(label, "table_report",
                               "embeds a non-clean TableReport; refusing to "
                               "load an uncertified artifact")
            summary = report.summary()
            for key in ("makespan", "predicted_ppermutes"):
                if key in emb and emb[key] != summary[key]:
                    raise _art_err(label, f"table_report.{key}",
                                   f"{emb[key]!r} != recomputed "
                                   f"{summary[key]!r}")
    else:
        from ..analysis import maybe_verify_schedule  # DTPP_VERIFY_TABLES hook
        maybe_verify_schedule(cs)
    return cs, art, orders, label


def _column_label(c: int) -> str:
    try:
        from ..analysis.table_check import COLUMN_NAMES
        return COLUMN_NAMES.get(c, f"col {c}")
    except Exception:
        return f"col {c}"


def load_schedule_artifact(source, *, verify: bool = True) -> CompiledSchedule:
    """Load a schedule artifact (path or dict) into a CompiledSchedule.

    Validation order: JSON/schema (shape, dtype, column count) → metadata
    ``config_fingerprint`` → recompile-from-orders diff (any mutated table
    cell fails with its exact (device, tick, column)) → ``check_table``
    certification. Every failure is a located :class:`ScheduleError` naming
    the artifact and field. With ``verify=False`` the full ``check_table``
    pass is skipped but the structural checks still run and
    ``DTPP_VERIFY_TABLES`` re-verifies via the build-time hook.
    """
    cs, _art, _orders, _label = _load_schedule_artifact_impl(source, verify)
    return cs


def register_schedule_artifact(source, *, name: Optional[str] = None,
                               overwrite: bool = True) -> CompiledSchedule:
    """Load, certify, and register an artifact as a named schedule.

    After this, ``compile_schedule(name, D, V, M)`` (and therefore
    ``ScheduleConfig``/fit/sweep/bench) resolves the searched schedule like
    any built-in — but pinned: the compile path re-checks the table digest
    against the artifact, so the certified table cannot drift.
    """
    cs, art, orders, label = _load_schedule_artifact_impl(source, True)
    reg_name = name if name is not None else cs.name
    if cs.placement != "wrap":
        raise ScheduleError(
            f"schedule artifact {label}: only wrap-placement artifacts can "
            "be registered (vshape placement is reserved for the ZBV builtin)")

    def order_fn(D: int, V: int, M: int) -> List[List[Action]]:
        want = (cs.n_devices, cs.n_virtual, cs.n_microbatches)
        if (D, V, M) != want:
            raise ScheduleError(
                f"schedule {reg_name!r} was certified for n_devices={want[0]}, "
                f"n_virtual={want[1]}, n_microbatches={want[2]}; requested "
                f"({D}, {V}, {M}) — re-run the search for this config")
        return [list(order) for order in orders]

    register_schedule(reg_name, order_fn, split_backward=cs.split_backward,
                      overwrite=overwrite)
    _ARTIFACT_PINS[reg_name] = {
        "table_digest": str(art["table_digest"]),
        "config_fingerprint": str(art["config_fingerprint"]),
        "source": label,
    }
    if reg_name != cs.name:
        cs = dataclasses.replace(cs, name=reg_name)
    return cs


def registered_artifact_info(name: str) -> Optional[Dict[str, str]]:
    """Pin metadata (table digest / fingerprint / source) for an
    artifact-backed schedule name, or None."""
    info = _ARTIFACT_PINS.get(name)
    return dict(info) if info is not None else None


def verify_artifact_pin(cs: CompiledSchedule) -> None:
    """For artifact-backed schedule names, re-check the compiled table's
    digest against the certified pin. Called on every compile/ingest path
    so a re-registered order function (or a mutated registry) can never
    swap in an uncertified table under a certified name."""
    pin = _ARTIFACT_PINS.get(cs.name)
    if pin is None:
        return
    got = table_digest(cs.table)
    if got != pin["table_digest"]:
        raise ScheduleError(
            f"schedule {cs.name!r}: compiled table digest {got[:12]}... does "
            f"not match the certified artifact pin "
            f"{pin['table_digest'][:12]}... (source {pin['source']}) — the "
            "registered orders no longer produce the certified table")


# ---------------------------------------------------------------------------
# Phase compression: the periodic-steady-state structure of a tick table
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Phase:
    """One maximal periodic run of tick-table rows.

    Covers rows ``[start, start + period * reps)``. ``base`` is the first
    repetition's row block ``[period, D, n_cols]``; repetition ``k``
    (``0 <= k < reps``) is exactly ``base + k * stride`` — active entries
    (``>= 0``) advance affinely per repetition (microbatch counters step,
    slot indices step or, over a period spanning a full slot-reuse cycle,
    stay put), inactive entries stay ``-1`` (``stride`` is 0 there). The
    *pattern* — which units run and which transfer channels are live on
    each device at each period position — is ``base >= 0`` and is constant
    across repetitions by construction, which is what lets the executor
    compile ONE specialized body per pattern and drive the run as a
    ``lax.scan`` (``unroll_ticks="phases"``). Rows that match no period
    fall out of :func:`compress_schedule` as ``period=1, reps=1`` phases.
    """

    start: int
    period: int
    reps: int
    base: np.ndarray    # [period, D, n_cols] int32
    stride: np.ndarray  # [period, D, n_cols] int32; 0 on inactive entries

    @property
    def length(self) -> int:
        return self.period * self.reps

    def pattern_key(self) -> Tuple[int, bytes]:
        """Hashable identity of the active/idle structure: the executor
        compiles one tick body per distinct key (slot/microbatch VALUES are
        scanned inputs, only this mask shapes the program)."""
        return (self.period, (self.base >= 0).tobytes())


def rows_of(phase: Phase) -> np.ndarray:
    """Materialize one phase's rows ``[length, D, n_cols]`` from its
    descriptor alone (base + per-rep stride; no table reference)."""
    ks = np.arange(phase.reps, dtype=phase.base.dtype)
    blocks = phase.base[None] + ks[:, None, None, None] * phase.stride[None]
    return blocks.reshape(phase.reps * phase.period, *phase.base.shape[1:])


def replay_phases(phases: Sequence[Phase]) -> np.ndarray:
    """Reconstruct the full tick table from phase descriptors —
    :func:`compress_schedule`'s inverse, and the property the compression
    self-check (and tests/test_schedules.py) assert bit-exactly."""
    return np.concatenate([rows_of(p) for p in phases], axis=0)


def compress_schedule(table: np.ndarray,
                      max_period: Optional[int] = None) -> Tuple[Phase, ...]:
    """Segment a tick table into maximal periodic runs (:class:`Phase`).

    Every schedule we execute is warmup + a periodic steady state +
    cooldown (arXiv:2401.10241's zero-bubble family makes the periodicity
    explicit; the tabular view of arXiv:2605.24006 makes it statically
    detectable from the rows). A run of period ``p`` starting at ``t``
    requires, for each repetition ``k``: the active/idle mask of rows
    ``table[t+k*p : t+(k+1)*p]`` equals the first repetition's, and active
    entries advance affinely (``base + k * stride``). Mask-alternating
    steady states (1F1B's F/B interleave) land at ``p >= 2``; cyclic slot
    reuse is absorbed by a period spanning the whole reuse cycle (slot
    stride 0, microbatch stride = slots per cycle). Greedy: at each row
    take the (period, reps) with maximal coverage, smallest period on
    ties; rows matching no period become ``period=1, reps=1`` phases
    (warmup/cooldown transients). The result is self-checked against
    :func:`replay_phases` before being returned.
    """
    table = np.asarray(table)
    T = table.shape[0]
    if max_period is None:
        max_period = min(T // 2, 64)
    phases: List[Phase] = []
    t = 0
    while t < T:
        best = None  # (coverage, -period, period, reps, stride)
        rem = T - t
        for p in range(1, min(max_period, rem // 2) + 1):
            base = table[t:t + p]
            mask = base >= 0
            nxt = table[t + p:t + 2 * p]
            if ((nxt >= 0) != mask).any():
                continue
            stride = np.where(mask, nxt - base, 0).astype(table.dtype)
            if not np.array_equal(base + stride, nxt):
                continue  # inactive entries drifted (non -1 sentinel)
            reps = 2
            while t + (reps + 1) * p <= T:
                blk = table[t + reps * p:t + (reps + 1) * p]
                # mask equality is checked separately: an active entry
                # walking onto -1 by arithmetic coincidence must NOT count
                # as a match — the executor's per-position specialization
                # relies on the mask being constant across repetitions
                if (((blk >= 0) == mask).all()
                        and np.array_equal(blk, base + reps * stride)):
                    reps += 1
                else:
                    break
            cand = (p * reps, -p, p, reps, stride)
            if best is None or cand[:2] > best[:2]:
                best = cand
        if best is not None:
            _, _, p, reps, stride = best
            phases.append(Phase(t, p, reps, table[t:t + p].copy(), stride))
            t += p * reps
        else:
            phases.append(Phase(t, 1, 1, table[t:t + 1].copy(),
                                np.zeros((1,) + table.shape[1:],
                                         dtype=table.dtype)))
            t += 1
    out = tuple(phases)
    if not np.array_equal(replay_phases(out), table):  # pragma: no cover
        raise ScheduleError("phase compression self-check failed: replay "
                            "does not reconstruct the tick table")
    return out


def phase_stats(phases: Sequence[Phase]) -> Dict[str, int]:
    """Compression summary: total rows, phase count, and the number of
    distinct patterns (= tick bodies the phase executor compiles, before
    the successor-mask refinement that may add a couple more)."""
    return {
        "n_rows": sum(p.length for p in phases),
        "n_phases": len(phases),
        "n_unique_patterns": len({p.pattern_key() for p in phases}),
    }


def phase_spans(phases: Sequence[Phase]) -> List[Tuple[int, int]]:
    """``[(start_tick, n_ticks)]`` per phase — the tick-axis alignment a
    measured per-phase timeline (``utils.telemetry``) is interpreted on.
    Spans tile ``[0, makespan)`` contiguously (compression invariant)."""
    return [(p.start, p.length) for p in phases]


def table_unit_activity(table: np.ndarray) -> np.ndarray:
    """Classify every (tick, device) cell of a tick table as F/B/W/idle.

    Returns ``[T, D, 4]`` 0/1 with the last axis ordered (F, B, W, idle).
    Works on both the 4-column forward-only table (col 2 is the forward
    microbatch) and the >=13-column training table (``COL_FWD_M`` /
    ``COL_BWD_M`` / ``COL_W_M``). A cell doing several units in one tick
    (e.g. B and W fused on non-split schedules' backward) counts each
    active op; ``idle`` is set only when no unit runs. This is the
    attribution mask that maps measured segment durations onto stages and
    ops (the measured counterpart of :func:`simulated_bubble`'s weights).
    """
    table = np.asarray(table)
    if table.ndim != 3:
        raise ScheduleError(f"expected [T, D, n_cols] table, got shape "
                            f"{table.shape}")
    n_cols = table.shape[2]
    f = table[:, :, COL_FWD_M] >= 0 if n_cols > COL_FWD_M else (
        table[:, :, n_cols - 2] >= 0)
    b = (table[:, :, COL_BWD_M] >= 0 if n_cols > COL_BWD_M
         else np.zeros(table.shape[:2], bool))
    w = (table[:, :, COL_W_M] >= 0 if n_cols > COL_W_M
         else np.zeros(table.shape[:2], bool))
    idle = ~(f | b | w)
    return np.stack([f, b, w, idle], axis=-1).astype(np.int64)


def phase_unit_activity(phases: Sequence[Phase]) -> np.ndarray:
    """Per-phase, per-device tick counts in (F, B, W, idle): ``[n_phases,
    D, 4]``. The weights that spread one phase's *measured* duration over
    stages and ops — see ``utils.telemetry.PipelineTelemetry
    .stage_breakdown``."""
    return np.stack([table_unit_activity(rows_of(p)).sum(axis=0)
                     for p in phases])


# Ring channels in the executor's recv-register order: (bank column,
# buffer kind). The recv register itself is the "second edge-slot buffer"
# of the double-buffered discipline — an arrival rides it across the tick
# until its bank stage, so the hop that produced it overlaps compute.
OVERLAP_CHANNELS: Tuple[Tuple[int, str], ...] = (
    (COL_STORE_F_SLOT, "act"),
    (COL_STORE_B_SLOT, "grad"),
    (COL_STORE_F_NEG_SLOT, "act"),
    (COL_STORE_B_POS_SLOT, "grad"),
)

# Bank stages: where within a tick a channel's arrival is committed from
# its recv register into the edge slot. Stage k means "immediately before
# unit k" with units ordered F(0), B(1), W(2); stage 3 is end-of-tick
# (just before the next hops replace the registers). Stage 0 is the
# lockstep discipline; later stages let the producing ppermute overlap
# this tick's earlier units.
BANK_BEFORE_F, BANK_BEFORE_B, BANK_BEFORE_W, BANK_END = 0, 1, 2, 3


def overlap_bank_stages(table: np.ndarray) -> np.ndarray:
    """Latest-safe bank stage per (tick, ring channel): ``[T, 4]`` int8.

    For each tick and each of the four ring channels (order =
    :data:`OVERLAP_CHANNELS`, matching the executor's recv registers),
    computes the latest point in the tick at which the arrival can be
    committed to its edge slot without changing any unit's inputs or the
    final buffer state — i.e. the earliest same-tick *conflict* with the
    banked slot, minimized across devices (SPMD: one program, one bank
    site per channel per tick). Conflicts, per device, against the
    device's banked slot ``s``:

    - the F unit (stage 0) reads AND writes ``act_buf[COL_FWD_SLOT]``
      and (vshape routes) writes ``act_buf[COL_FWD_LOCAL_SLOT]``;
      banking must precede a write so the unit's write lands last
      (write-last ordering of the lockstep tick is preserved).
    - the B unit (stage 1) reads ``act_buf[COL_BWD_ASLOT]`` and
      ``grad_buf[COL_BWD_GSLOT]``, and (vshape) writes
      ``grad_buf[COL_BWD_LOCAL_SLOT]``.
    - the W unit (stage 2) reads ``act_buf[COL_W_ASLOT]`` and
      ``grad_buf[COL_W_GSLOT]``.

    No conflict => stage 3 (end of tick). Banking EARLIER than the
    returned stage is always lockstep-correct, so the cross-device min is
    conservative and the staged executor is bit-identical to the lockstep
    one by construction. This classifier is the single source of truth:
    the executor banks at these stages, ``analysis.table_check`` verifies
    the register lifetime under them, and ``analysis.cost_model``'s
    ``comm_overlap`` mode derives per-tick overlappable hop time from
    them.
    """
    table = np.asarray(table)
    if table.ndim != 3 or table.shape[2] < N_COLS:
        raise ScheduleError(
            f"overlap_bank_stages needs a [T, D, {N_COLS}] training table, "
            f"got shape {table.shape}")
    T, D, _ = table.shape
    out = np.full((T, len(OVERLAP_CHANNELS)), BANK_END, dtype=np.int8)
    f_on = table[:, :, COL_FWD_M] >= 0
    b_on = table[:, :, COL_BWD_M] >= 0
    w_on = table[:, :, COL_W_M] >= 0
    # (stage, active-mask, slot-column, buffer kind); writes behave like
    # reads here — both pin the bank before the unit that touches the slot.
    touches = (
        (BANK_BEFORE_F, f_on, COL_FWD_SLOT, "act"),
        (BANK_BEFORE_F, table[:, :, COL_FWD_LOCAL_SLOT] >= 0,
         COL_FWD_LOCAL_SLOT, "act"),
        (BANK_BEFORE_B, b_on, COL_BWD_ASLOT, "act"),
        (BANK_BEFORE_B, b_on, COL_BWD_GSLOT, "grad"),
        (BANK_BEFORE_B, table[:, :, COL_BWD_LOCAL_SLOT] >= 0,
         COL_BWD_LOCAL_SLOT, "grad"),
        (BANK_BEFORE_W, w_on, COL_W_ASLOT, "act"),
        (BANK_BEFORE_W, w_on, COL_W_GSLOT, "grad"),
    )
    for ci, (bank_col, kind) in enumerate(OVERLAP_CHANNELS):
        slots = table[:, :, bank_col]          # [T, D]; -1 = no bank
        banked = slots >= 0
        if not banked.any():
            continue
        stage = np.full((T, D), BANK_END, dtype=np.int8)
        for st, on, slot_col, k in touches:
            if k != kind:
                continue
            hit = banked & on & (table[:, :, slot_col] == slots)
            stage = np.where(hit, np.minimum(stage, st), stage)
        stage = np.where(banked, stage, BANK_END)
        out[:, ci] = stage.min(axis=1)
    # Two channels of the same buffer landing in the SAME slot on the same
    # tick must keep their lockstep write order; forcing equal stages makes
    # the in-stage channel order (= lockstep order) decide.
    for i, j in ((0, 2), (1, 3)):
        si = table[:, :, OVERLAP_CHANNELS[i][0]]
        sj = table[:, :, OVERLAP_CHANNELS[j][0]]
        clash = ((si >= 0) & (sj >= 0) & (si == sj)).any(axis=1)
        if clash.any():
            m = np.minimum(out[:, i], out[:, j])
            out[:, i] = np.where(clash, m, out[:, i])
            out[:, j] = np.where(clash, m, out[:, j])
    return out


def phase_bank_stages(phase: Phase,
                      bank_stages: np.ndarray) -> np.ndarray:
    """Fold a table-wide ``[T, 4]`` bank-stage map onto one phase's period
    positions: ``[period, 4]``, min across repetitions AND across every
    tick the table maps to the position (conservative => lockstep-correct
    for all of them). The phase executor compiles one body per (pattern,
    successor-mask, bank-stage) triple and banks at these stages."""
    rows = bank_stages[phase.start:phase.start + phase.length]
    return rows.reshape(phase.reps, phase.period, -1).min(axis=0)


# ---------------------------------------------------------------------------
# Bubble analytics
# ---------------------------------------------------------------------------


def analytic_bubble_fraction(name: str, n_devices: int, n_virtual: int,
                             n_microbatches: int,
                             cs: "CompiledSchedule" = None) -> float:
    """Ideal bubble fraction in unit-cost ticks.

    GPipe / 1F1B: (D-1)/(M + D - 1) — the classic fill/drain bubble (1F1B
    matches GPipe's bubble; its win is activation memory, SURVEY.md §6 note).
    Interleaved / BFS: warmup/cooldown offsets stay proportional to D-1 while
    per-device work grows to 2MV ticks -> (D-1)/(M*V + D-1).

    ZB-H1 / ZB-V (closed forms, derived for THIS executor's work model —
    stage 0's dgrad ``B`` is elided, so device 0 genuinely runs M fewer
    actions than the papers' uniform-work accounting):

    - makespan at the papers' optimum, with our explicit 1-tick ppermute
      transit: ``3M + D - 1`` (H1) / ``6M + D - 1`` (V — the first
      microbatch pays the ramp once; the V placement returns the cotangent
      chain to device 0 with no extra turnaround).
    - mean per-device busy work: ``3M - M/D`` (H1) / ``6M - M/D`` (V).
    - mean bubble = 1 - busy/makespan. Note this *mean* counts device 0's
      elided-dgrad idle ticks as bubble even though they are a work
      *saving*, so it exceeds the papers' (D-1)/(3M + D-1)-style numbers
      by construction; the makespan factor is the apples-to-apples check.

    tests/test_zero_bubble.py asserts the compiled tables MEET these
    closed forms (north star: measured == analytic), which makes the
    check meaningful for exactly the schedules claiming the lowest
    bubbles (VERDICT r2 item 5).
    """
    D, M = n_devices, n_microbatches
    if name in _CUSTOM_SCHEDULES:
        # no closed form for arbitrary registered orders: report the
        # unit-cost tick simulation, which IS the executor's time model
        # (pass the caller's already-compiled ``cs`` to skip a recompile)
        if cs is None:
            cs = compile_schedule(name, D, n_virtual, M)
        return simulated_bubble(cs, w_f=1.0, w_b=1.0, w_w=1.0)[
            "bubble_fraction"]
    if name == "ZBH1":
        return 1.0 - (3 * M - M / D) / (3 * M + D - 1)
    if name == "ZBV":
        return 1.0 - (6 * M - M / D) / (6 * M + D - 1)
    V = n_virtual if name in ("Interleaved1F1B", "BFS") else 1
    return (D - 1) / (M * V + D - 1)


def paper_bubble_fraction(name: str, n_devices: int, n_virtual: int,
                          n_microbatches: int) -> float:
    """The PAPER-comparable bubble under uniform-work accounting.

    :func:`analytic_bubble_fraction`'s ZB numbers price device 0's elided
    dgrad as idle (this executor genuinely skips it — a work saving the
    per-device mean counts as bubble), so they are NOT comparable to the
    zero-bubble paper's figures or to this repo's pre-round-3 reports.
    This twin reports the classic ``1 - uniform_busy/makespan`` form on the
    same makespans — ``(D-1)/(3M+D-1)`` for ZB-H1, ``(D-1)/(6M+D-1)`` for
    ZB-V — and equals :func:`analytic_bubble_fraction` for every other
    builtin. Sweep CSVs / docs citing a ZB bubble should say which form
    they use (docs/schedules.md shows both)."""
    D, M = n_devices, n_microbatches
    if name == "ZBH1":
        return (D - 1) / (3 * M + D - 1)
    if name == "ZBV":
        return (D - 1) / (6 * M + D - 1)
    return analytic_bubble_fraction(name, n_devices, n_virtual,
                                    n_microbatches)


def simulated_bubble(cs: CompiledSchedule, w_f: float = 1.0,
                     w_b: float = 2.0, w_w: float = 1.0) -> Dict[str, float]:
    """Bubble measured on the compiled tick schedule under a cost model where
    a forward tick costs ``w_f``, a backward tick ``w_b`` and a wgrad tick
    ``w_w``. The default ``w_b=2`` is the STORED-backward cost model (~2
    grad-work forward-equivalents, no recompute) — the same per-action
    weight as the reference's torch-autograd runtime and as
    :func:`async_makespan`'s default, so the two models compare like for
    like. NOTE the executor's own D>1 default is the REMATERIALIZING
    backward (``pipeline.make_pipeline_grad_fn``), whose model is
    ``w_b=3`` (1 recompute + ~2 grad-work) — pass it explicitly when
    modeling a default multi-device run (``utils.sweep`` does, recording
    the weight used in its ``bubble_sim_w_b`` column). ``w_b=1`` is the
    unit-cost textbook model (= :func:`analytic_bubble_fraction`);
    ``w_b~=w_f`` fits split schedules whose B is dgrad-only. Lockstep
    SPMD: each tick lasts as long as its most expensive active device
    (the pessimistic bound — on hardware the ppermute dependency is
    pairwise, so realized makespans sit between this and
    :func:`async_makespan`)."""
    T = cs.makespan
    tick_cost = np.zeros(T + 1)
    busy = np.zeros(cs.n_devices)
    weight = {F: w_f, B: w_b, W: w_w}
    for a, t in cs.ticks.items():
        w = weight[a.op]
        d = a.stage % cs.n_devices
        tick_cost[t] = max(tick_cost[t], w)
        busy[d] += w
    makespan = float(tick_cost.sum())
    per_device = 1.0 - busy / makespan
    return {
        "makespan": makespan,
        "bubble_fraction": float(per_device.mean()),
        "bubble_fraction_max": float(per_device.max()),
    }


def async_makespan(name: str, n_devices: int, n_virtual: int,
                   n_microbatches: int, w_f: float = 1.0, w_b: float = 2.0,
                   w_w: float = 1.0, comm: float = 0.0) -> float:
    """Makespan of a schedule's per-device action orders under an **async**
    runtime model: each device advances through its own action list as soon
    as that action's dependencies have arrived — no lockstep tick barrier.

    This is the execution model of the reference's
    ``torch.distributed.pipelining`` runtime (async batched P2P, activation
    stash — so ``w_b=2``, a plain backward), as opposed to this framework's
    lockstep scan executor (``simulated_bubble``, ``w_b=3`` remat). Costs
    are per *action*; with V virtual chunks each action covers 1/V of the
    per-device layers, so cross-V comparisons scale weights by 1/V (see
    ``predicted_throughput``). Used to reconcile the reference's published
    schedule orderings with this executor's (docs/results.md).
    """
    D, V, M = n_devices, n_virtual, n_microbatches
    S = D * V
    # NOTE: comm is charged on every inter-stage hop; a vshape (ZBV)
    # placement's same-device chunk boundary would need placement-aware
    # exemption if comm > 0 matters there.
    orders = build_order(name, D, V, M)
    end: Dict[Action, float] = {}
    free = [0.0] * D
    ptr = [0] * D
    scale = 1.0 / V
    weight = {F: w_f * scale, B: w_b * scale, W: w_w * scale}

    def dep_ends(a: Action):
        if a.op == F:
            if a.stage == 0:
                return [0.0]
            dep = Action(a.stage - 1, F, a.microbatch)
            return [end[dep] + comm] if dep in end else None
        if a.op == W:
            # wgrad needs its own dgrad's cotangent (stage 0 has no B under
            # the split convention: it takes the B(1, m) arrival instead)
            dep = (Action(1, B, a.microbatch) if a.stage == 0
                   else Action(a.stage, B, a.microbatch))
            if dep not in end:
                return None
            return [end[dep] + (comm if a.stage == 0 else 0.0)]
        # B: forward stashed on-device + upstream cotangent arrival
        fw = Action(a.stage, F, a.microbatch)
        if fw not in end:
            return None
        needs = [end[fw]]
        if a.stage < S - 1:
            up = Action(a.stage + 1, B, a.microbatch)
            if up not in end:
                return None
            needs.append(end[up] + comm)
        return needs

    remaining = sum(len(o) for o in orders)
    while remaining:
        progressed = False
        for d in range(D):
            while ptr[d] < len(orders[d]):
                a = orders[d][ptr[d]]
                deps = dep_ends(a)
                if deps is None:
                    break
                start = max([free[d]] + deps)
                end[a] = start + weight[a.op]
                free[d] = end[a]
                ptr[d] += 1
                remaining -= 1
                progressed = True
        if not progressed:
            raise ScheduleError(f"async simulation deadlocked for {name} "
                                f"(D={D}, V={V}, M={M})")
    return max(free)


def predicted_throughput(name: str, n_devices: int, n_virtual: int,
                         n_microbatches: int, tokens_per_step: int,
                         w_f: float = 1.0, w_b: float = 2.0,
                         comm: float = 0.0) -> float:
    """Relative throughput prediction from :func:`async_makespan` (async /
    stash cost model — the reference runtime's): tokens per unit time where
    one unit = one full-model microbatch forward. Comparable across
    schedules and V at fixed (D, M, model)."""
    ms = async_makespan(name, n_devices, n_virtual, n_microbatches,
                        w_f=w_f, w_b=w_b, comm=comm)
    return tokens_per_step / ms
