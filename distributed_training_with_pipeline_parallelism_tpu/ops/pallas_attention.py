"""Fused flash-attention (forward + backward) in Pallas (Mosaic) for TPU.

This is the framework's native-kernel layer — the TPU analog of the C++/ATen
kernels the reference leans on through torch (SURVEY.md §2.3: "if a custom
native kernel layer is wanted ... it is Pallas (Mosaic) kernels"). The
forward computes softmax(QK^T/sqrt(d))V one query block at a time with the
online softmax recurrence (Dao et al., arXiv:2205.14135), so the [s, s]
score matrix never hits HBM: per grid step it lives in VMEM as a
[block_q, block_k] tile feeding the MXU. The forward also emits the per-row
logsumexp (lse), which is what makes the backward flash too.

Backward (the real flash backward, not dense recompute): with o and lse
saved, ``delta = rowsum(do * o)`` and the probabilities rebuild blockwise as
``p = exp(s - lse)`` — no second online-softmax pass and no [s, s]
materialization anywhere:

- ``dq`` kernel: grid (batch*heads, q blocks); each instance loops over the
  live k blocks accumulating ``dq += (p * (do v^T - delta)) k``.
- ``dk/dv`` kernel: grid (batch*heads, k blocks); each instance loops over
  the live q blocks accumulating ``dv += p^T do`` and
  ``dk += (p * (do v^T - delta))^T q``.

Causal masking prunes both loops to live blocks (at/below the diagonal for
dq, at/right of it for dk/dv), and a sliding ``window`` tightens both
bounds, so backward compute scales the same way forward does.

Measured kernel disciplines (rounds 3-4, one v5e chip — docs/profiles/):

- **MXU**: every dot keeps its inputs in the storage dtype (bf16 on the
  ladder configs) with f32 accumulation via ``preferred_element_type`` —
  f32 matmul inputs run the v5e MXU at a fraction of bf16 throughput.
  Softmax statistics (m, l, lse) stay f32.
- **VPU**: at head_dim 64 these kernels are vector-unit-bound (~256 MXU
  FLOPs but ~10 vector ops per score element against a ~50:1 MXU:VPU
  peak ratio at the corrected 197 TFLOP/s bf16 peak), so per-score-element
  vector work is minimized three ways (round 4):
  1. **exp2 domain**: the softmax scale and the ``log2(e)`` factor inside
     every ``exp`` fold into ONE constant applied to the [block_q, head_dim]
     q tile (``qc = q * scale*log2e``), so the per-element path is
     ``exp2(s2 - m2)`` with no multiply — the saved lse is log2-domain
     (internal: it only ever feeds these backward kernels).
  2. **static diagonal split**: on the plain causal training path
     (bq == bk, no padding/window) the one diagonal block per loop is
     peeled out STATICALLY — interior blocks run with no mask arithmetic
     at all, and the diagonal applies a precomputed additive 0/NEG_INF
     tile (one add/elem instead of compare+select). A scalar `lax.cond`
     gate was measured SLOWER (it costs Mosaic its k-loop software
     pipelining: fwd 1.16 -> 1.66 ms at gpt2-small shapes); the static
     peel has no branch. Other paths keep the k-block-invariant
     difference-tile mask (one compare per edge, scalar-broadcast).
     Masked scores go to NEG_INF so ``exp2`` underflows dead elements to
     exactly 0.0; dead-row guards are only paid where a fully-dead first
     block is reachable (a sliding window's left edge).
  3. **one-sweep backward**: dq, dk and dv come out of a single kernel
     gridded over k blocks. The q-block loop accumulates dk/dv in
     registers and dq into a grid-revisited f32 VMEM output block
     (index map ignores the k-grid axis; zeroed at k==0), so the scores,
     probabilities and dp are computed ONCE per (q, k) block pair instead
     of twice (the round-3 form ran separate dq and dk/dv kernels, each
     redoing s, exp and dp — 7 block matmuls and ~2x the VPU work per
     pair vs 5 matmuls here).

Layout (round 4): the training hot path (plain causal, full-length,
head_dim 64/128) runs the HEAD-PACKED kernels — inputs stay [b, s, h*dh]
exactly as the projection matmul wrote them, each grid instance owns a
128-lane-aligned slab of 128//head_dim heads, and the body unrolls the
slab's heads with static lane slices. That removes the
[b,s,h,dh] -> [b*h,s,dh] relayouts around every kernel (~10% of a GPT-2
step) AND the fusion barrier they imposed: gpt2-small device step
126.2 -> 117.2 ms. (The r3 full-head-per-instance attempt was slower
because its per-head BlockSpecs made lane-MISALIGNED strided reads; the
aligned slab is a clean DMA.) Other shapes (windows, ragged tails,
bq != bk, odd head dims) fall back to the classic [b*h, s, dh] form
plus explicit transposes.

On non-TPU backends the kernels run in interpreter mode so CPU CI exercises
the same code paths.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30
LOG2E = 1.4426950408889634


def _use_interpret() -> bool:
    plat = jax.devices()[0].platform
    return plat not in ("tpu", "axon")


def _make_block_mask(qi_base, block_shape, causal: bool, true_len: int,
                     seq_len: int, window: Optional[int]):
    """Per-grid-instance score-mask factory (or None if nothing masks).
    See the module docstring's VPU discipline for why it is shaped this
    way."""
    if not causal and true_len == seq_len and window is None:
        return None
    rows = jax.lax.broadcasted_iota(jnp.int32, block_shape, 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, block_shape, 1)
    rc = rows - cols  # = (abs_row - abs_col) - (qi_base - ki_base)

    def mask(s, ki_base):
        off = ki_base - qi_base
        keep = None
        if causal:
            keep = rc >= off  # abs_row >= abs_col
        if window is not None:
            w = rc < off + window  # abs_row - abs_col < window
            keep = w if keep is None else keep & w
        if true_len != seq_len:
            pad = cols < true_len - ki_base  # abs_col < true_len
            keep = pad if keep is None else keep & pad
        return jnp.where(keep, s, NEG_INF)

    return mask


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                      causal: bool, scale: float, seq_len: int,
                      true_len: int, window: Optional[int]):
    qi = pl.program_id(1)
    # exp2-domain scores: scale*log2e folds into the [block_q, dh] q tile
    # so the per-element softmax path has no multiplies (module docstring)
    q = (q_ref[0].astype(jnp.float32) * (scale * LOG2E)).astype(q_ref.dtype)
    block_q = q.shape[0]
    dh = q.shape[1]

    n_kv = pl.cdiv(seq_len, block_k)  # seq_len is padded to a block multiple
    if causal:
        # highest k block that the last query row of this block can see
        n_kv_live = jax.lax.min(n_kv, ((qi + 1) * block_q + block_k - 1) // block_k)
    else:
        n_kv_live = n_kv
    if window is not None:
        # lowest k block the FIRST query row of this block can still see:
        # its oldest visible key is qi*block_q - (window - 1)
        kv_start = jax.lax.max(0, (qi * block_q - (window - 1)) // block_k)
    else:
        kv_start = 0

    mask = _make_block_mask(qi * block_q, (block_q, block_k), causal,
                            true_len, seq_len, window)
    # A fully-dead row in a block is only a correctness hazard while its
    # running max is still NEG_INF (exp2(s - m) = exp2(0) = 1 instead of 0).
    # The first visited block always has a live element in every row —
    # causal's block 0 contains column 0; padding keeps column 0 live —
    # EXCEPT at a sliding window's left edge, where the top rows of the
    # q block may open strictly later than kv_start. Only that case pays
    # the dead-row guards.
    guard_dead_rows = window is not None
    # Static diagonal split (the plain causal/full training path,
    # bq == bk, no padding/window): interior blocks are fully live — NO
    # mask arithmetic at all — and the single diagonal block applies a
    # precomputed ADDITIVE tile (one add/elem instead of compare+select).
    diag_split = (causal and block_q == block_k and true_len == seq_len
                  and window is None)

    def make_body(msk):
        def body(ki, carry):
            m, l, acc = carry
            k = k_ref[0, pl.ds(ki * block_k, block_k), :]
            v = v_ref[0, pl.ds(ki * block_k, block_k), :]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # [bq, bk] log2-domain
            if msk is not None:
                s = msk(s, ki * block_k)
            m_blk = jnp.max(s, axis=1)
            m_new = jnp.maximum(m, m_blk)
            p = jnp.exp2(s - m_new[:, None])
            alpha = jnp.exp2(m - m_new)
            if guard_dead_rows:
                p = jnp.where(s <= NEG_INF / 2, 0.0, p)
                alpha = jnp.where(m <= NEG_INF / 2, 0.0, alpha)
            l_new = l * alpha + jnp.sum(p, axis=1)
            acc_new = acc * alpha[:, None] + jax.lax.dot(
                p.astype(v.dtype), v, preferred_element_type=jnp.float32)
            return m_new, l_new, acc_new
        return body

    carry0 = (jnp.full((block_q,), NEG_INF, jnp.float32),
              jnp.zeros((block_q,), jnp.float32),
              jnp.zeros((block_q, dh), jnp.float32))
    if diag_split:
        # diagonal tile: rc >= 0 is instance-invariant at bq == bk
        rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        diag_add = jnp.where(rows >= cols, 0.0, NEG_INF)
        m, l, acc = jax.lax.fori_loop(0, qi, make_body(None), carry0)
        m, l, acc = make_body(lambda s, _: s + diag_add)(qi, (m, l, acc))
    else:
        m, l, acc = jax.lax.fori_loop(kv_start, n_kv_live, make_body(mask),
                                      carry0)
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)
    # per-row logsumexp of the (scaled, masked) scores, in LOG2 domain
    # (= log2 sum_j 2^{s2_j}; only the backward kernels consume it). Rides
    # as [bh, 1, s_pad] (rank-3) because Mosaic requires the last two block
    # dims to tile (8, 128) or equal the array dims
    lse_ref[0, 0] = m + jnp.log2(l)


def _pad_to_blocks(s: int, block_q: int, block_k: int) -> int:
    blk = math.lcm(block_q, block_k)
    return -(-s // blk) * blk


def _flash_fwd(q: jax.Array, k: jax.Array, v: jax.Array, causal: bool,
               block_q: int, block_k: int,
               window: Optional[int] = None):
    """q, k, v: [bh, s, dh] -> (out [bh, s, dh], lse [bh, 1, s_pad]). Ragged s
    (not a block multiple) is zero-padded up front; padded key columns are
    masked dead in-kernel and padded query rows are sliced off the output
    (the lse stays padded — it only feeds the backward kernels, which slice
    consistently)."""
    bh, s, dh = q.shape
    scale = 1.0 / (dh ** 0.5)
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    s_pad = _pad_to_blocks(s, block_q, block_k)
    if s_pad != s:
        pad = ((0, 0), (0, s_pad - s), (0, 0))
        q, k, v = (jnp.pad(x, pad) for x in (q, k, v))
    grid = (bh, s_pad // block_q)
    kernel = functools.partial(_flash_fwd_kernel, block_k=block_k,
                               causal=causal, scale=scale, seq_len=s_pad,
                               true_len=s, window=window)
    out, lse = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct((bh, 1, s_pad), jnp.float32)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, dh), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, s_pad, dh), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, s_pad, dh), lambda i, j: (i, 0, 0)),
        ],
        out_specs=(pl.BlockSpec((1, block_q, dh), lambda i, j: (i, j, 0)),
                   pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j))),
        interpret=_use_interpret(),
    )(q, k, v)
    return out[:, :s, :], lse


def _flash_bwd_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dk_ref, dv_ref, *, block_q: int, causal: bool,
                      scale: float, seq_len: int, true_len: int,
                      window: Optional[int]):
    """One-sweep backward: grid (batch*heads, k blocks). Each instance owns
    one k block, loops over its live q blocks, accumulates dk/dv in f32
    carries, and accumulates dq into a grid-revisited f32 VMEM output block
    (its index map ignores the k-grid axis, so the block stays resident
    across the sweep; zeroed when the sweep starts). Scores, probabilities
    and dp are computed once per (q, k) block pair — the round-3 two-kernel
    form computed each twice."""
    ki = pl.program_id(1)
    k = k_ref[0]  # [block_k, dh], storage dtype
    v = v_ref[0]
    block_k = k.shape[0]
    dh = k.shape[1]
    c = scale * LOG2E  # exp2-domain fold, matching the forward's lse

    @pl.when(ki == 0)
    def _zero_dq():
        dq_ref[...] = jnp.zeros_like(dq_ref)

    n_q = pl.cdiv(seq_len, block_q)
    if causal:
        # first q block whose last row can see this k block's first key
        q_start = (ki * block_k) // block_q
    else:
        q_start = 0
    if window is not None:
        # last q row that still sees this block's newest key is
        # ki*block_k + block_k - 1 + window - 1
        q_stop = jax.lax.min(
            n_q, (ki * block_k + block_k - 1 + window - 1) // block_q + 1)
    else:
        q_stop = n_q

    mask_needed = causal or true_len != seq_len or window is not None
    if mask_needed:
        # this kernel's grid walks ki (fixed per instance), so the
        # loop-invariant tile is rc_k = row_iota - abs_col; each edge is
        # then one scalar-broadcast compare against the varying qi offset
        shape = (block_q, block_k)
        col_abs = (ki * block_k
                   + jax.lax.broadcasted_iota(jnp.int32, shape, 1))
        rc_k = jax.lax.broadcasted_iota(jnp.int32, shape, 0) - col_abs
        pad_cols = col_abs < true_len if true_len != seq_len else None

    def apply_mask(s, qi):
        keep = None
        if causal:
            keep = rc_k >= -qi * block_q  # abs_row >= abs_col
        if window is not None:
            w = rc_k < window - qi * block_q
            keep = w if keep is None else keep & w
        if pad_cols is not None:
            keep = pad_cols if keep is None else keep & pad_cols
        return jnp.where(keep, s, NEG_INF)

    def make_body(msk):
        def body(qi, carry):
            dk_acc, dv_acc = carry
            qs = q_ref[0, pl.ds(qi * block_q, block_q), :]  # unscaled
            qc = (qs.astype(jnp.float32) * c).astype(qs.dtype)
            do = do_ref[0, pl.ds(qi * block_q, block_q), :]
            lse = lse_ref[0, 0, pl.ds(qi * block_q, block_q)]  # log2-domain
            delta = delta_ref[0, 0, pl.ds(qi * block_q, block_q)]
            s = jax.lax.dot_general(
                qc, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # [bq, bk] log2-domain
            if msk is not None:
                s = msk(s, qi)
            # padded q rows carry do = 0, so their (finite-garbage) p rows
            # contribute exactly 0 everywhere; dead elements underflow to 0
            # (every live row's lse is finite — its diagonal is always live)
            p = jnp.exp2(s - lse[:, None])
            dv_new = dv_acc + jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # [bq, bk] f32
            ds = p * (dp - delta[:, None])
            dsb = ds.astype(qs.dtype)
            dk_new = dk_acc + jax.lax.dot_general(
                dsb, qs, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            # dq rides unscaled f32; the caller applies `scale` (fused by
            # XLA into the cast/transpose that follows the kernel)
            dq_ref[0, pl.ds(qi * block_q, block_q), :] += jax.lax.dot(
                dsb, k, preferred_element_type=jnp.float32)
            return dk_new, dv_new
        return body

    dk0 = jnp.zeros((block_k, dh), jnp.float32)
    dv0 = jnp.zeros((block_k, dh), jnp.float32)
    # Static diagonal split, mirroring the forward: with bq == bk on the
    # plain causal/full path this instance's FIRST live q block (qi == ki)
    # is the diagonal — an instance-invariant additive tile — and every
    # later q block is fully live with no mask arithmetic at all.
    diag_split = (causal and block_q == block_k and true_len == seq_len
                  and window is None)
    if diag_split:
        diag_add = jnp.where(rc_k >= -ki * block_q, 0.0, NEG_INF)
        carry = make_body(lambda s, _: s + diag_add)(q_start, (dk0, dv0))
        dk, dv = jax.lax.fori_loop(q_start + 1, q_stop, make_body(None),
                                   carry)
    else:
        dk, dv = jax.lax.fori_loop(
            q_start, q_stop,
            make_body(apply_mask if mask_needed else None), (dk0, dv0))
    # qs was unscaled in the dk dot, so the scale applies once here
    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, g, causal, block_q, block_k, window):
    """Blockwise dq/dk/dv from saved (o, lse): the [s, s] matrix never
    materializes. Inputs [bh, s, dh] unpadded; lse [bh, 1, s_pad] (padded,
    log2-domain, from the forward). One fused kernel produces all three
    grads (see _flash_bwd_kernel)."""
    bh, s, dh = q.shape
    scale = 1.0 / (dh ** 0.5)
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    s_pad = _pad_to_blocks(s, block_q, block_k)
    # delta_i = rowsum(do_i * o_i) in f32 — O(s*dh), the only non-kernel work
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)[:, None, :]  # [bh, 1, s] (rank-3, see lse note)
    if s_pad != s:
        pad3 = ((0, 0), (0, s_pad - s), (0, 0))
        q, k, v, g = (jnp.pad(x, pad3) for x in (q, k, v, g))
        delta = jnp.pad(delta, ((0, 0), (0, 0), (0, s_pad - s)))
    dq, dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_kernel, block_q=block_q, causal=causal,
                          scale=scale, seq_len=s_pad, true_len=s,
                          window=window),
        out_shape=(jax.ShapeDtypeStruct(q.shape, jnp.float32),  # dq, f32
                   jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)),
        grid=(bh, s_pad // block_k),
        in_specs=[
            pl.BlockSpec((1, s_pad, dh), lambda i, j: (i, 0, 0)),     # q
            pl.BlockSpec((1, block_k, dh), lambda i, j: (i, j, 0)),   # k
            pl.BlockSpec((1, block_k, dh), lambda i, j: (i, j, 0)),   # v
            pl.BlockSpec((1, s_pad, dh), lambda i, j: (i, 0, 0)),     # do
            pl.BlockSpec((1, 1, s_pad), lambda i, j: (i, 0, 0)),      # lse
            pl.BlockSpec((1, 1, s_pad), lambda i, j: (i, 0, 0)),      # delta
        ],
        out_specs=(
            # dq: revisited across the k-grid axis (accumulator)
            pl.BlockSpec((1, s_pad, dh), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, block_k, dh), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, dh), lambda i, j: (i, j, 0)),
        ),
        interpret=_use_interpret(),
    )(q, k, v, g, lse, delta)
    # the deferred `scale` fold (see kernel docstring); XLA fuses it into
    # the cast + transpose that follow
    dq = (dq * scale).astype(q.dtype)
    return dq[:, :s, :], dk[:, :s, :], dv[:, :s, :]


# ---------------------------------------------------------------------------
# Head-packed (transpose-free) kernels — round 4.
#
# The classic form above wants [b*h, s, dh] inputs, which costs explicit
# [b,s,h,dh] -> [b*h,s,dh] relayouts around every kernel (~10% of a GPT-2
# train step at 77% HBM; docs/profiles/). Here the heads STAY where the
# projection matmul wrote them: inputs are [b, s, h*dh] (a free reshape),
# each grid instance owns a 128-lane-ALIGNED slab of HP = 128//dh heads
# (the r3 full-head variant was slow because its per-head BlockSpecs were
# lane-misaligned strided reads; a 128-lane slab is a clean DMA), and the
# kernel unrolls the HP heads in its body with per-head lane slices.
# Plain-causal full-length path only (the training hot path); everything
# else falls back to the transpose form.
# ---------------------------------------------------------------------------


# The packed kernels keep whole [s, h*dh] head-slabs resident in VMEM per
# batch grid cell (q, k, v, o, do plus the f32 dq accumulator in the
# backward). Measured cliff on v5e (round 5, h*dh = 768 bf16): the
# backward compiles at s = 5120 (7.9 MB/slab) and fails at s = 6144
# (9.4 MB/slab), so cap the slab at 8 MB and fall back to the classic
# per-(batch, head) form — whose K/V residency is [s, dh], 1 MB at
# s = 8192 — beyond it. The fallback pays the head transpose relayouts
# (~10% at GPT-2 shapes) but compiles at any sequence length.
_PACKED_SLAB_LIMIT_BYTES = 8 * 1024 * 1024


def _packed_ok(s, h, dh, causal, window, block_q, block_k, itemsize=2):
    hp = 128 // dh if dh in (64, 128) else 0
    return (causal and window is None and hp > 0 and h % max(hp, 1) == 0
            and block_q == block_k and s % block_q == 0
            and s * h * dh * itemsize <= _PACKED_SLAB_LIMIT_BYTES
            # Mosaic lowering constraint on the packed-lse BlockSpec
            # (1, 1, hp, block_q): its last block dim must tile 128 lanes
            # or span the whole array dim
            and (block_q % 128 == 0 or block_q == s))


def _flash_fwd_kernel_packed(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                             block_k: int, dh: int, hp: int, scale: float,
                             seq_len: int):
    qi = pl.program_id(1)
    q2 = q_ref[0]  # [block_q, hp*dh]
    block_q = q2.shape[0]
    c = scale * LOG2E
    rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    diag_add = jnp.where(rows >= cols, 0.0, NEG_INF)

    for p in range(hp):
        sl = slice(p * dh, (p + 1) * dh)
        qh = (q2[:, sl].astype(jnp.float32) * c).astype(q2.dtype)

        def body(ki, carry, msk=None):
            m, l, acc = carry
            k = k_ref[0, pl.ds(ki * block_k, block_k), sl]
            v = v_ref[0, pl.ds(ki * block_k, block_k), sl]
            s = jax.lax.dot_general(
                qh, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            if msk is not None:
                s = s + msk
            m_blk = jnp.max(s, axis=1)
            m_new = jnp.maximum(m, m_blk)
            pr = jnp.exp2(s - m_new[:, None])
            alpha = jnp.exp2(m - m_new)
            l_new = l * alpha + jnp.sum(pr, axis=1)
            acc_new = acc * alpha[:, None] + jax.lax.dot(
                pr.astype(v.dtype), v, preferred_element_type=jnp.float32)
            return m_new, l_new, acc_new

        carry0 = (jnp.full((block_q,), NEG_INF, jnp.float32),
                  jnp.zeros((block_q,), jnp.float32),
                  jnp.zeros((block_q, dh), jnp.float32))
        m, l, acc = jax.lax.fori_loop(0, qi, body, carry0)
        m, l, acc = body(qi, (m, l, acc), msk=diag_add)
        l = jnp.maximum(l, 1e-30)
        o_ref[0, :, sl] = (acc / l[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0, p, :] = m + jnp.log2(l)


def _flash_bwd_kernel_packed(q_ref, k_ref, v_ref, do_ref, lse_ref,
                             delta_ref, dq_ref, dk_ref, dv_ref, *,
                             block_q: int, dh: int, hp: int, scale: float,
                             seq_len: int):
    ki = pl.program_id(1)
    block_k = k_ref.shape[1]
    n_q = seq_len // block_q
    c = scale * LOG2E

    @pl.when(ki == 0)
    def _zero_dq():
        dq_ref[...] = jnp.zeros_like(dq_ref)

    rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    diag_add = jnp.where(rows >= cols, 0.0, NEG_INF)

    for p in range(hp):
        sl = slice(p * dh, (p + 1) * dh)
        k = k_ref[0, :, sl]
        v = v_ref[0, :, sl]

        def body(qi, carry, msk=None):
            dk_acc, dv_acc = carry
            qs = q_ref[0, pl.ds(qi * block_q, block_q), sl]
            qc = (qs.astype(jnp.float32) * c).astype(qs.dtype)
            do = do_ref[0, pl.ds(qi * block_q, block_q), sl]
            lse = lse_ref[0, 0, p, pl.ds(qi * block_q, block_q)]
            delta = delta_ref[0, 0, p, pl.ds(qi * block_q, block_q)]
            s = jax.lax.dot_general(
                qc, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            if msk is not None:
                s = s + msk
            pr = jnp.exp2(s - lse[:, None])
            dv_new = dv_acc + jax.lax.dot_general(
                pr.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            ds = pr * (dp - delta[:, None])
            dsb = ds.astype(qs.dtype)
            dk_new = dk_acc + jax.lax.dot_general(
                dsb, qs, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dq_ref[0, pl.ds(qi * block_q, block_q), sl] += jax.lax.dot(
                dsb, k, preferred_element_type=jnp.float32)
            return dk_new, dv_new

        carry0 = (jnp.zeros((block_k, dh), jnp.float32),
                  jnp.zeros((block_k, dh), jnp.float32))
        carry = body(ki, carry0, msk=diag_add)  # diagonal (q_start == ki)
        dk, dv = jax.lax.fori_loop(ki + 1, n_q, body, carry)
        dk_ref[0, :, sl] = (dk * scale).astype(dk_ref.dtype)
        dv_ref[0, :, sl] = dv.astype(dv_ref.dtype)


def _flash_fwd_packed(q, k, v, h, block_q, block_k):
    """q, k, v: [b, s, h*dh] -> (out [b, s, h*dh], lse [b, nhp, HP, s])."""
    b, s, hd = q.shape
    dh = hd // h
    hp = 128 // dh
    nhp = h // hp
    scale = 1.0 / (dh ** 0.5)
    grid = (b * nhp, s // block_q)
    kernel = functools.partial(_flash_fwd_kernel_packed, block_k=block_k,
                               dh=dh, hp=hp, scale=scale, seq_len=s)
    slab = hp * dh  # = 128 lanes

    out, lse = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct((b, nhp, hp, s), jnp.float32)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, slab),
                         lambda i, j: (i // nhp, j, i % nhp)),
            pl.BlockSpec((1, s, slab), lambda i, j: (i // nhp, 0, i % nhp)),
            pl.BlockSpec((1, s, slab), lambda i, j: (i // nhp, 0, i % nhp)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, slab),
                         lambda i, j: (i // nhp, j, i % nhp)),
            pl.BlockSpec((1, 1, hp, block_q),
                         lambda i, j: (i // nhp, i % nhp, 0, j)),
        ),
        interpret=_use_interpret(),
    )(q, k, v)
    return out, lse


def _flash_bwd_packed(q, k, v, o, lse, g, h, block_q, block_k):
    b, s, hd = q.shape
    dh = hd // h
    hp = 128 // dh
    nhp = h // hp
    scale = 1.0 / (dh ** 0.5)
    slab = hp * dh
    # per-head delta = rowsum(do_h * o_h): [b, s, h] -> [b, nhp, hp, s]
    delta = jnp.sum((g.astype(jnp.float32) * o.astype(jnp.float32))
                    .reshape(b, s, h, dh), axis=-1)
    delta = delta.reshape(b, s, nhp, hp).transpose(0, 2, 3, 1)
    kernel = functools.partial(_flash_bwd_kernel_packed, block_q=block_q,
                               dh=dh, hp=hp, scale=scale, seq_len=s)
    dq, dk, dv = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct(q.shape, jnp.float32),  # dq f32
                   jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)),
        grid=(b * nhp, s // block_k),
        in_specs=[
            pl.BlockSpec((1, s, slab), lambda i, j: (i // nhp, 0, i % nhp)),
            pl.BlockSpec((1, block_k, slab),
                         lambda i, j: (i // nhp, j, i % nhp)),
            pl.BlockSpec((1, block_k, slab),
                         lambda i, j: (i // nhp, j, i % nhp)),
            pl.BlockSpec((1, s, slab), lambda i, j: (i // nhp, 0, i % nhp)),
            pl.BlockSpec((1, 1, hp, s), lambda i, j: (i // nhp, i % nhp,
                                                      0, 0)),
            pl.BlockSpec((1, 1, hp, s), lambda i, j: (i // nhp, i % nhp,
                                                      0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, s, slab), lambda i, j: (i // nhp, 0, i % nhp)),
            pl.BlockSpec((1, block_k, slab),
                         lambda i, j: (i // nhp, j, i % nhp)),
            pl.BlockSpec((1, block_k, slab),
                         lambda i, j: (i // nhp, j, i % nhp)),
        ),
        interpret=_use_interpret(),
    )(q, k, v, g, lse, delta)
    return (dq * scale).astype(q.dtype), dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_packed(q, k, v, h, block_q, block_k):
    out, _ = _flash_fwd_packed(q, k, v, h, block_q, block_k)
    return out


def _flash_packed_vjp_fwd(q, k, v, h, block_q, block_k):
    out, lse = _flash_fwd_packed(q, k, v, h, block_q, block_k)
    return out, (q, k, v, out, lse)


def _flash_packed_vjp_bwd(h, block_q, block_k, res, g):
    q, k, v, o, lse = res
    return _flash_bwd_packed(q, k, v, o, lse, g, h, block_q, block_k)


_flash_packed.defvjp(_flash_packed_vjp_fwd, _flash_packed_vjp_bwd)


def _dense_attention(q, k, v, causal, window=None):
    """Reference path in plain XLA (f32 accumulation) for tests/benchmarks."""
    dh = q.shape[-1]
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / (dh ** 0.5)
    if causal:
        from .attention import band_mask
        s = jnp.where(band_mask(s.shape[-2], s.shape[-1], window)[None],
                      s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, window):
    out, _ = _flash_fwd(q, k, v, causal, block_q, block_k, window)
    return out


def _flash_vjp_fwd(q, k, v, causal, block_q, block_k, window):
    out, lse = _flash_fwd(q, k, v, causal, block_q, block_k, window)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(causal, block_q, block_k, window, res, g):
    q, k, v, o, lse = res
    return _flash_bwd(q, k, v, o, lse, g, causal, block_q, block_k, window)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def _auto_block(s: int) -> int:
    """Default kernel block (v5e measurements, docs/performance.md).

    s <= 1024: ONE block covers the whole row — no interior k loop, the
    diagonal-split tile is the entire score matrix; measured fastest
    (round 4: fwd 0.94 -> 0.83 ms at gpt2-small shapes vs 512 blocks,
    and `min(block, s)` keeps short rows unpadded). Beyond 1024 the
    [bq, bk] f32 tiles exceed VMEM at block 1024 (the backward fails to
    compile) and 512 measured up to ~20% (fwd) / ~34% (grad) faster per
    row than 256; estimated time ~ padded_length / per-row-speed, so 256
    wins only where its padding saving exceeds 512's ~1.2x per-row
    advantage (s=1280: 1280 vs 1536/1.2 -> 256; s=2600: -> 512).
    Where the PADDED block-512 row length reaches 8192, 256 is forced:
    COMPOSED train-step programs (flash backward custom-calls next to
    the weight-grad dots) crash the v5e compiler at block 512 with
    8192-long rows — the isolated kernel compiles at any block, the
    failure needs the surrounding fusion, and block 256 compiles
    (round-5 bisection; s=7168 with 512 is fine). The check uses the
    padded length because the kernels pad ragged rows up to a block
    multiple, so s=7700 would compile the same crash-prone 8192-row
    block-512 shape. Per-row speed is secondary to compiling at all."""
    if s <= 1024:
        return 1024
    if -(-s // 512) * 512 >= 8192:
        return 256
    if -(-s // 256) * 256 * 1.2 <= -(-s // 512) * 512:
        return 256
    return 512


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False, block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    window: Optional[int] = None) -> jax.Array:
    """Fused attention: q, k, v [batch, seq, heads, head_dim] -> same shape.

    Drop-in replacement for the dense attention inside
    ``ops.attention.mha_apply`` (GQA repeat must happen before the call);
    differentiable with a fully-blockwise Pallas backward (see module
    docstring). EXPLICIT blocks below 128 lower on real TPUs only when
    the block spans the whole (padded) sequence — the rank-3 lse
    BlockSpec's last dim must tile 128 lanes or equal the array dim
    (Mosaic constraint; :func:`_auto_block`'s 256/512/1024 are always
    safe, and CPU interpret mode takes any block, which is what the
    small-block unit tests use). ``block_q``/``block_k`` default to :func:`_auto_block`
    (512, or 256 where it avoids a dead padding block); both kernels keep
    one [block_q, block_k] f32 tile plus the full per-(batch, head) K/V
    in VMEM, so block size trades tile-reuse against grid parallelism,
    not memory. ``window`` (requires ``causal``) applies the Mistral
    sliding-window band: both directions skip K/V (resp. Q) blocks entirely
    outside ``[i - window + 1, i]``, so long-sequence *compute* scales with
    the window. K/V VMEM residency still scales with the sequence (the
    whole [s, dh] K/V maps in per (batch, head)); truly long sequences
    should shard over a 'seq' mesh axis instead (ring attention).
    """
    if window is not None and (not causal or window < 1):
        raise ValueError("window requires causal attention and window >= 1")
    b, s, h, dh = q.shape
    # AUTO blocks clamp to the sequence so short full-length rows
    # (s <= 1024, where _auto_block returns 1024) still satisfy
    # _packed_ok's s % block_q == 0 and take the transpose-free packed
    # path (block_q == s is an admissible packed-lse config under the
    # Mosaic lane constraint). EXPLICIT blocks are taken literally: a
    # caller-tuned block larger than the sequence is a config error, and
    # silently clamping it made "why is my tuned block ignored?"
    # undiagnosable (ADVICE r5) — raise instead.
    for name, blk in (("block_q", block_q), ("block_k", block_k)):
        if blk is not None and blk > s:
            raise ValueError(
                f"explicit {name}={blk} exceeds the sequence length {s}; "
                f"pass {name}=None to let _auto_block pick (auto blocks "
                f"clamp to the sequence)")
    block_q = block_q or min(_auto_block(s), s)
    block_k = block_k or min(_auto_block(s), s)
    if _packed_ok(s, h, dh, causal, window, block_q, block_k,
                  q.dtype.itemsize):
        # transpose-free path: heads stay packed in the lane dimension
        # (see _flash_packed) — the [b,s,h,dh]->[b*h,s,dh] relayouts this
        # call otherwise pays were ~10% of a GPT-2 train step
        def pack(x):
            return x.reshape(b, s, h * dh)

        out = _flash_packed(pack(q), pack(k), pack(v), h, block_q, block_k)
        return out.reshape(b, s, h, dh)

    def flat(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, dh)

    out = _flash(flat(q), flat(k), flat(v), causal, block_q, block_k, window)
    return out.reshape(b, h, s, dh).transpose(0, 2, 1, 3)
