"""Fused flash-attention kernel in Pallas (Mosaic) for TPU.

This is the framework's native-kernel layer — the TPU analog of the C++/ATen
kernels the reference leans on through torch (SURVEY.md §2.3: "if a custom
native kernel layer is wanted ... it is Pallas (Mosaic) kernels"). The kernel
computes softmax(QK^T/sqrt(d))V one query block at a time with the online
softmax recurrence (Dao et al., arXiv:2205.14135), so the [s, s] score matrix
never hits HBM: per grid step it lives in VMEM as a [block_q, block_k] tile
feeding the MXU.

Layout: the grid is (batch*heads, seq/block_q); each kernel instance holds
its query block plus the full K/V for that (batch, head) in VMEM and loops
over K/V blocks with ``jax.lax.fori_loop`` + ``pl.ds`` dynamic slices.
Causal masking prunes the loop to blocks at or below the diagonal.

Training support: ``flash_attention`` carries a ``jax.custom_vjp`` whose
backward recomputes attention blockwise in plain XLA (flash-style
rematerialization of the forward, dense [s, s] scores per (b, h) tile in the
bwd matmuls — exact, memory-bounded by the backward tile, not by the kernel).
On non-TPU backends the kernel runs in interpreter mode so CPU CI exercises
the same code path.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _use_interpret() -> bool:
    plat = jax.devices()[0].platform
    return plat not in ("tpu", "axon")


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int,
                      causal: bool, scale: float, seq_len: int,
                      true_len: int, window: Optional[int]):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # [block_q, dh]
    block_q = q.shape[0]
    dh = q.shape[1]

    n_kv = pl.cdiv(seq_len, block_k)  # seq_len is padded to a block multiple
    if causal:
        # highest k block that the last query row of this block can see
        n_kv_live = jax.lax.min(n_kv, ((qi + 1) * block_q + block_k - 1) // block_k)
    else:
        n_kv_live = n_kv
    if window is not None:
        # lowest k block the FIRST query row of this block can still see:
        # its oldest visible key is qi*block_q - (window - 1)
        kv_start = jax.lax.max(0, (qi * block_q - (window - 1)) // block_k)
    else:
        kv_start = 0

    def body(ki, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())))  # [bq, bk]
        if causal or true_len != seq_len:
            rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            keep = cols < true_len  # keys in the ragged padding are dead
            if causal:
                keep &= rows >= cols
                if window is not None:
                    keep &= rows - cols < window
            s = jnp.where(keep, s, NEG_INF)
        m_blk = jnp.max(s, axis=1)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        alpha = jnp.exp(m - m_new)
        alpha = jnp.where(m <= NEG_INF / 2, 0.0, alpha)
        l_new = l * alpha + jnp.sum(p, axis=1)
        acc_new = acc * alpha[:, None] + jax.lax.dot(p, v)
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, dh), jnp.float32)
    m, l, acc = jax.lax.fori_loop(kv_start, n_kv_live, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)


def _flash_fwd(q: jax.Array, k: jax.Array, v: jax.Array, causal: bool,
               block_q: int, block_k: int,
               window: Optional[int] = None) -> jax.Array:
    """q, k, v: [bh, s, dh] -> [bh, s, dh]. Ragged s (not a block multiple)
    is zero-padded up front; padded key columns are masked dead in-kernel
    and padded query rows are sliced off the output."""
    bh, s, dh = q.shape
    scale = 1.0 / (dh ** 0.5)
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    blk = math.lcm(block_q, block_k)
    s_pad = -(-s // blk) * blk
    if s_pad != s:
        pad = ((0, 0), (0, s_pad - s), (0, 0))
        q, k, v = (jnp.pad(x, pad) for x in (q, k, v))
    grid = (bh, s_pad // block_q)
    kernel = functools.partial(_flash_fwd_kernel, block_k=block_k,
                               causal=causal, scale=scale, seq_len=s_pad,
                               true_len=s, window=window)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, dh), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, s_pad, dh), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, s_pad, dh), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, dh), lambda i, j: (i, j, 0)),
        interpret=_use_interpret(),
    )(q, k, v)
    return out[:, :s, :]


def _dense_attention(q, k, v, causal, window=None):
    """Reference/backward path in plain XLA (f32 accumulation)."""
    dh = q.shape[-1]
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / (dh ** 0.5)
    if causal:
        from .attention import band_mask
        s = jnp.where(band_mask(s.shape[-2], s.shape[-1], window)[None],
                      s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, window):
    return _flash_fwd(q, k, v, causal, block_q, block_k, window)


def _flash_vjp_fwd(q, k, v, causal, block_q, block_k, window):
    return _flash_fwd(q, k, v, causal, block_q, block_k, window), (q, k, v)


def _flash_vjp_bwd(causal, block_q, block_k, window, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: _dense_attention(q, k, v, causal, window), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False, block_q: int = 256,
                    block_k: int = 256,
                    window: Optional[int] = None) -> jax.Array:
    """Fused attention: q, k, v [batch, seq, heads, head_dim] -> same shape.

    Drop-in replacement for the dense attention inside
    ``ops.attention.mha_apply`` (GQA repeat must happen before the call).
    ``window`` (requires ``causal``) applies the Mistral sliding-window
    band: the kernel skips K/V blocks entirely outside
    ``[i - window + 1, i]``, so long-sequence forward *compute* scales with
    the window. K/V VMEM residency still scales with the sequence (the
    whole [s, dh] K/V maps in per (batch, head)); truly long sequences
    should shard over a 'seq' mesh axis instead (ring attention).
    """
    if window is not None and (not causal or window < 1):
        raise ValueError("window requires causal attention and window >= 1")
    b, s, h, dh = q.shape

    def flat(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, dh)

    out = _flash(flat(q), flat(k), flat(v), causal, block_q, block_k, window)
    return out.reshape(b, h, s, dh).transpose(0, 2, 1, 3)
