"""Multi-head attention as a pure function.

Semantics match ``torch.nn.MultiheadAttention`` (batch_first): packed Q/K/V
projections, scaled dot-product over heads, output projection. Exposed as
separate q/k/v weight leaves so stage-stacking and tensor-parallel sharding
stay natural; the torch-parity test splits torch's packed ``in_proj_weight``
into these leaves.

Supports grouped-query attention (n_kv_heads < n_heads) and an optional RoPE
rotation for the Llama family.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from .layers import (dropout_apply, linear_init, linear_apply,
                     sharded_dropout_apply)


def mha_init(key: jax.Array, dim: int, n_heads: int, n_kv_heads: Optional[int] = None,
             bias: bool = True, o_bias: Optional[bool] = None,
             head_dim: Optional[int] = None) -> Dict:
    """``bias`` covers q/k/v; ``o_bias`` the output projection (defaults to
    ``bias`` — Qwen2-family blocks set bias=True, o_bias=False).
    ``head_dim`` decouples per-head width from ``dim // n_heads``
    (Gemma-family blocks)."""
    n_kv_heads = n_kv_heads or n_heads
    head_dim = head_dim or dim // n_heads
    kq, kk, kv, ko = jax.random.split(key, 4)
    return {
        "q": linear_init(kq, dim, n_heads * head_dim, bias=bias),
        "k": linear_init(kk, dim, n_kv_heads * head_dim, bias=bias),
        "v": linear_init(kv, dim, n_kv_heads * head_dim, bias=bias),
        "o": linear_init(ko, n_heads * head_dim, dim,
                         bias=bias if o_bias is None else o_bias),
    }


def _split_heads(x: jax.Array, n_heads: int) -> jax.Array:
    b, s, _ = x.shape
    return x.reshape(b, s, n_heads, -1)


def rope_frequencies(head_dim: int, max_seq_len: int, theta: float = 10000.0,
                     scaling: Optional[tuple] = None) -> jax.Array:
    """Precompute RoPE angles [max_seq_len, head_dim//2].

    ``scaling`` applies Llama-3.1 long-context frequency scaling — a tuple
    ``(factor, low_freq_factor, high_freq_factor,
    original_max_position_embeddings)`` matching transformers'
    ``rope_scaling`` with ``rope_type="llama3"``: wavelengths shorter than
    ``orig/high`` keep their frequency, longer than ``orig/low`` divide by
    ``factor``, and the band between interpolates smoothly.
    """
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    if scaling is not None:
        factor, low_f, high_f, orig_max = scaling
        wavelen = 2.0 * jnp.pi / inv
        smooth = (orig_max / wavelen - low_f) / (high_f - low_f)
        mid = (1.0 - smooth) * inv / factor + smooth * inv
        inv = jnp.where(wavelen > orig_max / low_f, inv / factor,
                        jnp.where(wavelen < orig_max / high_f, inv, mid))
    t = jnp.arange(max_seq_len, dtype=jnp.float32)
    return jnp.outer(t, inv)


def apply_rope(x: jax.Array, angles: jax.Array) -> jax.Array:
    """Rotate [b, s, h, d] query/key tensors by per-position angles [s, d//2]."""
    x1, x2 = jnp.split(x, 2, axis=-1)
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)  # rotation runs in f32; don't promote bf16 activations


def band_mask(n_q: int, n_k: int, window: Optional[int] = None,
              q_offset=0) -> jax.Array:
    """Causal [n_q, n_k] mask, optionally banded to a sliding window: query
    i (at global position q_offset + i) sees keys in
    ``[pos - window + 1, pos]``. The single source of the window
    convention — used by the dense train path, the flash kernel's backward,
    and the KV-cache decode path."""
    iq = q_offset + jnp.arange(n_q)[:, None]
    ik = jnp.arange(n_k)[None, :]
    mask = iq >= ik
    if window is not None:
        mask &= iq - ik < window
    return mask


def gqa_expand(k: jax.Array, v: jax.Array, n_heads: int):
    """Repeat kv heads up to n_heads for grouped-query attention (no-op for MHA)."""
    n_kv = k.shape[2]
    if n_kv != n_heads:
        rep = n_heads // n_kv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return k, v


def scaled_dot_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                         mask: Optional[jax.Array] = None,
                         dropout_rate: float = 0.0,
                         dropout_rng=None,
                         head_shard: Optional[tuple] = None) -> jax.Array:
    """Core attention: q [b,s,h,d] x k/v [b,t,h,d] -> [b,s,h,d].

    ``mask`` broadcasts against scores [b,h,s,t]; False positions are dropped.
    Shared by the training path (:func:`mha_apply`) and the KV-cache decode
    path (:mod:`..models.generate`) so the two cannot drift. Softmax runs in
    f32 regardless of activation dtype. ``dropout_rng`` (train mode) applies
    dropout to the attention probabilities, as torch's MultiheadAttention
    does with a nonzero ``dropout`` constructor arg. ``head_shard`` —
    ``(axis_name, n_shards)`` when the head dim is a tensor/sequence-parallel
    local shard — keys the dropout mask to the *global* head index so the
    sharded run reproduces the unsharded masks exactly.
    """
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], dtype=q.dtype))
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if mask is not None:
        scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
    # checkpoint: saves only the [b,h,s,t] scores for backward (the f32
    # softmax output and its compute-dtype copy — 3x the scores bytes —
    # are recomputed, a pointwise cost). Cuts every stored-activation
    # path's residual traffic; the flash kernel path never builds these.
    probs = jax.checkpoint(
        lambda s: jax.nn.softmax(s.astype(jnp.float32),
                                 axis=-1).astype(q.dtype))(scores)
    if head_shard is not None and head_shard[1] > 1:
        probs = sharded_dropout_apply(probs, dropout_rate, dropout_rng,
                                      axis=head_shard[0],
                                      n_shards=head_shard[1], shard_dim=1)
    else:
        probs = dropout_apply(probs, dropout_rate, dropout_rng)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def qkv_project(params: Dict, q_in: jax.Array, kv_in: jax.Array, n_heads: int,
                rope_angles: Optional[jax.Array] = None,
                expand_gqa: bool = True):
    """Shared attention prologue: linear q/k/v projections, head split,
    optional RoPE, optional GQA expansion. Used by the dense path
    (:func:`mha_apply`) and both sequence-parallel wrappers
    (``parallel.ring_attention`` / ``parallel.ulysses``) so the projection
    conventions cannot drift between them."""
    head_dim = params["q"]["w"].shape[1] // n_heads
    n_kv = params["k"]["w"].shape[1] // head_dim
    q = _split_heads(linear_apply(params["q"], q_in), n_heads)
    k = _split_heads(linear_apply(params["k"], kv_in), n_kv)
    v = _split_heads(linear_apply(params["v"], kv_in), n_kv)
    if rope_angles is not None:
        q = apply_rope(q, rope_angles)
        k = apply_rope(k, rope_angles)
    if expand_gqa:
        k, v = gqa_expand(k, v, n_heads)
    return q, k, v


def mha_apply(params: Dict, q_in: jax.Array, kv_in: jax.Array, n_heads: int,
              causal: bool = False, rope_angles: Optional[jax.Array] = None,
              flash: bool = False, tp_axis: Optional[str] = None,
              window: Optional[int] = None, dropout_rate: float = 0.0,
              dropout_rng=None, tp_size: int = 1) -> jax.Array:
    """Attention: queries from ``q_in``, keys/values from ``kv_in`` (both [b, s, d]).

    ``flash=True`` routes the core attention through the fused Pallas kernel
    (:mod:`.pallas_attention`) instead of dense XLA softmax-matmuls.

    ``tp_axis`` enables Megatron tensor parallelism inside a manual-SPMD
    region: the q/k/v/o weight leaves are the caller's *local shards*
    (heads column-split; ``n_heads`` is the local head count), the inputs
    are replicated (``tp_copy`` marks them so input cotangents sum), and
    the output projection is row-parallel (``tp_reduce`` completes it).
    """
    from .collectives import tp_attention_inputs, tp_output_projection
    q_in, kv_in = tp_attention_inputs(q_in, kv_in, tp_axis)
    q, k, v = qkv_project(params, q_in, kv_in, n_heads, rope_angles)
    if flash:
        if dropout_rng is not None and dropout_rate > 0.0:
            raise ValueError("flash attention does not support attention-prob "
                             "dropout (guarded in ModelConfig)")
        from .pallas_attention import flash_attention
        out = flash_attention(q, k, v, causal=causal, window=window)
    else:
        mask = None
        if causal:
            s = q_in.shape[1]
            mask = band_mask(s, s, window)[None, None]
        out = scaled_dot_attention(
            q, k, v, mask, dropout_rate, dropout_rng,
            head_shard=(tp_axis, tp_size) if tp_axis is not None else None)
    out = out.reshape(q_in.shape[0], q_in.shape[1], -1)
    return tp_output_projection(params["o"], out, tp_axis)
