"""Megatron-style conjugate collective pairs for manual-SPMD tensor
parallelism (Shoeybi et al., arXiv:1909.08053 §3: the f/g operators).

Inside ``shard_map`` the pipeline executor runs with replication checking
off, so AD through raw ``psum`` is easy to get subtly wrong; these wrap the
two patterns with explicit ``custom_vjp``s that encode the correct
transposes:

- :func:`tp_copy` — identity forward, **psum backward**. Marks a replicated
  activation entering column-parallel weights: each model shard contributes
  a partial input-cotangent that must be summed.
- :func:`tp_reduce` — **psum forward**, identity backward. Completes a
  row-parallel matmul: partial outputs are summed; the output cotangent is
  already replicated and flows to every shard unchanged.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_copy(x: jax.Array, axis_name: str) -> jax.Array:
    return x


def _copy_fwd(x, axis_name):
    return x, None


def _copy_bwd(axis_name, _, g):
    return (jax.lax.psum(g, axis_name),)


tp_copy.defvjp(_copy_fwd, _copy_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_reduce(x: jax.Array, axis_name: str) -> jax.Array:
    return jax.lax.psum(x, axis_name)


def _reduce_fwd(x, axis_name):
    return jax.lax.psum(x, axis_name), None


def _reduce_bwd(axis_name, _, g):
    return (g,)


tp_reduce.defvjp(_reduce_fwd, _reduce_bwd)


def row_parallel_linear(params, x: jax.Array, axis_name: str) -> jax.Array:
    """Row-parallel linear: local ``x @ w`` partial, psum over the model
    axis, then the (replicated) bias added once."""
    y = tp_reduce(x @ params["w"], axis_name)
    if "b" in params:
        y = y + params["b"]
    return y


def tp_attention_inputs(q_in, kv_in, tp_axis):
    """Megatron TP prologue shared by the dense and ring attention paths:
    mark replicated inputs entering column-parallel projections. For
    self-attention (same array) one copy suffices — one backward psum."""
    if tp_axis is None:
        return q_in, kv_in
    if kv_in is q_in:
        q_in = kv_in = tp_copy(q_in, tp_axis)
    else:
        q_in = tp_copy(q_in, tp_axis)
        kv_in = tp_copy(kv_in, tp_axis)
    return q_in, kv_in


def tp_output_projection(o_params, out, tp_axis):
    """Megatron TP epilogue shared by the dense and ring attention paths:
    plain linear when unsharded, row-parallel (psum + bias-once) under TP."""
    if tp_axis is None:
        from .layers import linear_apply
        return linear_apply(o_params, out)
    return row_parallel_linear(o_params, out, tp_axis)


def _vocab_parallel_nll(logits_local: jax.Array, targets: jax.Array,
                        axis_name: str) -> jax.Array:
    """Per-position NLL over a vocab-sharded logits tensor (Megatron
    parallel cross-entropy, arXiv:1909.08053 §3): each device holds a
    contiguous vocab slice ``[my*Vl, (my+1)*Vl)``; the full ``[..., V]``
    tensor never materializes. Shared core of the mean and ignore-index
    variants so their collective numerics cannot drift.

    The max for numerical stability is a stop-gradient pmax; logsumexp and
    the target logit each take one psum over ``axis_name``. Differentiable
    w.r.t. ``logits_local`` (grouped collectives: safe inside schedule
    conds).
    """
    v_local = logits_local.shape[-1]
    my = jax.lax.axis_index(axis_name)
    x = logits_local.astype(jnp.float32)
    # stop_gradient BEFORE the collective: pmax has no differentiation rule,
    # but with a symbolic-zero tangent it never needs one (the max is only
    # a numerical-stability shift anyway)
    m = jax.lax.pmax(
        jax.lax.stop_gradient(jnp.max(x, axis=-1)), axis_name)  # [...]
    # tp_reduce, not raw psum: under check_vma=False a psum inside a
    # differentiated region transposes to another psum (double-counting
    # cotangents); tp_reduce's custom VJP encodes the correct
    # psum-fwd/identity-bwd pair.
    lse = jnp.log(tp_reduce(
        jnp.sum(jnp.exp(x - m[..., None]), axis=-1), axis_name)) + m
    local_t = targets - my * v_local
    hit = (local_t >= 0) & (local_t < v_local)
    tl_part = jnp.take_along_axis(
        x, jnp.clip(local_t, 0, v_local - 1)[..., None], axis=-1)[..., 0]
    tl = tp_reduce(jnp.where(hit, tl_part, 0.0), axis_name)
    return lse - tl


def vocab_parallel_xent(logits_local: jax.Array, targets: jax.Array,
                        axis_name: str) -> jax.Array:
    """Mean token-wise cross entropy via :func:`_vocab_parallel_nll`."""
    return jnp.mean(_vocab_parallel_nll(logits_local, targets, axis_name))


def vocab_parallel_masked_xent_sum(logits_local: jax.Array,
                                   targets: jax.Array, axis_name: str,
                                   pad_id: int):
    """Ignore-index twin of :func:`vocab_parallel_xent`: NLL SUM over
    non-pad positions plus the valid count. Same (sum, count) contract as
    ``ops.layers.masked_xent_sum`` so the pipeline's global-valid-count
    normalization applies unchanged."""
    nll = _vocab_parallel_nll(logits_local, targets, axis_name)
    valid = targets != pad_id
    return jnp.sum(jnp.where(valid, nll, 0.0)), jnp.sum(valid)
