"""Megatron-style conjugate collective pairs for manual-SPMD tensor
parallelism (Shoeybi et al., arXiv:1909.08053 §3: the f/g operators).

Inside ``shard_map`` the pipeline executor runs with replication checking
off, so AD through raw ``psum`` is easy to get subtly wrong; these wrap the
two patterns with explicit ``custom_vjp``s that encode the correct
transposes:

- :func:`tp_copy` — identity forward, **psum backward**. Marks a replicated
  activation entering column-parallel weights: each model shard contributes
  a partial input-cotangent that must be summed.
- :func:`tp_reduce` — **psum forward**, identity backward. Completes a
  row-parallel matmul: partial outputs are summed; the output cotangent is
  already replicated and flows to every shard unchanged.

On top of the conjugate pairs, the **collective-matmul** forms overlap TP
communication with the matmuls that consume it (Wang et al.,
arXiv:2211.05102 — the "collective matmul" decomposition TPU compilers
apply to Megatron blocks):

- :func:`all_gather_matmul` — ``all_gather(x) @ w`` as a ring of
  ``axis_size - 1`` ppermute hops, each issued *before* the chunk matmul
  it overlaps with, so the gather rides under the up-projection;
- :func:`matmul_reduce_scatter` — ``reduce_scatter(z @ w)`` as the
  conjugate ring: partial chunk products accumulate along the ring, each
  hop overlapping the next chunk's down-projection matmul;
- :func:`seq_scatter` / :func:`seq_all_gather` — the replicated <->
  sequence-sharded boundary conversions (slice forward / ring gather
  forward, with the conjugate transposes as ``custom_vjp``s).

All ring forms are plain differentiable JAX (``ppermute`` has an exact
transpose), so backward passes get the same overlapped ring structure for
free, and the portable form runs bit-for-bit on the CPU proxy mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_copy(x: jax.Array, axis_name: str) -> jax.Array:
    return x


def _copy_fwd(x, axis_name):
    return x, None


def _copy_bwd(axis_name, _, g):
    return (jax.lax.psum(g, axis_name),)


tp_copy.defvjp(_copy_fwd, _copy_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_reduce(x: jax.Array, axis_name: str) -> jax.Array:
    return jax.lax.psum(x, axis_name)


def _reduce_fwd(x, axis_name):
    return jax.lax.psum(x, axis_name), None


def _reduce_bwd(axis_name, _, g):
    return (g,)


tp_reduce.defvjp(_reduce_fwd, _reduce_bwd)


def row_parallel_linear(params, x: jax.Array, axis_name: str) -> jax.Array:
    """Row-parallel linear: local ``x @ w`` partial, psum over the model
    axis, then the (replicated) bias added once."""
    y = tp_reduce(x @ params["w"], axis_name)
    if "b" in params:
        y = y + params["b"]
    return y


def tp_attention_inputs(q_in, kv_in, tp_axis):
    """Megatron TP prologue shared by the dense and ring attention paths:
    mark replicated inputs entering column-parallel projections. For
    self-attention (same array) one copy suffices — one backward psum."""
    if tp_axis is None:
        return q_in, kv_in
    if kv_in is q_in:
        q_in = kv_in = tp_copy(q_in, tp_axis)
    else:
        q_in = tp_copy(q_in, tp_axis)
        kv_in = tp_copy(kv_in, tp_axis)
    return q_in, kv_in


def tp_output_projection(o_params, out, tp_axis):
    """Megatron TP epilogue shared by the dense and ring attention paths:
    plain linear when unsharded, row-parallel (psum + bias-once) under TP."""
    if tp_axis is None:
        from .layers import linear_apply
        return linear_apply(o_params, out)
    return row_parallel_linear(o_params, out, tp_axis)


def _vocab_parallel_nll(logits_local: jax.Array, targets: jax.Array,
                        axis_name: str) -> jax.Array:
    """Per-position NLL over a vocab-sharded logits tensor (Megatron
    parallel cross-entropy, arXiv:1909.08053 §3): each device holds a
    contiguous vocab slice ``[my*Vl, (my+1)*Vl)``; the full ``[..., V]``
    tensor never materializes. Shared core of the mean and ignore-index
    variants so their collective numerics cannot drift.

    The max for numerical stability is a stop-gradient pmax; logsumexp and
    the target logit each take one psum over ``axis_name``. Differentiable
    w.r.t. ``logits_local`` (grouped collectives: safe inside schedule
    conds).
    """
    v_local = logits_local.shape[-1]
    my = jax.lax.axis_index(axis_name)
    x = logits_local.astype(jnp.float32)
    # stop_gradient BEFORE the collective: pmax has no differentiation rule,
    # but with a symbolic-zero tangent it never needs one (the max is only
    # a numerical-stability shift anyway)
    m = jax.lax.pmax(
        jax.lax.stop_gradient(jnp.max(x, axis=-1)), axis_name)  # [...]
    # tp_reduce, not raw psum: under check_vma=False a psum inside a
    # differentiated region transposes to another psum (double-counting
    # cotangents); tp_reduce's custom VJP encodes the correct
    # psum-fwd/identity-bwd pair.
    lse = jnp.log(tp_reduce(
        jnp.sum(jnp.exp(x - m[..., None]), axis=-1), axis_name)) + m
    local_t = targets - my * v_local
    hit = (local_t >= 0) & (local_t < v_local)
    tl_part = jnp.take_along_axis(
        x, jnp.clip(local_t, 0, v_local - 1)[..., None], axis=-1)[..., 0]
    tl = tp_reduce(jnp.where(hit, tl_part, 0.0), axis_name)
    return lse - tl


def vocab_parallel_xent(logits_local: jax.Array, targets: jax.Array,
                        axis_name: str) -> jax.Array:
    """Mean token-wise cross entropy via :func:`_vocab_parallel_nll`."""
    return jnp.mean(_vocab_parallel_nll(logits_local, targets, axis_name))


def vocab_parallel_masked_xent_sum(logits_local: jax.Array,
                                   targets: jax.Array, axis_name: str,
                                   pad_id: int):
    """Ignore-index twin of :func:`vocab_parallel_xent`: NLL SUM over
    non-pad positions plus the valid count. Same (sum, count) contract as
    ``ops.layers.masked_xent_sum`` so the pipeline's global-valid-count
    normalization applies unchanged."""
    nll = _vocab_parallel_nll(logits_local, targets, axis_name)
    valid = targets != pad_id
    return jnp.sum(jnp.where(valid, nll, 0.0)), jnp.sum(valid)


# ---------------------------------------------------------------------------
# Collective matmul: ring-overlapped all-gather/reduce-scatter fused with
# the projections that consume them (arXiv:2211.05102 §3.3)
# ---------------------------------------------------------------------------


def _ring_perm(axis_size: int, offset: int):
    return [(i, (i + offset) % axis_size) for i in range(axis_size)]


def all_gather_matmul(x_loc: jax.Array, w: jax.Array, axis_name: str,
                      axis_size: int) -> jax.Array:
    """``all_gather(x, seq) @ w`` with the gather overlapped into the matmul.

    ``x_loc``: this rank's sequence chunk ``[B, C, d]`` (chunk index =
    rank); ``w``: the column-parallel local shard ``[d, F_loc]``. Returns
    the full-sequence column-sharded product ``[B, T*C, F_loc]``.

    Ring decomposition: at step ``k`` the rank holds chunk ``(my + k) % T``
    — it issues the ppermute fetching the *next* chunk first, then runs
    the current chunk's matmul, so the hop and the matmul are independent
    ops the latency-hiding scheduler overlaps. Per-row-block matmul is
    exact, so the result is bit-identical to gather-then-matmul.
    Differentiable as-is: the transposed ring has the same overlapped
    structure (ppermute transposes to the inverse ppermute).
    """
    T = int(axis_size)
    my = jax.lax.axis_index(axis_name)
    B, C, _ = x_loc.shape
    out = jnp.zeros((B, T * C, w.shape[-1]), dtype=jnp.result_type(x_loc, w))
    # receive from (i+1): after k hops we hold chunk (my + k) % T
    perm = _ring_perm(T, -1)
    chunk = x_loc
    for k in range(T):
        nxt = (jax.lax.ppermute(chunk, axis_name, perm)
               if k + 1 < T else None)  # issued before the overlapping matmul
        blk = chunk @ w
        out = jax.lax.dynamic_update_slice_in_dim(
            out, blk, ((my + k) % T) * C, axis=1)
        chunk = nxt
    return out


def matmul_reduce_scatter(z: jax.Array, w: jax.Array, axis_name: str,
                          axis_size: int) -> jax.Array:
    """``reduce_scatter(z @ w, seq)`` with the scatter overlapped into the
    matmul.

    ``z``: full-sequence column-sharded activations ``[B, T*C, F_loc]``;
    ``w``: the row-parallel local shard ``[F_loc, d]``. Returns this
    rank's sequence chunk of the cross-rank partial sum ``[B, C, d]`` —
    i.e. chunk ``my`` of ``psum_r(z_r @ w_r)``.

    Ring decomposition: the accumulator travels the ``+1`` ring; at step
    ``k`` each rank adds its product for the chunk destined ``T - 1 - k``
    hops downstream, so every hop overlaps the next chunk's matmul.
    Summation order is the fixed ring order (deterministic, but not the
    same reduction tree as ``psum`` — parity with the unfused form is
    numerical, not bitwise).
    """
    T = int(axis_size)
    my = jax.lax.axis_index(axis_name)
    C = z.shape[1] // T
    perm = _ring_perm(T, +1)
    acc = None
    for k in range(T):
        idx = ((my - k - 1) % T) * C
        blk = jax.lax.dynamic_slice_in_dim(z, idx, C, axis=1) @ w
        # hop first (independent of this step's matmul), add after
        acc = blk if acc is None else jax.lax.ppermute(
            acc, axis_name, perm) + blk
    return acc


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def seq_scatter(x: jax.Array, axis_name: str, axis_size: int) -> jax.Array:
    """Replicated ``[B, S, d]`` -> this rank's sequence chunk
    ``[B, S/T, d]``. Free forward (a slice of a replicated value); the
    backward is the conjugate chunk gather — each rank's cotangent chunk
    is distinct, and the replicated input's cotangent is their
    concatenation."""
    my = jax.lax.axis_index(axis_name)
    c = x.shape[1] // axis_size
    return jax.lax.dynamic_slice_in_dim(x, my * c, c, axis=1)


def _seq_scatter_fwd(x, axis_name, axis_size):
    return seq_scatter(x, axis_name, axis_size), None


def _seq_scatter_bwd(axis_name, axis_size, _, g):
    return (seq_all_gather(g, axis_name, axis_size),)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def seq_all_gather(x_loc: jax.Array, axis_name: str,
                   axis_size: int) -> jax.Array:
    """Sequence chunks ``[B, S/T, d]`` (chunk index = rank) -> the full
    replicated ``[B, S, d]``, gathered over the ring. Backward is the
    conjugate slice: the output cotangent is replicated, each rank keeps
    its own chunk."""
    T = int(axis_size)
    my = jax.lax.axis_index(axis_name)
    B, C, d = x_loc.shape
    out = jnp.zeros((B, T * C, d), dtype=x_loc.dtype)
    perm = _ring_perm(T, -1)
    chunk = x_loc
    for k in range(T):
        nxt = (jax.lax.ppermute(chunk, axis_name, perm)
               if k + 1 < T else None)
        out = jax.lax.dynamic_update_slice_in_dim(
            out, chunk, ((my + k) % T) * C, axis=1)
        chunk = nxt
    return out


def _seq_all_gather_fwd(x_loc, axis_name, axis_size):
    return seq_all_gather(x_loc, axis_name, axis_size), None


def _seq_all_gather_bwd(axis_name, axis_size, _, g):
    return (seq_scatter(g, axis_name, axis_size),)


seq_scatter.defvjp(_seq_scatter_fwd, _seq_scatter_bwd)
seq_all_gather.defvjp(_seq_all_gather_fwd, _seq_all_gather_bwd)


def ring_matmul_hops(axis_size: int, n_collective_matmuls: int) -> int:
    """ppermute hops the ring collective-matmul forms trace: each
    :func:`all_gather_matmul` / :func:`matmul_reduce_scatter` /
    :func:`seq_all_gather` contributes ``axis_size - 1`` (the census the
    jaxpr auditor pins for TP-overlap programs)."""
    return (int(axis_size) - 1) * int(n_collective_matmuls)
