"""Elementary neural-net ops as pure functions over parameter pytrees.

All ops take a params dict and return arrays; initializers mirror torch's
defaults closely enough for healthy training (the reference never asserts loss
values — SURVEY.md §0 — so distributional parity, not bit parity, is the bar;
bit-level parity against torch is established in tests by copying weights).
"""

from __future__ import annotations

import math
from typing import Dict, Tuple

import jax
import jax.numpy as jnp


def linear_init(key: jax.Array, in_dim: int, out_dim: int, bias: bool = True) -> Dict:
    """Kaiming-uniform weight + uniform bias, matching ``torch.nn.Linear.reset_parameters``."""
    wkey, bkey = jax.random.split(key)
    bound = 1.0 / math.sqrt(in_dim)
    params = {"w": jax.random.uniform(wkey, (in_dim, out_dim), minval=-bound, maxval=bound)}
    if bias:
        params["b"] = jax.random.uniform(bkey, (out_dim,), minval=-bound, maxval=bound)
    return params


def linear_apply(params: Dict, x: jax.Array) -> jax.Array:
    y = x @ params["w"]
    if "b" in params:
        y = y + params["b"]
    return y


def layer_norm_init(dim: int) -> Dict:
    return {"scale": jnp.ones((dim,)), "bias": jnp.zeros((dim,))}


def layer_norm_apply(params: Dict, x: jax.Array, eps: float = 1e-5) -> jax.Array:
    # checkpoint: backward saves only (x, scale, bias) and recomputes the
    # stats — without it autodiff banks the f32 normalized copy (2-4x the
    # input bytes at bf16 compute), the single largest residual class in
    # the stored-activation profiles (docs/performance.md)
    def core(scale, bias, x):
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        xn = (x - mean) * jax.lax.rsqrt(var + eps)
        return xn * scale + bias

    return jax.checkpoint(core)(params["scale"], params["bias"], x)


def rms_norm_init(dim: int) -> Dict:
    return {"scale": jnp.ones((dim,))}


def rms_norm_apply(params: Dict, x: jax.Array, eps: float = 1e-5) -> jax.Array:
    # checkpointed for the same residual-traffic reason as layer_norm_apply
    def core(scale, x):
        ms = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
        return x * jax.lax.rsqrt(ms + eps) * scale

    return jax.checkpoint(core)(params["scale"], x)


def embedding_init(key: jax.Array, vocab: int, dim: int) -> jax.Array:
    """N(0, 1) like ``torch.nn.Embedding``."""
    return jax.random.normal(key, (vocab, dim))


def embedding_apply(table: jax.Array, tokens: jax.Array) -> jax.Array:
    return jnp.take(table, tokens, axis=0)


def dropout_apply(x: jax.Array, rate: float, rng) -> jax.Array:
    """Inverted dropout: zero each element with probability ``rate`` and scale
    survivors by 1/(1-rate), matching ``torch.nn.functional.dropout`` train
    semantics. ``rng=None`` (eval mode) or ``rate=0`` is the identity.
    ``rate`` must be a static Python float (it selects the compiled program).
    """
    if rng is None or rate == 0.0:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), jnp.zeros((), x.dtype))


def sharded_dropout_apply(x: jax.Array, rate: float, rng,
                          axis: str = None, n_shards: int = 1,
                          shard_dim: int = -1) -> jax.Array:
    """Dropout on a tensor whose ``shard_dim`` is this device's 1/n_shards
    slice of a larger tensor (tensor-parallel attention heads / FFN hidden,
    sequence-parallel positions). The mask is drawn at the FULL shape from
    the replicated ``rng`` and the local block sliced out by
    ``lax.axis_index(axis)`` — so every shard's mask is exactly the
    single-device mask restricted to its slice, and a sharded run matches
    the unsharded oracle bit-for-bit (the axis-aware mask folding of
    VERDICT r1 item 5). Mask bits are threefry ALU work, cheap next to the
    matmuls the mask sits between; no [full] tensor is materialized beyond
    the mask itself.
    """
    if rng is None or rate == 0.0:
        return x
    if axis is None or n_shards == 1:
        return dropout_apply(x, rate, rng)
    shard_dim = shard_dim % x.ndim
    full_shape = list(x.shape)
    full_shape[shard_dim] *= n_shards
    keep_full = jax.random.bernoulli(rng, 1.0 - rate, tuple(full_shape))
    idx = jax.lax.axis_index(axis)
    keep = jax.lax.dynamic_slice_in_dim(
        keep_full, idx * x.shape[shard_dim], x.shape[shard_dim], shard_dim)
    return jnp.where(keep, x / (1.0 - rate), jnp.zeros((), x.dtype))


def _token_nll(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Per-position NLL (fp32 log-softmax), the core shared by the masked
    and unmasked loss paths so they cannot diverge."""
    logz = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.take_along_axis(logz, targets[..., None], axis=-1)[..., 0]


def cross_entropy_loss(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean token-wise cross entropy over all positions.

    Matches the reference's ``tokenwise_loss_fn`` — ``nn.CrossEntropyLoss`` over
    flattened ``(B*S, V)`` logits (``LLMsDistributedTrainingHelper.py:197-201``).
    """
    return jnp.mean(_token_nll(logits, targets))


def masked_xent_sum(logits: jax.Array, targets: jax.Array,
                    pad_id: int) -> Tuple[jax.Array, jax.Array]:
    """Cross-entropy SUM over non-pad positions plus the valid-token count.

    The building block for ignore-index losses (torch's
    ``CrossEntropyLoss(ignore_index=...)``): the caller divides the summed
    NLL by the (possibly globally reduced) count, so microbatched/sharded
    runs can normalize by the GLOBAL valid count instead of a per-chunk
    mean-of-means (which would weight short sequences more).
    """
    nll = _token_nll(logits, targets)
    valid = targets != pad_id
    return jnp.sum(jnp.where(valid, nll, 0.0)), jnp.sum(valid)


def global_pad_scale(targets: jax.Array, pad_id: int, n_micro: int,
                     data_axis=None, shard_axes=None) -> jax.Array:
    """The factor that turns per-microbatch masked NLL sums into the
    globally normalized ignore-index mean under the pipeline executor's
    standard reductions: the executor later multiplies accumulated loss by
    ``1/n_micro`` and means over ``data_axis`` replicas (``shard_axes`` —
    an axis name or tuple of them, e.g. seq/expert — are summed unscaled),
    so pre-multiplying each sum by ``n_micro * n_data / n_valid_global``
    cancels everything into ``total_nll / global_valid_count``. The valid
    count psums over every given axis. Must be called OUTSIDE the schedule
    scan."""
    n_valid = jnp.sum(targets != pad_id).astype(jnp.float32)
    n_data = 1
    if data_axis is not None:
        n_valid = jax.lax.psum(n_valid, data_axis)
        n_data = jax.lax.axis_size(data_axis)
    axes = (shard_axes,) if isinstance(shard_axes, str) else (shard_axes or ())
    for axis in axes:
        n_valid = jax.lax.psum(n_valid, axis)
    return n_micro * n_data / jnp.maximum(n_valid, 1.0)


def select_masked_xent_sum(use_fused: bool):
    """Pick the ignore-index loss core: the XLA :func:`masked_xent_sum` or
    its fused-kernel twin. Same (sum, count) contract, identical values."""
    if use_fused:
        from .pallas_xent import fused_masked_xent_sum
        return fused_masked_xent_sum
    return masked_xent_sum


def select_xent(use_fused: bool):
    """Pick the loss implementation: the XLA formulation above, or the Pallas
    fused kernel (``ops.pallas_xent``) which never materializes the [N, V]
    log-softmax. Both compute identical values (tested)."""
    if use_fused:
        from .pallas_xent import fused_cross_entropy_loss
        return fused_cross_entropy_loss
    return cross_entropy_loss
