"""Fused softmax-cross-entropy kernel in Pallas (Mosaic) for TPU.

The XLA path (``ops.layers.cross_entropy_loss``, matching the reference's
``tokenwise_loss_fn`` at ``LLMsDistributedTrainingHelper.py:197-201``) computes
``log_softmax`` over the full ``[B*S, V]`` logits in float32 before gathering
the target column — at GPT-2 scale (B*S=4096, V=50257) that intermediate is
~0.8 GB of HBM traffic per step. This kernel computes the per-row
``logsumexp`` and target logit in VMEM tiles, so only the ``[N]``-shaped
``nll`` / ``lse`` vectors ever reach HBM on the forward.

Backward (``jax.custom_vjp``): with the saved ``lse`` the gradient is a pure
elementwise function of the logits — ``(exp(x - lse) - onehot) * g`` — which
XLA fuses into a single read-logits / write-grad pass; no extra intermediate
is materialized.

Layout: grid is ``(N // block_n,)``; each instance holds a
``[block_n, V]`` row tile in VMEM. ``block_n`` adapts to the vocab so the
tile stays under the VMEM budget. Rows must divide evenly (true for every
batch*seq in the sweep); otherwise the caller falls back to the XLA path.
On non-TPU backends the kernel runs in interpreter mode so CPU CI exercises
the same code path (same convention as ``ops.pallas_attention``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .pallas_attention import NEG_INF, _use_interpret

_VMEM_TILE_BYTES = 4 * 1024 * 1024  # fp32 row-tile budget per kernel instance


def _pick_block_n(n_rows: int, vocab: int) -> int:
    """Largest power-of-two row count that divides ``n_rows`` and keeps the
    fp32 ``[block_n, V]`` tile within the VMEM budget."""
    cap = max(1, _VMEM_TILE_BYTES // (4 * vocab))
    bn = 1
    while bn * 2 <= min(cap, 128) and n_rows % (bn * 2) == 0:
        bn *= 2
    return bn


def _xent_fwd_kernel(logits_ref, targets_ref, nll_ref, lse_ref, *, vocab: int):
    x = logits_ref[...].astype(jnp.float32)  # [block_n, V]
    cols = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    x = jnp.where(cols < vocab, x, NEG_INF)  # mask any lane padding
    m = jnp.max(x, axis=1)
    lse = m + jnp.log(jnp.sum(jnp.exp(x - m[:, None]), axis=1))
    tgt = targets_ref[...][:, 0]  # [block_n]
    tl = jnp.sum(jnp.where(cols == tgt[:, None], x, 0.0), axis=1)
    nll_ref[...] = (lse - tl)[:, None]
    lse_ref[...] = lse[:, None]


def _xent_fwd_pallas(logits: jax.Array, targets: jax.Array):
    """logits [N, V], targets [N] int -> (nll [N] f32, lse [N] f32)."""
    n, v = logits.shape
    block_n = _pick_block_n(n, v)
    out = pl.pallas_call(
        functools.partial(_xent_fwd_kernel, vocab=v),
        out_shape=(jax.ShapeDtypeStruct((n, 1), jnp.float32),
                   jax.ShapeDtypeStruct((n, 1), jnp.float32)),
        grid=(n // block_n,),
        in_specs=[
            pl.BlockSpec((block_n, v), lambda i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i: (i, 0)),
        ],
        out_specs=(pl.BlockSpec((block_n, 1), lambda i: (i, 0)),
                   pl.BlockSpec((block_n, 1), lambda i: (i, 0))),
        interpret=_use_interpret(),
    )(logits, targets.astype(jnp.int32)[:, None])
    nll, lse = out
    return nll[:, 0], lse[:, 0]


@jax.custom_vjp
def _xent(logits, targets):
    nll, _ = _xent_fwd_pallas(logits, targets)
    return nll


def _xent_vjp_fwd(logits, targets):
    nll, lse = _xent_fwd_pallas(logits, targets)
    return nll, (logits, targets, lse)


def _xent_vjp_bwd(res, g):
    logits, targets, lse = res
    # d nll_i / d x_ij = softmax(x)_ij - onehot(t_i)_j ; fused by XLA into one
    # read-logits/write-grad pass (p is a fusion intermediate, not an array).
    p = jnp.exp(logits.astype(jnp.float32) - lse[:, None])
    cols = jnp.arange(logits.shape[-1], dtype=targets.dtype)[None, :]
    grad = (p - (cols == targets[:, None]).astype(jnp.float32)) * g[:, None]
    return (grad.astype(logits.dtype),
            np.zeros(targets.shape, dtype=jax.dtypes.float0))


_xent.defvjp(_xent_vjp_fwd, _xent_vjp_bwd)


def fused_softmax_xent(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Per-token negative log likelihood, fused: [..., V] x [...] -> [...] f32.

    Differentiable w.r.t. ``logits``. Falls back to the XLA formulation when
    the flattened row count does not tile (``_pick_block_n`` degenerates to
    single-row instances, e.g. an odd row count).
    """
    v = logits.shape[-1]
    shape = logits.shape[:-1]
    flat_logits = logits.reshape(-1, v)
    flat_targets = targets.reshape(-1)
    n = flat_logits.shape[0]
    if n > 1 and _pick_block_n(n, v) == 1:
        # Degenerate tiling (e.g. odd row count): a grid of [1, V] instances
        # would be a throughput cliff; use the XLA formulation instead.
        logz = jax.nn.log_softmax(flat_logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logz, flat_targets[:, None], axis=-1)[:, 0]
    else:
        nll = _xent(flat_logits, flat_targets)
    return nll.reshape(shape)


def fused_cross_entropy_loss(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Drop-in for ``ops.layers.cross_entropy_loss`` (mean token-wise NLL,
    reference ``tokenwise_loss_fn`` semantics) through the fused kernel."""
    return jnp.mean(fused_softmax_xent(logits, targets))


def fused_masked_xent_sum(logits: jax.Array, targets: jax.Array, pad_id: int):
    """Fused twin of ``ops.layers.masked_xent_sum`` (ignore-index): NLL sum
    over non-pad positions + valid count. Masking happens on the kernel's
    per-token NLL output, so the custom-vjp backward sees a zero cotangent
    on pad rows and their logit gradients vanish exactly (tested)."""
    nll = fused_softmax_xent(logits, targets)
    valid = targets != pad_id
    return jnp.sum(jnp.where(valid, nll, 0.0)), jnp.sum(valid)
