"""Checkpoint save/restore via Orbax.

The reference has no checkpointing at all (SURVEY.md §5: no
state_dict/save/load anywhere — models are random-initialized per experiment
and discarded); this exists for the real-model ladder (GPT-2/Llama configs),
which at minimum needs parameter loading.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict, Optional

Pytree = Any

# A checkpoint dir is COMMITTED once this marker file exists inside it.
# Orbax's own directory layout gives no cheap "is this save complete?"
# predicate for a process that died mid-flush; the marker is written
# atomically (tmp + rename) strictly AFTER the flush, so its presence
# implies a readable checkpoint. utils.resilience builds the manager /
# retention / fallback-restore protocol on these primitives.
COMMIT_MARKER = "_COMMITTED.json"


_SHARED = None

# Partial-restore sentinel: newer orbax exports ``ocp.PLACEHOLDER``; the
# 0.7.x line in some containers does not. Fall back to a private object
# nothing matches, so full-template restores (every training/resume path)
# work regardless of orbax version, and only the partial-restore helpers
# depend on the real sentinel being available.
_NO_PLACEHOLDER = object()


def _placeholder():
    import orbax.checkpoint as ocp
    return getattr(ocp, "PLACEHOLDER", _NO_PLACEHOLDER)


def _checkpointer():
    # one shared checkpointer so async saves serialize against each other
    # (and against restores) instead of racing
    global _SHARED
    if _SHARED is None:
        import orbax.checkpoint as ocp
        _SHARED = ocp.StandardCheckpointer()
    return _SHARED


def wait_for_async_saves() -> None:
    """Block until every async save issued through this process's shared
    checkpointer has landed on disk (no-op when none is in flight)."""
    if _SHARED is not None:
        _SHARED.wait_until_finished()


def write_commit_marker(path: str, meta: Dict[str, Any]) -> None:
    """Atomically place the commit marker inside checkpoint dir ``path``:
    write to a tmp file, ``os.replace`` into place — a crash mid-write
    leaves no (partial) marker, so commitment is all-or-nothing."""
    tmp = os.path.join(path, COMMIT_MARKER + ".tmp")
    with open(tmp, "w") as fh:
        json.dump(meta, fh, indent=2, default=str)
        fh.write("\n")
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, os.path.join(path, COMMIT_MARKER))


def read_commit_marker(path: str) -> Optional[Dict[str, Any]]:
    """The commit-marker dict of checkpoint dir ``path``, or None when
    absent or unreadable (an unreadable marker is treated as
    uncommitted — restore must not trust it)."""
    marker = os.path.join(path, COMMIT_MARKER)
    try:
        with open(marker) as fh:
            out = json.load(fh)
        return out if isinstance(out, dict) else None
    except (OSError, ValueError):
        return None


def is_committed(path: str) -> bool:
    return read_commit_marker(path) is not None


def save_checkpoint(path: str, state: Pytree, wait: bool = True) -> None:
    """Save a pytree (params, or {'params': ..., 'opt_state': ...}) to
    ``path``. The dir must not already hold a *committed* checkpoint
    (refused — silent overwrite of good state is never right); an
    existing **uncommitted** dir — the shell a run killed between flush
    and commit leaves behind — is removed and the save retried, so a
    resumed run can re-save the same step it died on.

    ``wait=False`` returns as soon as the on-device state is snapshotted and
    lets Orbax write to disk in the background — training continues while
    the previous checkpoint flushes (the next save/restore waits for it
    first). The training loop uses this for periodic mid-run saves and
    ``wait=True`` for the final one. Saving does NOT write the commit
    marker — callers (``utils.resilience.CheckpointManager``) commit
    once the flush has finished."""
    ckpt = _checkpointer()
    ckpt.wait_until_finished()  # serialize with any in-flight async save
    apath = os.path.abspath(path)
    if os.path.isdir(apath):
        if is_committed(apath):
            raise ValueError(
                f"refusing to overwrite committed checkpoint {apath} — "
                "remove it (or let retention GC) first")
        import shutil
        logging.getLogger(__name__).warning(
            "save_checkpoint: %s exists without a commit marker (prior "
            "save died mid-flush?); removing and re-saving", apath)
        shutil.rmtree(apath)
    ckpt.save(apath, state)
    if wait:
        ckpt.wait_until_finished()


def restore_checkpoint(path: str, template: Optional[Pytree] = None) -> Pytree:
    """Restore a pytree saved by :func:`save_checkpoint`. ``template`` (a
    matching pytree of arrays or ShapeDtypeStructs) restores with the right
    structure/dtypes/shardings; without it, orbax restores as saved."""
    import jax
    ckpt = _checkpointer()
    ckpt.wait_until_finished()  # a prior async save must land first
    if template is not None:
        from jax.sharding import NamedSharding

        import orbax.checkpoint as ocp

        PH = _placeholder()

        def as_struct(x):
            # carry mesh-aware shardings (e.g. ZeRO-1 moments) so restore
            # materializes directly into the sharded layout; everything else
            # passes None, letting orbax restore per the checkpoint's own
            # metadata (in-process this reproduces the saved placement, so
            # jit inputs stay compatible with the mesh they were saved
            # under). ocp.PLACEHOLDER leaves pass through: orbax skips them
            # (partial restore — e.g. the export CLI leaving the optimizer
            # moments on disk).
            if x is PH:
                return x
            sh = getattr(x, "sharding", None)
            sh = sh if isinstance(sh, NamedSharding) else None
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh)

        structs = jax.tree.map(as_struct, template)
        leaves = jax.tree.leaves(structs)
        partial = any(l is PH for l in leaves)
        had_none = any(getattr(s, "sharding", 1) is None for s in leaves)

        def _restore(tree):
            if partial:
                # partial restore: StandardCheckpointHandler rejects
                # PLACEHOLDER; the PyTree handler skips those subtrees
                # entirely (never read from disk). It ignores the item
                # structs' shardings, so they travel via restore_args.
                rargs = jax.tree.map(
                    lambda s: ocp.RestoreArgs() if s is PH
                    else ocp.ArrayRestoreArgs(sharding=s.sharding,
                                              global_shape=s.shape,
                                              dtype=s.dtype),
                    tree)
                with ocp.Checkpointer(ocp.PyTreeCheckpointHandler()) as c:
                    return c.restore(
                        os.path.abspath(path),
                        args=ocp.args.PyTreeRestore(item=tree,
                                                    restore_args=rargs))
            return ckpt.restore(os.path.abspath(path), tree)

        try:
            return _restore(structs)
        except ValueError as e:
            # None shardings are rejected when the checkpoint's saved device
            # topology is not resolvable in this process (e.g. the export
            # CLI reading a checkpoint written under a simulated multi-device
            # mesh): pin those leaves to one local device and retry. Retry
            # ONLY for that condition — any other ValueError (shape/template
            # mismatch) would just fail again after a multi-GB re-read. The
            # match is pinned to orbax's topology-resolution messages
            # (jax_array_handlers.py: 'Unable to deserialize sharding.',
            # 'Sharding of jax.Array cannot be None.') rather than the bare
            # substring 'sharding', which also appears in genuine
            # template-mismatch errors.
            topology_failure = ("deserialize sharding" in str(e)
                                or "Sharding of jax.Array cannot be None"
                                in str(e))
            if not had_none or not topology_failure:
                raise
            import logging
            logging.getLogger(__name__).warning(
                "checkpoint restore: saved device topology not resolvable "
                "(%s); retrying with all unpinned leaves on a single local "
                "device", e)
            dev0 = jax.sharding.SingleDeviceSharding(jax.devices()[0])
            pinned = jax.tree.map(
                lambda s: s if s is PH
                or getattr(s, "sharding", 1) is not None
                else jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=dev0),
                structs)
            return _restore(pinned)
    return ckpt.restore(os.path.abspath(path))


def restore_subtree(path: str, key: str, template: Pytree) -> Pytree:
    """Restore only ``state[key]`` from a checkpoint, never reading the rest
    from disk — and without needing to know the rest's structure (the export
    CLI can't: the saved opt_state depends on optimizer/grad-accum options).
    The checkpoint's own metadata supplies the full tree; every subtree but
    ``key`` becomes a PLACEHOLDER."""
    import jax
    import orbax.checkpoint as ocp

    with ocp.Checkpointer(ocp.PyTreeCheckpointHandler()) as c:
        md = c.metadata(os.path.abspath(path)).item_metadata.tree
    full = jax.tree.map(lambda _: _placeholder(), md)
    if not isinstance(full, dict) or key not in full:
        raise KeyError(
            f"checkpoint at {path} has no {key!r} subtree "
            f"(top-level keys: {sorted(full) if isinstance(full, dict) else type(full)})")
    full[key] = template
    return restore_checkpoint(path, template=full)[key]
