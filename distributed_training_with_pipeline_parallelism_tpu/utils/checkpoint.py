"""Checkpoint save/restore via Orbax.

The reference has no checkpointing at all (SURVEY.md §5: no
state_dict/save/load anywhere — models are random-initialized per experiment
and discarded); this exists for the real-model ladder (GPT-2/Llama configs),
which at minimum needs parameter loading.
"""

from __future__ import annotations

import os
from typing import Any, Optional

Pytree = Any


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.StandardCheckpointer()


def save_checkpoint(path: str, state: Pytree) -> None:
    """Save a pytree (params, or {'params': ..., 'opt_state': ...}) to
    ``path`` (created; must not already contain a checkpoint)."""
    ckpt = _checkpointer()
    ckpt.save(os.path.abspath(path), state)
    ckpt.wait_until_finished()


def restore_checkpoint(path: str, template: Optional[Pytree] = None) -> Pytree:
    """Restore a pytree saved by :func:`save_checkpoint`. ``template`` (a
    matching pytree of arrays or ShapeDtypeStructs) restores with the right
    structure/dtypes/shardings; without it, orbax restores as saved."""
    import jax
    ckpt = _checkpointer()
    if template is not None:
        from jax.sharding import NamedSharding

        def as_struct(x):
            # carry mesh-aware shardings (e.g. ZeRO-1 moments) so restore
            # materializes directly into the sharded layout; plain
            # single-device placements restore uncommitted, as before
            sh = getattr(x, "sharding", None)
            sh = sh if isinstance(sh, NamedSharding) else None
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh)

        return ckpt.restore(os.path.abspath(path), jax.tree.map(as_struct,
                                                                template))
    return ckpt.restore(os.path.abspath(path))
