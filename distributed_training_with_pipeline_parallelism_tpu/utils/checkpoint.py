"""Checkpoint save/restore via Orbax.

The reference has no checkpointing at all (SURVEY.md §5: no
state_dict/save/load anywhere — models are random-initialized per experiment
and discarded); this exists for the real-model ladder (GPT-2/Llama configs),
which at minimum needs parameter loading.
"""

from __future__ import annotations

import os
from typing import Any, Optional

Pytree = Any


_SHARED = None


def _checkpointer():
    # one shared checkpointer so async saves serialize against each other
    # (and against restores) instead of racing
    global _SHARED
    if _SHARED is None:
        import orbax.checkpoint as ocp
        _SHARED = ocp.StandardCheckpointer()
    return _SHARED


def save_checkpoint(path: str, state: Pytree, wait: bool = True) -> None:
    """Save a pytree (params, or {'params': ..., 'opt_state': ...}) to
    ``path`` (created; must not already contain a checkpoint).

    ``wait=False`` returns as soon as the on-device state is snapshotted and
    lets Orbax write to disk in the background — training continues while
    the previous checkpoint flushes (the next save/restore waits for it
    first). The training loop uses this for periodic mid-run saves and
    ``wait=True`` for the final one."""
    ckpt = _checkpointer()
    ckpt.wait_until_finished()  # serialize with any in-flight async save
    ckpt.save(os.path.abspath(path), state)
    if wait:
        ckpt.wait_until_finished()


def restore_checkpoint(path: str, template: Optional[Pytree] = None) -> Pytree:
    """Restore a pytree saved by :func:`save_checkpoint`. ``template`` (a
    matching pytree of arrays or ShapeDtypeStructs) restores with the right
    structure/dtypes/shardings; without it, orbax restores as saved."""
    import jax
    ckpt = _checkpointer()
    ckpt.wait_until_finished()  # a prior async save must land first
    if template is not None:
        from jax.sharding import NamedSharding

        import orbax.checkpoint as ocp

        def as_struct(x):
            # carry mesh-aware shardings (e.g. ZeRO-1 moments) so restore
            # materializes directly into the sharded layout; everything else
            # passes None, letting orbax restore per the checkpoint's own
            # metadata (in-process this reproduces the saved placement, so
            # jit inputs stay compatible with the mesh they were saved
            # under). ocp.PLACEHOLDER leaves pass through: orbax skips them
            # (partial restore — e.g. the export CLI leaving the optimizer
            # moments on disk).
            if x is ocp.PLACEHOLDER:
                return x
            sh = getattr(x, "sharding", None)
            sh = sh if isinstance(sh, NamedSharding) else None
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh)

        structs = jax.tree.map(as_struct, template)
        leaves = jax.tree.leaves(structs)
        partial = any(l is ocp.PLACEHOLDER for l in leaves)
        had_none = any(getattr(s, "sharding", 1) is None for s in leaves)

        def _restore(tree):
            if partial:
                # partial restore: StandardCheckpointHandler rejects
                # PLACEHOLDER; the PyTree handler skips those subtrees
                # entirely (never read from disk). It ignores the item
                # structs' shardings, so they travel via restore_args.
                rargs = jax.tree.map(
                    lambda s: ocp.RestoreArgs() if s is ocp.PLACEHOLDER
                    else ocp.ArrayRestoreArgs(sharding=s.sharding,
                                              global_shape=s.shape,
                                              dtype=s.dtype),
                    tree)
                with ocp.Checkpointer(ocp.PyTreeCheckpointHandler()) as c:
                    return c.restore(
                        os.path.abspath(path),
                        args=ocp.args.PyTreeRestore(item=tree,
                                                    restore_args=rargs))
            return ckpt.restore(os.path.abspath(path), tree)

        try:
            return _restore(structs)
        except ValueError as e:
            # None shardings are rejected when the checkpoint's saved device
            # topology is not resolvable in this process (e.g. the export
            # CLI reading a checkpoint written under a simulated multi-device
            # mesh): pin those leaves to one local device and retry. Retry
            # ONLY for that condition — any other ValueError (shape/template
            # mismatch) would just fail again after a multi-GB re-read. The
            # match is pinned to orbax's topology-resolution messages
            # (jax_array_handlers.py: 'Unable to deserialize sharding.',
            # 'Sharding of jax.Array cannot be None.') rather than the bare
            # substring 'sharding', which also appears in genuine
            # template-mismatch errors.
            topology_failure = ("deserialize sharding" in str(e)
                                or "Sharding of jax.Array cannot be None"
                                in str(e))
            if not had_none or not topology_failure:
                raise
            import logging
            logging.getLogger(__name__).warning(
                "checkpoint restore: saved device topology not resolvable "
                "(%s); retrying with all unpinned leaves on a single local "
                "device", e)
            dev0 = jax.sharding.SingleDeviceSharding(jax.devices()[0])
            pinned = jax.tree.map(
                lambda s: s if s is ocp.PLACEHOLDER
                or getattr(s, "sharding", 1) is not None
                else jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=dev0),
                structs)
            return _restore(pinned)
    return ckpt.restore(os.path.abspath(path))


def restore_subtree(path: str, key: str, template: Pytree) -> Pytree:
    """Restore only ``state[key]`` from a checkpoint, never reading the rest
    from disk — and without needing to know the rest's structure (the export
    CLI can't: the saved opt_state depends on optimizer/grad-accum options).
    The checkpoint's own metadata supplies the full tree; every subtree but
    ``key`` becomes a PLACEHOLDER."""
    import jax
    import orbax.checkpoint as ocp

    with ocp.Checkpointer(ocp.PyTreeCheckpointHandler()) as c:
        md = c.metadata(os.path.abspath(path)).item_metadata.tree
    full = jax.tree.map(lambda _: ocp.PLACEHOLDER, md)
    if not isinstance(full, dict) or key not in full:
        raise KeyError(
            f"checkpoint at {path} has no {key!r} subtree "
            f"(top-level keys: {sorted(full) if isinstance(full, dict) else type(full)})")
    full[key] = template
    return restore_checkpoint(path, template=full)[key]
