"""Result plots matching the reference notebook's figures (SURVEY.md C10).

- :func:`plot_speedup_and_efficiency` — cell 28 (``.ipynb:863-943``): a 1x2
  figure of speedup and scaling-efficiency lines vs model config ``L{n}_H{h}``,
  color by schedule, marker by device count, with the GPipe = 1.0 / 100%
  reference lines.
- :func:`plot_throughput_grid` — cell 30 (``.ipynb:955-1004``): a 3x3 grid of
  throughput-vs-device-count panels, one per (layers, heads).
"""

from __future__ import annotations

from typing import Optional

import pandas as pd


def _mpl():
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    return plt

SCHEDULE_COLORS = {"GPipe": "tab:blue", "1F1B": "tab:orange",
                   "Interleaved1F1B": "tab:green",
                   "ZBH1": "tab:red", "BFS": "tab:purple",
                   "ZBV": "tab:brown"}
PROC_MARKERS = {2: "o", 4: "s", 8: "^", 16: "D"}


def plot_speedup_and_efficiency(speedup_df: pd.DataFrame,
                                path: Optional[str] = None):
    plt = _mpl()
    fig, (ax_s, ax_e) = plt.subplots(1, 2, figsize=(14, 5))
    configs = sorted({(r.n_layers, r.n_heads)
                      for r in speedup_df.itertuples()})
    labels = [f"L{L}_H{H}" for L, H in configs]
    xs = range(len(configs))
    for schedule, g1 in speedup_df.groupby("schedule"):
        for procs, g2 in g1.groupby("num_processes"):
            lookup = {(r.n_layers, r.n_heads): r for r in g2.itertuples()}
            ys_s = [lookup[c].speedup if c in lookup else None for c in configs]
            ys_e = [lookup[c].efficiency if c in lookup else None for c in configs]
            style = dict(color=SCHEDULE_COLORS.get(schedule),
                         marker=PROC_MARKERS.get(procs, "x"),
                         label=f"{schedule} ({procs} devices)")
            ax_s.plot(xs, ys_s, **style)
            ax_e.plot(xs, ys_e, **style)
    ax_s.axhline(1.0, color="gray", linestyle="--", label="GPipe baseline")
    ax_e.axhline(100.0, color="gray", linestyle="--")
    for ax, title, ylabel in ((ax_s, "Speedup vs GPipe", "speedup"),
                              (ax_e, "Scaling efficiency", "efficiency (%)")):
        ax.set_xticks(list(xs))
        ax.set_xticklabels(labels, rotation=45)
        ax.set_xlabel("model configuration")
        ax.set_ylabel(ylabel)
        ax.set_title(title)
        ax.grid(alpha=0.3)
    ax_s.legend(fontsize=8)
    fig.tight_layout()
    if path:
        fig.savefig(path, dpi=120)
    return fig


def plot_throughput_grid(df: pd.DataFrame, path: Optional[str] = None):
    plt = _mpl()
    layer_vals = sorted(df["n_layers"].unique())
    head_vals = sorted(df["n_heads"].unique())
    fig, axes = plt.subplots(len(layer_vals), len(head_vals),
                             figsize=(4 * len(head_vals), 3.2 * len(layer_vals)),
                             squeeze=False)
    for i, L in enumerate(layer_vals):
        for j, H in enumerate(head_vals):
            ax = axes[i][j]
            sub = df[(df["n_layers"] == L) & (df["n_heads"] == H)]
            for schedule, g in sub.groupby("schedule"):
                g = g.sort_values("num_processes")
                ax.plot(g["num_processes"], g["throughput"],
                        marker="o", color=SCHEDULE_COLORS.get(schedule),
                        label=schedule)
            ax.set_title(f"L{L}, H{H}", fontsize=10)
            ax.set_xlabel("devices")
            ax.set_ylabel("tokens/sec")
            ax.grid(alpha=0.3)
            if i == 0 and j == 0:
                ax.legend(fontsize=8)
    fig.tight_layout()
    if path:
        fig.savefig(path, dpi=120)
    return fig
