"""Result plots matching the reference notebook's figures (SURVEY.md C10).

- :func:`plot_speedup_and_efficiency` — cell 28 (``.ipynb:863-943``): a 1x2
  figure of speedup and scaling-efficiency lines vs model config ``L{n}_H{h}``,
  color by schedule, marker by device count, with the GPipe = 1.0 / 100%
  reference lines.
- :func:`plot_throughput_grid` — cell 30 (``.ipynb:955-1004``): a 3x3 grid of
  throughput-vs-device-count panels, one per (layers, heads).
- :func:`plot_schedule_timeline` — the reference Part 1's schedule-timeline
  diagrams (cells 4/7/9/11, ``.ipynb:30-171``), but *exact*: rendered from
  the compiled tick table the executor actually runs, for any schedule and
  any (D, V, M), bubbles included.
"""

from __future__ import annotations

from typing import Optional

import pandas as pd


def _mpl():
    import matplotlib
    # headless default — but do NOT clobber a notebook's inline backend,
    # or executed notebooks silently lose every figure
    if "inline" not in matplotlib.get_backend().lower():
        matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    return plt

SCHEDULE_COLORS = {"GPipe": "tab:blue", "1F1B": "tab:orange",
                   "Interleaved1F1B": "tab:green",
                   "ZBH1": "tab:red", "BFS": "tab:purple",
                   "ZBV": "tab:brown"}
PROC_MARKERS = {2: "o", 4: "s", 8: "^", 16: "D"}


def plot_speedup_and_efficiency(speedup_df: pd.DataFrame,
                                path: Optional[str] = None):
    plt = _mpl()
    fig, (ax_s, ax_e) = plt.subplots(1, 2, figsize=(14, 5))
    configs = sorted({(r.n_layers, r.n_heads)
                      for r in speedup_df.itertuples()})
    labels = [f"L{L}_H{H}" for L, H in configs]
    xs = range(len(configs))
    for schedule, g1 in speedup_df.groupby("schedule"):
        for procs, g2 in g1.groupby("num_processes"):
            lookup = {(r.n_layers, r.n_heads): r for r in g2.itertuples()}
            ys_s = [lookup[c].speedup if c in lookup else None for c in configs]
            ys_e = [lookup[c].efficiency if c in lookup else None for c in configs]
            style = dict(color=SCHEDULE_COLORS.get(schedule),
                         marker=PROC_MARKERS.get(procs, "x"),
                         label=f"{schedule} ({procs} devices)")
            ax_s.plot(xs, ys_s, **style)
            ax_e.plot(xs, ys_e, **style)
    ax_s.axhline(1.0, color="gray", linestyle="--", label="GPipe baseline")
    ax_e.axhline(100.0, color="gray", linestyle="--")
    for ax, title, ylabel in ((ax_s, "Speedup vs GPipe", "speedup"),
                              (ax_e, "Scaling efficiency", "efficiency (%)")):
        ax.set_xticks(list(xs))
        ax.set_xticklabels(labels, rotation=45)
        ax.set_xlabel("model configuration")
        ax.set_ylabel(ylabel)
        ax.set_title(title)
        ax.grid(alpha=0.3)
    ax_s.legend(fontsize=8)
    fig.tight_layout()
    if path:
        fig.savefig(path, dpi=120)
    return fig


OP_COLORS = {"F": "#4e9ad1", "B": "#f29d4b", "W": "#8ec07c"}


def plot_schedule_timeline(name_or_cs, n_devices: int = None,
                           n_virtual: int = 1, n_microbatches: int = 4,
                           path: Optional[str] = None, ax=None,
                           annotate: bool = True):
    """Per-device schedule timeline rendered from the compiled tick table.

    The reference's Part 1 carries four hand-drawn schedule diagrams (cells
    4/7/9/11) as embedded PNGs; this renders the *actual* executed schedule:
    each row is a device, each cell a tick, colored by op (F blue / B orange
    / W green), labeled with the microbatch index, with virtual-stage chunks
    hatched by shade. Blank cells ARE the bubble — the figure is exact for
    any (schedule, D, V, M), including beyond-parity ones (ZBH1/ZBV/BFS and
    custom registrations).

    Accepts a schedule name + dims, or an already-compiled
    :class:`~..parallel.schedules.CompiledSchedule`.
    """
    from ..parallel.schedules import (CompiledSchedule, compile_schedule,
                                      placement_chunk_of, placement_device_of)
    if isinstance(name_or_cs, CompiledSchedule):
        cs = name_or_cs
    else:
        cs = compile_schedule(name_or_cs, n_devices, n_virtual, n_microbatches)
    D, V = cs.n_devices, cs.n_virtual
    plt = _mpl()
    if ax is None:
        fig, ax = plt.subplots(
            figsize=(max(6, 0.32 * cs.makespan), 0.6 * D + 1.2))
    else:
        fig = ax.figure

    for action, tick in cs.ticks.items():
        dev = placement_device_of(cs.placement, action.stage, D)
        chunk = placement_chunk_of(cs.placement, action.stage, D)
        from matplotlib.colors import to_rgb
        base = OP_COLORS[action.op]
        # deeper virtual chunks darken (the reference's diagrams shade the
        # second chunk of interleaved schedules the same way)
        shade = 1.0 - 0.35 * (chunk / max(1, V - 1)) if V > 1 else 1.0
        rgb = tuple(min(1.0, c * shade) for c in to_rgb(base))
        ax.add_patch(plt.Rectangle((tick, D - 1 - dev + 0.08), 1.0, 0.84,
                                   facecolor=rgb, edgecolor="white",
                                   linewidth=0.6))
        if annotate and cs.makespan <= 80:
            ax.text(tick + 0.5, D - 1 - dev + 0.5, str(action.microbatch),
                    ha="center", va="center", fontsize=7,
                    color="black")

    ax.set_xlim(0, cs.makespan)
    ax.set_ylim(0, D)
    ax.set_yticks([D - 1 - d + 0.5 for d in range(D)])
    ax.set_yticklabels([f"device {d}" for d in range(D)])
    ax.set_xlabel("tick")
    from ..parallel.schedules import simulated_bubble
    bub = simulated_bubble(cs, 1.0, 1.0)["bubble_fraction"]
    ax.set_title(f"{cs.name}  D={D} V={V} M={cs.n_microbatches}  "
                 f"(makespan {cs.makespan} ticks, unit-cost bubble "
                 f"{bub:.1%})", fontsize=10)
    handles = [plt.Rectangle((0, 0), 1, 1, facecolor=OP_COLORS[o])
               for o in ("F", "B", "W")]
    labels = ["forward", "backward (dgrad)" if cs.split_backward
              else "backward", "weight grad"]
    n_leg = 3 if cs.split_backward else 2
    ax.legend(handles[:n_leg], labels[:n_leg], fontsize=7, loc="lower right")
    fig.tight_layout()
    if path:
        fig.savefig(path, dpi=120)
    return fig


def plot_timeline_overlay(name_or_cs, timeline, n_devices: int = None,
                          n_virtual: int = 1, n_microbatches: int = 4,
                          path: Optional[str] = None):
    """Measured vs simulated timeline, stacked on a shared tick axis.

    Top panel: the compiled schedule's tick timeline
    (:func:`plot_schedule_timeline` — the SIMULATED structure, unit-cost
    ticks). Bottom panel: the MEASURED per-tick cost from a
    ``utils.telemetry.PipelineTelemetry`` timeline — each instrumented
    segment (phase / tick / whole step) drawn as a horizontal span over the
    ticks it covers at height ``duration / n_ticks`` (ms per tick), so
    warmup, steady state and cooldown line up column-for-column with the
    schedule cells above. A flat measured profile means unit-cost
    simulation was a good model; spikes localize where real time deviates
    (reading guide: docs/observability.md).

    ``timeline`` is ``PipelineTelemetry.timeline()``'s record list (or the
    ``telemetry.timeline`` section of a run-report manifest).
    """
    from ..parallel.schedules import CompiledSchedule, compile_schedule
    if isinstance(name_or_cs, CompiledSchedule):
        cs = name_or_cs
    else:
        cs = compile_schedule(name_or_cs, n_devices, n_virtual,
                              n_microbatches)
    plt = _mpl()
    fig, (ax_top, ax_bot) = plt.subplots(
        2, 1, figsize=(max(6, 0.32 * cs.makespan), 0.6 * cs.n_devices + 3.4),
        sharex=True, gridspec_kw={"height_ratios": [cs.n_devices, 2.2]})
    plot_schedule_timeline(cs, ax=ax_top, annotate=cs.makespan <= 80)
    ax_top.set_xlabel("")

    for rec in timeline:
        dur = rec.get("duration_s")
        t0, n = rec.get("start_tick", 0), rec.get("n_ticks", 1)
        if dur is None or n <= 0:
            continue
        per_tick_ms = dur / n * 1e3
        ax_bot.fill_between([t0, t0 + n], 0.0, per_tick_ms,
                            step=None, color="#4e9ad1", alpha=0.55,
                            edgecolor="#2a6496", linewidth=0.8)
        if "phase" in rec and cs.makespan <= 80:
            ax_bot.text(t0 + n / 2.0, per_tick_ms, f"p{rec['phase']}",
                        ha="center", va="bottom", fontsize=6, color="#2a6496")
    ax_bot.set_xlim(0, cs.makespan)
    ax_bot.set_ylim(bottom=0.0)
    ax_bot.set_xlabel("tick")
    ax_bot.set_ylabel("measured ms/tick")
    ax_bot.grid(alpha=0.3)
    ax_bot.set_title("measured segment cost (host-stamped)", fontsize=9)
    fig.tight_layout()
    if path:
        fig.savefig(path, dpi=120)
    return fig


def plot_latency_curve(section, path: Optional[str] = None):
    """The serving SLO observatory's headline figure: latency vs offered
    load from a ``serving_load`` manifest section (``serving.loadgen.
    sweep_offered_load`` / ``scripts/serve_load.py``'s ``curve.json``).

    Left panel: p50/p99 TTFT and the admission-wait p99 against offered
    load (units of ring capacity), with the SLO's p99 TTFT budget as a
    horizontal line and the detected saturation knee as a vertical one —
    the hockey stick and where it breaks the budget, on one axis. Right
    panel: goodput and goodput-under-SLO, which flatten (then part ways)
    past the knee. ``section`` is the manifest dict; percentiles missing
    from a row (empty point) plot as gaps.
    """
    plt = _mpl()

    def col(key, pct=None):
        out = []
        for row in section.get("curve", []):
            v = row.get(key)
            if pct is not None:
                v = v.get(pct) if isinstance(v, dict) else None
            out.append(v if isinstance(v, (int, float)) else float("nan"))
        return out

    loads = [row.get("offered_load") for row in section.get("curve", [])]
    fig, (ax_l, ax_g) = plt.subplots(1, 2, figsize=(11, 4.2))
    ax_l.plot(loads, col("ttft_ticks", "p99"), marker="o",
              color="tab:red", label="TTFT p99")
    ax_l.plot(loads, col("ttft_ticks", "p50"), marker="o",
              color="tab:blue", label="TTFT p50")
    ax_l.plot(loads, col("admit_wait_ticks", "p99"), marker="s",
              color="tab:orange", linestyle="--", label="admission wait p99")
    slo = section.get("slo") or {}
    if isinstance(slo.get("ttft_p99_ticks"), (int, float)):
        ax_l.axhline(slo["ttft_p99_ticks"], color="gray", linestyle=":",
                     label=f"SLO p99 budget ({slo['ttft_p99_ticks']:g})")
    knee = section.get("knee") or {}
    for ax in (ax_l, ax_g):
        if isinstance(knee.get("knee_load"), (int, float)):
            ax.axvline(knee["knee_load"], color="black", linestyle="--",
                       alpha=0.6,
                       label=f"knee @ {knee['knee_load']:g} "
                             f"({knee.get('reason')})")
        ax.set_xlabel("offered load (x ring capacity)")
        ax.grid(alpha=0.3)
    ax_l.set_ylabel("latency (ticks)")
    ax_l.set_title("tail latency vs offered load")
    ax_l.legend(fontsize=8)
    ax_g.plot(loads, col("goodput"), marker="o", color="tab:green",
              label="goodput (tok/tick)")
    slo_good = [((row.get("slo") or {}).get("goodput_under_slo")
                 if isinstance((row.get("slo") or {})
                               .get("goodput_under_slo"), (int, float))
                 else float("nan"))
                for row in section.get("curve", [])]
    ax_g.plot(loads, slo_good, marker="s", color="tab:purple",
              linestyle="--", label="goodput under SLO")
    ax_g.set_ylabel("tokens / tick")
    ax_g.set_title("goodput vs offered load")
    ax_g.legend(fontsize=8)
    fig.tight_layout()
    if path:
        fig.savefig(path, dpi=120)
    return fig


def plot_queue_depth(summary, path: Optional[str] = None):
    """Queue depth and slot occupancy over ticks for one serving run —
    the open-loop early-warning picture: a queue ramp that precedes the
    TTFT blow-up by a trace length, against how full the ring's slots
    are while it builds.

    ``summary`` is a ``serving_summary`` dict (or a ``serving_load``
    curve row's nested ``summary``) carrying the block-boundary
    ``queue_depth`` / ``occupancy`` series as ``[[tick, n], ...]``; the
    ``n_slots`` ceiling is drawn when present. Step-drawn: each sample
    holds until the next block boundary (the fast-forward boundary
    samples make idle gaps render as zeros, not interpolated slopes).
    """
    plt = _mpl()
    fig, ax = plt.subplots(figsize=(9, 3.6))
    for key, color, label in (("queue_depth", "tab:red", "admission queue"),
                              ("occupancy", "tab:blue", "busy slots")):
        series = summary.get(key) or []
        if series:
            ts = [float(t) for t, _ in series]
            ns = [int(n) for _, n in series]
            ax.step(ts, ns, where="post", color=color, label=label)
    n_slots = summary.get("n_slots")
    if isinstance(n_slots, (int, float)):
        ax.axhline(n_slots, color="gray", linestyle=":",
                   label=f"slot count ({int(n_slots)})")
    ax.set_xlabel("tick")
    ax.set_ylabel("requests")
    ax.set_ylim(bottom=0)
    ax.set_title(f"queue depth & slot occupancy "
                 f"({summary.get('policy', '?')} policy)", fontsize=10)
    ax.grid(alpha=0.3)
    ax.legend(fontsize=8)
    fig.tight_layout()
    if path:
        fig.savefig(path, dpi=120)
    return fig


def plot_throughput_grid(df: pd.DataFrame, path: Optional[str] = None):
    plt = _mpl()
    layer_vals = sorted(df["n_layers"].unique())
    head_vals = sorted(df["n_heads"].unique())
    fig, axes = plt.subplots(len(layer_vals), len(head_vals),
                             figsize=(4 * len(head_vals), 3.2 * len(layer_vals)),
                             squeeze=False)
    for i, L in enumerate(layer_vals):
        for j, H in enumerate(head_vals):
            ax = axes[i][j]
            sub = df[(df["n_layers"] == L) & (df["n_heads"] == H)]
            for schedule, g in sub.groupby("schedule"):
                g = g.sort_values("num_processes")
                ax.plot(g["num_processes"], g["throughput"],
                        marker="o", color=SCHEDULE_COLORS.get(schedule),
                        label=schedule)
            ax.set_title(f"L{L}, H{H}", fontsize=10)
            ax.set_xlabel("devices")
            ax.set_ylabel("tokens/sec")
            ax.grid(alpha=0.3)
            if i == 0 and j == 0:
                ax.legend(fontsize=8)
    fig.tight_layout()
    if path:
        fig.savefig(path, dpi=120)
    return fig
