"""Optimizer-coupled training on top of the pipeline executor.

The reference *measures* forward+backward only — no ``optim.step()`` exists
anywhere in it (SURVEY.md §3.3 note) — so the benchmark path
(:func:`..parallel.pipeline.make_pipeline_step`) stays optimizer-free for
parity. Real training on the model ladder (GPT-2 / Llama configs) composes
the same pipeline gradients with an optax optimizer under a single jit here.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import json
import logging
import math
import os
import time
from typing import Any, Callable, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from ..parallel.mesh import PIPE_AXIS
from ..parallel.pipeline import make_pipeline_grad_fn
from .checkpoint import restore_checkpoint, save_checkpoint
from .config import ModelConfig, ScheduleConfig
from .dynamics import as_dynamics_config, nonfinite_per_stage, stage_stats

Pytree = Any


def make_train_step(cfg: ModelConfig, mesh: Mesh, sched: ScheduleConfig,
                    optimizer: optax.GradientTransformation, moe=None,
                    sp_attn_impl: str = "ring",
                    tp_vocab_parallel: bool = False,
                    fsdp: bool = False, remat_backward=None,
                    unroll_ticks=None, telemetry=None,
                    guard=None, fault_plan=None, dynamics=None,
                    ) -> Callable[[Pytree, Any, jax.Array, jax.Array],
                                  Tuple[Pytree, Any, jax.Array]]:
    """Jitted ``(params, opt_state, tokens, targets) ->
    (params, opt_state, loss)``: pipeline grads + optax update in one XLA
    program (so the update fuses with the grad psum epilogue). ``moe``
    (a MoEConfig) selects MoE pipeline stages — see
    :func:`..parallel.pipeline.make_pipeline_grad_fn`. ``fsdp`` runs
    ZeRO-3 inside the pipeline (params placed via ``fsdp_shard_params``;
    grads come back in the same pipe x data layout, so the optax update —
    elementwise — runs shard-local and moments are born sharded).
    ``remat_backward`` picks the backward's activation policy (None = auto:
    stored where supported; True = rematerialize for minimal activation
    memory — see :func:`..parallel.pipeline.make_pipeline_grad_fn`).
    ``unroll_ticks`` picks the tick-executor formulation (None = auto:
    unrolled up to 64 table rows, phase-compressed scan beyond; also
    ``True``/``False``/``"phases"`` — compile-time economics in
    :func:`..parallel.pipeline.make_pipeline_grad_fn`). ``telemetry``
    (opt-in ``utils.telemetry.PipelineTelemetry``) records a measured
    tick/phase timeline for the grad program; None (default) compiles
    zero instrumentation.

    ``guard`` (a ``utils.resilience.AnomalyGuard``) switches to the
    *guarded* step: ``(params, opt_state, tokens, targets[, rng],
    guard_state) -> (params, opt_state, loss, guard_state)``. Inside the
    same XLA program it checks the loss and a PER-STAGE non-finite
    reduction over the gradients (stages partition the layer stack, so
    the poisoned stage is identified without a host round-trip) and, on
    failure, SELECTS the incoming params/opt_state (the anomalous step
    is skipped, the optimizer clock does not advance) and bumps
    device-resident anomaly counters (``resilience.init_guard_state``)
    including ``last_bad_stage`` — the first non-finite stage index, -2
    when only the loss was non-finite, -1 when no anomaly has fired.
    Everything stays on device — the counters ride the loss fetch at
    the caller's existing sync points, so the happy path costs zero
    extra host syncs. ``fault_plan.nan_grad_steps`` (requires
    ``guard``) poisons the gradients at those global step indices with
    NaN, baked into the traced program as a step-index compare — the
    deterministic blowup the guard tests recover from; with
    ``fault_plan.nan_grad_stage`` set, only that stage's layer-grad
    rows are poisoned (the loss stays finite), exercising the per-stage
    attribution path specifically.

    ``dynamics`` (True or a ``utils.dynamics.DynamicsConfig``) appends a
    device-resident stat dict to the step's outputs — per-stage/
    per-layer grad norms, param RMS, update ratios, non-finite counts
    (:func:`utils.dynamics.stage_stats`) plus, when the pipeline
    supports it (``DynamicsConfig.gns``), the per-microbatch squared
    grad norms feeding the gradient-noise-scale estimator. Like the
    guard counters the dict is read only at the caller's log syncs;
    with ``dynamics`` falsy the traced program is byte-identical to a
    build without the argument."""
    dcfg = as_dynamics_config(dynamics)
    want_gns = dcfg is not None and dcfg.gns
    grad_fn = make_pipeline_grad_fn(cfg, mesh, sched, moe=moe,
                                    sp_attn_impl=sp_attn_impl,
                                    tp_vocab_parallel=tp_vocab_parallel,
                                    fsdp=fsdp, remat_backward=remat_backward,
                                    unroll_ticks=unroll_ticks,
                                    telemetry=telemetry,
                                    dynamics=want_gns)
    n_stages = mesh.shape[PIPE_AXIS] * sched.n_virtual
    nan_steps = tuple(getattr(fault_plan, "nan_grad_steps", ()) or ())
    nan_stage = getattr(fault_plan, "nan_grad_stage", None)
    if nan_steps and guard is None:
        raise ValueError(
            "fault_plan.nan_grad_steps requires an AnomalyGuard — injected "
            "NaN grads without the guard would corrupt the params forever")
    if nan_stage is not None and not 0 <= nan_stage < n_stages:
        raise ValueError(f"fault_plan.nan_grad_stage={nan_stage} out of "
                         f"range for {n_stages} stages")

    def run_grads(params, tokens, targets, rng):
        """(loss, grads, sq_mb|None) — arity bridge over the dynamics
        pipeline variant."""
        args = (params, tokens, targets) + (() if rng is None else (rng,))
        if want_gns:
            return grad_fn(*args)
        loss, grads = grad_fn(*args)
        return loss, grads, None

    def dyn_stats(grads, params, updates, sq_mb):
        stats = stage_stats(cfg.n_layers, n_stages, grads, params=params,
                            updates=updates)
        if sq_mb is not None:
            stats["sq_mb"] = sq_mb
        return stats

    if guard is None:
        if cfg.dropout > 0.0:
            # train-mode dropout: the step takes a per-step PRNG key
            if dcfg is not None:
                @jax.jit
                def train_step_dropout_dyn(params, opt_state, tokens,
                                           targets, rng):
                    loss, grads, sq_mb = run_grads(params, tokens, targets,
                                                   rng)
                    updates, opt_state = optimizer.update(grads, opt_state,
                                                          params)
                    dyn = dyn_stats(grads, params, updates, sq_mb)
                    params = optax.apply_updates(params, updates)
                    return params, opt_state, loss, dyn

                return train_step_dropout_dyn

            @jax.jit
            def train_step_dropout(params, opt_state, tokens, targets, rng):
                loss, grads = grad_fn(params, tokens, targets, rng)
                updates, opt_state = optimizer.update(grads, opt_state,
                                                      params)
                params = optax.apply_updates(params, updates)
                return params, opt_state, loss

            return train_step_dropout

        if dcfg is not None:
            @jax.jit
            def train_step_dyn(params, opt_state, tokens, targets):
                loss, grads, sq_mb = run_grads(params, tokens, targets, None)
                updates, opt_state = optimizer.update(grads, opt_state,
                                                      params)
                dyn = dyn_stats(grads, params, updates, sq_mb)
                params = optax.apply_updates(params, updates)
                return params, opt_state, loss, dyn

            return train_step_dyn

        @jax.jit
        def train_step(params, opt_state, tokens, targets):
            loss, grads = grad_fn(params, tokens, targets)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        return train_step

    def guarded(params, opt_state, tokens, targets, guard_state, rng=None):
        loss, grads, sq_mb = run_grads(params, tokens, targets, rng)
        step = guard_state["step"]
        if nan_steps:
            bad = functools.reduce(
                jnp.logical_or, [step == k for k in nan_steps])
            if nan_stage is None:
                poison = jnp.where(bad, jnp.float32(jnp.nan),
                                   jnp.float32(1.0))
                grads = jax.tree.map(lambda g: g * poison.astype(g.dtype),
                                     grads)
                loss = loss * poison.astype(loss.dtype)
            else:
                # stage-targeted fault: poison only that stage's layer
                # rows and leave the loss finite — ONLY the per-stage
                # reduction can catch and attribute it. Multiplicative
                # (NaN*g) like the global path, not a select: a
                # where(mask, nan, g) per leaf interacts pathologically
                # with XLA:CPU's fusion when max-reductions consume the
                # result (observed 140s vs 50s compiles on the smoke
                # config).
                lps = cfg.n_layers // n_stages
                in_stage = (jnp.arange(cfg.n_layers) // lps) == nan_stage
                row = jnp.where(bad & in_stage, jnp.float32(jnp.nan),
                                jnp.float32(1.0))

                def poison_layer(g):
                    m = row.reshape((cfg.n_layers,) + (1,) * (g.ndim - 1))
                    return g * m.astype(g.dtype)

                grads = dict(grads, layers=jax.tree.map(
                    poison_layer, grads["layers"]))
        # fused per-stage predicate: loss finite AND every stage's grads
        # finite. Computed on device; no host readback here (the caller
        # fetches the guard counters only where it already fetches the
        # loss). The per-stage counts replace the old all-or-nothing
        # global-norm isfinite — same verdict, now attributable.
        nf = nonfinite_per_stage(cfg.n_layers, n_stages, grads)
        loss_ok = jnp.isfinite(loss)
        stage_ok = nf == 0
        grads_ok = stage_ok.all()
        ok = loss_ok & grads_ok
        first_bad = jnp.where(
            grads_ok,
            jnp.where(loss_ok, jnp.int32(-1), jnp.int32(-2)),
            jnp.argmax(~stage_ok).astype(jnp.int32))
        updates, new_opt = optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        dyn = (dyn_stats(grads, params, updates, sq_mb)
               if dcfg is not None else None)

        def keep(new, old):
            return jnp.where(ok, new, old)

        params = jax.tree.map(keep, new_params, params)
        opt_state = jax.tree.map(keep, new_opt, opt_state)
        anom = (~ok).astype(jnp.int32)
        guard_state = {
            "step": step + 1,
            "consec": jnp.where(ok, 0, guard_state["consec"] + 1),
            "total": guard_state["total"] + anom,
            "last_anomaly_step": jnp.where(
                ok, guard_state["last_anomaly_step"], step),
            "last_bad_stage": jnp.where(
                ok, guard_state["last_bad_stage"], first_bad),
        }
        if dcfg is not None:
            return params, opt_state, loss, guard_state, dyn
        return params, opt_state, loss, guard_state

    if cfg.dropout > 0.0:
        @jax.jit
        def guarded_step_dropout(params, opt_state, tokens, targets, rng,
                                 guard_state):
            return guarded(params, opt_state, tokens, targets, guard_state,
                           rng)

        return guarded_step_dropout

    @jax.jit
    def guarded_step(params, opt_state, tokens, targets, guard_state):
        return guarded(params, opt_state, tokens, targets, guard_state)

    return guarded_step


def init_sharded_opt_state(optimizer: optax.GradientTransformation,
                           params: Pytree, mesh: Mesh) -> Pytree:
    """ZeRO-1 init without the replicated peak: compute the state's shape
    tree abstractly, derive FSDP placements, and jit ``optimizer.init``
    with those out_shardings so the moments are born sharded."""
    from jax.sharding import NamedSharding

    from ..parallel.fsdp import fsdp_specs
    from ..parallel.mesh import DATA_AXIS

    n = mesh.shape.get(DATA_AXIS, 1)
    if n <= 1:
        return optimizer.init(params)
    shapes = jax.eval_shape(optimizer.init, params)
    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s),
                             fsdp_specs(shapes, n),
                             is_leaf=lambda x: not isinstance(
                                 x, (dict, list, tuple)))
    return jax.jit(optimizer.init, out_shardings=shardings)(params)


def shard_opt_state(opt_state: Pytree, mesh: Mesh) -> Pytree:
    """ZeRO-1: place optimizer-state leaves (Adam moments etc.) sharded over
    the mesh's 'data' axis, each on its largest divisible dimension
    (reusing the FSDP placement rule). Parameters stay replicated; the
    train step's elementwise update computes on local shards and XLA
    all-gathers the (sharded) updates back onto the replicated params —
    the ZeRO-1 dataflow from sharding annotations alone. Committed input
    shardings propagate through jit — the returned state keeps its data
    sharding across steps (asserted in tests/test_fsdp.py). Composes with
    every pipeline configuration (the grad function runs under its own
    shard_map; only the optax update is affected)."""
    from ..parallel.fsdp import shard_params_fsdp
    from ..parallel.mesh import DATA_AXIS

    if mesh.shape.get(DATA_AXIS, 1) <= 1:
        return opt_state
    return shard_params_fsdp(opt_state, mesh)


def adamw(learning_rate: float = 3e-4, weight_decay: float = 0.01,
          warmup_steps: int = 100, total_steps: int = 10000,
          max_grad_norm: float = 1.0) -> optax.GradientTransformation:
    """Standard LM recipe: global-norm clip + AdamW + linear-warmup cosine.

    Weight decay applies to projection matrices only — biases, norm
    scales/biases, and embeddings are excluded, the standard LM practice
    (decaying LayerNorm scales toward zero actively hurts). Leaf ndim
    cannot distinguish these in the stacked-layer layout (a per-layer bias
    stack is 2-D), so the mask keys off this framework's naming
    convention: matrices live under "w" (linear/attention/router) and
    "w1"/"w2" (MoE expert stacks)."""
    lr = optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=learning_rate, warmup_steps=warmup_steps,
        decay_steps=max(total_steps, warmup_steps + 1))

    def decay_mask(params):
        return jax.tree_util.tree_map_with_path(
            lambda path, _: getattr(path[-1], "key", None) in ("w", "w1", "w2"),
            params)

    return optax.chain(
        optax.clip_by_global_norm(max_grad_norm),
        optax.adamw(lr, weight_decay=weight_decay, mask=decay_mask),
    )


def make_eval_fn(cfg: ModelConfig, mesh: Mesh, sched: ScheduleConfig,
                 moe=None, sp_attn_impl: str = "ring",
                 tp_vocab_parallel: bool = False, fsdp: bool = False,
                 ) -> Callable[[Pytree, jax.Array, jax.Array], jax.Array]:
    """Jitted eval-mode loss over the mesh. Every training mesh (data x
    pipe x model x seq x expert, any n_virtual, incl. vocab-parallel CE
    and MoE stages) uses the forward-only pipelined loss — no backward,
    no rematerialization. **MoE convention**: the eval loss is the CE
    term only (the routing load-balance aux is a training regularizer,
    not a model-quality quantity — perplexity comes from CE), so an MoE
    eval loss is directly comparable across capacity/aux settings. Any
    configuration the training step accepts evaluates here (both require
    n_layers to divide the stage count); dropout configs evaluate in
    eval mode (dropout off)."""
    from ..parallel.pipeline import make_pipeline_loss_fn

    eval_cfg = (dataclasses.replace(cfg, dropout=0.0)
                if cfg.dropout else cfg)
    return make_pipeline_loss_fn(eval_cfg, mesh, sched,
                                 sp_attn_impl=sp_attn_impl,
                                 tp_vocab_parallel=tp_vocab_parallel,
                                 fsdp=fsdp, moe=moe)


def evaluate(eval_fn, params, data: Iterator[Tuple[jax.Array, jax.Array]],
             num_batches: int) -> dict:
    """Mean eval loss and perplexity over ``num_batches`` from ``data``.

    The reference has no evaluation path at all (SURVEY.md §5: loss values
    are never asserted, data is random tokens); this is the standard LM eval
    the model ladder needs. Returns ``{"eval_loss", "perplexity",
    "num_batches"}``; perplexity = exp(mean token CE).
    """
    total = 0.0
    n = 0
    for _ in range(num_batches):
        try:
            tokens, targets = next(data)
        except StopIteration:
            break
        total += float(eval_fn(params, tokens, targets))
        n += 1
    if n == 0:
        raise ValueError("evaluate: data iterator yielded no batches")
    mean = total / n
    return {"eval_loss": mean, "perplexity": math.exp(min(mean, 700.0)),
            "num_batches": n}


def _latest_step_dir(checkpoint_dir: str) -> Optional[Tuple[int, str]]:
    """Find the newest *committed* ``step_{n}`` checkpoint under
    ``checkpoint_dir``. Picking the newest dir by number alone would
    hand resume a partially-written async save that died mid-flush;
    :func:`.resilience.latest_committed_step_dir` skips uncommitted
    shells (warning on fallback) and only trusts a marker-less tree
    when NO dir has a marker (legacy checkpoints)."""
    from .resilience import latest_committed_step_dir
    return latest_committed_step_dir(checkpoint_dir)


def fit(cfg: ModelConfig, mesh: Mesh, sched: ScheduleConfig, params: Pytree,
        data: Iterator[Tuple[jax.Array, jax.Array]], num_steps: int,
        optimizer: Optional[optax.GradientTransformation] = None,
        log_every: int = 10, verbose: bool = True,
        checkpoint_dir: Optional[str] = None, checkpoint_every: int = 0,
        resume: bool = False, skip_data_on_resume: bool = True,
        metrics_path: Optional[str] = None, moe=None,
        sp_attn_impl: str = "ring", tp_vocab_parallel: bool = False,
        zero1: bool = False, fsdp: bool = False, remat_backward=None,
        unroll_ticks=None,
        dropout_seed: int = 0,
        eval_data: Optional[Callable[[], Iterator]] = None,
        eval_every: int = 0, eval_batches: int = 8,
        profile_dir: Optional[str] = None,
        profile_steps: Tuple[int, int] = (2, 5),
        grad_accum: int = 1,
        report_dir: Optional[str] = None,
        telemetry=None,
        keep_last: Optional[int] = None,
        guard=None, fault_plan=None,
        handle_preemption: bool = False,
        stall_timeout_s: Optional[float] = None,
        dynamics=None):
    """Training loop over a ``(tokens, targets)`` iterator.

    Returns (params, list of (step, loss)). The data contract matches the
    reference's synthetic setup (random token batches,
    ``LLMsDistributedTrainingHelper.py:191-194``) but accepts any iterator.

    Beyond the minimal loop (capabilities the reference lacks, SURVEY.md §5):

    - ``checkpoint_dir`` + ``checkpoint_every``: save
      ``{'params', 'opt_state', 'step'}`` to ``step_{n}/`` via Orbax every n
      steps (and at the end); ``resume=True`` restores the newest one and
      continues counting from it. With ``skip_data_on_resume`` (default) the
      completed steps' batches are drained from ``data`` first, so re-running
      an interrupted job with the same (deterministic) data stream reproduces
      the uninterrupted run instead of double-training early batches. Pass
      ``False`` only if the caller re-positions the iterator itself.
    - ``metrics_path``: append one JSON line per log point —
      ``{"step", "loss", "tokens_per_sec", "elapsed_s"}`` — the streaming
      twin of the sweep's metrics dict (same tokens/sec definition:
      batch*seq*steps / wall-clock between log points).
    - ``eval_data`` + ``eval_every``: every n steps (and at the end), run
      :func:`evaluate` over ``eval_batches`` batches from a FRESH iterator
      (``eval_data`` is a zero-arg callable returning one, so the same
      held-out batches are scored every time); results go to the metrics
      stream and (``verbose``) stdout. Eval runs in eval mode
      (no dropout) on the forward-only pipelined loss where the mesh allows.
    - ``profile_dir``: capture a ``jax.profiler`` trace (XProf/TensorBoard)
      of steps ``profile_steps`` = [start, end) — default (2, 5): past the
      compile step, three steady-state steps.
    - ``grad_accum``: average gradients over k data batches before each
      optimizer update (``optax.MultiSteps``) — accumulation ACROSS steps,
      on top of the within-step microbatch accumulation the pipeline
      schedule already performs. k accumulated steps on batch B step the
      optimizer exactly as one step on batch k*B would. ``num_steps``
      counts data batches, so optimizer updates = num_steps / k.
    - ``report_dir``: write a structured :class:`.telemetry.RunReport` —
      ``events.jsonl`` streamed as the run progresses (every train-log and
      eval point) plus a final ``report.json`` manifest (config, mesh
      shape, schedule, compile_s, jax/jaxlib versions, final metrics) in
      the schema ``telemetry.validate_report`` checks — the same schema
      sweep rows and ``bench.py`` emit (docs/observability.md).
    - ``telemetry``: opt-in ``telemetry.PipelineTelemetry`` wired into the
      compiled step (measured tick/phase timeline); its analysis is
      embedded in the report manifest when ``report_dir`` is also set.

    Resilience (docs/resilience.md; all opt-in, off by default):

    - Checkpoints go through ``resilience.CheckpointManager``: every save
      is committed via an atomic marker (step, config fingerprint, pytree
      digest) once its flush lands, resume restores the newest *committed*
      checkpoint (skipping shells a killed async save left behind), and
      ``keep_last`` garbage-collects older committed ones.
    - ``guard`` (``True`` or a ``resilience.AnomalyGuard``): the jitted
      step skips non-finite steps (see :func:`make_train_step`); the
      device-resident counters are read only at log points (zero extra
      syncs per step), anomalies land as report events/counters, and
      exceeding the consecutive-anomaly budget checkpoints the last good
      state and raises ``resilience.AnomalyBudgetExceeded``.
    - ``handle_preemption``: SIGTERM/SIGINT finish the in-flight step,
      write a synchronous committed checkpoint, emit a ``preempted``
      event and return normally — the resumed run continues bit-exact.
    - ``stall_timeout_s``: a wall-clock watchdog thread logs (and
      reports) a ``stall`` diagnostic when no step completes in time.
    - Any other crash banks the last completed step in a committed
      checkpoint before the exception propagates.
    - ``fault_plan`` (``resilience.FaultPlan``) injects deterministic
      faults — NaN grads, data-iterator failure, kill-during-save,
      simulated preemption — for the resilience tests and smoke.

    Training dynamics (docs/observability.md §7; opt-in, off by default):

    - ``dynamics`` (``True`` or a ``dynamics.DynamicsConfig``): per-stage /
      per-layer gradient statistics computed inside the jitted step and
      read only at log points (riding the loss sync — zero extra syncs), a
      gradient-noise-scale estimate from the per-microbatch squared norms
      the pipeline accumulates anyway, a host-side ring buffer of recent
      step stats + batch digests, and — on an anomaly or a z-score loss
      spike — a forensic bundle written next to the manifest (requires
      ``report_dir``). With ``guard`` set, skipped steps additionally emit
      an ``anomaly_attributed`` event naming the first non-finite stage.
      ``dynamics=None`` (default) leaves the compiled step byte-identical.
    """
    from .resilience import (AnomalyBudgetExceeded, AnomalyGuard,
                             CheckpointManager, PreemptionHandler,
                             SimulatedKill, StepWatchdog,
                             config_fingerprint, init_guard_state)
    if guard is True:
        guard = AnomalyGuard()
    if optimizer is None:
        # the LR schedule advances once per OPTIMIZER update, which under
        # grad_accum happens every k data batches — size its horizon in
        # updates, not batches, or warmup/decay stretch k times too long
        optimizer = adamw(total_steps=max(1, num_steps // grad_accum))
    if grad_accum > 1:
        optimizer = optax.MultiSteps(optimizer, every_k_schedule=grad_accum)
    dcfg = as_dynamics_config(dynamics)
    step_fn = make_train_step(cfg, mesh, sched, optimizer, moe=moe,
                              sp_attn_impl=sp_attn_impl,
                              tp_vocab_parallel=tp_vocab_parallel,
                              fsdp=fsdp, remat_backward=remat_backward,
                              unroll_ticks=unroll_ticks,
                              telemetry=telemetry,
                              guard=guard, fault_plan=fault_plan,
                              dynamics=dcfg)
    report = None
    if report_dir is not None:
        from .telemetry import RunReport
        report = RunReport(out_dir=report_dir, name="fit")
        # artifact-backed schedules record their certification pin (table
        # digest + fingerprint + source) so the manifest names exactly
        # which certified table the run executed
        from ..parallel.schedules import registered_artifact_info
        art_info = registered_artifact_info(sched.name)
        report.set_meta(config=dataclasses.asdict(cfg),
                        schedule=dataclasses.asdict(sched),
                        mesh_shape=dict(mesh.shape),
                        num_steps=num_steps, grad_accum=grad_accum,
                        backend=jax.devices()[0].platform,
                        **({"schedule_artifact": art_info}
                           if art_info else {}))
    if fsdp and zero1:
        raise ValueError("fsdp already shards optimizer state (ZeRO-3 "
                         "subsumes ZeRO-1) — drop --zero1")
    if fsdp:
        # pp x fsdp (ZeRO-3 in-pipeline): params rest pipe x data sharded;
        # the elementwise optax init/update inherits that layout through
        # jit, so moments are born sharded with no extra machinery
        from ..parallel.pipeline import fsdp_shard_params
        params = fsdp_shard_params(params, cfg, mesh, moe=moe)
        opt_state = jax.jit(optimizer.init)(params)
    elif zero1:
        # init directly INTO the sharded layout: the replicated moments
        # never materialize, so the ZeRO-1 memory ceiling holds at init too
        opt_state = init_sharded_opt_state(optimizer, params, mesh)
    else:
        opt_state = optimizer.init(params)

    mgr = None
    if checkpoint_dir:
        mgr = CheckpointManager(
            checkpoint_dir, keep_last=keep_last,
            fingerprint=config_fingerprint(cfg, sched, dict(mesh.shape)),
            fault_plan=fault_plan)
    if fault_plan is not None:
        data = fault_plan.wrap_data(data)

    start_step = 0
    if resume and mgr is not None:
        restored = mgr.restore_latest({
            "params": params, "opt_state": opt_state,
            "step": jnp.asarray(0)})
        if restored is not None:
            n, path, state = restored
            # the restore template carries the live shardings (see
            # checkpoint.restore_checkpoint), so a zero1 run restores its
            # moments directly into the sharded layout
            params, opt_state = state["params"], state["opt_state"]
            start_step = int(state["step"]) + 1
            if skip_data_on_resume:
                for _ in range(start_step):
                    next(data)
            if verbose:
                print(f"resumed from {path} (step {n})", flush=True)
            if report is not None:
                report.event("resumed", step=n, path=path)

    def _save(i, wait=True):
        mgr.save(i, {"params": params, "opt_state": opt_state,
                     "step": jnp.asarray(i)}, wait=wait)

    guard_state = init_guard_state(start_step) if guard is not None else None
    guard_seen = 0  # anomalies already surfaced (host high-water mark)

    # Training-dynamics host state: GNS estimator over the per-microbatch
    # squared norms, ring buffer + spike detector, and the latest device
    # stats (fetched only at log syncs). All None when dynamics is off.
    gns_est = None
    recorder = None
    dyn_latest = None  # device-resident stats from the newest step
    dyn_host = None    # host copy fetched at the last log sync
    n_skipped_attributed = 0
    if dcfg is not None:
        from .dynamics import (GNSEstimator, ForensicRecorder, batch_digest,
                               dynamics_section)
        recorder = ForensicRecorder(out_dir=report_dir, ring=dcfg.ring,
                                    spike_z=dcfg.spike_z,
                                    warmup=dcfg.spike_warmup)

    def _checkpoint_pointer():
        """Last committed checkpoint step/path for forensic bundles."""
        if mgr is None:
            return None
        s = mgr.stats()
        return {k: s[k] for k in ("last_committed_step",) if k in s}

    # Per-step dropout keys fold the step index from one base key, so a
    # resumed run draws the same masks the uninterrupted run would have.
    drop_key = jax.random.key(dropout_seed) if cfg.dropout > 0.0 else None

    eval_fn = None
    if eval_data is not None and eval_every:
        eval_fn = make_eval_fn(cfg, mesh, sched, moe=moe,
                               sp_attn_impl=sp_attn_impl,
                               tp_vocab_parallel=tp_vocab_parallel,
                               fsdp=fsdp)

    def _eval(i):
        m = evaluate(eval_fn, params, eval_data(), eval_batches)
        if verbose:
            print(f"step {i}: eval_loss {m['eval_loss']:.4f} "
                  f"ppl {m['perplexity']:.2f}", flush=True)
        if metrics_path:
            with open(metrics_path, "a") as f:
                f.write(json.dumps({"step": i, **m}) + "\n")
        if report is not None:
            report.event("eval", step=i, **m)
        return m

    preempt = PreemptionHandler(enabled=handle_preemption)
    watchdog = None
    if stall_timeout_s:
        def _on_stall(info):
            logging.getLogger(__name__).warning(
                "fit: no step completed in %.1fs (last completed step %s) "
                "— stalled collective or dead input pipeline?",
                info["stalled_s"], info["step"])
            if report is not None:
                report.count("stalls")
                report.event("stall", **info)
        watchdog = StepWatchdog(stall_timeout_s, _on_stall)

    history = []
    window_start = time.perf_counter()
    window_tokens = 0
    profiling = False
    preempted = False
    last_done = start_step - 1  # newest step whose outputs params hold
    data_shape = None  # (batch, seq) of the first batch, for the cost model

    def _finalize_report():
        if report is None:
            return
        report.count("steps", max(last_done - start_step + 1, 0))
        if history:
            report.gauge("final_loss", history[-1][1])
        if telemetry is not None:
            report.attach_telemetry(telemetry)
            # close the predicted<->measured loop: roofline section over
            # the same compiled table the stamps were recorded against
            # (docs/observability.md "Cost model & MFU"); never lets an
            # accounting error take down the run's report
            if telemetry.events and data_shape is not None:
                try:
                    from ..analysis.calibration import (
                        calibration_section_from_cost_model,
                        maybe_load_default_corrections)
                    from ..analysis.cost_model import cost_model_section
                    from ..parallel.schedules import compile_schedule
                    cs = compile_schedule(sched.name, mesh.shape["pipe"],
                                          sched.n_virtual,
                                          sched.n_microbatches)
                    if (telemetry.table is not None
                            and cs.table.shape == telemetry.table.shape):
                        corrections = maybe_load_default_corrections()
                        cm = cost_model_section(
                            cs, cfg, batch_size=data_shape[0],
                            seq_length=data_shape[1],
                            remat_backward=remat_backward,
                            telemetry=telemetry, correction=corrections)
                        report.attach_cost_model(cm)
                        # the run's own predicted-vs-measured point
                        # (docs/observability.md §9)
                        cal = calibration_section_from_cost_model(
                            cm, backend=jax.devices()[0].platform,
                            name=f"train_{sched.name}",
                            correction=corrections)
                        if cal is not None:
                            report.attach_calibration(cal)
                except Exception as e:
                    report.event("cost_model_error", error=str(e))
        if data_shape is not None:
            # bytes-domain twin of the cost-model attach: analytic HBM
            # from the verifier's slot peaks (+ AdamW's two fp32 moments)
            # plus any live watermarks the stamps sampled — same
            # never-take-down-the-run discipline
            try:
                from ..analysis.memory_model import memory_model_section
                from ..parallel.schedules import compile_schedule
                cs = compile_schedule(sched.name, mesh.shape["pipe"],
                                      sched.n_virtual, sched.n_microbatches)
                report.attach_memory(memory_model_section(
                    cs, cfg, batch_size=data_shape[0],
                    seq_length=data_shape[1],
                    remat_backward=remat_backward,
                    optimizer_slots=2, telemetry=telemetry))
            except Exception as e:
                report.event("memory_model_error", error=str(e))
        if dcfg is not None:
            report.attach_dynamics(dynamics_section(
                mesh.shape[PIPE_AXIS] * sched.n_virtual,
                last_stats=dyn_host,
                gns=gns_est.value() if gns_est is not None else None,
                gns_updates=gns_est.n_updates if gns_est is not None else 0,
                n_skipped_attributed=n_skipped_attributed,
                forensic_bundles=recorder.bundles))
        res = {}
        if mgr is not None:
            res.update(mgr.stats())
        if guard is not None:
            res["anomaly_budget"] = guard.max_consecutive
            res["anomalies"] = guard_seen
        if handle_preemption or (fault_plan is not None
                                 and fault_plan.preempt_at_step is not None):
            res["preempted"] = preempted
        if watchdog is not None:
            res["stalls"] = watchdog.stalls
        if res:
            report.attach_resilience(res)
        report.write()

    # profile_steps counts from the first step THIS run executes, so a
    # resumed job still captures a window instead of silently skipping it
    prof_start = start_step + profile_steps[0]
    prof_stop = start_step + max(profile_steps[1], profile_steps[0] + 1)
    try:
        with preempt:
            for i in range(start_step, num_steps):
                if fault_plan is not None and fault_plan.preempt_at_step == i:
                    preempt.trigger()  # deterministic stand-in for SIGTERM
                if profile_dir is not None:
                    if i == prof_start and not profiling:
                        jax.profiler.start_trace(profile_dir)
                        profiling = True
                    elif i == prof_stop and profiling:
                        jax.profiler.stop_trace()
                        profiling = False
                        if verbose:
                            print(f"profile trace written to {profile_dir}",
                                  flush=True)
                tokens, targets = next(data)
                if data_shape is None:
                    data_shape = (int(tokens.shape[0]), int(tokens.shape[1]))
                if recorder is not None:
                    # inputs are host-visible already — hashing adds no sync
                    recorder.note_batch(i, batch_digest(tokens, targets))
                # first executed step = trace + compile + run; the report's
                # compile_s timer brackets it (forced, so the timer is honest)
                first = report is not None and i == start_step
                with (report.timer("compile_s") if first
                      else contextlib.nullcontext()):
                    args = (params, opt_state, tokens, targets)
                    if drop_key is not None:
                        args += (jax.random.fold_in(drop_key, i),)
                    if guard_state is not None and dcfg is not None:
                        (params, opt_state, loss, guard_state,
                         dyn_latest) = step_fn(*args, guard_state)
                    elif guard_state is not None:
                        params, opt_state, loss, guard_state = step_fn(
                            *args, guard_state)
                    elif dcfg is not None:
                        params, opt_state, loss, dyn_latest = step_fn(*args)
                    else:
                        params, opt_state, loss = step_fn(*args)
                    if first:
                        jax.block_until_ready(loss)
                last_done = i
                if watchdog is not None:
                    watchdog.beat(i)
                window_tokens += tokens.shape[0] * tokens.shape[1]
                if i % log_every == 0 or i == num_steps - 1:
                    loss_f = float(loss)  # device sync: closes the timing window
                    elapsed = time.perf_counter() - window_start
                    history.append((i, loss_f))
                    if verbose:
                        print(f"step {i}: loss {loss_f:.4f}", flush=True)
                    if metrics_path:
                        with open(metrics_path, "a") as f:
                            f.write(json.dumps({
                                "step": i, "loss": loss_f,
                                "tokens_per_sec": round(window_tokens / elapsed,
                                                        2),
                                "elapsed_s": round(elapsed, 4)}) + "\n")
                    if report is not None:
                        report.event("train_log", step=i, loss=loss_f,
                                     tokens_per_sec=round(window_tokens / elapsed,
                                                          2),
                                     elapsed_s=round(elapsed, 4))
                    if dyn_latest is not None:
                        # same program as the loss just fetched — this read
                        # rides that sync, it does not add one
                        dyn_host = jax.device_get(dyn_latest)
                        if (dyn_host.get("sq_mb") is not None
                                and data_shape is not None):
                            if gns_est is None:
                                nd = dict(mesh.shape).get("data", 1)
                                toks = data_shape[0] * data_shape[1]
                                small = toks / (nd * sched.n_microbatches)
                                if small < toks:  # M*data==1: no norm pair
                                    gns_est = GNSEstimator(
                                        batch_small=small, batch_big=toks,
                                        ema=dcfg.ema)
                            if gns_est is not None:
                                gns_est.update(
                                    float(dyn_host["sq_mb"].mean()),
                                    float(dyn_host["grad_norm"]) ** 2)
                        gns_val = (gns_est.value() if gns_est is not None
                                   else None)
                        if report is not None:
                            report.event(
                                "dynamics", step=i,
                                grad_norm=float(dyn_host["grad_norm"]),
                                grad_norm_per_stage=[
                                    float(x) for x in
                                    dyn_host["grad_norm_per_stage"]],
                                nonfinite_per_stage=[
                                    int(x) for x in
                                    dyn_host["nonfinite_per_stage"]],
                                gns=gns_val)
                        spike_z = recorder.observe(i, loss_f, stats=dyn_host,
                                                   gns=gns_val)
                        if spike_z is not None:
                            path = recorder.dump(
                                i, "loss_spike", loss=loss_f, z=spike_z,
                                stats={k: v for k, v in dyn_host.items()
                                       if k != "sq_mb"},
                                checkpoint=_checkpoint_pointer())
                            if verbose:
                                print(f"step {i}: loss spike (z={spike_z:.1f})"
                                      + (f" — forensics at {path}"
                                         if path else ""), flush=True)
                            if report is not None:
                                report.count("loss_spikes")
                                report.event("loss_spike", step=i,
                                             loss=loss_f,
                                             z=round(float(spike_z), 2),
                                             bundle=path)
                    if guard_state is not None:
                        # the counters were computed by the same program as the
                        # loss just fetched — this read rides that sync, it
                        # does not add one
                        gs = {k: int(v)
                              for k, v in jax.device_get(guard_state).items()}
                        if gs["total"] > guard_seen:
                            delta = gs["total"] - guard_seen
                            guard_seen = gs["total"]
                            bad = gs.get("last_bad_stage", -1)
                            where = (f" in stage {bad}" if bad >= 0
                                     else " (loss only)" if bad == -2 else "")
                            if verbose:
                                print(f"step {i}: anomaly guard skipped {delta} "
                                      f"step(s) (total {gs['total']}, last at "
                                      f"step {gs['last_anomaly_step']}{where})",
                                      flush=True)
                            if report is not None:
                                report.count("anomalies", delta)
                                report.event(
                                    "anomaly", step=i, total=gs["total"],
                                    consec=gs["consec"],
                                    last_anomaly_step=gs["last_anomaly_step"],
                                    last_bad_stage=bad)
                            if dcfg is not None:
                                # explainable verdict: which stage first went
                                # non-finite, and on what statistic
                                n_skipped_attributed += delta
                                statistic = ("nonfinite_grad" if bad >= 0
                                             else "nonfinite_loss")
                                attribution = {
                                    "stage": bad, "statistic": statistic,
                                    "last_anomaly_step":
                                        gs["last_anomaly_step"]}
                                if dyn_host is not None:
                                    attribution["nonfinite_per_stage"] = [
                                        int(x) for x in
                                        dyn_host["nonfinite_per_stage"]]
                                if report is not None:
                                    report.event("anomaly_attributed", step=i,
                                                 **attribution)
                                path = recorder.dump(
                                    i, "anomaly", loss=loss_f,
                                    stats=None if dyn_host is None else {
                                        k: v for k, v in dyn_host.items()
                                        if k != "sq_mb"},
                                    attribution=attribution,
                                    checkpoint=_checkpoint_pointer())
                                if report is not None and path is not None:
                                    report.event("forensic_bundle", step=i,
                                                 trigger="anomaly",
                                                 bundle=path)
                        if gs["consec"] >= guard.max_consecutive:
                            # params/opt_state are the last GOOD state — every
                            # anomalous update was selected away in the step
                            if report is not None:
                                report.count("anomaly_aborts")
                                report.event("anomaly_abort", step=i,
                                             consec=gs["consec"],
                                             budget=guard.max_consecutive)
                            if mgr is not None:
                                _save(i, wait=True)
                            _finalize_report()
                            raise AnomalyBudgetExceeded(
                                f"{gs['consec']} consecutive anomalous steps at "
                                f"step {i} (budget {guard.max_consecutive})"
                                + (" — last good state checkpointed"
                                   if mgr is not None else ""))
                    window_start = time.perf_counter()
                    window_tokens = 0
                if (eval_fn is not None and (i + 1) % eval_every == 0
                        and i != num_steps - 1):
                    _eval(i)
                    # eval time isn't train time: restart the whole timing
                    # window (tokens too, else tokens_per_sec over-reports)
                    window_start = time.perf_counter()
                    window_tokens = 0
                if preempt.triggered:
                    # the in-flight step already finished (the handler only
                    # sets a flag): bank it synchronously and exit resumable
                    preempted = True
                    sig = preempt.signum
                    if verbose:
                        print(f"step {i}: preemption ({sig}) — checkpointing "
                              "and exiting resumable", flush=True)
                    if report is not None:
                        report.count("preemptions")
                        report.event("preempted", step=i,
                                     signal=int(sig) if sig is not None
                                     else None)
                    if mgr is not None:
                        _save(i, wait=True)
                    break
                if (mgr is not None and checkpoint_every
                        and (i + 1) % checkpoint_every == 0
                        and i != num_steps - 1):
                    _save(i, wait=False)  # flush in background; training goes on
    except (SimulatedKill, AnomalyBudgetExceeded):
        raise  # injected death / already-handled abort: no crash save
    except BaseException as e:
        # crash-safe exit: params/opt_state are step last_done's outputs —
        # bank them committed so the run resumes instead of restarting
        if mgr is not None and last_done >= start_step:
            try:
                _save(last_done, wait=True)
                if verbose:
                    print(f"crash at step {last_done + 1}: banked committed "
                          f"checkpoint at step {last_done}", flush=True)
            except Exception:
                logging.getLogger(__name__).exception(
                    "fit: crash checkpoint at step %d failed", last_done)
        if report is not None:
            report.event("crash", step=last_done, error=repr(e))
            with contextlib.suppress(Exception):
                _finalize_report()
        raise
    finally:
        if watchdog is not None:
            watchdog.stop()
        if profiling:  # the profile window ran past the last executed step
            jax.profiler.stop_trace()
            profiling = False
    if eval_fn is not None and num_steps > start_step and not preempted:
        _eval(num_steps - 1)
    if (mgr is not None and checkpoint_every and num_steps > start_step
            and not preempted):
        _save(num_steps - 1)
    if mgr is not None:
        mgr.commit_pending()
    _finalize_report()
    return params, history


def synthetic_data(cfg: ModelConfig, batch_size: int, seq_length: int,
                   seed: int = 0) -> Iterator[Tuple[jax.Array, jax.Array]]:
    """Random-token batches, the reference's data regime. Targets are the
    inputs shifted by one (next-token prediction), unlike the reference's
    independent random targets — random targets make loss a constant-entropy
    floor, which is useless for verifying that optimization works.

    Thin wrapper over :func:`.data.synthetic_batches` (the single
    implementation of the regime) with the model config supplying vocab."""
    from .data import synthetic_batches
    return synthetic_batches(cfg.vocab_size, batch_size, seq_length, seed=seed)
