"""Optimizer-coupled training on top of the pipeline executor.

The reference *measures* forward+backward only — no ``optim.step()`` exists
anywhere in it (SURVEY.md §3.3 note) — so the benchmark path
(:func:`..parallel.pipeline.make_pipeline_step`) stays optimizer-free for
parity. Real training on the model ladder (GPT-2 / Llama configs) composes
the same pipeline gradients with an optax optimizer under a single jit here.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from ..parallel.pipeline import make_pipeline_grad_fn
from .config import ModelConfig, ScheduleConfig

Pytree = Any


def make_train_step(cfg: ModelConfig, mesh: Mesh, sched: ScheduleConfig,
                    optimizer: optax.GradientTransformation,
                    ) -> Callable[[Pytree, Any, jax.Array, jax.Array],
                                  Tuple[Pytree, Any, jax.Array]]:
    """Jitted ``(params, opt_state, tokens, targets) ->
    (params, opt_state, loss)``: pipeline grads + optax update in one XLA
    program (so the update fuses with the grad psum epilogue)."""
    grad_fn = make_pipeline_grad_fn(cfg, mesh, sched)

    @jax.jit
    def train_step(params, opt_state, tokens, targets):
        loss, grads = grad_fn(params, tokens, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step


def adamw(learning_rate: float = 3e-4, weight_decay: float = 0.01,
          warmup_steps: int = 100, total_steps: int = 10000,
          max_grad_norm: float = 1.0) -> optax.GradientTransformation:
    """Standard LM recipe: global-norm clip + AdamW + linear-warmup cosine."""
    lr = optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=learning_rate, warmup_steps=warmup_steps,
        decay_steps=max(total_steps, warmup_steps + 1))
    return optax.chain(
        optax.clip_by_global_norm(max_grad_norm),
        optax.adamw(lr, weight_decay=weight_decay),
    )


def fit(cfg: ModelConfig, mesh: Mesh, sched: ScheduleConfig, params: Pytree,
        data: Iterator[Tuple[jax.Array, jax.Array]], num_steps: int,
        optimizer: Optional[optax.GradientTransformation] = None,
        log_every: int = 10, verbose: bool = True):
    """Minimal training loop over a ``(tokens, targets)`` iterator.

    Returns (params, list of (step, loss)). The data contract matches the
    reference's synthetic setup (random token batches,
    ``LLMsDistributedTrainingHelper.py:191-194``) but accepts any iterator.
    """
    optimizer = optimizer or adamw(total_steps=num_steps)
    step_fn = make_train_step(cfg, mesh, sched, optimizer)
    opt_state = optimizer.init(params)
    history = []
    for i in range(num_steps):
        tokens, targets = next(data)
        params, opt_state, loss = step_fn(params, opt_state, tokens, targets)
        if i % log_every == 0 or i == num_steps - 1:
            loss_f = float(loss)
            history.append((i, loss_f))
            if verbose:
                print(f"step {i}: loss {loss_f:.4f}", flush=True)
    return params, history


def synthetic_data(cfg: ModelConfig, batch_size: int, seq_length: int,
                   seed: int = 0) -> Iterator[Tuple[jax.Array, jax.Array]]:
    """Random-token batches, the reference's data regime. Targets are the
    inputs shifted by one (next-token prediction), unlike the reference's
    independent random targets — random targets make loss a constant-entropy
    floor, which is useless for verifying that optimization works.

    Thin wrapper over :func:`.data.synthetic_batches` (the single
    implementation of the regime) with the model config supplying vocab."""
    from .data import synthetic_batches
    return synthetic_batches(cfg.vocab_size, batch_size, seq_length, seed=seed)
