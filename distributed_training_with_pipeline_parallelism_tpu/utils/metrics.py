"""Timed training iterations + the reference's metrics dict.

Parity with ``run_train_iterations`` (SURVEY.md C4,
``LLMsDistributedTrainingHelper.py:98-143``): 2 untimed warmup iterations,
``num_iterations`` timed schedule steps (forward + backward + inter-stage
transfer, **no optimizer** — the reference never creates one, SURVEY.md §3.3
note), throughput = batch * seq * iters / elapsed, and the same result dict
``{"elapsed_time", "throughput", "tokens_processed"}``.

In SPMD there is no rank-role dispatch (the reference feeds x on rank 0 and
target=y on the last rank): every device runs the same program, and
``jax.block_until_ready`` around the timed loop gives the honest wall-clock
the reference gets from process joins.
"""

from __future__ import annotations

import time
from typing import Callable, Dict

import jax


def run_train_iterations(step: Callable, params, tokens, targets,
                         num_iterations: int = 10,
                         warmup_iterations: int = 2) -> Dict[str, float]:
    """Time ``num_iterations`` pipeline steps after untimed warmup."""
    total_toks = tokens.shape[0] * tokens.shape[1] * num_iterations

    out = None
    for _ in range(warmup_iterations):
        out = step(params, tokens, targets)
    if out is not None:
        jax.block_until_ready(out)

    start = time.perf_counter()
    for _ in range(num_iterations):
        out = step(params, tokens, targets)
    jax.block_until_ready(out)
    elapsed = time.perf_counter() - start

    return {
        "elapsed_time": elapsed,
        "throughput": total_toks / elapsed,
        "tokens_processed": total_toks,
    }
