"""Timed training iterations + the reference's metrics dict.

Parity with ``run_train_iterations`` (SURVEY.md C4,
``LLMsDistributedTrainingHelper.py:98-143``): 2 untimed warmup iterations,
``num_iterations`` timed schedule steps (forward + backward + inter-stage
transfer, **no optimizer** — the reference never creates one, SURVEY.md §3.3
note), throughput = batch * seq * iters / elapsed, and the same result dict
``{"elapsed_time", "throughput", "tokens_processed"}``.

In SPMD there is no rank-role dispatch (the reference feeds x on rank 0 and
target=y on the last rank): every device runs the same program. Honest
wall-clock (the reference gets it from process joins) comes from
:func:`force_completion` — fetching an output scalar to the host — because
``jax.block_until_ready`` alone does not reliably wait for execution through
remote-device tunnels (observed: it returned in ~0.3 ms for a ~20 ms step);
a device-to-host read of the last step's output cannot complete before the
FIFO device queue drains.
"""

from __future__ import annotations

import time
from typing import Callable, Dict

import jax


def force_completion(out) -> None:
    """Force real completion of every computation enqueued so far by reading
    the smallest *array* leaf of ``out`` (for a ``(loss, grads)`` pair: the
    scalar loss) back to the host. Non-array leaves can't synchronize, so
    they are ignored; with no array leaves at all, fall back to
    ``block_until_ready`` (a no-op on host values)."""
    arrays = [x for x in jax.tree.leaves(out) if isinstance(x, jax.Array)]
    if arrays:
        jax.device_get(min(arrays, key=lambda x: x.size))
    else:
        jax.block_until_ready(out)


def run_train_iterations(step: Callable, params, tokens, targets,
                         num_iterations: int = 10,
                         warmup_iterations: int = 2,
                         report=None,
                         telemetry=None) -> Dict[str, float]:
    """Time ``num_iterations`` pipeline steps after untimed warmup.

    ``report`` (opt-in :class:`.telemetry.RunReport`) records the warmup
    (compile-inclusive) and timed-loop wall clocks as timers plus the
    returned metrics as gauges. ``telemetry`` (opt-in
    :class:`.telemetry.PipelineTelemetry`, already wired into ``step``) is
    reset after warmup so its recorded events cover exactly the timed
    iterations."""
    total_toks = tokens.shape[0] * tokens.shape[1] * num_iterations

    warm0 = time.perf_counter()
    out = None
    for _ in range(warmup_iterations):
        out = step(params, tokens, targets)
    if out is not None:
        force_completion(out)
    if report is not None:
        report.timers["warmup_s"] = time.perf_counter() - warm0
    if telemetry is not None:
        telemetry.reset()  # timeline covers the timed loop only

    start = time.perf_counter()
    for _ in range(num_iterations):
        out = step(params, tokens, targets)
    force_completion(out)
    elapsed = time.perf_counter() - start

    metrics = {
        "elapsed_time": elapsed,
        "throughput": total_toks / elapsed,
        "tokens_processed": total_toks,
    }
    if report is not None:
        report.timers["timed_loop_s"] = elapsed
        report.count("timed_iterations", num_iterations)
        for k, v in metrics.items():
            report.gauge(k, v)
        if telemetry is not None:
            report.attach_telemetry(telemetry)
    return metrics
