"""Pipeline telemetry: measured tick/phase timelines + structured run reports.

The reference's only instrumentation is ``time.time()`` around the timed
loop (SURVEY.md §5). This module makes the *measured* counterpart of the
simulated tick timeline (``schedules.simulated_bubble``, ``replay_phases``)
first-class, following arXiv:2605.24006's argument that the tick table is
the right axis for evaluation and arXiv:2401.10241's that per-stage idle
time should be measured, not inferred.

Two pieces:

- :class:`PipelineTelemetry` — an opt-in recorder the executors in
  ``parallel.pipeline`` stamp from inside the traced program via
  ``jax.experimental.io_callback``. Off by default: when no collector is
  passed, the executor emits **no** callback at trace time (the jaxpr is
  bit-identical to an uninstrumented build — tests assert ``"io_callback"
  not in str(jaxpr)``). When enabled, each phase-scan segment (phase
  executor), each tick (unrolled executor), or the whole table scan
  records host-side ``perf_counter`` stamps, keyed so the analysis side
  can reassemble a measured timeline aligned tick-for-tick with
  ``schedules.compress_schedule``'s phases.

- :class:`RunReport` — a structured run recorder (counters, timers,
  gauges, JSONL event stream + a single JSON manifest carrying config,
  mesh shape, schedule, phase stats, compile time and jax/jaxlib
  versions) with a dependency-free :func:`validate_report` so sweeps,
  ``fit`` and ``bench.py`` all emit the same schema instead of ad-hoc
  dicts.

Two consumers of the stamps beyond the tabular breakdown:

- :func:`perfetto_trace` / :func:`write_perfetto_trace` — the measured
  timeline serialized as Chrome-trace JSON (one track per device, one
  complete "X" slice per F/B/W/idle cell, flow arrows for every ring-hop
  store), loadable in ui.perfetto.dev or chrome://tracing
  (docs/observability.md "Opening traces in Perfetto").

- :func:`critical_path` — walks the measured ticks and attributes each
  to compute (naming the straggler device under the per-tick lockstep
  model) vs comm (a ring hop in flight, nothing computing) vs bubble
  (nothing at all) — the attribution table the ``cost_model`` manifest
  section embeds (``analysis.cost_model``).

Stamp semantics under SPMD: ``io_callback`` inside ``shard_map`` fires
once **per device** (a 4-device mesh emits 4 stamps per logical event), so
every analysis groups events by ``(kind, index)`` and takes ``min`` of
start stamps / ``max`` of end stamps — the earliest entry and the last
straggler bound the segment. Each stamp carries a scalar *probe* derived
from the executor's carry so plain dataflow (not effect ordering) pins the
stamp after the computation it closes over; callbacks are emitted
unordered, which keeps the program legal on backends where ordered
effects constrain control flow.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

SCHEMA_VERSION = 1

# Event kinds carried in the first operand of every stamp.
STEP_START, PHASE_START, PHASE_END, TICK, STEP_END = 0, 1, 2, 3, 4
_KIND_NAMES = {STEP_START: "step_start", PHASE_START: "phase_start",
               PHASE_END: "phase_end", TICK: "tick", STEP_END: "step_end"}


# ---------------------------------------------------------------------------
# Measured timelines
# ---------------------------------------------------------------------------


def probe_of(carry) -> Any:
    """Smallest array leaf of an executor carry, as the data-dependence
    anchor of a stamp: the callback consumes this value, so XLA cannot
    float the stamp before the computation that produced the carry (nor
    drop it). Both executors' carries end in the scalar ``loss_acc``,
    which this picks."""
    import jax
    leaves = [x for x in jax.tree_util.tree_leaves(carry)
              if hasattr(x, "size")]
    x = min(leaves, key=lambda v: v.size)
    return x.ravel()[0]


class PipelineTelemetry:
    """Host-side collector for executor timing stamps.

    Build-time: ``make_pipeline_grad_fn(..., telemetry=tel)`` calls
    :meth:`attach` with the compiled tick table, its phases and the tick
    executor it resolved, then plants :meth:`emit` calls at segment
    boundaries. Run-time: each executed instrumented step appends
    ``(kind, index, t_host)`` rows here (once per device). Analysis:
    :meth:`timeline` / :meth:`stage_breakdown` / :meth:`report` after at
    least one step has been forced to completion
    (``utils.metrics.force_completion``).
    """

    def __init__(self) -> None:
        self.events: List[Tuple[int, int, float]] = []
        self.table: Optional[np.ndarray] = None
        self.phases = None  # Tuple[schedules.Phase, ...] | None
        self.executor: Optional[str] = None
        # live HBM watermarks sampled at step boundaries (see _stamp);
        # None = capability not probed yet, [] = backend has no stats
        self.memory_samples: List[Dict[str, Any]] = []
        self._mem_devices = None

    # -- build-time -----------------------------------------------------

    def attach(self, table: np.ndarray, phases, executor: str) -> None:
        """Record the schedule the instrumented program was built against
        (the alignment target every measured stamp is interpreted on)."""
        self.table = np.asarray(table)
        self.phases = tuple(phases) if phases is not None else None
        self.executor = executor

    def emit(self, kind: int, index: int, probe) -> None:
        """Plant one stamp in the traced program. Called during tracing by
        the executors; ``probe`` is a scalar from the live carry (see
        :func:`probe_of`)."""
        import jax.numpy as jnp
        from jax.experimental import io_callback
        io_callback(self._stamp, None, jnp.int32(kind), jnp.int32(index),
                    probe, ordered=False)

    # -- run-time host target -------------------------------------------

    def _stamp(self, kind, index, _probe) -> None:
        k = int(kind)
        t = time.perf_counter()
        self.events.append((k, int(index), t))
        if k in (STEP_START, STEP_END):
            self._sample_memory(k, t)

    def _sample_memory(self, kind: int, t: float) -> None:
        """Record per-device ``memory_stats()`` watermarks at a step
        boundary. Rides the *existing* stamp callback — telemetry-off
        builds still trace zero host callbacks, and backends whose
        devices return ``None`` (CPU) probe once then no-op forever."""
        if self._mem_devices is None:
            try:
                import jax
                self._mem_devices = [
                    d for d in jax.devices()
                    if isinstance(d.memory_stats(), dict)]
            except Exception:
                self._mem_devices = []
        for dev in self._mem_devices:
            try:
                stats = dev.memory_stats()
                in_use = int(stats.get("bytes_in_use", 0))
                self.memory_samples.append({
                    "kind": _KIND_NAMES.get(kind, str(kind)),
                    "device": int(dev.id), "t": t,
                    "bytes_in_use": in_use,
                    "peak_bytes_in_use": int(
                        stats.get("peak_bytes_in_use", in_use)),
                })
            except Exception:
                pass

    def memory_summary(self) -> Dict[str, Any]:
        """The ``live`` subsection of the manifest's ``memory`` block:
        per-device high-water marks over the recorded samples.
        ``available=False`` (no per-device rows) on backends without
        allocator stats — consumers must degrade, not assume."""
        per_dev: Dict[int, Dict[str, int]] = {}
        for s in self.memory_samples:
            d = s["device"]
            row = per_dev.setdefault(
                d, {"device": d, "peak_bytes_in_use": 0,
                    "last_bytes_in_use": 0, "n_samples": 0})
            row["peak_bytes_in_use"] = max(row["peak_bytes_in_use"],
                                           s["peak_bytes_in_use"],
                                           s["bytes_in_use"])
            row["last_bytes_in_use"] = s["bytes_in_use"]
            row["n_samples"] += 1
        rows = [per_dev[d] for d in sorted(per_dev)]
        return {
            "available": bool(rows),
            "n_samples": len(self.memory_samples),
            "per_device": rows,
            "peak_bytes_in_use": (max(r["peak_bytes_in_use"] for r in rows)
                                  if rows else None),
        }

    def reset(self) -> None:
        """Drop recorded events (keep the attached schedule) — call between
        steps when only the last step's timeline is wanted."""
        self.events = []
        self.memory_samples = []

    # -- analysis -------------------------------------------------------

    def spans(self) -> Dict[Tuple[int, int], Tuple[float, float, int]]:
        """Group per-device stamps: ``(kind, index) -> (t_min, t_max, n)``."""
        out: Dict[Tuple[int, int], Tuple[float, float, int]] = {}
        for kind, idx, t in self.events:
            key = (kind, idx)
            if key in out:
                lo, hi, n = out[key]
                out[key] = (min(lo, t), max(hi, t), n + 1)
            else:
                out[key] = (t, t, 1)
        return out

    def timeline(self) -> List[Dict[str, Any]]:
        """The measured timeline, one record per instrumented segment.

        Phase executor: one record per :class:`~..parallel.schedules.Phase`
        (``phase``, ``start_tick``, ``n_ticks``, ``period``, ``reps``,
        ``duration_s``) — directly comparable to ``replay_phases``' tick
        spans. Unrolled executor: one record per tick. Scan executor: a
        single whole-table record. Durations take the earliest start stamp
        to the latest end stamp across devices (lockstep SPMD: the
        straggler defines the segment).
        """
        if not self.events:
            raise ValueError(
                "no telemetry events recorded — run (and force completion "
                "of) at least one instrumented step first")
        spans = self.spans()
        records: List[Dict[str, Any]] = []
        if self.executor == "phases":
            if self.phases is None:
                raise ValueError("phase timeline requested but no phases "
                                 "attached (was attach() called?)")
            for j, ph in enumerate(self.phases):
                start = spans.get((PHASE_START, j))
                end = spans.get((PHASE_END, j))
                if start is None or end is None:
                    raise ValueError(f"phase {j} missing stamps (start="
                                     f"{start}, end={end}) — incomplete run")
                dur = max(end[1] - start[0], 0.0)
                records.append({
                    "kind": "phase", "phase": j, "start_tick": ph.start,
                    "n_ticks": ph.length, "period": ph.period,
                    "reps": ph.reps, "t0": start[0], "t1": end[1],
                    "duration_s": dur,
                })
        elif self.executor == "unrolled":
            t0 = spans.get((STEP_START, 0))
            ticks = sorted(i for k, i in spans if k == TICK)
            prev = t0[0] if t0 is not None else None
            for t in ticks:
                _, hi, _ = spans[(TICK, t)]
                records.append({
                    "kind": "tick", "tick": t, "start_tick": t, "n_ticks": 1,
                    "t1": hi,
                    "duration_s": (max(hi - prev, 0.0)
                                   if prev is not None else None),
                })
                prev = hi
        else:  # whole-table scan: one segment
            start = spans.get((STEP_START, 0))
            end = spans.get((STEP_END, 0))
            if start is None or end is None:
                raise ValueError("scan executor run missing step start/end "
                                 "stamps — incomplete run")
            n = self.table.shape[0] if self.table is not None else 0
            records.append({
                "kind": "step", "start_tick": 0, "n_ticks": n,
                "t0": start[0], "t1": end[1],
                "duration_s": max(end[1] - start[0], 0.0),
            })
        return records

    def stage_breakdown(self) -> Dict[str, Any]:
        """Per-stage measured F/B/W/idle attribution and bubble.

        Each segment's measured duration is spread uniformly over its
        ticks, and each (device, tick) is classified by the tick table's
        op columns (``schedules.table_unit_activity``). That uniform
        spread is an attribution model — within a phase the executor runs
        a single fused scan, so per-tick variation inside a segment is
        not observable; across segments (where schedules actually differ)
        the attribution is measured. ``bubble_measured`` per stage is its
        idle share of the measured makespan, the measured counterpart of
        ``simulated_bubble``'s per-device fractions."""
        from ..parallel.schedules import table_unit_activity
        if self.table is None:
            raise ValueError("no tick table attached")
        activity = table_unit_activity(self.table)  # [T, D, 4] 0/1
        D = activity.shape[1]
        seconds = np.zeros((D, 4))
        total = 0.0
        for rec in self.timeline():
            dur = rec.get("duration_s")
            if dur is None:
                continue
            total += dur
            t0, n = rec["start_tick"], rec["n_ticks"]
            if n <= 0:
                continue
            per_tick = dur / n
            seconds += activity[t0:t0 + n].sum(axis=0) * per_tick
        per_stage = []
        for d in range(D):
            f_s, b_s, w_s, idle_s = (float(x) for x in seconds[d])
            per_stage.append({
                "device": d, "f_s": f_s, "b_s": b_s, "w_s": w_s,
                "idle_s": idle_s,
                "bubble_measured": idle_s / total if total > 0 else 0.0,
            })
        busy = seconds[:, :3].sum()
        split = (seconds[:, :3].sum(axis=0) / busy if busy > 0
                 else np.zeros(3))
        return {
            "total_s": total,
            "per_stage": per_stage,
            "f_frac": float(split[0]), "b_frac": float(split[1]),
            "w_frac": float(split[2]),
            "bubble_measured_mean": float(
                np.mean([s["bubble_measured"] for s in per_stage])),
        }

    def report(self) -> Dict[str, Any]:
        """The telemetry section embedded in :class:`RunReport` manifests."""
        out: Dict[str, Any] = {"executor": self.executor,
                               "n_events": len(self.events)}
        if self.phases is not None:
            from ..parallel.schedules import phase_stats
            out["phase_stats"] = phase_stats(self.phases)
        if self.events:
            out["timeline"] = self.timeline()
            if self.table is not None:
                out["stage_breakdown"] = self.stage_breakdown()
        if self.memory_samples:
            out["memory_watermarks"] = self.memory_summary()
        return out


# ---------------------------------------------------------------------------
# Trace export + critical-path attribution
# ---------------------------------------------------------------------------


def _tick_times(telemetry: PipelineTelemetry):
    """Per-tick ``(t0, duration)`` seconds, relative to the first stamp.

    Segment durations are spread uniformly over the segment's ticks (the
    same attribution model as :meth:`PipelineTelemetry.stage_breakdown`:
    inside one fused scan per-tick variation is not observable). Phase and
    scan segments carry absolute ``t0``/``t1`` stamps; unrolled records
    only a ``t1`` per tick, so starts chain from the previous boundary."""
    if telemetry.table is None:
        raise ValueError("no tick table attached")
    T = int(telemetry.table.shape[0])
    t0 = np.zeros(T)
    dur = np.zeros(T)
    origin = None
    cursor = 0.0
    for rec in telemetry.timeline():
        start, n = rec["start_tick"], rec["n_ticks"]
        d = rec.get("duration_s") or 0.0
        per = d / n if n else 0.0
        if rec.get("t0") is not None:
            if origin is None:
                origin = rec["t0"]
            base = rec["t0"] - origin
        elif rec.get("t1") is not None:
            if origin is None:
                origin = rec["t1"] - d
            base = rec["t1"] - origin - d
        else:
            base = cursor
        for k in range(n):
            if start + k < T:
                t0[start + k] = base + k * per
                dur[start + k] = per
        cursor = base + n * per
    return t0, dur


def _store_channels():
    """(name, store column, sender offset) per ring direction: a store at
    ``(t, d, col)`` banks data ppermuted during tick ``t-1`` by device
    ``(d - offset) % D`` (same convention as
    ``analysis.table_check.RING_CHANNELS``)."""
    from ..parallel.schedules import (COL_STORE_B_POS_SLOT, COL_STORE_B_SLOT,
                                      COL_STORE_F_NEG_SLOT, COL_STORE_F_SLOT)
    return (("fwd_ring_pos", COL_STORE_F_SLOT, +1),
            ("bwd_ring_neg", COL_STORE_B_SLOT, -1),
            ("fwd_ring_neg", COL_STORE_F_NEG_SLOT, -1),
            ("bwd_ring_pos", COL_STORE_B_POS_SLOT, +1))


def critical_path(telemetry: PipelineTelemetry) -> Dict[str, Any]:
    """Attribute each measured tick to compute vs comm vs bubble.

    Under the executor's lockstep model every device waits for the tick's
    straggler, so a tick is *compute* when any device runs a unit (the
    straggler = the device with the heaviest weighted work that tick,
    F=1/B=2/W=1), *comm* when nothing computes but a ring hop is in
    flight (some channel banks a store next tick), and *bubble* when the
    tick does neither. Returns aggregate seconds, the per-tick
    classification, and per-device straggler time — "which stage is the
    step waiting on" as a number.

    Training-table comm is additionally attributed overlap-aware: each
    hop landing at tick ``t`` is classified by its verified bank stage
    (:func:`parallel.schedules.overlap_bank_stages`) into
    ``hops_exposed`` (banks before the first unit — serial even under
    ``comm_overlap="ring"``) vs ``hops_overlappable`` (hides behind the
    units that run before its bank point); the aggregate
    ``exposed_hop_ticks`` / ``overlappable_hop_ticks`` are the same
    counts the cost model's ``comm_overlap`` mode prices."""
    from ..parallel.schedules import (BANK_BEFORE_F, N_COLS,
                                      overlap_bank_stages,
                                      table_unit_activity)
    if telemetry.table is None:
        raise ValueError("no tick table attached")
    table = telemetry.table
    T, D = int(table.shape[0]), int(table.shape[1])
    activity = table_unit_activity(table)  # [T, D, 4]
    t0, dur = _tick_times(telemetry)
    weights = np.array([1.0, 2.0, 1.0, 0.0])
    work = activity.astype(np.float64) @ weights  # [T, D]
    channels = _store_channels()
    store_cols = [col for _, col, _ in channels]
    # overlap-aware hop attribution: classify each landing hop by its
    # verified bank stage (exposed = fences the tick's first unit even
    # under comm_overlap="ring"; overlappable = hides behind the units
    # before its bank point). Forward-only tables have no stage map.
    bank_st = (overlap_bank_stages(table) if table.shape[2] >= N_COLS
               else None)
    agg = {"compute": 0.0, "comm": 0.0, "bubble": 0.0}
    exposed_hops = overlappable_hops = 0
    straggler_s = np.zeros(D)
    per_tick: List[Dict[str, Any]] = []
    for t in range(T):
        hop_in_flight = (t + 1 < T
                         and bool((table[t + 1][:, store_cols] >= 0).any()))
        if work[t].max() > 0:
            cls = "compute"
            straggler = int(work[t].argmax())
            straggler_s[straggler] += dur[t]
        elif hop_in_flight:
            cls, straggler = "comm", None
        else:
            cls, straggler = "bubble", None
        agg[cls] += dur[t]
        row: Dict[str, Any] = {"tick": t, "class": cls,
                               "straggler": straggler,
                               "duration_s": float(dur[t])}
        if bank_st is not None and t >= 1:
            n_exp = n_lap = 0
            for ci, (_, col, _) in enumerate(channels):
                if (table[t][:, col] >= 0).any():
                    if int(bank_st[t, ci]) == BANK_BEFORE_F:
                        n_exp += 1
                    else:
                        n_lap += 1
            if n_exp or n_lap:
                row["hops_exposed"], row["hops_overlappable"] = n_exp, n_lap
            exposed_hops += n_exp
            overlappable_hops += n_lap
        per_tick.append(row)
    sd = int(straggler_s.argmax())
    return {
        "n_ticks": T,
        "total_s": float(dur.sum()),
        "compute_s": float(agg["compute"]),
        "comm_s": float(agg["comm"]),
        "bubble_s": float(agg["bubble"]),
        "exposed_hop_ticks": exposed_hops,
        "overlappable_hop_ticks": overlappable_hops,
        "straggler_s_per_device": [float(x) for x in straggler_s],
        "straggler_device": sd,
        "straggler_stage": f"device {sd}",
        "per_tick": per_tick,
    }


def perfetto_trace(telemetry: PipelineTelemetry,
                   serving_events: Optional[List[Dict[str, Any]]] = None,
                   dynamics_events: Optional[List[Dict[str, Any]]] = None,
                   predicted_tick_s: Optional[Sequence[float]] = None
                   ) -> Dict[str, Any]:
    """The measured timeline as a Chrome-trace/Perfetto JSON object.

    One track (tid) per pipeline device under a single process, one
    complete ``"X"`` slice per (tick, device) unit — named ``F m3`` /
    ``B v1 m2`` / ``W m0`` / ``idle``, categorized by kind — and one
    ``"s"``→``"f"`` flow pair per ring-hop store (cat ``ppermute``,
    anchored mid-slice on the sending and receiving ticks) so arrows in
    the UI show exactly the hops the table predicts; each flow's args
    carry its verified ``bank_stage`` and an ``overlap`` tag
    (``exposed`` = fences the landing tick's first unit,
    ``overlappable`` = hides behind compute under
    ``comm_overlap="ring"``). When the telemetry
    carries live watermark samples, each device additionally gets a
    ``"C"`` counter track (``HBM bytes_in_use``) sampled at step
    boundaries, drawn right next to the F/B/W slices. ``serving_events``:
    RunReport event rows — ``serve_admit``/``serve_finish`` pairs become
    async request slices on a separate "requests" process
    (:func:`perfetto_request_events`). ``dynamics_events``: RunReport
    ``dynamics`` event rows — per-stage grad-norm counter tracks on a
    "training dynamics" process (:func:`perfetto_dynamics_events`).
    ``predicted_tick_s``: the cost model's per-tick predicted seconds
    (``analysis.cost_model.predicted_tick_seconds``, length ``T``) — when
    given, every per-tick slice's args additionally carry
    ``predicted_tick_s`` / ``measured_tick_s`` / ``rel_err`` (signed,
    predicted vs measured), so clicking any slice answers "was this tick
    slower than the model said" without leaving the UI (the calibration
    observatory's per-tick view, docs/observability.md §9).
    Timestamps are microseconds from the first stamp, sorted ascending;
    load the written file in ui.perfetto.dev or chrome://tracing."""
    from ..parallel.schedules import (COL_BWD_M, COL_BWD_V, COL_FWD_M,
                                      COL_FWD_V, COL_W_M, COL_W_V)
    if telemetry.table is None:
        raise ValueError("no tick table attached")
    table = telemetry.table
    T, D = int(table.shape[0]), int(table.shape[1])
    n_virtual = max(1, (int(table[..., (COL_FWD_V, COL_BWD_V, COL_W_V),
                                ].max()) + 1))
    t0, dur = _tick_times(telemetry)
    us = 1e6
    events: List[Dict[str, Any]] = [{
        "ph": "M", "name": "process_name", "pid": 0, "tid": 0, "ts": 0.0,
        "args": {"name": f"pipeline ({telemetry.executor})"},
    }]
    for d in range(D):
        events.append({"ph": "M", "name": "thread_name", "pid": 0, "tid": d,
                       "ts": 0.0, "args": {"name": f"device {d}"}})
    units = ((COL_FWD_V, COL_FWD_M, "F"), (COL_BWD_V, COL_BWD_M, "B"),
             (COL_W_V, COL_W_M, "W"))
    n_predicted = 0
    for t in range(T):
        ts, width = t0[t] * us, dur[t] * us
        # calibration annotation: the cost model's prediction for this
        # tick next to its measured duration, on every slice of the tick
        pred_args: Dict[str, Any] = {}
        if predicted_tick_s is not None and t < len(predicted_tick_s):
            n_predicted += 1
            p = float(predicted_tick_s[t])
            pred_args = {"predicted_tick_s": p,
                         "measured_tick_s": float(dur[t])}
            if dur[t] > 0:
                pred_args["rel_err"] = (p - float(dur[t])) / float(dur[t])
        for d in range(D):
            row = table[t, d]
            active = 0
            for col_v, col_m, kind in units:
                if row[col_m] >= 0:
                    active += 1
                    v, m = int(row[col_v]), int(row[col_m])
                    name = (f"{kind} v{v} m{m}" if n_virtual > 1
                            else f"{kind} m{m}")
                    events.append({
                        "ph": "X", "name": name, "cat": kind, "pid": 0,
                        "tid": d, "ts": ts, "dur": width,
                        "args": {"tick": t, "v": v, "m": m, **pred_args}})
            if active == 0:
                events.append({"ph": "X", "name": "idle", "cat": "idle",
                               "pid": 0, "tid": d, "ts": ts, "dur": width,
                               "args": {"tick": t, **pred_args}})
    # flow args carry the hop's verified bank stage so overlapped comm
    # reads directly off the arrows: stage 0 arrivals fence the landing
    # tick's first unit (exposed), later stages ride under its compute
    from ..parallel.schedules import (BANK_BEFORE_F, N_COLS,
                                      overlap_bank_stages)
    bank_st = (overlap_bank_stages(table) if table.shape[2] >= N_COLS
               else None)
    flow_id = 0
    n_overlappable = 0
    for t in range(1, T):
        for ci, (name, col, offset) in enumerate(_store_channels()):
            stage = None if bank_st is None else int(bank_st[t, ci])
            overlapped = stage is not None and stage > BANK_BEFORE_F
            for d in range(D):
                if table[t, d, col] >= 0:
                    flow_id += 1
                    n_overlappable += int(overlapped)
                    sender = (d - offset) % D
                    args = ({} if stage is None else
                            {"bank_stage": stage,
                             "overlap": ("overlappable" if overlapped
                                         else "exposed")})
                    events.append({
                        "ph": "s", "id": flow_id, "name": name,
                        "cat": "ppermute", "pid": 0, "tid": sender,
                        "ts": (t0[t - 1] + 0.5 * dur[t - 1]) * us,
                        "args": args})
                    events.append({
                        "ph": "f", "bp": "e", "id": flow_id, "name": name,
                        "cat": "ppermute", "pid": 0, "tid": d,
                        "ts": (t0[t] + 0.5 * dur[t]) * us,
                        "args": args})
    # live HBM counter track: one "C" event per (boundary sample, device),
    # on the same clock as the stamps so the sawtooth lines up with ticks
    n_counters = 0
    if telemetry.memory_samples:
        origin = min(t for _, _, t in telemetry.events)
        for s in telemetry.memory_samples:
            n_counters += 1
            events.append({
                "ph": "C", "name": f"HBM device {s['device']}",
                "cat": "memory", "pid": 0, "tid": 0,
                "ts": max(s["t"] - origin, 0.0) * us,
                "args": {"bytes_in_use": s["bytes_in_use"],
                         "peak_bytes_in_use": s["peak_bytes_in_use"]}})
    if serving_events:
        events.extend(perfetto_request_events(serving_events))
    n_dyn = 0
    if dynamics_events:
        dyn_rows = perfetto_dynamics_events(dynamics_events)
        n_dyn = sum(1 for e in dyn_rows if e["ph"] == "C")
        events.extend(dyn_rows)
    # sorted ts is part of the format contract (and what the schema test
    # pins); metadata first among equals so track names land before slices
    events.sort(key=lambda e: (e["ts"], 0 if e["ph"] == "M" else 1))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"executor": telemetry.executor, "n_devices": D,
                      "n_ticks": T, "n_flows": flow_id,
                      "n_overlappable_flows": n_overlappable,
                      "n_memory_counters": n_counters,
                      "n_dynamics_counters": n_dyn,
                      "n_predicted_ticks": n_predicted},
    }


def perfetto_request_events(serving_events: List[Dict[str, Any]],
                            pid: int = 1) -> List[Dict[str, Any]]:
    """Per-request async slices from ``serve_admit``/``serve_finish``
    RunReport event rows: one ``"b"``→``"e"`` pair per request id on a
    "requests" process track, laid out on the events' wall clock
    (normalized to the first admit). The slice args carry the on-device
    tick stamps — ``admit_tick``, prompt length / budget from the admit
    row, ``finish_tick``/``n_tokens``/``ttft_ticks`` from the finish row
    — so a TTFT/TPOT outlier in the UI names the exact ticks to inspect
    on the pipeline timeline. Slices land on a per-slot tid, so slot
    reuse reads as a row of back-to-back requests."""
    admits = {}
    finishes = {}
    for row in serving_events or []:
        if row.get("kind") == "serve_admit" and "rid" in row:
            admits[row["rid"]] = row
        elif row.get("kind") == "serve_finish" and "rid" in row:
            finishes[row["rid"]] = row
    if not admits:
        return []
    us = 1e6
    origin = min(r["t"] for r in admits.values())
    out: List[Dict[str, Any]] = [{
        "ph": "M", "name": "process_name", "pid": pid, "tid": 0, "ts": 0.0,
        "args": {"name": "serving requests"}}]
    slots = sorted({int(r.get("slot", 0)) for r in admits.values()})
    for slot in slots:
        out.append({"ph": "M", "name": "thread_name", "pid": pid,
                    "tid": slot, "ts": 0.0,
                    "args": {"name": f"slot {slot}"}})
    for rid, adm in sorted(admits.items(), key=lambda kv: kv[1]["t"]):
        fin = finishes.get(rid)
        slot = int(adm.get("slot", 0))
        ts = (adm["t"] - origin) * us
        args = {"rid": rid, "slot": slot,
                "admit_tick": adm.get("tick"),
                "prompt_len": adm.get("prompt_len"),
                "budget": adm.get("budget")}
        if fin is not None:
            args.update({"finish_tick": fin.get("tick"),
                         "n_tokens": fin.get("n_tokens"),
                         "ttft_ticks": fin.get("ttft_ticks")})
        common = {"cat": "request", "id": int(rid), "name": f"req {rid}",
                  "pid": pid, "tid": slot}
        out.append({"ph": "b", "ts": ts, "args": args, **common})
        # unfinished requests (failed / still in flight) close zero-width
        end_ts = (fin["t"] - origin) * us if fin is not None else ts
        out.append({"ph": "e", "ts": end_ts, "args": {}, **common})
    return out


def perfetto_serving_load_events(serving_events: List[Dict[str, Any]],
                                 occupancy: Optional[List[Any]] = None,
                                 queue_depth: Optional[List[Any]] = None,
                                 s_per_tick: Optional[float] = None,
                                 pages_used: Optional[List[Any]] = None,
                                 page_fragmentation: Optional[List[Any]] = None,
                                 acceptance: Optional[List[Any]] = None,
                                 pid: int = 3) -> List[Dict[str, Any]]:
    """The serving-load debugging surface on the **tick clock**: per-slot
    request slices split into *queue wait* vs *execution* sub-spans, plus
    queue-depth and slot-occupancy counter tracks.

    Rides the same ``serve_admit``/``serve_finish`` RunReport rows as
    :func:`perfetto_request_events`, but lays everything out in ticks —
    the exact on-device stamps (``arrival``/``tick`` on the admit row,
    ``tick`` on the finish row) rather than host wall-clock, so a
    latency outlier decomposes visually: a long ``wait`` slice is
    queueing (saturation), a long ``serve`` slice is the ring itself.
    ``occupancy``/``queue_depth`` are ``(tick, n)`` block-boundary
    samples (``ServeResult.occupancy``/``.queue_depth``); each becomes a
    ``"C"`` counter track right under the request rows, so the queue
    ramp that precedes a TTFT blow-up is on screen with it.
    ``s_per_tick`` scales ticks to real time when known (1 tick = 1 us
    otherwise — relative layout is what matters). Admit rows without an
    ``arrival`` field (pre-SLO-observatory streams) degrade to a
    zero-width wait slice. Paged-engine runs add ``pages used`` and
    ``page fragmentation`` counter tracks from the same block-boundary
    samples (``ServeResult.pages_used``/``.page_fragmentation``), so a
    TTFT blow-up under prefix traffic decomposes into queue pressure vs
    page-pool pressure on one screen. Speculative runs add an
    ``acceptance rate`` counter track from ``(tick, alpha)`` samples
    (``ServeResult.acceptance_series``) and nest a ``verify`` sub-span
    under each finished request's serve slice carrying its
    draft-verify gauges (``spec_verify_visits``/``spec_accepted``/
    ``accepted_len_mean`` from the finish row), so an acceptance-rate
    sag lines up with the exact requests it slowed."""
    admits: Dict[Any, Dict[str, Any]] = {}
    finishes: Dict[Any, Dict[str, Any]] = {}
    for row in serving_events or []:
        if row.get("kind") == "serve_admit" and "rid" in row:
            admits[row["rid"]] = row
        elif row.get("kind") == "serve_finish" and "rid" in row:
            finishes[row["rid"]] = row
    if not admits and not occupancy and not queue_depth and not pages_used:
        return []
    tick_us = (s_per_tick * 1e6) if s_per_tick else 1.0
    out: List[Dict[str, Any]] = [{
        "ph": "M", "name": "process_name", "pid": pid, "tid": 0, "ts": 0.0,
        "args": {"name": "serving load (ticks)"}}]
    slots = sorted({int(r.get("slot", 0)) for r in admits.values()})
    for slot in slots:
        out.append({"ph": "M", "name": "thread_name", "pid": pid,
                    "tid": slot + 1, "ts": 0.0,
                    "args": {"name": f"slot {slot}"}})
    for rid, adm in sorted(admits.items(),
                           key=lambda kv: kv[1].get("tick", 0)):
        slot = int(adm.get("slot", 0))
        admit_tick = float(adm.get("tick", 0))
        arrival = adm.get("arrival")
        arrival = float(arrival) if isinstance(arrival, (int, float)) \
            else admit_tick
        args = {"rid": rid, "slot": slot, "arrival": arrival,
                "admit_tick": adm.get("tick"),
                "prompt_len": adm.get("prompt_len"),
                "budget": adm.get("budget")}
        if arrival < admit_tick:
            out.append({"ph": "X", "name": f"wait r{rid}",
                        "cat": "queue_wait", "pid": pid, "tid": slot + 1,
                        "ts": arrival * tick_us,
                        "dur": (admit_tick - arrival) * tick_us,
                        "args": args})
        fin = finishes.get(rid)
        end_tick = (float(fin["tick"]) if fin is not None
                    and isinstance(fin.get("tick"), (int, float))
                    else admit_tick)
        fargs = dict(args)
        if fin is not None:
            fargs.update({"finish_tick": fin.get("tick"),
                          "n_tokens": fin.get("n_tokens"),
                          "ttft_ticks": fin.get("ttft_ticks")})
        out.append({"ph": "X", "name": f"serve r{rid}", "cat": "execution",
                    "pid": pid, "tid": slot + 1,
                    "ts": admit_tick * tick_us,
                    "dur": max(end_tick - admit_tick, 0.0) * tick_us,
                    "args": fargs})
        # draft-verify sub-span: equal-duration slice emitted after the
        # serve slice nests under it in the UI; args carry the
        # per-request speculative gauges from the finish row
        if fin is not None and fin.get("spec_verify_visits"):
            out.append({
                "ph": "X", "name": f"verify r{rid} "
                f"x{int(fin['spec_verify_visits'])}",
                "cat": "spec_verify", "pid": pid, "tid": slot + 1,
                "ts": admit_tick * tick_us,
                "dur": max(end_tick - admit_tick, 0.0) * tick_us,
                "args": {"rid": rid,
                         "spec_verify_visits": fin.get("spec_verify_visits"),
                         "spec_accepted": fin.get("spec_accepted"),
                         "accepted_len_mean": fin.get("accepted_len_mean")}})
    for name, series in (("slot occupancy", occupancy),
                         ("queue depth", queue_depth),
                         ("pages used", pages_used)):
        for t, n in series or []:
            out.append({"ph": "C", "name": name, "cat": "serving_load",
                        "pid": pid, "tid": 0, "ts": float(t) * tick_us,
                        "args": {name.replace(" ", "_"): int(n)}})
    for t, f in page_fragmentation or []:
        out.append({"ph": "C", "name": "page fragmentation",
                    "cat": "serving_load", "pid": pid, "tid": 0,
                    "ts": float(t) * tick_us,
                    "args": {"page_fragmentation": float(f)}})
    for t, a in acceptance or []:
        if a is None:
            continue  # pre-first-verify samples carry no rate yet
        out.append({"ph": "C", "name": "acceptance rate",
                    "cat": "serving_load", "pid": pid, "tid": 0,
                    "ts": float(t) * tick_us,
                    "args": {"acceptance_rate": float(a)}})
    return out


def perfetto_dynamics_events(dynamics_events: List[Dict[str, Any]],
                             pid: int = 2) -> List[Dict[str, Any]]:
    """Per-stage grad-norm counter tracks from RunReport ``dynamics``
    event rows (the rows ``fit`` streams at every log sync), one ``"C"``
    counter per (log point, stage) plus global grad-norm and GNS tracks
    — the model-health twin of the HBM sawtooth. The rows carry the
    event stream's wall clock (a different clock than the executor
    stamps), so they land on their own "training dynamics" process,
    normalized to the first dynamics row; within the process, step
    ordering is exact."""
    rows = [r for r in (dynamics_events or [])
            if r.get("kind") == "dynamics" and "t" in r]
    if not rows:
        return []
    us = 1e6
    origin = min(r["t"] for r in rows)
    out: List[Dict[str, Any]] = [{
        "ph": "M", "name": "process_name", "pid": pid, "tid": 0, "ts": 0.0,
        "args": {"name": "training dynamics"}}]

    def finite(x):
        return isinstance(x, (int, float)) and np.isfinite(x)

    for r in sorted(rows, key=lambda r: r["t"]):
        ts = (r["t"] - origin) * us
        if finite(r.get("grad_norm")):
            out.append({"ph": "C", "name": "grad_norm", "cat": "dynamics",
                        "pid": pid, "tid": 0, "ts": ts,
                        "args": {"grad_norm": float(r["grad_norm"])}})
        if finite(r.get("gns")):
            out.append({"ph": "C", "name": "gns", "cat": "dynamics",
                        "pid": pid, "tid": 0, "ts": ts,
                        "args": {"gns": float(r["gns"])}})
        for s, v in enumerate(r.get("grad_norm_per_stage") or []):
            if finite(v):
                out.append({
                    "ph": "C", "name": f"grad_norm stage {s}",
                    "cat": "dynamics", "pid": pid, "tid": 0, "ts": ts,
                    "args": {"grad_norm": float(v)}})
    return out


def write_perfetto_trace(telemetry: Optional[PipelineTelemetry], path: str,
                         serving_events: Optional[List[Dict[str, Any]]] = None,
                         dynamics_events: Optional[List[Dict[str, Any]]] = None,
                         serving_load_tracks: Optional[Dict[str, Any]] = None,
                         predicted_tick_s: Optional[Sequence[float]] = None
                         ) -> str:
    """Serialize :func:`perfetto_trace` to ``path``; returns the path.
    With ``telemetry=None`` (a serving-only run has no pipeline
    telemetry) the trace holds just the requests/dynamics tracks.
    ``serving_load_tracks`` (optional) adds the tick-clock serving-load
    process (:func:`perfetto_serving_load_events`): a dict with any of
    ``occupancy``/``queue_depth`` (block-boundary ``(tick, n)`` samples)
    and ``s_per_tick``; the request sub-spans come from
    ``serving_events``. ``predicted_tick_s``: per-tick cost-model
    predictions for the calibration annotations (see
    :func:`perfetto_trace`)."""
    if telemetry is None:
        rows = perfetto_request_events(serving_events or [])
        rows.extend(perfetto_dynamics_events(dynamics_events or []))
        trace: Dict[str, Any] = {
            "traceEvents": rows,
            "displayTimeUnit": "ms",
            "otherData": {"executor": "serving"},
        }
    else:
        trace = perfetto_trace(telemetry, serving_events=serving_events,
                               dynamics_events=dynamics_events,
                               predicted_tick_s=predicted_tick_s)
    if serving_load_tracks is not None:
        trace["traceEvents"].extend(perfetto_serving_load_events(
            serving_events or [],
            occupancy=serving_load_tracks.get("occupancy"),
            queue_depth=serving_load_tracks.get("queue_depth"),
            s_per_tick=serving_load_tracks.get("s_per_tick"),
            pages_used=serving_load_tracks.get("pages_used"),
            page_fragmentation=serving_load_tracks.get(
                "page_fragmentation"),
            acceptance=serving_load_tracks.get("acceptance")))
    with open(path, "w") as fh:
        json.dump(trace, fh)
    return path


# ---------------------------------------------------------------------------
# Serving latency summaries
# ---------------------------------------------------------------------------


def _pct(xs: Sequence[float]) -> Dict[str, float]:
    """p50/p95/p99/mean of a latency sample (empty-safe)."""
    if not len(xs):
        return {"p50": None, "p95": None, "p99": None, "mean": None, "n": 0}
    a = np.asarray(xs, np.float64)
    return {"p50": float(np.percentile(a, 50)),
            "p95": float(np.percentile(a, 95)),
            "p99": float(np.percentile(a, 99)),
            "mean": float(a.mean()), "n": int(a.size)}


def serving_summary(result) -> Dict[str, Any]:
    """Per-request latency + throughput summary of one serving run.

    ``result`` is a :class:`...serving.engine.ServeResult` (duck-typed —
    anything with ``completions`` carrying ``ttft_ticks``/``tpot_ticks``,
    plus ``tokens_out``/``ticks``/``wall_s``/``n_slots``/``policy``/
    ``occupancy``). Latencies are reported in *ticks* (exact, stamped
    on-device at token-banking time) with the measured ``s_per_tick``
    factor alongside, so wall-clock latencies are one multiply away and
    the tick numbers stay comparable across hosts.
    """
    # failed completions (serving hardening: rejected/poisoned requests
    # retired with status="failed") carry no latency stamps — count them
    # separately, keep the percentile samples clean
    ok = [c for c in result.completions
          if getattr(c, "status", "ok") == "ok"]
    ttfts = [c.ttft_ticks for c in ok]
    tpots = [c.tpot_ticks for c in ok if c.tpot_ticks is not None]
    # TTFT split: admission wait (admit - arrival, pure queueing) vs
    # service TTFT (first token - admit, the ring's own latency). Older
    # ServeResult-likes without the stamps degrade to empty samples.
    waits = [c.admit_wait_ticks for c in ok
             if getattr(c, "admit_wait_ticks", None) is not None]
    service = [c.service_ttft_ticks for c in ok
               if getattr(c, "service_ttft_ticks", None) is not None]
    occ = [int(n) for _, n in result.occupancy]
    qd_series = list(getattr(result, "queue_depth", []) or [])
    qd = [int(n) for _, n in qd_series]
    busy = getattr(result, "busy_ticks", None)
    return {
        "policy": result.policy,
        "n_requests": len(ok),
        "n_failed": len(result.completions) - len(ok),
        "n_slots": int(result.n_slots),
        "ticks": int(result.ticks),
        "busy_ticks": int(busy) if busy is not None else None,
        "wall_s": float(result.wall_s),
        "s_per_tick": (float(result.wall_s) / result.ticks
                       if result.ticks else None),
        "tokens_out": int(result.tokens_out),
        "tokens_per_sec": float(result.tokens_per_sec),
        "goodput": float(result.goodput),
        "goodput_busy": (float(result.goodput_busy)
                         if hasattr(result, "goodput_busy") else None),
        "ttft_ticks": _pct(ttfts),
        "tpot_ticks": _pct(tpots),
        "admit_wait_ticks": _pct(waits),
        "service_ttft_ticks": _pct(service),
        "occupancy_mean": float(np.mean(occ)) if occ else 0.0,
        "occupancy": [[int(t), int(n)] for t, n in result.occupancy],
        "queue_depth_mean": float(np.mean(qd)) if qd else 0.0,
        "queue_depth_max": int(max(qd)) if qd else 0,
        "queue_depth": [[int(t), int(n)] for t, n in qd_series],
        **_paged_summary_fields(result),
        **_spec_summary_fields(result),
    }


def _paged_summary_fields(result) -> Dict[str, Any]:
    """Paged-KV gauges for :func:`serving_summary` — empty dict for
    contiguous runs, so their summaries are byte-identical to before the
    paged engine existed."""
    if not getattr(result, "paged", False):
        return {}
    pages = [int(n) for _, n in (result.pages_used or [])]
    frag = [float(f) for _, f in (result.page_fragmentation or [])]
    return {
        "paged": True,
        "pages_capacity": int(result.pages_capacity),
        "pages_used_mean": float(np.mean(pages)) if pages else 0.0,
        "pages_used_max": int(max(pages)) if pages else 0,
        "pages_used": [[int(t), int(n)] for t, n in result.pages_used],
        "page_fragmentation_mean": (float(np.mean(frag)) if frag else 0.0),
        "page_fragmentation": [[int(t), float(f)]
                               for t, f in result.page_fragmentation],
        "prefix_hit_rate": (float(result.prefix_hit_rate)
                            if result.prefix_hit_rate is not None else 0.0),
        "prefill_skipped_tokens": int(result.prefill_skipped_tokens),
        "n_cow": int(result.n_cow),
        "n_backpressure": int(result.n_backpressure),
    }


def _spec_summary_fields(result) -> Dict[str, Any]:
    """Speculative-decoding gauges for :func:`serving_summary` — empty
    dict for non-speculative runs (their summaries stay byte-identical).
    ``acceptance_rate``/``accepted_len_mean`` are ``None`` rather than a
    division error when a run finished before its first verify tick
    (zero-finished sweep points included)."""
    if not getattr(result, "speculative", False):
        return {}
    series = list(getattr(result, "acceptance_series", []) or [])
    rate = result.acceptance_rate
    alm = result.accepted_len_mean
    return {
        "speculative": True,
        "gamma": int(result.gamma),
        "spec_verify_visits": int(result.spec_verify_visits),
        "spec_accepted_tokens": int(result.spec_accepted_tokens),
        "acceptance_rate": float(rate) if rate is not None else None,
        "accepted_len_mean": float(alm) if alm is not None else None,
        "acceptance_series": [[int(t), (float(a) if a is not None else None)]
                              for t, a in series],
    }


# ---------------------------------------------------------------------------
# Structured run reports
# ---------------------------------------------------------------------------


class RunReport:
    """Counters / timers / gauges + JSONL events + a single JSON manifest.

    One instance per run (a ``fit`` call, a sweep row, a bench
    invocation). With ``out_dir`` set, :meth:`event` streams to
    ``events.jsonl`` as it happens (crash-safe partial record) and
    :meth:`write` drops ``report.json``; without it everything stays
    in-memory and :meth:`manifest` returns the same schema for embedding.
    """

    def __init__(self, out_dir: Optional[str] = None,
                 name: str = "run") -> None:
        import jax
        import jaxlib
        self.meta: Dict[str, Any] = {
            "name": name,
            "created_unix": time.time(),
            "jax_version": jax.__version__,
            "jaxlib_version": getattr(jaxlib, "__version__", "unknown"),
        }
        self.counters: Dict[str, int] = {}
        self.gauges: Dict[str, Any] = {}
        self.timers: Dict[str, float] = {}
        self.events: List[Dict[str, Any]] = []
        self.telemetry: Optional[Dict[str, Any]] = None
        self.serving: List[Dict[str, Any]] = []
        self.serving_load: Optional[Dict[str, Any]] = None
        self.resilience: Optional[Dict[str, Any]] = None
        self.static_analysis: Optional[Dict[str, Any]] = None
        self.cost_model: Optional[Dict[str, Any]] = None
        self.memory: Optional[Dict[str, Any]] = None
        self.dynamics: Optional[Dict[str, Any]] = None
        self.calibration: Optional[Dict[str, Any]] = None
        self.out_dir = out_dir
        self._events_fh = None
        # the event stream is written from the training loop AND from
        # background threads (resilience.StepWatchdog stall diagnostics)
        self._events_lock = threading.Lock()
        if out_dir is not None:
            os.makedirs(out_dir, exist_ok=True)

    # -- recording ------------------------------------------------------

    def set_meta(self, **fields: Any) -> None:
        """Merge run-identifying fields (config, mesh_shape, schedule,
        phase_stats, backend, ...) into the manifest's ``meta`` block."""
        self.meta.update(fields)

    def count(self, name: str, n: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + int(n)

    def gauge(self, name: str, value: Any) -> None:
        self.gauges[name] = value

    @contextlib.contextmanager
    def timer(self, name: str):
        """Accumulating wall-clock timer: ``with report.timer("compile_s"):``."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.timers[name] = (self.timers.get(name, 0.0)
                                 + time.perf_counter() - t0)

    def event(self, kind: str, **fields: Any) -> None:
        """Append one timestamped event; streamed to ``events.jsonl`` when
        the report has an output directory."""
        row = {"t": time.time(), "kind": kind, **fields}
        with self._events_lock:  # watchdog threads stream events too
            self.events.append(row)
            if self.out_dir is not None:
                if self._events_fh is None:
                    self._events_fh = open(
                        os.path.join(self.out_dir, "events.jsonl"), "a")
                self._events_fh.write(json.dumps(row, default=_jsonable)
                                      + "\n")
                self._events_fh.flush()

    def attach_telemetry(self, telemetry: PipelineTelemetry) -> None:
        """Embed a measured-timeline section (:meth:`PipelineTelemetry.report`)."""
        self.telemetry = telemetry.report()

    def attach_serving(self, summary: Dict[str, Any]) -> None:
        """Append one serving-run latency summary
        (:func:`serving_summary`) to the manifest's ``serving`` list —
        a benchmark that runs continuous and static policies back to
        back attaches both."""
        self.serving.append(summary)

    def attach_serving_load(self, section: Dict[str, Any]) -> None:
        """Embed an offered-load sweep
        (:func:`...serving.loadgen.sweep_offered_load` /
        :func:`...serving.slo.serving_load_section`: latency-vs-load
        curve rows, the saturation knee, the SLOSpec and the regression
        reference point) as the manifest's ``serving_load`` block — the
        record ``scripts/regress.py`` guards ``max_sustainable_load``
        and reference p99 TTFT from."""
        self.serving_load = dict(section)

    def attach_resilience(self, section: Dict[str, Any]) -> None:
        """Embed the run's resilience summary (anomaly / preemption /
        stall counters, checkpoint-commit stats — assembled by
        ``utils.train.fit`` from ``resilience.CheckpointManager.stats``
        and the guard counters) as the manifest's ``resilience`` block."""
        self.resilience = dict(section)

    def attach_static_analysis(self, section: Dict[str, Any]) -> None:
        """Embed the static-verification digest
        (:func:`analysis.table_check.static_analysis_section`: verifier
        version, schedules checked, hazard count, slot high-water marks)
        as the manifest's ``static_analysis`` block."""
        self.static_analysis = dict(section)

    def attach_cost_model(self, section: Dict[str, Any]) -> None:
        """Embed the roofline accounting
        (:func:`analysis.cost_model.cost_model_section`: predicted vs
        measured step time, bubble fractions, ppermute hops, MFU/HFU,
        critical-path attribution) as the manifest's ``cost_model``
        block — the record ``scripts/regress.py`` reads."""
        self.cost_model = dict(section)

    def attach_dynamics(self, section: Dict[str, Any]) -> None:
        """Embed the training-dynamics summary
        (:func:`utils.dynamics.dynamics_section`: final grad norm,
        gradient-noise scale, per-stage stat rows, attributed-skip count
        and the run's forensic bundles) as the manifest's ``dynamics``
        block — the model-health record ``scripts/regress.py`` tracks."""
        self.dynamics = dict(section)

    def attach_memory(self, section: Dict[str, Any]) -> None:
        """Embed the HBM accounting
        (:func:`analysis.memory_model.memory_model_section` /
        ``serving_memory_section``: analytic per-device bytes from the
        verifier's slot peaks, AOT-compiled ``memory_analysis()``, live
        watermark summary and their reconciliation) as the manifest's
        ``memory`` block — the bytes-domain record ``scripts/regress.py``
        guards."""
        self.memory = dict(section)

    def attach_calibration(self, section: Dict[str, Any]) -> None:
        """Embed the predicted-vs-measured calibration record
        (:func:`analysis.calibration.calibration_section`: compact
        per-config probe rows, the raw-vs-corrected median error
        summary, the fitted per-hardware correction factors and the
        ledger path) as the manifest's ``calibration`` block — the
        model-trust record ``scripts/regress.py`` guards and the PR-19
        planner search will consume."""
        self.calibration = dict(section)

    # -- output ---------------------------------------------------------

    def manifest(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "schema_version": SCHEMA_VERSION,
            "meta": _jsonable(self.meta),
            "counters": dict(self.counters),
            "gauges": _jsonable(self.gauges),
            "timers": dict(self.timers),
            "n_events": len(self.events),
        }
        if self.out_dir is not None:
            out["events_path"] = os.path.join(self.out_dir, "events.jsonl")
        else:
            out["events"] = _jsonable(self.events)
        if self.telemetry is not None:
            out["telemetry"] = _jsonable(self.telemetry)
        if self.serving:
            out["serving"] = _jsonable(self.serving)
        if self.serving_load is not None:
            out["serving_load"] = _jsonable(self.serving_load)
        if self.resilience is not None:
            out["resilience"] = _jsonable(self.resilience)
        if self.static_analysis is not None:
            out["static_analysis"] = _jsonable(self.static_analysis)
        if self.cost_model is not None:
            out["cost_model"] = _jsonable(self.cost_model)
        if self.memory is not None:
            out["memory"] = _jsonable(self.memory)
        if self.dynamics is not None:
            out["dynamics"] = _jsonable(self.dynamics)
        if self.calibration is not None:
            out["calibration"] = _jsonable(self.calibration)
        return out

    def write(self, path: Optional[str] = None) -> Dict[str, Any]:
        """Validate + write the manifest (``report.json`` under ``out_dir``
        by default); returns the manifest dict."""
        m = self.manifest()
        validate_report(m)
        if path is None:
            if self.out_dir is None:
                raise ValueError("RunReport has no out_dir; pass a path")
            path = os.path.join(self.out_dir, "report.json")
        with open(path, "w") as fh:
            json.dump(m, fh, indent=2, default=_jsonable)
            fh.write("\n")
        if self._events_fh is not None:
            self._events_fh.close()
            self._events_fh = None
        return m


def _jsonable(x: Any) -> Any:
    """Best-effort conversion to JSON-serializable primitives (numpy
    scalars/arrays, dataclass-likes, tuples)."""
    if isinstance(x, dict):
        return {str(k): _jsonable(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_jsonable(v) for v in x]
    if isinstance(x, (np.integer,)):
        return int(x)
    if isinstance(x, (np.floating,)):
        return float(x)
    if isinstance(x, np.ndarray):
        return x.tolist()
    if isinstance(x, (str, int, float, bool)) or x is None:
        return x
    if hasattr(x, "__dataclass_fields__"):
        import dataclasses
        return _jsonable(dataclasses.asdict(x))
    return str(x)


def validate_report(manifest: Dict[str, Any]) -> None:
    """Schema check for a RunReport manifest (hand-rolled: the container
    has no jsonschema). Raises ``ValueError`` on the first violation."""
    def fail(msg: str):
        raise ValueError(f"invalid run report: {msg}")

    if not isinstance(manifest, dict):
        fail("manifest must be a dict")
    if manifest.get("schema_version") != SCHEMA_VERSION:
        fail(f"schema_version must be {SCHEMA_VERSION}, got "
             f"{manifest.get('schema_version')!r}")
    meta = manifest.get("meta")
    if not isinstance(meta, dict):
        fail("meta must be a dict")
    for key in ("name", "jax_version", "jaxlib_version"):
        if not isinstance(meta.get(key), str):
            fail(f"meta.{key} must be a string")
    if not isinstance(meta.get("created_unix"), (int, float)):
        fail("meta.created_unix must be a number")
    counters = manifest.get("counters")
    if not isinstance(counters, dict) or not all(
            isinstance(v, int) for v in counters.values()):
        fail("counters must be a dict of ints")
    if not isinstance(manifest.get("gauges"), dict):
        fail("gauges must be a dict")
    timers = manifest.get("timers")
    if not isinstance(timers, dict) or not all(
            isinstance(v, (int, float)) for v in timers.values()):
        fail("timers must be a dict of numbers")
    if not isinstance(manifest.get("n_events"), int):
        fail("n_events must be an int")
    events = manifest.get("events")
    if events is not None:
        if not isinstance(events, list):
            fail("events must be a list")
        for row in events:
            if not isinstance(row, dict) or not isinstance(
                    row.get("kind"), str) or not isinstance(
                    row.get("t"), (int, float)):
                fail("each event needs a str 'kind' and numeric 't'")
    elif not isinstance(manifest.get("events_path"), str):
        fail("manifest needs either inline 'events' or an 'events_path'")
    tel = manifest.get("telemetry")
    if tel is not None:
        if not isinstance(tel, dict):
            fail("telemetry must be a dict")
        if "timeline" in tel:
            if not isinstance(tel["timeline"], list) or not all(
                    isinstance(r, dict) and "duration_s" in r
                    and "n_ticks" in r for r in tel["timeline"]):
                fail("telemetry.timeline rows need duration_s and n_ticks")
    serving = manifest.get("serving")
    if serving is not None:
        if not isinstance(serving, list):
            fail("serving must be a list of run summaries")
        for row in serving:
            if not isinstance(row, dict):
                fail("each serving summary must be a dict")
            if not isinstance(row.get("policy"), str):
                fail("serving summary needs a str 'policy'")
            for key in ("tokens_out", "ticks", "n_requests"):
                if not isinstance(row.get(key), int):
                    fail(f"serving summary needs an int {key!r}")
            for key in ("wall_s", "tokens_per_sec", "goodput"):
                if not isinstance(row.get(key), (int, float)):
                    fail(f"serving summary needs a numeric {key!r}")
            for key in ("ttft_ticks", "tpot_ticks"):
                if not isinstance(row.get(key), dict):
                    fail(f"serving summary needs a dict {key!r} "
                         "(p50/p95/p99/mean)")
            if "n_failed" in row and not isinstance(row["n_failed"], int):
                fail("serving summary n_failed must be an int")
    sl = manifest.get("serving_load")
    if sl is not None:
        if not isinstance(sl, dict):
            fail("serving_load must be a dict")
        if not isinstance(sl.get("policy"), str):
            fail("serving_load.policy must be a string")
        wl = sl.get("workload")
        if not isinstance(wl, dict) or not isinstance(
                wl.get("mix"), str) or not isinstance(
                wl.get("n_requests"), int):
            fail("serving_load.workload needs a str 'mix' and int "
                 "'n_requests'")
        slo = sl.get("slo")
        if not isinstance(slo, dict) or not isinstance(
                slo.get("ttft_p99_ticks"), (int, float)):
            fail("serving_load.slo needs a numeric ttft_p99_ticks")
        curve = sl.get("curve")
        if not isinstance(curve, list) or not curve:
            fail("serving_load.curve must be a non-empty list")
        loads = []
        for row in curve:
            if not isinstance(row, dict) or not isinstance(
                    row.get("offered_load"), (int, float)):
                fail("serving_load curve rows need a numeric "
                     "'offered_load'")
            loads.append(float(row["offered_load"]))
            for key in ("ticks", "tokens_out"):
                if not isinstance(row.get(key), int):
                    fail(f"serving_load curve rows need an int {key!r}")
            for key in ("ttft_ticks", "tpot_ticks"):
                pct = row.get(key)
                if not isinstance(pct, dict) or "p99" not in pct:
                    fail(f"serving_load curve row {key!r} must be a "
                         "percentile dict carrying p99")
                if pct["p99"] is not None and not isinstance(
                        pct["p99"], (int, float)):
                    fail(f"serving_load curve row {key}.p99 must be a "
                         "number or null")
            # paged-engine gauge columns are optional (contiguous runs
            # omit them) but typed when present
            for key in ("goodput", "queue_depth_mean", "prefix_hit_rate",
                        "pages_used_mean", "page_fragmentation_mean"):
                if key in row and row[key] is not None and not isinstance(
                        row[key], (int, float)):
                    fail(f"serving_load curve row {key!r} must be numeric")
            for key in ("pages_capacity", "pages_used_max", "n_cow",
                        "n_backpressure", "prefill_skipped_tokens"):
                if key in row and row[key] is not None and not isinstance(
                        row[key], int):
                    fail(f"serving_load curve row {key!r} must be an int")
        if any(b <= a for a, b in zip(loads, loads[1:])):
            fail(f"serving_load offered loads must be strictly "
                 f"increasing, got {loads}")
        knee = sl.get("knee")
        if not isinstance(knee, dict) or not isinstance(
                knee.get("detected"), bool):
            fail("serving_load.knee must be a dict with a bool 'detected'")
        for key in ("knee_load", "max_sustainable_load"):
            v = knee.get(key)
            if v is not None and not isinstance(v, (int, float)):
                fail(f"serving_load.knee.{key} must be a number or null")
        if knee["detected"] and not isinstance(
                knee.get("knee_load"), (int, float)):
            fail("serving_load.knee.detected without a numeric knee_load")
        ref = sl.get("reference")
        if ref is not None:
            if not isinstance(ref, dict) or not isinstance(
                    ref.get("offered_load"), (int, float)):
                fail("serving_load.reference needs a numeric "
                     "'offered_load'")
    res = manifest.get("resilience")
    if res is not None:
        if not isinstance(res, dict):
            fail("resilience must be a dict")
        for key in ("anomalies", "anomaly_budget", "stalls", "n_committed",
                    "n_saved", "gc_removed"):
            if key in res and not isinstance(res[key], int):
                fail(f"resilience.{key} must be an int")
        if "preempted" in res and not isinstance(res["preempted"], bool):
            fail("resilience.preempted must be a bool")
    sa = manifest.get("static_analysis")
    if sa is not None:
        if not isinstance(sa, dict):
            fail("static_analysis must be a dict")
        if not isinstance(sa.get("verifier_version"), int):
            fail("static_analysis.verifier_version must be an int")
        if not isinstance(sa.get("schedules"), list) or not all(
                isinstance(s, str) for s in sa["schedules"]):
            fail("static_analysis.schedules must be a list of strings")
        if not isinstance(sa.get("hazards"), int):
            fail("static_analysis.hazards must be an int")
        shw = sa.get("slot_high_water")
        if not isinstance(shw, dict) or not all(
                isinstance(v, dict) and isinstance(v.get("act"), int)
                and isinstance(v.get("grad"), int) for v in shw.values()):
            fail("static_analysis.slot_high_water must map schedule labels "
                 "to {'act': int, 'grad': int}")
    cm = manifest.get("cost_model")
    if cm is not None:
        if not isinstance(cm, dict):
            fail("cost_model must be a dict")
        if not isinstance(cm.get("schedule"), str):
            fail("cost_model.schedule must be a string")
        hw = cm.get("hardware")
        if not isinstance(hw, dict) or not isinstance(
                hw.get("name"), str) or not isinstance(
                hw.get("peak_flops"), (int, float)):
            fail("cost_model.hardware needs a str name and numeric "
                 "peak_flops")
        pred = cm.get("predicted")
        if not isinstance(pred, dict):
            fail("cost_model.predicted must be a dict")
        for key in ("step_s", "step_s_comm_overlap", "bubble_table_exact",
                    "bubble_closed_form"):
            if not isinstance(pred.get(key), (int, float)):
                fail(f"cost_model.predicted.{key} must be a number")
        comm = cm.get("comm")
        if not isinstance(comm, dict) or not isinstance(
                comm.get("hops"), int):
            fail("cost_model.comm needs an int 'hops'")
        measured = cm.get("measured")
        if measured is not None:
            if not isinstance(measured, dict):
                fail("cost_model.measured must be a dict")
            for key in ("step_s", "mfu"):
                if not isinstance(measured.get(key), (int, float)):
                    fail(f"cost_model.measured.{key} must be a number")
        attrib = cm.get("attribution")
        if attrib is not None:
            if not isinstance(attrib, dict):
                fail("cost_model.attribution must be a dict")
            for key in ("compute_s", "comm_s", "bubble_s"):
                if not isinstance(attrib.get(key), (int, float)):
                    fail(f"cost_model.attribution.{key} must be a number")
    mem = manifest.get("memory")
    if mem is not None:
        if not isinstance(mem, dict):
            fail("memory must be a dict")
        if not isinstance(mem.get("schedule"), str):
            fail("memory.schedule must be a string")
        hw = mem.get("hardware")
        if not isinstance(hw, dict) or not isinstance(hw.get("name"), str):
            fail("memory.hardware needs a str name")
        ana = mem.get("analytic")
        if not isinstance(ana, dict):
            fail("memory.analytic must be a dict")
        for key in ("act_slot_bytes", "grad_slot_bytes", "peak_bytes",
                    "params_per_device_bytes"):
            if not isinstance(ana.get(key), (int, float)):
                fail(f"memory.analytic.{key} must be a number")
        devs = ana.get("per_device")
        if not isinstance(devs, list) or not devs:
            fail("memory.analytic.per_device must be a non-empty list")
        for row in devs:
            if not isinstance(row, dict) or not isinstance(
                    row.get("device"), int):
                fail("memory.analytic.per_device rows need an int 'device'")
            for key in ("act_bytes", "grad_bytes", "total_bytes"):
                if not isinstance(row.get(key), (int, float)):
                    fail(f"memory.analytic.per_device.{key} must be a "
                         "number")
        comp = mem.get("compiled")
        if comp is not None:
            if not isinstance(comp, dict):
                fail("memory.compiled must be a dict")
            if "error" not in comp:
                for key in ("argument_bytes", "output_bytes", "temp_bytes"):
                    if not isinstance(comp.get(key), (int, float)):
                        fail(f"memory.compiled.{key} must be a number")
        live = mem.get("live")
        if live is not None:
            if not isinstance(live, dict) or not isinstance(
                    live.get("available"), bool):
                fail("memory.live needs a bool 'available'")
            if not isinstance(live.get("per_device"), list):
                fail("memory.live.per_device must be a list")
    dyn = manifest.get("dynamics")
    if dyn is not None:
        if not isinstance(dyn, dict):
            fail("dynamics must be a dict")
        if not isinstance(dyn.get("n_stages"), int):
            fail("dynamics.n_stages must be an int")
        for key in ("gns_updates", "n_skipped_attributed"):
            if not isinstance(dyn.get(key), int):
                fail(f"dynamics.{key} must be an int")
        # grad_norm_final / gns may be None (no log sync ran / estimator
        # unarmed) or a number; a poisoned final step serializes as the
        # string repr ("nan") — still a valid record of what happened
        for key in ("grad_norm_final", "gns"):
            if key in dyn and not isinstance(
                    dyn[key], (int, float, str, type(None))):
                fail(f"dynamics.{key} must be a number, string or null")
        rows = dyn.get("per_stage")
        if not isinstance(rows, list):
            fail("dynamics.per_stage must be a list")
        for row in rows:
            if not isinstance(row, dict) or not isinstance(
                    row.get("stage"), int):
                fail("dynamics.per_stage rows need an int 'stage'")
            if not isinstance(row.get("nonfinite"), int):
                fail("dynamics.per_stage rows need an int 'nonfinite'")
            for key in ("grad_norm", "grad_max", "param_rms",
                        "update_ratio"):
                if key in row and not isinstance(
                        row[key], (int, float, str)):
                    fail(f"dynamics.per_stage.{key} must be a number "
                         "(or a non-finite repr string)")
        bundles = dyn.get("forensic_bundles")
        if not isinstance(bundles, list) or not all(
                isinstance(b, str) for b in bundles):
            fail("dynamics.forensic_bundles must be a list of filenames")
    cal = manifest.get("calibration")
    if cal is not None:
        if not isinstance(cal, dict):
            fail("calibration must be a dict")
        if not isinstance(cal.get("schema_version"), int):
            fail("calibration.schema_version must be an int")
        rows = cal.get("rows")
        if not isinstance(rows, list):
            fail("calibration.rows must be a list")
        if cal.get("n_rows") != len(rows):
            fail(f"calibration.n_rows ({cal.get('n_rows')!r}) must equal "
                 f"len(rows) ({len(rows)})")
        for row in rows:
            if not isinstance(row, dict):
                fail("calibration.rows entries must be dicts")
            for key in ("schedule", "schedule_family", "backward_policy",
                        "comm_overlap"):
                if not isinstance(row.get(key), str):
                    fail(f"calibration row {key!r} must be a string")
            for key in ("n_devices", "n_microbatches"):
                if not isinstance(row.get(key), int):
                    fail(f"calibration row {key!r} must be an int")
            # predicted/measured/rel_err may be null (backfilled rows with
            # only one side of the comparison) but must be present
            for key in ("predicted_step_s", "measured_step_s", "rel_err"):
                if key not in row:
                    fail(f"calibration row missing {key!r}")
                if row[key] is not None and not isinstance(
                        row[key], (int, float)):
                    fail(f"calibration row {key!r} must be a number or null")
        summary = cal.get("summary")
        if not isinstance(summary, dict):
            fail("calibration.summary must be a dict")
        for key in ("median_abs_rel_err_raw", "median_abs_rel_err_corrected"):
            if key not in summary:
                fail(f"calibration.summary missing {key!r}")
            if summary[key] is not None and not isinstance(
                    summary[key], (int, float)):
                fail(f"calibration.summary.{key} must be a number or null")
        if not isinstance(summary.get("groups"), dict):
            fail("calibration.summary.groups must be a dict")
        corr = cal.get("correction")
        if corr is not None:
            if not isinstance(corr, dict):
                fail("calibration.correction must be a dict")
            for hw_name, factors in corr.items():
                if not isinstance(factors, dict):
                    fail(f"calibration.correction[{hw_name!r}] must be "
                         "a dict")
                for key in ("flops_efficiency", "bandwidth_efficiency"):
                    if not isinstance(factors.get(key), (int, float)):
                        fail(f"calibration.correction[{hw_name!r}].{key} "
                             "must be a number")
        lp = cal.get("ledger_path")
        if lp is not None and not isinstance(lp, str):
            fail("calibration.ledger_path must be a string or null")
