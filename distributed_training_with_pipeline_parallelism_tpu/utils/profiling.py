"""Profiling and pipeline-bubble measurement.

The reference's only instrumentation is ``time.time()`` around the timed loop
(SURVEY.md §5 tracing row; upstream's ``record_function`` blocks are never
collected). Here:

- :func:`trace` wraps ``jax.profiler.trace`` — traces open in
  XProf/TensorBoard with per-op device timelines (the honest way to see
  bubbles on real hardware).
- :func:`measure_bubble` derives an end-to-end *measured* bubble fraction
  from wall-clocks, no profiler needed: a perfectly pipelined D-stage run
  would take ``t_single / D`` per step (same total FLOPs, spread over D
  chips); the measured bubble is the shortfall from that ideal,
  ``1 - t_single / (D * t_pipe)``. Comparable to the analytic
  ``(D-1)/(M+D-1)`` and the tick-simulated fraction
  (:func:`..parallel.schedules.simulated_bubble`) — the BASELINE.json
  north-star asks for measured-vs-analytic agreement.

Note the measured number also absorbs communication and remat overhead, so
it upper-bounds the pure schedule bubble; the gap between measured and
simulated (w_b=3) is the transport+overhead cost.

Caveat for simulated (CPU) meshes: the measurement assumes the D mesh
devices actually run in parallel. On a host with fewer cores than devices
the "parallel" ticks serialize and ``bubble_measured`` degenerates toward
``1 - 1/D`` regardless of schedule (docs/performance.md §bubbles) — use
the tick simulation for schedule comparisons there, and reserve this
function for real multi-chip slices.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Optional

import jax


@contextlib.contextmanager
def trace(log_dir: str):
    """Profile a block: ``with trace('/tmp/prof'): step(...)`` then inspect
    in TensorBoard/XProf. The pipeline executors label their compute with
    ``pp/...`` named scopes (``pp/phase3``, ``pp/fwd``, ``pp/ring_bwd``,
    ...), so trace rows group by schedule structure — see
    docs/observability.md for the reading guide."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str):
    """Label HOST-side spans of a traced block in XProf:
    ``with annotate("step3"): step(...)``. Complements the executors'
    ``jax.named_scope`` labels, which name DEVICE-side ops at trace time:
    ``TraceAnnotation`` marks wall-clock regions of the host timeline
    (e.g. which bench rung or train step issued the work). No-op cost when
    no profiler session is active."""
    with jax.profiler.TraceAnnotation(name):
        yield


def _time_fn(fn, *args, iters: int = 5, warmup: int = 2) -> float:
    from .metrics import force_completion  # host fetch: see metrics.py note
    out = None
    for _ in range(warmup):
        out = fn(*args)
    force_completion(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    force_completion(out)
    return (time.perf_counter() - t0) / iters


def measure_bubble(cfg, mesh, sched, batch_size: int = 32,
                   seq_length: int = 128, iters: int = 5,
                   seed: int = 0) -> Dict[str, float]:
    """Measured vs analytic vs simulated bubble for one config.

    Runs the pipeline step on the mesh and an equivalent single-device step
    (same model, same microbatch gradient accumulation via a GPipe program on
    a 1-device mesh, so remat costs cancel out of the comparison), then
    reports ``bubble_measured = 1 - t_single / (D * t_pipe)``.
    """
    from ..models.transformer import transformer_init
    from ..parallel.mesh import make_mesh
    from ..parallel.pipeline import make_pipeline_step
    from ..parallel.schedules import (analytic_bubble_fraction,
                                      compile_schedule, simulated_bubble)
    from ..utils.config import ScheduleConfig

    D = mesh.shape["pipe"]
    params = transformer_init(jax.random.key(seed), cfg)
    kx, ky = jax.random.split(jax.random.key(seed + 1))
    tokens = jax.random.randint(kx, (batch_size, seq_length), 0, cfg.vocab_size)
    targets = jax.random.randint(ky, (batch_size, seq_length), 0, cfg.vocab_size)

    pipe_step = make_pipeline_step(cfg, mesh, sched)
    t_pipe = _time_fn(pipe_step, params, tokens, targets, iters=iters)

    single_mesh = make_mesh(n_pipe=1, devices=list(mesh.devices.flat)[:1])
    single_sched = ScheduleConfig(name="GPipe",
                                  n_microbatches=sched.n_microbatches)
    # force the tick executor AND the rematerializing backward so the
    # comparator pays the same per-unit costs as the D-device pipeline run
    # (the degenerate fast path skips remat entirely, and the D=1 default
    # is the unrolled stored program — either would skew the ratio)
    single_step = make_pipeline_step(cfg, single_mesh, single_sched,
                                     force_tick_executor=True,
                                     remat_backward=True)
    t_single = _time_fn(single_step, params, tokens, targets, iters=iters)

    cs = compile_schedule(sched.name, D, sched.n_virtual, sched.n_microbatches)
    return {
        "t_pipeline": t_pipe,
        "t_single_device": t_single,
        "bubble_measured": 1.0 - t_single / (D * t_pipe),
        "bubble_analytic": analytic_bubble_fraction(
            sched.name, D, sched.n_virtual, sched.n_microbatches, cs=cs),
        "bubble_simulated": simulated_bubble(cs, w_f=1.0, w_b=3.0)[
            "bubble_fraction"],
    }
