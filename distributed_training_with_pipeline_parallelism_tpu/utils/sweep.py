"""Experiment sweep driver: the reference's 54-config harness, TPU-native.

Parity targets (SURVEY.md C6-C9):

- ``run_one_experiment`` (notebook cell 19, ``.ipynb:296-335``) — one config,
  one metrics dict. The reference spawns ``num_processes`` fresh interpreters
  rendezvousing over gloo; here a config is one jitted SPMD program over a
  ``num_devices``-wide pipeline mesh, so "launch" is just compile + run.
- ``run_all_experiments`` (cell 20, ``.ipynb:337-394``) — the full cross
  product layers {4,8,12} x heads {4,8,12} x devices {2,4} x schedules
  {GPipe, 1F1B, Interleaved1F1B} = 54 experiments, 5 timed iterations each,
  batch 32, seq 128; per-experiment progress printing; errors logged and
  skipped (same contract: a failed config contributes an ``error`` row and
  the sweep continues).
- ``compute_speedup_and_efficiency`` (cell 21, ``.ipynb:396-435``) —
  ``speedup = throughput / GPipe throughput`` per (layers, heads, devices)
  group; ``efficiency = speedup / devices * 100``.

Additions over the reference (SURVEY.md §5 metrics row): analytic and
simulated pipeline-bubble columns, and tokens/sec/chip.
"""

from __future__ import annotations

import traceback
from typing import Dict, Iterable, Optional, Sequence

import pandas as pd

from .config import ModelConfig, RunConfig, ScheduleConfig, virtual_stages_for
from .metrics import run_train_iterations

SCHEDULES = ("GPipe", "1F1B", "Interleaved1F1B")


def run_one_experiment(n_layers: int, n_heads: int, num_devices: int,
                       schedule_type: str, batch_size: int = 32,
                       seq_length: int = 128, num_iterations: int = 5,
                       dim: int = 768, vocab_size: int = 10000,
                       n_microbatches: int = 4, seed: int = 0,
                       arch: str = "ref_decoder",
                       dtype: str = "float32",
                       remat_backward=None,
                       unroll_ticks=None,
                       report_dir: Optional[str] = None,
                       schedule_artifact: Optional[str] = None,
                       oom_preflight: bool = True,
                       dynamics: bool = False
                       ) -> Dict[str, float]:
    """Run one pipeline experiment; returns the reference's metrics dict plus
    bubble analytics, or ``{"error": ...}`` on failure.

    ``report_dir``: also emit the row as a structured
    :class:`.telemetry.RunReport` manifest — config/mesh/schedule meta,
    the metrics as gauges, timed-loop timers — appended as one JSON line
    to ``{report_dir}/sweep_reports.jsonl`` (validated against the shared
    schema before writing), so sweep rows, ``fit`` runs and ``bench.py``
    all speak the same report format (docs/observability.md).

    Self-describing columns (so the artifact cannot be misread without its
    docs): ``backward_policy`` records which backward the executor compiled
    ('stored', 'remat' or 'split' — ``analysis.cost_model``'s shared
    resolution), ``tick_executor`` which tick-loop formulation
    ('unrolled', 'scan', or 'phases' — the ``unroll_ticks`` resolution),
    ``bubble_sim_w_b`` the matching per-tick backward
    weight the ``bubble_simulated`` column was computed under, and
    ``host_serialized`` whether the mesh was CPU-simulated on a host — where
    every "parallel" tick serializes, wall-clock measures total work plus
    per-tick overhead, and the throughput columns must NOT be read as
    pipeline-overlap measurements (schedule-ordering claims come from the
    bubble/cost-model columns; docs/results.md §2).

    ``schedule_artifact``: path to a certified schedule artifact
    (``scripts/search_schedule.py``). It is registered and re-certified
    on load, and overrides ``schedule_type``/``n_microbatches``/the
    virtual-stage rule with the artifact's own certified config, so a
    searched schedule is a first-class sweep row (the row records the
    pinned table digest in ``schedule_artifact_digest``).

    ``oom_preflight``: price the config with ``analysis.memory_model``
    against the detected chip's HBM capacity BEFORE compiling anything;
    a predicted overflow returns a ``skip_reason="predicted_oom"`` row
    (with the predicted bytes) instead of crashing mid-sweep. Pass
    ``False`` to force the compile anyway.

    ``dynamics``: also run one dynamics-instrumented gradient pass after
    the timed loop (off the clock — the timed throughput columns are
    unaffected) and fill the ``grad_norm_final`` / ``gns`` /
    ``n_skipped_attributed`` model-health columns
    (docs/observability.md §7). Off by default; the columns are present
    either way (None when off) so DataFrames concatenate cleanly."""
    import jax

    from ..models.transformer import transformer_init
    from ..parallel.mesh import make_mesh
    from ..parallel.pipeline import make_pipeline_step
    from ..parallel.schedules import (analytic_bubble_fraction,
                                      compile_schedule, simulated_bubble)

    try:
        artifact_info = None
        if schedule_artifact is not None:
            from ..parallel.schedules import (register_schedule_artifact,
                                              registered_artifact_info)
            cs_art = register_schedule_artifact(schedule_artifact)
            schedule_type = cs_art.name
            n_microbatches = cs_art.n_microbatches
            n_virtual = cs_art.n_virtual
            artifact_info = registered_artifact_info(schedule_type)
        else:
            n_virtual = virtual_stages_for(schedule_type, n_layers,
                                           num_devices)
        if schedule_type == "ZBV":
            # ZBV's steady state needs M >= 2D; lift the reference's fixed 4
            # where required (recorded in the row's n_microbatches column)
            n_microbatches = max(n_microbatches, 2 * num_devices)
        cfg = ModelConfig(dim=dim, n_layers=n_layers, n_heads=n_heads,
                          vocab_size=vocab_size, arch=arch, dtype=dtype)
        sched = ScheduleConfig(name=schedule_type,
                               n_microbatches=n_microbatches,
                               n_virtual=n_virtual)
        cs = compile_schedule(schedule_type, num_devices, n_virtual,
                              n_microbatches)
        # OOM preflight: price the config BEFORE the (expensive, possibly
        # fatal) compile; a predicted overflow becomes a skipped row
        from ..analysis.memory_model import (memory_model_section,
                                             oom_preflight as _preflight)
        mem_section = memory_model_section(
            cs, cfg, batch_size=batch_size, seq_length=seq_length,
            remat_backward=remat_backward)
        if oom_preflight:
            pf = _preflight(mem_section)
            if not pf["ok"]:
                return {
                    "skip_reason": "predicted_oom",
                    "predicted_peak_bytes": pf["predicted_peak_bytes"],
                    "hbm_bytes": pf["hbm_bytes"],
                    "n_virtual": n_virtual,
                    "n_microbatches": n_microbatches,
                }
        mesh = make_mesh(n_pipe=num_devices)
        step = make_pipeline_step(cfg, mesh, sched,
                                  remat_backward=remat_backward,
                                  unroll_ticks=unroll_ticks)

        params = transformer_init(jax.random.key(seed), cfg)
        kx, ky = jax.random.split(jax.random.key(seed + 1))
        tokens = jax.random.randint(kx, (batch_size, seq_length), 0, vocab_size)
        targets = jax.random.randint(ky, (batch_size, seq_length), 0, vocab_size)

        report = None
        if report_dir is not None:
            from .telemetry import RunReport
            report = RunReport(name=f"sweep_L{n_layers}_H{n_heads}_"
                                    f"D{num_devices}_{schedule_type}")
            meta_extra = ({"schedule_artifact": artifact_info}
                          if artifact_info else {})
            report.set_meta(config=cfg, schedule=sched,
                            mesh_shape=dict(mesh.shape),
                            batch_size=batch_size, seq_length=seq_length,
                            backend=jax.devices()[0].platform,
                            **meta_extra)
        metrics = run_train_iterations(step, params, tokens, targets,
                                       num_iterations=num_iterations,
                                       report=report)
        # bubble_simulated uses the weights of the backward the executor
        # actually compiled, mirroring make_pipeline_grad_fn's resolution
        # (shared with the roofline in analysis.cost_model): stored
        # (w_b=2, ~2 fwd-equivalents of grad work) at D==1 by default or
        # on explicit remat_backward=False; otherwise remat (w_b=3: +1
        # recompute). Split-backward schedules always rematerialize:
        # B = recompute + dgrad ~ 2, W = recompute + wgrad ~ 2.
        from ..analysis.cost_model import (backward_weights,
                                           cost_model_section,
                                           resolve_backward_policy)
        policy = resolve_backward_policy(cs, remat_backward, num_devices)
        w_b, w_w = backward_weights(policy)
        sim = simulated_bubble(cs, w_f=1.0, w_b=w_b, w_w=w_w)
        # the full roofline section (predicted vs measured step time,
        # table-exact bubble, MFU) — its headline numbers also land as
        # sweep columns so schedule comparisons stay one-DataFrame reads;
        # fitted calibration corrections (scripts/probe.py) apply when
        # the artifact is present
        from ..analysis.calibration import maybe_load_default_corrections
        corrections = maybe_load_default_corrections()
        cost_model = cost_model_section(
            cs, cfg, batch_size=batch_size, seq_length=seq_length,
            remat_backward=remat_backward,
            measured_step_s=metrics["elapsed_time"] / num_iterations,
            correction=corrections)
        metrics.update({
            "throughput_per_chip": metrics["throughput"] / num_devices,
            "n_virtual": n_virtual,
            "n_microbatches": n_microbatches,
            # first-class predicted-vs-measured columns (the calibration
            # ledger's headline axis; scripts/regress.py extracts these
            # uniformly from sweep rows and bench results)
            "predicted_step_s": cost_model["predicted"]["step_s"],
            "rel_err": cost_model.get("measured", {}).get("rel_err"),
            "rel_err_corrected": cost_model.get("measured", {}).get(
                "rel_err_corrected"),
            "bubble_analytic": analytic_bubble_fraction(
                schedule_type, num_devices, n_virtual, n_microbatches, cs=cs),
            "bubble_simulated": sim["bubble_fraction"],
            "bubble_sim_w_b": w_b,
            "bubble_table_exact": cost_model["predicted"][
                "bubble_table_exact"],
            "mfu": cost_model.get("measured", {}).get("mfu"),
            "backward_policy": policy,
            # which tick-loop formulation compiled (mirrors the auto
            # resolution in make_pipeline_grad_fn; 'unrolled' also covers
            # the D==1 stored program, which is unrolled by construction)
            "tick_executor": (
                {True: "unrolled", False: "scan", "phases": "phases"}
                [unroll_ticks] if unroll_ticks is not None
                else ("unrolled" if cs.table.shape[0] <= 64 else "phases")),
            "host_serialized": jax.devices()[0].platform == "cpu",
        })
        # model-health columns: present on every row (None when dynamics
        # is off) so sweeps with and without them concatenate cleanly
        dyn_cols: Dict[str, object] = {"grad_norm_final": None, "gns": None,
                                       "n_skipped_attributed": None}
        if dynamics:
            from ..parallel.pipeline import make_pipeline_grad_fn
            from .dynamics import GNSEstimator, stage_stats
            # one instrumented pass off the clock; the tick executor with
            # remat is the configuration the GNS accumulator supports
            dyn_grad = make_pipeline_grad_fn(
                cfg, mesh, sched, remat_backward=True, unroll_ticks=True,
                dynamics=True)
            _, grads_d, sq_mb = dyn_grad(params, tokens, targets)
            st = stage_stats(cfg.n_layers, num_devices * n_virtual, grads_d)
            dyn_cols["grad_norm_final"] = float(st["grad_norm"])
            dyn_cols["n_skipped_attributed"] = 0  # no guard in a sweep row
            if n_microbatches > 1:
                est = GNSEstimator(
                    batch_small=batch_size * seq_length / n_microbatches,
                    batch_big=batch_size * seq_length)
                est.update(float(sq_mb.mean()),
                           float(st["grad_norm"]) ** 2)
                dyn_cols["gns"] = est.value()
        metrics.update(dyn_cols)
        if artifact_info is not None:
            metrics["schedule_artifact_digest"] = \
                artifact_info["table_digest"]
        if report is not None:
            import json
            import os

            from .telemetry import validate_report
            for k, v in metrics.items():
                report.gauge(k, v)
            report.attach_cost_model(cost_model)
            # the run's own predicted-vs-measured point as a calibration
            # section (docs/observability.md §9)
            from ..analysis.calibration import (
                calibration_section_from_cost_model)
            cal_section = calibration_section_from_cost_model(
                cost_model, backend=jax.devices()[0].platform,
                name=f"sweep_{schedule_type}", correction=corrections)
            if cal_section is not None:
                report.attach_calibration(cal_section)
            # bytes-domain section: the preflight's analytic model plus
            # XLA's own accounting (free — the step is already compiled)
            from ..parallel.pipeline import aot_memory_analysis
            mem_section = memory_model_section(
                cs, cfg, batch_size=batch_size, seq_length=seq_length,
                remat_backward=remat_backward,
                compiled=aot_memory_analysis(step, params, tokens, targets))
            report.attach_memory(mem_section)
            if dynamics and dyn_cols["grad_norm_final"] is not None:
                from .dynamics import dynamics_section
                report.attach_dynamics(dynamics_section(
                    num_devices * n_virtual, last_stats=st,
                    gns=dyn_cols["gns"],
                    gns_updates=0 if dyn_cols["gns"] is None else 1))
            manifest = report.manifest()
            validate_report(manifest)
            os.makedirs(report_dir, exist_ok=True)
            with open(os.path.join(report_dir, "sweep_reports.jsonl"),
                      "a") as fh:
                fh.write(json.dumps(manifest) + "\n")
        return metrics
    except Exception as e:  # same catch-all contract as the reference worker
        traceback.print_exc()
        return {"error": str(e)}


def run_all_experiments(layers: Sequence[int] = (4, 8, 12),
                        heads: Sequence[int] = (4, 8, 12),
                        devices: Sequence[int] = (2, 4),
                        schedules: Sequence[str] = SCHEDULES,
                        batch_size: int = 32, seq_length: int = 128,
                        num_iterations: int = 5,
                        verbose: bool = True,
                        **kwargs) -> pd.DataFrame:
    """The reference's full cross-product sweep -> DataFrame (54 rows by
    default). Failed configs are reported and skipped, not fatal."""
    configs = [(L, H, D, s) for L in layers for H in heads
               for D in devices for s in schedules]
    rows = []
    for k, (L, H, D, s) in enumerate(configs, 1):
        if verbose:
            print(f"[{k}/{len(configs)}] Running: layers={L} heads={H} "
                  f"devices={D} schedule={s}", flush=True)
        result = run_one_experiment(L, H, D, s, batch_size=batch_size,
                                    seq_length=seq_length,
                                    num_iterations=num_iterations, **kwargs)
        if "error" in result:
            if verbose:
                print(f"    ERROR: {result['error']}", flush=True)
            continue
        if "skip_reason" in result:
            # a priced-out config is a row, not a crash: the DataFrame
            # records WHY it was skipped and how far over budget it was
            if verbose:
                print(f"    SKIPPED ({result['skip_reason']}): predicted "
                      f"{result.get('predicted_peak_bytes', 0) / 1e9:.2f} GB "
                      f"> {result.get('hbm_bytes', 0) / 1e9:.2f} GB HBM",
                      flush=True)
            rows.append({
                "n_layers": L, "n_heads": H, "num_processes": D,
                "schedule": s, **result,
            })
            continue
        if verbose:
            print(f"    throughput: {result['throughput']:.2f} tokens/sec",
                  flush=True)
            if result.get("grad_norm_final") is not None:
                gns = result.get("gns")
                print(f"    dynamics: grad_norm "
                      f"{result['grad_norm_final']:.4f}, gns "
                      + (f"{gns:.1f}" if gns is not None else "n/a"),
                      flush=True)
        rows.append({
            "n_layers": L, "n_heads": H, "num_processes": D, "schedule": s,
            **result,
        })
    return pd.DataFrame(rows)


def compute_speedup_and_efficiency(df: pd.DataFrame) -> pd.DataFrame:
    """Per (layers, heads, devices) group: speedup of each schedule over
    GPipe; scaling efficiency = speedup / devices * 100 (the problem-set
    formula, notebook cell 21)."""
    rows = []
    for (L, H, D), g in df.groupby(["n_layers", "n_heads", "num_processes"]):
        gp = g[g["schedule"] == "GPipe"]
        if gp.empty:
            continue
        base = float(gp["throughput"].iloc[0])
        # every non-GPipe schedule present (the reference's two, plus any
        # beyond-parity/custom schedules the sweep was run with)
        for schedule in [s for s in g["schedule"].unique() if s != "GPipe"]:
            row = g[g["schedule"] == schedule]
            speedup = float(row["throughput"].iloc[0]) / base
            rows.append({
                "n_layers": L, "n_heads": H, "num_processes": D,
                "schedule": schedule, "speedup": speedup,
                "efficiency": speedup / D * 100.0,
            })
    return pd.DataFrame(rows)


def summarize_dynamics(df: pd.DataFrame) -> pd.DataFrame:
    """Per-schedule model-health summary over a ``dynamics=True`` sweep:
    median ``grad_norm_final`` / ``gns`` and total attributed skips.
    Rows the dynamics pass did not run for (column absent or None) are
    excluded; an all-None sweep summarizes to an empty frame."""
    empty = pd.DataFrame(
        columns=["schedule", "n", "grad_norm_final_median",
                 "gns_median", "n_skipped_attributed"])
    if "grad_norm_final" not in df.columns:
        return empty
    d = df[df["grad_norm_final"].notna()]
    if d.empty:  # all-None: same schema as the column-absent case
        return empty
    rows = []
    for schedule, g in d.groupby("schedule"):
        gns = g["gns"].dropna() if "gns" in g.columns else []
        skipped = (g["n_skipped_attributed"].dropna().sum()
                   if "n_skipped_attributed" in g.columns else 0)
        rows.append({
            "schedule": schedule,
            "n": len(g),
            "grad_norm_final_median": float(g["grad_norm_final"].median()),
            "gns_median": (float(pd.Series(gns).median())
                           if len(gns) else None),
            "n_skipped_attributed": int(skipped),
        })
    return pd.DataFrame(rows)


def pivot_throughput(df: pd.DataFrame) -> pd.DataFrame:
    """Cell-25-style pivot: throughput by (layers, heads) x (schedule, devices)."""
    return df.pivot_table(index=["n_layers", "n_heads"],
                          columns=["schedule", "num_processes"],
                          values="throughput")
