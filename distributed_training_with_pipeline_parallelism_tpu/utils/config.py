"""Configuration dataclasses for models, meshes, schedules, and runs.

Reference parity: the reference keeps hyperparameters in a tiny ``ModelArgs``
dataclass (``LLMsDistributedTrainingHelper.py:23-28``: dim=768, n_layers=8,
n_heads=8, vocab_size=10000) and hard-codes run constants (batch 32, seq 128,
4 microbatches) inline. Here every knob is an explicit dataclass so the sweep
driver stays declarative.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple, Union


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Decoder-only transformer LM hyperparameters.

    Defaults mirror the reference's ``ModelArgs`` plus the implicit defaults it
    inherits from ``nn.TransformerDecoderLayer`` (ffn_dim=2048, post-LN,
    relu activation, no causal mask, no positional encoding —
    ``LLMsDistributedTrainingHelper.py:31-55`` never passes masks and never adds
    position embeddings).

    ``arch`` selects the block family:
      - "ref_decoder": reference-parity block — post-LN, self-attn + cross-attn
        where memory == the block's own input (``layer(h, h)``), relu MLP.
      - "gpt2": pre-LN, causal self-attn, gelu MLP, learned position embeddings.
      - "llama": pre-RMSNorm, causal self-attn with RoPE, SwiGLU MLP, no biases,
        tied-free output head.
    """

    dim: int = 768
    n_layers: int = 8
    n_heads: int = 8
    vocab_size: int = 10000
    ffn_dim: int = 2048
    max_seq_len: int = 2048
    arch: str = "ref_decoder"
    dropout: float = 0.0  # train-mode dropout rate. The reference implicitly
    # trains with torch's default 0.1 (nn.TransformerDecoderLayer); we default
    # to 0.0 for determinism (it never asserts loss values — only throughput).
    # Active only when an rng is passed to the apply/loss/pipeline functions
    # (train mode); calls without an rng always run deterministically.
    dtype: str = "float32"
    # Mixed-precision master weights: store parameters in this dtype while
    # computing in ``dtype``. None = same as ``dtype`` (no mixing). The
    # standard TPU recipe is dtype="bfloat16", param_dtype="float32": MXU
    # matmuls run bf16, but weights, gradient accumulation, and optimizer
    # moments stay fp32 (the cast sits inside autodiff, so grads come back
    # fp32 automatically).
    param_dtype: Optional[str] = None
    # Tie the output head to the token embedding (GPT-2 upstream,
    # Llama-3.2-class): the head has no "out" matrix; logits are
    # ``norm(h) @ embed.tok.T`` and the embedding receives gradient from
    # both its lookup and the head matmul. The reference's Linear head is
    # untied (SURVEY.md C2), so False is the parity default.
    tie_embeddings: bool = False
    # Ignore-index loss masking: target positions equal to this id contribute
    # nothing to the loss, and the mean divides by the GLOBAL valid-token
    # count (torch CrossEntropyLoss(ignore_index=...) semantics) — for
    # right-padded batches of ragged sequences. None = every position counts
    # (the reference's regime).
    pad_token_id: Optional[int] = None
    # Attention kernel routing: True forces the Pallas flash kernel, False
    # forces dense XLA softmax-matmuls, "auto" (default) picks flash exactly
    # where it measures faster end-to-end on TPU — causal attention at
    # seq >= 1024 with no attention-prob dropout (docs/performance.md: the
    # flash backward is 1.15-24x the XLA dense backward there) — and dense
    # everywhere else (short sequences, non-causal ref_decoder, CPU CI).
    use_flash_attention: Union[bool, str] = "auto"
    use_fused_xent: bool = False  # route the loss through the Pallas fused-CE kernel
    remat_layers: bool = False  # jax.checkpoint each layer: trade FLOPs for HBM
    # Unroll the per-layer scan into straight-line code: XLA fuses across
    # layers and backward residuals avoid the scan-boundary HBM round-trip
    # (measured +5-12% train-step throughput on one v5e chip at GPT-2
    # scale, docs/performance.md). Costs compile time on deep models.
    unroll_layers: bool = False
    # Llama-only knobs.
    n_kv_heads: Optional[int] = None
    rope_theta: float = 10000.0
    # Llama-3.1 rope scaling: (factor, low_freq_factor, high_freq_factor,
    # original_max_position_embeddings), or None for plain RoPE.
    rope_scaling: Optional[Tuple[float, float, float, int]] = None
    rms_eps: float = 1e-5
    # Mistral-style sliding-window attention: each position attends to at
    # most this many preceding positions (None = full causal). llama arch.
    sliding_window: Optional[int] = None
    # Qwen2-style attention biases: q/k/v projections carry biases while the
    # output projection stays bias-free. llama arch only.
    attention_qkv_bias: bool = False
    # Gemma-family knobs (llama arch only):
    # - head_dim_override: decouple per-head width from dim/n_heads
    #   (Gemma: 256 regardless of dim); None = dim // n_heads.
    # - mlp_act: the gated MLP's gate activation — "silu" (Llama SwiGLU) or
    #   "gelu" (Gemma GeGLU, tanh approximation).
    # - embed_scale: multiply embedding OUTPUTS by sqrt(dim) (the tied head
    #   keeps the unscaled table, so this cannot fold into the weights).
    #   Unlike the other two knobs, also allowed on arch='gpt2' so the MoE
    #   LM (gpt2-style blocks) can use Gemma-style scaled embeddings.
    # Gemma's (1 + w) RMSNorm parametrization needs no knob: the +1 is
    # folded into the stored scale at HF import/export (models/hf.py).
    head_dim_override: Optional[int] = None
    mlp_act: str = "silu"
    embed_scale: bool = False
    # TP comm/compute overlap for the manual-SPMD MLP under a 'model' axis:
    # "none" (default) keeps the unfused Megatron block bitwise unchanged;
    # "ring" routes the MLP boundary through the collective-matmul forms
    # (ops.collectives.all_gather_matmul / matmul_reduce_scatter), which
    # overlap the TP all-gather with the up-projection and the
    # reduce-scatter with the down-projection (requires seq divisible by
    # the model-axis size); "auto" picks ring on TPU where the shapes
    # divide and falls back to the unfused path on the CPU proxy
    # (parallel.tensor_parallel.resolve_tp_overlap).
    tp_overlap: str = "none"

    def __post_init__(self):
        if self.dim % self.n_heads != 0:
            raise ValueError(f"dim={self.dim} must be divisible by n_heads={self.n_heads}")
        if self.n_kv_heads is not None and self.n_heads % self.n_kv_heads != 0:
            raise ValueError(f"n_heads={self.n_heads} must be divisible by n_kv_heads={self.n_kv_heads}")
        if self.arch not in ("ref_decoder", "gpt2", "llama"):
            raise ValueError(f"unknown arch {self.arch!r}")
        if self.attention_qkv_bias and self.arch != "llama":
            raise ValueError("attention_qkv_bias requires arch='llama' "
                             "(Qwen2-family blocks; gpt2/ref biases are "
                             "always on)")
        if self.mlp_act not in ("silu", "gelu"):
            raise ValueError(f"mlp_act={self.mlp_act!r} must be 'silu' or "
                             f"'gelu'")
        if ((self.head_dim_override is not None or self.mlp_act != "silu")
                and self.arch != "llama"):
            raise ValueError("head_dim_override / mlp_act are Gemma-family "
                             "knobs on arch='llama' blocks")
        if self.embed_scale and self.arch == "ref_decoder":
            raise ValueError("embed_scale applies to gpt2/llama blocks "
                             "(Gemma-style scaled embeddings; gpt2 is "
                             "allowed so the MoE LM — gpt2-style blocks — "
                             "can use it)")
        if self.head_dim_override is not None and self.head_dim_override < 1:
            raise ValueError(f"head_dim_override={self.head_dim_override}")
        if self.sliding_window is not None:
            if self.arch != "llama":
                raise ValueError("sliding_window requires arch='llama' "
                                 "(Mistral-family blocks)")
            if self.sliding_window < 1:
                raise ValueError(f"sliding_window={self.sliding_window} must "
                                 f"be >= 1")
        if not 0.0 <= self.dropout < 1.0:
            raise ValueError(f"dropout={self.dropout} must be in [0, 1)")
        if self.tp_overlap not in ("none", "ring", "auto"):
            raise ValueError(f"tp_overlap={self.tp_overlap!r} must be "
                             f"'none', 'ring', or 'auto'")
        if self.use_flash_attention not in (True, False, "auto"):
            raise ValueError(
                f"use_flash_attention={self.use_flash_attention!r} must be "
                f"True, False, or 'auto'")
        if self.dropout > 0.0 and self.use_flash_attention is True:
            raise ValueError(
                "dropout composes with the dense XLA attention path only: "
                "the Pallas flash kernel does not implement attention-prob "
                "dropout (torch applies dropout to attention weights, so "
                "silently skipping it would change train-mode semantics; "
                "'auto' resolves to the dense path under dropout)")

    @property
    def causal(self) -> bool:
        return self.arch != "ref_decoder"

    def flash_for(self, causal: bool, seq_len: int) -> bool:
        """Resolve ``use_flash_attention`` for one attention call site.
        'auto' = flash exactly where it measured faster end-to-end on real
        TPU (docs/performance.md): causal, seq >= 1024, no attention-prob
        dropout. Non-TPU backends resolve to dense — the kernel only runs
        in (slow) interpreter mode there."""
        if self.use_flash_attention is True:
            return True
        if self.use_flash_attention == "auto":
            if self.dropout > 0.0 or not causal or seq_len < 1024:
                return False
            import jax
            return jax.devices()[0].platform in ("tpu", "axon")
        return False

    @property
    def storage_dtype(self) -> str:
        """The dtype parameters are stored in (param_dtype, else dtype)."""
        return self.param_dtype or self.dtype

    @property
    def mixed_precision(self) -> bool:
        return self.storage_dtype != self.dtype

    @property
    def head_dim(self) -> int:
        if self.head_dim_override is not None:
            return self.head_dim_override
        assert self.dim % self.n_heads == 0
        return self.dim // self.n_heads


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Device-mesh shape. axis order is ('data', 'pipe')."""

    n_pipe: int = 2
    n_data: int = 1

    @property
    def n_devices(self) -> int:
        return self.n_pipe * self.n_data


@dataclasses.dataclass(frozen=True)
class ScheduleConfig:
    """Pipeline schedule selection.

    ``name`` in {"GPipe", "1F1B", "Interleaved1F1B"} — the same strings the
    reference dispatches on (``LLMsDistributedTrainingHelper.py:215-220``) —
    or the beyond-parity schedules "ZBH1" (zero-bubble with split
    dgrad/wgrad backward, arXiv:2401.10241) and "BFS" (breadth-first
    virtual-stage GPipe, arXiv:2211.05953).
    ``n_microbatches`` defaults to the reference's fixed 4 (``:214``).
    ``n_virtual`` is the number of virtual stages per device; the reference picks
    2 iff ``schedule=='Interleaved1F1B' and n_layers % (world_size*2)==0`` else
    1 (``:181-185``) — use :func:`virtual_stages_for` to reproduce that rule.
    """

    name: str = "GPipe"
    n_microbatches: int = 4
    n_virtual: int = 1

    def __post_init__(self):
        _check_schedule_name(self.name)

    @classmethod
    def from_artifact(cls, source, *, name: Optional[str] = None
                      ) -> "ScheduleConfig":
        """Register a certified schedule artifact (a path or parsed dict
        from ``analysis.schedule_search`` / ``scripts/search_schedule.py``)
        and return the :class:`ScheduleConfig` that selects it.

        The artifact is fully re-certified on load (recompile + cell diff
        + ``check_table``) and pinned, so ``fit``/``sweep``/``bench`` runs
        under the returned config execute exactly the certified table —
        see ``parallel.schedules.register_schedule_artifact``."""
        from ..parallel.schedules import register_schedule_artifact
        cs = register_schedule_artifact(source, name=name)
        return cls(name=cs.name, n_microbatches=cs.n_microbatches,
                   n_virtual=cs.n_virtual)


# The single source of builtin names is the schedule module; re-exported here
# because config is the user-facing surface (CLIs use it for --schedule).
from ..parallel.schedules import BUILTIN_SCHEDULE_NAMES as SCHEDULE_NAMES  # noqa: E402


def _check_schedule_name(name: str) -> None:
    """Builtin or registered-custom, else ValueError listing every option."""
    from ..parallel.schedules import schedule_names
    if name not in schedule_names():
        raise ValueError(f"unknown schedule {name!r}; expected one of "
                         f"{schedule_names()}")


def virtual_stages_for(schedule_name: str, n_layers: int, n_pipe: int) -> int:
    """Reference rule for stages-per-worker (``LLMsDistributedTrainingHelper.py:181-185``).
    ZBV always runs its 2 V-placed chunks; custom registered schedules get 1
    (the reference rule only special-cases Interleaved)."""
    _check_schedule_name(schedule_name)
    if schedule_name == "ZBV":
        return 2
    # BFS gets the same 2-chunk rule as Interleaved: with V=1 it degenerates
    # to GPipe by construction (every breadth-first round is the whole
    # device ring), so sweep rows labeled BFS would silently benchmark GPipe.
    if (schedule_name in ("Interleaved1F1B", "BFS")
            and n_layers % (n_pipe * 2) == 0):
        return 2
    return 1


@dataclasses.dataclass(frozen=True)
class RunConfig:
    """One experiment's run parameters (reference: ``run_one_experiment`` kwargs,
    notebook cell 19)."""

    batch_size: int = 32
    seq_length: int = 128
    num_iterations: int = 5
    warmup_iterations: int = 2
    seed: int = 0
