"""ctypes binding for the native (C++) prefetching token loader.

``NativeTokenLoader`` is the production twin of
:class:`utils.data.TokenFileDataset`: the same random-crop / next-token-shift
semantics (tested equivalent in distribution), but crop assembly runs in
background C++ threads over an mmap'd file with a bounded prefetch queue —
the Python thread's cost per batch is one memcpy. Build/fallback convention
matches :mod:`parallel.native` (the schedule engine): built on first use via
``csrc/Makefile``; callers that can live without it should check
:func:`native_loader_available` and fall back to ``TokenFileDataset``.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Iterator, Tuple

import numpy as np

from ..parallel.native import NativeLib

_DTYPE_CODES = {np.dtype(np.uint16): 0, np.dtype(np.int32): 1}


def _configure(lib: ctypes.CDLL) -> None:
    lib.dtpp_dl_open.restype = ctypes.c_void_p
    lib.dtpp_dl_open.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int,
        ctypes.c_uint64, ctypes.c_int, ctypes.c_int,
        ctypes.c_char_p, ctypes.c_int,
    ]
    lib.dtpp_dl_next.restype = ctypes.c_int
    lib.dtpp_dl_next.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.dtpp_dl_stop.restype = None
    lib.dtpp_dl_stop.argtypes = [ctypes.c_void_p]
    lib.dtpp_dl_close.restype = None
    lib.dtpp_dl_close.argtypes = [ctypes.c_void_p]


_loader_lib = NativeLib("libdata_loader.so", "data_loader.cpp", _configure)


def _load():
    return _loader_lib.get()


def native_loader_available() -> bool:
    return _load() is not None


class NativeTokenLoader:
    """Threaded native random-crop loader over a flat binary token file.

    Yields ``(tokens, targets)`` int32 ``[batch_size, seq_length]`` pairs,
    targets shifted by one (``TokenFileDataset.sample`` semantics). With
    ``n_threads=1`` the batch stream is deterministic in ``seed``.
    """

    def __init__(self, path: str, seq_length: int, batch_size: int,
                 dtype: np.dtype = np.uint16, seed: int = 0,
                 n_threads: int = 2, depth: int = 4):
        lib = _load()
        if lib is None:
            raise RuntimeError("native data loader unavailable (no compiler?)")
        code = _DTYPE_CODES.get(np.dtype(dtype))
        if code is None:
            raise ValueError(f"unsupported token dtype {dtype!r}; "
                             f"use uint16 or int32")
        err = ctypes.create_string_buffer(256)
        self._lib = lib
        self._handle = lib.dtpp_dl_open(
            os.fspath(path).encode(), seq_length, batch_size, code,
            seed, n_threads, depth, err, len(err))
        if not self._handle:
            raise ValueError(err.value.decode() or "dtpp_dl_open failed")
        self.seq_length = seq_length
        self.batch_size = batch_size
        # close() must not free the native Loader under a next() blocked in
        # C (ctypes releases the GIL): next() registers in-flight under this
        # condition, close() nulls the handle, stops the loader (which
        # unblocks readers), waits for in-flight to drain, then frees.
        self._cond = threading.Condition()
        self._inflight = 0

    def next(self) -> Tuple[np.ndarray, np.ndarray]:
        with self._cond:
            if self._handle is None:
                raise RuntimeError("loader is closed")
            handle = self._handle
            self._inflight += 1
        try:
            shape = (self.batch_size, self.seq_length)
            toks = np.empty(shape, np.int32)
            tgts = np.empty(shape, np.int32)
            rc = self._lib.dtpp_dl_next(
                handle,
                toks.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                tgts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        finally:
            with self._cond:
                self._inflight -= 1
                if self._inflight == 0:
                    self._cond.notify_all()
        if rc != 0:
            raise RuntimeError("loader closed while waiting for a batch")
        return toks, tgts

    def batches(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        while True:
            yield self.next()

    def close(self) -> None:
        with self._cond:
            if self._handle is None:
                return
            handle, self._handle = self._handle, None
            self._lib.dtpp_dl_stop(handle)  # unblocks in-flight next() calls
            while self._inflight:
                self._cond.wait()
        self._lib.dtpp_dl_close(handle)

    def __enter__(self) -> "NativeTokenLoader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
