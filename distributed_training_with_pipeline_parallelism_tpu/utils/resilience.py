"""Resilience layer: crash-safe checkpoints, anomaly guards, preemption
handling, and a deterministic fault-injection harness.

The reference harness has no fault story at all (SURVEY.md §5: no
checkpointing, every run is disposable), but at production scale failure
is the common case — TPU preemptions, hosts killed mid-save, NaN
blowups, stalled collectives. Production MPMD pipeline systems treat
restartability and anomaly containment as first-class (cf. "Scaling
Deep Learning Training with MPMD Pipeline Parallelism", PAPERS.md).
This module supplies the pieces ``utils.train.fit`` wires together:

- **Commit protocol** (:class:`CheckpointManager`,
  :func:`latest_committed_step_dir`, :func:`gc_checkpoints`): a
  checkpoint directory is not trustworthy just because it exists — an
  async save that died mid-flush leaves a ``step_N`` shell Orbax cannot
  restore. A save counts only once its ``_COMMITTED.json`` marker (step,
  config fingerprint, pytree digest) has been atomically renamed into
  place (``checkpoint.write_commit_marker``), and restore walks *past*
  uncommitted or mismatched dirs to the newest committed one.
  Keep-last-k retention garbage-collects older committed checkpoints
  (and dead uncommitted shells strictly older than the newest committed
  step — never a newer shell, which may be an in-flight async save).

- **Anomaly guards** (:class:`AnomalyGuard`, :func:`init_guard_state`):
  the train step folds a finite-check on loss and global grad norm into
  the jitted program and *selects* the old params/opt-state when the
  check fails — a skipped step, not a poisoned run. The guard state
  (step / consecutive / total anomaly counters) stays device-resident
  and is read back only at the existing log-sync points, so the happy
  path costs zero extra host syncs. A bounded consecutive-anomaly
  budget turns a persistent blowup into :class:`AnomalyBudgetExceeded`
  after a final committed checkpoint.

- **Preemption + stalls** (:class:`PreemptionHandler`,
  :class:`StepWatchdog`): SIGTERM/SIGINT set a flag; ``fit`` finishes
  the in-flight step, writes a synchronous committed checkpoint, emits a
  ``preempted`` report event and returns — the resumed run continues
  bit-exact. The watchdog is a daemon thread that fires a stall
  callback when no step completes within a wall-clock timeout (stalled
  collectives are otherwise silent forever).

- **Fault injection** (:class:`FaultPlan`): deterministic faults —
  NaN grads at chosen steps (baked into the traced program as a
  step-index compare, so the injected run is reproducible), a raising
  data iterator, a simulated kill between async flush and commit
  (:class:`SimulatedKill`), a simulated preemption signal, poisoned /
  delayed serving requests. Tests and ``scripts/resilience_smoke.py``
  use it to prove interrupted + resumed runs bit-match uninterrupted
  ones.

JAX imports stay inside functions so importing this module (e.g. from
the serving engine) stays cheap.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import shutil
import signal
import threading
import time
from typing import (Any, Callable, Dict, Iterator, List, Mapping, Optional,
                    Tuple)

from .checkpoint import (is_committed, read_commit_marker, restore_checkpoint,
                         save_checkpoint, wait_for_async_saves,
                         write_commit_marker)

Pytree = Any

log = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Fault injection
# ---------------------------------------------------------------------------


class SimulatedFault(RuntimeError):
    """Base class for every injected fault — tests catch this to tell an
    injected failure from a real one."""


class SimulatedKill(SimulatedFault):
    """Raised by :meth:`CheckpointManager.save` after the checkpoint data
    has been flushed but BEFORE the commit marker is written — the
    moment a real host death leaves an uncommitted ``step_N`` shell."""


class InjectedDataFault(SimulatedFault):
    """Raised from inside the (wrapped) data iterator at a chosen batch
    index — a host-side input-pipeline failure mid-run."""


class AnomalyBudgetExceeded(RuntimeError):
    """The guarded train loop saw more consecutive anomalous (non-finite)
    steps than :attr:`AnomalyGuard.max_consecutive`. ``fit`` writes a
    final committed checkpoint and an ``anomaly_abort`` report event
    before raising this."""


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """A deterministic script of faults to inject into one run.

    All fields are step/request indices, so two runs with the same plan
    fail identically — the property the resume-equivalence tests and
    ``scripts/resilience_smoke.py`` are built on.

    - ``nan_grad_steps``: poison the gradients (and loss) with NaN at
      these global step indices. Baked into the traced train step as a
      step-index compare; requires an :class:`AnomalyGuard` (otherwise
      the poisoned update would corrupt the params forever).
    - ``nan_grad_stage``: narrow ``nan_grad_steps`` to a single pipeline
      stage — only that stage's layer-gradient rows are poisoned and
      the loss stays finite, so ONLY the guard's per-stage non-finite
      reduction can catch it; the skip verdict's ``last_bad_stage``
      must name this stage (the attribution contract
      ``scripts/resilience_smoke.py`` asserts).
    - ``data_fail_step``: the wrapped data iterator raises
      :class:`InjectedDataFault` instead of yielding this batch index
      (counted over the iterator's lifetime, resume drain included).
    - ``kill_in_save_step``: :meth:`CheckpointManager.save` of this step
      flushes the checkpoint fully, then raises :class:`SimulatedKill`
      without writing the commit marker.
    - ``preempt_at_step``: ``fit`` triggers its own preemption handler
      at the top of this step — the deterministic stand-in for a real
      SIGTERM.
    - ``serve_poison_rids``: the serving scheduler raises
      :class:`SimulatedFault` while admitting these request ids; the
      hardened loop must retire them as ``failed`` without wedging the
      slot.
    - ``serve_delay``: per-rid extra arrival delay in ticks — a slow /
      straggling request injected deterministically.
    """
    nan_grad_steps: Tuple[int, ...] = ()
    nan_grad_stage: Optional[int] = None
    data_fail_step: Optional[int] = None
    kill_in_save_step: Optional[int] = None
    preempt_at_step: Optional[int] = None
    serve_poison_rids: Tuple[int, ...] = ()
    serve_delay: Optional[Mapping[int, float]] = None

    def wrap_data(self, data: Iterator) -> Iterator:
        """Wrap a data iterator so batch ``data_fail_step`` raises
        :class:`InjectedDataFault` instead of being yielded. Identity
        when no data fault is scheduled."""
        if self.data_fail_step is None:
            return data

        def gen():
            for i, batch in enumerate(data):
                if i == self.data_fail_step:
                    raise InjectedDataFault(
                        f"injected data-iterator failure at batch {i}")
                yield batch
        return gen()


# ---------------------------------------------------------------------------
# Fingerprints / digests
# ---------------------------------------------------------------------------


def config_fingerprint(*objs: Any) -> str:
    """Stable 16-hex-char fingerprint of run-defining configuration
    (dataclasses, dicts, primitives). Stored in the commit marker so a
    resume under a *different* config skips that checkpoint with a
    warning instead of restoring garbage into the wrong program."""
    def canon(x):
        if dataclasses.is_dataclass(x) and not isinstance(x, type):
            return {"__dc__": type(x).__name__,
                    **{k: canon(v)
                       for k, v in sorted(dataclasses.asdict(x).items())}}
        if isinstance(x, Mapping):
            return {str(k): canon(v) for k, v in sorted(x.items())}
        if isinstance(x, (list, tuple)):
            return [canon(v) for v in x]
        return x
    blob = json.dumps([canon(o) for o in objs], sort_keys=True,
                      default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def pytree_digest(tree: Pytree) -> str:
    """Structural digest of a pytree: treedef + per-leaf shape/dtype,
    16 hex chars. Deliberately *not* a content hash — hashing leaf
    values would force a device sync and a full host transfer on every
    save. This catches the realistic corruption class (wrong template,
    truncated/mixed-up state, changed optimizer) cheaply."""
    import jax
    leaves, treedef = jax.tree.flatten(tree)
    h = hashlib.sha256(str(treedef).encode())
    for leaf in leaves:
        shape = getattr(leaf, "shape", ())
        dtype = getattr(leaf, "dtype", type(leaf).__name__)
        h.update(f"{tuple(shape)}:{dtype};".encode())
    return h.hexdigest()[:16]


# ---------------------------------------------------------------------------
# Committed-checkpoint discovery + retention
# ---------------------------------------------------------------------------


def list_step_dirs(checkpoint_dir: str) -> List[Tuple[int, str]]:
    """All ``step_{n}`` dirs under ``checkpoint_dir``, newest first."""
    if not os.path.isdir(checkpoint_dir):
        return []
    out = []
    for name in os.listdir(checkpoint_dir):
        if name.startswith("step_"):
            try:
                n = int(name[len("step_"):])
            except ValueError:
                continue
            out.append((n, os.path.join(checkpoint_dir, name)))
    return sorted(out, reverse=True)


def latest_committed_step_dir(checkpoint_dir: str,
                              fingerprint: Optional[str] = None,
                              digest: Optional[str] = None,
                              ) -> Optional[Tuple[int, str]]:
    """Newest *committed* ``step_{n}`` under ``checkpoint_dir`` as
    ``(n, path)``, or None.

    Walks newest-to-oldest, skipping (with a warning) dirs with no
    commit marker — a save that died mid-flush — and committed dirs
    whose marker's config ``fingerprint`` / pytree ``digest`` disagree
    with the expected ones (when given). Legacy escape hatch: a tree
    where NO dir carries a marker predates the commit protocol; the
    newest dir is returned with a warning rather than refusing to
    resume old runs."""
    dirs = list_step_dirs(checkpoint_dir)
    if not dirs:
        return None
    any_marker = False
    skipped: List[str] = []
    for n, path in dirs:
        marker = read_commit_marker(path)
        if marker is None:
            skipped.append(f"step_{n} (uncommitted)")
            continue
        any_marker = True
        if (fingerprint and marker.get("fingerprint")
                and marker["fingerprint"] != fingerprint):
            skipped.append(f"step_{n} (config fingerprint "
                           f"{marker['fingerprint']} != {fingerprint})")
            continue
        if digest and marker.get("digest") and marker["digest"] != digest:
            skipped.append(f"step_{n} (pytree digest mismatch)")
            continue
        if skipped:
            log.warning(
                "checkpoint resume: skipping %s; falling back to committed "
                "step_%d under %s", ", ".join(skipped), n, checkpoint_dir)
        return n, path
    if not any_marker:
        n, path = dirs[0]
        log.warning(
            "checkpoint resume: no commit markers anywhere under %s "
            "(legacy checkpoints predating the commit protocol); using "
            "newest step_%d unverified", checkpoint_dir, n)
        return n, path
    log.warning("checkpoint resume: no usable committed checkpoint under "
                "%s (skipped: %s)", checkpoint_dir, ", ".join(skipped))
    return None


def gc_checkpoints(checkpoint_dir: str, keep_last: int) -> List[str]:
    """Retention GC: keep the newest ``keep_last`` *committed*
    checkpoints, remove older committed ones and uncommitted shells
    strictly older than the newest committed step. Uncommitted dirs at
    or past the newest committed step are never touched — one of them
    may be an in-flight async save. Returns the removed paths."""
    if keep_last < 1:
        raise ValueError(f"keep_last must be >= 1, got {keep_last}")
    dirs = list_step_dirs(checkpoint_dir)
    committed = [(n, p) for n, p in dirs if is_committed(p)]
    if not committed:
        return []
    keep = {p for _, p in committed[:keep_last]}
    newest_committed = committed[0][0]
    removed = []
    for n, path in dirs:
        if path in keep:
            continue
        if is_committed(path) or n < newest_committed:
            shutil.rmtree(path, ignore_errors=True)
            removed.append(path)
    if removed:
        log.info("checkpoint GC: removed %d of %d dirs under %s "
                 "(keep_last=%d)", len(removed), len(dirs), checkpoint_dir,
                 keep_last)
    return removed


class CheckpointManager:
    """Crash-safe step-checkpoint lifecycle over one directory.

    Wraps :func:`..checkpoint.save_checkpoint` /
    :func:`..checkpoint.restore_checkpoint` with the commit protocol:
    a synchronous save flushes, writes the commit marker, then GCs;
    an async save (``wait=False``) records the marker as *pending* and
    :meth:`commit_pending` (called automatically before the next save
    or restore) waits for the flush and commits it. A process that
    dies between flush and commit leaves an uncommitted shell that
    restore skips and a later save at the same step overwrites.
    """

    def __init__(self, checkpoint_dir: str, *, keep_last: Optional[int] = None,
                 fingerprint: Optional[str] = None,
                 fault_plan: Optional[FaultPlan] = None) -> None:
        self.checkpoint_dir = checkpoint_dir
        self.keep_last = keep_last
        self.fingerprint = fingerprint
        self.fault_plan = fault_plan
        self.gc_removed = 0
        self.n_saved = 0
        self.last_restored_path: Optional[str] = None
        self._pending: Optional[Tuple[str, Dict[str, Any]]] = None
        os.makedirs(checkpoint_dir, exist_ok=True)

    def step_path(self, step: int) -> str:
        return os.path.join(self.checkpoint_dir, f"step_{step}")

    def save(self, step: int, state: Pytree, wait: bool = True) -> str:
        """Save ``state`` as ``step_{step}``; commit immediately
        (``wait=True``) or leave the commit pending behind the async
        flush (``wait=False``). Idempotent: a step already committed
        with the same state digest is left alone (the crash path may
        re-save the last completed step)."""
        self.commit_pending()
        path = self.step_path(step)
        meta = {"step": int(step),
                "fingerprint": self.fingerprint,
                "digest": pytree_digest(state),
                "committed_unix": time.time()}
        prior = read_commit_marker(path)
        if (prior is not None and prior.get("step") == int(step)
                and prior.get("digest") == meta["digest"]):
            return path
        kill = (self.fault_plan is not None
                and self.fault_plan.kill_in_save_step == step)
        # an injected kill must leave a fully-flushed-but-uncommitted
        # shell, so force the flush to finish before "dying"
        save_checkpoint(path, state, wait=wait or kill)
        if kill:
            raise SimulatedKill(
                f"injected kill after flushing step_{step} (no commit "
                "marker written)")
        if wait:
            write_commit_marker(path, meta)
            self.n_saved += 1
            self._gc()
        else:
            self._pending = (path, meta)
        return path

    def commit_pending(self) -> None:
        """Land any outstanding async save: wait for the flush, write
        its commit marker, run retention GC."""
        if self._pending is None:
            return
        path, meta = self._pending
        self._pending = None
        wait_for_async_saves()
        write_commit_marker(path, meta)
        self.n_saved += 1
        self._gc()

    def restore_latest(self, template: Pytree,
                       ) -> Optional[Tuple[int, str, Pytree]]:
        """Restore the newest committed checkpoint matching this
        manager's fingerprint and the template's structural digest.
        Returns ``(step, path, state)`` or None."""
        self.commit_pending()
        latest = latest_committed_step_dir(
            self.checkpoint_dir, fingerprint=self.fingerprint,
            digest=pytree_digest(template))
        if latest is None:
            return None
        n, path = latest
        self.last_restored_path = path
        return n, path, restore_checkpoint(path, template=template)

    def _gc(self) -> None:
        if self.keep_last:
            self.gc_removed += len(
                gc_checkpoints(self.checkpoint_dir, self.keep_last))

    def stats(self) -> Dict[str, Any]:
        """Summary block for the RunReport ``resilience`` section."""
        committed = [n for n, p in list_step_dirs(self.checkpoint_dir)
                     if is_committed(p)]
        return {"n_committed": len(committed),
                "last_committed_step": committed[0] if committed else None,
                "n_saved": self.n_saved,
                "gc_removed": self.gc_removed}


# ---------------------------------------------------------------------------
# Anomaly guard (device-side state; the jitted check lives in train.py)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class AnomalyGuard:
    """Policy for the jitted finite-check in the train step.

    ``max_consecutive`` bounds how many anomalous (non-finite loss or
    grad-norm) steps in a row are *skipped* before the loop gives up,
    checkpoints, and raises :class:`AnomalyBudgetExceeded`. The budget
    is enforced at log-sync granularity (the guard counters ride the
    existing ``float(loss)`` sync — see docs/resilience.md), so with
    ``log_every > 1`` the abort fires at the first log point at or
    after the budget was crossed."""
    max_consecutive: int = 3


def init_guard_state(start_step: int = 0) -> Dict[str, Any]:
    """Device-resident guard counters threaded through the guarded train
    step: current global step, consecutive / total anomaly counts, the
    last anomalous step (-1 = none), and the last anomaly's attribution
    ``last_bad_stage`` (first pipeline stage with non-finite grads;
    -2 = only the loss was non-finite; -1 = no anomaly yet)."""
    import jax.numpy as jnp
    i32 = jnp.int32
    return {"step": jnp.asarray(start_step, i32),
            "consec": jnp.zeros((), i32),
            "total": jnp.zeros((), i32),
            "last_anomaly_step": jnp.asarray(-1, i32),
            "last_bad_stage": jnp.asarray(-1, i32)}


# ---------------------------------------------------------------------------
# Preemption + stall watchdog
# ---------------------------------------------------------------------------


class PreemptionHandler:
    """Context manager turning SIGTERM/SIGINT into a cooperative flag.

    The signal handler only records the signal — the training loop
    checks :attr:`triggered` after each completed step, so the in-flight
    step always finishes and the checkpoint it writes is a real step
    boundary. :meth:`trigger` injects the same flag programmatically
    (used by :class:`FaultPlan.preempt_at_step`). Handlers are restored
    on exit; installation is skipped with a debug log when not on the
    main thread (Python forbids it there)."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT),
                 enabled: bool = True) -> None:
        self.signals = tuple(signals)
        self.enabled = enabled
        self.signum: Optional[int] = None
        self._triggered = False
        self._old: Dict[int, Any] = {}

    @property
    def triggered(self) -> bool:
        return self._triggered

    def trigger(self, signum: int = signal.SIGTERM) -> None:
        self._on(signum, None)

    def _on(self, signum, _frame) -> None:
        self.signum = signum
        self._triggered = True

    def __enter__(self) -> "PreemptionHandler":
        if self.enabled:
            for s in self.signals:
                try:
                    self._old[s] = signal.signal(s, self._on)
                except ValueError:  # not the main thread
                    log.debug("preemption handler: cannot install signal "
                              "%s off the main thread", s)
        return self

    def __exit__(self, *exc) -> None:
        for s, old in self._old.items():
            signal.signal(s, old)
        self._old.clear()
        return None


class StepWatchdog:
    """Wall-clock stall detector: a daemon thread that calls
    ``on_stall({"step", "stalled_s"})`` once per stall when no
    :meth:`beat` arrives within ``timeout_s``. Re-arms after the next
    beat; never interrupts the run (a stalled collective is diagnosed,
    not killed — aborting is the scheduler's call)."""

    def __init__(self, timeout_s: float,
                 on_stall: Callable[[Dict[str, Any]], None],
                 poll_s: Optional[float] = None) -> None:
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        self.timeout_s = timeout_s
        self.on_stall = on_stall
        self.stalls = 0
        self._last_beat = time.monotonic()
        self._last_step: Optional[int] = None
        self._fired = False
        self._stop = threading.Event()
        self._lock = threading.Lock()
        poll = poll_s if poll_s is not None else max(timeout_s / 4.0, 0.01)
        self._thread = threading.Thread(
            target=self._watch, args=(poll,), name="dtpp-step-watchdog",
            daemon=True)
        self._thread.start()

    def beat(self, step: int) -> None:
        with self._lock:
            self._last_beat = time.monotonic()
            self._last_step = step
            self._fired = False

    def _watch(self, poll: float) -> None:
        while not self._stop.wait(poll):
            with self._lock:
                stalled = time.monotonic() - self._last_beat
                fire = stalled >= self.timeout_s and not self._fired
                step = self._last_step
                if fire:
                    self._fired = True
                    self.stalls += 1
            if fire:
                try:
                    self.on_stall({"step": step,
                                   "stalled_s": round(stalled, 3)})
                except Exception:  # a broken callback must not kill the dog
                    log.exception("step watchdog: on_stall callback raised")

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
